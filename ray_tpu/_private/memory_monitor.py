"""Memory monitor + OOM worker-killing policies.

Analogs of the reference's MemoryMonitor (src/ray/common/memory_monitor.h)
and the raylet's worker-killing policies
(src/ray/raylet/worker_killing_policy.h + _group_by_owner variant): a
periodic poll of system/cgroup memory (native memmon.cc, /proc fallback in
Python) that, above ``memory_usage_threshold``, picks a victim among the
running tasks and fails it with an OutOfMemoryError — retriable tasks are
preferred victims, newest first, so forward progress (the oldest work) is
protected.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Callable, Dict, List, Optional

logger = logging.getLogger("ray_tpu")


def _load():
    import ctypes

    from ray_tpu._private.native_build import load_library_cached

    def configure(lib):
        lib.rmm_snapshot.restype = ctypes.c_int64
        lib.rmm_snapshot.argtypes = [ctypes.c_char_p, ctypes.c_int64]
        lib.rmm_usage_fraction.restype = ctypes.c_double
        lib.rmm_usage_fraction.argtypes = []

    return load_library_cached("memmon", configure=configure)


def memory_snapshot() -> Dict[str, int]:
    """{'system_total', 'system_available', 'cgroup_limit', 'cgroup_used'}
    in bytes (-1 unknown, cgroup_limit -2 unlimited)."""
    lib = _load()
    if lib is not None:
        import ctypes
        buf = ctypes.create_string_buffer(512)
        lib.rmm_snapshot(buf, 512)
        out = {}
        for part in buf.value.decode().split(";"):
            k, _, v = part.partition("=")
            out[k] = int(v)
        return out
    # Python fallback (same fields).
    out = {"system_total": -1, "system_available": -1,
           "cgroup_limit": -1, "cgroup_used": -1}
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemTotal:"):
                    out["system_total"] = int(line.split()[1]) * 1024
                elif line.startswith("MemAvailable:"):
                    out["system_available"] = int(line.split()[1]) * 1024
    except OSError:
        pass
    try:
        with open("/sys/fs/cgroup/memory.max") as f:
            raw = f.read().strip()
            out["cgroup_limit"] = -2 if raw == "max" else int(raw)
        with open("/sys/fs/cgroup/memory.current") as f:
            out["cgroup_used"] = int(f.read().strip())
    except OSError:
        pass
    return out


def usage_fraction(snapshot: Optional[Dict[str, int]] = None) -> float:
    """Effective memory pressure in [0, 1]; -1 if unknown."""
    s = snapshot or memory_snapshot()
    if s.get("cgroup_limit", -1) > 0 and s.get("cgroup_used", -1) >= 0:
        return s["cgroup_used"] / s["cgroup_limit"]
    if s.get("system_total", -1) > 0 and s.get("system_available", -1) >= 0:
        return 1.0 - s["system_available"] / s["system_total"]
    return -1.0


# -- worker-killing policies ----------------------------------------------


def retriable_lifo_policy(tasks: List[Any]) -> Optional[Any]:
    """The reference's RetriableLIFOWorkerKillingPolicy: prefer a task that
    can retry; among those, the most recently started (its lost progress is
    smallest)."""
    def start_time(spec):
        return getattr(spec, "_start_time", 0.0)

    retriable = [t for t in tasks
                 if t.attempt_number < t.max_retries]
    pool = retriable or list(tasks)
    if not pool:
        return None
    return max(pool, key=start_time)


def group_by_owner_policy(tasks: List[Any]) -> Optional[Any]:
    """The reference's GroupByOwner policy: find the owner (job/actor) with
    the most running tasks and kill its newest retriable task — spreading
    pain away from small owners."""
    groups: Dict[Any, List[Any]] = {}
    for t in tasks:
        owner = t.actor_id or getattr(t.task_id, "job_id", lambda: None)()
        groups.setdefault(owner, []).append(t)
    if not groups:
        return None
    largest = max(groups.values(), key=len)
    return retriable_lifo_policy(largest)


POLICIES = {
    "retriable_lifo": retriable_lifo_policy,
    "group_by_owner": group_by_owner_policy,
}


class MemoryMonitor:
    """Polls memory pressure every ``refresh_ms``; above ``threshold`` asks
    the runtime for its running tasks, picks a victim via the policy, and
    invokes ``kill_fn(spec)``."""

    def __init__(self, threshold: float, refresh_ms: int,
                 get_running_tasks: Callable[[], List[Any]],
                 kill_fn: Callable[[Any], None],
                 policy: str = "retriable_lifo",
                 usage_fn: Callable[[], float] = usage_fraction,
                 kill_cooldown_s: Optional[float] = None):
        self.threshold = threshold
        self.refresh_s = max(refresh_ms, 50) / 1000.0
        self._get_running = get_running_tasks
        self._kill = kill_fn
        self._policy = POLICIES[policy]
        self._usage = usage_fn
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.kills = 0
        # After a kill, back off before killing again: the victim (a thread
        # in this backend) needs time to actually unwind and release memory;
        # killing every poll would burn retry budgets without reclaiming
        # anything (the reference kills whole worker processes).
        self.kill_cooldown_s = (kill_cooldown_s if kill_cooldown_s is not None
                                else max(10 * self.refresh_s, 2.0))
        self._last_kill = float("-inf")

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._loop, name="ray_tpu-memmon", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)

    def check_once(self) -> Optional[Any]:
        """One poll; returns the killed spec (tests drive this directly)."""
        frac = self._usage()
        if frac < 0 or frac < self.threshold:
            return None
        if time.monotonic() - self._last_kill < self.kill_cooldown_s:
            return None
        victim = self._policy(self._get_running())
        if victim is None:
            return None
        logger.warning(
            "Memory pressure %.0f%% above threshold %.0f%%: killing task "
            "%s (attempt %d/%d)", frac * 100, self.threshold * 100,
            victim.name, victim.attempt_number, victim.max_retries)
        self._kill(victim)
        self.kills += 1
        self._last_kill = time.monotonic()
        return victim

    def _loop(self) -> None:
        while not self._stop.wait(self.refresh_s):
            try:
                self.check_once()
            except Exception:  # noqa: BLE001 - monitor must survive
                logger.exception("memory monitor poll failed")
