"""Task specifications and option validation.

Analog of the reference's TaskSpecification (src/ray/common/task/task_spec.h)
plus the central option table (python/ray/_private/ray_option_utils.py).
Resources are floats; ``num_cpus`` defaults to 1 for tasks and 0 for actors,
matching the reference's defaults. TPU chips are a first-class resource
(``num_tpus``) instead of GPUs.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu._private.ids import ActorID, ObjectID, TaskID

TPU_RESOURCE = "TPU"
CPU_RESOURCE = "CPU"
MEMORY_RESOURCE = "memory"


class TaskKind(enum.Enum):
    NORMAL = 0
    ACTOR_CREATION = 1
    ACTOR_TASK = 2


_COMMON_OPTIONS = {
    "num_cpus", "num_tpus", "num_gpus", "resources", "memory", "name",
    "num_returns", "max_retries", "retry_exceptions", "scheduling_strategy",
    "placement_group", "placement_group_bundle_index", "runtime_env",
    "max_concurrency", "lifetime", "max_restarts", "max_task_retries",
    "namespace", "get_if_exists", "concurrency_groups", "label_selector",
    "accelerator_type", "_metadata",
}


def validate_resource_name(name: Any) -> None:
    """Reject names the schedulers cannot represent. The native engine's
    C ABI encodes resource maps as ``name=value;...`` (and PG bundles with
    ``|``), so separator/control characters in a name would silently corrupt
    its parse; both engines enforce the same rule for decision parity."""
    if not isinstance(name, str) or not name:
        raise ValueError(
            f"Resource name must be a non-empty string, got {name!r}")
    if any(c in "=;|" or ord(c) < 32 for c in name):
        raise ValueError(
            f"Invalid resource name {name!r}: must not contain '=', ';', "
            "'|' or control characters")


def validate_options(options: Dict[str, Any], for_actor: bool) -> Dict[str, Any]:
    for key in options:
        if key not in _COMMON_OPTIONS:
            raise ValueError(
                f"Invalid option keyword {key!r}. Valid options: "
                f"{sorted(_COMMON_OPTIONS)}")
    for res_key in ("num_cpus", "num_tpus", "num_gpus", "memory"):
        val = options.get(res_key)
        if val is not None and (not isinstance(val, (int, float)) or val < 0):
            raise ValueError(f"{res_key} must be a non-negative number, got {val!r}")
    resources = options.get("resources")
    if resources is not None:
        if not isinstance(resources, dict):
            raise ValueError("resources must be a dict of name -> quantity")
        for k, v in resources.items():
            validate_resource_name(k)
            if k in (CPU_RESOURCE, TPU_RESOURCE, "GPU"):
                raise ValueError(
                    f"Use num_cpus/num_tpus/num_gpus instead of resources[{k!r}]")
            if not isinstance(v, (int, float)) or v < 0:
                raise ValueError(f"resources[{k!r}] must be non-negative")
    if options.get("runtime_env") is not None:
        from ray_tpu._private import runtime_env as _renv
        _renv.validate(options["runtime_env"])
    num_returns = options.get("num_returns")
    if num_returns is not None:
        if num_returns != "dynamic" and (
                not isinstance(num_returns, int) or num_returns < 0):
            raise ValueError("num_returns must be a non-negative int or 'dynamic'")
    lifetime = options.get("lifetime")
    if lifetime not in (None, "non_detached", "detached"):
        raise ValueError(
            "lifetime must be None, 'non_detached' or 'detached', "
            f"got {lifetime!r}")
    if lifetime == "detached" and not for_actor:
        raise ValueError("lifetime='detached' is only valid for actors")
    if for_actor:
        max_restarts = options.get("max_restarts")
        if max_restarts is not None and (
                not isinstance(max_restarts, int) or max_restarts < -1):
            raise ValueError("max_restarts must be an int >= -1")
    return options


def resources_from_options(options: Dict[str, Any], for_actor: bool) -> Dict[str, float]:
    resources: Dict[str, float] = {}
    num_cpus = options.get("num_cpus")
    if num_cpus is None:
        num_cpus = 0 if for_actor else 1
    if num_cpus:
        resources[CPU_RESOURCE] = float(num_cpus)
    num_tpus = options.get("num_tpus") or options.get("num_gpus")
    if num_tpus:
        resources[TPU_RESOURCE] = float(num_tpus)
    memory = options.get("memory")
    if memory:
        resources[MEMORY_RESOURCE] = float(memory)
    for k, v in (options.get("resources") or {}).items():
        if v:
            resources[k] = float(v)
    return resources


@dataclass
class TaskSpec:
    task_id: TaskID
    kind: TaskKind
    function_id: bytes  # key into the runtime's function table
    args: Tuple[Any, ...]  # flattened; ObjectRefs appear in arg_deps positions
    kwargs: Dict[str, Any]
    resources: Dict[str, float]
    num_returns: Any  # int or "dynamic"
    name: str = ""
    max_retries: int = 3
    retry_exceptions: Any = False  # False | True | list of exception types
    actor_id: Optional[ActorID] = None
    method_name: str = ""
    sequence_number: int = 0  # per-handle ordering for actor tasks
    caller_handle_id: str = ""  # which ActorHandle issued the call
    # Named concurrency group this actor call routes to (reference:
    # concurrency_group_manager.h); None = the actor's default group.
    concurrency_group: Optional[str] = None
    placement_group_id: Optional[Any] = None
    placement_group_bundle_index: int = -1
    scheduling_strategy: Any = None
    return_ids: List[ObjectID] = field(default_factory=list)
    # Filled at submission: ObjectRef deps that must be resolved pre-dispatch.
    dependencies: List[ObjectID] = field(default_factory=list)
    attempt_number: int = 0
    runtime_env: Optional[Dict[str, Any]] = None
    # Set when the task's node died mid-run: results are discarded, a retry
    # owns the return objects (multi-node failure semantics).
    invalidated: bool = False
    # Tracing context propagated from the caller's active span (declared so
    # clone_for_retry keeps retried tasks inside their trace).
    trace_ctx: Optional[Dict[str, str]] = None

    def clone_for_retry(self) -> "TaskSpec":
        """Fresh spec for a node-death retry/reconstruction. The original
        stays invalidated forever (its zombie thread must not store results
        or release resources); the clone shares return_ids so the retry
        seals the same objects, but carries none of the original's placement
        state (_node_id/_acquired_bundle/_tpu_ids live only on instances
        that went through dispatch)."""
        import dataclasses
        clone = dataclasses.replace(self)
        clone.attempt_number = self.attempt_number + 1
        clone.invalidated = False
        return clone
