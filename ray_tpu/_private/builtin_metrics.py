"""Built-in ``ray_tpu_*`` runtime metrics.

Analog of the reference's core-runtime stats (stats/metric_defs.h:
tasks, scheduler, object store, and worker-pool series every Ray
process emits). Each accessor lazily (re-)binds the metric through the
registry so ``ray_tpu.util.metrics.clear_registry()`` in tests cannot
orphan the instrumentation: the next event simply re-registers.

Counters are incremented at the runtime's choke points (task state
transitions, spills, restarts, log batches); level-style gauges are
refreshed by per-agent collector callbacks right before each snapshot
(``MetricsAgent.add_collector``) so hot paths stay untouched.
"""

from __future__ import annotations

# ray_tpu.util.metrics is imported inside each accessor: importing it at
# module scope would execute ray_tpu.util/__init__ (which pulls
# placement_group -> _private.worker) while _private modules that
# instrument themselves are still initializing - a circular import.

# -- tasks / scheduler ----------------------------------------------------


def tasks_submitted() -> Counter:
    from ray_tpu.util.metrics import Counter
    return Counter("ray_tpu_tasks_submitted_total",
                   "Tasks submitted to the runtime.")


def tasks_started() -> Counter:
    from ray_tpu.util.metrics import Counter
    return Counter("ray_tpu_tasks_started_total",
                   "Tasks that began executing.")


def tasks_finished() -> Counter:
    from ray_tpu.util.metrics import Counter
    return Counter("ray_tpu_tasks_finished_total",
                   "Tasks that finished successfully.")


def tasks_failed() -> Counter:
    from ray_tpu.util.metrics import Counter
    return Counter("ray_tpu_tasks_failed_total",
                   "Tasks that failed (after retries).")


_TASK_STATUS_COUNTERS = {
    "SUBMITTED": tasks_submitted,
    "RUNNING": tasks_started,
    "FINISHED": tasks_finished,
    "FAILED": tasks_failed,
}


def record_task_event(status: str) -> None:
    """Map a task state transition onto its counter (no-op for statuses
    that are not terminal/throughput signals, e.g. OOM_RETRY)."""
    accessor = _TASK_STATUS_COUNTERS.get(status)
    if accessor is not None:
        accessor().inc()


def scheduler_pending_tasks() -> Gauge:
    from ray_tpu.util.metrics import Gauge
    return Gauge("ray_tpu_scheduler_pending_tasks",
                 "Tasks queued waiting for resources or leases.")


def alive_nodes() -> Gauge:
    from ray_tpu.util.metrics import Gauge
    return Gauge("ray_tpu_alive_nodes", "Nodes currently alive.")


def actors_gauge() -> Gauge:
    from ray_tpu.util.metrics import Gauge
    return Gauge("ray_tpu_actors", "Live actors registered at the head.")


def actor_restarts() -> Counter:
    from ray_tpu.util.metrics import Counter
    return Counter(
        "ray_tpu_actor_restarts_total",
        "Actor restarts, including detached-actor rebinds after a head "
        "restart.", tag_keys=("kind",))


# -- object store ---------------------------------------------------------


def object_store_bytes() -> Gauge:
    from ray_tpu.util.metrics import Gauge
    return Gauge("ray_tpu_object_store_bytes",
                 "Bytes resident in the local object store.")


def object_spilled_bytes() -> Counter:
    from ray_tpu.util.metrics import Counter
    return Counter("ray_tpu_object_spilled_bytes_total",
                   "Bytes spilled from the object store to disk.")


def object_store_hits() -> Counter:
    from ray_tpu.util.metrics import Counter
    return Counter("ray_tpu_object_store_hits_total",
                   "Object reads served from memory (plasma-analog hit).")


def object_store_misses() -> Counter:
    from ray_tpu.util.metrics import Counter
    return Counter(
        "ray_tpu_object_store_misses_total",
        "Object reads that had to restore a spilled payload from disk.")


# -- worker pool ----------------------------------------------------------


def worker_pool_size() -> Gauge:
    from ray_tpu.util.metrics import Gauge
    return Gauge("ray_tpu_worker_pool_size",
                 "Live worker subprocesses in this process's pool.")


def worker_lease_wait() -> Histogram:
    from ray_tpu.util.metrics import Histogram
    return Histogram(
        "ray_tpu_worker_lease_wait_seconds",
        "Seconds a lease request waited for a worker subprocess.",
        boundaries=[0.001, 0.01, 0.05, 0.25, 1, 5, 30])


# -- log subsystem --------------------------------------------------------


def log_lines() -> Counter:
    from ray_tpu.util.metrics import Counter
    return Counter("ray_tpu_log_monitor_lines_total",
                   "Log lines published by this node's log monitor.")


def log_lines_dropped() -> Counter:
    from ray_tpu.util.metrics import Counter
    return Counter(
        "ray_tpu_log_monitor_lines_dropped_total",
        "Log lines dropped by backpressure (publish returned False).")
