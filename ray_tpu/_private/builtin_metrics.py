"""Built-in ``ray_tpu_*`` runtime metrics.

Analog of the reference's core-runtime stats (stats/metric_defs.h:
tasks, scheduler, object store, and worker-pool series every Ray
process emits). Each accessor lazily (re-)binds the metric through the
registry so ``ray_tpu.util.metrics.clear_registry()`` in tests cannot
orphan the instrumentation: the next event simply re-registers.

Counters are incremented at the runtime's choke points (task state
transitions, spills, restarts, log batches); level-style gauges are
refreshed by per-agent collector callbacks right before each snapshot
(``MetricsAgent.add_collector``) so hot paths stay untouched.

Hot-path events (per-task state transitions, store hits, transfer
bytes, lease waits that were serviced immediately) do NOT touch the
registry inline: a ``Counter.inc`` takes a lock and a tag-dict merge,
which showed up as a double-digit tasks_per_sec regression. They bump
plain-dict integer cells instead — a single int add under the GIL —
and ``flush_fast_counters`` (registered as a default MetricsAgent
collector) folds the cells into the real metrics right before each
snapshot. Increments racing a flush survive because the flush
decrements by the amount it read rather than zeroing the cell.
"""

from __future__ import annotations

from typing import Optional

# ray_tpu.util.metrics is imported inside each accessor: importing it at
# module scope would execute ray_tpu.util/__init__ (which pulls
# placement_group -> _private.worker) while _private modules that
# instrument themselves are still initializing - a circular import.

# -- fast cells (hot-path increments, folded by flush_fast_counters) ------

_fast_task_events = {"SUBMITTED": 0, "RUNNING": 0, "FINISHED": 0,
                     "FAILED": 0}
# (node_id_hex, status) -> count: per-node task transitions, the series
# behind `ray-tpu top`'s per-node submit/finish rates. Unbounded only by
# node count x 4 statuses.
_fast_node_task_events: dict = {}
_fast_store = {"hit": 0, "miss": 0}
_fast_transfer = {"in": 0, "out": 0}
_fast_chunks = {"n": 0}
_fast_lease_immediate = {"n": 0}
_fast_channel = {"bytes": 0, "acks": 0}
# Continuous-profiler stack walks: bumped every sampler tick (hz rate),
# folded into ray_tpu_profile_samples_total at each snapshot.
_fast_profile = {"samples": 0}
# Alerting plane cells: state -> transition count and severity -> event
# count. Transitions happen inside ClusterMetrics.update's merge path
# and journal appends can ride task/spill hot paths, so both stay
# dict adds until flush.
_fast_alert_transitions: dict = {}
_fast_cluster_events: dict = {}


def record_alert_transition(state: str) -> None:
    _fast_alert_transitions[state] = \
        _fast_alert_transitions.get(state, 0) + 1


def record_cluster_event(severity: str) -> None:
    _fast_cluster_events[severity] = \
        _fast_cluster_events.get(severity, 0) + 1


def record_store_hit() -> None:
    _fast_store["hit"] += 1


def record_store_miss() -> None:
    _fast_store["miss"] += 1


def record_transfer_in(nbytes: int) -> None:
    _fast_transfer["in"] += nbytes


def record_transfer_out(nbytes: int) -> None:
    _fast_transfer["out"] += nbytes


def record_pull_chunks(n: int) -> None:
    _fast_chunks["n"] += n


def record_channel_bytes_sent(nbytes: int) -> None:
    """Every ResilientChannel write (header + payload bytes): one dict
    int add on the frame send path, folded at flush."""
    _fast_channel["bytes"] += nbytes


def record_channel_ack_sent() -> None:
    _fast_channel["acks"] += 1


def record_profile_samples(n: int) -> None:
    """Stacks walked by one ProfilerAgent tick: a dict int add on the
    sampler thread, folded at flush."""
    _fast_profile["samples"] += n


def record_lease_immediate() -> None:
    """A lease request satisfied without waiting: lands in the lease-wait
    histogram's smallest bucket at flush time, skipping two monotonic
    clock reads and a locked observe on the lease fast path."""
    _fast_lease_immediate["n"] += 1


def flush_fast_counters() -> None:
    """Fold the fast cells into the registry metrics. Runs as a
    MetricsAgent collector before each snapshot (and may be called
    directly in tests). Decrements each cell by the value it read so
    increments racing the flush are kept for the next one."""
    for status, n in list(_fast_task_events.items()):
        if n:
            _fast_task_events[status] -= n
            _TASK_STATUS_COUNTERS[status]().inc(n)
    for (node_hex, status), n in list(_fast_node_task_events.items()):
        if n:
            _fast_node_task_events[(node_hex, status)] -= n
            node_task_events().inc(
                n, tags={"node_id": node_hex, "status": status})
    for kind, n in list(_fast_store.items()):
        if n:
            _fast_store[kind] -= n
            acc = object_store_hits if kind == "hit" else object_store_misses
            acc().inc(n)
    for direction, n in list(_fast_transfer.items()):
        if n:
            _fast_transfer[direction] -= n
            object_transfer_bytes().inc(n, tags={"direction": direction})
    n = _fast_chunks["n"]
    if n:
        _fast_chunks["n"] -= n
        pull_chunks().inc(n)
    n = _fast_channel["bytes"]
    if n:
        _fast_channel["bytes"] -= n
        channel_bytes_sent().inc(n)
    n = _fast_channel["acks"]
    if n:
        _fast_channel["acks"] -= n
        channel_acks_sent().inc(n)
    n = _fast_profile["samples"]
    if n:
        _fast_profile["samples"] -= n
        profile_samples().inc(n)
    for state, n in list(_fast_alert_transitions.items()):
        if n:
            _fast_alert_transitions[state] -= n
            alerts_transitions().inc(n, tags={"state": state})
    for severity, n in list(_fast_cluster_events.items()):
        if n:
            _fast_cluster_events[severity] -= n
            cluster_events().inc(n, tags={"severity": severity})
    n = _fast_lease_immediate["n"]
    if n:
        _fast_lease_immediate["n"] -= n
        h = worker_lease_wait()
        key = h._key(None)
        with h._lock:
            buckets = h._buckets.setdefault(
                key, [0] * (len(h.boundaries) + 1))
            buckets[0] += n
            h._counts[key] = h._counts.get(key, 0) + n
            h._sums[key] = h._sums.get(key, 0.0) + 0.0
            h._series[key] = 0.0


# -- tasks / scheduler ----------------------------------------------------


def tasks_submitted() -> Counter:
    from ray_tpu.util.metrics import Counter
    return Counter("ray_tpu_tasks_submitted_total",
                   "Tasks submitted to the runtime.")


def tasks_started() -> Counter:
    from ray_tpu.util.metrics import Counter
    return Counter("ray_tpu_tasks_started_total",
                   "Tasks that began executing.")


def tasks_finished() -> Counter:
    from ray_tpu.util.metrics import Counter
    return Counter("ray_tpu_tasks_finished_total",
                   "Tasks that finished successfully.")


def tasks_failed() -> Counter:
    from ray_tpu.util.metrics import Counter
    return Counter("ray_tpu_tasks_failed_total",
                   "Tasks that failed (after retries).")


_TASK_STATUS_COUNTERS = {
    "SUBMITTED": tasks_submitted,
    "RUNNING": tasks_started,
    "FINISHED": tasks_finished,
    "FAILED": tasks_failed,
}


def node_task_events() -> Counter:
    from ray_tpu.util.metrics import Counter
    return Counter(
        "ray_tpu_node_task_events_total",
        "Task state transitions attributed to the executing node; the "
        "windowed rate per (node_id, status) feeds `ray-tpu top`'s "
        "per-node submit/finish columns.",
        tag_keys=("node_id", "status"))


def record_task_event(status: str,
                      node_hex: Optional[str] = None) -> None:
    """Map a task state transition onto its counter (no-op for statuses
    that are not terminal/throughput signals, e.g. OOM_RETRY). This is
    on the per-task submit/execute fast path: one dict int add (two
    when the executing node is known), folded into the real counters by
    ``flush_fast_counters``."""
    if status in _fast_task_events:
        _fast_task_events[status] += 1
        if node_hex:
            key = (node_hex, status)
            _fast_node_task_events[key] = \
                _fast_node_task_events.get(key, 0) + 1


def scheduler_pending_tasks() -> Gauge:
    from ray_tpu.util.metrics import Gauge
    return Gauge("ray_tpu_scheduler_pending_tasks",
                 "Tasks queued waiting for resources or leases.")


def alive_nodes() -> Gauge:
    from ray_tpu.util.metrics import Gauge
    return Gauge("ray_tpu_alive_nodes", "Nodes currently alive.")


def actors_gauge() -> Gauge:
    from ray_tpu.util.metrics import Gauge
    return Gauge("ray_tpu_actors", "Live actors registered at the head.")


def actor_restarts() -> Counter:
    from ray_tpu.util.metrics import Counter
    return Counter(
        "ray_tpu_actor_restarts_total",
        "Actor restarts, including detached-actor rebinds after a head "
        "restart.", tag_keys=("kind",))


# -- object store ---------------------------------------------------------


def object_store_bytes() -> Gauge:
    from ray_tpu.util.metrics import Gauge
    return Gauge("ray_tpu_object_store_bytes",
                 "Bytes resident in the local object store.")


def object_spilled_bytes() -> Counter:
    from ray_tpu.util.metrics import Counter
    return Counter("ray_tpu_object_spilled_bytes_total",
                   "Bytes spilled from the object store to disk.")


def object_restores() -> Counter:
    from ray_tpu.util.metrics import Counter
    return Counter(
        "ray_tpu_object_restores_total",
        "Lost-object recoveries by the tier that paid for them: "
        "replica = re-pointed at another in-memory holder, spill = "
        "payload read back from a surviving spill URI, lineage = "
        "producer task re-executed (the most expensive tier).",
        tag_keys=("source",))


def object_spill_failures() -> Counter:
    from ray_tpu.util.metrics import Counter
    return Counter(
        "ray_tpu_object_spill_failures_total",
        "Spill-backend IO failures by op (write = spill kept the "
        "in-memory copy instead; restore = tier miss, recovery fell "
        "down a tier). Includes chaos-injected io_oserror faults.",
        tag_keys=("op",))


def object_store_hits() -> Counter:
    from ray_tpu.util.metrics import Counter
    return Counter("ray_tpu_object_store_hits_total",
                   "Object reads served from memory (plasma-analog hit).")


def object_store_misses() -> Counter:
    from ray_tpu.util.metrics import Counter
    return Counter(
        "ray_tpu_object_store_misses_total",
        "Object reads that had to restore a spilled payload from disk.")


# -- data plane -----------------------------------------------------------


def object_transfer_bytes() -> Counter:
    from ray_tpu.util.metrics import Counter
    return Counter(
        "ray_tpu_object_transfer_bytes_total",
        "Bytes moved over the node-to-node data plane, by direction "
        "(in = pulled to this node, out = served to peers).",
        tag_keys=("direction",))


def pull_chunks() -> Counter:
    from ray_tpu.util.metrics import Counter
    return Counter(
        "ray_tpu_pull_chunks_total",
        "Ranged chunks fetched by the chunked parallel pull path.")


def broadcast_trees() -> Counter:
    from ray_tpu.util.metrics import Counter
    return Counter(
        "ray_tpu_broadcast_trees_total",
        "Spanning-tree push broadcasts issued by the head (explicit "
        "ray_tpu.broadcast hints + auto-triggered hot-object fan-out).")


def push_bytes() -> Counter:
    from ray_tpu.util.metrics import Counter
    return Counter(
        "ray_tpu_push_bytes_total",
        "Bytes replicated through push_object broadcast directives "
        "(head seed sends + tree-edge forwards), as acknowledged by "
        "completing nodes.")


def lease_locality() -> Counter:
    from ray_tpu.util.metrics import Counter
    return Counter(
        "ray_tpu_lease_locality_total",
        "Locality-aware placement outcomes for tasks with remote "
        "argument bytes: local = landed on the node holding the "
        "largest share, spillback = preferred node was over the "
        "spillback threshold or lost the acquire, remote = no usable "
        "preference (holders dead or sizes unknown).",
        tag_keys=("outcome",))


# -- worker pool ----------------------------------------------------------


def worker_pool_size() -> Gauge:
    from ray_tpu.util.metrics import Gauge
    return Gauge("ray_tpu_worker_pool_size",
                 "Live worker subprocesses in this process's pool.")


def worker_lease_wait() -> Histogram:
    from ray_tpu.util.metrics import Histogram
    return Histogram(
        "ray_tpu_worker_lease_wait_seconds",
        "Seconds a lease request waited for a worker subprocess.",
        boundaries=[0.001, 0.01, 0.05, 0.25, 1, 5, 30])


# -- distributed tracing ---------------------------------------------------


def trace_stage_seconds() -> Histogram:
    from ray_tpu.util.metrics import Histogram
    return Histogram(
        "ray_tpu_trace_stage_seconds",
        "Span durations by pipeline stage (submit/queue/lease/pull/"
        "execute/store/serve_dispatch/serve_handle), observed by the "
        "head's trace assembler as sampled spans arrive — the "
        "critical-path attribution behind `ray-tpu trace --summary`.",
        boundaries=[0.0001, 0.001, 0.01, 0.1, 1, 10, 100],
        tag_keys=("stage",))


# -- log subsystem --------------------------------------------------------


def log_lines() -> Counter:
    from ray_tpu.util.metrics import Counter
    return Counter("ray_tpu_log_monitor_lines_total",
                   "Log lines published by this node's log monitor.")


def log_lines_dropped() -> Counter:
    from ray_tpu.util.metrics import Counter
    return Counter(
        "ray_tpu_log_monitor_lines_dropped_total",
        "Log lines dropped by backpressure (publish returned False).")


# -- channel resilience ----------------------------------------------------
# Rare-path events (a reconnect is news, not load): plain lazy
# accessors, no fast cells. Incremented from channel.py attach/send
# paths and the dataplane's pooled-socket retry classification.


def channel_reconnects() -> Counter:
    from ray_tpu.util.metrics import Counter
    return Counter(
        "ray_tpu_channel_reconnects_total",
        "Successful session-channel resumes (socket re-dialed and "
        "re-attached without node death).")


def channel_frames_resent() -> Counter:
    from ray_tpu.util.metrics import Counter
    return Counter(
        "ray_tpu_channel_frames_resent_total",
        "Unacked frames replayed from the resend ring after a channel "
        "resume.")


def channel_send_retries() -> Counter:
    from ray_tpu.util.metrics import Counter
    return Counter(
        "ray_tpu_channel_send_retries_total",
        "Transient transport errors classified as retryable (channel "
        "send breaks, stale pooled-socket retries) instead of "
        "escalating to node death or pull failure.")


# -- membership fencing (wire v9) ------------------------------------------


def frames_fenced() -> Counter:
    from ray_tpu.util.metrics import Counter
    return Counter(
        "ray_tpu_frames_fenced_total",
        "Frames and handshakes rejected because they carried a dead "
        "incarnation's epoch (or came from a session the head no "
        "longer knows): stale-envelope drops, fenced resume attempts, "
        "and unknown-node health-channel announces. Counted, never "
        "applied — and never per-frame log spam.")


def node_deaths() -> Counter:
    from ray_tpu.util.metrics import Counter
    return Counter(
        "ray_tpu_node_deaths_total",
        "Node incarnations declared dead, by how the detector decided "
        "(hard = process-gone evidence; suspicion = accrual phi over "
        "threshold; lease = hard silence bound).",
        tag_keys=("kind",))


# -- head failover ---------------------------------------------------------
# Rare-path events (a head recovery is news): plain lazy accessors.
# Incremented from gcs_store load, the runtime's recovery path, and the
# node daemon's re-dial loop.


def gcs_corrupt_records() -> Counter:
    from ray_tpu.util.metrics import Counter
    return Counter(
        "ray_tpu_gcs_corrupt_records_total",
        "gcs_store records skipped at load because they were truncated "
        "or failed their CRC (torn write through kill -9, disk "
        "corruption). Skipped with a warning, never fatal: the rest of "
        "the snapshot still restores.")


def head_recoveries() -> Counter:
    from ray_tpu.util.metrics import Counter
    return Counter(
        "ray_tpu_head_recoveries_total",
        "Head processes that started against a gcs_store with prior "
        "state and rehydrated the control plane from it (membership "
        "epochs, actor/serve/job records, object spill URIs).")


def head_recovery_replayed() -> Counter:
    from ray_tpu.util.metrics import Counter
    return Counter(
        "ray_tpu_head_recovery_replayed_total",
        "Records replayed from the gcs_store during a head recovery, "
        "by table (kv, actors, jobs, node_epochs, serve_deployments, "
        "spill_uris, object_replicas).",
        tag_keys=("kind",))


def daemon_redials() -> Counter:
    from ray_tpu.util.metrics import Counter
    return Counter(
        "ray_tpu_daemon_redials_total",
        "Daemon re-dial attempts against a lost head session, by how "
        "they ended: resumed (same head, channel re-attached), "
        "reregistered (full re-register — head restarted or resume "
        "rejected), gave_up (head_failover_window_s exhausted; the "
        "daemon exits).",
        tag_keys=("outcome",))


# -- serve resilience ------------------------------------------------------
# Control-plane events (a failover or a drain is news, not load): plain
# lazy accessors, no fast cells. Incremented from the serve router's
# completion callbacks and the controller's lifecycle loop.


def serve_failovers() -> Counter:
    from ray_tpu.util.metrics import Counter
    return Counter(
        "ray_tpu_serve_failovers_total",
        "Serve requests transparently re-assigned to another replica "
        "after a system failure (actor death / object loss) — never "
        "application exceptions.")


def serve_drained() -> Counter:
    from ray_tpu.util.metrics import Counter
    return Counter(
        "ray_tpu_serve_drained_total",
        "Replicas retired through the DRAINING state, by outcome "
        "(clean = in-flight requests reached zero; timeout = killed "
        "with requests still running after the drain window).",
        tag_keys=("outcome",))


def serve_health_check_failures() -> Counter:
    from ray_tpu.util.metrics import Counter
    return Counter(
        "ray_tpu_serve_health_check_failures_total",
        "Failed replica health probes (check_health raised or timed "
        "out); a replica is replaced after the consecutive-failure "
        "threshold.")


def serve_shed() -> Counter:
    from ray_tpu.util.metrics import Counter
    return Counter(
        "ray_tpu_serve_shed_total",
        "Serve requests fast-failed with BackPressureError because the "
        "deployment's max_queued_requests cap was hit (HTTP 503 via "
        "the proxy).")


# -- serve signal plane ----------------------------------------------------
# Per-deployment traffic series the autoscaler reads from the head's
# time-series store (qps, p95, queue depth, replica count). Incremented
# from the router's assign/settle path — serve settles are not the task
# hot path, so these touch the registry directly.


def serve_requests() -> Counter:
    from ray_tpu.util.metrics import Counter
    return Counter(
        "ray_tpu_serve_requests_total",
        "Serve requests settled (completed or raised), per deployment; "
        "the windowed rate of this series is the deployment's qps.",
        tag_keys=("deployment",))


def serve_request_latency() -> "Histogram":
    from ray_tpu.util.metrics import Histogram
    return Histogram(
        "ray_tpu_serve_request_latency_seconds",
        "End-to-end serve request latency at the router (assign to "
        "settle, including queueing and retries).",
        boundaries=(0.001, 0.005, 0.01, 0.05, 0.1, 0.25, 0.5, 1.0,
                    2.5, 5.0, 10.0, 30.0),
        tag_keys=("deployment",))


def serve_queue_depth() -> Gauge:
    from ray_tpu.util.metrics import Gauge
    return Gauge(
        "ray_tpu_serve_queue_depth",
        "Outstanding (assigned, unsettled) serve requests at a router, "
        "per deployment.",
        tag_keys=("deployment",))


def serve_replicas() -> Gauge:
    from ray_tpu.util.metrics import Gauge
    return Gauge(
        "ray_tpu_serve_replicas",
        "Replica count in the router's current routing table, per "
        "deployment (refreshed on every controller long-poll).",
        tag_keys=("deployment",))


# -- serve autoscaler + batching engines -----------------------------------
# Actuation-plane series: the controller's autoscale pass sets the
# target gauge every pass (so target-vs-actual graphs exist at steady
# state) and counts actuated decisions; the batching engines gauge
# their live operating point.


def serve_target_replicas() -> Gauge:
    from ray_tpu.util.metrics import Gauge
    return Gauge(
        "ray_tpu_serve_target_replicas",
        "Autoscaler's desired replica count per deployment (compare "
        "with ray_tpu_serve_replicas for target-vs-actual).",
        tag_keys=("deployment",))


def serve_autoscale_decisions() -> Counter:
    from ray_tpu.util.metrics import Counter
    return Counter(
        "ray_tpu_serve_autoscale_decisions_total",
        "Actuated autoscaling decisions (replica target changed), per "
        "deployment and direction.",
        tag_keys=("deployment", "direction"))


def serve_batch_size() -> Gauge:
    from ray_tpu.util.metrics import Gauge
    return Gauge(
        "ray_tpu_serve_batch_size",
        "Size of the last executed @serve.batch batch, per batched "
        "function (adaptive batching moves this with load).",
        tag_keys=("fn",))


def serve_batch_size_limit() -> Gauge:
    from ray_tpu.util.metrics import Gauge
    return Gauge(
        "ray_tpu_serve_batch_size_limit",
        "Current adaptive max-batch-size operating point of a "
        "@serve.batch queue (AIMD-tuned against the latency budget).",
        tag_keys=("fn",))


def serve_decode_active_slots() -> Gauge:
    from ray_tpu.util.metrics import Gauge
    return Gauge(
        "ray_tpu_serve_decode_active_slots",
        "Occupied slots in a continuous-batching decode loop, per "
        "engine (fixed-shape pjit batch; free slots admit new "
        "sequences at iteration boundaries).",
        tag_keys=("engine",))


def serve_decode_admitted() -> Counter:
    from ray_tpu.util.metrics import Counter
    return Counter(
        "ray_tpu_serve_decode_admitted_total",
        "Sequences admitted into a continuous-batching decode loop, "
        "by admission kind (fresh = loop was idle, running = joined a "
        "live decode batch at an iteration boundary).",
        tag_keys=("engine", "kind"))


# -- control-loop saturation -----------------------------------------------


def loop_lag() -> Gauge:
    from ray_tpu.util.metrics import Gauge
    return Gauge(
        "ray_tpu_loop_lag_seconds",
        "Scheduling lag of a control loop: how far past its intended "
        "period/deadline the loop actually woke (head membership sweep, "
        "dashboard asyncio loop, metrics agent ticks).",
        tag_keys=("loop",))


# -- continuous profiling --------------------------------------------------


def profile_samples() -> Counter:
    from ray_tpu.util.metrics import Counter
    return Counter(
        "ray_tpu_profile_samples_total",
        "Thread stacks sampled by this process's continuous "
        "ProfilerAgent (profiling.py; RAY_TPU_PROFILE_HZ ticks x "
        "threads walked).")


def profile_batches_dropped() -> Counter:
    from ray_tpu.util.metrics import Counter
    return Counter(
        "ray_tpu_profile_batches_dropped_total",
        "profile_batch publishes that failed (no live head session / "
        "full sender); the samples are refunded into the accumulator "
        "and ride the next tick.")


# -- dataplane flow observability ------------------------------------------


def flow_batches_dropped() -> Counter:
    from ray_tpu.util.metrics import Counter
    return Counter(
        "ray_tpu_flow_batches_dropped_total",
        "flow_batch publishes that failed (no live head session / full "
        "sender); the transfer records are refunded into the "
        "FlowRecorder and ride the next tick.")


def transfer_inflight_bytes() -> Gauge:
    from ray_tpu.util.metrics import Gauge
    return Gauge(
        "ray_tpu_transfer_inflight_bytes",
        "Object payload bytes currently mid-pull in this process "
        "(admission granted, body not yet landed) — the FlowRecorder's "
        "in-flight gauge.")


# -- alerting plane / cluster events ---------------------------------------


def alerts_transitions() -> Counter:
    from ray_tpu.util.metrics import Counter
    return Counter(
        "ray_tpu_alerts_transitions_total",
        "Alert state-machine transitions by the state entered (firing = "
        "a rule breached past its hold; resolved = the breach cleared).",
        tag_keys=("state",))


def cluster_events() -> Counter:
    from ray_tpu.util.metrics import Counter
    return Counter(
        "ray_tpu_cluster_events_total",
        "Events appended to the head's cluster event journal "
        "(_private/events.py), by severity.",
        tag_keys=("severity",))


# -- train fault tolerance -------------------------------------------------
# Gang lifecycle events (a restart or a persisted checkpoint is news,
# not load): plain lazy accessors, no fast cells. Incremented from the
# BackendExecutor restart loop and the durable CheckpointManager.


def train_gang_restarts() -> Counter:
    from ray_tpu.util.metrics import Counter
    return Counter(
        "ray_tpu_train_gang_restarts_total",
        "Whole-gang train restarts from the latest checkpoint, by cause "
        "(system = worker/daemon death or failed liveness probe; app = "
        "the train loop raised).",
        tag_keys=("cause",))


def train_checkpoints_persisted() -> Counter:
    from ray_tpu.util.metrics import Counter
    return Counter(
        "ray_tpu_train_checkpoints_persisted_total",
        "Reported train checkpoints persisted durably through the "
        "storage_path spill backend (what a gang restart resumes from).")


def train_checkpoint_persist_failures() -> Counter:
    from ray_tpu.util.metrics import Counter
    return Counter(
        "ray_tpu_train_checkpoint_persist_failures_total",
        "Reported checkpoints whose durable persist raised SpillFailure "
        "(training continues on the in-memory copy; a gang restart "
        "would resume from an older checkpoint). Watched by the "
        "checkpoint_persist_failures alert rule.")


# -- sharded checkpoints ---------------------------------------------------
# Per-rank sharded saves (train/_internal/sharded_checkpoint.py): every
# rank writes only its local shard, the manifest commit is driver-side.


def train_ckpt_shard_bytes() -> Counter:
    from ray_tpu.util.metrics import Counter
    return Counter(
        "ray_tpu_train_ckpt_shard_bytes_total",
        "Bytes of checkpoint shard files written, by rank — N live rank "
        "labels per save is the signature of the parallel sharded path "
        "(a single-writer monolithic save only moves rank 0).",
        tag_keys=("rank",))


def train_ckpt_save_seconds() -> Histogram:
    from ray_tpu.util.metrics import Histogram
    return Histogram(
        "ray_tpu_train_ckpt_save_seconds",
        "End-to-end sharded save wall time: slowest rank's shard write "
        "plus the manifest commit.",
        boundaries=(0.01, 0.05, 0.25, 1.0, 5.0, 30.0, 120.0, 600.0))


def train_ckpt_restore_seconds() -> Histogram:
    from ray_tpu.util.metrics import Histogram
    return Histogram(
        "ray_tpu_train_ckpt_restore_seconds",
        "Per-rank sharded checkpoint restore wall time (byte-range "
        "reads + reassembly; includes reshard overlap math when the "
        "mesh changed).",
        boundaries=(0.01, 0.05, 0.25, 1.0, 5.0, 30.0, 120.0, 600.0))


def train_reshards() -> Counter:
    from ray_tpu.util.metrics import Counter
    return Counter(
        "ray_tpu_train_reshards_total",
        "Sharded-checkpoint resumes by mesh-change direction: shrink "
        "(elastic gang came back smaller), grow, or same (plain "
        "restart).",
        tag_keys=("direction",))


def train_ckpt_orphans_gc() -> Counter:
    from ray_tpu.util.metrics import Counter
    return Counter(
        "ray_tpu_train_ckpt_orphans_gc_total",
        "Orphaned checkpoint files garbage-collected at index load: "
        "shard files no committed manifest references (mid-save crash "
        "debris) and manifests with missing/corrupt shards.")


def channel_bytes_sent() -> Counter:
    from ray_tpu.util.metrics import Counter
    return Counter(
        "ray_tpu_channel_bytes_sent_total",
        "Bytes written to session channels (seq envelope + payload), "
        "fed by the per-frame fast cell.")


def channel_acks_sent() -> Counter:
    from ray_tpu.util.metrics import Counter
    return Counter(
        "ray_tpu_channel_acks_sent_total",
        "Pure ack frames (seq 0) flushed by the deferred-ack timer — "
        "acks piggybacked on regular traffic are not counted here.")
