"""Persistent GCS tables: control-plane state that survives head death.

The analog of the reference's GCS storage layer (gcs_server.cc:523 —
in-memory vs Redis store; gcs/store_client/redis_store_client.h): the
head persists its control-plane tables (internal KV, named-actor
registry, job records) to a single file, atomically rewritten on every
mutation. A NEW driver started with the same ``gcs_store_path`` (and
head port) restores them: daemons reconnect with their resident actor
ids, the head rebinds named actors to the live daemon instances, and
``get_actor(name)`` answers again — head death is no longer cluster
death.

State that is deliberately NOT persisted (matching the reference's
in-memory-GCS behavior for non-table state): in-flight tasks, object
refs owned by the dead driver, and placement-group reservations —
the driver that owned them is gone.
"""

from __future__ import annotations

import os
import pickle
import threading
from typing import Any, Dict, Optional


class GcsStore:
    """One pickle file holding all persisted tables. Mutations rewrite
    atomically (tmp + rename) — the file is always a consistent
    snapshot, even through kill -9."""

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()
        self.kv: Dict[str, Dict[bytes, bytes]] = {}
        # actor_id hex → {"name", "namespace", "max_restarts",
        #                 "max_concurrency"}
        self.actors: Dict[str, Dict[str, Any]] = {}
        self.jobs: Dict[str, Dict[str, Any]] = {}
        # node_id hex → incarnation epoch (v9 membership fencing). The
        # counter is the max recorded value, so a restarted head keeps
        # minting ABOVE every epoch it handed out in a previous life —
        # a partitioned daemon returning across a head restart is still
        # recognizably stale.
        self.node_epochs: Dict[str, int] = {}
        if os.path.exists(path):
            try:
                with open(path, "rb") as f:
                    data = pickle.load(f)
                self.kv = data.get("kv", {})
                self.actors = data.get("actors", {})
                self.jobs = data.get("jobs", {})
                self.node_epochs = data.get("node_epochs", {})
            except Exception:  # noqa: BLE001 - torn file: start fresh
                pass

    def _save_locked(self) -> None:
        tmp = self.path + ".tmp"
        os.makedirs(os.path.dirname(os.path.abspath(self.path)),
                    exist_ok=True)
        with open(tmp, "wb") as f:
            pickle.dump({"kv": self.kv, "actors": self.actors,
                         "jobs": self.jobs,
                         "node_epochs": self.node_epochs}, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)

    # -- node epochs (v9 membership fencing) ---------------------------

    def record_node_epoch(self, node_id_hex: str, epoch: int) -> None:
        with self._lock:
            self.node_epochs[node_id_hex] = int(epoch)
            self._save_locked()

    def max_node_epoch(self) -> int:
        """Floor for the head's epoch counter: mint strictly above
        every epoch any previous head life handed out."""
        with self._lock:
            return max(self.node_epochs.values(), default=0)

    # -- internal KV (reference: gcs_kv_manager.h InternalKV) ----------

    def kv_put(self, namespace: str, key: bytes, value: bytes,
               overwrite: bool = True) -> bool:
        """Returns already_exists (reference internal_kv semantics)."""
        with self._lock:
            ns = self.kv.setdefault(namespace, {})
            existed = key in ns
            if overwrite or not existed:
                ns[key] = value
                self._save_locked()
            return existed

    def kv_get(self, namespace: str, key: bytes) -> Optional[bytes]:
        with self._lock:
            return self.kv.get(namespace, {}).get(key)

    def kv_del(self, namespace: str, key: bytes) -> bool:
        with self._lock:
            existed = self.kv.get(namespace, {}).pop(key, None) is not None
            if existed:
                self._save_locked()
            return existed

    def kv_keys(self, namespace: str, prefix: bytes = b"") -> list:
        with self._lock:
            return [k for k in self.kv.get(namespace, {})
                    if k.startswith(prefix)]

    # -- named actors --------------------------------------------------

    def record_actor(self, actor_id_hex: str, name: str, namespace: str,
                     max_restarts: int, max_concurrency: int,
                     cls_bytes: Optional[bytes] = None,
                     resources: Optional[Dict[str, float]] = None,
                     concurrency_groups: Optional[Dict[str, int]] = None,
                     lifetime: Optional[str] = None,
                     num_restarts: int = 0,
                     creation_payload: Optional[bytes] = None) -> None:
        """cls_bytes: the pickled actor class, so a restarted head can
        rebuild handles (method introspection) for rebound actors.
        resources: the creation-time reservation, re-acquired on the
        actor's node at rebind so a restarted head cannot double-book
        what the resident instance still consumes.
        lifetime/num_restarts/creation_payload: detached actors carry
        their full restart budget AND pickled __init__ (args, kwargs)
        across head restarts — a rebound detached actor can still be
        restarted elsewhere after its node dies."""
        with self._lock:
            self.actors[actor_id_hex] = {
                "name": name, "namespace": namespace,
                "max_restarts": max_restarts,
                "max_concurrency": max_concurrency,
                "cls_bytes": cls_bytes,
                "resources": dict(resources or {}),
                "concurrency_groups": dict(concurrency_groups or {}),
                "lifetime": lifetime,
                "num_restarts": num_restarts,
                "creation_payload": creation_payload,
            }
            self._save_locked()

    def update_actor(self, actor_id_hex: str, **fields: Any) -> None:
        """Merge fields into an existing record (restart-budget burn-down:
        ``num_restarts`` must survive a SECOND head restart too). No-op
        for unknown actors — a racing kill wins."""
        with self._lock:
            rec = self.actors.get(actor_id_hex)
            if rec is None:
                return
            rec.update(fields)
            self._save_locked()

    def remove_actor(self, actor_id_hex: str) -> None:
        with self._lock:
            if self.actors.pop(actor_id_hex, None) is not None:
                self._save_locked()

    # -- jobs ----------------------------------------------------------

    def record_job(self, job_id: str, record: Dict[str, Any]) -> None:
        with self._lock:
            self.jobs[job_id] = record
            self._save_locked()
