"""Persistent GCS tables: control-plane state that survives head death.

The analog of the reference's GCS storage layer (gcs_server.cc:523 —
in-memory vs Redis store; gcs/store_client/redis_store_client.h): the
head persists its control-plane tables to a single file, atomically
rewritten on every mutation. A NEW driver started with the same
``gcs_store_path`` (and head port) restores them: daemons reconnect
with their resident actor ids, the head rebinds named actors to the
live daemon instances, serve deployments redeploy from their persisted
configs, and durable spill URIs rejoin the object directory — head
death is no longer cluster death.

On-disk format (v2): a magic header followed by independently framed
records — ``[u32 length][u32 crc32][pickle((kind, key, value))]``.
Every write goes tmp → flush+fsync → ``os.replace`` (the same
discipline as spill.py), so the file is always a complete snapshot;
per-record CRCs mean a flipped byte or a truncated tail costs only the
damaged records, which are skipped with a counted warning
(``ray_tpu_gcs_corrupt_records_total``) instead of raising at load.
Legacy v1 files (one monolithic pickle) still load.

Tables:

* ``kv`` — internal KV (reference: gcs_kv_manager.h InternalKV)
* ``actors`` — named/detached actor records (rebind after restart)
* ``jobs`` — driver job records (GcsJobManager analog)
* ``node_epochs`` — incarnation epochs (wire-v9 fencing floor)
* ``serve`` — serve deployment configs + autoscaler targets
* ``spill_uris`` / ``object_replicas`` — the durable half of the
  object directory (spill-URI restore survives head death; replica
  holders are recovered for accounting — their node ids are reminted
  on re-registration)
* ``meta`` — head incarnation counter + last-recovery record

State that is deliberately NOT persisted (matching the reference's
in-memory-GCS behavior for non-table state): in-flight tasks, object
refs owned by the dead driver, and placement-group reservations —
the driver that owned them is gone.
"""

from __future__ import annotations

import logging
import os
import pickle
import struct
import threading
import time
import zlib
from typing import Any, Dict, Iterator, Optional, Tuple

logger = logging.getLogger(__name__)

#: v2 header. v1 files begin with a pickle opcode (0x80), never this.
_MAGIC = b"RTGCS2\n"
_FRAME = struct.Struct("<II")  # payload length, crc32(payload)

#: Replica-holder updates arrive on hot paths (pull-learn); they are a
#: cache, not the durable tier, so their saves coalesce to at most one
#: rewrite per this many seconds (any unthrottled save flushes them).
_THROTTLE_S = 1.0


def _count_corrupt(n: int = 1) -> None:
    """Best-effort metric bump (the store must work in tools/tests
    without a metrics registry)."""
    try:
        from ray_tpu._private import builtin_metrics
        builtin_metrics.gcs_corrupt_records().inc(n)
    except Exception:  # noqa: BLE001 - metrics are optional here
        pass


class GcsStore:
    """One record-framed file holding all persisted tables. Mutations
    rewrite atomically (tmp + fsync + rename) — the file is always a
    consistent snapshot, even through kill -9; a damaged record is
    skipped at load, never fatal."""

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()
        self.kv: Dict[str, Dict[bytes, bytes]] = {}
        # actor_id hex → {"name", "namespace", "max_restarts",
        #                 "max_concurrency", ...}
        self.actors: Dict[str, Dict[str, Any]] = {}
        self.jobs: Dict[str, Dict[str, Any]] = {}
        # node_id hex → incarnation epoch (v9 membership fencing). The
        # counter is the max recorded value, so a restarted head keeps
        # minting ABOVE every epoch it handed out in a previous life —
        # a partitioned daemon returning across a head restart is still
        # recognizably stale.
        self.node_epochs: Dict[str, int] = {}
        # deployment name → serve deployment record (pickled def +
        # init payload + scale target); the authoritative copy the
        # serve controller replays after a head restart.
        self.serve_deployments: Dict[str, Dict[str, Any]] = {}
        # daemon object key → (uri, size): durable spill locations
        # announced by daemons — the restore tier that still works when
        # BOTH the head and the spilling daemon died.
        self.spill_uris: Dict[str, Tuple[str, int]] = {}
        # object_id hex → [node_id hex, ...]: in-memory replica holders.
        # Recovered for accounting only (node ids are reminted when
        # daemons re-register), and saved throttled — they are learned
        # on pull paths and must not fsync per update.
        self.object_replicas: Dict[str, list] = {}
        # {"incarnation": int, "last_recovery": {...}} — bumped by
        # begin_head_incarnation() once per head life.
        self.meta: Dict[str, Any] = {}
        #: Records skipped at load (CRC mismatch / truncated tail /
        #: undecodable payload). Also counted into
        #: ray_tpu_gcs_corrupt_records_total.
        self.corrupt_records = 0
        #: True when the file existed and yielded at least one record —
        #: the signal that this head is a RECOVERY, not a first boot.
        self.had_prior_state = False
        self._dirty = False
        self._last_save = 0.0
        if os.path.exists(path):
            self._load()

    # -- load ----------------------------------------------------------

    def _load(self) -> None:
        try:
            with open(self.path, "rb") as f:
                blob = f.read()
        except OSError:
            logger.exception("could not read gcs store %s", self.path)
            return
        if not blob:
            return
        if blob.startswith(_MAGIC):
            n = 0
            for kind, key, value in self._iter_file_records(blob):
                self._apply_record(kind, key, value)
                n += 1
            self.had_prior_state = n > 0
            return
        # Legacy v1: one monolithic pickle of the table dict.
        try:
            data = pickle.loads(blob)
            self.kv = data.get("kv", {})
            self.actors = data.get("actors", {})
            self.jobs = data.get("jobs", {})
            self.node_epochs = data.get("node_epochs", {})
            self.had_prior_state = bool(
                self.kv or self.actors or self.jobs or self.node_epochs)
        except Exception:  # noqa: BLE001 - torn v1 file: start fresh,
            # but COUNT it — silent data loss is the bug this format
            # replaces.
            self.corrupt_records += 1
            _count_corrupt()
            logger.warning(
                "gcs store %s is unreadable (legacy format, torn "
                "write?); starting fresh", self.path)

    def _iter_file_records(self, blob: bytes
                           ) -> Iterator[Tuple[str, Any, Any]]:
        """Yield intact (kind, key, value) records; skip+count damaged
        ones. A bad payload with intact framing only loses itself; a
        truncated tail loses the records past the tear."""
        off = len(_MAGIC)
        end = len(blob)
        while off < end:
            if off + _FRAME.size > end:
                self._note_corrupt("truncated record header")
                return
            length, crc = _FRAME.unpack_from(blob, off)
            off += _FRAME.size
            payload = blob[off:off + length]
            off += length
            if len(payload) < length:
                self._note_corrupt("truncated record payload")
                return
            if zlib.crc32(payload) != crc:
                self._note_corrupt("crc mismatch")
                continue
            try:
                kind, key, value = pickle.loads(payload)
            except Exception:  # noqa: BLE001 - undecodable record
                self._note_corrupt("undecodable payload")
                continue
            yield kind, key, value

    def _note_corrupt(self, why: str) -> None:
        self.corrupt_records += 1
        _count_corrupt()
        logger.warning("gcs store %s: skipping corrupt record (%s)",
                       self.path, why)

    def _apply_record(self, kind: str, key: Any, value: Any) -> None:
        if kind == "kv":
            ns, k = key
            self.kv.setdefault(ns, {})[k] = value
        elif kind == "actor":
            self.actors[key] = value
        elif kind == "job":
            self.jobs[key] = value
        elif kind == "node_epoch":
            self.node_epochs[key] = int(value)
        elif kind == "serve":
            self.serve_deployments[key] = value
        elif kind == "spill_uri":
            self.spill_uris[key] = (value[0], int(value[1]))
        elif kind == "object_replicas":
            self.object_replicas[key] = list(value)
        elif kind == "meta":
            self.meta[key] = value
        # Unknown kinds from a newer build are ignored (and dropped on
        # the next rewrite) rather than fatal — forward compatibility.

    # -- save ----------------------------------------------------------

    def _iter_records(self) -> Iterator[Tuple[str, Any, Any]]:
        for ns, table in self.kv.items():
            for k, v in table.items():
                yield "kv", (ns, k), v
        for key, rec in self.actors.items():
            yield "actor", key, rec
        for key, rec in self.jobs.items():
            yield "job", key, rec
        for key, epoch in self.node_epochs.items():
            yield "node_epoch", key, epoch
        for key, rec in self.serve_deployments.items():
            yield "serve", key, rec
        for key, rec in self.spill_uris.items():
            yield "spill_uri", key, rec
        for key, rec in self.object_replicas.items():
            yield "object_replicas", key, rec
        for key, rec in self.meta.items():
            yield "meta", key, rec

    def _save_locked(self) -> None:
        tmp = self.path + ".tmp"
        os.makedirs(os.path.dirname(os.path.abspath(self.path)),
                    exist_ok=True)
        with open(tmp, "wb") as f:
            f.write(_MAGIC)
            for kind, key, value in self._iter_records():
                try:
                    payload = pickle.dumps((kind, key, value))
                except Exception:  # noqa: BLE001 - one unpicklable
                    # record must not take the whole snapshot down.
                    logger.warning("gcs store: dropping unpicklable "
                                   "%s record %r", kind, key)
                    continue
                f.write(_FRAME.pack(len(payload), zlib.crc32(payload)))
                f.write(payload)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)
        self._dirty = False
        self._last_save = time.monotonic()

    def _save_throttled_locked(self) -> None:
        """Coalesced save for hot-path cache tables (replica holders):
        at most one rewrite per _THROTTLE_S; anything deferred flushes
        with the next unthrottled save. Losing <1s of replica-holder
        updates to a crash is fine — they are an optimization tier."""
        if time.monotonic() - self._last_save >= _THROTTLE_S:
            self._save_locked()
        else:
            self._dirty = True

    def flush(self) -> None:
        """Write any deferred (throttled) updates now."""
        with self._lock:
            if self._dirty:
                self._save_locked()

    def counts(self) -> Dict[str, int]:
        """Per-table record counts (recovery accounting + status)."""
        with self._lock:
            return {
                "kv": sum(len(t) for t in self.kv.values()),
                "actors": len(self.actors),
                "jobs": len(self.jobs),
                "node_epochs": len(self.node_epochs),
                "serve_deployments": len(self.serve_deployments),
                "spill_uris": len(self.spill_uris),
                "object_replicas": len(self.object_replicas),
            }

    # -- head incarnations (failover accounting) -----------------------

    def head_incarnation(self) -> int:
        with self._lock:
            return int((self.meta.get("head") or {}).get(
                "incarnation", 0))

    def begin_head_incarnation(
            self, recovery: Optional[Dict[str, Any]] = None) -> int:
        """Bump the head incarnation counter (once per head life) and
        record the recovery summary; returns the new incarnation."""
        with self._lock:
            rec = dict(self.meta.get("head") or {})
            inc = int(rec.get("incarnation", 0)) + 1
            rec["incarnation"] = inc
            rec["started_at"] = time.time()
            if recovery is not None:
                rec["last_recovery"] = recovery
            self.meta["head"] = rec
            self._save_locked()
            return inc

    def last_recovery(self) -> Optional[Dict[str, Any]]:
        with self._lock:
            return (self.meta.get("head") or {}).get("last_recovery")

    # -- node epochs (v9 membership fencing) ---------------------------

    def record_node_epoch(self, node_id_hex: str, epoch: int) -> None:
        with self._lock:
            self.node_epochs[node_id_hex] = int(epoch)
            self._save_locked()

    def max_node_epoch(self) -> int:
        """Floor for the head's epoch counter: mint strictly above
        every epoch any previous head life handed out."""
        with self._lock:
            return max(self.node_epochs.values(), default=0)

    # -- internal KV (reference: gcs_kv_manager.h InternalKV) ----------

    def kv_put(self, namespace: str, key: bytes, value: bytes,
               overwrite: bool = True) -> bool:
        """Returns already_exists (reference internal_kv semantics)."""
        with self._lock:
            ns = self.kv.setdefault(namespace, {})
            existed = key in ns
            if overwrite or not existed:
                ns[key] = value
                self._save_locked()
            return existed

    def kv_get(self, namespace: str, key: bytes) -> Optional[bytes]:
        with self._lock:
            return self.kv.get(namespace, {}).get(key)

    def kv_del(self, namespace: str, key: bytes) -> bool:
        with self._lock:
            existed = self.kv.get(namespace, {}).pop(key, None) is not None
            if existed:
                self._save_locked()
            return existed

    def kv_keys(self, namespace: str, prefix: bytes = b"") -> list:
        with self._lock:
            return [k for k in self.kv.get(namespace, {})
                    if k.startswith(prefix)]

    # -- named actors --------------------------------------------------

    def record_actor(self, actor_id_hex: str, name: str, namespace: str,
                     max_restarts: int, max_concurrency: int,
                     cls_bytes: Optional[bytes] = None,
                     resources: Optional[Dict[str, float]] = None,
                     concurrency_groups: Optional[Dict[str, int]] = None,
                     lifetime: Optional[str] = None,
                     num_restarts: int = 0,
                     creation_payload: Optional[bytes] = None) -> None:
        """cls_bytes: the pickled actor class, so a restarted head can
        rebuild handles (method introspection) for rebound actors.
        resources: the creation-time reservation, re-acquired on the
        actor's node at rebind so a restarted head cannot double-book
        what the resident instance still consumes.
        lifetime/num_restarts/creation_payload: detached actors carry
        their full restart budget AND pickled __init__ (args, kwargs)
        across head restarts — a rebound detached actor can still be
        restarted elsewhere after its node dies."""
        with self._lock:
            self.actors[actor_id_hex] = {
                "name": name, "namespace": namespace,
                "max_restarts": max_restarts,
                "max_concurrency": max_concurrency,
                "cls_bytes": cls_bytes,
                "resources": dict(resources or {}),
                "concurrency_groups": dict(concurrency_groups or {}),
                "lifetime": lifetime,
                "num_restarts": num_restarts,
                "creation_payload": creation_payload,
            }
            self._save_locked()

    def update_actor(self, actor_id_hex: str, **fields: Any) -> None:
        """Merge fields into an existing record (restart-budget burn-down:
        ``num_restarts`` must survive a SECOND head restart too). No-op
        for unknown actors — a racing kill wins."""
        with self._lock:
            rec = self.actors.get(actor_id_hex)
            if rec is None:
                return
            rec.update(fields)
            self._save_locked()

    def remove_actor(self, actor_id_hex: str) -> None:
        with self._lock:
            if self.actors.pop(actor_id_hex, None) is not None:
                self._save_locked()

    # -- jobs ----------------------------------------------------------

    def record_job(self, job_id: str, record: Dict[str, Any]) -> None:
        with self._lock:
            self.jobs[job_id] = record
            self._save_locked()

    # -- serve deployments ---------------------------------------------

    def record_serve_deployment(self, name: str,
                                record: Dict[str, Any]) -> None:
        """The controller persists the full deploy payload (pickled def,
        init args, scale target, autoscaling config) so a head restart
        can replay the deploy against a fresh controller."""
        with self._lock:
            self.serve_deployments[name] = record
            self._save_locked()

    def remove_serve_deployment(self, name: str) -> None:
        with self._lock:
            if self.serve_deployments.pop(name, None) is not None:
                self._save_locked()

    # -- object directory (durable tiers) ------------------------------

    def record_spill_uri(self, key: str, uri: str, size: int) -> None:
        with self._lock:
            self.spill_uris[key] = (uri, int(size))
            self._save_locked()

    def remove_spill_uri(self, key: str) -> None:
        with self._lock:
            if self.spill_uris.pop(key, None) is not None:
                # Retractions ride the throttle: a mass free must not
                # fsync per object.
                self._save_throttled_locked()

    def record_object_replica(self, oid_hex: str, node_hex: str) -> None:
        with self._lock:
            holders = self.object_replicas.setdefault(oid_hex, [])
            if node_hex not in holders:
                holders.append(node_hex)
                self._save_throttled_locked()

    def remove_object_replicas(self, oid_hex: str) -> None:
        with self._lock:
            if self.object_replicas.pop(oid_hex, None) is not None:
                self._save_throttled_locked()
