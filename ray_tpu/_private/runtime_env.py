"""Runtime environments (analog of python/ray/_private/runtime_env/).

The reference materializes per-task/actor environments (conda/pip/
working_dir/py_modules/env_vars) through a per-node agent before the worker
starts (dashboard/modules/runtime_env/runtime_env_agent.py:162). On the
in-process thread backend the environment is necessarily process-shared, so
the supported subset is what composes safely:

* ``env_vars`` — applied around task execution under a global lock (visible
  to the task body via os.environ, restored after).
* ``working_dir`` / ``py_modules`` — validated + prepended to sys.path once
  per unique URI (the reference's URI cache, _private/runtime_env/uri_cache.py).
* ``pip`` / ``conda`` — validated and recorded; actual installation requires
  the process worker backend and is rejected with RuntimeEnvSetupError unless
  the packages are already importable.
"""

from __future__ import annotations

import importlib.util
import os
import sys
import threading
from typing import Any, Dict, Optional

from ray_tpu.exceptions import RuntimeEnvSetupError

_KNOWN_FIELDS = {"env_vars", "working_dir", "py_modules", "pip", "conda",
                 "container", "config", "excludes", "worker_process"}

_path_cache: set = set()
_env_lock = threading.RLock()


def validate(runtime_env: Optional[Dict[str, Any]]) -> Dict[str, Any]:
    if not runtime_env:
        return {}
    unknown = set(runtime_env) - _KNOWN_FIELDS
    if unknown:
        raise ValueError(
            f"Unknown runtime_env fields {sorted(unknown)}; supported: "
            f"{sorted(_KNOWN_FIELDS)}")
    env_vars = runtime_env.get("env_vars")
    if env_vars is not None:
        if not isinstance(env_vars, dict) or not all(
                isinstance(k, str) and isinstance(v, str)
                for k, v in env_vars.items()):
            raise ValueError("runtime_env['env_vars'] must be Dict[str, str]")
    wd = runtime_env.get("working_dir")
    if wd is not None and not os.path.isdir(wd):
        raise ValueError(
            f"runtime_env['working_dir'] {wd!r} is not a directory")
    if runtime_env.get("pip") and runtime_env.get("conda"):
        # Same exclusion as the reference's validation (a conda env
        # already pins its own pip set; two interpreter-selecting
        # plugins cannot both win).
        raise ValueError(
            "runtime_env cannot specify both 'pip' and 'conda'; put "
            "pip packages inside the conda spec's dependencies "
            "(- pip: [...]) instead")
    conda_spec = runtime_env.get("conda")
    if conda_spec is not None and not isinstance(conda_spec, (str, dict)):
        raise ValueError(
            "runtime_env['conda'] must be an env name (str) or an "
            "environment.yml-style dict")
    container = runtime_env.get("container")
    if container:
        # Accepted when a container engine exists (reference:
        # _private/runtime_env/container.py wraps workers in podman;
        # here the worker's framed protocol rides stdio through
        # `engine run -i` with /dev/shm shared for the object arena).
        from ray_tpu._private.worker_process import container_engine
        if not isinstance(container, dict) or not container.get("image"):
            raise ValueError(
                "runtime_env['container'] must be a dict with an "
                "'image' (and optional 'run_options': [str], "
                "'python': str)")
        if container_engine() is None:
            raise ValueError(
                "runtime_env['container'] needs a container engine: "
                "install docker or podman on every node (or set "
                "RAY_TPU_CONTAINER_ENGINE), or use 'conda'/'pip' for "
                "dependency isolation without containers.")
    return dict(runtime_env)


def setup(runtime_env: Dict[str, Any]) -> None:
    """One-time setup of the path-based parts (URI-cached)."""
    wd = runtime_env.get("working_dir")
    if wd:
        wd = os.path.abspath(wd)
        if wd not in _path_cache:
            sys.path.insert(0, wd)
            _path_cache.add(wd)
    for mod_path in runtime_env.get("py_modules") or []:
        mod_path = os.path.abspath(mod_path)
        parent = os.path.dirname(mod_path)
        if parent not in _path_cache:
            sys.path.insert(0, parent)
            _path_cache.add(parent)
    conda_spec = runtime_env.get("conda")
    if conda_spec:
        from ray_tpu._private.runtime_env_conda import (
            interpreter_matches)
        if not interpreter_matches(conda_spec):
            raise RuntimeEnvSetupError(
                f"runtime_env['conda'] = {conda_spec!r} requires a "
                "worker running under that environment's interpreter; "
                "this process is "
                f"{sys.executable}. Enable worker processes (the "
                "default) so the pool can lease a conda interpreter.")
    for pkg in runtime_env.get("pip") or []:
        # Shared resolver (runtime_env_pip.base_satisfies): version
        # specifiers included, dist-metadata fallback for module!=dist
        # names (scikit-learn -> sklearn).
        from ray_tpu._private.runtime_env_pip import base_satisfies
        if base_satisfies(pkg):
            continue
        raise RuntimeEnvSetupError(
            f"runtime_env['pip'] requires {pkg!r} which is not satisfied "
            "in this interpreter; in-process workers cannot install "
            "packages (no network). Pre-install it, use a pip venv "
            "worker (RAY_TPU_PIP_FIND_LINKS), or drop the requirement.")


class applied:
    """Context manager applying env_vars around a task body.

    The lock is held only while mutating os.environ (set on enter, restore
    on exit), NOT across the task body — holding it for the body would
    serialize every env_vars task and deadlock nested ``ray.get`` chains.
    The cost: concurrent tasks with *conflicting* env_vars can observe each
    other's values (os.environ is process-global on the thread backend; the
    reference gets true isolation from process workers)."""

    def __init__(self, runtime_env: Optional[Dict[str, Any]]):
        self._env_vars = (runtime_env or {}).get("env_vars") or {}
        self._saved: Dict[str, Optional[str]] = {}

    def __enter__(self):
        if not self._env_vars:
            return self
        with _env_lock:
            for k, v in self._env_vars.items():
                self._saved[k] = os.environ.get(k)
                os.environ[k] = v
        return self

    def __exit__(self, *exc):
        if not self._env_vars:
            return
        with _env_lock:
            for k, old in self._saved.items():
                if old is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = old
