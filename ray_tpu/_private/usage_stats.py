"""Usage stats: opt-out, local-only telemetry summary.

Analog of the reference's _private/usage/usage_lib.py:94 — collects
coarse usage counters per session. This rebuild never egresses anything:
the report is written to the session's local temp dir only, and
``RAY_TPU_USAGE_STATS_ENABLED=0`` disables even that.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict

_lock = threading.Lock()
_counters: Dict[str, int] = {}
_features: set = set()


def usage_stats_enabled() -> bool:
    return os.environ.get("RAY_TPU_USAGE_STATS_ENABLED", "1") != "0"


def record_library_usage(name: str) -> None:
    """Called by libraries on first use (train/tune/serve/data/rllib)."""
    if not usage_stats_enabled():
        return
    with _lock:
        _features.add(name)


def record_extra_usage_tag(key: str, value: int = 1) -> None:
    if not usage_stats_enabled():
        return
    with _lock:
        _counters[key] = _counters.get(key, 0) + value


def usage_report() -> Dict[str, Any]:
    import ray_tpu
    with _lock:
        return {
            "version": ray_tpu.__version__,
            "collected_at": time.time(),
            "libraries_used": sorted(_features),
            "counters": dict(_counters),
        }


def write_usage_report(session_dir: str) -> str:
    """Persist the report next to the session logs (never uploaded)."""
    path = os.path.join(session_dir, "usage_stats.json")
    os.makedirs(session_dir, exist_ok=True)
    with open(path, "w") as f:
        json.dump(usage_report(), f, indent=2)
    return path


def reset() -> None:
    with _lock:
        _counters.clear()
        _features.clear()
