"""Structured cluster event journal.

Analog of the reference's GCS-side event/export subsystem
(src/ray/util/event.h + dashboard event modules): significant cluster
transitions — the things we previously only *counted* — become typed
records an operator (or the alerting plane) can read back in order:
membership joins/deaths/fencing, serve replica lifecycle and drain
outcomes, train gang restarts, object spill/restore tiers, channel
reconnects, flight-recorder incidents, and every alert state
transition.

Two halves:

* A **process-local pending buffer**: :func:`emit` appends a sanitized
  record to a small bounded deque from any process (head, daemon,
  worker). ``MetricsAgent.poll_once`` drains it into each
  ``metrics_batch`` (the ``"events"`` field, riding the existing
  transport exactly like the EventStats piggyback), refunding on a
  dropped frame — no new wire frames, no hot-path registry work.
* The head-side :class:`EventJournal`: ``ClusterMetrics.update``
  ingests piggybacked events, stamps the origin node id, assigns a
  monotonic ``seq``, and appends to a bounded ring
  (``RAY_TPU_EVENTS_MAX``, <= 0 disables). With
  ``RAY_TPU_EVENTS_SPILL_URI`` set, the ring is persisted as JSONL
  through the spill-backend URI system (atomic write-then-rename), so
  a ``session://`` or ``mock-s3://`` journal survives head restarts
  and is reloaded on construction.

Timestamps are ``time.monotonic()`` stamped at head ingest (the
emitting process's clock is meaningless here); reads report ``age_s``.
Severities: ``info`` < ``warning`` < ``error`` < ``critical``.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

logger = logging.getLogger(__name__)

SEVERITIES = ("info", "warning", "error", "critical")

DEFAULT_EVENTS_MAX = 2048
#: Pending events a single process buffers between agent ticks; beyond
#: this the oldest are dropped (and counted in the drained batch).
PENDING_MAX = 512
#: Label hygiene bounds: events cross process boundaries and land in a
#: long-lived ring, so label cardinality and value size are capped at
#: emit time — a misbehaving caller cannot bloat the journal.
MAX_LABELS = 16
MAX_VALUE_LEN = 128
MAX_MESSAGE_LEN = 512
#: Durable persistence throttle: at most one ring rewrite per this many
#: seconds (the ring is bounded, so each write is small and atomic).
PERSIST_MIN_INTERVAL_S = 2.0
PERSIST_FILENAME = "cluster_events.jsonl"


def configured_events_max() -> int:
    """Journal ring bound; honors the documented uppercase env spelling
    first, then the flag table (live runtime config > env > default)."""
    raw = os.environ.get("RAY_TPU_EVENTS_MAX", "")
    if raw:
        try:
            return int(float(raw))
        except ValueError:
            pass
    from ray_tpu._private.ray_config import runtime_config_value
    return int(runtime_config_value("events_max", DEFAULT_EVENTS_MAX))


def configured_spill_uri() -> str:
    raw = os.environ.get("RAY_TPU_EVENTS_SPILL_URI")
    if raw is not None:
        return raw
    from ray_tpu._private.ray_config import runtime_config_value
    return str(runtime_config_value("events_spill_uri", ""))


def sanitize_labels(labels: Optional[Dict[str, Any]]) -> Dict[str, str]:
    """str->str coercion with bounded cardinality and value length."""
    out: Dict[str, str] = {}
    if not labels:
        return out
    for k, v in labels.items():
        if len(out) >= MAX_LABELS:
            break
        out[str(k)[:MAX_VALUE_LEN]] = str(v)[:MAX_VALUE_LEN]
    return out


# ---------------------------------------------------------------------------
# Process-local pending buffer (any process; drained by the MetricsAgent)
# ---------------------------------------------------------------------------

_pending: deque = deque(maxlen=PENDING_MAX)
_pending_lock = threading.Lock()


def emit(source: str, message: str, *, severity: str = "info",
         node_id: Optional[str] = None,
         labels: Optional[Dict[str, Any]] = None) -> None:
    """Queue one event from this process. Cheap (a deque append under a
    lock), never raises — instrumentation must not break its host."""
    try:
        if severity not in SEVERITIES:
            severity = "info"
        rec = {
            "severity": severity,
            "source": str(source)[:MAX_VALUE_LEN],
            "message": str(message)[:MAX_MESSAGE_LEN],
            "labels": sanitize_labels(labels),
        }
        if node_id:
            rec["node_id"] = str(node_id)
        with _pending_lock:
            _pending.append(rec)
    except Exception:  # noqa: BLE001 - emitters must never be hurt
        pass


def drain_pending() -> List[Dict[str, Any]]:
    """Take (and clear) this process's queued events — called by
    ``MetricsAgent.poll_once`` when building a batch."""
    with _pending_lock:
        if not _pending:
            return []
        out = list(_pending)
        _pending.clear()
        return out


def refund_pending(events: List[Dict[str, Any]]) -> None:
    """Re-queue events whose batch was dropped (a broken channel); they
    ride the next tick instead of vanishing."""
    if not events:
        return
    with _pending_lock:
        _pending.extendleft(reversed(events))


# ---------------------------------------------------------------------------
# Head-side journal
# ---------------------------------------------------------------------------


class EventJournal:
    """Bounded, ordered ring of cluster events with optional durable
    persistence through a spill-backend URI."""

    def __init__(self, maxlen: Optional[int] = None,
                 spill_uri: Optional[str] = None):
        self.maxlen = configured_events_max() if maxlen is None else maxlen
        self.enabled = self.maxlen > 0
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=max(1, self.maxlen))
        self._seq = 0
        self.dropped = 0  # emitted while the journal was disabled/full
        self.spill_uri = (configured_spill_uri() if spill_uri is None
                          else spill_uri)
        self._backend = None
        self._persist_at = 0.0  # monotonic time of the last persist
        self._dirty = False
        if self.enabled and self.spill_uri:
            self._open_backend()
            self._load()

    # -- persistence ------------------------------------------------------

    def _open_backend(self) -> None:
        try:
            from ray_tpu._private import spill
            self._backend = spill.backend_for_uri(self.spill_uri)
        except Exception:  # noqa: BLE001 - journal degrades to in-memory
            logger.warning("event journal: cannot open spill backend %r; "
                           "journal is in-memory only", self.spill_uri,
                           exc_info=True)
            self._backend = None

    def _load(self) -> None:
        """Reload a persisted journal (head restart with a durable URI).
        Restored events keep their seq/labels; ages restart from load
        time (monotonic clocks don't survive the process)."""
        if self._backend is None:
            return
        try:
            data = self._backend.read(
                self._backend.uri_for(PERSIST_FILENAME))
        except Exception:  # noqa: BLE001 - a torn journal is a fresh one
            data = None
        if not data:
            return
        now = time.monotonic()
        restored = []
        for line in data.decode("utf-8", "replace").splitlines():
            if not line.strip():
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            rec["time"] = now
            rec["restored"] = True
            restored.append(rec)
        with self._lock:
            for rec in restored[-self.maxlen:]:
                self._ring.append(rec)
                self._seq = max(self._seq, int(rec.get("seq", 0)))

    def _maybe_persist_locked(self, now: float, force: bool = False) -> None:
        if self._backend is None or not self._dirty:
            return
        if not force and now - self._persist_at < PERSIST_MIN_INTERVAL_S:
            return
        payload = "\n".join(
            json.dumps({k: v for k, v in rec.items() if k != "time"})
            for rec in self._ring).encode()
        try:
            self._backend.write(PERSIST_FILENAME, payload)
            self._persist_at = now
            self._dirty = False
        except Exception:  # noqa: BLE001 - spill layer already counted it
            # Leave dirty: the next record retries after the throttle.
            self._persist_at = now

    def flush(self) -> None:
        """Force-persist the ring (tests and head teardown)."""
        with self._lock:
            self._maybe_persist_locked(time.monotonic(), force=True)

    # -- ingest -----------------------------------------------------------

    def record(self, source: str, message: str, *, severity: str = "info",
               node_id: str = "", labels: Optional[Dict[str, Any]] = None,
               now: Optional[float] = None) -> Optional[Dict[str, Any]]:
        """Append one event (head-local emitters call this directly);
        returns the stored record, or None when the journal is off."""
        if not self.enabled:
            self.dropped += 1
            return None
        if severity not in SEVERITIES:
            severity = "info"
        now = time.monotonic() if now is None else now
        rec = {
            "severity": severity,
            "source": str(source)[:MAX_VALUE_LEN],
            "node_id": str(node_id or ""),
            "message": str(message)[:MAX_MESSAGE_LEN],
            "labels": sanitize_labels(labels),
            "time": now,
        }
        with self._lock:
            self._seq += 1
            rec["seq"] = self._seq
            self._ring.append(rec)
            self._dirty = True
            self._maybe_persist_locked(now)
        try:
            from ray_tpu._private import builtin_metrics
            builtin_metrics.record_cluster_event(severity)
        except Exception:  # noqa: BLE001 - counter is best-effort
            pass
        return rec

    def ingest(self, node_id: str, events: List[Dict[str, Any]]) -> None:
        """Merge piggybacked events from one metrics_batch; the transport
        node id wins unless the emitter stamped a subject node."""
        for ev in events or ():
            if not isinstance(ev, dict):
                continue
            self.record(
                ev.get("source", ""), ev.get("message", ""),
                severity=ev.get("severity", "info"),
                node_id=ev.get("node_id") or node_id or "",
                labels=ev.get("labels"))

    # -- read -------------------------------------------------------------

    def query(self, *, severity: Optional[str] = None,
              source: Optional[str] = None,
              node_id: Optional[str] = None,
              since_seq: Optional[int] = None,
              limit: Optional[int] = None) -> List[Dict[str, Any]]:
        """Filtered, seq-ordered events (oldest first); each row carries
        ``age_s`` instead of its raw monotonic timestamp. ``severity``
        is a floor: ``warning`` returns warning and above."""
        if severity is not None and severity not in SEVERITIES:
            raise ValueError(f"unknown severity {severity!r} "
                             f"(one of {', '.join(SEVERITIES)})")
        floor = SEVERITIES.index(severity) if severity else 0
        now = time.monotonic()
        with self._lock:
            rows = list(self._ring)
        out = []
        for rec in rows:
            if SEVERITIES.index(rec.get("severity", "info")) < floor:
                continue
            if source and rec.get("source") != source:
                continue
            if node_id and rec.get("node_id") != node_id:
                continue
            if since_seq is not None and rec.get("seq", 0) <= since_seq:
                continue
            row = {k: v for k, v in rec.items() if k != "time"}
            row["age_s"] = max(0.0, now - rec.get("time", now))
            out.append(row)
        if limit is not None and limit >= 0:
            out = out[-limit:]
        return out

    def annotations(self, *, limit: int = 200) -> List[Dict[str, Any]]:
        """Grafana annotations-style rows derived from the journal:
        ``{text, tags, age_s}`` — the dashboard layer converts age to an
        absolute epoch-ms ``time`` at the HTTP boundary (wall clocks
        stay out of _private/)."""
        out = []
        for rec in self.query(limit=limit):
            tags = [rec.get("severity", "info"),
                    rec.get("source", "")]
            if rec.get("node_id"):
                tags.append(f"node:{rec['node_id'][:12]}")
            out.append({"text": rec.get("message", ""),
                        "tags": [t for t in tags if t],
                        "age_s": rec["age_s"]})
        return out

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            count, seq = len(self._ring), self._seq
        return {"count": count, "seq": seq, "max": self.maxlen,
                "dropped": self.dropped, "enabled": self.enabled,
                "spill_uri": self.spill_uri}
