"""Reference counting (distributed GC) — native engine + Python fallback.

The ownership model of the reference's ReferenceCounter
(src/ray/core_worker/reference_count.h:61): every object created by this
process (put / task return) is *owned* here; language handles hold local
references, pending submitted tasks hold dependency references, serialized
handles in other workers are borrowers, and stored values pin the objects
their payload contains. When an owned object's combined count reaches zero
its value is freed from the store and its lineage pruned.

The native engine (src/ray_tpu_native/refcount.cc) is used when buildable;
``RAY_TPU_NATIVE_REFCOUNT=0`` forces the pure-Python twin. Both expose the
same interface and must make identical decisions (tests/test_refcount.py
runs the parity suite against each).
"""

from __future__ import annotations

import ctypes
import os
import threading
from typing import Dict, List, Optional, Set

from ray_tpu._private.ids import ObjectID

def _load():
    from ray_tpu._private.native_build import load_library_cached
    # keep_gil: add_local/add_owned run per object creation on the
    # submit hot path — GIL release per microsecond call convoys.
    return load_library_cached("refcount", configure=_configure,
                               keep_gil=True)


def _configure(lib) -> None:
    P, I, L, C = (ctypes.c_void_p, ctypes.c_int, ctypes.c_int64,
                  ctypes.c_char_p)
    lib.rrc_create.restype = P
    lib.rrc_destroy.argtypes = [P]
    lib.rrc_add_owned.argtypes = [P, C]
    lib.rrc_add_local.argtypes = [P, C]
    lib.rrc_remove_local.restype = L
    lib.rrc_remove_local.argtypes = [P, C, ctypes.c_char_p, L]
    lib.rrc_add_task_deps.argtypes = [P, C]
    lib.rrc_remove_task_deps.restype = L
    lib.rrc_remove_task_deps.argtypes = [P, C, ctypes.c_char_p, L]
    lib.rrc_add_borrower.argtypes = [P, C, C]
    lib.rrc_remove_borrower.restype = L
    lib.rrc_remove_borrower.argtypes = [P, C, C, ctypes.c_char_p, L]
    lib.rrc_add_contained.argtypes = [P, C, C]
    lib.rrc_force_free.restype = L
    lib.rrc_force_free.argtypes = [P, C, ctypes.c_char_p, L]
    lib.rrc_last_freed.restype = L
    lib.rrc_last_freed.argtypes = [P, ctypes.c_char_p, L]
    lib.rrc_has.restype = I
    lib.rrc_has.argtypes = [P, C]
    lib.rrc_local_count.restype = L
    lib.rrc_local_count.argtypes = [P, C]
    lib.rrc_num_tracked.restype = L
    lib.rrc_num_tracked.argtypes = [P]
    lib.rrc_dump.restype = L
    lib.rrc_dump.argtypes = [P, ctypes.c_char_p, L]


def native_refcount_available() -> bool:
    if os.environ.get("RAY_TPU_NATIVE_REFCOUNT", "1") == "0":
        return False
    return _load() is not None


class NativeReferenceCounter:
    """ctypes wrapper over the C++ counter."""

    def __init__(self):
        self._lib = _load()
        self._h = self._lib.rrc_create()
        # Serializes freeing mutations with their possible last_freed
        # re-read — a concurrent mutation would overwrite the stash.
        self._free_lock = threading.Lock()

    def __del__(self):
        try:
            self._lib.rrc_destroy(self._h)
        except Exception:  # noqa: BLE001 - interpreter teardown
            pass

    @staticmethod
    def _ids(oids: List[ObjectID]) -> bytes:
        return ";".join(o.hex() for o in oids).encode()

    def _call_freeing(self, fn, *args) -> List[ObjectID]:
        """Run a freeing mutation once; if the result overflowed the buffer,
        re-read it via the read-only rrc_last_freed stash (never retry the
        mutation — it would double-apply the decrement)."""
        cap = 4096
        with self._free_lock:
            buf = ctypes.create_string_buffer(cap)
            n = fn(self._h, *args, buf, cap)
            while n >= cap:
                cap = n + 1
                buf = ctypes.create_string_buffer(cap)
                n = self._lib.rrc_last_freed(self._h, buf, cap)
        raw = buf.value.decode()
        if not raw:
            return []
        return [ObjectID.from_hex(tok) for tok in raw.split(";")]

    def add_owned(self, oid: ObjectID) -> None:
        self._lib.rrc_add_owned(self._h, oid.hex().encode())

    def add_local(self, oid: ObjectID) -> None:
        self._lib.rrc_add_local(self._h, oid.hex().encode())

    def remove_local(self, oid: ObjectID) -> List[ObjectID]:
        return self._call_freeing(self._lib.rrc_remove_local,
                                  oid.hex().encode())

    def add_task_deps(self, oids: List[ObjectID]) -> None:
        if oids:
            self._lib.rrc_add_task_deps(self._h, self._ids(oids))

    def remove_task_deps(self, oids: List[ObjectID]) -> List[ObjectID]:
        if not oids:
            return []
        return self._call_freeing(self._lib.rrc_remove_task_deps,
                                  self._ids(oids))

    def add_borrower(self, oid: ObjectID, borrower: str) -> None:
        self._lib.rrc_add_borrower(self._h, oid.hex().encode(),
                                   borrower.encode())

    def remove_borrower(self, oid: ObjectID, borrower: str) -> List[ObjectID]:
        return self._call_freeing(self._lib.rrc_remove_borrower,
                                  oid.hex().encode(), borrower.encode())

    def add_contained(self, parent: ObjectID,
                      children: List[ObjectID]) -> None:
        if children:
            self._lib.rrc_add_contained(self._h, parent.hex().encode(),
                                        self._ids(children))

    def force_free(self, oid: ObjectID) -> List[ObjectID]:
        return self._call_freeing(self._lib.rrc_force_free,
                                  oid.hex().encode())

    def has(self, oid: ObjectID) -> bool:
        return bool(self._lib.rrc_has(self._h, oid.hex().encode()))

    def local_count(self, oid: ObjectID) -> int:
        return int(self._lib.rrc_local_count(self._h, oid.hex().encode()))

    def num_tracked(self) -> int:
        return int(self._lib.rrc_num_tracked(self._h))

    def dump(self) -> Dict[str, Dict[str, int]]:
        cap = 1 << 16
        while True:
            buf = ctypes.create_string_buffer(cap)
            n = self._lib.rrc_dump(self._h, buf, cap)
            if n < cap:
                break
            cap = n + 1
        out: Dict[str, Dict[str, int]] = {}
        raw = buf.value.decode()
        if not raw:
            return out
        for row in raw.split(";"):
            oid, _, counts = row.partition("=")
            local, deps, cont, borrow = (int(x) for x in counts.split(","))
            out[oid] = {"local": local, "task_deps": deps,
                        "contained_in": cont, "borrowers": borrow}
        return out


class _Ref:
    __slots__ = ("local", "task_deps", "contained_in", "borrowers",
                 "contained", "owned", "value_live")

    def __init__(self):
        self.local = 0
        self.task_deps = 0
        self.contained_in = 0
        self.borrowers: Set[str] = set()
        self.contained: List[ObjectID] = []
        self.owned = False
        self.value_live = False

    def freeable(self) -> bool:
        return (self.owned and self.value_live and self.local == 0
                and self.task_deps == 0 and self.contained_in == 0
                and not self.borrowers)

    def dead(self) -> bool:
        return (not self.value_live and self.local == 0
                and self.task_deps == 0 and self.contained_in == 0
                and not self.borrowers)


class PyReferenceCounter:
    """Pure-Python twin of the native counter (decision parity)."""

    def __init__(self):
        self._refs: Dict[ObjectID, _Ref] = {}
        self._lock = threading.Lock()

    def _collect(self, oid: ObjectID, out: List[ObjectID]) -> None:
        ref = self._refs.get(oid)
        if ref is None or not ref.freeable():
            return
        children, ref.contained = ref.contained, []
        ref.value_live = False
        out.append(oid)
        self._maybe_erase(oid)
        for child in children:
            cref = self._refs.get(child)
            if cref is None:
                continue
            if cref.contained_in > 0:
                cref.contained_in -= 1
            self._collect(child, out)
            self._maybe_erase(child)

    def _maybe_erase(self, oid: ObjectID) -> None:
        ref = self._refs.get(oid)
        if ref is not None and ref.dead():
            del self._refs[oid]

    def add_owned(self, oid: ObjectID) -> None:
        with self._lock:
            ref = self._refs.setdefault(oid, _Ref())
            ref.owned = True
            ref.value_live = True

    def add_local(self, oid: ObjectID) -> None:
        with self._lock:
            self._refs.setdefault(oid, _Ref()).local += 1

    def remove_local(self, oid: ObjectID) -> List[ObjectID]:
        with self._lock:
            freed: List[ObjectID] = []
            ref = self._refs.get(oid)
            if ref is not None:
                if ref.local > 0:
                    ref.local -= 1
                self._collect(oid, freed)
                self._maybe_erase(oid)
            return freed

    def add_task_deps(self, oids: List[ObjectID]) -> None:
        with self._lock:
            for oid in oids:
                self._refs.setdefault(oid, _Ref()).task_deps += 1

    def remove_task_deps(self, oids: List[ObjectID]) -> List[ObjectID]:
        with self._lock:
            freed: List[ObjectID] = []
            for oid in oids:
                ref = self._refs.get(oid)
                if ref is None:
                    continue
                if ref.task_deps > 0:
                    ref.task_deps -= 1
                self._collect(oid, freed)
                self._maybe_erase(oid)
            return freed

    def add_borrower(self, oid: ObjectID, borrower: str) -> None:
        with self._lock:
            self._refs.setdefault(oid, _Ref()).borrowers.add(borrower)

    def remove_borrower(self, oid: ObjectID, borrower: str) -> List[ObjectID]:
        with self._lock:
            freed: List[ObjectID] = []
            ref = self._refs.get(oid)
            if ref is not None:
                ref.borrowers.discard(borrower)
                self._collect(oid, freed)
                self._maybe_erase(oid)
            return freed

    def add_contained(self, parent: ObjectID,
                      children: List[ObjectID]) -> None:
        if not children:
            return
        with self._lock:
            pref = self._refs.setdefault(parent, _Ref())
            for child in children:
                self._refs.setdefault(child, _Ref()).contained_in += 1
                pref.contained.append(child)

    def force_free(self, oid: ObjectID) -> List[ObjectID]:
        with self._lock:
            freed: List[ObjectID] = []
            ref = self._refs.get(oid)
            if ref is not None and ref.value_live:
                children, ref.contained = ref.contained, []
                ref.value_live = False
                freed.append(oid)
                self._maybe_erase(oid)
                for child in children:
                    cref = self._refs.get(child)
                    if cref is None:
                        continue
                    if cref.contained_in > 0:
                        cref.contained_in -= 1
                    self._collect(child, freed)
                    self._maybe_erase(child)
            return freed

    def has(self, oid: ObjectID) -> bool:
        with self._lock:
            ref = self._refs.get(oid)
            return ref is not None and ref.value_live

    def local_count(self, oid: ObjectID) -> int:
        with self._lock:
            ref = self._refs.get(oid)
            return 0 if ref is None else ref.local

    def num_tracked(self) -> int:
        with self._lock:
            return len(self._refs)

    def dump(self) -> Dict[str, Dict[str, int]]:
        with self._lock:
            return {
                oid.hex(): {"local": r.local, "task_deps": r.task_deps,
                            "contained_in": r.contained_in,
                            "borrowers": len(r.borrowers)}
                for oid, r in self._refs.items()
            }


def make_reference_counter(use_native: bool = True):
    """``use_native=False`` (the use_native_refcount config flag) forces the
    Python twin; RAY_TPU_NATIVE_REFCOUNT=0 also disables."""
    if use_native and native_refcount_available():
        return NativeReferenceCounter()
    return PyReferenceCounter()
