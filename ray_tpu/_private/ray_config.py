"""RayConfig: the typed runtime flag table.

Python face of the native flag system (src/ray_tpu_native/config.cc — the
analog of the reference's RAY_CONFIG macro table,
src/ray/common/ray_config_def.h). Flags carry typed defaults compiled into
the native library, overridable per-process by ``RAY_TPU_<name>``
environment variables and per-cluster by the ``_system_config`` dict passed
to ``ray_tpu.init`` — the same precedence the reference implements.

Falls back to a pure-Python table (same defaults, same precedence) when the
native library is unavailable.
"""

from __future__ import annotations

import ctypes
import os
import threading
from typing import Any, Dict, Optional

_PY_DEFAULTS: Dict[str, Any] = {
    # Kept in sync with kDefaults in config.cc; the parity test
    # (tests/test_ray_config.py) diffs the two tables.
    "scheduler_spread_threshold": 0.5,
    "max_pending_lease_requests_per_scheduling_category": 10,
    "worker_lease_enabled": True,
    "max_tasks_in_flight_per_worker": 10,
    "pull_manager_max_inflight_bytes": 268435456,
    "pull_chunk_bytes": 4194304,
    "pull_parallelism": 4,
    "worker_prestart_count": 1,
    "worker_cap_multiplier": 8,
    "worker_cap_min": 64,
    "task_retry_delay_ms": 0,
    "actor_restart_backoff_ms": 0,
    "max_task_events": 100_000,
    "lineage_max_entries": 1_000_000,
    "object_locations_max_entries": 1_000_000,
    "object_store_memory_fraction": 0.3,
    "object_store_full_delay_ms": 100,
    "object_spilling_threshold_bytes": 0,
    "object_spilling_directory": "",
    # Spill-backend URI: "" = per-process file:// dir (legacy),
    # "session://" = host-shared session dir (survives daemon death),
    # "mock-s3://<bucket>" = local stand-in for remote object storage.
    "object_spill_uri": "",
    "remote_object_inline_limit_bytes": 1 << 20,
    "gc_sweep_interval_ms": 500,
    "health_check_period_ms": 3000,
    "health_check_timeout_ms": 10000,
    "health_check_failure_threshold": 5,
    "node_death_grace_ms": 0,
    # Fenced membership / fast failure detection (wire v9, see
    # _private/membership.py): the head probes each node's health
    # socket every period with this timeout; channel frames feed the
    # accrual detector for free. Death fires when the suspicion score
    # (phi, a -log10 improbability of the observed silence) crosses the
    # threshold, or unconditionally once a node is silent past the hard
    # lease.
    "health_probe_timeout_s": 1.0,
    "health_probe_period_s": 0.25,
    "node_lease_s": 10.0,
    "node_suspicion_threshold": 8.0,
    # Resilient session channels (wire v7): a broken head<->daemon
    # socket is re-dialed and resumed within this window before node
    # death fires; unacked frames wait in a ring of this many bytes.
    "channel_reconnect_window_s": 30.0,
    "channel_resend_ring_bytes": 67108864,
    # Head failover: a daemon whose session breaks against a DEAD head
    # (resume impossible) keeps re-dialing the head address with
    # jittered backoff for this long before giving up — wide enough
    # for a supervisor-restarted or standby head to come up, replay
    # the gcs_store, and accept re-registrations.
    "head_failover_window_s": 120.0,
    # Deferred acks: after this many unacked inbound frames an ack goes
    # pending, piggybacking on the next outbound frame or flushed as a
    # pure ack once the interval expires.
    "channel_ack_every": 32,
    "channel_ack_flush_ms": 20,
    # Serve resilience (controller lifecycle + router failover): replica
    # startup is bounded and retried against a per-deployment budget;
    # DRAINING replicas get this long to finish in-flight requests;
    # health checks run in parallel every period and a replica is
    # replaced after this many consecutive failures; a failed-over
    # request is retried on another replica at most this many times.
    "serve_startup_timeout_s": 30.0,
    "serve_start_budget": 3,
    "serve_drain_timeout_s": 30.0,
    "serve_health_check_period_s": 1.0,
    "serve_health_check_timeout_s": 5.0,
    "serve_health_failure_threshold": 3,
    "serve_failover_retries": 3,
    # Serve autoscaling (actuation plane): the controller runs an
    # autoscale pass every interval (<=0 disables), sizing each
    # autoscaled deployment from windowed queue-depth/qps/p95 stats;
    # cluster-default up/down delays apply when the deployment's
    # autoscaling_config doesn't override them; firing scale_hint
    # alerts expire after the TTL so a dead alert engine can't pin a
    # hint forever. Batch queues with no declared target adapt against
    # the cluster-wide latency budget (0 = fixed batching).
    "serve_autoscale_interval_s": 2.0,
    "serve_autoscale_window_s": 15.0,
    "serve_autoscale_upscale_delay_s": 0.0,
    "serve_autoscale_downscale_delay_s": 10.0,
    "serve_scale_hint_ttl_s": 120.0,
    "serve_batch_target_latency_ms": 0.0,
    # Train fault tolerance: a gang round with no result for this long
    # liveness-probes every pending rank and treats failed probes as a
    # system failure (gang restart from the latest durable checkpoint);
    # a gang restart waits this long for the full worker complement
    # before shrinking to ScalingConfig.min_workers.
    "train_hang_timeout_s": 60.0,
    "train_restart_wait_s": 30.0,
    # Sharded checkpoints: reader-side fan-out of per-parameter loads,
    # whether full-block restores/GC validate crc32 checksums, and
    # whether a gang whose size differs from the saved mesh may resume
    # by resharding (off = refuse instead of silently reshaping).
    "train_ckpt_shard_parallelism": 8,
    "train_ckpt_verify_checksums": True,
    "train_reshard_on_restart": True,
    "metrics_report_interval_ms": 10_000,
    # Distributed tracing: head-of-trace sampling probability (decided
    # once at the driver, carried in the propagated context) and how
    # many assembled traces the head retains before evicting oldest.
    "trace_sample_rate": 1.0,
    "trace_retention": 1000,
    # Head-side windowed time-series store (timeseries.py): retention
    # window in seconds (<= 0 disables the store) and the bound on
    # distinct label sets held before new series are dropped+counted.
    "timeseries_window_s": 300.0,
    "timeseries_max_series": 4096,
    # Continuous profiling plane (profiling.py + profile_store.py):
    # per-process sample rate (0 disables), head-side retention window
    # (<= 0 disables the store), origin/per-bucket stack caps, the
    # loop-lag threshold that trips the flight recorder (<= 0 disables
    # it), its incident-ring bound, and the cap on an on-demand burst's
    # duration (dashboard/daemon profile endpoints).
    "profile_hz": 10.0,
    "profile_window_s": 300.0,
    "profile_max_series": 256,
    "profile_max_stacks": 2000,
    "profile_flight_lag_s": 1.0,
    "profile_max_incidents": 32,
    "profile_max_duration_s": 60.0,
    # Alerting plane + cluster event journal (alerting.py / events.py):
    # rule-evaluation cadence on the ClusterMetrics merge path (<= 0
    # disables the engine), the bound on retained alert transitions,
    # the journal ring size (<= 0 disables the journal), and an
    # optional spill-backend URI for durable journal persistence.
    "alert_eval_period_s": 5.0,
    "alert_max_firing_history": 256,
    "events_max": 2048,
    "events_spill_uri": "",
    # Dataplane flow observability (flow.py): per-process transfer
    # ledger bound (0 disables recording; fast counters still tick),
    # head-side matrix window and cardinality caps, and the thresholds
    # behind the slow_link / hot_object_fanout built-in alert rules.
    "flow_max_records": 4096,
    "flow_window_s": 60.0,
    "flow_max_links": 512,
    "flow_max_objects": 512,
    "flow_slow_link_mbps": 1.0,
    "flow_fanout_nodes": 8,
    # Collective dataplane: spanning-tree push broadcast fan-out (children
    # per node; <= 0 disables broadcast), the cap on holders a striped
    # multi-source pull reads from concurrently (1 = failover-only), and
    # the utilization past which locality-aware placement spills a task
    # away from the node holding its argument bytes.
    "broadcast_fanout": 2,
    "pull_stripe_max_sources": 4,
    "locality_spillback_threshold": 0.85,
    "task_events_enabled": True,
    "memory_monitor_refresh_ms": 250,
    "memory_usage_threshold": 0.95,
    "testing_submit_delay_us": 0,
    "testing_dispatch_delay_us": 0,
    "testing_store_delay_us": 0,
    "testing_rpc_failure_pct": 0,
    "gcs_store_path": "",
    "tpu_autodetect": True,
    "tpu_chips_per_host_default": 4,
    "ici_topology": "",
    "use_native_scheduler": True,
    "use_native_object_store": True,
    "use_native_refcount": True,
}

def _load():
    from ray_tpu._private.native_build import load_library_cached
    return load_library_cached("config", configure=_configure,
                               keep_gil=True)


def _configure(lib) -> None:
    P, I, L, D, C = (ctypes.c_void_p, ctypes.c_int, ctypes.c_int64,
                     ctypes.c_double, ctypes.c_char_p)
    lib.rcfg_create.restype = P
    lib.rcfg_create.argtypes = [C]
    lib.rcfg_destroy.argtypes = [P]
    lib.rcfg_has.restype = I
    lib.rcfg_has.argtypes = [P, C, ctypes.POINTER(I)]
    lib.rcfg_get_int.restype = L
    lib.rcfg_get_int.argtypes = [P, C, L]
    lib.rcfg_get_double.restype = D
    lib.rcfg_get_double.argtypes = [P, C, D]
    lib.rcfg_get_bool.restype = I
    lib.rcfg_get_bool.argtypes = [P, C, I]
    lib.rcfg_get_str.restype = L
    lib.rcfg_get_str.argtypes = [P, C, ctypes.c_char_p, L]
    lib.rcfg_set.restype = I
    lib.rcfg_set.argtypes = [P, C, C]
    lib.rcfg_dump.restype = L
    lib.rcfg_dump.argtypes = [P, ctypes.c_char_p, L]


def runtime_config_value(name: str, default: Any) -> Any:
    """Read a flag with the standard precedence: the live runtime's
    config table (native/python, env + _system_config already applied)
    when a runtime is up, else the raw ``RAY_TPU_<name>`` env var
    coerced to the default's type, else the default. Shared by serve
    (``serve_config``) and train (hang/restart knobs) so components
    read flags identically with or without an initialized runtime."""
    try:
        from ray_tpu._private.worker import global_worker
        runtime = global_worker._runtime
        cfg = getattr(runtime, "config", None)
        if cfg is not None:
            return cfg.get(name)
    except Exception:  # noqa: BLE001 - fall back to the env var
        pass
    env = os.environ.get(f"RAY_TPU_{name}")
    if env is None:
        return default
    if isinstance(default, bool):
        return env.lower() in ("1", "true", "yes", "on")
    if isinstance(default, int):
        try:
            return int(float(env))
        except ValueError:
            return default
    if isinstance(default, float):
        try:
            return float(env)
        except ValueError:
            return default
    return env


def native_config_available() -> bool:
    if os.environ.get("RAY_TPU_NATIVE_CONFIG", "1") == "0":
        return False
    return _load() is not None


def _encode_overrides(overrides: Optional[Dict[str, Any]]) -> bytes:
    if not overrides:
        return b""
    parts = []
    for k, v in overrides.items():
        if isinstance(v, bool):
            v = "true" if v else "false"
        parts.append(f"{k}={v}")
    return ";".join(parts).encode()


class NativeRayConfig:
    def __init__(self, overrides: Optional[Dict[str, Any]] = None):
        self._lib = _load()
        self._h = self._lib.rcfg_create(_encode_overrides(overrides))

    def __del__(self):
        try:
            self._lib.rcfg_destroy(self._h)
        except Exception:  # noqa: BLE001 - interpreter teardown
            pass

    def _type_of(self, name: str) -> Optional[int]:
        t = ctypes.c_int(0)
        if not self._lib.rcfg_has(self._h, name.encode(), ctypes.byref(t)):
            return None
        return t.value

    def get(self, name: str):
        t = self._type_of(name)
        if t is None:
            raise AttributeError(f"Unknown config flag {name!r}")
        key = name.encode()
        if t == 0:
            return int(self._lib.rcfg_get_int(self._h, key, 0))
        if t == 1:
            return float(self._lib.rcfg_get_double(self._h, key, 0.0))
        if t == 2:
            return bool(self._lib.rcfg_get_bool(self._h, key, 0))
        cap = 256
        while True:
            buf = ctypes.create_string_buffer(cap)
            n = self._lib.rcfg_get_str(self._h, key, buf, cap)
            if n < 0:
                return ""
            if n < cap:
                return buf.value.decode()
            cap = n + 1

    def set(self, name: str, value: Any) -> None:
        if isinstance(value, bool):
            value = "true" if value else "false"
        if not self._lib.rcfg_set(self._h, name.encode(), str(value).encode()):
            raise AttributeError(f"Unknown config flag {name!r}")

    def dump(self) -> Dict[str, str]:
        cap = 1 << 14
        while True:
            buf = ctypes.create_string_buffer(cap)
            n = self._lib.rcfg_dump(self._h, buf, cap)
            if n < cap:
                break
            cap = n + 1
        out = {}
        for row in buf.value.decode().split(";"):
            if row:
                k, _, v = row.partition("=")
                out[k] = v
        return out

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        return self.get(name)


class PyRayConfig:
    """Pure-Python twin (same defaults, same env/override precedence)."""

    def __init__(self, overrides: Optional[Dict[str, Any]] = None):
        values = dict(_PY_DEFAULTS)
        for name, default in _PY_DEFAULTS.items():
            env = os.environ.get(f"RAY_TPU_{name}")
            if env is not None:
                values[name] = self._coerce(default, env)
        for name, val in (overrides or {}).items():
            if name in values:
                values[name] = self._coerce(values[name], val)
        object.__setattr__(self, "_values", values)

    @staticmethod
    def _coerce(default: Any, val: Any) -> Any:
        if isinstance(default, bool):
            if isinstance(val, str):
                return val.lower() in ("1", "true", "yes", "on")
            return bool(val)
        if isinstance(default, int):
            try:
                return int(float(val))
            except (TypeError, ValueError):
                return 0
        if isinstance(default, float):
            try:
                return float(val)
            except (TypeError, ValueError):
                return 0.0
        return str(val)

    def get(self, name: str):
        try:
            return self._values[name]
        except KeyError:
            raise AttributeError(f"Unknown config flag {name!r}") from None

    def set(self, name: str, value: Any) -> None:
        if name not in self._values:
            raise AttributeError(f"Unknown config flag {name!r}")
        self._values[name] = self._coerce(self._values[name], value)

    def dump(self) -> Dict[str, str]:
        out = {}
        for k, v in self._values.items():
            if isinstance(v, bool):
                out[k] = "true" if v else "false"
            else:
                out[k] = str(v)
        return out

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        return self.get(name)


def make_ray_config(overrides: Optional[Dict[str, Any]] = None):
    if native_config_available():
        return NativeRayConfig(overrides)
    return PyRayConfig(overrides)
