"""Alert rule engine over the head's windowed time-series store.

Analog of the reference dashboard's alerting surface paired with a
Prometheus-style rule evaluator, TPU-first: rules are evaluated
head-locally on the existing ``ClusterMetrics.update`` cadence (no new
wires, no scrape round-trip) against the :class:`TimeSeriesStore`
derivations that already power ``ray-tpu top``.

Rule grammar (the ``expr`` string)::

    TERM  := FUNC(metric[, by=label])
    FUNC  := rate | gauge_max | gauge_avg | p50 | p95 | hist_rate
             | hist_mean
    EXPR  := TERM OP NUMBER | TERM / TERM OP NUMBER
    OP    := > | >= | < | <= | ==

``rate`` is the reset-safe counter rate; ``gauge_max``/``gauge_avg``
read ``gauge_stats`` (max of lasts / windowed average);
``p50``/``p95``/``hist_rate``/``hist_mean`` read ``histogram_stats``.
``by=label`` fans the rule out per label value — each group value is an
independent alert instance (label-keyed dedup comes free: one instance
per ``(rule, group)``).

Two rule kinds:

* :class:`AlertRule` — threshold: the expr must breach continuously for
  ``for_s`` before ``pending`` promotes to ``firing``.
* :class:`BurnRateRule` — multi-window SLO burn: the expr is evaluated
  over a fast AND a slow window; the burn rate (``value / objective``)
  must exceed ``burn_threshold`` in BOTH windows to fire (the fast
  window gives responsiveness, the slow window keeps one spike from
  paging).

State machine per instance: ``pending -> firing -> resolved``, with a
per-rule ``cooldown_s`` after a resolve before the same instance may
fire again (anti-flap), a bounded firing history
(``RAY_TPU_ALERT_MAX_FIRING_HISTORY``), every transition mirrored into
the cluster event journal and counted in
``ray_tpu_alerts_transitions_total{state}``. Rules may attach a typed
``scale_hint`` (``{"deployment", "direction"}``) surfaced to
subscribers — the serve controller records these for its autoscaler.

Evaluation is gated by ``RAY_TPU_ALERT_EVAL_PERIOD_S`` (0 disables the
engine entirely — the bench's off arm).
"""

from __future__ import annotations

import logging
import os
import re
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

logger = logging.getLogger(__name__)

DEFAULT_EVAL_PERIOD_S = 5.0
DEFAULT_MAX_FIRING_HISTORY = 256
#: Resolved instances linger this long in snapshots before eviction.
RESOLVED_RETENTION_S = 300.0

_TERM_RE = re.compile(
    r"\s*(?P<func>[a-z_0-9]+)\(\s*(?P<metric>[A-Za-z_][\w.]*)"
    r"(?:\s*,\s*by\s*=\s*(?P<by>[A-Za-z_]\w*))?\s*\)\s*")
_OP_RE = re.compile(r"(>=|<=|==|>|<)")

_OPS: Dict[str, Callable[[float, float], bool]] = {
    ">": lambda a, b: a > b,
    ">=": lambda a, b: a >= b,
    "<": lambda a, b: a < b,
    "<=": lambda a, b: a <= b,
    "==": lambda a, b: a == b,
}

_FUNCS = ("rate", "gauge_max", "gauge_avg", "p50", "p95", "hist_rate",
          "hist_mean")


def configured_eval_period_s() -> float:
    """Engine cadence; honors the documented uppercase env spelling
    first, then the flag table. ``<= 0`` disables evaluation."""
    raw = os.environ.get("RAY_TPU_ALERT_EVAL_PERIOD_S", "")
    if raw:
        try:
            return float(raw)
        except ValueError:
            pass
    from ray_tpu._private.ray_config import runtime_config_value
    return float(runtime_config_value("alert_eval_period_s",
                                      DEFAULT_EVAL_PERIOD_S))


def configured_max_firing_history() -> int:
    raw = os.environ.get("RAY_TPU_ALERT_MAX_FIRING_HISTORY", "")
    if raw:
        try:
            return int(float(raw))
        except ValueError:
            pass
    from ray_tpu._private.ray_config import runtime_config_value
    return int(runtime_config_value("alert_max_firing_history",
                                    DEFAULT_MAX_FIRING_HISTORY))


# ---------------------------------------------------------------------------
# Expr parsing / evaluation
# ---------------------------------------------------------------------------


class _Term:
    __slots__ = ("func", "metric", "by")

    def __init__(self, func: str, metric: str, by: Optional[str]):
        if func not in _FUNCS:
            raise ValueError(f"unknown derivation {func!r} "
                             f"(one of {', '.join(_FUNCS)})")
        self.func = func
        self.metric = metric
        self.by = by

    def evaluate(self, ts, window: float) -> Dict[str, float]:
        """Per-group values; groups with no data are absent (a rule over
        a silent metric simply does not breach)."""
        if self.func == "rate":
            return ts.counter_rate(self.metric, window=window,
                                   group_by=self.by)
        if self.func in ("gauge_max", "gauge_avg"):
            stats = ts.gauge_stats(self.metric, window=window,
                                   group_by=self.by)
            field = "last_max" if self.func == "gauge_max" else "avg_sum"
            return {k: float(v[field]) for k, v in stats.items()
                    if v.get(field) is not None}
        field = {"p50": "p50", "p95": "p95", "hist_rate": "rate",
                 "hist_mean": "mean"}[self.func]
        stats = ts.histogram_stats(self.metric, window=window,
                                   group_by=self.by)
        return {k: float(v[field]) for k, v in stats.items()
                if v.get(field) is not None}


def _parse_term(text: str) -> _Term:
    m = _TERM_RE.fullmatch(text)
    if m is None:
        raise ValueError(f"bad alert term {text!r} "
                         "(expected FUNC(metric[, by=label]))")
    return _Term(m.group("func"), m.group("metric"), m.group("by"))


class Expr:
    """A parsed rule expression: one term (or a term ratio) compared to
    a constant. ``evaluate`` returns per-group observed values plus the
    breach verdict per group."""

    def __init__(self, text: str):
        self.text = text
        parts = _OP_RE.split(text, maxsplit=1)
        if len(parts) != 3:
            raise ValueError(
                f"bad alert expr {text!r} (expected TERM OP NUMBER)")
        lhs, self.op, rhs = parts
        if self.op not in _OPS:
            raise ValueError(f"bad comparison {self.op!r}")
        try:
            self.threshold = float(rhs)
        except ValueError:
            raise ValueError(
                f"alert threshold must be a number, got {rhs!r}") from None
        num, sep, den = lhs.partition("/")
        self.numerator = _parse_term(num)
        self.denominator = _parse_term(den) if sep else None

    def values(self, ts, window: float) -> Dict[str, float]:
        num = self.numerator.evaluate(ts, window)
        if self.denominator is None:
            return num
        den = self.denominator.evaluate(ts, window)
        out = {}
        for key, n in num.items():
            d = den.get(key)
            if d is None and len(den) == 1 and self.denominator.by is None:
                d = next(iter(den.values()))  # ungrouped denominator
            if d and d > 0:
                out[key] = n / d
            elif n > 0:
                # Failures with zero successes: the worst ratio, not a
                # silent divide-by-zero skip.
                out[key] = float("inf")
        return out

    def breaches(self, value: float) -> bool:
        return _OPS[self.op](value, self.threshold)


# ---------------------------------------------------------------------------
# Rules
# ---------------------------------------------------------------------------


class AlertRule:
    """Threshold rule: ``expr`` must breach continuously for ``for_s``."""

    kind = "threshold"

    def __init__(self, name: str, expr: str, *, for_s: float = 0.0,
                 window_s: float = 60.0, severity: str = "warning",
                 cooldown_s: float = 60.0,
                 labels: Optional[Dict[str, str]] = None,
                 message: Optional[str] = None,
                 scale_hint: Optional[Dict[str, str]] = None):
        if not name:
            raise ValueError("alert rule needs a name")
        self.name = name
        self.expr = Expr(expr)
        self.for_s = max(0.0, float(for_s))
        self.window_s = float(window_s)
        self.severity = severity if severity in ("info", "warning", "error",
                                                 "critical") else "warning"
        self.cooldown_s = max(0.0, float(cooldown_s))
        self.labels = dict(labels or {})
        self.message = message or f"{name}: {expr}"
        self.scale_hint = dict(scale_hint) if scale_hint else None

    def evaluate(self, ts) -> Dict[str, float]:
        """group key -> observed value, breaching groups only."""
        vals = self.expr.values(ts, self.window_s)
        return {k: v for k, v in vals.items() if self.expr.breaches(v)}

    def describe(self) -> Dict[str, Any]:
        return {"name": self.name, "kind": self.kind,
                "expr": self.expr.text, "for_s": self.for_s,
                "window_s": self.window_s, "severity": self.severity,
                "cooldown_s": self.cooldown_s,
                "threshold": self.expr.threshold}

    def hint_for(self, key: str) -> Optional[Dict[str, str]]:
        if self.scale_hint is None:
            return None
        hint = dict(self.scale_hint)
        if key and "deployment" not in hint:
            hint["deployment"] = key
        return hint


class BurnRateRule(AlertRule):
    """Multi-window SLO burn: expr / objective must exceed
    ``burn_threshold`` in BOTH the fast and the slow window."""

    kind = "burn_rate"

    def __init__(self, name: str, expr: str, *, objective: float,
                 fast_window_s: float = 60.0, slow_window_s: float = 300.0,
                 burn_threshold: float = 1.0, **kwargs):
        kwargs.setdefault("window_s", fast_window_s)
        super().__init__(name, expr, **kwargs)
        if objective <= 0:
            raise ValueError("burn-rate objective must be > 0")
        self.objective = float(objective)
        self.fast_window_s = float(fast_window_s)
        self.slow_window_s = float(slow_window_s)
        self.burn_threshold = float(burn_threshold)

    def evaluate(self, ts) -> Dict[str, float]:
        fast = self.expr.values(ts, self.fast_window_s)
        slow = self.expr.values(ts, self.slow_window_s)
        out = {}
        for key, v in fast.items():
            sv = slow.get(key)
            if sv is None:
                continue
            fast_burn = v / self.objective
            if (fast_burn > self.burn_threshold
                    and sv / self.objective > self.burn_threshold):
                out[key] = fast_burn
        return out

    def describe(self) -> Dict[str, Any]:
        d = super().describe()
        d.update(objective=self.objective,
                 fast_window_s=self.fast_window_s,
                 slow_window_s=self.slow_window_s,
                 burn_threshold=self.burn_threshold)
        return d


def builtin_rules() -> List[AlertRule]:
    """The rules every cluster ships with. Conservative thresholds —
    operators tune via ``runtime.add_alert_rule`` (same name replaces)."""
    from ray_tpu._private import flow as _flow
    fanout_n = _flow.configured_fanout_nodes()
    return [
        AlertRule(
            "node_down", "rate(ray_tpu_node_deaths_total) > 0",
            window_s=60.0, for_s=0.0, severity="critical",
            cooldown_s=30.0,
            message="node death(s) declared in the last minute"),
        AlertRule(
            "head_loop_lag",
            "gauge_max(ray_tpu_loop_lag_seconds, by=loop) > 1.0",
            window_s=60.0, for_s=10.0, severity="warning",
            message="a control loop is waking >1s late (saturated head?)"),
        AlertRule(
            "spill_failures",
            "rate(ray_tpu_object_spill_failures_total) > 0",
            window_s=120.0, for_s=0.0, severity="warning",
            message="object spill/restore IO is failing"),
        AlertRule(
            "checkpoint_persist_failures",
            "rate(ray_tpu_train_checkpoint_persist_failures_total) > 0",
            window_s=120.0, for_s=0.0, severity="error",
            message="train checkpoints are failing to persist durably"),
        BurnRateRule(
            "serve_p95_burn",
            "p95(ray_tpu_serve_request_latency_seconds, by=deployment) > 0",
            objective=0.5, fast_window_s=60.0, slow_window_s=300.0,
            burn_threshold=1.0, for_s=10.0, severity="warning",
            scale_hint={"direction": "up"},
            message="serve p95 latency is burning its 500ms objective"),
        BurnRateRule(
            "serve_error_burn",
            "rate(ray_tpu_serve_failovers_total) / "
            "rate(ray_tpu_serve_requests_total) > 0",
            objective=0.05, fast_window_s=60.0, slow_window_s=300.0,
            burn_threshold=1.0, for_s=0.0, severity="error",
            message="serve system-failure rate is burning its 5% objective"),
        # Dataplane flow plane (flow.py). The stalled gauge is
        # synthesized BY the FlowStore: 1.0 iff a link moved bytes in
        # the window AND its windowed MB/s is below
        # flow_slow_link_mbps — "slow while bytes in flight" as one
        # restamped value, so the rule resolves as soon as the link
        # goes idle or speeds back up.
        AlertRule(
            "slow_link",
            "gauge_max(ray_tpu_transfer_link_stalled, by=link) >= 1",
            window_s=30.0, for_s=5.0, severity="warning",
            cooldown_s=30.0,
            message="an object-transfer link is moving bytes below the "
                    "slow-link MB/s floor (saturated NIC? chaos?)"),
        AlertRule(
            "hot_object_fanout",
            "gauge_max(ray_tpu_object_fanout_nodes, by=key) >= "
            f"{fanout_n}",
            window_s=60.0, for_s=0.0, severity="warning",
            cooldown_s=60.0,
            message=f"a single object was pulled by >={fanout_n} nodes "
                    "in the window (broadcast amplification — consider "
                    "a tree broadcast)"),
    ]


# ---------------------------------------------------------------------------
# Engine
# ---------------------------------------------------------------------------


class _Instance:
    __slots__ = ("state", "value", "pending_since", "fired_at",
                 "resolved_at", "last_breach")

    def __init__(self):
        self.state = "pending"
        self.value = 0.0
        self.pending_since: Optional[float] = None
        self.fired_at: Optional[float] = None
        self.resolved_at: Optional[float] = None
        self.last_breach: Optional[float] = None


def _render_alert(rule: AlertRule, key: str, inst: _Instance,
                  now: float) -> Dict[str, Any]:
    alert = {
        "rule": rule.name, "key": key, "state": inst.state,
        "severity": rule.severity, "value": inst.value,
        "threshold": rule.expr.threshold, "kind": rule.kind,
        "message": rule.message, "labels": dict(rule.labels),
        "since_s": max(0.0, now - (inst.pending_since or now)),
    }
    if isinstance(rule, BurnRateRule):
        alert["threshold"] = rule.burn_threshold
        alert["objective"] = rule.objective
    hint = rule.hint_for(key)
    if hint:
        alert["scale_hint"] = hint
    return alert


class AlertEngine:
    """Evaluates the rule table against a TimeSeriesStore on the
    ClusterMetrics merge cadence; owns per-instance state machines."""

    def __init__(self, period_s: Optional[float] = None,
                 max_history: Optional[int] = None, journal=None):
        self.period_s = (configured_eval_period_s() if period_s is None
                         else period_s)
        self.enabled = self.period_s > 0
        self.journal = journal
        self._lock = threading.Lock()
        self._rules: Dict[str, AlertRule] = {}
        self._instances: Dict[tuple, _Instance] = {}
        hist = (configured_max_firing_history() if max_history is None
                else max_history)
        self._history: deque = deque(maxlen=max(1, hist))
        self._subscribers: List[Callable[[Dict[str, Any]], None]] = []
        self._last_eval: Optional[float] = None
        for rule in builtin_rules():
            self._rules[rule.name] = rule

    # -- rule table -------------------------------------------------------

    def add_rule(self, rule: AlertRule) -> None:
        """Install (or replace, by name) a rule; its instances reset."""
        with self._lock:
            self._rules[rule.name] = rule
            for key in [k for k in self._instances if k[0] == rule.name]:
                del self._instances[key]

    def remove_rule(self, name: str) -> bool:
        with self._lock:
            existed = self._rules.pop(name, None) is not None
            for key in [k for k in self._instances if k[0] == name]:
                del self._instances[key]
        return existed

    def rules(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [r.describe() for r in self._rules.values()]

    def subscribe(self, fn: Callable[[Dict[str, Any]], None]) -> None:
        """``fn(alert_dict)`` on every pending->firing and
        firing->resolved transition (the serve controller's scale_hint
        hook rides this)."""
        with self._lock:
            self._subscribers.append(fn)

    # -- evaluation -------------------------------------------------------

    def maybe_evaluate(self, ts, now: Optional[float] = None) -> bool:
        """Rate-limited entry point (called from ClusterMetrics.update);
        True when a full evaluation ran."""
        if not self.enabled:
            return False
        now = time.monotonic() if now is None else now
        if self._last_eval is not None and \
                now - self._last_eval < self.period_s:
            return False
        self.evaluate(ts, now=now)
        return True

    def evaluate(self, ts, now: Optional[float] = None) -> None:
        now = time.monotonic() if now is None else now
        self._last_eval = now
        transitions = []
        with self._lock:
            rules = list(self._rules.values())
        for rule in rules:
            try:
                breaching = rule.evaluate(ts)
            except Exception:  # noqa: BLE001 - one bad rule can't stop eval
                logger.exception("alert rule %s evaluation failed",
                                 rule.name)
                continue
            transitions.extend(self._step_rule(rule, breaching, now))
        for alert in transitions:
            self._announce(alert)

    def _step_rule(self, rule: AlertRule, breaching: Dict[str, float],
                   now: float) -> List[Dict[str, Any]]:
        """Advance every instance of one rule; returns transition
        records to announce outside the lock."""
        out = []
        with self._lock:
            for key, value in breaching.items():
                ikey = (rule.name, key)
                inst = self._instances.get(ikey)
                if inst is None or inst.state == "resolved":
                    # A resolve starts the cooldown clock: within it a
                    # new breach parks in pending (dedup/anti-flap)
                    # regardless of for_s.
                    prev = inst
                    inst = self._instances[ikey] = _Instance()
                    if prev is not None and prev.resolved_at is not None:
                        inst.resolved_at = prev.resolved_at
                    inst.pending_since = now
                inst.value = value
                inst.last_breach = now
                if inst.state == "pending":
                    held = now - (inst.pending_since or now)
                    cooling = (inst.resolved_at is not None and
                               now - inst.resolved_at < rule.cooldown_s)
                    if held >= rule.for_s and not cooling:
                        inst.state = "firing"
                        inst.fired_at = now
                        out.append(self._alert_dict_locked(
                            rule, key, inst, now))
            # Instances whose rule stopped breaching resolve (firing) or
            # drop (pending never fired); stale resolved entries age out.
            for ikey in list(self._instances):
                rname, key = ikey
                if rname != rule.name or key in breaching:
                    continue
                inst = self._instances[ikey]
                if inst.state == "firing":
                    inst.state = "resolved"
                    inst.resolved_at = now
                    out.append(self._alert_dict_locked(rule, key, inst, now))
                elif inst.state == "pending":
                    del self._instances[ikey]
                elif inst.resolved_at is not None and \
                        now - inst.resolved_at > RESOLVED_RETENTION_S:
                    del self._instances[ikey]
        return out

    def _alert_dict_locked(self, rule: AlertRule, key: str,
                           inst: _Instance, now: float) -> Dict[str, Any]:
        """A transition record: rendered AND appended to the bounded
        firing history (only _step_rule calls this, on fire/resolve)."""
        alert = _render_alert(rule, key, inst, now)
        self._history.append(dict(alert))
        return alert

    def _announce(self, alert: Dict[str, Any]) -> None:
        """Count, journal, and fan out one transition (outside the
        instance lock — subscribers may call back into the engine)."""
        try:
            from ray_tpu._private import builtin_metrics
            builtin_metrics.record_alert_transition(alert["state"])
        except Exception:  # noqa: BLE001 - counter is best-effort
            pass
        if self.journal is not None:
            sev = alert["severity"] if alert["state"] == "firing" else "info"
            key_part = f"[{alert['key']}]" if alert["key"] else ""
            self.journal.record(
                "alerting",
                f"alert {alert['rule']}{key_part} -> {alert['state']} "
                f"(value={alert['value']:.4g})",
                severity=sev,
                labels={"rule": alert["rule"], "key": alert["key"],
                        "state": alert["state"]})
        with self._lock:
            subs = list(self._subscribers)
        for fn in subs:
            try:
                fn(dict(alert))
            except Exception:  # noqa: BLE001 - a bad subscriber is not fatal
                logger.exception("alert subscriber failed")

    # -- read -------------------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        """Active instances + rule table + bounded firing history, all
        ages relative (monotonic discipline)."""
        now = time.monotonic()
        with self._lock:
            alerts = []
            for (rname, key), inst in self._instances.items():
                rule = self._rules.get(rname)
                if rule is None:
                    continue
                alerts.append(_render_alert(rule, key, inst, now))
            order = {"firing": 0, "pending": 1, "resolved": 2}
            alerts.sort(key=lambda a: (order.get(a["state"], 3), a["rule"]))
            return {
                "enabled": self.enabled,
                "period_s": self.period_s,
                "alerts": alerts,
                "firing": [a for a in alerts if a["state"] == "firing"],
                "rules": [r.describe() for r in self._rules.values()],
                "history": list(self._history),
            }

    def firing(self) -> List[Dict[str, Any]]:
        return self.snapshot()["firing"]
