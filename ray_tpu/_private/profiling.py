"""On-demand CPU profiling: stack sampling without external tooling.

Analog of the reference's dashboard profiling endpoints
(dashboard/modules/reporter/profile_manager.py:54 — py-spy flamegraphs /
speedscope traces on demand). py-spy is not a dependency here; instead
every ray_tpu process can sample ITS OWN threads via
``sys._current_frames`` at a fixed rate and emit collapsed ("folded")
stacks or a speedscope document. Cross-process profiling works by asking
the target process to sample itself: node daemons answer a ``profile``
control message (multinode.py), so ``ray-tpu profile --node <id>``
needs no ptrace and no extra binaries. When py-spy IS installed, it is
preferred for arbitrary pids (native stacks, no cooperation needed).
"""

from __future__ import annotations

import sys
import threading
import time
from typing import Dict, List, Optional

__all__ = ["sample_self", "folded_to_speedscope", "profile_self",
           "pyspy_available", "profile_pid_pyspy"]


def sample_self(duration_s: float = 5.0, hz: int = 100,
                skip_profiler: bool = True,
                stats: Optional[dict] = None) -> Dict[str, int]:
    """Sample every thread's Python stack for ``duration_s`` seconds at
    ``hz``; returns collapsed stacks ("thr;outer;...;inner" -> count,
    flamegraph.pl / speedscope input format).

    The sampler sleeps to the NEXT ABSOLUTE tick, not for a fixed
    period: ``sleep(period)`` after each sample would add the walk cost
    of every deep stack to the interval, silently dropping the
    effective rate below ``hz``. When a walk overruns one or more
    ticks, the missed ticks are skipped (not compressed into a burst)
    so samples stay evenly spaced. Pass a ``stats`` dict to receive
    ``{"ticks", "elapsed_s", "achieved_hz"}`` — the honest rate, which
    the speedscope export reports and uses to weight samples."""
    counts: Dict[str, int] = {}
    me = threading.get_ident()
    names = {t.ident: t.name for t in threading.enumerate()}
    period = 1.0 / max(hz, 1)
    t0 = time.monotonic()
    deadline = t0 + duration_s
    next_tick = t0
    ticks = 0
    while True:
        now = time.monotonic()
        if now >= deadline:
            break
        for ident, frame in sys._current_frames().items():
            if skip_profiler and ident == me:
                continue
            stack: List[str] = []
            f = frame
            while f is not None:
                code = f.f_code
                stack.append(f"{code.co_name} "
                             f"({code.co_filename.rsplit('/', 1)[-1]}:"
                             f"{f.f_lineno})")
                f = f.f_back
            name = names.get(ident) or str(ident)
            key = ";".join([name] + stack[::-1])
            counts[key] = counts.get(key, 0) + 1
        ticks += 1
        next_tick += period
        now = time.monotonic()
        while next_tick <= now:  # overran: skip missed ticks, stay on grid
            next_tick += period
        time.sleep(max(0.0, min(next_tick, deadline) - now))
    if stats is not None:
        elapsed = max(time.monotonic() - t0, 1e-9)
        stats["ticks"] = ticks
        stats["elapsed_s"] = elapsed
        stats["achieved_hz"] = ticks / elapsed
    return counts


def folded_to_speedscope(counts: Dict[str, int], name: str = "ray_tpu",
                         hz: int = 100,
                         achieved_hz: Optional[float] = None) -> dict:
    """Collapsed stacks -> a speedscope 'sampled' profile document
    (https://www.speedscope.app file-format-schema). When the sampler's
    measured ``achieved_hz`` is known, it weights the samples (each
    sample represents the real inter-tick interval, not the requested
    one) and is reported in the document."""
    frame_index: Dict[str, int] = {}
    frames: List[dict] = []
    samples: List[List[int]] = []
    weights: List[float] = []
    dt = 1.0 / max(achieved_hz or hz, 1e-9)
    for key, count in sorted(counts.items()):
        stack_ids = []
        for part in key.split(";"):
            if part not in frame_index:
                frame_index[part] = len(frames)
                frames.append({"name": part})
            stack_ids.append(frame_index[part])
        samples.append(stack_ids)
        weights.append(count * dt)
    total = sum(weights) or 1.0
    return {
        "$schema": "https://www.speedscope.app/file-format-schema.json",
        "shared": {"frames": frames},
        "profiles": [{
            "type": "sampled",
            "name": name,
            "unit": "seconds",
            "startValue": 0,
            "endValue": total,
            "samples": samples,
            "weights": weights,
        }],
        "name": name,
        "activeProfileIndex": 0,
        "exporter": "ray_tpu-profiler",
        "requestedHz": hz,
        "achievedHz": achieved_hz,
    }


def profile_self(duration_s: float = 5.0, hz: int = 100,
                 fmt: str = "folded"):
    """One-call self-profile: 'folded' text or 'speedscope' dict."""
    stats: dict = {}
    counts = sample_self(duration_s, hz, stats=stats)
    if fmt == "folded":
        return "\n".join(f"{k} {v}" for k, v in sorted(counts.items()))
    if fmt == "speedscope":
        return folded_to_speedscope(counts, hz=hz,
                                    achieved_hz=stats.get("achieved_hz"))
    raise ValueError(f"unknown profile format {fmt!r}")


def pyspy_available() -> bool:
    import shutil
    return shutil.which("py-spy") is not None


def profile_pid_pyspy(pid: int, duration_s: float = 5.0,
                      fmt: str = "speedscope") -> bytes:
    """Profile an arbitrary pid with py-spy (when installed): returns the
    raw output file bytes (reference: profile_manager.py py-spy record)."""
    import subprocess
    import tempfile
    suffix = ".speedscope.json" if fmt == "speedscope" else ".txt"
    out = tempfile.NamedTemporaryFile(suffix=suffix, delete=False)
    out.close()
    pyspy_fmt = "speedscope" if fmt == "speedscope" else "raw"
    subprocess.run(
        ["py-spy", "record", "--pid", str(pid), "--duration",
         str(int(duration_s)), "--format", pyspy_fmt, "--output", out.name],
        check=True, capture_output=True, timeout=duration_s + 30)
    with open(out.name, "rb") as f:
        return f.read()
