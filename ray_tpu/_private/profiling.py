"""CPU profiling: stack sampling without external tooling.

Analog of the reference's dashboard profiling endpoints
(dashboard/modules/reporter/profile_manager.py:54 — py-spy flamegraphs /
speedscope traces on demand). py-spy is not a dependency here; instead
every ray_tpu process can sample ITS OWN threads via
``sys._current_frames`` at a fixed rate and emit collapsed ("folded")
stacks or a speedscope document. Cross-process profiling works by asking
the target process to sample itself: node daemons answer a ``profile``
control message (multinode.py), so ``ray-tpu profile --node <id>``
needs no ptrace and no extra binaries. When py-spy IS installed, it is
preferred for arbitrary pids (native stacks, no cooperation needed).

Beyond the on-demand path, :class:`ProfilerAgent` runs a CONTINUOUS
low-rate sampler in every process (reference: Google-Wide Profiling —
always-on fleet sampling at a rate cheap enough to never turn off).
Samples accumulate as folded stacks tagged per thread with a
running/waiting annotation; the metrics cadence drains them into
``profile_batch`` frames toward the head's profile store
(``_private/profile_store.py``). ``RAY_TPU_PROFILE_HZ`` (flag
``profile_hz``) sets the rate; ``0`` disables the sampler entirely.

Sampler loops here must use ABSOLUTE-DEADLINE scheduling (sleep to the
next grid tick, skip missed ticks) — a constant-period ``sleep`` adds
every stack walk's cost to the interval and silently decays the rate;
an AST lint (tests/test_log_lint.py) bans constant ``time.sleep``
arguments anywhere in this module.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from typing import Dict, List, Optional

__all__ = ["sample_self", "folded_to_speedscope", "profile_self",
           "pyspy_available", "profile_pid_pyspy", "merge_folded",
           "ProfilerAgent", "configured_profile_hz", "ensure_profiler",
           "global_profiler", "shutdown_profiler"]

#: Default continuous-sampling rate: low enough that walking a handful
#: of thread stacks costs well under 1% CPU, high enough that a 5s
#: metrics tick ships ~50 samples per process.
DEFAULT_PROFILE_HZ = 10.0


def configured_profile_hz() -> float:
    """Continuous sampler rate; honors the documented uppercase env
    spelling first, then the flag table (live runtime config > env >
    default). ``<= 0`` disables the always-on sampler."""
    raw = os.environ.get("RAY_TPU_PROFILE_HZ", "")
    if raw:
        try:
            return float(raw)
        except ValueError:
            pass
    from ray_tpu._private.ray_config import runtime_config_value
    return float(runtime_config_value("profile_hz", DEFAULT_PROFILE_HZ))


def merge_folded(dst: Dict[str, int], src: Dict[str, int]
                 ) -> Dict[str, int]:
    """Merge folded-stack counts ``src`` into ``dst`` (in place; also
    returned). Addition is associative and commutative, so batches can
    merge in any grouping/order — the property the head-side store and
    the cluster-burst fan-in both rely on."""
    for key, count in src.items():
        dst[key] = dst.get(key, 0) + count
    return dst


def sample_self(duration_s: float = 5.0, hz: int = 100,
                skip_profiler: bool = True,
                stats: Optional[dict] = None) -> Dict[str, int]:
    """Sample every thread's Python stack for ``duration_s`` seconds at
    ``hz``; returns collapsed stacks ("thr;outer;...;inner" -> count,
    flamegraph.pl / speedscope input format).

    The sampler sleeps to the NEXT ABSOLUTE tick, not for a fixed
    period: ``sleep(period)`` after each sample would add the walk cost
    of every deep stack to the interval, silently dropping the
    effective rate below ``hz``. When a walk overruns one or more
    ticks, the missed ticks are skipped (not compressed into a burst)
    so samples stay evenly spaced. Pass a ``stats`` dict to receive
    ``{"ticks", "elapsed_s", "achieved_hz"}`` — the honest rate, which
    the speedscope export reports and uses to weight samples."""
    counts: Dict[str, int] = {}
    me = threading.get_ident()
    names = {t.ident: t.name for t in threading.enumerate()}
    period = 1.0 / max(hz, 1)
    t0 = time.monotonic()
    deadline = t0 + duration_s
    next_tick = t0
    ticks = 0
    while True:
        now = time.monotonic()
        if now >= deadline:
            break
        for ident, frame in sys._current_frames().items():
            if skip_profiler and ident == me:
                continue
            stack: List[str] = []
            f = frame
            while f is not None:
                code = f.f_code
                stack.append(f"{code.co_name} "
                             f"({code.co_filename.rsplit('/', 1)[-1]}:"
                             f"{f.f_lineno})")
                f = f.f_back
            name = names.get(ident) or str(ident)
            key = ";".join([name] + stack[::-1])
            counts[key] = counts.get(key, 0) + 1
        ticks += 1
        next_tick += period
        now = time.monotonic()
        while next_tick <= now:  # overran: skip missed ticks, stay on grid
            next_tick += period
        time.sleep(max(0.0, min(next_tick, deadline) - now))
    if stats is not None:
        elapsed = max(time.monotonic() - t0, 1e-9)
        stats["ticks"] = ticks
        stats["elapsed_s"] = elapsed
        stats["achieved_hz"] = ticks / elapsed
    return counts


def folded_to_speedscope(counts: Dict[str, int], name: str = "ray_tpu",
                         hz: int = 100,
                         achieved_hz: Optional[float] = None) -> dict:
    """Collapsed stacks -> a speedscope 'sampled' profile document
    (https://www.speedscope.app file-format-schema). When the sampler's
    measured ``achieved_hz`` is known, it weights the samples (each
    sample represents the real inter-tick interval, not the requested
    one) and is reported in the document."""
    frame_index: Dict[str, int] = {}
    frames: List[dict] = []
    samples: List[List[int]] = []
    weights: List[float] = []
    dt = 1.0 / max(achieved_hz or hz, 1e-9)
    for key, count in sorted(counts.items()):
        stack_ids = []
        for part in key.split(";"):
            if part not in frame_index:
                frame_index[part] = len(frames)
                frames.append({"name": part})
            stack_ids.append(frame_index[part])
        samples.append(stack_ids)
        weights.append(count * dt)
    total = sum(weights) or 1.0
    return {
        "$schema": "https://www.speedscope.app/file-format-schema.json",
        "shared": {"frames": frames},
        "profiles": [{
            "type": "sampled",
            "name": name,
            "unit": "seconds",
            "startValue": 0,
            "endValue": total,
            "samples": samples,
            "weights": weights,
        }],
        "name": name,
        "activeProfileIndex": 0,
        "exporter": "ray_tpu-profiler",
        "requestedHz": hz,
        "achievedHz": achieved_hz,
    }


def profile_self(duration_s: float = 5.0, hz: int = 100,
                 fmt: str = "folded"):
    """One-call self-profile: 'folded' text, 'speedscope' dict, or the
    raw 'dict' mapping (what cluster bursts ship so the head can merge
    before rendering)."""
    stats: dict = {}
    counts = sample_self(duration_s, hz, stats=stats)
    if fmt == "dict":
        return counts
    if fmt == "folded":
        return "\n".join(f"{k} {v}" for k, v in sorted(counts.items()))
    if fmt == "speedscope":
        return folded_to_speedscope(counts, hz=hz,
                                    achieved_hz=stats.get("achieved_hz"))
    raise ValueError(f"unknown profile format {fmt!r}")


#: Innermost-frame function names that mean the thread is parked, not
#: burning CPU — the running/waiting annotation distinguishes "the loop
#: is hot" from "the loop is blocked on IO/a lock" in flamegraphs.
_WAIT_FRAME_NAMES = frozenset({
    "wait", "wait_for", "sleep", "select", "poll", "epoll", "kqueue",
    "accept", "recv", "recv_into", "recvfrom", "read", "read1",
    "readinto", "readline", "acquire", "join", "get", "settimeout",
    "flush", "dowait", "_recv_msg", "recv_frame",
})


class ProfilerAgent:
    """Always-on background stack sampler for THIS process.

    Walks ``sys._current_frames()`` at ``hz`` on a daemon thread and
    accumulates folded stacks keyed
    ``"<thread> [running|waiting];outer;...;inner"``. The transport
    drains on the metrics cadence via :meth:`drain` and refunds failed
    publishes via :meth:`refund` so samples survive a dropped frame.
    ``hz <= 0`` builds a disabled agent (no thread, drains are empty).
    """

    def __init__(self, component: str, hz: Optional[float] = None,
                 start: bool = True):
        self.component = component
        self.hz = configured_profile_hz() if hz is None else float(hz)
        self.pid = os.getpid()
        self._lock = threading.Lock()
        self._counts: Dict[str, int] = {}
        self._samples = 0  # stack walks accumulated since last drain
        self._window_t0 = time.monotonic()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        if start and self.hz > 0:
            self._thread = threading.Thread(
                target=self._loop, daemon=True,
                name=f"ray_tpu-profiler-{component}")
            self._thread.start()

    @property
    def enabled(self) -> bool:
        return self.hz > 0 and not self._stop.is_set()

    def _loop(self) -> None:
        from ray_tpu._private import builtin_metrics
        period = 1.0 / max(self.hz, 1e-3)
        next_tick = time.monotonic()
        me = threading.get_ident()
        while not self._stop.is_set():
            now = time.monotonic()
            if now < next_tick:
                # Event wait doubles as the pacing sleep: a stop() wakes
                # the loop immediately instead of after one more period.
                if self._stop.wait(next_tick - now):
                    return
            try:
                walked = self._sample_once(me)
                builtin_metrics.record_profile_samples(walked)
            except Exception:  # noqa: BLE001 - sampling must never kill host
                pass
            next_tick += period
            now = time.monotonic()
            while next_tick <= now:  # overran: skip ticks, stay on grid
                next_tick += period

    def _sample_once(self, skip_ident: Optional[int] = None) -> int:
        """One walk over every thread; returns the number of stacks
        sampled. Public for tests and tick-less (worker) callers."""
        names = {t.ident: t.name for t in threading.enumerate()}
        walked = 0
        fresh: Dict[str, int] = {}
        for ident, frame in sys._current_frames().items():
            if ident == skip_ident:
                continue
            stack: List[str] = []
            f = frame
            while f is not None:
                code = f.f_code
                stack.append(f"{code.co_name} "
                             f"({code.co_filename.rsplit('/', 1)[-1]}:"
                             f"{f.f_lineno})")
                f = f.f_back
            if not stack:
                continue
            # stack[0] is the INNERMOST frame: a leaf parked in a wait
            # primitive marks the whole sample as blocked, anything
            # else as on-CPU (approximate — the GIL was held by someone
            # else during the walk — but cheap and overwhelmingly right
            # for the park-vs-burn question).
            leaf = stack[0].split(" ", 1)[0]
            state = "waiting" if leaf in _WAIT_FRAME_NAMES else "running"
            name = names.get(ident) or str(ident)
            key = ";".join([f"{name} [{state}]"] + stack[::-1])
            fresh[key] = fresh.get(key, 0) + 1
            walked += 1
        if fresh:
            with self._lock:
                merge_folded(self._counts, fresh)
                self._samples += walked
        return walked

    def drain(self) -> Optional[dict]:
        """Take (and clear) the accumulated stacks. Returns
        ``{"stacks", "samples", "duration_s"}`` or None when empty."""
        now = time.monotonic()
        with self._lock:
            if not self._counts:
                self._window_t0 = now
                return None
            stacks, self._counts = self._counts, {}
            samples, self._samples = self._samples, 0
            t0, self._window_t0 = self._window_t0, now
        return {"stacks": stacks, "samples": samples,
                "duration_s": max(0.0, now - t0)}

    def refund(self, stacks: Dict[str, int]) -> None:
        """Merge a failed-publish batch back into the accumulator so a
        dropped frame loses no samples (they ship on the next tick)."""
        with self._lock:
            merge_folded(self._counts, stacks)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None


_agent_lock = threading.Lock()
_agent: Optional[ProfilerAgent] = None


def ensure_profiler(component: str) -> Optional[ProfilerAgent]:
    """Start (or return) this process's singleton ProfilerAgent. None
    when the configured rate disables sampling."""
    global _agent
    with _agent_lock:
        if _agent is not None and _agent.enabled:
            return _agent
        agent = ProfilerAgent(component)
        if not agent.enabled:
            return None
        _agent = agent
        return agent


def global_profiler() -> Optional[ProfilerAgent]:
    return _agent


def shutdown_profiler() -> None:
    """Stop and forget the process profiler (runtime shutdown; a later
    ``ensure_profiler`` starts a fresh one)."""
    global _agent
    with _agent_lock:
        agent, _agent = _agent, None
    if agent is not None:
        agent.stop()


def pyspy_available() -> bool:
    import shutil
    return shutil.which("py-spy") is not None


def profile_pid_pyspy(pid: int, duration_s: float = 5.0,
                      fmt: str = "speedscope") -> bytes:
    """Profile an arbitrary pid with py-spy (when installed): returns the
    raw output file bytes (reference: profile_manager.py py-spy record)."""
    import subprocess
    import tempfile
    suffix = ".speedscope.json" if fmt == "speedscope" else ".txt"
    out = tempfile.NamedTemporaryFile(suffix=suffix, delete=False)
    out.close()
    pyspy_fmt = "speedscope" if fmt == "speedscope" else "raw"
    subprocess.run(
        ["py-spy", "record", "--pid", str(pid), "--duration",
         str(int(duration_s)), "--format", pyspy_fmt, "--output", out.name],
        check=True, capture_output=True, timeout=duration_s + 30)
    with open(out.name, "rb") as f:
        return f.read()
