"""Cluster metrics export: per-process agent + head-side cluster registry.

Analog of the reference's per-node metrics agent (dashboard/agent.py +
stats/metric_exporter.cc): every Ray process pushes its OpenCensus view
deltas to a local agent and Prometheus scrapes one endpoint per node
with ``Node``/``Component`` tags. Here the topology is simpler — one
scrape for the whole cluster:

* :class:`MetricsAgent` runs in every worker and daemon (and the head
  driver). On an interval (``RAY_TPU_METRICS_EXPORT_INTERVAL_S``,
  default 5s, ``<= 0`` disables) it snapshots the process-local registry
  (``util/metrics.py``), diffs against the previous snapshot, drains
  finished tracing spans, and hands the batch to a ``publish`` callback:
  daemons ship ``metrics_batch`` wire frames over the coalescing reply
  sender (the log subsystem's channel), workers buffer batches that
  piggyback on task replies, and the head publishes straight into its
  :class:`ClusterMetrics`.
* :class:`ClusterMetrics` (head only) merges batches per origin
  ``(node_id, pid, component)`` — values are cumulative, so merge is
  overwrite — and renders the cluster-wide Prometheus exposition with
  ``node_id``/``pid``/``component`` labels. Origins of a dead node are
  evicted once the staleness window passes
  (``RAY_TPU_METRICS_STALENESS_S``, default 30s).
"""

from __future__ import annotations

import logging
import os
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from ray_tpu.util import metrics as _metrics
from ray_tpu.util import tracing as _tracing

logger = logging.getLogger(__name__)

DEFAULT_INTERVAL_S = 5.0
DEFAULT_STALENESS_S = 30.0
#: Every Nth tick ships the full snapshot instead of a diff, healing any
#: batch a dying connection dropped (frames are best-effort).
FULL_REFRESH_TICKS = 12
#: Retained remote spans (matches util/tracing._MAX_SPANS).
MAX_CLUSTER_SPANS = 100_000


def export_interval_s() -> float:
    """The agent tick interval; ``<= 0`` disables export entirely."""
    raw = os.environ.get("RAY_TPU_METRICS_EXPORT_INTERVAL_S", "")
    if not raw:
        return DEFAULT_INTERVAL_S
    try:
        return float(raw)
    except ValueError:
        return DEFAULT_INTERVAL_S


def staleness_s() -> float:
    raw = os.environ.get("RAY_TPU_METRICS_STALENESS_S", "")
    try:
        return float(raw) if raw else DEFAULT_STALENESS_S
    except ValueError:
        return DEFAULT_STALENESS_S


class MetricsAgent:
    """Interval snapshot/diff/publish loop for one process's registry.

    ``publish(batch: dict) -> bool`` receives ``{"pid", "component",
    "metrics", "spans"}`` (no ``type``/``node_id`` — the transport stamps
    those) and returns False when the batch was dropped; the agent then
    resends the full snapshot on the next tick so the head re-converges.
    ``start=False`` leaves polling to the caller (tests, and the worker
    loop which flushes on every task reply).
    """

    def __init__(self, publish: Callable[[dict], bool], *,
                 component: str, interval_s: Optional[float] = None,
                 start: bool = True,
                 publish_profile: Optional[Callable[[dict], bool]] = None,
                 publish_flow: Optional[Callable[[dict], bool]] = None):
        self._publish = publish
        self.component = component
        self.pid = os.getpid()
        self.interval_s = (export_interval_s() if interval_s is None
                           else interval_s)
        # Continuous profiling rides the metrics cadence: when the host
        # supplies a profile transport, the agent owns a ProfilerAgent
        # and drains it into `publish_profile` every tick. A zero
        # RAY_TPU_PROFILE_HZ leaves _profiler None and the whole plane
        # dormant.
        self._publish_profile = publish_profile
        self._profiler = None
        if publish_profile is not None:
            from ray_tpu._private import profiling
            self._profiler = profiling.ensure_profiler(component)
        # Dataplane flow ledger rides the same cadence: the process's
        # FlowRecorder is drained into `publish_flow` every tick, with
        # refund-on-drop so transfer records are never silently lost.
        self._publish_flow = publish_flow
        # Every agent folds the hot-path fast cells before snapshotting,
        # so built-in counters bumped via dict adds reach the registry.
        from ray_tpu._private import builtin_metrics
        self._collectors: List[Callable[[], None]] = [
            builtin_metrics.flush_fast_counters]
        self._prev: Optional[List[Dict[str, Any]]] = None
        self._span_cursor = 0
        self._ticks = 0
        self._force_full = False
        self._poll_lock = threading.Lock()
        self._stop_event = threading.Event()
        self._thread: Optional[threading.Thread] = None
        if start and self.interval_s > 0:
            self._thread = threading.Thread(
                target=self._loop, daemon=True,
                name=f"ray_tpu-metrics-agent-{component}")
            self._thread.start()

    @property
    def enabled(self) -> bool:
        return self.interval_s > 0

    def add_collector(self, fn: Callable[[], None]) -> None:
        """Register a callback run right before each snapshot — the place
        level-style gauges (queue depth, pool size, store bytes) are
        refreshed without touching any hot path."""
        self._collectors.append(fn)

    def _loop(self) -> None:
        from ray_tpu._private import builtin_metrics
        while True:
            t0 = time.monotonic()
            if self._stop_event.wait(self.interval_s):
                return
            # Tick drift doubles as a per-process saturation gauge: a
            # GIL-starved or blocked process wakes late and the lag
            # series shows it cluster-wide.
            lag = (time.monotonic() - t0) - self.interval_s
            try:
                builtin_metrics.loop_lag().set(
                    max(0.0, lag), tags={"loop": f"agent.{self.component}"})
            except Exception:  # noqa: BLE001 - gauge is best-effort
                pass
            try:
                self.poll_once()
            except Exception:  # noqa: BLE001 - export must never kill host
                logger.exception("metrics agent poll failed")

    def poll_once(self, force_full: bool = False) -> bool:
        """One snapshot/diff/publish cycle. Returns True when a non-empty
        batch was handed to (and accepted by) the publish callback."""
        with self._poll_lock:
            for fn in self._collectors:
                try:
                    fn()
                except Exception:  # noqa: BLE001 - a bad gauge is not fatal
                    logger.exception("metrics collector failed")
            cur = _metrics.snapshot()
            full = (force_full or self._force_full or self._prev is None
                    or self._ticks % FULL_REFRESH_TICKS == 0)
            batch_metrics = cur if full else _metrics.diff_snapshot(
                self._prev, cur)
            self._ticks += 1
            self._prev = cur
            spans, self._span_cursor = _tracing.drain_finished_spans(
                self._span_cursor)
            self._maybe_publish_profile()
            self._maybe_publish_flow()
            # Cluster events ride the same frames as metrics (the
            # EventStats piggyback pattern): drain this process's
            # pending buffer into the batch, refund on a dropped frame.
            from ray_tpu._private import events as _events
            pending_events = _events.drain_pending()
            if not batch_metrics and not spans and not pending_events:
                return False
            batch = {"pid": self.pid, "component": self.component,
                     "metrics": batch_metrics, "spans": spans}
            if pending_events:
                batch["events"] = pending_events
            sent = bool(self._publish(batch))
            # A dropped frame means the head may now hold stale series:
            # resend everything once the channel recovers.
            self._force_full = not sent
            if not sent and pending_events:
                _events.refund_pending(pending_events)
            return sent

    def _maybe_publish_profile(self) -> None:
        """Drain the process profiler into its transport. A dropped
        frame refunds the stacks into the live window (they merge with
        the next drain) and bumps the drop counter — sample weight is
        never silently lost."""
        if self._profiler is None or self._publish_profile is None:
            return
        try:
            window = self._profiler.drain()
        except Exception:  # noqa: BLE001 - profiling is best-effort
            return
        if not window:
            return
        batch = {"pid": self.pid, "component": self.component,
                 "stacks": window["stacks"],
                 "samples": window["samples"],
                 "duration_s": window["duration_s"]}
        try:
            sent = bool(self._publish_profile(batch))
        except Exception:  # noqa: BLE001 - transport must not kill polls
            sent = False
        if not sent:
            from ray_tpu._private import builtin_metrics
            self._profiler.refund(window["stacks"])
            try:
                builtin_metrics.profile_batches_dropped().inc()
            except Exception:  # noqa: BLE001 - counter is best-effort
                pass

    def _maybe_publish_flow(self) -> None:
        """Drain the process FlowRecorder into its transport. A dropped
        frame refunds the records into the buffer (they ride the next
        tick) and bumps the drop counter — transfer accounting is never
        silently lost."""
        if self._publish_flow is None:
            return
        from ray_tpu._private import flow
        try:
            records = flow.global_flow_recorder().drain()
        except Exception:  # noqa: BLE001 - flow plane is best-effort
            return
        if not records:
            return
        batch = {"pid": self.pid, "component": self.component,
                 "records": records}
        try:
            sent = bool(self._publish_flow(batch))
        except Exception:  # noqa: BLE001 - transport must not kill polls
            sent = False
        if not sent:
            from ray_tpu._private import builtin_metrics
            flow.global_flow_recorder().refund(records)
            try:
                builtin_metrics.flow_batches_dropped().inc()
            except Exception:  # noqa: BLE001 - counter is best-effort
                pass

    def stop(self, drain: bool = True) -> None:
        self._stop_event.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
        if drain:
            try:
                self.poll_once(force_full=True)
            except Exception:  # noqa: BLE001 - teardown is best-effort
                pass
        if self._profiler is not None:
            from ray_tpu._private import profiling
            if profiling.global_profiler() is self._profiler:
                profiling.shutdown_profiler()
            else:
                self._profiler.stop()
            self._profiler = None


class _Origin:
    __slots__ = ("entries", "last_seen", "dead_at", "event_stats")

    def __init__(self):
        self.entries: Dict[str, Dict[str, Any]] = {}
        self.last_seen = time.monotonic()
        self.dead_at: Optional[float] = None
        # Latest EventStats summary shipped inside this origin's
        # metrics_batch frames (daemon control loops), if any.
        self.event_stats: Optional[Dict[str, Any]] = None


class ClusterMetrics:
    """Head-side cluster registry: merged per-origin snapshots + spans."""

    def __init__(self, staleness: Optional[float] = None):
        from ray_tpu._private.trace_assembler import TraceAssembler
        from ray_tpu._private.timeseries import TimeSeriesStore
        from ray_tpu._private.profile_store import ProfileStore
        self._lock = threading.Lock()
        self._origins: Dict[Tuple[str, int, str], _Origin] = {}
        self._spans: deque = deque(maxlen=MAX_CLUSTER_SPANS)
        # Every span converging here (head agent, daemon frames, worker
        # piggybacks) also feeds trace assembly, keyed by trace_id.
        self.traces = TraceAssembler()
        self.staleness = staleness_s() if staleness is None else staleness
        # Windowed history behind runtime.get_timeseries / serve stats /
        # `ray-tpu top` — every merged sample is also appended here.
        self.timeseries = TimeSeriesStore(staleness=self.staleness)
        # Continuous-profiling plane: profile_batch frames land here and
        # the loop-lag flight recorder watches every merged lag sample.
        self.profiles = ProfileStore(staleness=self.staleness)
        # Alerting plane: the journal collects piggybacked cluster
        # events; the engine evaluates its rule table against the
        # time-series store on this merge cadence (period-gated).
        from ray_tpu._private.events import EventJournal
        from ray_tpu._private.alerting import AlertEngine
        self.events = EventJournal()
        self.alerts = AlertEngine(journal=self.events)
        # Dataplane flow plane: flow_batch frames land here; the store
        # keeps the per-link matrix / fan-out table and restamps its
        # synthesized series into the time-series store each merge tick.
        from ray_tpu._private.flow import FlowStore
        self.flows = FlowStore()

    def update(self, node_id: str, batch: Dict[str, Any]) -> None:
        """Merge one ``metrics_batch`` payload. Cumulative values make the
        merge an overwrite per (metric, series key)."""
        key = (node_id or "", int(batch.get("pid", 0)),
               str(batch.get("component", "")))
        with self._lock:
            origin = self._origins.get(key)
            if origin is None:
                origin = self._origins[key] = _Origin()
            origin.last_seen = time.monotonic()
            origin.dead_at = None  # a publishing origin is alive
            for entry in batch.get("metrics", ()):
                name = entry.get("name")
                if not name:
                    continue
                held = origin.entries.get(name)
                if held is None or held.get("type") != entry.get("type"):
                    held = origin.entries[name] = {
                        "name": name, "type": entry.get("type"),
                        "desc": entry.get("desc", ""),
                        "tag_keys": tuple(entry.get("tag_keys") or ()),
                        "series": {},
                    }
                    if entry.get("type") == "histogram":
                        held["boundaries"] = tuple(
                            entry.get("boundaries") or ())
                        held["buckets"] = {}
                        held["sums"] = {}
                        held["counts"] = {}
                held["series"].update(entry.get("series", {}))
                if entry.get("type") == "histogram":
                    for field in ("buckets", "sums", "counts"):
                        held[field].update(entry.get(field, {}))
            stats = batch.get("event_stats")
            if stats:
                origin.event_stats = stats
        self.timeseries.ingest_batch(
            key[0], key[1], key[2], batch.get("metrics", ()))
        # Flight recorder: any loop-lag sample crossing the configured
        # threshold snapshots the lagging origin's hot stacks while the
        # window still holds them.
        for entry in batch.get("metrics", ()):
            if entry.get("name") != "ray_tpu_loop_lag_seconds":
                continue
            for tag_vals, lag in entry.get("series", {}).items():
                loop = tag_vals[0] if tag_vals else ""
                try:
                    recorded = self.profiles.observe_loop_lag(
                        str(loop), float(lag), key[0], key[1], key[2])
                    if recorded:
                        # Flight-recorder incidents are journal-worthy:
                        # the lag and origin land next to the alert the
                        # head_loop_lag rule may raise from them.
                        self.events.record(
                            "flight_recorder",
                            f"loop {loop} lagged {float(lag):.2f}s "
                            f"(stacks snapshotted)",
                            severity="warning", node_id=key[0],
                            labels={"loop": str(loop),
                                    "component": key[2]})
                except Exception:  # noqa: BLE001 - recorder is best-effort
                    logger.exception("flight recorder observe failed")
        events = batch.get("events")
        if events:
            self.events.ingest(node_id or "", events)
        # Restamp flow gauges (link mbps / stalled / fan-out) on the
        # merge cadence so idle links decay to zero and alert rules see
        # fresh values even when no new flow_batch arrives.
        try:
            self.flows.maybe_publish(self.timeseries)
        except Exception:  # noqa: BLE001 - flow plane must not break merges
            logger.exception("flow series publish failed")
        try:
            self.alerts.maybe_evaluate(self.timeseries)
        except Exception:  # noqa: BLE001 - alerting must not break merges
            logger.exception("alert evaluation failed")
        for span in batch.get("spans", ()):
            stamped = dict(span)
            stamped["node_id"] = node_id or ""
            stamped["pid"] = batch.get("pid", 0)
            stamped["component"] = batch.get("component", "")
            self._spans.append(stamped)
            self.traces.add_span(stamped)

    def update_profile(self, node_id: str, batch: Dict[str, Any]) -> None:
        """Merge one ``profile_batch`` payload into the profile store."""
        self.profiles.ingest(
            node_id or "", int(batch.get("pid", 0)),
            str(batch.get("component", "")),
            batch.get("stacks") or {},
            samples=int(batch.get("samples", 0)))

    def update_flows(self, node_id: str, batch: Dict[str, Any]) -> None:
        """Merge one ``flow_batch`` payload into the flow store and
        restamp its synthesized series immediately (throttled inside)."""
        self.flows.ingest(node_id or "", batch)
        self.flows.maybe_publish(self.timeseries)

    def mark_node_dead(self, node_id: str) -> None:
        """Start the staleness clock for every origin of a dead node; the
        series stay scrapeable through the window (Prometheus gets a last
        look) and are evicted after it."""
        now = time.monotonic()
        with self._lock:
            for (nid, _pid, _comp), origin in self._origins.items():
                if nid == node_id and origin.dead_at is None:
                    origin.dead_at = now
        self.timeseries.mark_node_dead(node_id)
        self.profiles.mark_node_dead(node_id)
        self.flows.mark_node_dead(node_id)

    def evict_stale(self) -> None:
        now = time.monotonic()
        with self._lock:
            dead = [key for key, origin in self._origins.items()
                    if origin.dead_at is not None
                    and now - origin.dead_at > self.staleness]
            for key in dead:
                del self._origins[key]
        self.timeseries.evict_stale()
        self.profiles.evict_stale()
        self.flows.evict_stale()

    def cluster_event_stats(self) -> Dict[str, Dict[str, Any]]:
        """EventStats summaries shipped in metrics_batch frames, keyed
        ``"<node_id>:<component>"`` (latest writer wins per handler)."""
        with self._lock:
            out: Dict[str, Dict[str, Any]] = {}
            ordered = sorted(self._origins.items(),
                             key=lambda kv: kv[1].last_seen)
            for (nid, _pid, comp), origin in ordered:
                if origin.event_stats:
                    out.setdefault(f"{nid}:{comp}", {}).update(
                        origin.event_stats)
            return out

    def origins(self) -> List[Tuple[str, int, str]]:
        with self._lock:
            return list(self._origins)

    def render(self) -> str:
        """The cluster-wide Prometheus exposition: every origin's series
        with node_id/pid/component labels appended."""
        self.evict_stale()
        groups = []
        with self._lock:
            for (node_id, pid, component), origin in self._origins.items():
                extra = {"node_id": node_id, "pid": str(pid),
                         "component": component}
                for entry in origin.entries.values():
                    groups.append((entry, extra))
        return _metrics.render_exposition(groups)

    def chrome_spans(self) -> List[Dict[str, Any]]:
        """Remote spans as chrome://tracing complete events (merged into
        /api/timeline next to the head's task events)."""
        out = []
        for s in list(self._spans):
            dur = s.get("duration")
            if dur is None:  # pre-monotonic peers ship no duration
                end = s.get("end_time") or s.get("start_time", 0.0)
                dur = end - s.get("start_time", 0.0)
            out.append({
                "name": s.get("name", ""),
                "cat": "remote_trace",
                "ph": "X",
                "ts": s.get("start_time", 0.0) * 1e6,
                "dur": max(0.0, dur) * 1e6,
                "pid": f"node:{(s.get('node_id') or 'head')[:12]}"
                       f"/{s.get('component', '')}-{s.get('pid', 0)}",
                "tid": s.get("span_id", ""),
                "args": dict(s.get("attributes") or {},
                             trace_id=s.get("trace_id", ""),
                             parent_id=s.get("parent_id")),
            })
        return out
