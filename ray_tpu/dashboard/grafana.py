"""Grafana dashboard factory.

Analog of the reference's
dashboard/modules/metrics/grafana_dashboard_factory.py: generates
importable Grafana dashboard JSON whose panels query THIS cluster's
Prometheus metrics (`/metrics` on the dashboard). Default panels cover
the core serving/scheduling surface; live registry metrics not covered
by a default panel get an auto-generated one, so custom
``util.metrics`` Counters/Gauges show up without configuration.
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, Optional

# (title, promql expr, unit) — the curated core panels (reference:
# grafana_dashboard_factory.py's default dashboard rows).
_DEFAULT_PANELS = [
    ("Tasks finished / s", "rate(ray_tpu_tasks_finished_total[1m])",
     "ops"),
    ("Tasks failed / s", "rate(ray_tpu_tasks_failed_total[1m])", "ops"),
    ("Scheduler queue depth", "ray_tpu_scheduler_pending_tasks", "short"),
    ("Object store bytes", "ray_tpu_object_store_bytes", "bytes"),
    ("Object spilled bytes / s",
     "rate(ray_tpu_object_spilled_bytes_total[1m])", "Bps"),
    ("Object restores / s (by recovery tier)",
     "sum by (source) (rate(ray_tpu_object_restores_total[5m]))", "ops"),
    ("Object spill failures / s (by op)",
     "sum by (op) (rate(ray_tpu_object_spill_failures_total[5m]))",
     "ops"),
    ("Object store hit rate",
     "rate(ray_tpu_object_store_hits_total[5m]) / "
     "(rate(ray_tpu_object_store_hits_total[5m]) + "
     "rate(ray_tpu_object_store_misses_total[5m]))", "percentunit"),
    ("Node count", "ray_tpu_alive_nodes", "short"),
    ("Actor count", "ray_tpu_actors", "short"),
    ("Actor restarts / s", "rate(ray_tpu_actor_restarts_total[5m])",
     "ops"),
    ("Channel reconnects / s",
     "rate(ray_tpu_channel_reconnects_total[5m])", "ops"),
    ("Channel frames resent / s",
     "rate(ray_tpu_channel_frames_resent_total[5m])", "ops"),
    ("Channel send retries / s",
     "rate(ray_tpu_channel_send_retries_total[5m])", "ops"),
    ("Channel bytes sent / s",
     "rate(ray_tpu_channel_bytes_sent_total[1m])", "Bps"),
    ("Channel pure acks / s",
     "rate(ray_tpu_channel_acks_sent_total[1m])", "ops"),
    ("Alert transitions / s (by state)",
     "sum by (state) (rate(ray_tpu_alerts_transitions_total[5m]))",
     "ops"),
    ("Cluster events / s (by severity)",
     "sum by (severity) (rate(ray_tpu_cluster_events_total[5m]))",
     "ops"),
    ("Profile samples / s (by component)",
     "sum by (component) (rate(ray_tpu_profile_samples_total[1m]))",
     "ops"),
    ("Profile batches dropped / s",
     "rate(ray_tpu_profile_batches_dropped_total[5m])", "ops"),
    ("Head recoveries", "ray_tpu_head_recoveries_total", "short"),
    ("Head recovery records replayed (by kind)",
     "sum by (kind) (ray_tpu_head_recovery_replayed_total)", "short"),
    ("Daemon re-dials / s (by outcome)",
     "sum by (outcome) (rate(ray_tpu_daemon_redials_total[5m]))", "ops"),
    ("GCS corrupt records skipped",
     "ray_tpu_gcs_corrupt_records_total", "short"),
    ("Serve failovers / s", "rate(ray_tpu_serve_failovers_total[5m])",
     "ops"),
    ("Serve replicas drained / s (by outcome)",
     "sum by (outcome) (rate(ray_tpu_serve_drained_total[5m]))", "ops"),
    ("Serve health-check failures / s",
     "rate(ray_tpu_serve_health_check_failures_total[5m])", "ops"),
    ("Serve requests shed / s", "rate(ray_tpu_serve_shed_total[1m])",
     "ops"),
    ("Serve qps (by deployment)",
     "sum by (deployment) (rate(ray_tpu_serve_requests_total[1m]))",
     "ops"),
    ("Serve p95 latency (by deployment)",
     "histogram_quantile(0.95, sum by (le, deployment) "
     "(rate(ray_tpu_serve_request_latency_seconds_bucket[5m])))", "s"),
    ("Serve queue depth (by deployment)",
     "sum by (deployment) (ray_tpu_serve_queue_depth)", "short"),
    ("Serve replicas (by deployment)",
     "max by (deployment) (ray_tpu_serve_replicas)", "short"),
    ("Serve target replicas (by deployment)",
     "max by (deployment) (ray_tpu_serve_target_replicas)", "short"),
    ("Serve autoscale decisions / min (by direction)",
     "sum by (direction) "
     "(rate(ray_tpu_serve_autoscale_decisions_total[5m])) * 60", "ops"),
    ("Serve batch size (by fn)",
     "max by (fn) (ray_tpu_serve_batch_size)", "short"),
    ("Head loop lag (by loop)",
     "max by (loop) (ray_tpu_loop_lag_seconds)", "s"),
    ("Train gang restarts / s (by cause)",
     "sum by (cause) (rate(ray_tpu_train_gang_restarts_total[5m]))",
     "ops"),
    ("Train checkpoints persisted / s",
     "rate(ray_tpu_train_checkpoints_persisted_total[5m])", "ops"),
    ("Train ckpt shard write bytes / s (by rank)",
     "sum by (rank) (rate(ray_tpu_train_ckpt_shard_bytes_total[5m]))",
     "Bps"),
    ("Train reshards / s (by direction)",
     "sum by (direction) (rate(ray_tpu_train_reshards_total[5m]))",
     "ops"),
    ("Worker pool size", "ray_tpu_worker_pool_size", "short"),
    ("Worker lease wait p95 (s)",
     "histogram_quantile(0.95, "
     "rate(ray_tpu_worker_lease_wait_seconds_bucket[5m]))", "s"),
    ("Log lines / s", "rate(ray_tpu_log_monitor_lines_total[1m])",
     "ops"),
    ("Trace stage p95 latency (s)",
     "histogram_quantile(0.95, sum by (le, stage) "
     "(rate(ray_tpu_trace_stage_seconds_bucket[5m])))", "s"),
    ("Trace stage time share",
     "sum by (stage) (rate(ray_tpu_trace_stage_seconds_sum[5m])) / "
     "ignoring (stage) group_left sum "
     "(rate(ray_tpu_trace_stage_seconds_sum[5m]))", "percentunit"),
    ("Data-plane pulled bytes / s",
     "rate(ray_tpu_dataplane_pulled_bytes_total[1m])", "Bps"),
    ("Object transfer bytes / s (by direction)",
     "sum by (direction) (rate(ray_tpu_object_transfer_bytes_total[1m]))",
     "Bps"),
    ("Pull chunks / s", "rate(ray_tpu_pull_chunks_total[1m])", "ops"),
    # Dataplane flow plane (flow.py): the head-synthesized per-link
    # series — a heatmap-able bytes rate per (src,dst) cell, the
    # windowed per-link MB/s gauge, and the top fan-out objects that
    # mark broadcast amplification.
    ("Transfer link bytes / s (src->dst heatmap)",
     "sum by (src, dst) (rate(ray_tpu_transfer_link_bytes_total[1m]))",
     "Bps"),
    ("Per-link transfer MB/s",
     "max by (link) (ray_tpu_transfer_link_mbps)", "MBs"),
    ("Top fan-out objects (nodes pulling one object)",
     "topk(10, max by (key) (ray_tpu_object_fanout_nodes))", "short"),
    # Collective dataplane: spanning-tree broadcasts launched, bytes
    # moved over the push tier, and how often locality placement lands
    # a task next to its argument bytes vs spilling it elsewhere.
    ("Broadcast trees / s", "rate(ray_tpu_broadcast_trees_total[5m])",
     "ops"),
    ("Broadcast push bytes / s",
     "rate(ray_tpu_push_bytes_total[1m])", "Bps"),
    ("Lease locality outcomes / s",
     "sum by (outcome) (rate(ray_tpu_lease_locality_total[5m]))",
     "ops"),
]


def _panel(panel_id: int, title: str, expr: str, unit: str,
           x: int, y: int) -> Dict[str, Any]:
    return {
        "id": panel_id,
        "title": title,
        "type": "timeseries",
        "datasource": {"type": "prometheus", "uid": "${datasource}"},
        "fieldConfig": {"defaults": {"unit": unit}, "overrides": []},
        "gridPos": {"h": 8, "w": 12, "x": x, "y": y},
        "targets": [{"expr": expr, "refId": "A",
                     "legendFormat": "__auto"}],
    }


def generate_dashboard(extra_metrics: Optional[List[str]] = None
                       ) -> Dict[str, Any]:
    """A complete importable Grafana dashboard document."""
    panels = []
    covered = set()
    pid = 1
    for i, (title, expr, unit) in enumerate(_DEFAULT_PANELS):
        panels.append(_panel(pid, title, expr, unit,
                             x=(i % 2) * 12, y=(i // 2) * 8))
        # Every metric family a curated expr touches counts as covered
        # (hit-rate/quantile exprs reference several; suffixes like
        # _bucket reduce to the registry's family name).
        for ref in re.findall(r"ray_tpu[a-zA-Z0-9_]*", expr):
            covered.add(ref)
            for suffix in ("_bucket", "_total"):
                if ref.endswith(suffix):
                    covered.add(ref[:-len(suffix)])
        pid += 1
    # Auto-panels for live registry metrics without a curated panel.
    names = list(extra_metrics or [])
    try:
        from ray_tpu.util.metrics import Counter, registry
        for name, metric in sorted(registry().items()):
            prom = name if name.startswith("ray_tpu") else \
                f"ray_tpu_{name}"
            if prom in covered or f"{prom}_total" in covered:
                continue
            if isinstance(metric, Counter):
                names.append(f"rate({prom}_total[1m])")
            else:
                names.append(prom)
    except Exception:  # noqa: BLE001 - registry optional in tools context
        pass
    base_y = (len(_DEFAULT_PANELS) // 2 + 1) * 8
    for i, expr in enumerate(names):
        title = expr.replace("rate(", "").split("[")[0].rstrip(")")
        panels.append(_panel(pid, title, expr, "short",
                             x=(i % 2) * 12, y=base_y + (i // 2) * 8))
        pid += 1
    return {
        "title": "ray_tpu cluster",
        "uid": "ray-tpu-core",
        "schemaVersion": 38,
        "version": 1,
        "refresh": "10s",
        "time": {"from": "now-30m", "to": "now"},
        "templating": {"list": [{
            "name": "datasource",
            "type": "datasource",
            "query": "prometheus",
        }]},
        # The event journal doubles as the annotation source: the
        # dashboard head serves Grafana-shaped rows ({time: epoch-ms,
        # text, tags}) at GET /api/events?fmt=annotations for a JSON
        # datasource; severity/source/node ride along as tags.
        "annotations": {"list": [{
            "name": "cluster events",
            "enable": True,
            "iconColor": "red",
            "hide": False,
            "target": {"type": "tags", "tags": ["error", "critical"]},
        }]},
        "panels": panels,
    }


def write_dashboards(out_dir: str) -> List[str]:
    """Write dashboard JSON files for Grafana provisioning; returns the
    written paths (the CLI face: ray-tpu grafana-dashboards)."""
    import json
    import os
    os.makedirs(out_dir, exist_ok=True)
    path = os.path.join(out_dir, "ray_tpu_core_dashboard.json")
    with open(path, "w") as f:
        json.dump(generate_dashboard(), f, indent=2)
    return [path]
