"""Dashboard head: the cluster's HTTP observability/ops endpoint.

Analog of the reference's dashboard head process (dashboard/dashboard.py +
dashboard/head.py with its pluggable modules): one aiohttp server exposing
the state API, metrics, job submission, and Serve status as JSON (the
reference's React client is a non-goal — SURVEY.md §7; consumers are the
CLI, the SDK, and curl).

Routes (all JSON unless noted):
  GET  /api/version            — framework version + session
  GET  /api/cluster_status     — resources, node table, demand
  GET  /api/v0/{actors,tasks,objects,nodes,placement_groups} — state API
  GET  /api/v0/tasks/summarize — task state counts
  GET  /metrics                — Prometheus text format
  GET  /api/logs               — session log tails (?node_id=&pid=
                                 &filename=&tail=; ?list=1 enumerates)
  GET  /api/jobs/              — list jobs
  POST /api/jobs/              — submit {entrypoint, runtime_env?}
  GET  /api/jobs/{id}          — job detail
  GET  /api/jobs/{id}/logs     — {logs}
  POST /api/jobs/{id}/stop
  GET  /api/serve/applications — Serve status
  PUT  /api/serve/applications — apply declarative Serve config
  GET  /api/timeline           — chrome://tracing events
  GET  /api/traces             — assembled distributed traces (?limit=)
  GET  /api/traces/{trace_id}  — one trace: spans, stages, origins
  GET  /api/event_stats        — control-plane handler latency stats
                                 (local head process + per-node merge)
  GET  /api/timeseries         — windowed metric history from the head
                                 store (?name=&window=&step=&label.k=v;
                                 no name lists the stored series)
  GET  /api/serve/stats        — per-deployment qps/p95/queue/replicas
                                 rollup (?window=, default 30s)
  GET  /api/alerts             — alert engine snapshot: active
                                 instances, rule table (?history=1
                                 adds the transition history)
  GET  /api/events             — cluster event journal (?severity=
                                 floor &source=&node_id=&since_seq=
                                 &limit=; ?fmt=annotations returns a
                                 Grafana annotations feed, epoch ms)
  GET  /                       — minimal HTML index
"""

from __future__ import annotations

import asyncio
import json
import logging
import threading
from typing import Any, Dict, Optional

logger = logging.getLogger("ray_tpu")


class DashboardHead:
    def __init__(self, host: str = "127.0.0.1", port: int = 8265):
        self.host = host
        self.port = port
        self._runner = None
        self._site = None
        self._thread: Optional[threading.Thread] = None
        self._loop = None
        self._job_manager = None
        self.bound_port: Optional[int] = None

    # -- handlers --------------------------------------------------------

    def _json(self, payload: Any, status: int = 200):
        from aiohttp import web
        return web.Response(text=json.dumps(payload, default=str),
                            status=status, content_type="application/json")

    async def _index(self, request):
        from aiohttp import web
        rows = "".join(
            f"<li><a href='{path}'>{path}</a></li>"
            for path in ("/api/version", "/api/cluster_status",
                         "/api/v0/actors", "/api/v0/tasks",
                         "/api/v0/nodes", "/api/jobs/", "/metrics",
                         "/api/logs?list=1",
                         "/api/serve/applications", "/api/timeline",
                         "/api/traces", "/api/event_stats",
                         "/api/timeseries", "/api/serve/stats",
                         "/api/alerts", "/api/events", "/api/flows"))
        return web.Response(
            text=f"<html><body><h2>ray_tpu dashboard</h2><ul>{rows}</ul>"
                 "</body></html>",
            content_type="text/html")

    async def _version(self, request):
        import ray_tpu
        from ray_tpu._private.worker import global_worker
        runtime = getattr(global_worker, "_runtime", None)
        return self._json({
            "version": ray_tpu.__version__,
            "session_id": getattr(runtime, "session_id", None),
        })

    async def _cluster_status(self, request):
        import ray_tpu
        from ray_tpu._private.worker import global_worker
        runtime = getattr(global_worker, "_runtime", None)
        # Membership internals (PR 11) read-only: per-node incarnation
        # epoch, phi suspicion, and silence since the last liveness
        # arrival — keyed by node_id for joins against `nodes`.
        membership = {}
        snap = getattr(runtime, "membership_snapshot", None)
        if snap is not None:
            membership = {row["node_id"]: row for row in snap()}
        # Firing alerts ride along so one status poll answers "is the
        # cluster healthy" without a second round-trip.
        alerts = {"firing": [], "firing_count": 0}
        alerts_fn = getattr(runtime, "alerts_snapshot", None)
        if alerts_fn is not None:
            try:
                firing = [a for a in
                          (await asyncio.to_thread(alerts_fn))["alerts"]
                          if a.get("state") == "firing"]
                alerts = {"firing": firing, "firing_count": len(firing)}
            except Exception:  # noqa: BLE001 - status must still answer
                pass
        return self._json({
            "cluster_resources": ray_tpu.cluster_resources(),
            "available_resources": ray_tpu.available_resources(),
            "nodes": ray_tpu.nodes(),
            "membership": membership,
            "alerts": alerts,
        })

    async def _state(self, request):
        from ray_tpu.experimental.state import api as state_api
        resource = request.match_info["resource"]
        fns = {
            "actors": state_api.list_actors,
            "tasks": state_api.list_tasks,
            "objects": state_api.list_objects,
            "nodes": state_api.list_nodes,
            "placement_groups": state_api.list_placement_groups,
        }
        if resource not in fns:
            return self._json({"error": f"unknown resource {resource}"},
                              status=404)
        return self._json({"result": fns[resource]()})

    async def _summarize_tasks(self, request):
        from ray_tpu.experimental.state import api as state_api
        return self._json({"result": state_api.summarize_tasks()})

    async def _metrics(self, request):
        """One Prometheus scrape for the whole cluster: the head's
        registry plus every daemon/worker batch, labeled node_id/pid/
        component. Falls back to the process-local exposition when no
        runtime is up (tools context)."""
        import asyncio

        from aiohttp import web

        from ray_tpu._private.worker import global_worker
        runtime = getattr(global_worker, "_runtime", None)
        text_fn = getattr(runtime, "cluster_metrics_text", None)
        if text_fn is not None:
            text = await asyncio.to_thread(text_fn)
        else:
            from ray_tpu.util.metrics import export_prometheus
            text = export_prometheus()
        return web.Response(text=text, content_type="text/plain")

    async def _event_stats(self, request):
        """Per-handler latency/queue stats of the control plane
        (reference: RAY_event_stats / instrumented_io_context dumps).
        ``local`` is this (head) process; ``cluster`` merges the
        summaries daemons piggyback on metrics_batch frames, keyed
        ``"<node_id>:<component>"``."""
        from ray_tpu._private.event_stats import GLOBAL
        from ray_tpu._private.worker import global_worker
        runtime = getattr(global_worker, "_runtime", None)
        cluster = {}
        fn = getattr(runtime, "cluster_event_stats", None)
        if fn is not None:
            cluster = await asyncio.to_thread(fn)
        return self._json({"local": GLOBAL.summary(), "cluster": cluster})

    async def _timeseries(self, request):
        """Windowed history for one metric from the head's time-series
        store: ``?name=`` (required), ``?window=`` seconds, ``?step=``
        resolution (1/10/60), optional ``?label.key=value`` filters."""
        from ray_tpu._private.worker import global_worker
        runtime = getattr(global_worker, "_runtime", None)
        if runtime is None:
            return self._json({"error": "no runtime"}, status=503)
        name = request.query.get("name")
        if not name:
            store = runtime._cluster_metrics.timeseries
            return self._json({"series_names": store.names(),
                               "series": store.series_count(),
                               "dropped_series": store.dropped_series})
        window = step = None
        try:
            if request.query.get("window"):
                window = float(request.query["window"])
            if request.query.get("step"):
                step = float(request.query["step"])
        except ValueError:
            return self._json({"error": "window/step must be numbers"},
                              status=400)
        labels = {k[len("label."):]: v for k, v in request.query.items()
                  if k.startswith("label.")}
        result = await asyncio.to_thread(
            runtime.get_timeseries, name, labels or None, window, step)
        return self._json(result)

    async def _serve_stats(self, request):
        """Per-deployment qps/p95/queue/replica rollup over ``?window=``
        seconds (default 30) — the autoscaler's polling input."""
        from ray_tpu._private.worker import global_worker
        runtime = getattr(global_worker, "_runtime", None)
        if runtime is None:
            return self._json({"error": "no runtime"}, status=503)
        window = None
        try:
            if request.query.get("window"):
                window = float(request.query["window"])
        except ValueError:
            return self._json({"error": "window must be a number"},
                              status=400)
        return self._json(
            await asyncio.to_thread(runtime.serve_stats, window))

    async def _timeline(self, request):
        from ray_tpu._private.state import timeline
        return self._json(timeline())

    async def _traces_list(self, request):
        """Assembled distributed traces, newest first (the head-side
        trace assembler merges spans arriving on metrics_batch frames
        per trace_id). ``?limit=N`` caps the listing;
        ``?summary=1`` returns the cluster-level stage breakdown
        instead (what `ray-tpu trace --summary` prints)."""
        import asyncio

        from ray_tpu._private.worker import global_worker
        runtime = getattr(global_worker, "_runtime", None)
        if runtime is None:
            return self._json({"error": "runtime not initialized"},
                              status=503)
        if request.query.get("summary"):
            return self._json(await asyncio.to_thread(
                runtime.trace_summary))
        limit = request.query.get("limit")
        rows = await asyncio.to_thread(
            runtime.trace_list, int(limit) if limit else None)
        return self._json({"traces": rows})

    async def _traces_get(self, request):
        """One assembled trace: spans sorted by start time, per-stage
        breakdown, participating origins. ``?fmt=perfetto`` returns
        Chrome-trace/Perfetto JSON (slices + cross-process flow
        events) loadable in ui.perfetto.dev."""
        import asyncio

        from ray_tpu._private.worker import global_worker
        runtime = getattr(global_worker, "_runtime", None)
        if runtime is None:
            return self._json({"error": "runtime not initialized"},
                              status=503)
        trace_id = request.match_info["trace_id"]
        if request.query.get("fmt") == "perfetto":
            events = await asyncio.to_thread(runtime.trace_perfetto,
                                             trace_id)
            if not events:
                return self._json({"error": f"no trace {trace_id!r}"},
                                  status=404)
            return self._json({"traceEvents": events})
        trace = await asyncio.to_thread(runtime.trace_get, trace_id)
        if trace is None:
            return self._json({"error": f"no trace {trace_id!r}"},
                              status=404)
        return self._json(trace)

    async def _logs(self, request):
        """Session log files over HTTP (reference: dashboard
        /api/v0/logs backed by the log agent; here the head reads the
        session dir directly). ``?list=1`` enumerates the capture
        files; otherwise returns the tail of files matching
        ``?node_id=&pid=&filename=&tail=``."""
        import asyncio

        from ray_tpu.experimental.state import api as state_api
        node_id = request.query.get("node_id")
        try:
            if request.query.get("list"):
                rows = await asyncio.to_thread(
                    state_api.list_logs, node_id)
                return self._json({"result": rows})
            pid = request.query.get("pid")
            tail = int(request.query.get("tail", 1000))
            lines = await asyncio.to_thread(
                state_api.get_log, request.query.get("filename"),
                node_id, int(pid) if pid is not None else None, tail)
            return self._json({"result": lines})
        except FileNotFoundError as exc:
            return self._json({"error": str(exc)}, status=404)
        except ValueError as exc:
            return self._json({"error": str(exc)}, status=400)

    # jobs ---------------------------------------------------------------

    def _jobs(self):
        if self._job_manager is None:
            from ray_tpu.job_submission import JobManager
            self._job_manager = JobManager()
        return self._job_manager

    async def _jobs_list(self, request):
        return self._json({"jobs": [j.__dict__ for j in
                                    self._jobs().list_jobs()]})

    async def _jobs_submit(self, request):
        body = await request.json()
        if "entrypoint" not in body:
            return self._json({"error": "entrypoint required"}, status=400)
        job_id = self._jobs().submit_job(
            entrypoint=body["entrypoint"],
            runtime_env=body.get("runtime_env"),
            submission_id=body.get("submission_id"))
        return self._json({"submission_id": job_id})

    async def _jobs_get(self, request):
        try:
            info = self._jobs().get_job_info(
                request.match_info["job_id"])
        except KeyError:
            return self._json({"error": "no such job"}, status=404)
        return self._json(info.__dict__)

    async def _jobs_logs(self, request):
        try:
            logs = self._jobs().get_job_logs(request.match_info["job_id"])
        except KeyError:
            return self._json({"error": "no such job"}, status=404)
        return self._json({"logs": logs})

    async def _jobs_stop(self, request):
        stopped = self._jobs().stop_job(request.match_info["job_id"])
        return self._json({"stopped": stopped})

    # serve --------------------------------------------------------------

    async def _serve_get(self, request):
        from ray_tpu import serve
        try:
            return self._json(serve.status())
        except Exception as exc:  # noqa: BLE001 - serve not running
            return self._json({"error": str(exc)}, status=503)

    async def _serve_put(self, request):
        from ray_tpu.serve.schema import apply_config
        body = await request.json()
        try:
            apply_config(body)
        except Exception as exc:  # noqa: BLE001 - config error → 400
            return self._json({"error": str(exc)}, status=400)
        return self._json({"status": "deployed"})

    # workflow events ----------------------------------------------------

    async def _workflows_list(self, request):
        from ray_tpu import workflow
        try:
            rows = workflow.list_all()
        except Exception as exc:  # noqa: BLE001 - storage not initialized
            return self._json({"error": str(exc)}, status=503)
        return self._json([{"workflow_id": wid, "status": status}
                           for wid, status in rows])

    async def _workflow_trigger_event(self, request):
        """Analog of the reference's workflow/http_event_provider.py: an
        external system POSTs here to release workflow tasks parked on
        workflow.wait_for_event(event_key). Body (optional JSON) becomes
        the event payload."""
        from ray_tpu import workflow
        event_key = request.match_info["event_key"]
        try:
            payload = await request.json()
        except Exception:  # noqa: BLE001 - empty/non-JSON body → None
            payload = None
        try:
            reached = workflow.trigger_event(event_key, payload)
        except ValueError as exc:
            return self._json({"error": str(exc)}, status=400)
        except Exception as exc:  # noqa: BLE001 - runtime not up yet
            return self._json({"error": str(exc)}, status=503)
        return self._json({"event_key": event_key, "reached": reached})

    # -- lifecycle -------------------------------------------------------

    async def _profile(self, request):
        """On-demand CPU profile (reference: dashboard
        modules/reporter/profile_manager.py:54). Targets: the head
        process (default), a node daemon (?node_id=, cooperative
        self-sampling over the control channel), or an arbitrary pid
        (?pid=, requires py-spy). ?fmt=folded|speedscope, ?duration=s."""
        import asyncio

        from ray_tpu._private.profiling import (profile_pid_pyspy,
                                                profile_self,
                                                pyspy_available)
        from ray_tpu._private.ray_config import runtime_config_value
        # Malformed knobs are the CALLER's error: answer 400 with the
        # offending name, never an unhandled 500.
        try:
            duration = float(request.query.get("duration", 5))
            hz = int(request.query.get("hz", 100))
        except ValueError:
            return self._json(
                {"error": "duration and hz must be numeric"}, status=400)
        if duration <= 0 or hz <= 0:
            return self._json(
                {"error": "duration and hz must be positive"}, status=400)
        duration = min(duration,
                       float(runtime_config_value(
                           "profile_max_duration_s", 60.0)))
        fmt = request.query.get("fmt", "folded")
        node_id = request.query.get("node_id")
        pid = request.query.get("pid")
        if pid is not None:
            try:
                pid = int(pid)
            except ValueError:
                return self._json({"error": "pid must be an integer"},
                                  status=400)
        try:
            if pid is not None:
                import os
                if int(pid) == os.getpid():
                    result = await asyncio.to_thread(
                        profile_self, duration, hz, fmt)
                else:
                    # Cluster pids (pool workers, any daemon's workers)
                    # resolve cooperatively through the owning process's
                    # burst endpoint; py-spy is only needed for pids the
                    # cluster does not know.
                    from ray_tpu._private.worker import global_worker
                    runtime = global_worker.runtime
                    try:
                        result = await asyncio.to_thread(
                            runtime.profile_pid, int(pid), duration, hz,
                            fmt)
                    except ValueError:
                        if not pyspy_available():
                            return self._json(
                                {"error": "pid is not a cluster worker "
                                          "and py-spy is not on PATH; "
                                          "use node_id= for daemons or "
                                          "omit pid for the head "
                                          "process"}, status=501)
                        raw = await asyncio.to_thread(
                            profile_pid_pyspy, int(pid), duration, fmt)
                        from aiohttp import web
                        return web.Response(body=raw)
            elif node_id is not None:
                from ray_tpu._private.worker import global_worker
                runtime = global_worker.runtime
                conn = None
                for nid, c in runtime._remote_nodes.items():
                    if nid.hex().startswith(node_id):
                        conn = c
                        break
                if conn is None:
                    return self._json(
                        {"error": f"no live node matches {node_id!r}"},
                        status=404)
                result = await asyncio.to_thread(
                    conn.profile, duration, hz, fmt)
            else:
                result = await asyncio.to_thread(
                    profile_self, duration, hz, fmt)
        except Exception as exc:  # noqa: BLE001 - surface to the caller
            return self._json({"error": repr(exc)}, status=500)
        if fmt == "speedscope":
            return self._json(result)
        from aiohttp import web
        return web.Response(text=result)

    async def _profile_flame(self, request):
        """Merged flamegraph from the continuous profiling windows
        (tentpole surface): ?component=driver|daemon|worker, ?node= (hex
        prefix), ?window=s, ?fmt=folded|speedscope|dict."""
        from ray_tpu._private.worker import global_worker
        import asyncio
        fmt = request.query.get("fmt", "folded")
        component = request.query.get("component")
        node = request.query.get("node")
        window = request.query.get("window")
        if window is not None:
            try:
                window = float(window)
            except ValueError:
                return self._json({"error": "window must be numeric"},
                                  status=400)
            if window <= 0:
                return self._json({"error": "window must be positive"},
                                  status=400)
        runtime = global_worker.runtime
        try:
            result = await asyncio.to_thread(
                runtime.profile_flame, component, node, window, fmt)
        except ValueError as exc:
            return self._json({"error": str(exc)}, status=400)
        if fmt == "folded":
            from aiohttp import web
            return web.Response(text=result)
        return self._json(result)

    async def _profile_diff(self, request):
        """Window-vs-window stack diff: ?window=s (default 60),
        ?component=, ?node=, ?limit=."""
        from ray_tpu._private.worker import global_worker
        import asyncio
        try:
            window = float(request.query.get("window", 60))
            limit = int(request.query.get("limit", 50))
        except ValueError:
            return self._json(
                {"error": "window and limit must be numeric"}, status=400)
        if window <= 0 or limit <= 0:
            return self._json(
                {"error": "window and limit must be positive"},
                status=400)
        runtime = global_worker.runtime
        rows = await asyncio.to_thread(
            runtime.profile_diff, window,
            request.query.get("component"), request.query.get("node"),
            limit)
        return self._json({"window_s": window, "diff": rows})

    async def _profile_incidents(self, request):
        """The loop-lag flight recorder's ring, newest first."""
        from ray_tpu._private.worker import global_worker
        runtime = global_worker.runtime
        return self._json({
            "incidents": runtime.profile_incidents(),
            "stats": runtime.profile_stats(),
        })

    async def _alerts(self, request):
        """Alert engine snapshot: active instances (firing → pending →
        resolved), rule table, ``?history=1`` adds the bounded
        transition history."""
        from ray_tpu._private.worker import global_worker
        runtime = getattr(global_worker, "_runtime", None)
        if runtime is None:
            return self._json({"error": "no runtime"}, status=503)
        snap = await asyncio.to_thread(runtime.alerts_snapshot)
        if not request.query.get("history"):
            snap.pop("history", None)
        return self._json(snap)

    async def _flows(self, request):
        """Dataplane flow plane: the per-link transfer matrix (windowed
        MB/s, p95 latency, failover/error counts per src->dst node
        pair), the per-object fan-out table, and per-node egress/
        ingress totals. ``?window=`` narrows the MB/s window (clamped
        to the store's)."""
        from ray_tpu._private.worker import global_worker
        runtime = getattr(global_worker, "_runtime", None)
        if runtime is None:
            return self._json({"error": "no runtime"}, status=503)
        q = request.query
        try:
            window = float(q["window"]) if q.get("window") else None
        except ValueError:
            return self._json({"error": "window must be a number"},
                              status=400)
        snap = await asyncio.to_thread(runtime.flows_snapshot, window)
        return self._json(snap)

    async def _events(self, request):
        """Cluster event journal. Filters: ``?severity=`` (a floor —
        ``warning`` includes error/critical), ``?source=``,
        ``?node_id=``, ``?since_seq=``, ``?limit=``.
        ``?fmt=annotations`` returns a Grafana annotations-style feed;
        journal rows are monotonic-stamped, so the epoch-ms conversion
        happens here at the HTTP boundary."""
        import time

        from ray_tpu._private.worker import global_worker
        runtime = getattr(global_worker, "_runtime", None)
        if runtime is None:
            return self._json({"error": "no runtime"}, status=503)
        q = request.query
        try:
            since_seq = int(q["since_seq"]) if q.get("since_seq") else None
            limit = int(q["limit"]) if q.get("limit") else None
        except ValueError:
            return self._json(
                {"error": "since_seq and limit must be integers"},
                status=400)
        if q.get("fmt") == "annotations":
            rows = await asyncio.to_thread(
                runtime.cluster_event_annotations, limit or 200)
            now_ms = int(time.time() * 1000)
            for row in rows:
                row["time"] = now_ms - int(row.pop("age_s", 0.0) * 1000)
            return self._json({"annotations": rows})
        try:
            rows = await asyncio.to_thread(
                runtime.cluster_events, q.get("severity"),
                q.get("source"), q.get("node_id"), since_seq, limit)
        except ValueError as exc:
            return self._json({"error": str(exc)}, status=400)
        return self._json({"events": rows,
                           "stats": runtime.cluster_events_stats()})

    async def _grafana(self, request):
        """Generated Grafana dashboard JSON over this cluster's
        Prometheus metrics (reference:
        metrics/grafana_dashboard_factory.py)."""
        from ray_tpu.dashboard.grafana import generate_dashboard
        return self._json(generate_dashboard())

    def _build_app(self):
        from aiohttp import web
        app = web.Application()
        app.router.add_get("/", self._index)
        app.router.add_get("/api/version", self._version)
        app.router.add_get("/api/cluster_status", self._cluster_status)
        app.router.add_get("/api/v0/tasks/summarize", self._summarize_tasks)
        app.router.add_get("/api/v0/{resource}", self._state)
        app.router.add_get("/metrics", self._metrics)
        app.router.add_get("/api/logs", self._logs)
        app.router.add_get("/api/timeline", self._timeline)
        app.router.add_get("/api/traces", self._traces_list)
        app.router.add_get("/api/traces/{trace_id}", self._traces_get)
        app.router.add_get("/api/event_stats", self._event_stats)
        app.router.add_get("/api/timeseries", self._timeseries)
        app.router.add_get("/api/serve/stats", self._serve_stats)
        app.router.add_get("/api/jobs/", self._jobs_list)
        app.router.add_post("/api/jobs/", self._jobs_submit)
        app.router.add_get("/api/jobs/{job_id}", self._jobs_get)
        app.router.add_get("/api/jobs/{job_id}/logs", self._jobs_logs)
        app.router.add_post("/api/jobs/{job_id}/stop", self._jobs_stop)
        app.router.add_get("/api/serve/applications", self._serve_get)
        app.router.add_put("/api/serve/applications", self._serve_put)
        app.router.add_get("/api/workflows/", self._workflows_list)
        app.router.add_post("/api/workflows/events/{event_key}",
                            self._workflow_trigger_event)
        app.router.add_get("/api/profile", self._profile)
        app.router.add_get("/api/profile/flame", self._profile_flame)
        app.router.add_get("/api/profile/diff", self._profile_diff)
        app.router.add_get("/api/profile/incidents",
                           self._profile_incidents)
        app.router.add_get("/api/alerts", self._alerts)
        app.router.add_get("/api/events", self._events)
        app.router.add_get("/api/flows", self._flows)
        app.router.add_get("/api/grafana_dashboard", self._grafana)
        return app

    def start(self) -> int:
        """Run the server on a daemon thread; returns the bound port."""
        import asyncio

        from aiohttp import web

        ready = threading.Event()

        def run():
            loop = asyncio.new_event_loop()
            asyncio.set_event_loop(loop)
            self._loop = loop

            async def setup():
                runner = web.AppRunner(self._build_app())
                await runner.setup()
                site = web.TCPSite(runner, self.host, self.port)
                await site.start()
                self._runner = runner
                self.bound_port = runner.addresses[0][1]

            async def lag_probe():
                # Asyncio loop saturation: sleep a fixed period and
                # gauge how late the wakeup lands — slow handlers or a
                # starved thread show up as dashboard loop lag.
                from ray_tpu._private import builtin_metrics
                period = 1.0
                while True:
                    t0 = loop.time()
                    await asyncio.sleep(period)
                    lag = (loop.time() - t0) - period
                    try:
                        builtin_metrics.loop_lag().set(
                            max(0.0, lag), tags={"loop": "dashboard"})
                    except Exception:  # noqa: BLE001 - best-effort
                        pass

            loop.run_until_complete(setup())
            self._lag_task = loop.create_task(lag_probe())
            ready.set()
            loop.run_forever()

        self._thread = threading.Thread(target=run, name="ray_tpu-dashboard",
                                        daemon=True)
        self._thread.start()
        if not ready.wait(timeout=10):
            raise RuntimeError("Dashboard failed to start within 10s")
        return self.bound_port

    def stop(self) -> None:
        import asyncio
        if self._loop is not None:
            async def teardown():
                task = getattr(self, "_lag_task", None)
                if task is not None:
                    task.cancel()
                if self._runner is not None:
                    await self._runner.cleanup()
            fut = asyncio.run_coroutine_threadsafe(teardown(), self._loop)
            try:
                fut.result(timeout=5)
            except Exception:  # noqa: BLE001
                pass
            self._loop.call_soon_threadsafe(self._loop.stop)
        if self._thread is not None:
            self._thread.join(timeout=5)


_dashboard: Optional[DashboardHead] = None


def start_dashboard(host: str = "127.0.0.1", port: int = 8265
                    ) -> DashboardHead:
    """Start (or return) the process-wide dashboard head. port=0 picks an
    ephemeral port (DashboardHead.bound_port)."""
    global _dashboard
    if _dashboard is None:
        _dashboard = DashboardHead(host, port)
        _dashboard.start()
    return _dashboard
