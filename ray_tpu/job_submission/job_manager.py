"""Job submission (analog of dashboard/modules/job/).

The reference runs each submitted job's entrypoint as a subprocess supervised
by a JobSupervisor actor, with status persisted to GCS KV and logs streamed
to per-job files (dashboard/modules/job/job_manager.py); the SDK/CLI talk to
it over REST (modules/job/sdk.py:40). Here the JobManager supervises the
subprocess directly (same contract: entrypoint shell command, env injection
via runtime_env, log capture, status polling, stop); JobSubmissionClient is
the SDK facade the CLI and user code share.
"""

from __future__ import annotations

import enum
import os
import subprocess
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional


class JobStatus(str, enum.Enum):
    PENDING = "PENDING"
    RUNNING = "RUNNING"
    STOPPED = "STOPPED"
    SUCCEEDED = "SUCCEEDED"
    FAILED = "FAILED"

    def is_terminal(self) -> bool:
        return self in (JobStatus.STOPPED, JobStatus.SUCCEEDED,
                        JobStatus.FAILED)


@dataclass
class JobDetails:
    job_id: str
    submission_id: str
    entrypoint: str
    status: JobStatus
    message: str = ""
    start_time: Optional[float] = None
    end_time: Optional[float] = None
    metadata: Dict[str, str] = field(default_factory=dict)
    runtime_env: Dict[str, Any] = field(default_factory=dict)


class _Job:
    def __init__(self, details: JobDetails, log_path: str):
        self.details = details
        self.log_path = log_path
        self.process: Optional[subprocess.Popen] = None
        self.monitor: Optional[threading.Thread] = None


class JobManager:
    """Supervises job subprocesses. One per (head) runtime."""

    def __init__(self, log_dir: Optional[str] = None):
        import tempfile
        self._jobs: Dict[str, _Job] = {}
        self._lock = threading.Lock()
        # "ray-tpu" (hyphen): an importable dir name here would shadow the
        # package for any driver whose cwd is the temp dir.
        self._log_dir = log_dir or os.path.join(
            tempfile.gettempdir(), "ray-tpu", "job_logs")
        os.makedirs(self._log_dir, exist_ok=True)

    def submit_job(self, *, entrypoint: str,
                   submission_id: Optional[str] = None,
                   runtime_env: Optional[dict] = None,
                   metadata: Optional[Dict[str, str]] = None) -> str:
        submission_id = submission_id or f"raysubmit_{uuid.uuid4().hex[:16]}"
        with self._lock:
            if submission_id in self._jobs:
                raise ValueError(
                    f"Job {submission_id!r} already exists.")
        details = JobDetails(
            job_id=submission_id, submission_id=submission_id,
            entrypoint=entrypoint, status=JobStatus.PENDING,
            metadata=dict(metadata or {}),
            runtime_env=dict(runtime_env or {}))
        log_path = os.path.join(self._log_dir, f"{submission_id}.log")
        job = _Job(details, log_path)
        with self._lock:
            self._jobs[submission_id] = job
        self._start(job)
        return submission_id

    def _start(self, job: _Job) -> None:
        env = dict(os.environ)
        renv = job.details.runtime_env
        env.update(renv.get("env_vars") or {})
        env["RAY_TPU_JOB_ID"] = job.details.submission_id
        cwd = renv.get("working_dir") or None
        log_file = open(job.log_path, "wb")
        try:
            job.process = subprocess.Popen(
                job.details.entrypoint, shell=True, env=env, cwd=cwd,
                stdout=log_file, stderr=subprocess.STDOUT)
        except OSError as e:
            job.details.status = JobStatus.FAILED
            job.details.message = f"Failed to start: {e}"
            log_file.close()
            return
        job.details.status = JobStatus.RUNNING
        job.details.start_time = time.time()
        job.monitor = threading.Thread(
            target=self._monitor, args=(job, log_file), daemon=True)
        job.monitor.start()

    def _monitor(self, job: _Job, log_file) -> None:
        code = job.process.wait()
        log_file.close()
        job.details.end_time = time.time()
        if job.details.status == JobStatus.STOPPED:
            return
        if code == 0:
            job.details.status = JobStatus.SUCCEEDED
            job.details.message = "Job finished successfully."
        else:
            job.details.status = JobStatus.FAILED
            job.details.message = f"Job failed with exit code {code}."

    def get_job_status(self, submission_id: str) -> JobStatus:
        return self._job(submission_id).details.status

    def get_job_info(self, submission_id: str) -> JobDetails:
        return self._job(submission_id).details

    def get_job_logs(self, submission_id: str) -> str:
        job = self._job(submission_id)
        try:
            with open(job.log_path, "rb") as f:
                return f.read().decode(errors="replace")
        except FileNotFoundError:
            return ""

    def stop_job(self, submission_id: str) -> bool:
        job = self._job(submission_id)
        if job.details.status.is_terminal() or job.process is None:
            return False
        job.details.status = JobStatus.STOPPED
        job.details.message = "Job was intentionally stopped."
        job.process.terminate()
        try:
            job.process.wait(timeout=5)
        except subprocess.TimeoutExpired:
            job.process.kill()
        return True

    def delete_job(self, submission_id: str) -> bool:
        job = self._job(submission_id)
        if not job.details.status.is_terminal():
            raise RuntimeError(
                f"Job {submission_id!r} is {job.details.status}; stop it "
                "before deleting.")
        with self._lock:
            del self._jobs[submission_id]
        return True

    def list_jobs(self) -> List[JobDetails]:
        with self._lock:
            return [j.details for j in self._jobs.values()]

    def _job(self, submission_id: str) -> _Job:
        with self._lock:
            job = self._jobs.get(submission_id)
        if job is None:
            raise ValueError(f"Job {submission_id!r} does not exist.")
        return job


_default_manager: Optional[JobManager] = None
_default_lock = threading.Lock()


def _manager() -> JobManager:
    global _default_manager
    with _default_lock:
        if _default_manager is None:
            _default_manager = JobManager()
        return _default_manager


class JobSubmissionClient:
    """SDK facade (analog of dashboard/modules/job/sdk.py:40). ``address``
    is accepted for API parity; the in-process manager serves all of them."""

    def __init__(self, address: Optional[str] = None):
        self.address = address or "local"
        self._manager = _manager()

    def submit_job(self, *, entrypoint: str,
                   submission_id: Optional[str] = None,
                   runtime_env: Optional[dict] = None,
                   metadata: Optional[Dict[str, str]] = None) -> str:
        return self._manager.submit_job(
            entrypoint=entrypoint, submission_id=submission_id,
            runtime_env=runtime_env, metadata=metadata)

    def get_job_status(self, submission_id: str) -> JobStatus:
        return self._manager.get_job_status(submission_id)

    def get_job_info(self, submission_id: str) -> JobDetails:
        return self._manager.get_job_info(submission_id)

    def get_job_logs(self, submission_id: str) -> str:
        return self._manager.get_job_logs(submission_id)

    def stop_job(self, submission_id: str) -> bool:
        return self._manager.stop_job(submission_id)

    def delete_job(self, submission_id: str) -> bool:
        return self._manager.delete_job(submission_id)

    def list_jobs(self) -> List[JobDetails]:
        return self._manager.list_jobs()

    def tail_job_logs(self, submission_id: str, timeout: float = 60.0):
        """Generator yielding log chunks until the job reaches a terminal
        state (SDK parity with the reference's async log tailing). Reads
        incrementally from the last offset (no full-file re-reads)."""
        log_path = self._manager._job(submission_id).log_path
        offset = 0
        deadline = time.monotonic() + timeout

        def _read_new():
            nonlocal offset
            try:
                with open(log_path, "rb") as f:
                    f.seek(offset)
                    chunk = f.read()
            except FileNotFoundError:
                return ""
            offset += len(chunk)
            return chunk.decode(errors="replace")

        while time.monotonic() < deadline:
            chunk = _read_new()
            if chunk:
                yield chunk
            if self.get_job_status(submission_id).is_terminal():
                final = _read_new()
                if final:
                    yield final
                return
            time.sleep(0.2)
