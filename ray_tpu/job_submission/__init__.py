from ray_tpu.job_submission.job_manager import (JobDetails, JobManager,
                                                JobStatus, JobSubmissionClient)

__all__ = ["JobSubmissionClient", "JobManager", "JobStatus", "JobDetails"]
