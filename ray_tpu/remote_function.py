"""@remote functions.

Analog of the reference's python/ray/remote_function.py: the decorator wraps
the function in a RemoteFunction whose ``.remote(...)`` submits a task and
returns ObjectRef(s); ``.options(...)`` overrides call options.
"""

from __future__ import annotations

import functools
from typing import Any, Dict

from ray_tpu._private import task_spec as ts
from ray_tpu._private.ids import TaskID
from ray_tpu._private.task_spec import TaskKind, TaskSpec, validate_options
from ray_tpu._private.worker import global_worker


class RemoteFunction:
    def __init__(self, fn, options: Dict[str, Any]):
        self._function = fn
        self._default_options = validate_options(options, for_actor=False)
        # Export cache keyed by runtime session (a new init() gets a fresh
        # function table, so the export must be redone).
        self._exported: tuple = ("", None)
        functools.update_wrapper(self, fn)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Remote function {self._function.__name__!r} cannot be called "
            "directly. Use .remote() instead.")

    def options(self, **options) -> "RemoteFunction":
        merged = {**self._default_options, **options}
        clone = RemoteFunction(self._function, merged)
        clone._exported = self._exported
        return clone

    def remote(self, *args, **kwargs):
        return self._remote(args, kwargs, self._default_options)

    def bind(self, *args, **kwargs):
        """Build a DAG node instead of submitting (reference:
        python/ray/dag/: fn.bind → FunctionNode)."""
        from ray_tpu.dag import FunctionNode
        return FunctionNode(self, args, kwargs)

    def _function_id_for(self, runtime):
        session, fn_id = self._exported
        if session != runtime.session_id:
            fn_id = runtime.register_function(self._function)
            self._exported = (runtime.session_id, fn_id)
        return fn_id

    def _remote(self, args, kwargs, options):
        runtime = global_worker.runtime
        function_id = self._function_id_for(runtime)
        num_returns = options.get("num_returns", 1)
        if num_returns is None:
            num_returns = 1
        from ray_tpu.util.scheduling_strategies import strategy_from_options
        strategy = strategy_from_options(options)
        spec = TaskSpec(
            task_id=TaskID.for_normal_task(runtime.job_id),
            kind=TaskKind.NORMAL,
            function_id=function_id,
            args=tuple(args),
            kwargs=dict(kwargs),
            resources=ts.resources_from_options(options, for_actor=False),
            num_returns=num_returns,
            name=options.get("name") or self._function.__qualname__,
            max_retries=options.get("max_retries", 3),
            retry_exceptions=options.get("retry_exceptions", False),
            scheduling_strategy=strategy,
            runtime_env=options.get("runtime_env"),
        )
        refs = runtime.submit_task(spec)
        if num_returns == 0:
            return None
        if num_returns == 1 or num_returns == "dynamic":
            return refs[0]
        return refs


def remote(*args, **kwargs):
    """``@remote`` / ``@remote(**options)`` for functions and classes."""
    from ray_tpu.actor import ActorClass

    def decorate(target, options):
        if isinstance(target, type):
            return ActorClass(target, options)
        if not callable(target):
            raise TypeError(
                "@remote must decorate a function or a class, got "
                f"{type(target).__name__}")
        return RemoteFunction(target, options)

    if len(args) == 1 and not kwargs and (callable(args[0])):
        return decorate(args[0], {})
    if args:
        raise TypeError(
            "@remote takes keyword options only, e.g. "
            "@remote(num_cpus=2, num_tpus=1)")
    return lambda target: decorate(target, kwargs)
