"""Internal key-value store (reference: ray.experimental.internal_kv →
GCS InternalKV, src/ray/gcs/gcs_server/gcs_kv_manager.h).

Head-resident; persisted to disk when the cluster runs with
``_system_config={"gcs_store_path": ...}`` so the table survives head
restarts (the reference's Redis-backed mode)."""

from __future__ import annotations

from typing import List, Optional


def _runtime():
    from ray_tpu._private.worker import global_worker
    runtime = getattr(global_worker, "_runtime", None)
    if runtime is None:
        raise RuntimeError("ray_tpu.init() has not been called")
    return runtime


def _internal_kv_initialized() -> bool:
    from ray_tpu._private.worker import global_worker
    return getattr(global_worker, "_runtime", None) is not None


def _as_bytes(v) -> bytes:
    return v.encode() if isinstance(v, str) else bytes(v)


def _internal_kv_put(key, value, overwrite: bool = True,
                     namespace: str = "default") -> bool:
    """Returns ``already_exists`` — True iff the key was present before
    this put (reference: ray.experimental.internal_kv semantics)."""
    return _runtime().kv_put(namespace, _as_bytes(key), _as_bytes(value),
                             overwrite)


def _internal_kv_get(key, namespace: str = "default") -> Optional[bytes]:
    return _runtime().kv_get(namespace, _as_bytes(key))


def _internal_kv_exists(key, namespace: str = "default") -> bool:
    return _runtime().kv_get(namespace, _as_bytes(key)) is not None


def _internal_kv_del(key, namespace: str = "default") -> bool:
    return _runtime().kv_del(namespace, _as_bytes(key))


def _internal_kv_list(prefix, namespace: str = "default") -> List[bytes]:
    return _runtime().kv_keys(namespace, _as_bytes(prefix))
