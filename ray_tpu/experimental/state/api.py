"""State API: programmatic cluster introspection.

Analog of the reference's python/ray/experimental/state/api.py
(list_actors :736, list_tasks :959, list_objects :1003, list_nodes,
list_placement_groups, summarize_tasks) backed by the runtime's live state
instead of the GCS/dashboard aggregator.
"""

from __future__ import annotations

from collections import Counter as _Counter
from typing import Any, Dict, List, Optional

from ray_tpu._private.worker import global_worker


def _runtime():
    rt = global_worker.runtime
    if rt is None:
        raise RuntimeError("ray_tpu is not initialized")
    return rt


def list_actors(filters: Optional[List[tuple]] = None,
                limit: int = 1000) -> List[Dict[str, Any]]:
    rt = _runtime()
    out = []
    for actor_id, state in list(rt._actors.items()):
        row = {
            "actor_id": actor_id.hex(),
            "class_name": state.creation_spec.name.replace(".__init__", ""),
            "state": "DEAD" if state.dead else (
                "ALIVE" if state.created.is_set() else "PENDING_CREATION"),
            "name": state.name,
            "namespace": state.namespace,
            "lifetime": state.lifetime or "non_detached",
            "num_restarts": state.num_restarts,
            "pending_tasks": len(state.unfinished),
        }
        out.append(row)
    return _apply_filters(out, filters)[:limit]


def list_tasks(filters: Optional[List[tuple]] = None,
               limit: int = 10_000) -> List[Dict[str, Any]]:
    rt = _runtime()
    latest: Dict[str, Dict[str, Any]] = {}
    for ev in rt.task_events():
        row = latest.setdefault(ev["task_id"], {
            "task_id": ev["task_id"], "name": ev["name"],
            "state": None, "node_id": None, "start_time": None,
            "end_time": None, "duration_s": None, "_last_time": 0.0})
        row["state"] = ev["status"]
        row["_last_time"] = ev["time"]
        if ev.get("node_id"):
            row["node_id"] = ev["node_id"]
        if ev["status"] == "RUNNING":
            row["start_time"] = ev["time"]
        elif ev["status"] in ("FINISHED", "FAILED"):
            row["end_time"] = ev["time"]
            if row["start_time"] is not None:
                row["duration_s"] = ev["time"] - row["start_time"]
    # Most-recent-first, and the limit applies AFTER the sort — dict
    # (insertion) order would keep the oldest tasks and drop the newest.
    rows = sorted(latest.values(), key=lambda r: r["_last_time"],
                  reverse=True)
    for row in rows:
        del row["_last_time"]
    return _apply_filters(rows, filters)[:limit]


def list_objects(filters: Optional[List[tuple]] = None,
                 limit: int = 10_000) -> List[Dict[str, Any]]:
    rt = _runtime()
    out = []
    with rt.store._lock:
        entries = list(rt.store._entries.items())
    for oid, entry in entries:
        out.append({
            "object_id": oid.hex(),
            "sealed": entry.event.is_set(),
            "is_exception": entry.is_exception,
            "freed": entry.freed,
            "in_native_store": entry.in_native,
            "size_bytes": entry.size_bytes,
        })
    return _apply_filters(out, filters)[:limit]


def list_nodes(filters: Optional[List[tuple]] = None) -> List[Dict[str, Any]]:
    import ray_tpu
    return ray_tpu.nodes()


def list_placement_groups(filters: Optional[List[tuple]] = None
                          ) -> List[Dict[str, Any]]:
    rt = _runtime()
    out = []
    for pg_id, bundles in rt.scheduler.placement_groups().items():
        out.append({"placement_group_id": pg_id.hex(),
                    "bundles": bundles})
    return _apply_filters(out, filters)


def summarize_tasks() -> Dict[str, Any]:
    tasks = list_tasks()
    by_state = _Counter(t["state"] for t in tasks)
    by_name = _Counter(t["name"] for t in tasks)
    return {"total": len(tasks),
            "by_state": dict(by_state),
            "by_name": dict(by_name.most_common(50))}


def summarize_objects() -> Dict[str, Any]:
    rt = _runtime()
    stats = rt.store.stats()
    if rt.store.native is not None:
        stats["native_objects"] = rt.store.native.num_objects()
        stats["native_used_bytes"] = rt.store.native.used_bytes()
    return stats


def _session_log_root() -> str:
    """The session whose logs to read: the live one when initialized,
    else the newest on disk (``session_latest``) — so `ray-tpu logs`
    works after the driver exits, without creating a fresh session."""
    import os

    from ray_tpu._private import ray_logging
    # NOT global_worker.runtime: that property auto-inits a runtime,
    # which would create (and repoint session_latest to) a fresh empty
    # session — exactly what a post-mortem `ray-tpu logs` must not do.
    sdir = None
    if global_worker._runtime is not None:
        sdir = ray_logging.current_session_dir()
    if sdir is None:
        sdir = ray_logging.latest_session_dir()
    if sdir is None:
        raise FileNotFoundError(
            "no ray_tpu session log directory found (nothing under "
            f"{ray_logging.sessions_root()})")
    return os.path.join(sdir, "logs")


def list_logs(node_id: Optional[str] = None,
              filters: Optional[List[tuple]] = None,
              limit: int = 1000) -> List[Dict[str, Any]]:
    """Enumerate the session's log files (reference: list_logs over the
    node's log dir). ``node_id`` matches the per-node directory name
    prefix ("head", or a node id hex prefix)."""
    import os
    root = _session_log_root()
    out = []
    try:
        node_dirs = sorted(os.listdir(root))
    except OSError:
        node_dirs = []
    for node_dir in node_dirs:
        label = node_dir[5:] if node_dir.startswith("node-") else node_dir
        if node_id and not label.startswith(node_id) \
                and not node_dir.startswith(node_id):
            continue
        full = os.path.join(root, node_dir)
        try:
            fnames = sorted(os.listdir(full))
        except OSError:
            continue
        for fname in fnames:
            path = os.path.join(full, fname)
            try:
                size = os.path.getsize(path)
            except OSError:
                continue
            out.append({"node": label, "filename": fname,
                        "size_bytes": size, "path": path})
    return _apply_filters(out, filters)[:limit]


def get_log(filename: Optional[str] = None,
            node_id: Optional[str] = None,
            pid: Optional[int] = None,
            tail: int = 1000) -> List[str]:
    """Read the last ``tail`` lines of matching session log files
    (reference: get_log streams a file from the agent; here the files
    are host-local). Select by exact ``filename``, ``pid`` (matches the
    per-proc naming), and/or ``node_id``; ``tail=-1`` reads whole
    files."""
    rows = list_logs(node_id=node_id, limit=10_000)
    pid_tag = str(pid) if pid is not None else None
    lines: List[str] = []
    for row in rows:
        fname = row["filename"]
        if filename and fname != filename:
            continue
        if fname.endswith(".log") and not filename:
            continue  # structured daemon logs only on explicit request
        if pid_tag and pid_tag not in \
                fname.rsplit(".", 1)[0].replace("-", ".").split("."):
            continue
        try:
            with open(row["path"], "rb") as f:
                data = f.read()
        except OSError:
            continue
        file_lines = data.decode("utf-8", "replace").splitlines()
        if tail >= 0:
            file_lines = file_lines[-tail:]
        lines.extend(file_lines)
    if tail >= 0:
        lines = lines[-tail:] if len(lines) > tail else lines
    return lines


def _apply_filters(rows: List[Dict[str, Any]],
                   filters: Optional[List[tuple]]) -> List[Dict[str, Any]]:
    if not filters:
        return rows
    for key, op, value in filters:
        if op == "=":
            rows = [r for r in rows if str(r.get(key)) == str(value)]
        elif op == "!=":
            rows = [r for r in rows if str(r.get(key)) != str(value)]
        else:
            raise ValueError(f"Unsupported filter op {op!r}")
    return rows
