"""State API: programmatic cluster introspection.

Analog of the reference's python/ray/experimental/state/api.py
(list_actors :736, list_tasks :959, list_objects :1003, list_nodes,
list_placement_groups, summarize_tasks) backed by the runtime's live state
instead of the GCS/dashboard aggregator.
"""

from __future__ import annotations

from collections import Counter as _Counter
from typing import Any, Dict, List, Optional

from ray_tpu._private.worker import global_worker


def _runtime():
    rt = global_worker.runtime
    if rt is None:
        raise RuntimeError("ray_tpu is not initialized")
    return rt


def list_actors(filters: Optional[List[tuple]] = None,
                limit: int = 1000) -> List[Dict[str, Any]]:
    rt = _runtime()
    out = []
    for actor_id, state in list(rt._actors.items()):
        row = {
            "actor_id": actor_id.hex(),
            "class_name": state.creation_spec.name.replace(".__init__", ""),
            "state": "DEAD" if state.dead else (
                "ALIVE" if state.created.is_set() else "PENDING_CREATION"),
            "name": state.name,
            "namespace": state.namespace,
            "num_restarts": state.num_restarts,
            "pending_tasks": len(state.unfinished),
        }
        out.append(row)
    return _apply_filters(out, filters)[:limit]


def list_tasks(filters: Optional[List[tuple]] = None,
               limit: int = 10_000) -> List[Dict[str, Any]]:
    rt = _runtime()
    latest: Dict[str, Dict[str, Any]] = {}
    for ev in rt.task_events():
        row = latest.setdefault(ev["task_id"], {
            "task_id": ev["task_id"], "name": ev["name"],
            "state": None, "start_time": None, "end_time": None})
        row["state"] = ev["status"]
        if ev["status"] == "RUNNING":
            row["start_time"] = ev["time"]
        elif ev["status"] in ("FINISHED", "FAILED"):
            row["end_time"] = ev["time"]
    return _apply_filters(list(latest.values()), filters)[:limit]


def list_objects(filters: Optional[List[tuple]] = None,
                 limit: int = 10_000) -> List[Dict[str, Any]]:
    rt = _runtime()
    out = []
    with rt.store._lock:
        entries = list(rt.store._entries.items())
    for oid, entry in entries:
        out.append({
            "object_id": oid.hex(),
            "sealed": entry.event.is_set(),
            "is_exception": entry.is_exception,
            "freed": entry.freed,
            "in_native_store": entry.in_native,
            "size_bytes": entry.size_bytes,
        })
    return _apply_filters(out, filters)[:limit]


def list_nodes(filters: Optional[List[tuple]] = None) -> List[Dict[str, Any]]:
    import ray_tpu
    return ray_tpu.nodes()


def list_placement_groups(filters: Optional[List[tuple]] = None
                          ) -> List[Dict[str, Any]]:
    rt = _runtime()
    out = []
    for pg_id, bundles in rt.scheduler.placement_groups().items():
        out.append({"placement_group_id": pg_id.hex(),
                    "bundles": bundles})
    return _apply_filters(out, filters)


def summarize_tasks() -> Dict[str, Any]:
    tasks = list_tasks()
    by_state = _Counter(t["state"] for t in tasks)
    by_name = _Counter(t["name"] for t in tasks)
    return {"total": len(tasks),
            "by_state": dict(by_state),
            "by_name": dict(by_name.most_common(50))}


def summarize_objects() -> Dict[str, Any]:
    rt = _runtime()
    stats = rt.store.stats()
    if rt.store.native is not None:
        stats["native_objects"] = rt.store.native.num_objects()
        stats["native_used_bytes"] = rt.store.native.used_bytes()
    return stats


def _apply_filters(rows: List[Dict[str, Any]],
                   filters: Optional[List[tuple]]) -> List[Dict[str, Any]]:
    if not filters:
        return rows
    for key, op, value in filters:
        if op == "=":
            rows = [r for r in rows if str(r.get(key)) == str(value)]
        elif op == "!=":
            rows = [r for r in rows if str(r.get(key)) != str(value)]
        else:
            raise ValueError(f"Unsupported filter op {op!r}")
    return rows
