"""Dataset creation: range/from_items/from_pandas/... and file readers.

Analog of the reference's python/ray/data/read_api.py (read_datasource at
read_api.py:237): every reader plans a set of read tasks, one per output
block, executed lazily as object-store tasks.
"""

from __future__ import annotations

import builtins
import os
from typing import Any, Callable, Dict, List, Optional, Union

import numpy as np

import ray_tpu
from ray_tpu.data.block import (TENSOR_COL, VALUE_COL, Block, BlockAccessor,
                                BlockMetadata)
from ray_tpu.data.dataset import Dataset

DEFAULT_PARALLELISM = 8


def _put_blocks(blocks: List[Block], input_files=None) -> Dataset:
    refs, metas = [], []
    for b in blocks:
        refs.append(ray_tpu.put(b))
        metas.append(BlockAccessor.for_block(b).get_metadata(input_files))
    return Dataset.from_blocks(refs, metas)


def _split_list(items: List[Any], n: int) -> List[List[Any]]:
    n = max(1, min(n, len(items))) if items else 1
    per = (len(items) + n - 1) // n if items else 0
    return [items[i * per:(i + 1) * per] for i in builtins.range(n)
            if items[i * per:(i + 1) * per]] or [[]]


def range(n: int, *, parallelism: int = DEFAULT_PARALLELISM) -> Dataset:
    """Dataset of dict rows {"id": 0..n-1} (reference: read_api.py range)."""
    import pyarrow as pa
    parallelism = max(1, min(parallelism, n or 1))
    per = (n + parallelism - 1) // parallelism
    blocks = []
    for i in builtins.range(parallelism):
        lo, hi = i * per, min((i + 1) * per, n)
        if lo >= hi and i > 0:
            continue
        blocks.append(pa.table({"id": np.arange(lo, hi, dtype=np.int64)}))
    return _put_blocks(blocks)


def range_tensor(n: int, *, shape: tuple = (1,),
                 parallelism: int = DEFAULT_PARALLELISM) -> Dataset:
    parallelism = max(1, min(parallelism, n or 1))
    per = (n + parallelism - 1) // parallelism
    blocks = []
    for i in builtins.range(parallelism):
        lo, hi = i * per, min((i + 1) * per, n)
        if lo >= hi and i > 0:
            continue
        base = np.arange(lo, hi, dtype=np.int64).reshape((-1,) + (1,) * len(shape))
        data = np.broadcast_to(base, (hi - lo,) + tuple(shape)).copy()
        from ray_tpu.data.block import _numpy_dict_to_arrow
        blocks.append(_numpy_dict_to_arrow({TENSOR_COL: data}))
    return _put_blocks(blocks)


def from_items(items: List[Any], *, parallelism: int = DEFAULT_PARALLELISM
               ) -> Dataset:
    import pyarrow as pa
    chunks = _split_list(list(items), parallelism)
    blocks = []
    for chunk in chunks:
        if chunk and isinstance(chunk[0], dict):
            blocks.append(pa.Table.from_pylist(chunk))
        else:
            blocks.append(list(chunk))
    return _put_blocks(blocks)


def from_pandas(dfs) -> Dataset:
    import pandas as pd
    if isinstance(dfs, pd.DataFrame):
        dfs = [dfs]
    return _put_blocks(list(dfs))


def from_arrow(tables) -> Dataset:
    import pyarrow as pa
    if isinstance(tables, pa.Table):
        tables = [tables]
    return _put_blocks(list(tables))


def from_numpy(arrays, column: str = TENSOR_COL) -> Dataset:
    from ray_tpu.data.block import _numpy_dict_to_arrow
    if isinstance(arrays, np.ndarray):
        arrays = [arrays]
    return _put_blocks([_numpy_dict_to_arrow({column: a}) for a in arrays])


def from_jax(arrays, column: str = TENSOR_COL) -> Dataset:
    """Device arrays → host Dataset (TPU-first addition)."""
    if not isinstance(arrays, (list, tuple)):
        arrays = [arrays]
    return from_numpy([np.asarray(a) for a in arrays], column)


# ----------------------------------------------------------------------
# File-based readers
# ----------------------------------------------------------------------

def _expand_paths(paths: Union[str, List[str]], suffix: Optional[str] = None
                  ) -> List[str]:
    if isinstance(paths, str):
        paths = [paths]
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for name in sorted(os.listdir(p)):
                full = os.path.join(p, name)
                if os.path.isfile(full) and (
                        suffix is None or name.endswith(suffix)):
                    out.append(full)
        else:
            out.append(p)
    if not out:
        raise ValueError(f"No input files found at {paths}")
    return out


def _read_files(paths: Union[str, List[str]], reader: Callable[[str], Block],
                *, parallelism: int = DEFAULT_PARALLELISM,
                suffix: Optional[str] = None) -> Dataset:
    files = _expand_paths(paths, suffix)

    def _read_group(group: List[str], _reader=reader) -> Block:
        blocks = [_reader(f) for f in group]
        return BlockAccessor.concat(blocks)

    task = ray_tpu.remote(_read_group)
    groups = _split_list(files, parallelism)
    refs = [task.remote(g) for g in groups]
    metas = []
    for ref, group in zip(refs, groups):
        block = ray_tpu.get(ref)
        metas.append(BlockAccessor.for_block(block).get_metadata(group))
    return Dataset.from_blocks(refs, metas)


def read_parquet(paths, *, parallelism: int = DEFAULT_PARALLELISM,
                 columns: Optional[List[str]] = None, **kwargs) -> Dataset:
    def reader(f):
        import pyarrow.parquet as pq
        return pq.read_table(f, columns=columns)

    return _read_files(paths, reader, parallelism=parallelism,
                       suffix=".parquet")


def read_csv(paths, *, parallelism: int = DEFAULT_PARALLELISM, **kwargs
             ) -> Dataset:
    def reader(f):
        import pyarrow.csv as pacsv
        return pacsv.read_csv(f)

    return _read_files(paths, reader, parallelism=parallelism, suffix=".csv")


def read_json(paths, *, parallelism: int = DEFAULT_PARALLELISM, **kwargs
              ) -> Dataset:
    def reader(f):
        import pandas as pd
        return pd.read_json(f, orient="records", lines=True)

    return _read_files(paths, reader, parallelism=parallelism, suffix=".json")


def read_numpy(paths, *, parallelism: int = DEFAULT_PARALLELISM,
               column: str = TENSOR_COL, **kwargs) -> Dataset:
    def reader(f, _col=column):
        from ray_tpu.data.block import _numpy_dict_to_arrow
        return _numpy_dict_to_arrow({_col: np.load(f)})

    return _read_files(paths, reader, parallelism=parallelism, suffix=".npy")


def read_text(paths, *, parallelism: int = DEFAULT_PARALLELISM,
              encoding: str = "utf-8", **kwargs) -> Dataset:
    def reader(f, _enc=encoding):
        import pyarrow as pa
        with open(f, "r", encoding=_enc) as fh:
            lines = [ln.rstrip("\n") for ln in fh]
        return pa.table({"text": lines})

    return _read_files(paths, reader, parallelism=parallelism)


def read_binary_files(paths, *, parallelism: int = DEFAULT_PARALLELISM,
                      include_paths: bool = False, **kwargs) -> Dataset:
    def reader(f, _inc=include_paths):
        import pyarrow as pa
        with open(f, "rb") as fh:
            data = fh.read()
        cols: Dict[str, Any] = {"bytes": [data]}
        if _inc:
            cols["path"] = [f]
        return pa.table(cols)

    return _read_files(paths, reader, parallelism=parallelism)


def read_images(paths, *, parallelism: int = DEFAULT_PARALLELISM,
                size: Optional[tuple] = None, mode: Optional[str] = None,
                include_paths: bool = False, **kwargs) -> Dataset:
    """Decode image files into a tensor column (reference:
    data/datasource/image_datasource.py). ``size=(h, w)`` resizes,
    ``mode`` converts (e.g. "RGB", "L"); images must share one shape
    per file-group (resize or group accordingly)."""
    def reader(f, _size=size, _mode=mode, _inc=include_paths):
        from PIL import Image

        from ray_tpu.data.block import _numpy_dict_to_arrow
        img = Image.open(f)
        if _mode:
            img = img.convert(_mode)
        if _size:
            img = img.resize((_size[1], _size[0]))
        arr = np.asarray(img)
        cols = {"image": arr[None]}
        if _inc:
            cols["path"] = np.asarray([f])
        return _numpy_dict_to_arrow(cols)

    return _read_files(
        paths, reader, parallelism=parallelism,
        suffix=(".png", ".jpg", ".jpeg", ".bmp", ".gif", ".webp"))


def read_tfrecords(paths, *, parallelism: int = DEFAULT_PARALLELISM,
                   **kwargs) -> Dataset:
    """Read TFRecord files of tf.train.Example protos WITHOUT a
    TensorFlow dependency (reference: tfrecords_datasource.py imports
    tf; ray_tpu/data/tfrecord.py speaks the wire formats directly).
    Scalar features unwrap to scalars; multi-value features stay
    lists."""
    def reader(f):
        import pyarrow as pa

        from ray_tpu.data.tfrecord import (decode_example,
                                           read_tfrecord_file)
        rows = [decode_example(rec) for rec in read_tfrecord_file(f)]
        cols: Dict[str, Any] = {}
        names: List[str] = []
        for row in rows:
            for name in row:
                if name not in cols:
                    cols[name] = []
                    names.append(name)
        for row in rows:
            for name in names:
                vals = row.get(name, [])
                cols[name].append(
                    vals[0] if len(vals) == 1 else list(vals))
        return pa.table(cols)

    return _read_files(paths, reader, parallelism=parallelism,
                       suffix=".tfrecord")


def read_datasource(datasource, *, parallelism: int = DEFAULT_PARALLELISM,
                    **read_args) -> Dataset:
    """Custom datasource entry point (reference: read_api.py:237). A
    datasource exposes ``prepare_read(parallelism, **args) -> [callable]``;
    each callable returns a Block."""
    read_tasks = datasource.prepare_read(parallelism, **read_args)
    task = ray_tpu.remote(lambda t: t())
    refs = [task.remote(t) for t in read_tasks]
    metas = [BlockAccessor.for_block(b).get_metadata()
             for b in ray_tpu.get(refs)]
    return Dataset.from_blocks(refs, metas)
