"""ray_tpu.data: distributed datasets on the object store.

TPU-first analog of the reference's python/ray/data: blocks are pyarrow
tables in the object store; transforms run as tasks/actor pools; the default
batch format is numpy dicts ready for jax.device_put, and ``iter_jax_batches``
/ ``Dataset.split`` feed per-host shards into a JaxTrainer mesh.
"""

from ray_tpu.data import aggregate
from ray_tpu.data._internal.compute import (ActorPoolStrategy,
                                            TaskPoolStrategy)
from ray_tpu.data.aggregate import (AggregateFn, Count, Max, Mean, Min, Std,
                                    Sum)
from ray_tpu.data.block import Block, BlockAccessor, BlockMetadata
from ray_tpu.data.dataset import Dataset, GroupedDataset
from ray_tpu.data.dataset_pipeline import DatasetPipeline
from ray_tpu.data.preprocessors import (BatchMapper, Chain, Concatenator,
                                        LabelEncoder, MinMaxScaler,
                                        OneHotEncoder, Preprocessor,
                                        SimpleImputer, StandardScaler)
from ray_tpu.data.read_api import (from_arrow, from_items, from_jax,
                                   from_numpy, from_pandas, range,
                                   range_tensor, read_binary_files, read_csv,
                                   read_datasource, read_images, read_json,
                                   read_numpy, read_parquet, read_text,
                                   read_tfrecords)

__all__ = [
    "ActorPoolStrategy", "AggregateFn", "BatchMapper", "Block",
    "BlockAccessor", "BlockMetadata", "Chain", "Concatenator", "Count",
    "Dataset", "DatasetPipeline", "GroupedDataset", "LabelEncoder", "Max",
    "Mean", "Min", "MinMaxScaler", "OneHotEncoder", "Preprocessor",
    "SimpleImputer", "StandardScaler", "Std", "Sum", "TaskPoolStrategy",
    "aggregate", "from_arrow", "from_items", "from_jax", "from_numpy",
    "from_pandas", "range", "range_tensor", "read_binary_files", "read_csv", "read_images", "read_tfrecords",
    "read_datasource", "read_json", "read_numpy", "read_parquet",
    "read_text",
]
