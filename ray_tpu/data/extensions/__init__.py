from ray_tpu.data.extensions.tensor_extension import (ArrowTensorArray,
                                                      ArrowTensorType)

__all__ = ["ArrowTensorArray", "ArrowTensorType"]
