"""Arrow tensor extension: fixed-shape ndarray columns in arrow blocks.

Analog of the reference's data/extensions/tensor_extension.py
(ArrowTensorType/ArrowTensorArray): an N-d numpy column is stored as a
FixedSizeList storage array over the flattened values — ZERO-COPY both
ways for contiguous numeric data — with the logical element shape kept
in extension-type metadata. Before this, rank>=2 batch columns went
through ``pa.array(v.tolist())`` (a full python materialization that
also loses dtype width) and came back via ``to_pylist``.

The type registers with arrow on import, so tensors survive IPC /
serialization round-trips between workers.
"""

from __future__ import annotations

import json
from typing import Tuple

import numpy as np
import pyarrow as pa


class ArrowTensorType(pa.ExtensionType):
    """Extension type for [*shape]-shaped tensors of a fixed value type;
    one column cell = one tensor."""

    def __init__(self, shape: Tuple[int, ...], value_type: pa.DataType):
        self._shape = tuple(int(d) for d in shape)
        size = int(np.prod(self._shape)) if self._shape else 1
        storage = pa.list_(value_type, size)
        super().__init__(storage, "ray_tpu.data.tensor")

    @property
    def shape(self) -> Tuple[int, ...]:
        return self._shape

    def __arrow_ext_serialize__(self) -> bytes:
        return json.dumps({"shape": list(self._shape)}).encode()

    @classmethod
    def __arrow_ext_deserialize__(cls, storage_type, serialized):
        shape = tuple(json.loads(serialized.decode())["shape"])
        return cls(shape, storage_type.value_type)

    def __arrow_ext_class__(self):
        return ArrowTensorArray

    def __reduce__(self):
        return (ArrowTensorType.__arrow_ext_deserialize__,
                (self.storage_type, self.__arrow_ext_serialize__()))


class ArrowTensorArray(pa.ExtensionArray):
    """Array of fixed-shape tensors over FixedSizeList storage."""

    @staticmethod
    def from_numpy(arr: np.ndarray) -> "ArrowTensorArray":
        """[N, *shape] ndarray -> tensor column; zero-copy for
        contiguous numeric input."""
        arr = np.asarray(arr)
        if arr.ndim < 2:
            raise ValueError(
                f"tensor column needs rank >= 2 ([N, *shape]); got "
                f"rank {arr.ndim}")
        n = arr.shape[0]
        shape = arr.shape[1:]
        flat = np.ascontiguousarray(arr).reshape(n, -1).reshape(-1)
        values = pa.array(flat)
        size = int(np.prod(shape)) if shape else 1
        storage = pa.FixedSizeListArray.from_arrays(values, size)
        typ = ArrowTensorType(shape, values.type)
        return pa.ExtensionArray.from_storage(typ, storage)

    def to_numpy(self, zero_copy_only: bool = True) -> np.ndarray:
        """-> [N, *shape] ndarray; zero-copy when the storage is
        null-free numeric. flatten(), not .values: a SLICED array's
        values still span the whole parent buffer — flatten respects
        the slice offset/length (and is zero-copy for offset slices of
        fixed-size lists)."""
        values = self.storage.flatten()
        np_values = values.to_numpy(zero_copy_only=zero_copy_only)
        return np_values.reshape((len(self),) + self.type.shape)

    def to_pylist(self, *args, **kwargs):
        # Lists of ndarrays (matches the reference's row view of tensor
        # cells). Signature-compatible with pa.Array.to_pylist (arrow
        # passes maps_as_pydicts through Table.to_pylist).
        return list(self.to_numpy(zero_copy_only=False))


def _register() -> None:
    try:
        pa.register_extension_type(
            ArrowTensorType((0,), pa.float64()))
    except pa.ArrowKeyError:  # pragma: no cover - already registered
        pass


_register()
