"""DatasetPipeline: windowed / repeated streaming over a Dataset.

Analog of the reference's python/ray/data/dataset_pipeline.py: a pipeline is
a sequence of Dataset *windows* executed lazily, so transforms on a window
overlap with consumption of the previous one; ``repeat`` provides per-epoch
iteration for training ingest.
"""

from __future__ import annotations

from typing import Callable, Iterator, List, Optional

import ray_tpu
from ray_tpu.data.dataset import Dataset


class DatasetPipeline:
    def __init__(self, window_factories: List[Callable[[], Dataset]],
                 length: Optional[int] = None):
        self._factories = window_factories
        self._length = length if length is not None else len(window_factories)
        self._stages: List[Callable[[Dataset], Dataset]] = []

    @staticmethod
    def from_dataset(ds: Dataset, blocks_per_window: int) -> "DatasetPipeline":
        blocks, metas = ds._execute()
        factories = []
        for i in range(0, len(blocks), blocks_per_window):
            b = blocks[i:i + blocks_per_window]
            m = metas[i:i + blocks_per_window]
            factories.append(lambda b=b, m=m: Dataset.from_blocks(b, m))
        return DatasetPipeline(factories)

    @staticmethod
    def from_dataset_repeated(ds: Dataset, times: Optional[int]
                              ) -> "DatasetPipeline":
        n = times if times is not None else 1_000_000_000
        blocks, metas = ds._execute()
        factories = [lambda e=e: Dataset.from_blocks(blocks, metas)
                     for e in range(min(n, 10**6))]
        return DatasetPipeline(factories, length=n)

    def _wrap(self, stage: Callable[[Dataset], Dataset]) -> "DatasetPipeline":
        p = DatasetPipeline(self._factories, self._length)
        p._stages = self._stages + [stage]
        return p

    def map_batches(self, fn, **kwargs) -> "DatasetPipeline":
        return self._wrap(lambda ds: ds.map_batches(fn, **kwargs))

    def map(self, fn, **kwargs) -> "DatasetPipeline":
        return self._wrap(lambda ds: ds.map(fn, **kwargs))

    def filter(self, fn, **kwargs) -> "DatasetPipeline":
        return self._wrap(lambda ds: ds.filter(fn, **kwargs))

    def random_shuffle_each_window(self, **kwargs) -> "DatasetPipeline":
        return self._wrap(lambda ds: ds.random_shuffle(**kwargs))

    def iter_datasets(self) -> Iterator[Dataset]:
        for factory in self._factories:
            ds = factory()
            for stage in self._stages:
                ds = stage(ds)
            yield ds

    def iter_epochs(self) -> Iterator[Dataset]:
        return self.iter_datasets()

    def iter_rows(self):
        for ds in self.iter_datasets():
            yield from ds.iter_rows()

    def iter_batches(self, **kwargs):
        for ds in self.iter_datasets():
            yield from ds.iter_batches(**kwargs)

    def take(self, n: int = 20):
        out = []
        for row in self.iter_rows():
            out.append(row)
            if len(out) >= n:
                break
        return out

    def count(self) -> int:
        return sum(ds.count() for ds in self.iter_datasets())
