"""Aggregation functions for Dataset.groupby / global aggregates.

Analog of the reference's python/ray/data/aggregate.py: AggregateFn with
init/accumulate/merge/finalize, plus the standard Count/Sum/Min/Max/Mean/Std.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import numpy as np


class AggregateFn:
    def __init__(self, init: Callable[[Any], Any],
                 accumulate_block: Callable[[Any, Any], Any],
                 merge: Callable[[Any, Any], Any],
                 finalize: Callable[[Any], Any] = lambda a: a,
                 name: str = "agg"):
        self.init = init
        self.accumulate_block = accumulate_block
        self.merge = merge
        self.finalize = finalize
        self.name = name


def _col(batch, on):
    if on is None:
        # first column
        key = next(iter(batch))
        return batch[key]
    return batch[on]


class Count(AggregateFn):
    def __init__(self):
        super().__init__(
            init=lambda k: 0,
            accumulate_block=lambda a, batch: a + len(_col(batch, None)),
            merge=lambda a, b: a + b,
            name="count()")


class Sum(AggregateFn):
    def __init__(self, on: Optional[str] = None):
        super().__init__(
            init=lambda k: 0,
            accumulate_block=lambda a, batch: a + float(np.sum(_col(batch, on))),
            merge=lambda a, b: a + b,
            name=f"sum({on or ''})")


class Min(AggregateFn):
    def __init__(self, on: Optional[str] = None):
        super().__init__(
            init=lambda k: None,
            accumulate_block=lambda a, batch: (
                float(np.min(_col(batch, on))) if a is None
                else min(a, float(np.min(_col(batch, on))))),
            merge=lambda a, b: b if a is None else (a if b is None else min(a, b)),
            name=f"min({on or ''})")


class Max(AggregateFn):
    def __init__(self, on: Optional[str] = None):
        super().__init__(
            init=lambda k: None,
            accumulate_block=lambda a, batch: (
                float(np.max(_col(batch, on))) if a is None
                else max(a, float(np.max(_col(batch, on))))),
            merge=lambda a, b: b if a is None else (a if b is None else max(a, b)),
            name=f"max({on or ''})")


class Mean(AggregateFn):
    def __init__(self, on: Optional[str] = None):
        super().__init__(
            init=lambda k: [0.0, 0],
            accumulate_block=lambda a, batch: [
                a[0] + float(np.sum(_col(batch, on))),
                a[1] + len(_col(batch, on))],
            merge=lambda a, b: [a[0] + b[0], a[1] + b[1]],
            finalize=lambda a: a[0] / a[1] if a[1] else None,
            name=f"mean({on or ''})")


class Std(AggregateFn):
    """Streaming variance via sum / sum-of-squares / count."""

    def __init__(self, on: Optional[str] = None, ddof: int = 1):
        def finalize(a):
            s, ss, n = a
            if n <= ddof:
                return None
            var = (ss - s * s / n) / (n - ddof)
            return float(np.sqrt(max(var, 0.0)))

        super().__init__(
            init=lambda k: [0.0, 0.0, 0],
            accumulate_block=lambda a, batch: [
                a[0] + float(np.sum(_col(batch, on))),
                a[1] + float(np.sum(np.square(np.asarray(_col(batch, on),
                                                         dtype=float)))),
                a[2] + len(_col(batch, on))],
            merge=lambda a, b: [a[0] + b[0], a[1] + b[1], a[2] + b[2]],
            finalize=finalize,
            name=f"std({on or ''})")
