"""Push-based two-stage shuffle.

Analog of the reference's data/_internal/push_based_shuffle.py (Exoshuffle)
and shuffle.py: a *map* stage partitions every input block into
``num_output`` sub-blocks (random, hash, or range partitioning), a *reduce*
stage concatenates sub-block j from every map task into output block j.
All movement is object-store refs; reduce tasks start as soon as their
inputs exist (task-level pipelining — the push-based property).
"""

from __future__ import annotations

import random
from typing import Any, Callable, List, Optional, Tuple

import numpy as np

import ray_tpu
from ray_tpu.data.block import Block, BlockAccessor, BlockMetadata


def _partition_block(block: Block, num_out: int, mode: str,
                     key, seed, boundaries) -> List[Block]:
    acc = BlockAccessor.for_block(block)
    n = acc.num_rows()
    if n == 0:
        return [acc.slice(0, 0) for _ in range(num_out)]
    if mode == "random":
        rng = np.random.default_rng(seed)
        assignment = rng.integers(0, num_out, n)
    elif mode == "hash":
        vals = acc.column_values(key)
        assignment = np.array([hash(v) % num_out for v in vals])
    elif mode == "range":
        vals = acc.column_values(key)
        assignment = np.searchsorted(boundaries, vals, side="right")
    elif mode == "split":
        # Contiguous equal split (repartition without shuffling rows).
        assignment = (np.arange(n) * num_out) // n
    else:
        raise ValueError(mode)
    parts = []
    for j in range(num_out):
        idx = np.nonzero(assignment == j)[0]
        parts.append(acc.take(idx.tolist()))
    return parts


def _reduce_blocks(*parts: Block) -> Tuple[Block, BlockMetadata]:
    out = BlockAccessor.concat(list(parts))
    return out, BlockAccessor.for_block(out).get_metadata()


_part_task_cache = {}


def _partition_task(num_out: int):
    # SPREAD (reference: data map tasks use the SPREAD strategy): shuffle
    # stages must fan across nodes — default hybrid-pack would pile every
    # map on one daemon and no data would ever ride the inter-node plane.
    key_ = num_out
    if key_ not in _part_task_cache:
        _part_task_cache[key_] = ray_tpu.remote(_partition_block).options(
            num_returns=num_out, scheduling_strategy="SPREAD")
    return _part_task_cache[key_]


_reduce_task = None


def _get_reduce_task():
    global _reduce_task
    if _reduce_task is None:
        _reduce_task = ray_tpu.remote(_reduce_blocks).options(
            num_returns=2, scheduling_strategy="SPREAD")
    return _reduce_task


def shuffle_blocks(
    blocks: List[Any],
    num_output: Optional[int] = None,
    mode: str = "random",
    key=None,
    seed: Optional[int] = None,
    boundaries=None,
) -> Tuple[List[Any], List[BlockMetadata]]:
    """Run the 2-stage shuffle; returns (block_refs, metadata)."""
    if not blocks:
        return [], []
    num_output = num_output or len(blocks)
    part_task = _partition_task(num_output)
    base_seed = seed if seed is not None else random.randrange(2**31)
    # Map stage: each input block → num_output partition refs.
    partials: List[List[Any]] = []
    for i, b in enumerate(blocks):
        refs = part_task.remote(b, num_output, mode, key, base_seed + i,
                                boundaries)
        if num_output == 1:
            refs = [refs]
        partials.append(refs)
    # Reduce stage: column j across all map outputs → output block j.
    reduce_task = _get_reduce_task()
    out_blocks, meta_refs = [], []
    for j in range(num_output):
        b_ref, m_ref = reduce_task.remote(*[p[j] for p in partials])
        out_blocks.append(b_ref)
        meta_refs.append(m_ref)
    return out_blocks, ray_tpu.get(meta_refs)


def sort_blocks(blocks: List[Any], key=None, descending: bool = False
                ) -> Tuple[List[Any], List[BlockMetadata]]:
    """Distributed sort: sample boundaries, range-partition, sort partitions.

    Reference: data/_internal/sort.py (sample → range partition → merge).
    """
    if not blocks:
        return [], []
    num_out = len(blocks)

    def _sample(block, key=key):
        acc = BlockAccessor.for_block(block)
        return acc.sample_keys(10, key)

    sample_task = ray_tpu.remote(_sample)
    samples = [s for ref in [sample_task.remote(b) for b in blocks]
               for s in ray_tpu.get(ref)]
    if not samples:
        return blocks, [BlockAccessor.for_block(ray_tpu.get(b)).get_metadata()
                        for b in blocks]
    samples.sort(reverse=False)
    q = np.linspace(0, len(samples) - 1, num_out + 1)[1:-1].astype(int)
    boundaries = [samples[i] for i in q]

    shuffled, _ = shuffle_blocks(blocks, num_out, mode="range", key=key,
                                 boundaries=np.array(boundaries))

    def _sort_local(block, key=key, descending=descending):
        return BlockAccessor.for_block(block).sort_by(key, descending)

    sort_task = ray_tpu.remote(_sort_local)
    sorted_refs = [sort_task.remote(b) for b in shuffled]
    if descending:
        sorted_refs = sorted_refs[::-1]
    metas = [BlockAccessor.for_block(b).get_metadata()
             for b in ray_tpu.get(sorted_refs)]
    return sorted_refs, metas
