"""StreamingExecutor: pipelined execution of an operator chain.

Analog of the reference's data/_internal/execution/streaming_executor.py:23
— the event loop that moves RefBundles through the operator topology.
Unlike the bulk path (stage N completes before stage N+1 starts), every
operator runs concurrently: a block can be in stage 3 while later blocks
are still being read, so the first output batch is available after one
block traverses the chain, and peak memory is bounded by the operators'
in-flight caps rather than the dataset size.
"""

from __future__ import annotations

import time
from typing import Iterator, List

from ray_tpu.data._internal.execution.interfaces import (ExecutionOptions,
                                                         PhysicalOperator,
                                                         RefBundle)


class StreamingExecutor:
    def __init__(self, options: ExecutionOptions = None):
        self.options = options or ExecutionOptions()

    def execute(self, operators: List[PhysicalOperator]
                ) -> Iterator[RefBundle]:
        """Run the chain (operators[0] is the input buffer) and yield the
        final operator's bundles as they complete."""
        if not operators:
            return
        try:
            done_flags = [False] * len(operators)
            while True:
                progressed = False
                # Move bundles downstream (upstream-first so a bundle can
                # traverse several operators in one pass).
                for i, op in enumerate(operators):
                    if i > 0:
                        op.work()
                    is_last = i == len(operators) - 1
                    if is_last:
                        continue
                    downstream = operators[i + 1]
                    while op.has_next():
                        downstream.add_input(op.get_next())
                        progressed = True
                    if op.completed() and not done_flags[i]:
                        done_flags[i] = True
                        downstream.all_inputs_done()
                    downstream.work()
                last = operators[-1]
                while last.has_next():
                    progressed = True
                    yield last.get_next()
                if last.completed():
                    return
                if not progressed:
                    # Everything in flight — avoid a busy spin.
                    time.sleep(0.002)
        finally:
            for op in operators:
                op.shutdown()
