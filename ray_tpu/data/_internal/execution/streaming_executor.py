"""StreamingExecutor: pipelined execution of an operator chain.

Analog of the reference's data/_internal/execution/streaming_executor.py:23
— the event loop that moves RefBundles through the operator topology.
Unlike the bulk path (stage N completes before stage N+1 starts), every
operator runs concurrently: a block can be in stage 3 while later blocks
are still being read, so the first output batch is available after one
block traverses the chain, and peak memory is bounded by the operators'
in-flight caps rather than the dataset size.
"""

from __future__ import annotations

import time
from typing import Iterator, List

from ray_tpu.data._internal.execution.interfaces import (ExecutionOptions,
                                                         PhysicalOperator,
                                                         RefBundle)


class StreamingExecutor:
    def __init__(self, options: ExecutionOptions = None):
        self.options = options or ExecutionOptions()

    def execute(self, operators: List[PhysicalOperator]
                ) -> Iterator[RefBundle]:
        """Run the chain (operators[0] is the input buffer) and yield the
        final operator's bundles as they complete."""
        if not operators:
            return
        import ray_tpu
        budget = self.options.max_in_flight_bytes
        try:
            done_flags = [False] * len(operators)
            while True:
                progressed = False
                # Resource-aware backpressure: operator i may hold
                # (in-flight + output) bytes up to the topology budget
                # minus what everything DOWNSTREAM of it already holds —
                # the sink gets budget first (so it keeps draining, no
                # deadlock) and upstream launches throttle as the chain
                # backs up (reference: per-operator resource accounting
                # in the streaming executor, interfaces.py:158
                # ExecutionResources).
                budgets = [float("inf")] * len(operators)
                suffix = 0
                for i in range(len(operators) - 1, -1, -1):
                    budgets[i] = budget - suffix
                    suffix += operators[i].buffered_bytes()
                # Move bundles downstream (upstream-first so a bundle can
                # traverse several operators in one pass).
                for i, op in enumerate(operators):
                    if i > 0:
                        op.work(byte_budget=budgets[i])
                    is_last = i == len(operators) - 1
                    if is_last:
                        continue
                    downstream = operators[i + 1]
                    # Transfer is throttled by the downstream budget too:
                    # bundles wait in the producer (where they are already
                    # counted) instead of inflating downstream queues. An
                    # empty downstream always accepts one bundle, so even
                    # a block bigger than the whole budget progresses.
                    while op.has_next() and (
                            downstream.buffered_bytes() < budgets[i + 1]
                            or downstream.buffered_bytes() == 0):
                        downstream.add_input(op.get_next())
                        progressed = True
                    if op.completed() and not done_flags[i]:
                        done_flags[i] = True
                        downstream.all_inputs_done()
                    downstream.work(byte_budget=budgets[i + 1])
                last = operators[-1]
                while last.has_next():
                    progressed = True
                    yield last.get_next()
                if last.completed():
                    return
                if not progressed:
                    # Block on in-flight work becoming ready instead of
                    # sleep-polling (the reference's event-driven loop);
                    # the short timeout covers non-ref progress sources
                    # (actor autoscaling, barrier stages).
                    refs = [r for op in operators
                            for r in op.active_refs()]
                    if refs:
                        ray_tpu.wait(refs, num_returns=1, timeout=0.2)
                    else:
                        time.sleep(0.002)
        finally:
            for op in operators:
                op.shutdown()
