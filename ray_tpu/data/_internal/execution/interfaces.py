"""Streaming-execution interfaces.

Analog of the reference's data/_internal/execution/interfaces.py
(PhysicalOperator :158, RefBundle): datasets execute as a chain of
physical operators that exchange bundles of block references. Operators
pull inputs as upstream produces them and bound their own in-flight work,
so blocks flow through the whole chain without materializing any
intermediate dataset — the memory high-water mark is O(in-flight blocks),
not O(dataset).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, List, Optional, Tuple


@dataclass
class RefBundle:
    """A group of (block_ref, metadata) pairs moving between operators.
    Metadata may itself still be a ref while the block is in flight."""

    blocks: List[Tuple[Any, Any]]  # [(block_ref, meta_or_meta_ref)]

    def block_refs(self) -> List[Any]:
        return [b for b, _ in self.blocks]


@dataclass
class ExecutionOptions:
    """Resource bounds for a streaming run (the analog of the reference's
    ExecutionResources limits on the StreamingExecutor)."""

    max_in_flight_per_operator: int = 8
    # Topology-wide cap on bytes buffered in flight (launched inputs +
    # unconsumed outputs). When exceeded, upstream operators stop
    # LAUNCHING (downstream keeps draining), so peak memory is bounded
    # by this budget instead of the dataset size (reference: operator
    # resource accounting in streaming_executor.py:23 /
    # ExecutionResources).
    max_in_flight_bytes: int = 512 * 1024 * 1024


class PhysicalOperator:
    """One stage of a streaming dataset topology.

    Lifecycle: ``add_input`` is called as upstream bundles arrive, then
    ``all_inputs_done`` exactly once; the executor polls ``work`` to let
    the operator launch/collect tasks, drains ``get_next`` while
    ``has_next``, and considers the operator finished when ``completed``.
    """

    def __init__(self, name: str):
        self.name = name

    def add_input(self, bundle: RefBundle) -> None:
        raise NotImplementedError

    def all_inputs_done(self) -> None:
        self._inputs_done = True

    def work(self, byte_budget: float = float("inf")) -> None:
        """Launch new tasks / collect finished ones (non-blocking).
        ``byte_budget`` is how many in-flight + output bytes this
        operator may hold before it must stop LAUNCHING (collection
        always proceeds); the executor derives it from
        ``ExecutionOptions.max_in_flight_bytes`` minus what downstream
        operators are already holding."""

    def active_refs(self) -> List[Any]:
        """Refs the executor may block on instead of sleep-polling: one
        becoming ready means ``work`` can make progress."""
        return []

    def buffered_bytes(self) -> int:
        """Bytes this operator holds in flight: launched-but-unfinished
        inputs plus produced-but-unconsumed outputs. Drives topology
        backpressure."""
        return 0

    def has_next(self) -> bool:
        raise NotImplementedError

    def get_next(self) -> RefBundle:
        raise NotImplementedError

    def completed(self) -> bool:
        raise NotImplementedError

    def num_active_tasks(self) -> int:
        return 0

    def shutdown(self) -> None:
        """Release operator resources (actor pools etc.)."""
