from ray_tpu.data._internal.execution.interfaces import (ExecutionOptions,
                                                         PhysicalOperator,
                                                         RefBundle)
from ray_tpu.data._internal.execution.operators import (AllToAllOperator,
                                                        InputDataBuffer,
                                                        MapOperator)
from ray_tpu.data._internal.execution.streaming_executor import (
    StreamingExecutor)

__all__ = ["AllToAllOperator", "ExecutionOptions", "InputDataBuffer",
           "MapOperator", "PhysicalOperator", "RefBundle",
           "StreamingExecutor"]
