"""Physical operators for the streaming executor.

Analogs of the reference's data/_internal/execution/operators/: the input
buffer, the task/actor map operator (with bounded in-flight work and
in-order output), and the all-to-all barrier operator wrapping shuffle-like
stage functions.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.data._internal.execution.interfaces import (PhysicalOperator,
                                                         RefBundle)


class InputDataBuffer(PhysicalOperator):
    """Feeds the topology from a materialized list of input blocks."""

    def __init__(self, blocks: List[Any], metadata: List[Any]):
        super().__init__("Input")
        self._bundles = [RefBundle([(b, m)])
                         for b, m in zip(blocks, metadata)]
        self._i = 0

    def add_input(self, bundle: RefBundle) -> None:
        raise RuntimeError("InputDataBuffer has no upstream")

    def has_next(self) -> bool:
        return self._i < len(self._bundles)

    def get_next(self) -> RefBundle:
        out = self._bundles[self._i]
        self._i += 1
        return out

    def completed(self) -> bool:
        return self._i >= len(self._bundles)


class MapOperator(PhysicalOperator):
    """Applies a block transform as remote tasks (or an actor pool),
    bounded in-flight, emitting outputs in input order (datasets are
    ordered)."""

    def __init__(self, name: str, transform: Callable,
                 compute=None, num_cpus: float = 1.0,
                 udf_constructor=None, max_in_flight: int = 8):
        super().__init__(name)
        import cloudpickle

        from ray_tpu.data._internal.compute import (ActorPoolStrategy,
                                                    _BlockTransformActor,
                                                    _get_transform_task)
        self._fn_bytes = cloudpickle.dumps(transform)
        self._inputs_done = False
        self._queue: List[RefBundle] = []       # not yet launched
        self._in_flight: List[tuple] = []       # ordered (out_ref, meta_ref)
        self._outputs: List[RefBundle] = []
        self._in_flight_bytes = 0               # launched input payloads
        self._out_bytes = 0                     # unconsumed output payloads
        self._queue_bytes = 0                   # queued (unlaunched) inputs
        self._launch_bytes: Dict[int, int] = {}  # id(refs) -> input bytes
        self._pool = None
        self._per_actor: Dict[int, int] = {}
        self._actor_cap = 0
        self._actor_of: Dict[int, int] = {}     # id(refs) -> actor idx
        if isinstance(compute, ActorPoolStrategy):
            self._ctor_bytes = (cloudpickle.dumps(udf_constructor)
                                if udf_constructor is not None else None)
            self._actor_cls = ray_tpu.remote(_BlockTransformActor)
            self._num_cpus = num_cpus
            self._pool = []
            self._pool_max = compute.max_size or max(compute.min_size, 1)
            self._actor_cap = compute.max_tasks_in_flight_per_actor
            for _ in range(max(compute.min_size, 1)):
                self._spawn_actor()
            self._max_in_flight = self._pool_max * self._actor_cap
        else:
            self._task = _get_transform_task(num_cpus)
            # TaskPoolStrategy.size is a user-set concurrency bound.
            self._max_in_flight = getattr(compute, "size", None) or \
                max_in_flight

    def _spawn_actor(self) -> None:
        idx = len(self._pool)
        self._pool.append(self._actor_cls.options(
            num_cpus=self._num_cpus).remote(self._ctor_bytes))
        self._per_actor[idx] = 0

    def add_input(self, bundle: RefBundle) -> None:
        # Normalize to one block per queue entry — upstream bundles may
        # group several blocks (RefBundle's contract), and every block must
        # be launched.
        for block_ref, meta in bundle.blocks:
            self._queue.append(RefBundle([(block_ref, meta)]))
            self._queue_bytes += self._meta_bytes(meta)

    @staticmethod
    def _meta_bytes(meta) -> int:
        size = getattr(meta, "size_bytes", None)
        return int(size) if size else 0

    def work(self, byte_budget: float = float("inf")) -> None:
        # Launch while count capacity remains AND the byte budget allows
        # more in-flight/output payload. The first launch is always
        # permitted when nothing is in flight (a single block larger than
        # the whole budget must still make progress).
        while self._queue and len(self._in_flight) < self._max_in_flight:
            if self._in_flight and \
                    self._in_flight_bytes + self._out_bytes >= byte_budget:
                break
            bundle = self._queue[0]
            block_ref = bundle.blocks[0][0]
            if self._pool is not None:
                target = min(self._per_actor, key=self._per_actor.get)
                if self._per_actor[target] >= self._actor_cap:
                    # Autoscale the pool toward max_size under backlog
                    # (ActorPoolStrategy semantics: min..max actors).
                    if len(self._pool) < self._pool_max:
                        self._spawn_actor()
                        target = len(self._pool) - 1
                    else:
                        break
                refs = self._pool[target].apply.options(
                    num_returns=2).remote(block_ref, self._fn_bytes)
                self._per_actor[target] += 1
                self._actor_of[id(refs)] = target
            else:
                refs = self._task.remote(block_ref, self._fn_bytes, False)
            self._queue.pop(0)
            in_bytes = self._meta_bytes(bundle.blocks[0][1])
            self._queue_bytes -= in_bytes
            self._in_flight_bytes += in_bytes
            self._launch_bytes[id(refs)] = in_bytes
            self._in_flight.append(refs)
        # Collect from the head (in-order): anything ready moves to outputs.
        while self._in_flight:
            head = self._in_flight[0]
            ready, _ = ray_tpu.wait([head[1]], num_returns=1, timeout=0)
            if not ready:
                break
            self._in_flight.pop(0)
            self._in_flight_bytes -= self._launch_bytes.pop(id(head), 0)
            if self._pool is not None:
                target = self._actor_of.pop(id(head), None)
                if target is not None:
                    self._per_actor[target] -= 1
            # Resolve the (ready) metadata here: downstream operators and
            # the executor's byte accounting get concrete sizes for free.
            meta = ray_tpu.get(head[1])
            self._out_bytes += self._meta_bytes(meta)
            self._outputs.append(RefBundle([(head[0], meta)]))

    def active_refs(self) -> List[Any]:
        return [refs[1] for refs in self._in_flight]

    def buffered_bytes(self) -> int:
        return self._queue_bytes + self._in_flight_bytes + self._out_bytes

    def has_next(self) -> bool:
        return bool(self._outputs)

    def get_next(self) -> RefBundle:
        out = self._outputs.pop(0)
        self._out_bytes -= self._meta_bytes(out.blocks[0][1])
        return out

    def completed(self) -> bool:
        return (self._inputs_done and not self._queue
                and not self._in_flight and not self._outputs)

    def num_active_tasks(self) -> int:
        return len(self._in_flight)

    def shutdown(self) -> None:
        if self._pool is not None:
            for a in self._pool:
                ray_tpu.kill(a)
            self._pool = None


class AllToAllOperator(PhysicalOperator):
    """Barrier operator for shuffle-like stages: buffers every input
    bundle, then runs the stage function over the whole block list (the
    reference's AllToAllOperator wrapping e.g. push-based shuffle)."""

    def __init__(self, name: str,
                 fn: Callable[[List[Any], List[Any]], tuple]):
        super().__init__(name)
        self._fn = fn
        self._in_blocks: List[Any] = []
        self._in_metas: List[Any] = []
        self._inputs_done = False
        self._ran = False
        self._outputs: List[RefBundle] = []

    def add_input(self, bundle: RefBundle) -> None:
        for block_ref, meta in bundle.blocks:
            self._in_blocks.append(block_ref)
            self._in_metas.append(meta)

    def work(self, byte_budget: float = float("inf")) -> None:
        # A barrier stage is exempt from launch throttling: it runs once
        # over the full input set and blocks the chain until done.
        if self._inputs_done and not self._ran:
            self._ran = True
            metas = [ray_tpu.get(m) if isinstance(m, ray_tpu.ObjectRef)
                     else m for m in self._in_metas]
            blocks, out_metas = self._fn(self._in_blocks, metas)
            self._outputs = [RefBundle([(b, m)])
                             for b, m in zip(blocks, out_metas)]

    def has_next(self) -> bool:
        return bool(self._outputs)

    def get_next(self) -> RefBundle:
        return self._outputs.pop(0)

    def completed(self) -> bool:
        return self._inputs_done and self._ran and not self._outputs
