"""Compute strategies: tasks vs. autoscaling actor pools.

Analog of the reference's python/ray/data/_internal/compute.py
(TaskPoolStrategy / ActorPoolStrategy): a one-to-one stage maps a block
transform over every block either as independent tasks (default) or on a
pool of long-lived actors (amortizing expensive UDF construction, e.g. a
model loaded onto a TPU chip for batch inference).

Both paths stream: at most ``max_in_flight`` block transforms are
outstanding, and results are yielded as they finish (the round-1 analog of
the reference's streaming executor backpressure,
data/_internal/execution/streaming_executor.py).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

import ray_tpu
from ray_tpu.data.block import Block, BlockAccessor, BlockMetadata


@dataclass
class TaskPoolStrategy:
    size: Optional[int] = None  # max concurrent tasks (None = unbounded-ish)


class ActorPoolStrategy:
    """Autoscaling pool of UDF actors (reference: compute.py ActorPoolStrategy:
    min_size..max_size actors, each processing blocks serially)."""

    def __init__(self, min_size: int = 1, max_size: Optional[int] = None,
                 max_tasks_in_flight_per_actor: int = 2):
        if max_size is None:
            max_size = max(min_size, 2)
        if min_size < 1 or max_size < min_size:
            raise ValueError("Need 1 <= min_size <= max_size")
        self.min_size = min_size
        self.max_size = max_size
        self.max_tasks_in_flight_per_actor = max_tasks_in_flight_per_actor


ComputeStrategy = Any  # TaskPoolStrategy | ActorPoolStrategy | str


def resolve_compute(compute) -> ComputeStrategy:
    if compute is None or compute == "tasks":
        return TaskPoolStrategy()
    if compute == "actors":
        return ActorPoolStrategy()
    if isinstance(compute, (TaskPoolStrategy, ActorPoolStrategy)):
        return compute
    raise ValueError(f"Unknown compute strategy: {compute!r}")


def _apply_transform(block: Block, fn_bytes: bytes,
                     meta_only: bool) -> Tuple[Block, BlockMetadata]:
    """Worker-side: run a pickled block transform."""
    import cloudpickle
    fn = cloudpickle.loads(fn_bytes)
    out = fn(block)
    acc = BlockAccessor.for_block(out)
    return out, acc.get_metadata()


_transform_task = None


def _get_transform_task(num_cpus: float):
    global _transform_task
    if _transform_task is None:
        _transform_task = ray_tpu.remote(_apply_transform)
    return _transform_task.options(num_cpus=num_cpus, num_returns=2)


class _BlockTransformActor:
    """Actor wrapper executing a (possibly stateful) block transform.

    For callable-class UDFs the class is constructed once here and reused
    for every block (reference: data/_internal/compute.py BlockWorker).
    """

    def __init__(self, fn_constructor_bytes: Optional[bytes]):
        import cloudpickle
        self._udf_instance = None
        if fn_constructor_bytes is not None:
            ctor, args, kwargs = cloudpickle.loads(fn_constructor_bytes)
            self._udf_instance = ctor(*args, **kwargs)

    def ready(self):
        return True

    def apply(self, block: Block, fn_bytes: bytes):
        import cloudpickle
        fn = cloudpickle.loads(fn_bytes)
        if self._udf_instance is not None:
            out = fn(block, self._udf_instance)
        else:
            out = fn(block)
        acc = BlockAccessor.for_block(out)
        return out, acc.get_metadata()


def map_blocks_streaming(
    blocks: List["ray_tpu.ObjectRef"],
    transform: Callable[[Block], Block],
    compute: ComputeStrategy,
    num_cpus: float = 1.0,
    udf_constructor: Optional[tuple] = None,
) -> Iterator[Tuple["ray_tpu.ObjectRef", "ray_tpu.ObjectRef"]]:
    """Yield (block_ref, meta_ref) pairs in input order, streaming with
    bounded in-flight work."""
    import cloudpickle
    fn_bytes = cloudpickle.dumps(transform)

    if isinstance(compute, ActorPoolStrategy):
        yield from _map_blocks_actor_pool(
            blocks, fn_bytes, compute, num_cpus, udf_constructor)
        return

    max_in_flight = compute.size or max(8, len(blocks))
    task = _get_transform_task(num_cpus)
    in_flight: List[tuple] = []  # (block_out_ref, meta_ref)
    i = 0
    results: List[tuple] = []
    while i < len(blocks) or in_flight:
        while i < len(blocks) and len(in_flight) < max_in_flight:
            refs = task.remote(blocks[i], fn_bytes, False)
            in_flight.append(refs)
            i += 1
        # Pop the head in order (order matters for datasets); wait on it.
        head = in_flight.pop(0)
        ray_tpu.wait([head[1]], num_returns=1)
        yield head


def _map_blocks_actor_pool(blocks, fn_bytes, strategy: ActorPoolStrategy,
                           num_cpus, udf_constructor):
    import cloudpickle
    ctor_bytes = (cloudpickle.dumps(udf_constructor)
                  if udf_constructor is not None else None)
    ActorCls = ray_tpu.remote(_BlockTransformActor)
    n_actors = min(strategy.max_size, max(strategy.min_size, len(blocks)))
    pool = [ActorCls.options(num_cpus=num_cpus).remote(ctor_bytes)
            for _ in range(n_actors)]
    # Round-robin with per-actor in-flight cap; yield in input order.
    pending: List[tuple] = []  # (out_refs,) ordered
    per_actor: Dict[int, int] = {i: 0 for i in range(n_actors)}
    cap = strategy.max_tasks_in_flight_per_actor
    i = 0
    queue: List[tuple] = []
    while i < len(blocks) or queue:
        # Fill: assign next block to the least-loaded actor with room.
        while i < len(blocks):
            target = min(per_actor, key=per_actor.get)
            if per_actor[target] >= cap:
                break
            refs = pool[target].apply.options(num_returns=2).remote(
                blocks[i], fn_bytes)
            queue.append((refs, target))
            per_actor[target] += 1
            i += 1
        refs, target = queue.pop(0)
        ray_tpu.wait([refs[1]], num_returns=1)
        per_actor[target] -= 1
        yield refs
    for a in pool:
        ray_tpu.kill(a)
