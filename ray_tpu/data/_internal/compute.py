"""Compute strategies: tasks vs. autoscaling actor pools.

Analog of the reference's python/ray/data/_internal/compute.py
(TaskPoolStrategy / ActorPoolStrategy): a one-to-one stage maps a block
transform over every block either as independent tasks (default) or on a
pool of long-lived actors (amortizing expensive UDF construction, e.g. a
model loaded onto a TPU chip for batch inference).

Both paths stream: at most ``max_in_flight`` block transforms are
outstanding, and results are yielded as they finish (the round-1 analog of
the reference's streaming executor backpressure,
data/_internal/execution/streaming_executor.py).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

import ray_tpu
from ray_tpu.data.block import Block, BlockAccessor, BlockMetadata


@dataclass
class TaskPoolStrategy:
    size: Optional[int] = None  # max concurrent tasks (None = unbounded-ish)


class ActorPoolStrategy:
    """Autoscaling pool of UDF actors (reference: compute.py ActorPoolStrategy:
    min_size..max_size actors, each processing blocks serially)."""

    def __init__(self, min_size: int = 1, max_size: Optional[int] = None,
                 max_tasks_in_flight_per_actor: int = 2):
        if max_size is None:
            max_size = max(min_size, 2)
        if min_size < 1 or max_size < min_size:
            raise ValueError("Need 1 <= min_size <= max_size")
        self.min_size = min_size
        self.max_size = max_size
        self.max_tasks_in_flight_per_actor = max_tasks_in_flight_per_actor


ComputeStrategy = Any  # TaskPoolStrategy | ActorPoolStrategy | str


def resolve_compute(compute) -> ComputeStrategy:
    if compute is None or compute == "tasks":
        return TaskPoolStrategy()
    if compute == "actors":
        return ActorPoolStrategy()
    if isinstance(compute, (TaskPoolStrategy, ActorPoolStrategy)):
        return compute
    raise ValueError(f"Unknown compute strategy: {compute!r}")


def _apply_transform(block: Block, fn_bytes: bytes,
                     meta_only: bool) -> Tuple[Block, BlockMetadata]:
    """Worker-side: run a pickled block transform."""
    import cloudpickle
    fn = cloudpickle.loads(fn_bytes)
    out = fn(block)
    acc = BlockAccessor.for_block(out)
    return out, acc.get_metadata()


_transform_task = None


def _get_transform_task(num_cpus: float):
    global _transform_task
    if _transform_task is None:
        _transform_task = ray_tpu.remote(_apply_transform)
    return _transform_task.options(num_cpus=num_cpus, num_returns=2)


class _BlockTransformActor:
    """Actor wrapper executing a (possibly stateful) block transform.

    For callable-class UDFs the class is constructed once here and reused
    for every block (reference: data/_internal/compute.py BlockWorker).
    """

    def __init__(self, fn_constructor_bytes: Optional[bytes]):
        import cloudpickle
        self._udf_instance = None
        if fn_constructor_bytes is not None:
            ctor, args, kwargs = cloudpickle.loads(fn_constructor_bytes)
            self._udf_instance = ctor(*args, **kwargs)

    def ready(self):
        return True

    def apply(self, block: Block, fn_bytes: bytes):
        import cloudpickle
        fn = cloudpickle.loads(fn_bytes)
        if self._udf_instance is not None:
            out = fn(block, self._udf_instance)
        else:
            out = fn(block)
        acc = BlockAccessor.for_block(out)
        return out, acc.get_metadata()
