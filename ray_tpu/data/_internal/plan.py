"""Lazy execution plan: fused one-to-one stages + all-to-all stages.

Analog of the reference's data/_internal/plan.py (ExecutionPlan + stage
fusion) and the logical planner (data/_internal/logical/): a Dataset holds
input blocks plus a chain of stages; execution fuses adjacent one-to-one
stages into a single task per block (so `.map_batches(f).filter(g)` costs
one task per block, not two) and materializes all-to-all stages (shuffle,
sort, repartition) through the 2-stage push-based shuffle.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

import ray_tpu
from ray_tpu.data._internal.compute import (ComputeStrategy, TaskPoolStrategy,
                                            resolve_compute)
from ray_tpu.data.block import Block, BlockAccessor, BlockMetadata


@dataclass
class OneToOneStage:
    """A per-block transform (map_batches / map / filter / flat_map /...)."""

    name: str
    transform: Callable[[Block], Block]
    compute: ComputeStrategy = field(default_factory=TaskPoolStrategy)
    num_cpus: float = 1.0
    udf_constructor: Optional[tuple] = None

    def can_fuse_with(self, other: "OneToOneStage") -> bool:
        # Actor stages don't fuse (each needs its own pool); plain task
        # stages with matching resources fuse freely.
        return (isinstance(self.compute, TaskPoolStrategy)
                and isinstance(other.compute, TaskPoolStrategy)
                and self.num_cpus == other.num_cpus
                and self.udf_constructor is None
                and other.udf_constructor is None)

    def fuse(self, other: "OneToOneStage") -> "OneToOneStage":
        first, second = self.transform, other.transform

        def fused(block):
            return second(first(block))

        return OneToOneStage(
            name=f"{self.name}->{other.name}", transform=fused,
            compute=other.compute, num_cpus=max(self.num_cpus, other.num_cpus))


@dataclass
class AllToAllStage:
    """A global re-organization (shuffle / sort / repartition / groupby).

    ``fn(block_refs, metas) -> (block_refs, metas)``.
    """

    name: str
    fn: Callable[[List[Any], List[BlockMetadata]],
                 Tuple[List[Any], List[BlockMetadata]]]


class ExecutionPlan:
    def __init__(self, blocks: List[Any], metadata: List[BlockMetadata],
                 stages: Optional[List[Any]] = None):
        self._in_blocks = list(blocks)
        self._in_metadata = list(metadata)
        self._stages: List[Any] = list(stages or [])
        self._out: Optional[Tuple[List[Any], List[BlockMetadata]]] = None

    def with_stage(self, stage) -> "ExecutionPlan":
        if self._out is not None:
            # Build on the materialized snapshot to avoid recomputation.
            return ExecutionPlan(self._out[0], self._out[1], [stage])
        return ExecutionPlan(self._in_blocks, self._in_metadata,
                             self._stages + [stage])

    def stage_names(self) -> List[str]:
        return [s.name for s in self._stages]

    def _fused_stages(self) -> List[Any]:
        fused: List[Any] = []
        for stage in self._stages:
            if (fused and isinstance(stage, OneToOneStage)
                    and isinstance(fused[-1], OneToOneStage)
                    and fused[-1].can_fuse_with(stage)):
                fused[-1] = fused[-1].fuse(stage)
            else:
                fused.append(stage)
        return fused

    def _build_operators(self, options=None):
        """Fused stages → physical operator chain (reference: the logical →
        physical planning in data/_internal/logical/planner.py)."""
        from ray_tpu.data._internal.execution import (AllToAllOperator,
                                                      ExecutionOptions,
                                                      InputDataBuffer,
                                                      MapOperator)
        options = options or ExecutionOptions(
            # Match the bulk path's old default: wide inputs run wide.
            max_in_flight_per_operator=max(8, len(self._in_blocks)))
        ops = [InputDataBuffer(self._in_blocks, self._in_metadata)]
        for stage in self._fused_stages():
            if isinstance(stage, OneToOneStage):
                ops.append(MapOperator(
                    stage.name, stage.transform, stage.compute,
                    stage.num_cpus, stage.udf_constructor,
                    max_in_flight=options.max_in_flight_per_operator))
            else:
                ops.append(AllToAllOperator(stage.name, stage.fn))
        return ops

    def iter_execute(self):
        """Stream (block_ref, metadata) pairs through the operator chain —
        consecutive map stages with different compute strategies pipeline
        against each other instead of materializing between them. Caches
        the full result when fully consumed."""
        if self._out is not None:
            yield from zip(*self._out)
            return
        from ray_tpu.data._internal.execution import StreamingExecutor
        out_blocks: List[Any] = []
        out_metas: List[BlockMetadata] = []
        for bundle in StreamingExecutor().execute(self._build_operators()):
            for block_ref, meta in bundle.blocks:
                if isinstance(meta, ray_tpu.ObjectRef):
                    meta = ray_tpu.get(meta)
                out_blocks.append(block_ref)
                out_metas.append(meta)
                yield block_ref, meta
        self._out = (out_blocks, out_metas)

    def execute(self) -> Tuple[List[Any], List[BlockMetadata]]:
        if self._out is not None:
            return self._out
        for _ in self.iter_execute():
            pass
        return self._out

    def is_executed(self) -> bool:
        return self._out is not None

    def clear_cache(self) -> None:
        self._out = None
