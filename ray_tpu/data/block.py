"""Blocks: the unit of distributed data.

Analog of the reference's python/ray/data/block.py + _internal/arrow_block.py
/ pandas_block.py / simple_block.py: a Dataset is a list of object-store
refs to *blocks*; a BlockAccessor provides a uniform view over the three
block representations (pyarrow.Table — canonical, pandas.DataFrame, and a
plain Python list for non-tabular rows).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Iterator, List, Optional, Tuple, Union

import numpy as np

# A Block is one of: pyarrow.Table, pandas.DataFrame, list (simple rows).
Block = Any

# Column name used when wrapping non-dict values into tabular form.
VALUE_COL = "value"
# Column name used for tensor datasets (range_tensor, from_numpy).
TENSOR_COL = "data"


@dataclass
class BlockMetadata:
    """Per-block stats carried alongside the block ref (reference:
    data/block.py BlockMetadata)."""

    num_rows: Optional[int] = None
    size_bytes: Optional[int] = None
    schema: Any = None
    input_files: List[str] = field(default_factory=list)


def _is_arrow(block) -> bool:
    import pyarrow as pa
    return isinstance(block, pa.Table)


def _is_pandas(block) -> bool:
    import pandas as pd
    return isinstance(block, pd.DataFrame)


class BlockAccessor:
    """Uniform operations over a block. Use ``BlockAccessor.for_block``."""

    def __init__(self, block: Block):
        self._block = block

    @staticmethod
    def for_block(block: Block) -> "BlockAccessor":
        if _is_arrow(block):
            return ArrowBlockAccessor(block)
        if _is_pandas(block):
            return PandasBlockAccessor(block)
        if isinstance(block, list):
            return SimpleBlockAccessor(block)
        raise TypeError(f"Not a block type: {type(block).__name__}")

    @staticmethod
    def batch_to_block(batch: Any) -> Block:
        """Convert a user-returned batch (dict of arrays / DataFrame /
        pyarrow Table / list) into a block."""
        import pandas as pd
        import pyarrow as pa
        if isinstance(batch, (pa.Table, pd.DataFrame, list)):
            return batch
        if isinstance(batch, dict):
            cols = {}
            for k, v in batch.items():
                v = np.asarray(v) if not isinstance(v, np.ndarray) else v
                cols[k] = v
            return _numpy_dict_to_arrow(cols)
        if isinstance(batch, np.ndarray):
            return _numpy_dict_to_arrow({TENSOR_COL: batch})
        raise TypeError(
            "map_batches UDF must return dict[str, np.ndarray], DataFrame, "
            f"pyarrow.Table, np.ndarray, or list; got {type(batch).__name__}")

    # -- interface -------------------------------------------------------
    def num_rows(self) -> int:
        raise NotImplementedError

    def size_bytes(self) -> int:
        raise NotImplementedError

    def schema(self) -> Any:
        raise NotImplementedError

    def slice(self, start: int, end: int) -> Block:
        raise NotImplementedError

    def take(self, indices: List[int]) -> Block:
        raise NotImplementedError

    def iter_rows(self) -> Iterator[Any]:
        raise NotImplementedError

    def to_pandas(self):
        raise NotImplementedError

    def to_arrow(self):
        raise NotImplementedError

    def to_numpy(self, columns=None) -> Dict[str, np.ndarray]:
        raise NotImplementedError

    def to_batch_format(self, batch_format: Optional[str]) -> Any:
        if batch_format in (None, "default", "native", "numpy"):
            out = self.to_numpy()
            if batch_format == "numpy" or isinstance(self, SimpleBlockAccessor):
                return out
            # default for tabular blocks is numpy dict too (TPU-first: the
            # training path wants host numpy it can device_put).
            return out
        if batch_format == "pandas":
            return self.to_pandas()
        if batch_format in ("pyarrow", "arrow"):
            return self.to_arrow()
        raise ValueError(f"Unknown batch_format: {batch_format!r}")

    def select_columns(self, cols: List[str]) -> Block:
        raise NotImplementedError

    def column_values(self, col: Optional[str]) -> np.ndarray:
        """Values of one column (or the single value column)."""
        raise NotImplementedError

    @staticmethod
    def concat(blocks: List[Block]) -> Block:
        """Concatenate same-kind blocks."""
        blocks = [b for b in blocks if BlockAccessor.for_block(b).num_rows()]
        if not blocks:
            return []
        first = blocks[0]
        if isinstance(first, list):
            out: List[Any] = []
            for b in blocks:
                out.extend(b)
            return out
        import pandas as pd
        import pyarrow as pa
        if _is_pandas(first):
            return pd.concat([BlockAccessor.for_block(b).to_pandas()
                              for b in blocks], ignore_index=True)
        return pa.concat_tables(
            [BlockAccessor.for_block(b).to_arrow() for b in blocks],
            promote_options="default")

    def get_metadata(self, input_files: Optional[List[str]] = None
                     ) -> BlockMetadata:
        return BlockMetadata(
            num_rows=self.num_rows(),
            size_bytes=self.size_bytes(),
            schema=self.schema(),
            input_files=list(input_files or []),
        )

    def sample_keys(self, n: int, key: Optional[str]) -> List[Any]:
        total = self.num_rows()
        if total == 0:
            return []
        idx = np.linspace(0, total - 1, min(n, total)).astype(int)
        vals = self.column_values(key)
        return [vals[i] for i in idx]

    def sort_by(self, key: Optional[str], descending: bool = False) -> Block:
        raise NotImplementedError


def _numpy_dict_to_arrow(cols: Dict[str, np.ndarray]):
    import pyarrow as pa
    arrays = []
    names = []
    for k, v in cols.items():
        v = np.asarray(v)
        if v.ndim <= 1:
            arrays.append(pa.array(v))
        elif v[0].size == 0:
            # Zero-size element shape: FixedSizeList(size=0) is invalid
            # in arrow — keep the legacy list-of-lists representation.
            arrays.append(pa.array(v.tolist()))
        else:
            # N-d tensors: fixed-shape extension column, zero-copy from
            # the contiguous values (reference:
            # data/extensions/tensor_extension.py ArrowTensorArray).
            # Like the reference's fixed-shape tensor type, every batch
            # of a column must share one element shape (the shape is
            # part of the arrow type).
            from ray_tpu.data.extensions import ArrowTensorArray
            arrays.append(ArrowTensorArray.from_numpy(v))
        names.append(k)
    return pa.table(arrays, names=names)


class ArrowBlockAccessor(BlockAccessor):
    def num_rows(self) -> int:
        return self._block.num_rows

    def size_bytes(self) -> int:
        return self._block.nbytes

    def schema(self):
        return self._block.schema

    def slice(self, start: int, end: int) -> Block:
        return self._block.slice(start, end - start)

    def take(self, indices: List[int]) -> Block:
        if len(indices) == 0:
            return self._block.slice(0, 0)
        return self._block.take(np.asarray(indices, dtype=np.int64))

    def iter_rows(self):
        for batch in self._block.to_batches():
            yield from batch.to_pylist()

    def to_pandas(self):
        return self._block.to_pandas()

    def to_arrow(self):
        return self._block

    def to_numpy(self, columns=None) -> Dict[str, np.ndarray]:
        from ray_tpu.data.extensions import ArrowTensorType
        cols = columns or self._block.column_names
        out = {}
        for c in cols:
            col = self._block[c]
            if isinstance(col.type, ArrowTensorType):
                out[c] = col.combine_chunks().to_numpy(
                    zero_copy_only=False)
                continue
            try:
                out[c] = col.to_numpy(zero_copy_only=False)
            except Exception:
                out[c] = np.array(col.to_pylist(), dtype=object)
        # Stack nested list columns into ndarrays when rectangular.
        for k, v in out.items():
            if v.dtype == object and len(v) and isinstance(v[0], (list, np.ndarray)):
                try:
                    stacked = np.array(self._block[k].to_pylist())
                    if stacked.dtype != object:
                        out[k] = stacked
                except ValueError:
                    pass
        return out

    def select_columns(self, cols: List[str]) -> Block:
        return self._block.select(cols)

    def column_values(self, col: Optional[str]) -> np.ndarray:
        if col is None:
            col = self._block.column_names[0]
        return self._block[col].to_numpy(zero_copy_only=False)

    def sort_by(self, key, descending=False) -> Block:
        if key is None:
            key = self._block.column_names[0]
        order = "descending" if descending else "ascending"
        return self._block.sort_by([(key, order)])


class PandasBlockAccessor(BlockAccessor):
    def num_rows(self) -> int:
        return len(self._block)

    def size_bytes(self) -> int:
        return int(self._block.memory_usage(deep=True).sum())

    def schema(self):
        return self._block.dtypes

    def slice(self, start: int, end: int) -> Block:
        return self._block.iloc[start:end]

    def take(self, indices: List[int]) -> Block:
        return self._block.iloc[indices]

    def iter_rows(self):
        for row in self._block.to_dict(orient="records"):
            yield row

    def to_pandas(self):
        return self._block

    def to_arrow(self):
        import pyarrow as pa
        return pa.Table.from_pandas(self._block, preserve_index=False)

    def to_numpy(self, columns=None) -> Dict[str, np.ndarray]:
        cols = columns or list(self._block.columns)
        return {c: self._block[c].to_numpy() for c in cols}

    def select_columns(self, cols: List[str]) -> Block:
        return self._block[cols]

    def column_values(self, col: Optional[str]) -> np.ndarray:
        if col is None:
            col = self._block.columns[0]
        return self._block[col].to_numpy()

    def sort_by(self, key, descending=False) -> Block:
        if key is None:
            key = self._block.columns[0]
        return self._block.sort_values(key, ascending=not descending)


class SimpleBlockAccessor(BlockAccessor):
    def num_rows(self) -> int:
        return len(self._block)

    def size_bytes(self) -> int:
        import sys
        return sum(sys.getsizeof(x) for x in self._block[:10]) * max(
            1, len(self._block) // max(len(self._block[:10]), 1))

    def schema(self):
        return type(self._block[0]).__name__ if self._block else None

    def slice(self, start: int, end: int) -> Block:
        return self._block[start:end]

    def take(self, indices: List[int]) -> Block:
        return [self._block[i] for i in indices]

    def iter_rows(self):
        return iter(self._block)

    def to_pandas(self):
        import pandas as pd
        return pd.DataFrame({VALUE_COL: self._block})

    def to_arrow(self):
        import pyarrow as pa
        return pa.table({VALUE_COL: self._block})

    def to_numpy(self, columns=None) -> Dict[str, np.ndarray]:
        return {VALUE_COL: np.array(self._block)}

    def select_columns(self, cols: List[str]) -> Block:
        raise ValueError("Simple blocks have no columns")

    def column_values(self, col: Optional[str]) -> np.ndarray:
        return np.array(self._block, dtype=object)

    def sort_by(self, key, descending=False) -> Block:
        return sorted(self._block, key=key if callable(key) else None,
                      reverse=descending)
