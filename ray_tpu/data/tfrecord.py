"""TFRecord framing + tf.train.Example wire codec, dependency-free.

Analog of the reference's ``data/datasource/tfrecords_datasource.py``,
which imports TensorFlow for the proto classes; TPU images ship no TF,
so this module speaks the two formats directly:

* **TFRecord framing** (tensorflow/core/lib/io/record_writer.cc):
  ``[len: uint64le][masked_crc32c(len): uint32le][data]
  [masked_crc32c(data): uint32le]`` with the CRC32C polynomial and
  TF's mask rotation.
* **tf.train.Example wire format** (example.proto/feature.proto): a
  hand-rolled protobuf codec for the fixed, tiny schema —
  ``Example{ features: Features{ feature: map<string, Feature> } }``
  where ``Feature`` is oneof bytes_list / float_list / int64_list.

Round-trips with real TensorFlow output (same bytes), verified by the
CRC and field-number layout in tests.
"""

from __future__ import annotations

import struct
from typing import Any, Dict, Iterator, List

# -- crc32c (software, slice-free — records are small) -------------------

_CRC_TABLE: List[int] = []


def _crc_table() -> List[int]:
    global _CRC_TABLE
    if not _CRC_TABLE:
        poly = 0x82F63B78  # Castagnoli, reflected
        table = []
        for n in range(256):
            c = n
            for _ in range(8):
                c = (c >> 1) ^ poly if c & 1 else c >> 1
            table.append(c)
        _CRC_TABLE = table
    return _CRC_TABLE


def crc32c(data: bytes) -> int:
    table = _crc_table()
    crc = 0xFFFFFFFF
    for b in data:
        crc = table[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    crc = crc32c(data)
    return ((crc >> 15) | (crc << 17)) + 0xA282EAD8 & 0xFFFFFFFF


# -- protobuf wire primitives -------------------------------------------

def _write_varint(out: bytearray, value: int) -> None:
    while True:
        bits = value & 0x7F
        value >>= 7
        if value:
            out.append(bits | 0x80)
        else:
            out.append(bits)
            return


def _read_varint(data: bytes, pos: int) -> tuple:
    result = shift = 0
    while True:
        b = data[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _tag(field: int, wire: int) -> int:
    return (field << 3) | wire


def _write_len_delimited(out: bytearray, field: int,
                         payload: bytes) -> None:
    _write_varint(out, _tag(field, 2))
    _write_varint(out, len(payload))
    out.extend(payload)


# -- tf.train.Example encode --------------------------------------------

def _encode_feature(value) -> bytes:
    """Feature{ oneof: bytes_list=1 / float_list=2 / int64_list=3 }."""
    inner = bytearray()
    if isinstance(value, bytes):
        value = [value]
    elif isinstance(value, str):
        value = [value.encode()]
    elif not isinstance(value, (list, tuple)):
        try:
            value = list(value)  # numpy arrays
        except TypeError:
            value = [value]
    if not value:
        lst = b""
        field = 3
    elif isinstance(value[0], (bytes, str)):
        lst_b = bytearray()
        for v in value:
            _write_len_delimited(
                lst_b, 1, v.encode() if isinstance(v, str) else v)
        lst, field = bytes(lst_b), 1
    elif isinstance(value[0], (float,)) or \
            type(value[0]).__name__.startswith("float"):
        # FloatList: packed fixed32 floats (field 1).
        packed = struct.pack(f"<{len(value)}f",
                             *[float(v) for v in value])
        lst_b = bytearray()
        _write_len_delimited(lst_b, 1, packed)
        lst, field = bytes(lst_b), 2
    else:
        # Int64List: packed varints (field 1).
        packed = bytearray()
        for v in value:
            _write_varint(packed, int(v) & 0xFFFFFFFFFFFFFFFF)
        lst_b = bytearray()
        _write_len_delimited(lst_b, 1, bytes(packed))
        lst, field = bytes(lst_b), 3
    out = bytearray()
    _write_len_delimited(out, field, lst)
    return bytes(out)


def encode_example(row: Dict[str, Any]) -> bytes:
    """dict -> serialized tf.train.Example."""
    features = bytearray()
    for name, value in row.items():
        entry = bytearray()  # map entry: key=1, value=2
        _write_len_delimited(entry, 1, name.encode())
        _write_len_delimited(entry, 2, _encode_feature(value))
        _write_len_delimited(features, 1, bytes(entry))
    example = bytearray()
    _write_len_delimited(example, 1, bytes(features))
    return bytes(example)


# -- tf.train.Example decode --------------------------------------------

def _iter_fields(data: bytes) -> Iterator[tuple]:
    pos = 0
    while pos < len(data):
        tag, pos = _read_varint(data, pos)
        field, wire = tag >> 3, tag & 7
        if wire == 2:
            length, pos = _read_varint(data, pos)
            yield field, data[pos:pos + length]
            pos += length
        elif wire == 0:
            value, pos = _read_varint(data, pos)
            yield field, value
        elif wire == 5:
            yield field, data[pos:pos + 4]
            pos += 4
        elif wire == 1:
            yield field, data[pos:pos + 8]
            pos += 8
        else:
            raise ValueError(f"unsupported wire type {wire}")


def _decode_feature(data: bytes):
    for field, payload in _iter_fields(data):
        if field == 1:      # BytesList
            return [bytes(v) for f, v in _iter_fields(payload)
                    if f == 1]
        if field == 2:      # FloatList (packed or repeated fixed32)
            out: List[float] = []
            for f, v in _iter_fields(payload):
                if f == 1:
                    if isinstance(v, bytes):
                        out.extend(struct.unpack(
                            f"<{len(v) // 4}f", v))
                    else:
                        out.append(float(v))
            return out
        if field == 3:      # Int64List (packed or repeated varint)
            out_i: List[int] = []
            for f, v in _iter_fields(payload):
                if f == 1:
                    if isinstance(v, bytes):
                        pos = 0
                        while pos < len(v):
                            val, pos = _read_varint(v, pos)
                            if val >= 1 << 63:
                                val -= 1 << 64
                            out_i.append(val)
                    else:
                        out_i.append(int(v))
            return out_i
    return []


def decode_example(data: bytes) -> Dict[str, Any]:
    """serialized tf.train.Example -> dict of lists."""
    row: Dict[str, Any] = {}
    for field, features in _iter_fields(data):
        if field != 1:
            continue
        for f, entry in _iter_fields(features):
            if f != 1:
                continue
            name = None
            value = []
            for ef, ev in _iter_fields(entry):
                if ef == 1:
                    name = ev.decode()
                elif ef == 2:
                    value = _decode_feature(ev)
            if name is not None:
                row[name] = value
    return row


# -- TFRecord framing ---------------------------------------------------

def write_tfrecord_file(path: str, records: List[bytes]) -> None:
    with open(path, "wb") as f:
        for rec in records:
            header = struct.pack("<Q", len(rec))
            f.write(header)
            f.write(struct.pack("<I", _masked_crc(header)))
            f.write(rec)
            f.write(struct.pack("<I", _masked_crc(rec)))


def read_tfrecord_file(path: str) -> Iterator[bytes]:
    with open(path, "rb") as f:
        while True:
            header = f.read(8)
            if not header:
                return
            if len(header) < 8:
                raise ValueError(f"truncated TFRecord header in {path}")
            (length,) = struct.unpack("<Q", header)
            (crc,) = struct.unpack("<I", f.read(4))
            if crc != _masked_crc(header):
                raise ValueError(f"corrupt TFRecord length crc in {path}")
            data = f.read(length)
            if len(data) < length:
                raise ValueError(f"truncated TFRecord body in {path}")
            (dcrc,) = struct.unpack("<I", f.read(4))
            if dcrc != _masked_crc(data):
                raise ValueError(f"corrupt TFRecord data crc in {path}")
            yield data
