"""Preprocessors: fit/transform over Datasets.

Analog of the reference's python/ray/data/preprocessor.py +
data/preprocessors/ (scalers, encoders, BatchMapper, Chain, Concatenator):
``fit`` computes dataset statistics with distributed aggregates;
``transform`` is a map_batches stage. Used standalone or passed to a
Trainer (air/config preprocessor plumbing).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import numpy as np


class Preprocessor:
    _is_fittable = True

    def __init__(self):
        self._fitted = False

    def fit(self, ds) -> "Preprocessor":
        if self._is_fittable:
            self._fit(ds)
        self._fitted = True
        return self

    def fit_transform(self, ds):
        self.fit(ds)
        return self.transform(ds)

    def transform(self, ds):
        if self._is_fittable and not self._fitted:
            raise RuntimeError(
                f"{type(self).__name__} must be fit before transform")
        return ds.map_batches(self._transform_numpy, batch_format="numpy")

    def transform_batch(self, batch: Dict[str, np.ndarray]):
        if self._is_fittable and not self._fitted:
            raise RuntimeError(
                f"{type(self).__name__} must be fit before transform_batch")
        return self._transform_numpy(batch)

    # -- subclass hooks --------------------------------------------------
    def _fit(self, ds) -> None:
        raise NotImplementedError

    def _transform_numpy(self, batch: Dict[str, np.ndarray]):
        raise NotImplementedError


class BatchMapper(Preprocessor):
    """Stateless UDF preprocessor (reference:
    data/preprocessors/batch_mapper.py)."""

    _is_fittable = False

    def __init__(self, fn: Callable, batch_format: str = "numpy"):
        super().__init__()
        self._fn = fn
        self._batch_format = batch_format

    def transform(self, ds):
        return ds.map_batches(self._fn, batch_format=self._batch_format)

    def _transform_numpy(self, batch):
        return self._fn(batch)


class Chain(Preprocessor):
    def __init__(self, *preprocessors: Preprocessor):
        super().__init__()
        self.preprocessors = list(preprocessors)

    def fit(self, ds):
        for p in self.preprocessors:
            ds = p.fit_transform(ds)
        self._fitted = True
        return self

    def fit_transform(self, ds):
        for p in self.preprocessors:
            ds = p.fit_transform(ds)
        self._fitted = True
        return ds

    def transform(self, ds):
        for p in self.preprocessors:
            ds = p.transform(ds)
        return ds

    def transform_batch(self, batch):
        for p in self.preprocessors:
            batch = p.transform_batch(batch)
        return batch


class StandardScaler(Preprocessor):
    def __init__(self, columns: List[str]):
        super().__init__()
        self.columns = columns
        self.stats_: Dict[str, tuple] = {}

    def _fit(self, ds):
        for c in self.columns:
            mean = ds.mean(c)
            std = ds.std(c, ddof=0) or 0.0
            self.stats_[c] = (mean, std)

    def _transform_numpy(self, batch):
        out = dict(batch)
        for c in self.columns:
            mean, std = self.stats_[c]
            denom = std if std else 1.0
            out[c] = (np.asarray(batch[c], dtype=np.float64) - mean) / denom
        return out


class MinMaxScaler(Preprocessor):
    def __init__(self, columns: List[str]):
        super().__init__()
        self.columns = columns
        self.stats_: Dict[str, tuple] = {}

    def _fit(self, ds):
        for c in self.columns:
            self.stats_[c] = (ds.min(c), ds.max(c))

    def _transform_numpy(self, batch):
        out = dict(batch)
        for c in self.columns:
            lo, hi = self.stats_[c]
            rng = (hi - lo) or 1.0
            out[c] = (np.asarray(batch[c], dtype=np.float64) - lo) / rng
        return out


class LabelEncoder(Preprocessor):
    def __init__(self, label_column: str):
        super().__init__()
        self.label_column = label_column
        self.classes_: Dict[Any, int] = {}

    def _fit(self, ds):
        values = ds.unique(self.label_column)
        self.classes_ = {v: i for i, v in enumerate(values)}

    def _transform_numpy(self, batch):
        out = dict(batch)
        out[self.label_column] = np.array(
            [self.classes_[v] for v in batch[self.label_column]],
            dtype=np.int64)
        return out


class OneHotEncoder(Preprocessor):
    def __init__(self, columns: List[str]):
        super().__init__()
        self.columns = columns
        self.classes_: Dict[str, Dict[Any, int]] = {}

    def _fit(self, ds):
        for c in self.columns:
            values = ds.unique(c)
            self.classes_[c] = {v: i for i, v in enumerate(values)}

    def _transform_numpy(self, batch):
        out = dict(batch)
        for c in self.columns:
            mapping = self.classes_[c]
            idx = np.array([mapping[v] for v in batch[c]])
            onehot = np.zeros((len(idx), len(mapping)), dtype=np.float32)
            onehot[np.arange(len(idx)), idx] = 1.0
            del out[c]
            for v, i in mapping.items():
                out[f"{c}_{v}"] = onehot[:, i]
        return out


class SimpleImputer(Preprocessor):
    def __init__(self, columns: List[str], strategy: str = "mean",
                 fill_value: Any = None):
        super().__init__()
        self.columns = columns
        self.strategy = strategy
        self.fill_value = fill_value
        self.stats_: Dict[str, Any] = {}

    def _fit(self, ds):
        for c in self.columns:
            if self.strategy == "mean":
                self.stats_[c] = ds.mean(c)
            elif self.strategy == "constant":
                self.stats_[c] = self.fill_value
            else:
                raise ValueError(self.strategy)

    def _transform_numpy(self, batch):
        out = dict(batch)
        for c in self.columns:
            v = np.asarray(batch[c], dtype=np.float64)
            v = np.where(np.isnan(v), self.stats_[c], v)
            out[c] = v
        return out


class Concatenator(Preprocessor):
    """Concatenate feature columns into one matrix column — the standard
    last step before tensor ingest (reference:
    data/preprocessors/concatenator.py)."""

    _is_fittable = False

    def __init__(self, output_column_name: str = "concat_out",
                 include: Optional[List[str]] = None,
                 exclude: Optional[List[str]] = None,
                 dtype=np.float32):
        super().__init__()
        self.output_column_name = output_column_name
        self.include = include
        self.exclude = set(exclude or [])
        self.dtype = dtype

    def _transform_numpy(self, batch):
        cols = self.include or [c for c in batch if c not in self.exclude]
        mats = [np.asarray(batch[c], dtype=self.dtype).reshape(
            len(batch[c]), -1) for c in cols]
        out = {k: v for k, v in batch.items() if k not in cols}
        out[self.output_column_name] = np.concatenate(mats, axis=1)
        return out
