"""Dataset: a distributed collection of blocks with lazy transforms.

Analog of the reference's python/ray/data/dataset.py: blocks live in the
object store as refs; transforms append stages to a lazy ExecutionPlan
(data/_internal/plan.py) which fuses one-to-one stages and runs all-to-all
stages through the push-based shuffle. The TPU-first difference: the default
batch format is a dict of host numpy arrays, ready for ``jax.device_put`` /
per-host sharded ingest into a JaxTrainer mesh (iter_jax_batches).
"""

from __future__ import annotations

import itertools
from typing import (Any, Callable, Dict, Iterator, List, Optional, Tuple,
                    Union)

import numpy as np

import ray_tpu
from ray_tpu.data import aggregate as agg_mod
from ray_tpu.data._internal.compute import resolve_compute
from ray_tpu.data._internal.plan import (AllToAllStage, ExecutionPlan,
                                         OneToOneStage)
from ray_tpu.data._internal.shuffle import shuffle_blocks, sort_blocks
from ray_tpu.data.block import (VALUE_COL, Block, BlockAccessor,
                                BlockMetadata)

BatchUDF = Callable[[Any], Any]
RowUDF = Callable[[Any], Any]


class Dataset:
    def __init__(self, plan: ExecutionPlan, epoch: int = 0):
        self._plan = plan
        self._epoch = epoch

    # ------------------------------------------------------------------
    # Construction helpers
    # ------------------------------------------------------------------

    @staticmethod
    def from_blocks(blocks: List[Any], metadata: List[BlockMetadata]
                    ) -> "Dataset":
        return Dataset(ExecutionPlan(blocks, metadata))

    def _execute(self) -> Tuple[List[Any], List[BlockMetadata]]:
        return self._plan.execute()

    def get_internal_block_refs(self) -> List[Any]:
        return self._execute()[0]

    def materialize(self) -> "Dataset":
        blocks, metas = self._execute()
        return Dataset.from_blocks(blocks, metas)

    # Alias matching the reference's older API.
    fully_executed = materialize

    # ------------------------------------------------------------------
    # One-to-one transforms
    # ------------------------------------------------------------------

    def map_batches(self, fn: BatchUDF, *, batch_size: Optional[int] = None,
                    batch_format: Optional[str] = "numpy",
                    compute=None, fn_constructor_args: tuple = (),
                    fn_constructor_kwargs: Optional[dict] = None,
                    num_cpus: float = 1.0, zero_copy_batch: bool = False,
                    **_ignored) -> "Dataset":
        """Apply ``fn`` to batches of rows. With a callable class + an
        ActorPoolStrategy, the class is constructed once per pool actor
        (reference: dataset.py map_batches / compute.py)."""
        compute = resolve_compute(compute)
        udf_constructor = None
        if isinstance(fn, type):
            udf_constructor = (fn, fn_constructor_args,
                               fn_constructor_kwargs or {})

            def transform(block, _fmt=batch_format, _bs=batch_size):
                raise RuntimeError("class UDF requires actor compute")

            def actor_transform(block, instance, _fmt=batch_format,
                                _bs=batch_size):
                return _map_batches_block(block, instance, _fmt, _bs)

            from ray_tpu.data._internal.compute import ActorPoolStrategy
            if not isinstance(compute, ActorPoolStrategy):
                raise ValueError(
                    "Callable-class UDFs require compute=ActorPoolStrategy "
                    "(the class is constructed once per pool actor)")
            stage = OneToOneStage(
                name="map_batches", transform=actor_transform,
                compute=compute, num_cpus=num_cpus,
                udf_constructor=udf_constructor)
            return Dataset(self._plan.with_stage(stage), self._epoch)

        def transform(block, _fn=fn, _fmt=batch_format, _bs=batch_size):
            return _map_batches_block(block, _fn, _fmt, _bs)

        stage = OneToOneStage(name="map_batches", transform=transform,
                              compute=compute, num_cpus=num_cpus)
        return Dataset(self._plan.with_stage(stage), self._epoch)

    def map(self, fn: RowUDF, *, compute=None, num_cpus: float = 1.0
            ) -> "Dataset":
        def transform(block, _fn=fn):
            acc = BlockAccessor.for_block(block)
            rows = [_fn(row) for row in acc.iter_rows()]
            return _rows_to_block(rows)

        stage = OneToOneStage(name="map", transform=transform,
                              compute=resolve_compute(compute),
                              num_cpus=num_cpus)
        return Dataset(self._plan.with_stage(stage), self._epoch)

    def flat_map(self, fn: RowUDF, *, compute=None, num_cpus: float = 1.0
                 ) -> "Dataset":
        def transform(block, _fn=fn):
            acc = BlockAccessor.for_block(block)
            rows = [out for row in acc.iter_rows() for out in _fn(row)]
            return _rows_to_block(rows)

        stage = OneToOneStage(name="flat_map", transform=transform,
                              compute=resolve_compute(compute),
                              num_cpus=num_cpus)
        return Dataset(self._plan.with_stage(stage), self._epoch)

    def filter(self, fn: RowUDF, *, compute=None, num_cpus: float = 1.0
               ) -> "Dataset":
        def transform(block, _fn=fn):
            acc = BlockAccessor.for_block(block)
            keep = [i for i, row in enumerate(acc.iter_rows()) if _fn(row)]
            return acc.take(keep)

        stage = OneToOneStage(name="filter", transform=transform,
                              compute=resolve_compute(compute),
                              num_cpus=num_cpus)
        return Dataset(self._plan.with_stage(stage), self._epoch)

    def select_columns(self, cols: List[str], **kwargs) -> "Dataset":
        def transform(block, _cols=tuple(cols)):
            return BlockAccessor.for_block(block).select_columns(list(_cols))

        stage = OneToOneStage(name="select_columns", transform=transform)
        return Dataset(self._plan.with_stage(stage), self._epoch)

    def drop_columns(self, cols: List[str], **kwargs) -> "Dataset":
        def transform(block, _drop=tuple(cols)):
            acc = BlockAccessor.for_block(block)
            tbl = acc.to_arrow()
            keep = [c for c in tbl.column_names if c not in _drop]
            return tbl.select(keep)

        stage = OneToOneStage(name="drop_columns", transform=transform)
        return Dataset(self._plan.with_stage(stage), self._epoch)

    def add_column(self, name: str, fn: Callable[[Any], Any], **kwargs
                   ) -> "Dataset":
        def transform(block, _name=name, _fn=fn):
            acc = BlockAccessor.for_block(block)
            df = acc.to_pandas().copy()
            df[_name] = _fn(df)
            return df

        stage = OneToOneStage(name="add_column", transform=transform)
        return Dataset(self._plan.with_stage(stage), self._epoch)

    # ------------------------------------------------------------------
    # All-to-all transforms
    # ------------------------------------------------------------------

    def repartition(self, num_blocks: int, *, shuffle: bool = False
                    ) -> "Dataset":
        def fn(blocks, metas, _n=num_blocks, _shuffle=shuffle):
            if _shuffle:
                return shuffle_blocks(blocks, _n, mode="random")
            # Order-preserving: slice the global row sequence evenly.
            total = sum(m.num_rows or 0 for m in metas)
            offsets = [(i * total) // _n for i in range(_n)] + [total]
            out = self._slice_rows(blocks, offsets)
            out_metas = [BlockAccessor.for_block(b).get_metadata()
                         for b in ray_tpu.get(out)]
            return out, out_metas

        return Dataset(self._plan.with_stage(
            AllToAllStage("repartition", fn)), self._epoch)

    def random_shuffle(self, *, seed: Optional[int] = None,
                       num_blocks: Optional[int] = None) -> "Dataset":
        def fn(blocks, metas, _seed=seed, _n=num_blocks):
            blocks, metas = shuffle_blocks(blocks, _n or len(blocks),
                                           mode="random", seed=_seed)
            # Shuffle rows within each output block too. Each block gets
            # its OWN stream (seed + index): a shared seed would apply the
            # same permutation to equal-sized blocks, leaving the "random"
            # shuffle structurally correlated across blocks.
            def _permute(block, s):
                acc = BlockAccessor.for_block(block)
                n = acc.num_rows()
                rng = np.random.default_rng(s)
                return acc.take(rng.permutation(n).tolist())
            out_blocks = []
            task = ray_tpu.remote(_permute)
            for i, b in enumerate(blocks):
                out_blocks.append(task.remote(
                    b, None if _seed is None else _seed + i))
            return out_blocks, metas

        return Dataset(self._plan.with_stage(
            AllToAllStage("random_shuffle", fn)), self._epoch)

    def randomize_block_order(self, *, seed: Optional[int] = None
                              ) -> "Dataset":
        def fn(blocks, metas, _seed=seed):
            rng = np.random.default_rng(_seed)
            order = rng.permutation(len(blocks)).tolist()
            return [blocks[i] for i in order], [metas[i] for i in order]

        return Dataset(self._plan.with_stage(
            AllToAllStage("randomize_block_order", fn)), self._epoch)

    def sort(self, key: Optional[str] = None, descending: bool = False
             ) -> "Dataset":
        def fn(blocks, metas, _key=key, _desc=descending):
            return sort_blocks(blocks, key=_key, descending=_desc)

        return Dataset(self._plan.with_stage(AllToAllStage("sort", fn)),
                       self._epoch)

    def groupby(self, key: Optional[str]) -> "GroupedDataset":
        return GroupedDataset(self, key)

    def zip(self, other: "Dataset") -> "Dataset":
        """Column-wise zip of equal-length datasets."""
        left = self.materialize()
        right = other.repartition_like(left)

        def _zip(a, b):
            import pyarrow as pa
            ta = BlockAccessor.for_block(a).to_arrow()
            tb = BlockAccessor.for_block(b).to_arrow()
            cols = list(ta.columns) + list(tb.columns)
            names = list(ta.column_names)
            for n in tb.column_names:
                names.append(n if n not in ta.column_names else n + "_1")
            return pa.table(cols, names=names)

        task = ray_tpu.remote(_zip)
        lb, lm = left._execute()
        rb, _ = right._execute()
        if len(lb) != len(rb):
            raise ValueError("zip requires equal block counts")
        out = [task.remote(a, b) for a, b in zip(lb, rb)]
        metas = [BlockAccessor.for_block(b).get_metadata()
                 for b in ray_tpu.get(out)]
        return Dataset.from_blocks(out, metas)

    def repartition_like(self, other: "Dataset") -> "Dataset":
        """Repartition so block row counts match ``other`` (zip helper)."""
        counts = [m.num_rows for m in other._execute()[1]]
        blocks, _ = self._execute()
        offsets = np.cumsum([0] + counts)
        rows_blocks = self._slice_rows(blocks, offsets)
        metas = [BlockAccessor.for_block(ray_tpu.get(b)).get_metadata()
                 for b in rows_blocks]
        return Dataset.from_blocks(rows_blocks, metas)

    def _slice_rows(self, blocks, offsets):
        """Re-slice blocks to the [offsets] row boundaries."""
        def _slice(start, end, *blks):
            merged = BlockAccessor.concat(list(blks))
            return BlockAccessor.for_block(merged).slice(start, end)

        task = ray_tpu.remote(_slice)
        out = []
        for i in range(len(offsets) - 1):
            out.append(task.remote(int(offsets[i]), int(offsets[i + 1]),
                                   *blocks))
        return out

    def union(self, *others: "Dataset") -> "Dataset":
        blocks, metas = [list(x) for x in self._execute()]
        for o in others:
            ob, om = o._execute()
            blocks.extend(ob)
            metas.extend(om)
        return Dataset.from_blocks(blocks, metas)

    # ------------------------------------------------------------------
    # Splitting / consumption
    # ------------------------------------------------------------------

    def split(self, n: int, *, equal: bool = False, locality_hints=None
              ) -> List["Dataset"]:
        """Split into n datasets by block (equal=True balances rows) —
        the Train ingest path (reference: dataset.py split / train
        _internal/dataset_spec.py)."""
        blocks, metas = self._execute()
        if equal:
            total = sum(m.num_rows or 0 for m in metas)
            per = total // n
            offsets = [i * per for i in range(n)] + [per * n]
            parts = self._slice_rows(blocks, offsets)
            out = []
            for ref in parts:
                block = ray_tpu.get(ref)
                out.append(Dataset.from_blocks(
                    [ref], [BlockAccessor.for_block(block).get_metadata()]))
            return out
        out = []
        for i in range(n):
            sel = list(range(i, len(blocks), n))
            out.append(Dataset.from_blocks([blocks[j] for j in sel],
                                           [metas[j] for j in sel]))
        return out

    def streaming_split(self, n: int, *, equal: bool = False,
                        locality_hints=None) -> List["DataIterator"]:
        """n disjoint iterators over this dataset — the per-worker Train
        ingest handles (reference: dataset.streaming_split feeding one
        DataIterator per train worker). Blocks are assigned round-robin;
        execution streams through the operator pipeline on first use."""
        if n <= 0:
            raise ValueError(f"streaming_split requires n >= 1, got {n}")
        return [DataIterator(self, shard_index=i, num_shards=n,
                             equal=equal, locality_hints=locality_hints)
                for i in range(n)]

    def split_at_indices(self, indices: List[int]) -> List["Dataset"]:
        blocks, metas = self._execute()
        total = sum(m.num_rows or 0 for m in metas)
        offsets = [0] + list(indices) + [total]
        parts = self._slice_rows(blocks, offsets)
        out = []
        for ref in parts:
            block = ray_tpu.get(ref)
            out.append(Dataset.from_blocks(
                [ref], [BlockAccessor.for_block(block).get_metadata()]))
        return out

    def limit(self, n: int) -> "Dataset":
        blocks, metas = self._execute()
        out_blocks, out_metas, used = [], [], 0
        for b, m in zip(blocks, metas):
            if used >= n:
                break
            rows = m.num_rows or 0
            if used + rows <= n:
                out_blocks.append(b)
                out_metas.append(m)
                used += rows
            else:
                take = n - used

                def _head(block, _take=take):
                    return BlockAccessor.for_block(block).slice(0, _take)

                ref = ray_tpu.remote(_head).remote(b)
                out_blocks.append(ref)
                out_metas.append(BlockAccessor.for_block(
                    ray_tpu.get(ref)).get_metadata())
                used = n
        return Dataset.from_blocks(out_blocks, out_metas)

    def take(self, n: int = 20) -> List[Any]:
        out = []
        for row in self.iter_rows():
            out.append(row)
            if len(out) >= n:
                break
        return out

    def take_all(self) -> List[Any]:
        return list(self.iter_rows())

    def show(self, n: int = 20) -> None:
        for row in self.take(n):
            print(row)

    def count(self) -> int:
        _, metas = self._execute()
        return sum(m.num_rows or 0 for m in metas)

    def num_blocks(self) -> int:
        return len(self._execute()[0])

    def size_bytes(self) -> int:
        _, metas = self._execute()
        return sum(m.size_bytes or 0 for m in metas)

    def schema(self):
        _, metas = self._execute()
        for m in metas:
            if m.schema is not None:
                return m.schema
        return None

    def columns(self) -> Optional[List[str]]:
        s = self.schema()
        if s is None:
            return None
        try:
            return list(s.names)
        except AttributeError:
            return None

    def input_files(self) -> List[str]:
        _, metas = self._execute()
        return sorted({f for m in metas for f in m.input_files})

    def stats(self) -> str:
        return (f"Dataset(num_blocks={self.num_blocks()}, "
                f"num_rows={self.count()}, "
                f"stages={self._plan.stage_names()})")

    def __repr__(self) -> str:
        try:
            n = self.count()
        except Exception:
            n = "?"
        return f"Dataset(num_blocks={self.num_blocks()}, num_rows={n})"

    # ------------------------------------------------------------------
    # Iteration
    # ------------------------------------------------------------------

    def iter_rows(self) -> Iterator[Any]:
        for block in self._iter_blocks():
            acc = BlockAccessor.for_block(block)
            is_simple = isinstance(block, list)
            for row in acc.iter_rows():
                yield row

    def _iter_blocks(self) -> Iterator[Block]:
        if not self._plan.is_executed():
            # Streaming execution: blocks flow through the whole operator
            # chain as they're produced (reference: streaming_executor.py) —
            # first batch latency is one block's traversal, not a full
            # materialization.
            for block_ref, _meta in self._plan.iter_execute():
                yield ray_tpu.get(block_ref)
            return
        blocks, _ = self._execute()
        # Prefetch one block ahead while the consumer processes the current
        # one (reference: block prefetching in iter_batches).
        for i, ref in enumerate(blocks):
            if i + 1 < len(blocks):
                ray_tpu.wait([blocks[i + 1]], num_returns=1, timeout=0)
            yield ray_tpu.get(ref)

    def iter_batches(self, *, batch_size: Optional[int] = 256,
                     batch_format: Optional[str] = "numpy",
                     drop_last: bool = False,
                     local_shuffle_buffer_size: Optional[int] = None,
                     local_shuffle_seed: Optional[int] = None,
                     prefetch_batches: int = 1) -> Iterator[Any]:
        """Iterate formatted batches. The TPU ingest hot path."""
        carry: Optional[Block] = None
        rng = (np.random.default_rng(local_shuffle_seed)
               if local_shuffle_buffer_size else None)
        for block in self._iter_blocks():
            if carry is not None:
                block = BlockAccessor.concat([carry, block])
                carry = None
            acc = BlockAccessor.for_block(block)
            n = acc.num_rows()
            if batch_size is None:
                yield acc.to_batch_format(batch_format)
                continue
            start = 0
            while n - start >= batch_size:
                piece = acc.slice(start, start + batch_size)
                if rng is not None:
                    pacc = BlockAccessor.for_block(piece)
                    piece = pacc.take(
                        rng.permutation(batch_size).tolist())
                yield BlockAccessor.for_block(piece).to_batch_format(
                    batch_format)
                start += batch_size
            if start < n:
                carry = acc.slice(start, n)
        if carry is not None and not drop_last:
            yield BlockAccessor.for_block(carry).to_batch_format(batch_format)

    def iter_jax_batches(self, *, batch_size: int = 256,
                         dtypes: Optional[dict] = None,
                         device=None, drop_last: bool = True,
                         **kwargs) -> Iterator[Dict[str, Any]]:
        """Batches as jax Arrays (device_put onto ``device``); the analog of
        the reference's iter_torch_batches (dataset.py) for the JaxTrainer."""
        import jax
        for batch in self.iter_batches(batch_size=batch_size,
                                       batch_format="numpy",
                                       drop_last=drop_last, **kwargs):
            out = {}
            for k, v in batch.items():
                if dtypes and k in dtypes:
                    v = v.astype(dtypes[k])
                out[k] = jax.device_put(v, device)
            yield out

    iter_torch_batches = iter_jax_batches  # capability alias

    def to_pandas(self, limit: int = 100_000):
        import pandas as pd
        blocks, metas = self._execute()
        total = sum(m.num_rows or 0 for m in metas)
        if total > limit:
            raise ValueError(
                f"Dataset has {total} rows > limit {limit}; pass a larger "
                "limit to to_pandas")
        frames = [BlockAccessor.for_block(b).to_pandas()
                  for b in ray_tpu.get(list(blocks))]
        if not frames:
            return pd.DataFrame()
        return pd.concat(frames, ignore_index=True)

    def to_arrow_refs(self) -> List[Any]:
        return list(self._execute()[0])

    def to_numpy_refs(self) -> List[Any]:
        def _conv(block):
            return BlockAccessor.for_block(block).to_numpy()

        task = ray_tpu.remote(_conv)
        return [task.remote(b) for b in self._execute()[0]]

    # ------------------------------------------------------------------
    # Global aggregates
    # ------------------------------------------------------------------

    def aggregate(self, *aggs: agg_mod.AggregateFn) -> Any:
        def _acc_block(block, _aggs=aggs):
            acc = BlockAccessor.for_block(block)
            batch = acc.to_numpy()
            return [a.accumulate_block(a.init(None), batch) for a in _aggs]

        task = ray_tpu.remote(_acc_block)
        partials = ray_tpu.get([task.remote(b)
                                for b in self._execute()[0]])
        results = []
        for i, a in enumerate(aggs):
            state = a.init(None)
            for p in partials:
                state = a.merge(state, p[i])
            results.append(a.finalize(state))
        if len(results) == 1:
            return results[0]
        return tuple(results)

    def sum(self, on: Optional[str] = None):
        return self.aggregate(agg_mod.Sum(on))

    def min(self, on: Optional[str] = None):
        return self.aggregate(agg_mod.Min(on))

    def max(self, on: Optional[str] = None):
        return self.aggregate(agg_mod.Max(on))

    def mean(self, on: Optional[str] = None):
        return self.aggregate(agg_mod.Mean(on))

    def std(self, on: Optional[str] = None, ddof: int = 1):
        return self.aggregate(agg_mod.Std(on, ddof))

    def unique(self, column: str) -> List[Any]:
        def _uniq(block, _c=column):
            return list(set(
                BlockAccessor.for_block(block).column_values(_c).tolist()))

        task = ray_tpu.remote(_uniq)
        out = set()
        for part in ray_tpu.get([task.remote(b)
                                 for b in self._execute()[0]]):
            out.update(part)
        return sorted(out)

    # ------------------------------------------------------------------
    # Writes
    # ------------------------------------------------------------------

    def write_parquet(self, path: str, **kwargs) -> None:
        self._write_files(path, "parquet", **kwargs)

    def write_csv(self, path: str, **kwargs) -> None:
        self._write_files(path, "csv", **kwargs)

    def write_json(self, path: str, **kwargs) -> None:
        self._write_files(path, "json", **kwargs)

    def write_tfrecords(self, path: str, **kwargs) -> None:
        """One .tfrecord file of tf.train.Example records per block
        (reference: Dataset.write_tfrecords) — no TF dependency
        (data/tfrecord.py); block writes run as parallel tasks like the
        other write formats."""
        import os
        os.makedirs(path, exist_ok=True)

        def _write(block, idx, _path=path):
            import os

            from ray_tpu.data.tfrecord import (encode_example,
                                               write_tfrecord_file)
            acc = BlockAccessor.for_block(block)
            records = [encode_example(row) for row in acc.iter_rows()]
            fname = os.path.join(_path, f"part-{idx:05d}.tfrecord")
            write_tfrecord_file(fname, records)
            return fname

        task = ray_tpu.remote(_write)
        blocks, _ = self._execute()
        ray_tpu.get([task.remote(b, i) for i, b in enumerate(blocks)])

    def write_numpy(self, path: str, column: str = "data", **kwargs) -> None:
        self._write_files(path, "numpy", column=column, **kwargs)

    def _write_files(self, path: str, fmt: str, column: str = "data",
                     **kwargs) -> None:
        import os
        os.makedirs(path, exist_ok=True)

        def _write(block, idx, _path=path, _fmt=fmt, _col=column):
            import os
            acc = BlockAccessor.for_block(block)
            ext = {"parquet": "parquet", "csv": "csv", "json": "json",
                   "numpy": "npy"}[_fmt]
            fname = os.path.join(_path, f"{idx:06d}.{ext}")
            if _fmt == "parquet":
                import pyarrow.parquet as pq
                pq.write_table(acc.to_arrow(), fname)
            elif _fmt == "csv":
                acc.to_pandas().to_csv(fname, index=False)
            elif _fmt == "json":
                acc.to_pandas().to_json(fname, orient="records", lines=True)
            else:
                np.save(fname, acc.to_numpy().get(_col))
            return fname

        task = ray_tpu.remote(_write)
        blocks, _ = self._execute()
        ray_tpu.get([task.remote(b, i) for i, b in enumerate(blocks)])

    # ------------------------------------------------------------------
    # Pipeline / epochs
    # ------------------------------------------------------------------

    def window(self, *, blocks_per_window: int = 10):
        from ray_tpu.data.dataset_pipeline import DatasetPipeline
        return DatasetPipeline.from_dataset(self, blocks_per_window)

    def repeat(self, times: Optional[int] = None):
        from ray_tpu.data.dataset_pipeline import DatasetPipeline
        return DatasetPipeline.from_dataset_repeated(self, times)


class GroupedDataset:
    """Hash-partition by key, then per-partition grouped aggregation
    (reference: data/grouped_dataset.py)."""

    def __init__(self, ds: Dataset, key: Optional[str]):
        self._ds = ds
        self._key = key

    def aggregate(self, *aggs: agg_mod.AggregateFn) -> Dataset:
        key = self._key
        blocks, _ = self._ds._execute()
        shuffled, _ = shuffle_blocks(blocks, len(blocks), mode="hash",
                                     key=key)

        def _group_agg(block, _key=key, _aggs=aggs):
            import pandas as pd
            acc = BlockAccessor.for_block(block)
            df = acc.to_pandas()
            if len(df) == 0:
                return df.head(0)
            rows = []
            for gval, gdf in df.groupby(_key, sort=True):
                batch = {c: gdf[c].to_numpy() for c in gdf.columns}
                row = {_key: gval}
                for a in _aggs:
                    state = a.accumulate_block(a.init(gval), batch)
                    row[a.name] = a.finalize(state)
                rows.append(row)
            return pd.DataFrame(rows)

        task = ray_tpu.remote(_group_agg)
        out = [task.remote(b) for b in shuffled]
        metas = [BlockAccessor.for_block(b).get_metadata()
                 for b in ray_tpu.get(out)]
        return Dataset.from_blocks(out, metas)

    def count(self) -> Dataset:
        return self.aggregate(agg_mod.Count())

    def sum(self, on: Optional[str] = None) -> Dataset:
        return self.aggregate(agg_mod.Sum(on))

    def min(self, on: Optional[str] = None) -> Dataset:
        return self.aggregate(agg_mod.Min(on))

    def max(self, on: Optional[str] = None) -> Dataset:
        return self.aggregate(agg_mod.Max(on))

    def mean(self, on: Optional[str] = None) -> Dataset:
        return self.aggregate(agg_mod.Mean(on))

    def std(self, on: Optional[str] = None) -> Dataset:
        return self.aggregate(agg_mod.Std(on))

    def map_groups(self, fn: Callable) -> Dataset:
        key = self._key
        blocks, _ = self._ds._execute()
        shuffled, _ = shuffle_blocks(blocks, len(blocks), mode="hash",
                                     key=key)

        def _map_groups(block, _key=key, _fn=fn):
            import pandas as pd
            df = BlockAccessor.for_block(block).to_pandas()
            if len(df) == 0:
                return df
            outs = []
            for _, gdf in df.groupby(_key, sort=True):
                out = _fn(gdf)
                outs.append(out if isinstance(out, pd.DataFrame)
                            else pd.DataFrame(out))
            return pd.concat(outs, ignore_index=True)

        task = ray_tpu.remote(_map_groups)
        out = [task.remote(b) for b in shuffled]
        metas = [BlockAccessor.for_block(b).get_metadata()
                 for b in ray_tpu.get(out)]
        return Dataset.from_blocks(out, metas)


def _map_batches_block(block: Block, fn, batch_format, batch_size) -> Block:
    acc = BlockAccessor.for_block(block)
    n = acc.num_rows()
    if n == 0:
        return block
    outs = []
    step = batch_size or n
    for start in range(0, n, step):
        piece = acc.slice(start, min(start + step, n))
        batch = BlockAccessor.for_block(piece).to_batch_format(batch_format)
        out = fn(batch)
        outs.append(BlockAccessor.batch_to_block(out))
    return BlockAccessor.concat(outs)


def _rows_to_block(rows: List[Any]) -> Block:
    if rows and isinstance(rows[0], dict):
        import pandas as pd
        import pyarrow as pa
        try:
            return pa.Table.from_pylist(rows)
        except Exception:
            return pd.DataFrame(rows)
    return list(rows)


class DataIterator:
    """A shard-scoped iterator over a Dataset (reference: DataIterator
    returned by streaming_split): each of the n iterators sees a disjoint
    round-robin subset of blocks, exposing the same iteration surface the
    full Dataset does (iter_batches / iter_rows / iter_jax_batches)."""

    def __init__(self, dataset: Dataset, shard_index: int, num_shards: int,
                 equal: bool = False, locality_hints=None):
        self._dataset = dataset
        self._shard_index = shard_index
        self._num_shards = num_shards
        self._equal = equal
        self._locality_hints = locality_hints
        self._shard: Optional[Dataset] = None

    def _materialize_shard(self) -> Dataset:
        if self._shard is None:
            self._shard = self._dataset.split(
                self._num_shards, equal=self._equal,
                locality_hints=self._locality_hints)[self._shard_index]
        return self._shard

    def iter_batches(self, **kwargs) -> Iterator[Any]:
        return self._materialize_shard().iter_batches(**kwargs)

    def iter_rows(self) -> Iterator[Any]:
        return self._materialize_shard().iter_rows()

    def iter_jax_batches(self, **kwargs) -> Iterator[Dict[str, Any]]:
        return self._materialize_shard().iter_jax_batches(**kwargs)

    def materialize(self) -> Dataset:
        return self._materialize_shard().materialize()

    def count(self) -> int:
        return self._materialize_shard().count()

    def __repr__(self):
        return (f"DataIterator(shard={self._shard_index}/"
                f"{self._num_shards})")
