"""ParallelIterator: sharded, lazily-transformed distributed iterators.

Analog of the reference's util/iter.py: ``from_items``/``from_range``
shard data across actor-held iterators; ``for_each``/``filter``/``batch``
chain lazily per shard; ``gather_sync`` round-robins results back to the
driver.
"""

from __future__ import annotations

from typing import Any, Callable, Iterator, List

import ray_tpu


class _ShardActor:
    def __init__(self, items: List[Any]):
        self._items = items
        self._ops: List[tuple] = []

    def apply_op(self, kind: str, fn_bytes: bytes) -> bool:
        self._ops.append((kind, fn_bytes))
        return True

    def run(self) -> List[Any]:
        import cloudpickle
        out: Iterator[Any] = iter(self._items)
        for kind, fn_bytes in self._ops:
            fn = cloudpickle.loads(fn_bytes) if fn_bytes else None
            if kind == "for_each":
                out = map(fn, out)
            elif kind == "filter":
                out = filter(fn, out)
            elif kind == "flatten":
                out = (x for it in out for x in it)
            elif kind == "batch":
                size = fn  # int smuggled through pickle

                def batcher(src, n):
                    buf = []
                    for x in src:
                        buf.append(x)
                        if len(buf) == n:
                            yield buf
                            buf = []
                    if buf:
                        yield buf

                out = batcher(out, size)
        return list(out)


class ParallelIterator:
    def __init__(self, shards: List[Any]):
        self._shards = shards

    @staticmethod
    def from_items(items: List[Any], num_shards: int = 2
                   ) -> "ParallelIterator":
        if not ray_tpu.is_initialized():
            ray_tpu.init()
        cls = ray_tpu.remote(_ShardActor)
        chunks: List[List[Any]] = [[] for _ in range(num_shards)]
        for i, item in enumerate(items):
            chunks[i % num_shards].append(item)
        return ParallelIterator([cls.remote(c) for c in chunks])

    @staticmethod
    def from_range(n: int, num_shards: int = 2) -> "ParallelIterator":
        return ParallelIterator.from_items(list(range(n)), num_shards)

    def _chain(self, kind: str, payload) -> "ParallelIterator":
        import cloudpickle
        blob = cloudpickle.dumps(payload)
        ray_tpu.get([s.apply_op.remote(kind, blob) for s in self._shards])
        return self

    def for_each(self, fn: Callable) -> "ParallelIterator":
        return self._chain("for_each", fn)

    def filter(self, fn: Callable) -> "ParallelIterator":
        return self._chain("filter", fn)

    def flatten(self) -> "ParallelIterator":
        return self._chain("flatten", None)

    def batch(self, n: int) -> "ParallelIterator":
        return self._chain("batch", n)

    def num_shards(self) -> int:
        return len(self._shards)

    def gather_sync(self) -> Iterator[Any]:
        """Round-robin merge of all shards' results."""
        results = ray_tpu.get([s.run.remote() for s in self._shards])
        iters = [iter(r) for r in results]
        while iters:
            alive = []
            for it in iters:
                try:
                    yield next(it)
                    alive.append(it)
                except StopIteration:
                    pass
            iters = alive

    def take(self, n: int) -> List[Any]:
        out = []
        for item in self.gather_sync():
            out.append(item)
            if len(out) >= n:
                break
        return out

    def stop(self) -> None:
        for s in self._shards:
            ray_tpu.kill(s)


from_items = ParallelIterator.from_items
from_range = ParallelIterator.from_range
