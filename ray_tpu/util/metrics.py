"""User-defined metrics: Counter / Gauge / Histogram.

Analog of the reference's python/ray/util/metrics.py:19,155 (Counter,
Gauge, Histogram over the C++ OpenCensus pipeline, stats/metric.h). Here a
process-local registry aggregates tagged series; ``export_prometheus``
renders the text exposition format the reference's metrics agent serves to
Prometheus.

Cluster export rides :func:`snapshot` / :func:`diff_snapshot`: every
worker/daemon's metrics agent (``_private/metrics_agent.py``) snapshots
this registry on an interval and ships the changed series to the head,
which merges them (tagged ``node_id``/``pid``/``component``) into one
cluster-wide exposition via :func:`render_exposition`.
"""

from __future__ import annotations

import bisect
import threading
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

_REGISTRY: Dict[str, "Metric"] = {}
_REGISTRY_LOCK = threading.Lock()


class Metric:
    metric_type = "untyped"

    def __init__(self, name: str, description: str = "",
                 tag_keys: Optional[Sequence[str]] = None):
        if not name:
            raise ValueError("metric name required")
        self.name = name
        self.description = description
        self.tag_keys = tuple(tag_keys or ())
        self._default_tags: Dict[str, str] = {}
        self._series: Dict[Tuple[str, ...], float] = {}
        self._lock = threading.Lock()
        with _REGISTRY_LOCK:
            existing = _REGISTRY.get(name)
            if existing is not None:
                # Re-registration with the SAME signature returns the same
                # series store (the reference keys metrics globally by name
                # too); a conflicting signature is a programming error that
                # used to be silently swallowed.
                if self._signature() != existing._signature():
                    raise ValueError(
                        f"metric {name!r} already registered with a "
                        f"different signature: existing "
                        f"{existing._signature()}, new {self._signature()}")
                self.__dict__ = existing.__dict__
            else:
                _REGISTRY[name] = self

    def _signature(self) -> Tuple:
        return (self.metric_type, self.description, self.tag_keys)

    def set_default_tags(self, tags: Dict[str, str]) -> "Metric":
        self._default_tags = dict(tags)
        return self

    def _key(self, tags: Optional[Dict[str, str]]) -> Tuple[str, ...]:
        merged = {**self._default_tags, **(tags or {})}
        extra = set(merged) - set(self.tag_keys)
        if extra:
            raise ValueError(f"Unknown tag keys {sorted(extra)}; declared "
                             f"tag_keys={self.tag_keys}")
        return tuple(merged.get(k, "") for k in self.tag_keys)

    def series(self) -> Dict[Tuple[str, ...], float]:
        with self._lock:
            return dict(self._series)


class Counter(Metric):
    metric_type = "counter"

    def inc(self, value: float = 1.0,
            tags: Optional[Dict[str, str]] = None) -> None:
        if value < 0:
            raise ValueError("Counters only increase")
        key = self._key(tags)
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + value


class Gauge(Metric):
    metric_type = "gauge"

    def set(self, value: float,
            tags: Optional[Dict[str, str]] = None) -> None:
        with self._lock:
            self._series[self._key(tags)] = float(value)


class Histogram(Metric):
    metric_type = "histogram"

    def __init__(self, name: str, description: str = "",
                 boundaries: Optional[Sequence[float]] = None,
                 tag_keys: Optional[Sequence[str]] = None):
        self.boundaries = sorted(boundaries or
                                 [0.001, 0.01, 0.1, 1, 10, 100, 1000])
        super().__init__(name, description, tag_keys)
        if not hasattr(self, "_buckets"):
            self._buckets: Dict[Tuple[str, ...], List[int]] = {}
            self._sums: Dict[Tuple[str, ...], float] = {}
            self._counts: Dict[Tuple[str, ...], int] = {}

    def _signature(self) -> Tuple:
        return (self.metric_type, self.description, self.tag_keys,
                tuple(self.boundaries))

    def observe(self, value: float,
                tags: Optional[Dict[str, str]] = None) -> None:
        key = self._key(tags)
        with self._lock:
            buckets = self._buckets.setdefault(
                key, [0] * (len(self.boundaries) + 1))
            buckets[bisect.bisect_left(self.boundaries, value)] += 1
            self._sums[key] = self._sums.get(key, 0.0) + value
            self._counts[key] = self._counts.get(key, 0) + 1
            self._series[key] = value  # last observation

    def percentile(self, q: float,
                   tags: Optional[Dict[str, str]] = None) -> float:
        """Approximate percentile from bucket boundaries."""
        key = self._key(tags)
        with self._lock:
            buckets = self._buckets.get(key)
            total = self._counts.get(key, 0)
        if not buckets or not total:
            return float("nan")
        target = q / 100.0 * total
        run = 0
        for i, c in enumerate(buckets):
            run += c
            if run >= target:
                return self.boundaries[min(i, len(self.boundaries) - 1)]
        return self.boundaries[-1]


def registry() -> Dict[str, Metric]:
    with _REGISTRY_LOCK:
        return dict(_REGISTRY)


def clear_registry() -> None:
    """Test hook: forget every registered metric. Live Metric objects keep
    working but stop being exported; the next registration under a name
    starts a fresh series store."""
    with _REGISTRY_LOCK:
        _REGISTRY.clear()


# ---------------------------------------------------------------------------
# Snapshots (the unit the metrics agents ship over the wire)
# ---------------------------------------------------------------------------


def snapshot() -> List[Dict[str, Any]]:
    """Picklable snapshot of every registered metric: one dict per metric
    with its full series state (histograms include buckets/sums/counts).
    This is what a metrics agent diffs and ships in ``metrics_batch``
    frames."""
    out: List[Dict[str, Any]] = []
    for _name, metric in sorted(registry().items()):
        with metric._lock:
            entry: Dict[str, Any] = {
                "name": metric.name,
                "type": metric.metric_type,
                "desc": metric.description,
                "tag_keys": tuple(metric.tag_keys),
                "series": dict(metric._series),
            }
            if isinstance(metric, Histogram):
                entry["boundaries"] = tuple(metric.boundaries)
                entry["buckets"] = {k: list(v)
                                    for k, v in metric._buckets.items()}
                entry["sums"] = dict(metric._sums)
                entry["counts"] = dict(metric._counts)
        out.append(entry)
    return out


def diff_snapshot(prev: Optional[List[Dict[str, Any]]],
                  cur: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """The entries (and within them only the series) that changed between
    two :func:`snapshot` results. Values are CUMULATIVE, so the receiver
    merges by overwrite — a dropped diff frame heals on the next change or
    full refresh."""
    if not prev:
        return list(cur)
    prev_by = {e["name"]: e for e in prev}
    out: List[Dict[str, Any]] = []
    for entry in cur:
        old = prev_by.get(entry["name"])
        if old is None or old.get("type") != entry.get("type"):
            out.append(entry)
            continue
        changed = {k for k, v in entry["series"].items()
                   if old["series"].get(k) != v}
        if entry["type"] == "histogram":
            changed |= {k for k, v in entry.get("counts", {}).items()
                        if old.get("counts", {}).get(k) != v}
        if not changed:
            continue
        slim = {k: v for k, v in entry.items()
                if k not in ("series", "buckets", "sums", "counts")}
        slim["series"] = {k: v for k, v in entry["series"].items()
                          if k in changed}
        if entry["type"] == "histogram":
            for field in ("buckets", "sums", "counts"):
                slim[field] = {k: v for k, v in entry.get(field, {}).items()
                               if k in changed}
        out.append(slim)
    return out


# ---------------------------------------------------------------------------
# Prometheus text exposition
# ---------------------------------------------------------------------------


def _sanitize(name: str) -> str:
    return name.replace("-", "_").replace(".", "_")


def _escape_help(text: str) -> str:
    """HELP text is one line by contract: escape backslash and newline."""
    return text.replace("\\", "\\\\").replace("\n", "\\n")


def _escape_label(value: str) -> str:
    return (str(value).replace("\\", "\\\\").replace("\n", "\\n")
            .replace('"', '\\"'))


def _fmt(value: float) -> str:
    f = float(value)
    if f.is_integer() and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _label_str(keys: Sequence[str], values: Sequence[str],
               extra: Optional[Dict[str, str]] = None) -> str:
    parts = [f'{k}="{_escape_label(v)}"' for k, v in zip(keys, values)]
    if extra:
        parts += [f'{k}="{_escape_label(v)}"' for k, v in extra.items()]
    return ",".join(parts)


def _render_entry(lines: List[str], safe: str, entry: Dict[str, Any],
                  extra: Optional[Dict[str, str]]) -> None:
    tag_keys = tuple(entry.get("tag_keys") or ())
    if entry.get("type") == "histogram":
        boundaries = list(entry.get("boundaries") or ())
        counts = entry.get("counts", {})
        sums = entry.get("sums", {})
        for key, buckets in entry.get("buckets", {}).items():
            base = _label_str(tag_keys, key, extra)
            sep = "," if base else ""
            run = 0
            for bound, n in zip(boundaries, buckets):
                run += n
                lines.append(
                    f'{safe}_bucket{{{base}{sep}le="{_fmt(bound)}"}} '
                    f"{run}")
            lines.append(f'{safe}_bucket{{{base}{sep}le="+Inf"}} '
                         f"{counts.get(key, run)}")
            lines.append(
                f"{safe}_sum{'{' + base + '}' if base else ''} "
                f"{_fmt(sums.get(key, 0.0))}")
            lines.append(
                f"{safe}_count{'{' + base + '}' if base else ''} "
                f"{counts.get(key, 0)}")
        return
    for key, value in entry.get("series", {}).items():
        labels = _label_str(tag_keys, key, extra)
        if labels:
            lines.append(f"{safe}{{{labels}}} {_fmt(value)}")
        else:
            lines.append(f"{safe} {_fmt(value)}")


def render_exposition(
        groups: Iterable[Tuple[Dict[str, Any],
                               Optional[Dict[str, str]]]]) -> str:
    """Prometheus text exposition from snapshot entries. ``groups`` is an
    iterable of (snapshot entry, extra label dict or None); entries for
    the same metric name (e.g. from different nodes) are merged under one
    HELP/TYPE header. Extra labels (node_id/pid/component) are appended
    to every series of their entry."""
    by_name: Dict[str, List[Tuple[Dict[str, Any],
                                  Optional[Dict[str, str]]]]] = {}
    for entry, extra in groups:
        by_name.setdefault(entry["name"], []).append((entry, extra))
    lines: List[str] = []
    for name in sorted(by_name):
        items = by_name[name]
        safe = _sanitize(name)
        first = items[0][0]
        if first.get("desc"):
            lines.append(f"# HELP {safe} {_escape_help(first['desc'])}")
        lines.append(f"# TYPE {safe} {first.get('type', 'untyped')}")
        for entry, extra in items:
            if entry.get("type") != first.get("type"):
                continue  # conflicting family type from another origin
            _render_entry(lines, safe, entry, extra)
    return "\n".join(lines) + "\n"


def export_prometheus() -> str:
    """Prometheus text exposition of every metric registered in THIS
    process (what the reference's per-node metrics agent serves,
    metrics_agent.py:189). The head's dashboard serves the cluster-merged
    variant via ``_private/metrics_agent.ClusterMetrics``."""
    return render_exposition((entry, None) for entry in snapshot())
