"""User-defined metrics: Counter / Gauge / Histogram.

Analog of the reference's python/ray/util/metrics.py:19,155 (Counter,
Gauge, Histogram over the C++ OpenCensus pipeline, stats/metric.h). Here a
process-local registry aggregates tagged series; ``export_prometheus``
renders the text exposition format the reference's metrics agent serves to
Prometheus.
"""

from __future__ import annotations

import bisect
import threading
from typing import Dict, List, Optional, Sequence, Tuple

_REGISTRY: Dict[str, "Metric"] = {}
_REGISTRY_LOCK = threading.Lock()


class Metric:
    metric_type = "untyped"

    def __init__(self, name: str, description: str = "",
                 tag_keys: Optional[Sequence[str]] = None):
        if not name:
            raise ValueError("metric name required")
        self.name = name
        self.description = description
        self.tag_keys = tuple(tag_keys or ())
        self._default_tags: Dict[str, str] = {}
        self._series: Dict[Tuple[str, ...], float] = {}
        self._lock = threading.Lock()
        with _REGISTRY_LOCK:
            existing = _REGISTRY.get(name)
            if existing is not None:
                # Re-registration returns the same series store (the
                # reference keys metrics globally by name too).
                self.__dict__ = existing.__dict__
            else:
                _REGISTRY[name] = self

    def set_default_tags(self, tags: Dict[str, str]) -> "Metric":
        self._default_tags = dict(tags)
        return self

    def _key(self, tags: Optional[Dict[str, str]]) -> Tuple[str, ...]:
        merged = {**self._default_tags, **(tags or {})}
        extra = set(merged) - set(self.tag_keys)
        if extra:
            raise ValueError(f"Unknown tag keys {sorted(extra)}; declared "
                             f"tag_keys={self.tag_keys}")
        return tuple(merged.get(k, "") for k in self.tag_keys)

    def series(self) -> Dict[Tuple[str, ...], float]:
        with self._lock:
            return dict(self._series)


class Counter(Metric):
    metric_type = "counter"

    def inc(self, value: float = 1.0,
            tags: Optional[Dict[str, str]] = None) -> None:
        if value < 0:
            raise ValueError("Counters only increase")
        key = self._key(tags)
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + value


class Gauge(Metric):
    metric_type = "gauge"

    def set(self, value: float,
            tags: Optional[Dict[str, str]] = None) -> None:
        with self._lock:
            self._series[self._key(tags)] = float(value)


class Histogram(Metric):
    metric_type = "histogram"

    def __init__(self, name: str, description: str = "",
                 boundaries: Optional[Sequence[float]] = None,
                 tag_keys: Optional[Sequence[str]] = None):
        self.boundaries = sorted(boundaries or
                                 [0.001, 0.01, 0.1, 1, 10, 100, 1000])
        super().__init__(name, description, tag_keys)
        if not hasattr(self, "_buckets"):
            self._buckets: Dict[Tuple[str, ...], List[int]] = {}
            self._sums: Dict[Tuple[str, ...], float] = {}
            self._counts: Dict[Tuple[str, ...], int] = {}

    def observe(self, value: float,
                tags: Optional[Dict[str, str]] = None) -> None:
        key = self._key(tags)
        with self._lock:
            buckets = self._buckets.setdefault(
                key, [0] * (len(self.boundaries) + 1))
            buckets[bisect.bisect_left(self.boundaries, value)] += 1
            self._sums[key] = self._sums.get(key, 0.0) + value
            self._counts[key] = self._counts.get(key, 0) + 1
            self._series[key] = value  # last observation

    def percentile(self, q: float,
                   tags: Optional[Dict[str, str]] = None) -> float:
        """Approximate percentile from bucket boundaries."""
        key = self._key(tags)
        with self._lock:
            buckets = self._buckets.get(key)
            total = self._counts.get(key, 0)
        if not buckets or not total:
            return float("nan")
        target = q / 100.0 * total
        run = 0
        for i, c in enumerate(buckets):
            run += c
            if run >= target:
                return self.boundaries[min(i, len(self.boundaries) - 1)]
        return self.boundaries[-1]


def registry() -> Dict[str, Metric]:
    with _REGISTRY_LOCK:
        return dict(_REGISTRY)


def clear_registry() -> None:
    with _REGISTRY_LOCK:
        _REGISTRY.clear()


def export_prometheus() -> str:
    """Prometheus text exposition of every registered metric (what the
    reference's per-node metrics agent serves, metrics_agent.py:189)."""
    lines: List[str] = []
    for name, metric in sorted(registry().items()):
        safe = name.replace("-", "_").replace(".", "_")
        if metric.description:
            lines.append(f"# HELP {safe} {metric.description}")
        lines.append(f"# TYPE {safe} {metric.metric_type}")
        for key, value in metric.series().items():
            if metric.tag_keys:
                tags = ",".join(f'{k}="{v}"'
                                for k, v in zip(metric.tag_keys, key))
                lines.append(f"{safe}{{{tags}}} {value}")
            else:
                lines.append(f"{safe} {value}")
        if isinstance(metric, Histogram):
            for key, count in metric._counts.items():
                tags = ",".join(f'{k}="{v}"'
                                for k, v in zip(metric.tag_keys, key))
                prefix = f"{safe}_count{{{tags}}}" if tags else \
                    f"{safe}_count"
                lines.append(f"{prefix} {count}")
    return "\n".join(lines) + "\n"
