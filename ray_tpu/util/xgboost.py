"""Distributed XGBoost on ray_tpu (analog of the xgboost_ray package
the reference ecosystem ships: RayDMatrix / RayParams / train /
predict over Ray actors; xgboost_ray/main.py starts a rabit tracker on
the driver and one training actor per shard).

Architecture here is the same: ``train`` starts xgboost's own
RabitTracker on the driver, spawns ``num_actors`` ray_tpu actors each
holding one data shard, and every actor runs ``xgb.train`` connected
to the tracker — xgboost's collective does the histogram allreduce, so
the result is EXACT distributed boosting, not bagging. ``predict``
fans shard predictions over the same actors.

xgboost itself is not bundled; every entry point raises a clear
ImportError without it. The orchestration (sharding, env fan-out,
result selection) is backend-injectable and covered by unit tests that
run without xgboost.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence, Tuple

__all__ = ["RayDMatrix", "RayParams", "train", "predict"]


def _require_xgboost():
    try:
        import xgboost
        return xgboost
    except ImportError as exc:
        raise ImportError(
            "ray_tpu.util.xgboost needs the xgboost package, which is "
            "not installed in this environment.") from exc


@dataclass
class RayParams:
    """Scale-out knobs (xgboost_ray.RayParams parity subset)."""
    num_actors: int = 2
    cpus_per_actor: float = 1.0
    resources_per_actor: Optional[Dict[str, float]] = None
    max_actor_restarts: int = 0


class RayDMatrix:
    """Sharded training data: X/y split row-wise into ``num_actors``
    shards at train time (xgboost_ray.RayDMatrix parity subset).
    ObjectRefs are accepted and resolved once at shard time."""

    def __init__(self, data, label=None, **dmatrix_kwargs):
        self.data = data
        self.label = label
        self.dmatrix_kwargs = dmatrix_kwargs

    def shards(self, n: int) -> List[Tuple[Any, Any]]:
        import numpy as np

        from ray_tpu._private.object_ref import ObjectRef

        def resolve(v):
            if isinstance(v, ObjectRef):
                import ray_tpu
                return ray_tpu.get(v)
            return v

        X = resolve(self.data)
        y = resolve(self.label)
        idx = np.array_split(np.arange(len(X)), n)
        return [(X[i[0]:i[-1] + 1],
                 None if y is None else y[i[0]:i[-1] + 1])
                for i in idx if len(i)]


class _XGBShardActor:
    """One training worker: joins the rabit collective and boosts on
    its shard (xgboost_ray's RayXGBoostActor analog)."""

    def __init__(self, shard, dmatrix_kwargs, backend=None):
        self._X, self._y = shard
        self._dmatrix_kwargs = dmatrix_kwargs
        self._backend = backend
        self._booster = None

    def train(self, params: dict, num_boost_round: int,
              collective_env: Dict[str, str], evals_result: bool):
        backend = self._backend or _XGBBackend()
        self._booster, result = backend.train_shard(
            params, self._X, self._y, self._dmatrix_kwargs,
            num_boost_round, collective_env)
        return result if evals_result else None

    def predict(self, model_bytes: Optional[bytes] = None):
        backend = self._backend or _XGBBackend()
        booster = (backend.load(model_bytes) if model_bytes is not None
                   else self._booster)
        return backend.predict_shard(booster, self._X,
                                     self._dmatrix_kwargs)

    def get_model(self) -> bytes:
        backend = self._backend or _XGBBackend()
        return backend.dump(self._booster)


class _XGBBackend:
    """The real xgboost calls, isolated so tests can inject a fake."""

    def tracker(self, n_workers: int):
        xgb = _require_xgboost()
        from xgboost.tracker import RabitTracker

        from ray_tpu.util.lightgbm import _advertise_ip
        host = _advertise_ip()  # NOT gethostbyname: 127.0.1.1 trap
        tracker = RabitTracker(host_ip=host, n_workers=n_workers)
        tracker.start()
        env = {"DMLC_TRACKER_URI": host,
               "DMLC_TRACKER_PORT": str(tracker.port),
               "DMLC_NUM_WORKER": str(n_workers)}
        return tracker, env

    def train_shard(self, params, X, y, dmatrix_kwargs,
                    num_boost_round, collective_env):
        xgb = _require_xgboost()
        from xgboost import collective
        args = {k: v for k, v in collective_env.items()}
        with collective.CommunicatorContext(**args):
            dtrain = xgb.DMatrix(X, label=y, **dmatrix_kwargs)
            evals_result: Dict[str, Any] = {}
            booster = xgb.train(params, dtrain,
                                num_boost_round=num_boost_round,
                                evals=[(dtrain, "train")],
                                evals_result=evals_result)
        return booster, evals_result

    def predict_shard(self, booster, X, dmatrix_kwargs):
        xgb = _require_xgboost()
        return booster.predict(xgb.DMatrix(X, **dmatrix_kwargs))

    def dump(self, booster) -> bytes:
        return booster.save_raw()

    def load(self, raw: bytes):
        xgb = _require_xgboost()
        booster = xgb.Booster()
        booster.load_model(bytearray(raw))
        return booster


def train(params: dict, dtrain: RayDMatrix, *,
          num_boost_round: int = 10,
          ray_params: Optional[RayParams] = None,
          evals_result: Optional[dict] = None,
          _backend=None):
    """Exact distributed boosting over ray_tpu actors (xgboost_ray
    train() parity subset). Returns the trained Booster (its raw bytes
    when a custom backend is injected)."""
    import ray_tpu
    rp = ray_params or RayParams()
    n = max(1, int(rp.num_actors))
    shards = dtrain.shards(n)
    n = len(shards)
    backend = _backend or _XGBBackend()
    tracker, env = backend.tracker(n)
    actor_cls = ray_tpu.remote(num_cpus=rp.cpus_per_actor,
                               resources=rp.resources_per_actor,
                               max_restarts=rp.max_actor_restarts)(
        _XGBShardActor)
    actors = [actor_cls.remote(shard, dtrain.dmatrix_kwargs,
                               _backend)
              for shard in shards]
    try:
        results = ray_tpu.get([
            a.train.remote(params, num_boost_round, env,
                           evals_result is not None)
            for a in actors])
        if evals_result is not None and results and results[0]:
            evals_result.update(results[0])
        # All workers hold the SAME model after collective boosting;
        # rank 0's copy is canonical (xgboost_ray does the same).
        raw = ray_tpu.get(actors[0].get_model.remote())
        return backend.load(raw)
    finally:
        for a in actors:
            ray_tpu.kill(a)
        _stop_tracker(tracker)


def predict(model, data: RayDMatrix, *,
            ray_params: Optional[RayParams] = None,
            _backend=None):
    """Sharded prediction over ray_tpu actors; concatenates in row
    order."""
    import numpy as np

    import ray_tpu
    rp = ray_params or RayParams()
    n = max(1, int(rp.num_actors))
    shards = data.shards(n)
    backend = _backend or _XGBBackend()
    raw = backend.dump(model)
    actor_cls = ray_tpu.remote(num_cpus=rp.cpus_per_actor,
                               resources=rp.resources_per_actor)(
        _XGBShardActor)
    actors = [actor_cls.remote(shard, data.dmatrix_kwargs, _backend)
              for shard in shards]
    try:
        parts = ray_tpu.get([a.predict.remote(raw) for a in actors])
        return np.concatenate([np.asarray(p) for p in parts])
    finally:
        for a in actors:
            ray_tpu.kill(a)


def _stop_tracker(tracker) -> None:
    if tracker is None:
        return
    for meth in ("free", "join", "stop"):
        fn = getattr(tracker, meth, None)
        if fn is not None:
            try:
                fn()
                return
            except Exception:  # noqa: BLE001 - teardown best-effort
                continue
