"""Dask-on-ray_tpu scheduler (reference: python/ray/util/dask/scheduler.py
ray_dask_get): execute a dask task graph on the cluster by mapping every
graph task to a ray_tpu task, with inter-task data passed as ObjectRefs
(no materialization through the driver between stages).

Dask graphs are PLAIN DICTS — ``{key: task}`` where a task is
``(callable, arg1, ...)``, keys may be strings OR tuples like
``('chunk', 0)`` (every dask collection uses tuple keys), values may be
lists of computations, and args may be keys, nested tasks, nested
lists, or literals (the "dask graph protocol"; dask/core.py). That
protocol needs nothing from dask itself, so this scheduler works
standalone and plugs into real dask as::

    import dask

    dask.config.set(scheduler=ray_tpu.util.dask.ray_dask_get)
    df.sum().compute()          # dask collections now run on the cluster

Each graph task becomes ONE ray_tpu task whose args are the ObjectRefs
of its dependencies — the scheduler builds the whole task DAG up front
and lets the runtime's dependency resolution drive execution order
(maximal parallelism, zero driver-side barriers).
"""

from __future__ import annotations

from typing import Any, Dict, Hashable, List

import ray_tpu

__all__ = ["ray_dask_get", "is_dask_task"]


def is_dask_task(value: Any) -> bool:
    """A dask-protocol task: a tuple whose head is callable."""
    return isinstance(value, tuple) and bool(value) and callable(value[0])


def _is_key(value: Any, dsk: Dict[Hashable, Any]) -> bool:
    """Keys are any HASHABLE graph members — strings or tuples like
    ``('chunk-name', 0)`` (membership first: a tuple arg that matches a
    key is a reference, per dask.core.get semantics)."""
    try:
        return value in dsk
    except TypeError:
        return False  # unhashable (e.g. list): never a key


def _execute_task(func, *resolved):
    """Worker-side shim: top-level ObjectRef args arrive already
    materialized (the runtime resolves dependencies); nested ref lists
    ride a _ListResolver and materialize here."""
    resolved = [a.resolve() if isinstance(a, _ListResolver) else a
                for a in resolved]
    return func(*resolved)


def _rebuild(arg: Any, refs: Dict[Hashable, Any], dsk) -> Any:
    """Substitute keys with their (ref) results; recurse into lists
    (dask nests args in lists) and INLINE nested tasks. Key membership
    is checked BEFORE task-shape: ``('x', 1)`` could be both."""
    if _is_key(arg, dsk):
        return refs[arg]
    if is_dask_task(arg):
        # Inline task (dask emits these for cheap ops): execute its
        # callable with recursively rebuilt args — but any ref among
        # them must materialize first, so resolve driver-side.
        func = arg[0]
        sub = [_rebuild(a, refs, dsk) for a in arg[1:]]
        sub = [ray_tpu.get(s) if isinstance(s, ray_tpu.ObjectRef) else s
               for s in sub]
        return func(*sub)
    if isinstance(arg, list):
        return [_rebuild(a, refs, dsk) for a in arg]
    return arg


def ray_dask_get(dsk: Dict[Hashable, Any], keys, **_kwargs):
    """The dask ``get`` entry point (reference: scheduler.py:42
    ray_dask_get): submit every graph task as a ray_tpu task (deps as
    refs), then materialize ``keys``. ``keys`` may be a single key or
    arbitrarily nested lists of keys (dask collection protocol)."""
    refs: Dict[Hashable, Any] = {}

    remote_exec = ray_tpu.remote(_execute_task)
    for key in toposort(dsk):
        task = dsk[key]
        if _is_key(task, dsk):
            refs[key] = refs[task]  # alias entry
        elif is_dask_task(task):
            func = task[0]
            args = [_rebuild(a, refs, dsk) for a in task[1:]]
            # Nested lists of refs must materialize worker-side; the
            # runtime only auto-resolves TOP-LEVEL ref args. Wrap lists
            # in a resolver task argument.
            args = [_ListResolver(a)
                    if isinstance(a, list) and _contains_ref_deep(a)
                    else a for a in args]
            refs[key] = remote_exec.remote(func, *args)
        elif isinstance(task, list):
            # List VALUE = list of computations (dask graph spec).
            refs[key] = _rebuild(task, refs, dsk)
        else:
            refs[key] = task  # literal

    def walk(v):
        if isinstance(v, ray_tpu.ObjectRef):
            return ray_tpu.get(v)
        if isinstance(v, list):
            return [walk(x) for x in v]
        return v

    def materialize(k):
        if isinstance(k, list):
            return [materialize(x) for x in k]
        return walk(refs[k])

    return materialize(keys)


def toposort(dsk: Dict[Hashable, Any]) -> List[Hashable]:
    """Dependency order over the graph's keys. Iterative DFS — real
    dask workloads chain thousands of tasks, far past the recursion
    limit. Cycles raise ValueError."""
    deps: Dict[Hashable, List[Hashable]] = {}

    def find(value, out):
        if _is_key(value, dsk):
            out.append(value)
            return
        if isinstance(value, (tuple, list)):
            items = value[1:] if is_dask_task(value) else value
            for v in items:
                find(v, out)

    for key, task in dsk.items():
        out: List[Hashable] = []
        find(task, out)
        deps[key] = out

    order: List[Hashable] = []
    done: set = set()
    in_progress: set = set()
    for root in dsk:
        if root in done:
            continue
        stack: List[tuple] = [(root, iter(deps[root]))]
        in_progress.add(root)
        while stack:
            node, it = stack[-1]
            advanced = False
            for child in it:
                if child in done:
                    continue
                if child in in_progress:
                    raise ValueError(
                        f"dask graph has a cycle through {child!r}")
                in_progress.add(child)
                stack.append((child, iter(deps[child])))
                advanced = True
                break
            if not advanced:
                stack.pop()
                in_progress.discard(node)
                done.add(node)
                order.append(node)
    return order


class _ListResolver:
    """Arg wrapper: a nested list containing ObjectRefs. The runtime
    passes it through opaquely; _execute_task resolves it worker-side
    (connected runtime: get works from any execution context)."""

    def __init__(self, value):
        self.value = value

    def resolve(self):
        def walk(v):
            if isinstance(v, _ListResolver):
                return v.resolve()
            if isinstance(v, list):
                return [walk(x) for x in v]
            if isinstance(v, ray_tpu.ObjectRef):
                return ray_tpu.get(v)
            return v
        return walk(self.value)


def _contains_ref_deep(value: Any) -> bool:
    if isinstance(value, list):
        return any(_contains_ref_deep(v) for v in value)
    return isinstance(value, ray_tpu.ObjectRef)
