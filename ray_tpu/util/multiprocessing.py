"""multiprocessing.Pool API over the task runtime.

Analog of the reference's util/multiprocessing/pool.py: a drop-in
``Pool`` whose workers are cluster tasks — ``map``/``starmap``/``apply``
(+async/unordered variants, chunking) schedule across the cluster instead
of local forked processes.
"""

from __future__ import annotations

import itertools
from typing import Any, Callable, Iterable, List, Optional

import ray_tpu


class AsyncResult:
    def __init__(self, refs: List[Any], single: bool = False,
                 chunked: bool = False):
        self._refs = refs
        self._single = single
        self._chunked = chunked

    def get(self, timeout: Optional[float] = None):
        results = ray_tpu.get(self._refs, timeout=timeout)
        if self._chunked:
            results = [item for chunk in results for item in chunk]
        return results[0] if self._single else results

    def wait(self, timeout: Optional[float] = None) -> None:
        ray_tpu.wait(self._refs, num_returns=len(self._refs),
                     timeout=timeout)

    def ready(self) -> bool:
        ready, _ = ray_tpu.wait(self._refs, num_returns=len(self._refs),
                                timeout=0)
        return len(ready) == len(self._refs)

    def successful(self) -> bool:
        try:
            self.get(timeout=0)
            return True
        except Exception:  # noqa: BLE001
            return False


class Pool:
    """``ray_tpu.util.multiprocessing.Pool(processes=N)``."""

    def __init__(self, processes: Optional[int] = None,
                 initializer: Optional[Callable] = None,
                 initargs: tuple = (), ray_remote_args: Optional[dict] = None):
        if not ray_tpu.is_initialized():
            ray_tpu.init()
        cpus = ray_tpu.cluster_resources().get("CPU", 1)
        self._processes = processes or max(int(cpus), 1)
        self._initializer = initializer
        self._initargs = initargs
        self._remote_args = ray_remote_args or {}
        self._closed = False

    def _task(self, fn: Callable):
        initializer, initargs = self._initializer, self._initargs

        def runner(chunk):
            if initializer is not None and not getattr(
                    runner, "_initialized", False):
                initializer(*initargs)
                runner._initialized = True  # type: ignore[attr-defined]
            return [fn(*args) if isinstance(args, tuple) else fn(args)
                    for args in chunk]

        return ray_tpu.remote(**self._remote_args)(runner) \
            if self._remote_args else ray_tpu.remote(runner)

    def _check_open(self):
        if self._closed:
            raise ValueError("Pool is closed")

    @staticmethod
    def _chunks(iterable: Iterable, chunksize: int) -> List[List[Any]]:
        it = iter(iterable)
        out = []
        while True:
            chunk = list(itertools.islice(it, chunksize))
            if not chunk:
                return out
            out.append(chunk)

    def _default_chunksize(self, items: List[Any]) -> int:
        return max(1, len(items) // (self._processes * 4) or 1)

    # -- apply -----------------------------------------------------------

    def apply(self, fn: Callable, args: tuple = (), kwds: dict = None):
        return self.apply_async(fn, args, kwds).get()

    def apply_async(self, fn: Callable, args: tuple = (),
                    kwds: dict = None) -> AsyncResult:
        self._check_open()
        kwds = kwds or {}
        task = ray_tpu.remote(lambda: fn(*args, **kwds))
        return AsyncResult([task.remote()], single=True)

    # -- map -------------------------------------------------------------

    def map(self, fn: Callable, iterable: Iterable,
            chunksize: Optional[int] = None) -> List[Any]:
        return self.map_async(fn, iterable, chunksize).get()

    def map_async(self, fn: Callable, iterable: Iterable,
                  chunksize: Optional[int] = None) -> AsyncResult:
        self._check_open()
        items = list(iterable)
        chunks = self._chunks(items, chunksize
                              or self._default_chunksize(items))
        task = self._task(fn)
        return AsyncResult([task.remote(c) for c in chunks], chunked=True)

    def starmap(self, fn: Callable, iterable: Iterable[tuple],
                chunksize: Optional[int] = None) -> List[Any]:
        return self.map(fn, [tuple(args) for args in iterable], chunksize)

    def starmap_async(self, fn: Callable, iterable: Iterable[tuple],
                      chunksize: Optional[int] = None) -> AsyncResult:
        return self.map_async(fn, [tuple(a) for a in iterable], chunksize)

    def imap(self, fn: Callable, iterable: Iterable,
             chunksize: Optional[int] = None):
        self._check_open()
        items = list(iterable)
        chunks = self._chunks(items, chunksize
                              or self._default_chunksize(items))
        task = self._task(fn)
        refs = [task.remote(c) for c in chunks]
        for ref in refs:  # in order
            yield from ray_tpu.get(ref)

    def imap_unordered(self, fn: Callable, iterable: Iterable,
                       chunksize: Optional[int] = None):
        self._check_open()
        items = list(iterable)
        chunks = self._chunks(items, chunksize
                              or self._default_chunksize(items))
        task = self._task(fn)
        pending = [task.remote(c) for c in chunks]
        while pending:
            ready, pending = ray_tpu.wait(pending, num_returns=1)
            yield from ray_tpu.get(ready[0])

    # -- lifecycle --------------------------------------------------------

    def close(self) -> None:
        self._closed = True

    def terminate(self) -> None:
        self._closed = True

    def join(self) -> None:
        if not self._closed:
            raise ValueError("Pool is still open")

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.terminate()
