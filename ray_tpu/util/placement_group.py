"""Placement groups: gang-scheduled resource bundles.

Analog of python/ray/util/placement_group.py. On a TPU cluster a PG's bundles
describe a mesh slice (one bundle per host, each with that host's chips);
``placement_group_table`` exposes the reserved topology so Train can build
the `jax.sharding.Mesh` that matches the reservation.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from ray_tpu._private.ids import PlacementGroupID
from ray_tpu._private.object_ref import ObjectRef
from ray_tpu._private.worker import global_worker

VALID_STRATEGIES = ("PACK", "SPREAD", "STRICT_PACK", "STRICT_SPREAD")

_pg_table: Dict[PlacementGroupID, dict] = {}


class PlacementGroup:
    def __init__(self, pg_id: PlacementGroupID,
                 bundles: List[Dict[str, float]], strategy: str,
                 name: str = ""):
        self.id = pg_id
        self._bundles = bundles
        self._strategy = strategy
        self._name = name

    @property
    def bundle_specs(self) -> List[Dict[str, float]]:
        return list(self._bundles)

    @property
    def bundle_count(self) -> int:
        return len(self._bundles)

    def ready(self) -> ObjectRef:
        """Returns a ref that resolves when the PG is reserved. Round-1
        reservation is synchronous, so this is an already-resolved ref."""
        return global_worker.runtime.put(self)

    def wait(self, timeout_seconds: float = 30) -> bool:
        return global_worker.runtime.scheduler.placement_group_exists(self.id)

    def __reduce__(self):
        return (PlacementGroup,
                (self.id, self._bundles, self._strategy, self._name))


def placement_group(bundles: List[Dict[str, float]], strategy: str = "PACK",
                    name: str = "", lifetime: Optional[str] = None,
                    _max_cpu_fraction_per_node: Optional[float] = None
                    ) -> PlacementGroup:
    if strategy not in VALID_STRATEGIES:
        raise ValueError(
            f"Invalid placement group strategy {strategy!r}; must be one of "
            f"{VALID_STRATEGIES}")
    if not bundles:
        raise ValueError("placement_group requires at least one bundle")
    from ray_tpu._private.task_spec import validate_resource_name
    for b in bundles:
        if not isinstance(b, dict) or not b:
            raise ValueError(f"Invalid bundle {b!r}: must be a non-empty dict")
        for res_name in b:
            validate_resource_name(res_name)
        if any(v < 0 for v in b.values()):
            raise ValueError(f"Invalid bundle {b!r}: negative resources")
    runtime = global_worker.runtime
    pg_id = runtime.create_placement_group(bundles, strategy, name)
    pg = PlacementGroup(pg_id, bundles, strategy, name)
    _pg_table[pg_id] = {
        "placement_group_id": pg_id.hex(),
        "name": name,
        "bundles": {i: dict(b) for i, b in enumerate(bundles)},
        "strategy": strategy,
        "state": "CREATED",
    }
    return pg


def remove_placement_group(pg: PlacementGroup) -> None:
    global_worker.runtime.remove_placement_group(pg.id)
    entry = _pg_table.get(pg.id)
    if entry is not None:
        entry["state"] = "REMOVED"


def placement_group_table(pg: Optional[PlacementGroup] = None) -> dict:
    if pg is not None:
        return dict(_pg_table.get(pg.id, {}))
    return {k.hex(): dict(v) for k, v in _pg_table.items()}


def get_current_placement_group() -> Optional[PlacementGroup]:
    from ray_tpu._private.runtime import current_task_spec
    spec = current_task_spec()
    if spec is None:
        return None
    strategy = spec.scheduling_strategy
    if strategy is not None and getattr(strategy, "placement_group", None):
        return strategy.placement_group
    return None
