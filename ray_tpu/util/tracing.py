"""Distributed tracing: spans propagated through task submission.

Analog of the reference's util/tracing/tracing_helper.py (OpenTelemetry
spans wrapping every .remote() with the context carried inside task specs,
_DictPropagator :160): an OTel-compatible-shaped but dependency-free span
recorder. Enable with ``enable_tracing()``; every task/actor call then
records a span parented to the caller's active span, and ``get_spans()`` /
``export_chrome_trace()`` expose the tree.
"""

from __future__ import annotations

import contextlib
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

_state = threading.local()
_lock = threading.Lock()
_spans: List["Span"] = []
_enabled = False
_MAX_SPANS = 100_000


@dataclass
class Span:
    name: str
    trace_id: str
    span_id: str
    parent_id: Optional[str]
    start_time: float
    end_time: Optional[float] = None
    attributes: Dict[str, Any] = field(default_factory=dict)
    # Set once the span has been drained into a metrics_batch frame, so a
    # long-open span ahead of it in the buffer cannot cause re-shipping.
    shipped: bool = field(default=False, repr=False, compare=False)

    def end(self) -> None:
        if self.end_time is None:
            self.end_time = time.time()

    def to_dict(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "start_time": self.start_time,
            "end_time": self.end_time,
            "attributes": dict(self.attributes),
        }


def enable_tracing() -> None:
    """Turn span recording on (reference: ray.init(_tracing_startup_hook))."""
    global _enabled
    _enabled = True


def disable_tracing() -> None:
    global _enabled
    _enabled = False


def is_tracing_enabled() -> bool:
    return _enabled


def clear_spans() -> None:
    with _lock:
        _spans.clear()


def current_span() -> Optional[Span]:
    return getattr(_state, "span", None)


def _record(span: Span) -> None:
    with _lock:
        if len(_spans) < _MAX_SPANS:
            _spans.append(span)


@contextlib.contextmanager
def start_span(name: str, attributes: Optional[Dict[str, Any]] = None):
    """Open a span as the thread's active context; nested spans (and remote
    tasks submitted inside) are parented to it."""
    if not _enabled:
        yield None
        return
    parent = current_span()
    span = Span(
        name=name,
        trace_id=parent.trace_id if parent else uuid.uuid4().hex[:16],
        span_id=uuid.uuid4().hex[:8],
        parent_id=parent.span_id if parent else None,
        start_time=time.time(),
        attributes=dict(attributes or {}),
    )
    _record(span)
    prev = parent
    _state.span = span
    try:
        yield span
    finally:
        span.end()
        _state.span = prev


def inject_context() -> Optional[Dict[str, str]]:
    """Serialize the active span context for a task spec (the reference's
    _DictPropagator.inject_current_context)."""
    span = current_span()
    if not _enabled or span is None:
        return None
    return {"trace_id": span.trace_id, "parent_id": span.span_id}


@contextlib.contextmanager
def continue_context(ctx: Optional[Dict[str, str]], name: str):
    """Worker-side: run a task under the caller's trace context."""
    if not _enabled or ctx is None:
        yield None
        return
    span = Span(
        name=name,
        trace_id=ctx["trace_id"],
        span_id=uuid.uuid4().hex[:8],
        parent_id=ctx.get("parent_id"),
        start_time=time.time(),
    )
    _record(span)
    prev = current_span()
    _state.span = span
    try:
        yield span
    finally:
        span.end()
        _state.span = prev


def drain_finished_spans(cursor: int = 0) -> tuple:
    """Ended, not-yet-shipped spans at or after ``cursor``, as plain
    dicts, plus the new cursor (the metrics agent's incremental export:
    spans ride ``metrics_batch`` frames to the head so /api/timeline can
    render cross-process task spans). Open spans are left in place and
    revisited on the next drain; the cursor only advances past the prefix
    whose spans are all shipped."""
    out: List[Dict[str, Any]] = []
    with _lock:
        cursor = max(0, min(cursor, len(_spans)))
        new_cursor = cursor
        advancing = True
        for i in range(cursor, len(_spans)):
            span = _spans[i]
            if span.end_time is None:
                advancing = False
            elif not span.shipped:
                span.shipped = True
                out.append(span.to_dict())
            if advancing:
                new_cursor = i + 1
    return out, new_cursor


def get_spans(trace_id: Optional[str] = None) -> List[Span]:
    with _lock:
        spans = list(_spans)
    if trace_id is not None:
        spans = [s for s in spans if s.trace_id == trace_id]
    return spans


def export_chrome_trace() -> List[Dict[str, Any]]:
    """Spans as chrome://tracing complete events (merges into the timeline
    the state API already emits)."""
    out = []
    for s in get_spans():
        end = s.end_time or time.time()
        out.append({
            "name": s.name,
            "cat": "trace",
            "ph": "X",
            "ts": s.start_time * 1e6,
            "dur": (end - s.start_time) * 1e6,
            "pid": s.trace_id,
            "tid": s.span_id,
            "args": s.attributes,
        })
    return out
