"""Distributed tracing: spans propagated through task submission.

Analog of the reference's util/tracing/tracing_helper.py (OpenTelemetry
spans wrapping every .remote() with the context carried inside task specs,
_DictPropagator :160): an OTel-compatible-shaped but dependency-free span
recorder. Enable with ``enable_tracing()``; every task/actor call then
records a span parented to the caller's active span, and ``get_spans()`` /
``export_chrome_trace()`` expose the tree.

Cross-process model (Dapper-style): the driver makes the sampling
decision ONCE per trace (``RAY_TPU_TRACE_SAMPLE_RATE``, head-of-trace
sampling) and serializes ``{trace_id, parent_id, sampled}`` into the
task spec / request metadata; every downstream hop parents its spans to
the carried context. Unsampled requests carry no context at all, so the
remote side's cost is a single attribute read. Finished spans ride
``metrics_batch`` frames to the head, where the trace assembler
(_private/trace_assembler.py) merges them per trace_id.

Timing: ``start_time`` is a wall-clock ANCHOR (for cross-process
alignment on one timeline); ``duration`` is measured monotonically so an
NTP step mid-span cannot corrupt it. ``end_time`` is derived
(anchor + duration), never a second wall-clock read.
"""

from __future__ import annotations

import contextlib
import os
import random
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

_state = threading.local()
_lock = threading.Lock()
_spans: List["Span"] = []
_enabled = False
_MAX_SPANS = 100_000
#: Resolved sample rate; None = not yet resolved (lazy: env/config may
#: not be final at import time).
_sample_rate: Optional[float] = None


@dataclass
class Span:
    name: str
    trace_id: str
    span_id: str
    parent_id: Optional[str]
    start_time: float  # wall-clock anchor (cross-process alignment only)
    end_time: Optional[float] = None  # derived: start_time + duration
    duration: Optional[float] = None  # monotonic, NTP-step-proof
    attributes: Dict[str, Any] = field(default_factory=dict)
    # Set once the span has been drained into a metrics_batch frame, so a
    # long-open span ahead of it in the buffer cannot cause re-shipping.
    shipped: bool = field(default=False, repr=False, compare=False)
    # Monotonic start, never serialized (meaningless across processes).
    _mono: float = field(default=0.0, repr=False, compare=False)

    def end(self) -> None:
        if self.end_time is None:
            self.duration = time.monotonic() - self._mono
            self.end_time = self.start_time + self.duration

    def to_dict(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "start_time": self.start_time,
            "end_time": self.end_time,
            "duration": self.duration,
            "attributes": dict(self.attributes),
        }


class _Unsampled:
    """Thread-local sentinel: the active trace drew NOT-sampled. Keeps
    the head-of-trace decision sticky for nested local spans (a child of
    an unsampled root must not re-draw and start recording mid-trace)."""

    __slots__ = ()


_UNSAMPLED = _Unsampled()


def enable_tracing() -> None:
    """Turn span recording on (reference: ray.init(_tracing_startup_hook))."""
    global _enabled
    _enabled = True


def disable_tracing() -> None:
    global _enabled
    _enabled = False


def is_tracing_enabled() -> bool:
    return _enabled


def clear_spans() -> None:
    with _lock:
        _spans.clear()


def set_sample_rate(rate: Optional[float]) -> None:
    """Override the head-of-trace sampling rate (None = re-resolve from
    env/config on next use). Tests and the overhead bench use this."""
    global _sample_rate
    _sample_rate = None if rate is None else max(0.0, min(1.0, float(rate)))


def sample_rate() -> float:
    """The head-of-trace sampling probability (``RAY_TPU_TRACE_SAMPLE_RATE``
    env var / ``trace_sample_rate`` config flag; default 1.0 — every
    trace records once tracing is enabled). Resolved lazily and cached."""
    global _sample_rate
    rate = _sample_rate
    if rate is None:
        raw = os.environ.get("RAY_TPU_TRACE_SAMPLE_RATE")
        if raw is None:
            raw = os.environ.get("RAY_TPU_trace_sample_rate")
        try:
            rate = float(raw) if raw is not None else 1.0
        except ValueError:
            rate = 1.0
        rate = max(0.0, min(1.0, rate))
        _sample_rate = rate
    return rate


def _draw_sampled() -> bool:
    rate = sample_rate()
    if rate >= 1.0:
        return True
    if rate <= 0.0:
        return False
    return random.random() < rate


def current_span() -> Optional[Span]:
    span = getattr(_state, "span", None)
    return None if span is _UNSAMPLED else span


def _record(span: Span) -> None:
    with _lock:
        if len(_spans) < _MAX_SPANS:
            _spans.append(span)


def _new_span(name: str, trace_id: str, parent_id: Optional[str],
              attributes: Optional[Dict[str, Any]] = None) -> Span:
    return Span(
        name=name,
        trace_id=trace_id,
        span_id=uuid.uuid4().hex[:8],
        parent_id=parent_id,
        start_time=time.time(),
        attributes=dict(attributes or {}),
        _mono=time.monotonic(),
    )


@contextlib.contextmanager
def start_span(name: str, attributes: Optional[Dict[str, Any]] = None):
    """Open a span as the thread's active context; nested spans (and remote
    tasks submitted inside) are parented to it. A ROOT span (no active
    parent) makes the head-of-trace sampling decision; the verdict sticks
    for everything nested under it."""
    if not _enabled:
        yield None
        return
    prev = getattr(_state, "span", None)
    if prev is _UNSAMPLED:
        yield None
        return
    if prev is None and not _draw_sampled():
        _state.span = _UNSAMPLED
        try:
            yield None
        finally:
            _state.span = None
        return
    span = _new_span(
        name,
        trace_id=prev.trace_id if prev else uuid.uuid4().hex[:16],
        parent_id=prev.span_id if prev else None,
        attributes=attributes,
    )
    _record(span)
    _state.span = span
    try:
        yield span
    finally:
        span.end()
        _state.span = prev


def inject_context() -> Optional[Dict[str, Any]]:
    """Serialize the active span context for a task spec (the reference's
    _DictPropagator.inject_current_context). With no active span this IS
    the head of a trace: the sampling decision is made here, once, and an
    unsampled draw returns None — remote hops then pay one attribute read
    and nothing else."""
    if not _enabled:
        return None
    span = getattr(_state, "span", None)
    if span is _UNSAMPLED:
        return None
    if span is not None:
        return {"trace_id": span.trace_id, "parent_id": span.span_id,
                "sampled": True}
    if not _draw_sampled():
        return None
    return {"trace_id": uuid.uuid4().hex[:16], "parent_id": None,
            "sampled": True}


def span_context(span: Optional[Span]) -> Optional[Dict[str, Any]]:
    """A propagation context parented to ``span`` (for threading a
    specific span — e.g. the driver-submit span — into a wire message
    without touching thread-local state)."""
    if span is None:
        return None
    return {"trace_id": span.trace_id, "parent_id": span.span_id,
            "sampled": True}


def _ctx_sampled(ctx: Optional[Dict[str, Any]]) -> bool:
    # Contexts from pre-sampling peers carry no flag: treat as sampled
    # (they were only injected when tracing was on).
    return bool(ctx) and bool(ctx.get("sampled", True))


@contextlib.contextmanager
def continue_context(ctx: Optional[Dict[str, Any]], name: str,
                     attributes: Optional[Dict[str, Any]] = None):
    """Worker-side: run a task under the caller's trace context.

    Deliberately NOT gated on the local ``_enabled`` flag: a carried
    sampled context IS the enablement signal — the driver made the
    decision, and daemons/workers (where enable_tracing was never
    called) record purely because the request asked them to."""
    if not _ctx_sampled(ctx):
        yield None
        return
    span = _new_span(name, trace_id=ctx["trace_id"],
                     parent_id=ctx.get("parent_id"),
                     attributes=attributes)
    _record(span)
    prev = getattr(_state, "span", None)
    _state.span = span
    try:
        yield span
    finally:
        span.end()
        _state.span = prev


def record_complete_span(name: str, ctx: Optional[Dict[str, Any]], *,
                         wall_start: float, duration: float,
                         attributes: Optional[Dict[str, Any]] = None
                         ) -> Optional[Span]:
    """Record an already-finished span under ``ctx`` retroactively —
    for stages measured across callbacks (queue wait, result store)
    where no ``with`` block brackets the interval. ``wall_start`` is the
    anchor; ``duration`` must come from monotonic deltas. Like
    continue_context, gated on the context alone, not ``_enabled``."""
    if not _ctx_sampled(ctx):
        return None
    duration = max(0.0, float(duration))
    span = Span(
        name=name,
        trace_id=ctx["trace_id"],
        span_id=uuid.uuid4().hex[:8],
        parent_id=ctx.get("parent_id"),
        start_time=wall_start,
        end_time=wall_start + duration,
        duration=duration,
        attributes=dict(attributes or {}),
    )
    _record(span)
    return span


@contextlib.contextmanager
def child_span(name: str, attributes: Optional[Dict[str, Any]] = None):
    """A span recorded ONLY under an active sampled parent (data-plane
    helpers like object pulls: traced when a traced task triggers them,
    free when nothing is tracing this request). The parent — not the
    local ``_enabled`` flag — is the gate, so pulls inside a propagated
    remote span record too."""
    parent = current_span()
    if parent is None:
        yield None
        return
    span = _new_span(name, trace_id=parent.trace_id,
                     parent_id=parent.span_id, attributes=attributes)
    _record(span)
    prev = parent
    _state.span = span
    try:
        yield span
    finally:
        span.end()
        _state.span = prev


def drain_finished_spans(cursor: int = 0) -> tuple:
    """Ended, not-yet-shipped spans at or after ``cursor``, as plain
    dicts, plus the new cursor (the metrics agent's incremental export:
    spans ride ``metrics_batch`` frames to the head so /api/timeline can
    render cross-process task spans). Open spans are left in place and
    revisited on the next drain; the cursor only advances past the prefix
    whose spans are all shipped."""
    out: List[Dict[str, Any]] = []
    with _lock:
        cursor = max(0, min(cursor, len(_spans)))
        new_cursor = cursor
        advancing = True
        for i in range(cursor, len(_spans)):
            span = _spans[i]
            if span.end_time is None:
                advancing = False
            elif not span.shipped:
                span.shipped = True
                out.append(span.to_dict())
            if advancing:
                new_cursor = i + 1
    return out, new_cursor


def get_spans(trace_id: Optional[str] = None) -> List[Span]:
    with _lock:
        spans = list(_spans)
    if trace_id is not None:
        spans = [s for s in spans if s.trace_id == trace_id]
    return spans


def export_chrome_trace() -> List[Dict[str, Any]]:
    """Spans as chrome://tracing complete events (merges into the timeline
    the state API already emits)."""
    out = []
    for s in get_spans():
        dur = s.duration if s.duration is not None else 0.0
        out.append({
            "name": s.name,
            "cat": "trace",
            "ph": "X",
            "ts": s.start_time * 1e6,
            "dur": dur * 1e6,
            "pid": s.trace_id,
            "tid": s.span_id,
            "args": s.attributes,
        })
    return out
