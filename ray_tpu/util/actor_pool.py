"""ActorPool: distribute work over a fixed set of actors.

Analog of the reference's python/ray/util/actor_pool.py (same public
surface: map / map_unordered / submit / get_next / get_next_unordered /
push / pop_idle / has_free / has_next).
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, Iterator, List, Optional

import ray_tpu


class ActorPool:
    def __init__(self, actors: List[Any]):
        self._idle = list(actors)
        self._future_to_actor = {}
        self._index_to_future = {}
        self._next_task_index = 0
        self._next_return_index = 0

    def map(self, fn: Callable[[Any, Any], Any], values: Iterable[Any]
            ) -> Iterator[Any]:
        """fn(actor, value) -> ObjectRef; yields results in order."""
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next()

    def map_unordered(self, fn, values) -> Iterator[Any]:
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next_unordered()

    def submit(self, fn, value) -> None:
        if self._idle:
            actor = self._idle.pop()
            future = fn(actor, value)
            self._future_to_actor[future] = (self._next_task_index, actor)
            self._index_to_future[self._next_task_index] = future
            self._next_task_index += 1
        else:
            # Wait for any in-flight task, recycle its actor, retry.
            ready, _ = ray_tpu.wait(list(self._future_to_actor),
                                    num_returns=1)
            self._return_actor(ready[0])
            self.submit(fn, value)

    def _return_actor(self, future) -> None:
        _, actor = self._future_to_actor[future]
        self._idle.append(actor)

    def has_next(self) -> bool:
        return bool(self._index_to_future)

    def get_next(self, timeout: Optional[float] = None) -> Any:
        if not self.has_next():
            raise StopIteration("No more results to get")
        future = self._index_to_future.pop(self._next_return_index)
        self._next_return_index += 1
        res = ray_tpu.get([future], timeout=timeout)[0]
        idx, actor = self._future_to_actor.pop(future)
        self._idle.append(actor)
        return res

    def get_next_unordered(self, timeout: Optional[float] = None) -> Any:
        if not self.has_next():
            raise StopIteration("No more results to get")
        ready, _ = ray_tpu.wait(list(self._future_to_actor), num_returns=1,
                                timeout=timeout)
        if not ready:
            raise TimeoutError("Timed out waiting for result")
        future = ready[0]
        idx, actor = self._future_to_actor.pop(future)
        self._index_to_future.pop(idx, None)
        self._idle.append(actor)
        return ray_tpu.get([future])[0]

    def has_free(self) -> bool:
        return bool(self._idle)

    def push(self, actor) -> None:
        self._idle.append(actor)

    def pop_idle(self) -> Optional[Any]:
        return self._idle.pop() if self._idle else None
