"""Distributed LightGBM on ray_tpu (analog of the lightgbm_ray package
in the reference ecosystem: RayDMatrix / RayParams / train / predict
over Ray actors; lightgbm_ray/main.py wires LightGBM's socket-based
parallel learner across the actors).

LightGBM's native distribution is peer-to-peer: every worker gets the
full ``machines`` list (ip:port per worker) and LightGBM's own
collective does the feature-histogram reduce-scatter. ``train`` here
allocates one port per ray_tpu actor, fans the machines list out, and
every actor runs ``lgb.train`` on its row shard — exact distributed
boosting, not bagging. lightgbm itself is not bundled; entry points
raise a clear ImportError without it, and the orchestration is
backend-injectable for the dependency-free unit tests.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.util.xgboost import RayDMatrix, RayParams  # shared shapes

__all__ = ["RayDMatrix", "RayParams", "train", "predict"]


def _advertise_ip() -> str:
    """The address peers can actually reach this worker on.
    gethostbyname(gethostname()) resolves to 127.0.1.1 on stock
    Debian/Ubuntu — peers would connect to themselves; a routing-table
    probe (same trick as the daemon control plane's getsockname)
    yields the outbound interface instead."""
    import socket
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        s.connect(("10.255.255.255", 1))
        return s.getsockname()[0]
    except OSError:
        return "127.0.0.1"
    finally:
        s.close()


def _require_lightgbm():
    try:
        import lightgbm
        return lightgbm
    except ImportError as exc:
        raise ImportError(
            "ray_tpu.util.lightgbm needs the lightgbm package, which "
            "is not installed in this environment.") from exc


class _LGBShardActor:
    """One training worker: joins LightGBM's socket collective and
    boosts on its shard (lightgbm_ray's RayLightGBMActor analog)."""

    def __init__(self, shard, dmatrix_kwargs, backend=None):
        self._X, self._y = shard
        self._dmatrix_kwargs = dmatrix_kwargs
        self._backend = backend
        self._booster = None

    def port(self) -> Tuple[str, int]:
        import socket
        s = socket.socket()
        s.bind(("", 0))
        self._sock = s  # held open: reserves the port until train
        return (_advertise_ip(), s.getsockname()[1])

    def train(self, params: dict, num_boost_round: int,
              machines: str, rank: int, num_machines: int):
        try:
            self._sock.close()  # LightGBM rebinds it
        except Exception:  # noqa: BLE001
            pass
        backend = self._backend or _LGBBackend()
        self._booster, result = backend.train_shard(
            dict(params, machines=machines,
                 num_machines=num_machines,
                 local_listen_port=int(machines.split(",")[rank]
                                       .split(":")[1]),
                 tree_learner=params.get("tree_learner", "data")),
            self._X, self._y, self._dmatrix_kwargs, num_boost_round)
        return result

    def predict(self, model_str: Optional[str] = None):
        backend = self._backend or _LGBBackend()
        booster = (backend.load(model_str) if model_str is not None
                   else self._booster)
        return backend.predict_shard(booster, self._X)

    def get_model(self) -> str:
        backend = self._backend or _LGBBackend()
        return backend.dump(self._booster)


class _LGBBackend:
    """The real lightgbm calls, isolated so tests can inject a fake."""

    def train_shard(self, params, X, y, dataset_kwargs,
                    num_boost_round):
        lgb = _require_lightgbm()
        dtrain = lgb.Dataset(X, label=y, **dataset_kwargs)
        evals: Dict[str, Any] = {}
        booster = lgb.train(params, dtrain,
                            num_boost_round=num_boost_round)
        return booster, evals

    def predict_shard(self, booster, X):
        return booster.predict(X)

    def dump(self, booster) -> str:
        return booster.model_to_string()

    def load(self, model_str: str):
        lgb = _require_lightgbm()
        return lgb.Booster(model_str=model_str)


def train(params: dict, dtrain: RayDMatrix, *,
          num_boost_round: int = 10,
          ray_params: Optional[RayParams] = None,
          _backend=None):
    """Exact distributed boosting over ray_tpu actors (lightgbm_ray
    train() parity subset)."""
    import ray_tpu
    rp = ray_params or RayParams()
    n = max(1, int(rp.num_actors))
    shards = dtrain.shards(n)
    n = len(shards)
    backend = _backend or _LGBBackend()
    actor_cls = ray_tpu.remote(num_cpus=rp.cpus_per_actor,
                               resources=rp.resources_per_actor,
                               max_restarts=rp.max_actor_restarts)(
        _LGBShardActor)
    actors = [actor_cls.remote(shard, dtrain.dmatrix_kwargs, _backend)
              for shard in shards]
    try:
        addrs = ray_tpu.get([a.port.remote() for a in actors])
        machines = ",".join(f"{h}:{p}" for h, p in addrs)
        results = ray_tpu.get([
            a.train.remote(params, num_boost_round, machines, rank, n)
            for rank, a in enumerate(actors)])
        del results
        model_str = ray_tpu.get(actors[0].get_model.remote())
        return backend.load(model_str)
    finally:
        for a in actors:
            ray_tpu.kill(a)


def predict(model, data: RayDMatrix, *,
            ray_params: Optional[RayParams] = None,
            _backend=None):
    """Sharded prediction over ray_tpu actors; concatenates row-wise."""
    import numpy as np

    import ray_tpu
    rp = ray_params or RayParams()
    shards = data.shards(max(1, int(rp.num_actors)))
    backend = _backend or _LGBBackend()
    model_str = backend.dump(model)
    actor_cls = ray_tpu.remote(num_cpus=rp.cpus_per_actor,
                               resources=rp.resources_per_actor)(
        _LGBShardActor)
    actors = [actor_cls.remote(shard, data.dmatrix_kwargs, _backend)
              for shard in shards]
    try:
        parts = ray_tpu.get([a.predict.remote(model_str)
                             for a in actors])
        return np.concatenate([np.asarray(p) for p in parts])
    finally:
        for a in actors:
            ray_tpu.kill(a)
