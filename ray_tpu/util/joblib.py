"""joblib backend over the task runtime.

Analog of the reference's util/joblib/: ``register_ray()`` installs a
joblib parallel backend whose batches run as cluster tasks, so
``with joblib.parallel_backend("ray_tpu"): Parallel()(delayed(f)(x) ...)``
fans out across the cluster.
"""

from __future__ import annotations

import threading

import ray_tpu


def register_ray() -> None:
    """Register the 'ray_tpu' joblib backend (import-gated)."""
    from joblib import register_parallel_backend
    from joblib._parallel_backends import ParallelBackendBase

    class _AsyncBatchResult:
        """Future-like handle joblib polls: the batch runs as a task; a
        watcher thread fires joblib's callback on completion (joblib's
        retrieval protocol requires the callback to be asynchronous)."""

        def __init__(self, ref, callback):
            self._ref = ref
            self._event = threading.Event()
            self._result = None
            self._error = None

            def watch():
                try:
                    self._result = ray_tpu.get(ref)
                except BaseException as exc:  # noqa: BLE001
                    self._error = exc
                finally:
                    self._event.set()
                    if callback is not None:
                        callback(self)

            threading.Thread(target=watch, daemon=True).start()

        def get(self, timeout=None):
            if not self._event.wait(timeout):
                raise TimeoutError("joblib batch timed out")
            if self._error is not None:
                raise self._error
            return self._result

    class RayTpuBackend(ParallelBackendBase):
        supports_timeout = True
        uses_threads = False
        supports_sharedmem = False
        supports_retrieve_callback = True

        def configure(self, n_jobs=1, parallel=None, **backend_args):
            self.parallel = parallel
            return self.effective_n_jobs(n_jobs)

        def effective_n_jobs(self, n_jobs):
            if n_jobs == 1:
                return 1
            cpus = int(ray_tpu.cluster_resources().get("CPU", 1)) or 1
            return cpus if n_jobs in (-1, None) else n_jobs

        def apply_async(self, func, callback=None):
            task = ray_tpu.remote(lambda: func())
            return _AsyncBatchResult(task.remote(), callback)

        def retrieve_result_callback(self, out):
            return out.get()

        def abort_everything(self, ensure_ready=True):
            if ensure_ready:
                self.configure(n_jobs=self.parallel.n_jobs,
                               parallel=self.parallel)

    register_parallel_backend("ray_tpu", RayTpuBackend)
