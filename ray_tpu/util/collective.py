"""Host-driven collective communication across actors/tasks.

API-compatible analog of the reference's `ray.util.collective`
(python/ray/util/collective/collective.py:258-655: init_collective_group /
allreduce / broadcast / allgather / reducescatter / barrier / send / recv).

The backend story is TPU-first (SURVEY.md §2.4): *inside* a jitted program,
collectives are XLA ICI collectives (psum/all_gather — see parallel/ and
ops/ring_attention.py) and never touch this module. This module covers the
reference's *host-driven* use case — actors exchanging arrays outside jit —
which the reference backs with NCCL/Gloo process groups. Here the rendezvous
point is a named coordinator actor (the same pattern the reference uses to
exchange the NCCL unique id), and the reduction itself runs in jax on the
contributing host.

Data path: the coordinator actor carries only CONTROL state for large
payloads — arrays above ``_INLINE_LIMIT`` travel as ObjectRefs through
the object store / node-to-node data plane, and ``allreduce`` switches to
a bandwidth-optimal ring (scatter-reduce + allgather, the NCCL
algorithm): each rank moves 2*(world-1)/world of its bytes to a single
neighbor, instead of every rank's full array funneling through one
coordinator process (O(world x bytes) there — the round-1 design).
Small arrays keep the one-hop star path, which has lower latency.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

import numpy as np

import ray_tpu

_COORD_NAME = "_ray_tpu_collective_coordinator"
_local = threading.local()  # per-worker-thread group registry

# Payloads above this go through the object store as refs (the
# coordinator only sees the ref); below it, inline via the coordinator
# (one hop beats put+get for small arrays). Tests may lower it to force
# the ring path on tiny arrays.
_INLINE_LIMIT = 1 << 19  # 512 KiB


class _Coordinator:
    """Async rendezvous actor: collects one contribution per rank, computes
    the collective result once, and hands it to every waiter."""

    def __init__(self):
        import asyncio
        self._rounds: Dict[str, dict] = {}
        self._lock = asyncio.Lock()

    async def contribute(self, key: str, rank: int, world: int, data,
                         combine: str):
        import asyncio
        async with self._lock:
            st = self._rounds.get(key)
            if st is None:
                st = {"parts": {}, "event": asyncio.Event(), "result": None,
                      "consumed": 0}
                self._rounds[key] = st
            st["parts"][rank] = data
            if len(st["parts"]) == world:
                st["result"] = _combine(st["parts"], world, combine)
                st["event"].set()
        await st["event"].wait()
        async with self._lock:
            st["consumed"] += 1
            result = st["result"]
            if st["consumed"] == world:
                del self._rounds[key]
        return result

    async def put_p2p(self, key: str, data):
        import asyncio
        async with self._lock:
            st = self._rounds.get(key)
            if st is None:
                st = {"parts": {}, "event": asyncio.Event(), "result": None,
                      "consumed": 0}
                self._rounds[key] = st
            st["result"] = data
            st["event"].set()
        return True

    async def get_p2p(self, key: str):
        import asyncio
        async with self._lock:
            st = self._rounds.get(key)
            if st is None:
                st = {"parts": {}, "event": asyncio.Event(), "result": None,
                      "consumed": 0}
                self._rounds[key] = st
        await st["event"].wait()
        async with self._lock:
            result = st["result"]
            del self._rounds[key]
        return result


def _combine(parts: Dict[int, Any], world: int, combine: str):
    ordered = [parts[r] for r in range(world)]
    if combine == "gather":
        return ordered
    if combine in ("sum", "product", "min", "max"):
        import jax.numpy as jnp
        op = {"sum": jnp.add, "product": jnp.multiply,
              "min": jnp.minimum, "max": jnp.maximum}[combine]
        acc = jnp.asarray(ordered[0])
        for p in ordered[1:]:
            acc = op(acc, jnp.asarray(p))
        return np.asarray(acc)
    if combine == "barrier":
        return None
    raise ValueError(combine)


class ReduceOp:
    SUM = "sum"
    PRODUCT = "product"
    MIN = "min"
    MAX = "max"


class _GroupState:
    __slots__ = ("world_size", "rank", "round_ids", "p2p_live")

    def __init__(self, world_size: int, rank: int):
        self.world_size = world_size
        self.rank = rank
        self.round_ids: Dict[str, int] = {}
        # Per-channel keep-alive for refs sent out-of-band (see send()):
        # the sender must pin each object until the receiver resolves it.
        # A window of `world_size` rounds per channel is provably enough:
        # the ring is a cycle, so a send of round k on any channel
        # requires recvs that transitively require the same channel's
        # round k-(world-1) having been consumed.
        self.p2p_live: Dict[str, Any] = {}

    def next_round(self, op: str) -> int:
        n = self.round_ids.get(op, 0)
        self.round_ids[op] = n + 1
        return n


def _groups() -> Dict[str, _GroupState]:
    if not hasattr(_local, "groups"):
        _local.groups = {}
    return _local.groups


def _coordinator():
    try:
        return ray_tpu.get_actor(_COORD_NAME)
    except ValueError:
        coord_cls = ray_tpu.remote(_Coordinator)
        return coord_cls.options(name=_COORD_NAME,
                                 get_if_exists=True).remote()


def init_collective_group(world_size: int, rank: int,
                          backend: str = "tpu",
                          group_name: str = "default") -> None:
    """Each participant calls this once with its rank (reference:
    collective.py:151 imperative path). Registry is per worker thread —
    actors with max_concurrency=1 (the default) are safe."""
    if rank >= world_size:
        raise ValueError(f"rank {rank} >= world_size {world_size}")
    _coordinator()  # ensure it exists before the first collective
    _groups()[group_name] = _GroupState(world_size, rank)


def destroy_collective_group(group_name: str = "default") -> None:
    _groups().pop(group_name, None)


def is_group_initialized(group_name: str = "default") -> bool:
    return group_name in _groups()


def get_rank(group_name: str = "default") -> int:
    g = _groups().get(group_name)
    return -1 if g is None else g.rank


def get_collective_group_size(group_name: str = "default") -> int:
    g = _groups().get(group_name)
    return -1 if g is None else g.world_size


def _run(group_name: str, op: str, data, combine: str):
    g = _groups().get(group_name)
    if g is None:
        raise RuntimeError(
            f"Collective group {group_name!r} is not initialized on this "
            "worker; call init_collective_group first")
    rnd = g.next_round(op)
    key = f"{group_name}:{op}:{rnd}"
    coord = _coordinator()
    return ray_tpu.get(
        coord.contribute.remote(key, g.rank, g.world_size, data, combine))


def _apply_op(a: np.ndarray, b: np.ndarray, op: str) -> np.ndarray:
    return {"sum": np.add, "product": np.multiply,
            "min": np.minimum, "max": np.maximum}[op](a, b)


def _ring_allreduce(g: _GroupState, group_name: str, arr: np.ndarray,
                    op: str) -> np.ndarray:
    """Ring allreduce over the P2P channels (payloads ride the object
    data plane): world-1 scatter-reduce steps, then world-1 allgather
    steps. Per-rank traffic is 2*(world-1)/world * nbytes to ONE
    neighbor — no process ever holds more than its own array plus one
    chunk (reference algorithm: NCCL ring / Baidu allreduce)."""
    world, rank = g.world_size, g.rank
    nxt, prv = (rank + 1) % world, (rank - 1) % world
    flat = np.ascontiguousarray(arr).reshape(-1)
    # Views, not copies: chunks are only rebound (_apply_op allocates its
    # result), never mutated in place.
    chunks = list(np.array_split(flat, world))
    # Scatter-reduce: after step s, rank r owns the full reduction of
    # chunk (r - s) mod world over ranks r-s..r.
    idx = rank
    for _ in range(world - 1):
        send(chunks[idx], nxt, group_name)
        idx = (idx - 1) % world
        chunks[idx] = _apply_op(chunks[idx], recv(prv, group_name), op)
    # Allgather: circulate each fully-reduced chunk around the ring.
    idx = (rank + 1) % world
    for _ in range(world - 1):
        send(chunks[idx], nxt, group_name)
        idx = (idx - 1) % world
        chunks[idx] = recv(prv, group_name)
    return np.concatenate(chunks).reshape(arr.shape).astype(
        arr.dtype, copy=False)


def allreduce(tensor, group_name: str = "default",
              op: str = ReduceOp.SUM):
    """Returns the reduced array (the reference mutates in place; jax arrays
    are immutable, so the result is returned)."""
    arr = np.asarray(tensor)
    g = _groups().get(group_name)
    if (g is not None and g.world_size > 1 and op in ("sum", "product",
                                                      "min", "max")
            and arr.nbytes > _INLINE_LIMIT):
        return _ring_allreduce(g, group_name, arr, op)
    return _run(group_name, f"allreduce-{op}", arr, op)


def allgather(tensor, group_name: str = "default") -> List[Any]:
    return _run(group_name, "allgather", np.asarray(tensor), "gather")


def broadcast(tensor, src_rank: int = 0, group_name: str = "default"):
    g = _groups().get(group_name)
    if g is None:
        raise RuntimeError(f"group {group_name!r} not initialized")
    parts = _run(group_name, "broadcast",
                 np.asarray(tensor) if g.rank == src_rank else None,
                 "gather")
    return parts[src_rank]


def reduce(tensor, dst_rank: int = 0, group_name: str = "default",
           op: str = ReduceOp.SUM):
    g = _groups().get(group_name)
    result = _run(group_name, f"reduce-{op}", np.asarray(tensor), op)
    return result if g.rank == dst_rank else tensor


def reducescatter(tensor, group_name: str = "default",
                  op: str = ReduceOp.SUM):
    """Reduce then return this rank's 1/world slice along axis 0."""
    g = _groups().get(group_name)
    full = _run(group_name, f"reducescatter-{op}", np.asarray(tensor), op)
    chunks = np.array_split(full, g.world_size, axis=0)
    return chunks[g.rank]


def barrier(group_name: str = "default") -> None:
    _run(group_name, "barrier", None, "barrier")


def send(tensor, dst_rank: int, group_name: str = "default") -> None:
    g = _groups().get(group_name)
    if g is None:
        raise RuntimeError(f"group {group_name!r} not initialized")
    chan = f"p2p-{g.rank}-{dst_rank}"
    n = g.round_ids.get(chan, 0)
    g.round_ids[chan] = n + 1
    key = f"{group_name}:p2p:{g.rank}->{dst_rank}:{n}"
    arr = np.asarray(tensor)
    if arr.nbytes > _INLINE_LIMIT:
        # Large payload: only the ObjectRef goes through the coordinator;
        # the bytes move sender-store -> receiver over the object data
        # plane when recv() resolves the ref. The ref is NESTED in a
        # marker dict — a top-level ObjectRef argument would be
        # dependency-resolved into the materialized array before the
        # coordinator method runs, putting all bytes back through it.
        # Nested refs are not runtime-pinned, so the sender keeps a
        # handle alive for a window of world_size rounds per channel
        # (see _GroupState.p2p_live for why that bound is safe).
        ref = ray_tpu.put(arr)
        from collections import deque
        live = g.p2p_live.setdefault(
            chan, deque(maxlen=max(g.world_size, 2)))
        live.append(ref)
        payload: Any = {"__collective_ref__": [ref]}
    else:
        payload = arr
    ray_tpu.get(_coordinator().put_p2p.remote(key, payload))


def recv(src_rank: int, group_name: str = "default"):
    g = _groups().get(group_name)
    if g is None:
        raise RuntimeError(f"group {group_name!r} not initialized")
    n = g.round_ids.get(f"p2p-{src_rank}-{g.rank}", 0)
    g.round_ids[f"p2p-{src_rank}-{g.rank}"] = n + 1
    key = f"{group_name}:p2p:{src_rank}->{g.rank}:{n}"
    value = ray_tpu.get(_coordinator().get_p2p.remote(key))
    if isinstance(value, dict) and "__collective_ref__" in value:
        # Out-of-band payload: resolve over the data plane, not the
        # coordinator (see send()).
        value = ray_tpu.get(value["__collective_ref__"][0])
    return value


def create_collective_group(actors: List[Any], world_size: int,
                            ranks: List[int], backend: str = "tpu",
                            group_name: str = "default"):
    """Declarative setup (reference: collective.py:151): initializes the
    group on each actor by invoking its ``init_collective_group`` method if
    it has one, else an injected generic call is required from the actor
    itself."""
    refs = []
    for actor, rank in zip(actors, ranks):
        refs.append(actor.init_collective_group.remote(
            world_size, rank, backend, group_name))
    return ray_tpu.get(refs)
