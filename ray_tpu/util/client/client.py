"""Thin client: drive a remote cluster over ``ray://host:port``.

Analog of the reference's util/client/worker.py + client_builder.py: the
client pickles functions/classes to the server-side driver and holds
ClientObjectRef/ClientActorHandle stubs; get/put/wait/kill proxy over the
socket protocol (server.py).
"""

from __future__ import annotations

import socket
import threading
from typing import Any, Dict, List, Optional

from ray_tpu.util.client.server import _recv, _send


class ClientObjectRef:
    __slots__ = ("_hex", "_client")

    def __init__(self, hex_id: str, client: "RayTpuClient"):
        self._hex = hex_id
        self._client = client

    def hex(self) -> str:
        return self._hex

    def __repr__(self):
        return f"ClientObjectRef({self._hex})"

    def __hash__(self):
        return hash(self._hex)

    def __eq__(self, other):
        return isinstance(other, ClientObjectRef) and \
            other._hex == self._hex


class ClientActorHandle:
    def __init__(self, actor_id: str, client: "RayTpuClient"):
        self._actor_id = actor_id
        self._client = client

    def __getattr__(self, method: str):
        if method.startswith("_"):
            raise AttributeError(method)
        return _ClientMethod(self, method)


class _ClientMethod:
    def __init__(self, handle: ClientActorHandle, method: str):
        self._handle = handle
        self._method = method

    def remote(self, *args, **kwargs) -> ClientObjectRef:
        client = self._handle._client
        reply = client._call({"op": "actor_call",
                              "actor": self._handle._actor_id,
                              "method": self._method,
                              "args": args, "kwargs": kwargs})
        return ClientObjectRef(reply["ref"], client)


class _ClientRemoteFunction:
    def __init__(self, fn, client: "RayTpuClient",
                 options: Optional[Dict[str, Any]] = None):
        self._fn = fn
        self._client = client
        self._options = options

    def options(self, **opts) -> "_ClientRemoteFunction":
        return _ClientRemoteFunction(self._fn, self._client, opts)

    def remote(self, *args, **kwargs) -> ClientObjectRef:
        wire_args = ["\0" + a.hex() if isinstance(a, ClientObjectRef)
                     else a for a in args]
        reply = self._client._call({
            "op": "task", "fn": self._fn, "args": wire_args,
            "kwargs": kwargs, "options": self._options})
        return ClientObjectRef(reply["ref"], self._client)


class _ClientRemoteClass:
    def __init__(self, cls, client: "RayTpuClient",
                 options: Optional[Dict[str, Any]] = None):
        self._cls = cls
        self._client = client
        self._options = options

    def options(self, **opts) -> "_ClientRemoteClass":
        return _ClientRemoteClass(self._cls, self._client, opts)

    def remote(self, *args, **kwargs) -> ClientActorHandle:
        reply = self._client._call({
            "op": "actor_create", "cls": self._cls, "args": args,
            "kwargs": kwargs, "options": self._options})
        return ClientActorHandle(reply["actor"], self._client)


class RayTpuClient:
    def __init__(self, host: str, port: int, timeout: float = 30.0):
        self._sock = socket.create_connection((host, port), timeout=timeout)
        self._sock.settimeout(None)
        self._lock = threading.Lock()
        reply = self._call({"op": "ping"})
        self.server_version = reply["version"]

    def _call(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        import cloudpickle
        with self._lock:
            _send(self._sock, cloudpickle.dumps(msg))
            raw = _recv(self._sock)
        if raw is None:
            raise ConnectionError("Client server closed the connection")
        reply = cloudpickle.loads(raw)
        if "error" in reply:
            raise reply["error"]
        return reply

    # -- API mirroring the top-level surface ------------------------------

    def remote(self, fn_or_class):
        import inspect
        if inspect.isclass(fn_or_class):
            return _ClientRemoteClass(fn_or_class, self)
        return _ClientRemoteFunction(fn_or_class, self)

    def put(self, value: Any) -> ClientObjectRef:
        return ClientObjectRef(self._call({"op": "put",
                                           "value": value})["ref"], self)

    def get(self, refs, timeout: Optional[float] = None):
        single = isinstance(refs, ClientObjectRef)
        ref_list = [refs] if single else list(refs)
        values = self._call({"op": "get",
                             "refs": [r.hex() for r in ref_list],
                             "timeout": timeout})["values"]
        return values[0] if single else values

    def wait(self, refs: List[ClientObjectRef], num_returns: int = 1,
             timeout: Optional[float] = None):
        reply = self._call({"op": "wait",
                            "refs": [r.hex() for r in refs],
                            "num_returns": num_returns,
                            "timeout": timeout})
        by_hex = {r.hex(): r for r in refs}
        return ([by_hex[h] for h in reply["ready"]],
                [by_hex[h] for h in reply["pending"]])

    def kill(self, handle: ClientActorHandle) -> None:
        self._call({"op": "actor_kill", "actor": handle._actor_id})

    def cluster_resources(self) -> Dict[str, float]:
        return self._call({"op": "cluster_resources"})["resources"]

    def disconnect(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass


ClientAPI = RayTpuClient


def connect(address: str) -> RayTpuClient:
    """Connect to a client server. Accepts 'host:port' or
    'ray://host:port'."""
    if address.startswith("ray://"):
        address = address[len("ray://"):]
    host, _, port = address.partition(":")
    return RayTpuClient(host or "127.0.0.1", int(port or 10001))
