"""Client server: hosts remote drivers over a socket protocol.

Analog of the reference's util/client/server (server.py:96 RayletServicer):
a driver process runs this server; thin clients connect over TCP and
proxy put/get/task/actor calls into the server's runtime. Frames are
length-prefixed cloudpickle messages (the reference uses gRPC; the wire
format differs, the capability — remote drivers against a live cluster —
is the same).

SECURITY: the protocol executes pickled callables from connected clients,
exactly like the reference's Ray Client; bind only on trusted interfaces.
"""

from __future__ import annotations

import socket
import socketserver
import struct
import threading
from typing import Any, Dict, Optional

import ray_tpu


def _send(sock: socket.socket, payload: bytes) -> None:
    sock.sendall(struct.pack("<Q", len(payload)) + payload)


def _recv(sock: socket.socket) -> Optional[bytes]:
    header = b""
    while len(header) < 8:
        chunk = sock.recv(8 - len(header))
        if not chunk:
            return None
        header += chunk
    (length,) = struct.unpack("<Q", header)
    data = b""
    while len(data) < length:
        chunk = sock.recv(min(1 << 20, length - len(data)))
        if not chunk:
            return None
        data += chunk
    return data


class _Session:
    """Per-connection state: refs and actors the client knows by id."""

    def __init__(self):
        self.refs: Dict[str, Any] = {}
        self.actors: Dict[str, Any] = {}


class _Handler(socketserver.BaseRequestHandler):
    def handle(self):
        import cloudpickle
        session = _Session()
        while True:
            raw = _recv(self.request)
            if raw is None:
                return
            try:
                msg = cloudpickle.loads(raw)
                reply = self._dispatch(session, msg)
            except BaseException as exc:  # noqa: BLE001 - ship to client
                reply = {"error": exc}
            _send(self.request, cloudpickle.dumps(reply))

    def _dispatch(self, session: _Session, msg: Dict[str, Any]
                  ) -> Dict[str, Any]:
        op = msg["op"]
        if op == "ping":
            return {"ok": True, "version": ray_tpu.__version__}
        if op == "put":
            ref = ray_tpu.put(msg["value"])
            session.refs[ref.hex()] = ref
            return {"ref": ref.hex()}
        if op == "get":
            refs = [session.refs[h] for h in msg["refs"]]
            return {"values": ray_tpu.get(refs, timeout=msg.get("timeout"))}
        if op == "wait":
            refs = [session.refs[h] for h in msg["refs"]]
            ready, pending = ray_tpu.wait(
                refs, num_returns=msg["num_returns"],
                timeout=msg.get("timeout"))
            return {"ready": [r.hex() for r in ready],
                    "pending": [r.hex() for r in pending]}
        if op == "task":
            fn = msg["fn"]
            args = [session.refs[a[1:]] if isinstance(a, str)
                    and a.startswith("\0") else a for a in msg["args"]]
            options = msg.get("options") or {}
            remote_fn = ray_tpu.remote(fn)
            if options:
                remote_fn = remote_fn.options(**options)
            ref = remote_fn.remote(*args, **msg.get("kwargs", {}))
            session.refs[ref.hex()] = ref
            return {"ref": ref.hex()}
        if op == "actor_create":
            cls = msg["cls"]
            options = msg.get("options") or {}
            remote_cls = ray_tpu.remote(cls)
            if options:
                remote_cls = remote_cls.options(**options)
            handle = remote_cls.remote(*msg.get("args", ()),
                                       **msg.get("kwargs", {}))
            actor_id = handle._actor_id.hex()
            session.actors[actor_id] = handle
            return {"actor": actor_id}
        if op == "actor_call":
            handle = session.actors[msg["actor"]]
            method = getattr(handle, msg["method"])
            ref = method.remote(*msg.get("args", ()),
                                **msg.get("kwargs", {}))
            session.refs[ref.hex()] = ref
            return {"ref": ref.hex()}
        if op == "actor_kill":
            handle = session.actors.pop(msg["actor"], None)
            if handle is not None:
                ray_tpu.kill(handle)
            return {"ok": True}
        if op == "free":
            for h in msg["refs"]:
                session.refs.pop(h, None)
            return {"ok": True}
        if op == "cluster_resources":
            return {"resources": ray_tpu.cluster_resources()}
        raise ValueError(f"Unknown op {op!r}")


class _ThreadingTCPServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


class ClientServer:
    def __init__(self, host: str = "127.0.0.1", port: int = 10001):
        if not ray_tpu.is_initialized():
            ray_tpu.init()
        self._server = _ThreadingTCPServer((host, port), _Handler)
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="ray_tpu-client-server",
            daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()


def serve(host: str = "127.0.0.1", port: int = 10001) -> ClientServer:
    """Start the client server (``ray://host:port`` endpoint)."""
    return ClientServer(host, port)
