from ray_tpu.util.client.client import (ClientAPI, ClientObjectRef,
                                        RayTpuClient, connect)
from ray_tpu.util.client.server import ClientServer, serve

__all__ = ["ClientAPI", "ClientObjectRef", "ClientServer", "RayTpuClient",
           "connect", "serve"]
