"""Distributed FIFO queue backed by an async actor.

Analog of the reference's python/ray/util/queue.py: a named actor holds an
asyncio.Queue; any worker can put/get with optional blocking + timeout.
"""

from __future__ import annotations

from typing import Any, List, Optional

import ray_tpu


class Empty(Exception):
    pass


class Full(Exception):
    pass


class _QueueActor:
    def __init__(self, maxsize: int):
        import asyncio
        self._q = asyncio.Queue(maxsize)

    async def put(self, item, timeout: Optional[float] = None):
        import asyncio
        if timeout is None:
            await self._q.put(item)
            return True
        try:
            await asyncio.wait_for(self._q.put(item), timeout)
            return True
        except asyncio.TimeoutError:
            return False

    async def put_nowait(self, item):
        import asyncio
        try:
            self._q.put_nowait(item)
            return True
        except asyncio.QueueFull:
            return False

    async def get(self, timeout: Optional[float] = None):
        import asyncio
        if timeout is None:
            return (True, await self._q.get())
        try:
            return (True, await asyncio.wait_for(self._q.get(), timeout))
        except asyncio.TimeoutError:
            return (False, None)

    async def get_nowait(self):
        import asyncio
        try:
            return (True, self._q.get_nowait())
        except asyncio.QueueEmpty:
            return (False, None)

    async def qsize(self):
        return self._q.qsize()

    async def empty(self):
        return self._q.empty()

    async def full(self):
        return self._q.full()


class Queue:
    def __init__(self, maxsize: int = 0, actor_options: Optional[dict] = None):
        cls = ray_tpu.remote(_QueueActor)
        if actor_options:
            cls = cls.options(**actor_options)
        self.actor = cls.remote(maxsize)

    def put(self, item, block: bool = True,
            timeout: Optional[float] = None) -> None:
        if not block:
            if not ray_tpu.get(self.actor.put_nowait.remote(item)):
                raise Full()
            return
        ok = ray_tpu.get(self.actor.put.remote(item, timeout))
        if not ok:
            raise Full()

    def get(self, block: bool = True, timeout: Optional[float] = None) -> Any:
        if not block:
            ok, item = ray_tpu.get(self.actor.get_nowait.remote())
            if not ok:
                raise Empty()
            return item
        ok, item = ray_tpu.get(self.actor.get.remote(timeout))
        if not ok:
            raise Empty()
        return item

    def put_nowait(self, item) -> None:
        self.put(item, block=False)

    def get_nowait(self) -> Any:
        return self.get(block=False)

    def put_nowait_batch(self, items: List[Any]) -> None:
        for item in items:
            self.put_nowait(item)

    def get_nowait_batch(self, num_items: int) -> List[Any]:
        return [self.get_nowait() for _ in range(num_items)]

    def qsize(self) -> int:
        return ray_tpu.get(self.actor.qsize.remote())

    def empty(self) -> bool:
        return ray_tpu.get(self.actor.empty.remote())

    def full(self) -> bool:
        return ray_tpu.get(self.actor.full.remote())

    def shutdown(self) -> None:
        ray_tpu.kill(self.actor)
