"""Spark-on-ray_tpu: run a ray_tpu cluster on a Spark cluster's
executors.

Analog of the reference's ray.util.spark
(python/ray/util/spark/cluster_init.py:772 setup_ray_cluster /
:1031 shutdown_ray_cluster): a Spark job's executors each start a
ray_tpu node daemon that joins a head running on the Spark driver, so
ray_tpu workloads (Train/Tune/Data) use the Spark cluster's capacity.
The TPU-native difference: daemons register their accelerator
resources and the head schedules onto them with the normal
mesh/sharding machinery — no change to the compute path.

pyspark is NOT bundled with this framework; every entry point degrades
with a clear error when it is absent. The executor-side launch logic
(`_start_worker_daemon`) is spark-agnostic — it is exercised directly
by the test suite and reused by the autoscaler's command runners.
"""

from __future__ import annotations

import logging
import os
import socket
import subprocess
import sys
from typing import Any, Dict, List, Optional, Tuple

logger = logging.getLogger(__name__)

_active: Dict[str, Any] = {"head": None, "spark_job": None}


def _require_pyspark():
    try:
        import pyspark  # noqa: F401
        return pyspark
    except ImportError as exc:
        raise ImportError(
            "ray_tpu.util.spark needs pyspark, which is not installed "
            "in this environment. Install pyspark, or start workers "
            "directly with `ray-tpu start --address <head>` (the "
            "executor-side launch is the same either way).") from exc


def _start_worker_daemon(head_address: str, *, num_cpus: float = 1.0,
                         num_tpus: float = 0.0,
                         resources: Optional[Dict[str, float]] = None,
                         object_store_memory: int = 1 << 28,
                         env: Optional[Dict[str, str]] = None
                         ) -> subprocess.Popen:
    """Launch one node daemon joining ``head_address`` — the per-
    executor body of setup_ray_cluster, callable from any launcher
    (Spark mapPartitions task, SSH, test)."""
    import json as _json
    cmd = [sys.executable, "-m", "ray_tpu._private.multinode",
           "--address", head_address,
           "--num-cpus", str(num_cpus),
           "--object-store-memory", str(int(object_store_memory))]
    if num_tpus:
        cmd += ["--num-tpus", str(num_tpus)]
    if resources:
        cmd += ["--resources", _json.dumps(resources)]
    full_env = dict(os.environ)
    if env:
        full_env.update(env)
    # Pre-registration output (import errors, bad args) lands in
    # session launch-log files when a session exists; the daemon
    # re-routes its own streams once registered. No DEVNULL: a daemon
    # that dies before registering must leave its words somewhere.
    from ray_tpu._private import ray_logging
    out_f, err_f = ray_logging.open_launch_capture("spark-daemon")
    kwargs = {}
    if out_f is not None:
        kwargs = {"stdout": out_f, "stderr": err_f}
    try:
        return subprocess.Popen(cmd, env=full_env, **kwargs)
    finally:
        for f in (out_f, err_f):
            if f is not None:
                f.close()  # the child holds its own copy


def setup_ray_cluster(num_worker_nodes: int, *,
                      num_cpus_per_node: float = 1.0,
                      num_tpus_per_node: float = 0.0,
                      resources_per_node: Optional[Dict[str, float]] = None,
                      object_store_memory_per_node: int = 1 << 28,
                      head_port: int = 0,
                      collect_log_to_path: Optional[str] = None
                      ) -> Tuple[str, None]:
    """Start a ray_tpu head on the Spark driver and one node daemon on
    each of ``num_worker_nodes`` Spark executors (reference:
    cluster_init.py setup_ray_cluster; the return mirrors its
    (address, dashboard) tuple shape). Blocks until every worker
    registered."""
    import time

    import ray_tpu
    pyspark = _require_pyspark()
    spark = pyspark.sql.SparkSession.getActiveSession()
    if spark is None:
        raise RuntimeError(
            "setup_ray_cluster must run inside an active Spark session "
            "(reference semantics: the head lives on the Spark driver)")
    if not ray_tpu.is_initialized():
        ray_tpu.init(num_cpus=1)
    # Baseline BEFORE workers launch: the readiness check below must
    # count only capacity the executors add — the head's own CPUs
    # (whatever init() gave it) would otherwise satisfy it instantly
    # with zero workers joined.
    base_cpu = ray_tpu.cluster_resources().get("CPU", 0)
    host, port = ray_tpu.start_head_server(port=head_port,
                                           host=_driver_ip())
    address = f"{host}:{port}"

    def _launch_partition(_it):
        proc = _start_worker_daemon(
            address, num_cpus=num_cpus_per_node,
            num_tpus=num_tpus_per_node,
            resources=resources_per_node,
            object_store_memory=object_store_memory_per_node)
        # The daemon must outlive this Spark task: detach and idle the
        # task slot (reference: start_ray_node.py keeps the node alive
        # for the Spark job's lifetime).
        import time as _t
        while proc.poll() is None:
            _t.sleep(10)
        yield proc.returncode

    sc = spark.sparkContext
    rdd = sc.parallelize(range(num_worker_nodes), num_worker_nodes)
    # Async job: the partitions idle for the cluster's lifetime.
    import threading
    job = threading.Thread(
        target=lambda: rdd.mapPartitions(_launch_partition).collect(),
        name="ray_tpu-spark-launch", daemon=True)
    job.start()
    _active["head"] = address
    _active["spark_job"] = job
    deadline = time.monotonic() + 120
    want = base_cpu + num_worker_nodes * num_cpus_per_node
    while time.monotonic() < deadline:
        if ray_tpu.cluster_resources().get("CPU", 0) >= want:
            return address, None
        time.sleep(0.5)
    raise TimeoutError(
        f"spark workers never joined: cluster CPU "
        f"{ray_tpu.cluster_resources().get('CPU', 0)} < {want}")


def shutdown_ray_cluster() -> None:
    """Tear the spark-hosted cluster down (reference:
    cluster_init.py:1031). Daemons exit when the head stops."""
    import ray_tpu
    _active["head"] = None
    _active["spark_job"] = None
    ray_tpu.shutdown()


def _driver_ip() -> str:
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        s.connect(("10.255.255.255", 1))
        return s.getsockname()[0]
    except OSError:
        return "127.0.0.1"
    finally:
        s.close()


MAX_NUM_WORKER_NODES = -1  # reference: sentinel for "all executors"
