"""Scheduling strategies (analog of python/ray/util/scheduling_strategies.py).

On TPU clusters a placement group maps onto an ICI mesh slice; the bundle
index selects the host within the slice.
"""

from __future__ import annotations

from typing import Optional


class PlacementGroupSchedulingStrategy:
    def __init__(self, placement_group,
                 placement_group_bundle_index: int = -1,
                 placement_group_capture_child_tasks: Optional[bool] = None):
        self.placement_group = placement_group
        self.placement_group_bundle_index = placement_group_bundle_index
        self.placement_group_capture_child_tasks = (
            placement_group_capture_child_tasks)


class NodeAffinitySchedulingStrategy:
    def __init__(self, node_id: str, soft: bool = False):
        self.node_id = node_id
        self.soft = soft


# String strategies: "DEFAULT" (hybrid pack/spread) and "SPREAD".
DEFAULT = "DEFAULT"
SPREAD = "SPREAD"


def strategy_from_options(options: dict):
    """Build + validate the scheduling strategy from call options (shared by
    RemoteFunction._remote and ActorClass._remote)."""
    strategy = options.get("scheduling_strategy")
    pg = options.get("placement_group")
    if pg is not None and strategy is None:
        strategy = PlacementGroupSchedulingStrategy(
            placement_group=pg,
            placement_group_bundle_index=options.get(
                "placement_group_bundle_index", -1))
    validate_strategy(strategy)
    return strategy


def validate_strategy(strategy) -> None:
    """Eagerly reject malformed strategies at call time."""
    if strategy is None or isinstance(strategy, str):
        return
    if isinstance(strategy, PlacementGroupSchedulingStrategy):
        pg = strategy.placement_group
        idx = strategy.placement_group_bundle_index
        if pg is not None and idx is not None and idx >= pg.bundle_count:
            raise ValueError(
                f"placement_group_bundle_index {idx} is out of range for a "
                f"placement group with {pg.bundle_count} bundles")
