"""User-facing exception types.

Mirrors the reference's python/ray/exceptions.py surface (RayError hierarchy):
task errors wrap the remote traceback; actor errors mark dead actors; object
loss / cancellation / timeout are distinct types so callers can catch narrowly.
"""

from __future__ import annotations

import traceback as _tb


class RayError(Exception):
    """Base class for all framework errors."""


class TaskError(RayError):
    """A task raised an exception during execution.

    Re-raised at ``get`` with the remote traceback embedded, wrapping the
    original exception as ``cause`` (reference: python/ray/exceptions.py
    RayTaskError).
    """

    def __init__(self, cause: BaseException, remote_traceback: str = "",
                 task_name: str = ""):
        self.cause = cause
        self.remote_traceback = remote_traceback
        self.task_name = task_name
        super().__init__(str(cause))

    def __str__(self):
        msg = f"Task {self.task_name or '<unknown>'} failed: "
        msg += f"{type(self.cause).__name__}: {self.cause}"
        if self.remote_traceback:
            msg += "\n\nRemote traceback:\n" + self.remote_traceback
        return msg

    @classmethod
    def from_exception(cls, exc: BaseException, task_name: str = "") -> "TaskError":
        return cls(exc, "".join(_tb.format_exception(exc)), task_name)


# Alias matching the reference name.
RayTaskError = TaskError


class ActorError(RayError):
    """An actor task cannot complete because the actor died."""

    def __init__(self, actor_id=None, message: str = ""):
        self.actor_id = actor_id
        super().__init__(message or f"The actor {actor_id} died unexpectedly.")


RayActorError = ActorError


class ActorDiedError(ActorError):
    pass


class ActorUnavailableError(ActorError):
    pass


class NodeDiedError(RayError):
    """The node a task was running on died (reference: node failure surfaces
    as RayTaskError with a node-death cause; here it is first-class)."""
    pass


class WorkerCrashedError(RayError):
    """The worker process executing the task died."""


class ObjectLostError(RayError):
    def __init__(self, object_id_hex: str = ""):
        super().__init__(f"Object {object_id_hex} was lost and cannot be reconstructed.")


class ObjectFreedError(RayError):
    pass


class BackPressureError(RayError):
    """A serve deployment's request queue is full: the router fast-fails
    instead of queueing unboundedly (reference: serve/exceptions.py
    BackPressureError, raised when ``max_queued_requests`` is exceeded).
    The HTTP proxy maps this to 503 with a Retry-After header."""

    def __init__(self, num_queued: int = 0, max_queued: int = 0,
                 deployment: str = ""):
        self.num_queued = num_queued
        self.max_queued = max_queued
        self.deployment = deployment
        super().__init__(
            f"Request dropped by deployment {deployment or '<unknown>'}: "
            f"{num_queued} requests outstanding >= max_queued_requests="
            f"{max_queued}. Retry later or raise max_queued_requests.")


class GetTimeoutError(RayError, TimeoutError):
    """``get`` did not complete within the requested timeout."""


class TaskCancelledError(RayError):
    def __init__(self, task_id=None):
        self.task_id = task_id
        super().__init__("This task or its dependency was cancelled.")


class RuntimeEnvSetupError(RayError):
    pass


# What counts as "the infrastructure failed" (safe to retry elsewhere /
# gang-restart) versus "the application raised" (surface to the caller
# unchanged). TaskError wraps application exceptions and is deliberately
# NOT here — but its ``cause`` may be one of these (a replica refusing
# work, a worker observing its peer's death), so classification walks
# one level into the cause. Shared by serve failover
# (serve/_private/router.py) and train gang recovery
# (train/_internal/backend_executor.py): one definition, one behavior.
SYSTEM_FAILURES = (ActorError, ObjectLostError, NodeDiedError,
                   WorkerCrashedError)


def is_system_failure(exc: BaseException) -> bool:
    """True if ``exc`` is an infrastructure failure (actor/node/worker
    death, object loss) rather than an application exception —
    including when it travels as the ``cause`` of a :class:`TaskError`."""
    if isinstance(exc, SYSTEM_FAILURES):
        return True
    return isinstance(getattr(exc, "cause", None), SYSTEM_FAILURES)


class OutOfMemoryError(RayError):
    pass


class PlacementGroupError(RayError):
    pass


class CrossLanguageError(RayError):
    pass
