"""Tuner + trial runner + ResultGrid.

Analog of the reference's tune/tuner.py:44 (Tuner.fit) and the
TrialRunner.step event loop (tune/execution/trial_runner.py:268,931): each
trial is an actor (reference: ray_trial_executor.py:191); the runner
multiplexes trial results with ray.wait, feeds the scheduler, and stops
trials early on its decision.
"""

from __future__ import annotations

import logging
import uuid
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.air.config import RunConfig
from ray_tpu.air.result import Result
from ray_tpu.train._internal.worker_group import TrainWorker
from ray_tpu.tune.schedulers import CONTINUE, FIFOScheduler, STOP
from ray_tpu.tune.search import generate_variants

logger = logging.getLogger("ray_tpu.tune")


@dataclass
class TuneConfig:
    metric: Optional[str] = None
    mode: str = "max"
    num_samples: int = 1
    max_concurrent_trials: Optional[int] = None
    scheduler: Any = None
    search_alg: Any = None  # reserved; basic variant generation built in
    seed: int = 0


@dataclass
class _Trial:
    trial_id: str
    config: Dict[str, Any]
    actor: Any = None
    history: List[dict] = field(default_factory=list)
    iteration: int = 0
    error: Optional[BaseException] = None
    done: bool = False
    stopped: bool = False


class ResultGrid:
    def __init__(self, results: List[Result], metric: Optional[str],
                 mode: str):
        self._results = results
        self._metric = metric
        self._mode = mode

    def __len__(self):
        return len(self._results)

    def __getitem__(self, i) -> Result:
        return self._results[i]

    @property
    def errors(self):
        return [r.error for r in self._results if r.error is not None]

    def get_best_result(self, metric: Optional[str] = None,
                        mode: Optional[str] = None) -> Result:
        metric = metric or self._metric
        mode = mode or self._mode
        if metric is None:
            raise ValueError("Specify metric= (none set in TuneConfig)")
        candidates = []
        for r in self._results:
            values = [h[metric] for h in r.metrics_history if metric in h]
            if not values:
                continue
            best = max(values) if mode == "max" else min(values)
            candidates.append((best, r))
        if not candidates:
            raise ValueError(f"No trial reported metric {metric!r}")
        candidates.sort(key=lambda t: t[0], reverse=(mode == "max"))
        return candidates[0][1]

    def get_dataframe(self):
        import pandas as pd
        rows = []
        for r in self._results:
            row = dict(r.metrics)
            row["trial_id"] = r.trial_id
            row.update({f"config/{k}": v for k, v in r.config.items()})
            rows.append(row)
        return pd.DataFrame(rows)


class Tuner:
    def __init__(self, trainable: Callable = None, *,
                 param_space: Optional[dict] = None,
                 tune_config: Optional[TuneConfig] = None,
                 run_config: Optional[RunConfig] = None):
        from ray_tpu.train.base_trainer import BaseTrainer
        if isinstance(trainable, BaseTrainer):
            self._trainable = trainable.as_trainable()
        else:
            self._trainable = trainable
        self.param_space = param_space or {}
        self.tune_config = tune_config or TuneConfig()
        self.run_config = run_config or RunConfig()
        self._trial_resources = getattr(
            trainable, "_tune_resources", None) or {"num_cpus": 1}

    def fit(self) -> ResultGrid:
        cfg = self.tune_config
        scheduler = cfg.scheduler or FIFOScheduler()
        if hasattr(scheduler, "set_metric") and cfg.metric:
            scheduler.set_metric(cfg.metric, cfg.mode)
        trials = [
            _Trial(trial_id=f"trial_{i:05d}_{uuid.uuid4().hex[:4]}",
                   config=variant)
            for i, variant in enumerate(
                generate_variants(self.param_space, cfg.num_samples,
                                  cfg.seed))
        ]
        max_concurrent = cfg.max_concurrent_trials or len(trials)
        pending = list(trials)
        running: Dict[Any, _Trial] = {}  # outstanding result ref -> trial

        def launch(trial: _Trial):
            actor_cls = TrainWorker.options(**self._trial_resources)
            trial.actor = actor_cls.remote(0, 1)
            # Don't block on creation: actor tasks are ordered, so the
            # result stream ref resolves once the trial actually starts —
            # trials queue naturally behind available resources.
            trial.actor.start_training.remote(
                self._trainable, trial.config,
                {"trial_id": trial.trial_id, "trial_name": trial.trial_id})
            ref = trial.actor.get_next_result.remote()
            running[ref] = trial

        while pending and len(running) < max_concurrent:
            launch(pending.pop(0))

        while running:
            ready, _ = ray_tpu.wait(list(running.keys()), num_returns=1,
                                    timeout=None)
            ref = ready[0]
            trial = running.pop(ref)
            payload = ray_tpu.get(ref)
            if payload.get("finished") or payload.get("timeout"):
                trial.done = True
                trial.error = payload.get("error")
                if payload.get("timeout"):
                    trial.error = TimeoutError("trial timed out")
                ray_tpu.kill(trial.actor)
                if pending:
                    launch(pending.pop(0))
                continue
            metrics = dict(payload.get("metrics", {}))
            trial.iteration += 1
            metrics.setdefault("training_iteration", trial.iteration)
            trial.history.append(metrics)
            decision = scheduler.on_result(trial.trial_id, metrics)
            if decision == STOP or self._hit_stop_criteria(metrics):
                trial.stopped = True
                trial.actor.request_stop.remote()
            # Re-arm the result stream for this trial.
            ref = trial.actor.get_next_result.remote()
            running[ref] = trial

        results = [
            Result(metrics=t.history[-1] if t.history else {},
                   metrics_history=t.history, config=t.config,
                   error=t.error, trial_id=t.trial_id)
            for t in trials
        ]
        errs = [r for r in results if r.error is not None]
        if errs:
            logger.warning("%d/%d trials errored", len(errs), len(results))
        return ResultGrid(results, cfg.metric, cfg.mode)

    def _hit_stop_criteria(self, metrics: dict) -> bool:
        stop = self.run_config.stop if self.run_config else None
        if not stop:
            return False
        return any(metrics.get(k) is not None and metrics[k] >= v
                   for k, v in stop.items())
