"""Tuner + trial runner + ResultGrid.

Analog of the reference's tune/tuner.py:44 (Tuner.fit) and the
TrialRunner.step event loop (tune/execution/trial_runner.py:268,931): each
trial is an actor (reference: ray_trial_executor.py:191); the runner
multiplexes trial results with ray.wait, feeds the scheduler and searcher,
stops trials early on scheduler decisions, restarts trials from donor
checkpoints on PBT EXPLOIT, invokes callbacks, and snapshots experiment
state for ``Tuner.restore`` (reference: tune/execution/trial_runner.py
checkpointing + tuner.py Tuner.restore).
"""

from __future__ import annotations

import json
import logging
import os
import uuid
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.air.config import RunConfig
from ray_tpu.air.result import Result
from ray_tpu.train._internal.worker_group import TrainWorker
from ray_tpu.tune.schedulers import CONTINUE, EXPLOIT, FIFOScheduler, STOP
from ray_tpu.tune.search import Searcher, generate_variants

logger = logging.getLogger("ray_tpu.tune")

_EXPERIMENT_STATE_FILE = "experiment_state.json"


@dataclass
class TuneConfig:
    metric: Optional[str] = None
    mode: str = "max"
    num_samples: int = 1
    max_concurrent_trials: Optional[int] = None
    scheduler: Any = None
    search_alg: Optional[Searcher] = None
    seed: int = 0


@dataclass
class _Trial:
    trial_id: str
    config: Dict[str, Any]
    actor: Any = None
    history: List[dict] = field(default_factory=list)
    iteration: int = 0
    error: Optional[BaseException] = None
    done: bool = False
    stopped: bool = False
    checkpoint: Any = None


class ResultGrid:
    def __init__(self, results: List[Result], metric: Optional[str],
                 mode: str):
        self._results = results
        self._metric = metric
        self._mode = mode

    def __len__(self):
        return len(self._results)

    def __getitem__(self, i) -> Result:
        return self._results[i]

    @property
    def errors(self):
        return [r.error for r in self._results if r.error is not None]

    def get_best_result(self, metric: Optional[str] = None,
                        mode: Optional[str] = None) -> Result:
        metric = metric or self._metric
        mode = mode or self._mode
        if metric is None:
            raise ValueError("Specify metric= (none set in TuneConfig)")
        candidates = []
        for r in self._results:
            values = [h[metric] for h in r.metrics_history if metric in h]
            if not values:
                continue
            best = max(values) if mode == "max" else min(values)
            candidates.append((best, r))
        if not candidates:
            raise ValueError(f"No trial reported metric {metric!r}")
        candidates.sort(key=lambda t: t[0], reverse=(mode == "max"))
        return candidates[0][1]

    def get_dataframe(self):
        import pandas as pd
        rows = []
        for r in self._results:
            row = dict(r.metrics)
            row["trial_id"] = r.trial_id
            row.update({f"config/{k}": v for k, v in r.config.items()})
            rows.append(row)
        return pd.DataFrame(rows)


class Tuner:
    def __init__(self, trainable: Callable = None, *,
                 param_space: Optional[dict] = None,
                 tune_config: Optional[TuneConfig] = None,
                 run_config: Optional[RunConfig] = None,
                 _restored_state: Optional[dict] = None):
        from ray_tpu.train.base_trainer import BaseTrainer
        if isinstance(trainable, BaseTrainer):
            self._trainable = trainable.as_trainable()
        else:
            self._trainable = trainable
        self.param_space = param_space or {}
        self.tune_config = tune_config or TuneConfig()
        self.run_config = run_config or RunConfig()
        self._trial_resources = getattr(
            trainable, "_tune_resources", None) or {"num_cpus": 1}
        self._restored_state = _restored_state

    # -- restore (reference: tune/tuner.py Tuner.restore) -----------------

    @classmethod
    def restore(cls, path: str, trainable: Callable, *,
                param_space: Optional[dict] = None,
                tune_config: Optional[TuneConfig] = None,
                run_config: Optional[RunConfig] = None) -> "Tuner":
        """Resume an interrupted experiment from its directory: finished
        trials keep their recorded results, unfinished ones rerun.

        Schedulers, searchers, stop criteria, and callbacks are not
        serialized in the experiment snapshot — pass the same ``tune_config``
        / ``run_config`` objects used for the original run to keep their
        semantics on the resumed trials (reference: tune/tuner.py
        Tuner.restore takes the re-specified trainable the same way)."""
        import dataclasses

        state_file = os.path.join(path, _EXPERIMENT_STATE_FILE)
        with open(state_file) as f:
            state = json.load(f)
        if tune_config is None:
            tune_config = TuneConfig(
                metric=state["metric"], mode=state["mode"],
                num_samples=state["num_samples"])
        else:
            # Merge into a copy — never mutate the caller's object.
            updates = {}
            if tune_config.metric is None:
                # metric and mode travel together: backfilling one from the
                # snapshot but not the other could flip the optimization
                # direction.
                updates["metric"] = state["metric"]
                updates["mode"] = state["mode"]
            if tune_config.num_samples < state["num_samples"]:
                updates["num_samples"] = state["num_samples"]
            tune_config = dataclasses.replace(tune_config, **updates)
        if run_config is None:
            run_config = RunConfig(name=state.get("name"),
                                   storage_path=state.get("storage_path"))
        else:
            updates = {}
            if run_config.name is None:
                updates["name"] = state.get("name")
            if run_config.storage_path is None:
                updates["storage_path"] = state.get("storage_path")
            run_config = dataclasses.replace(run_config, **updates)
        return cls(trainable, param_space=param_space or {},
                   tune_config=tune_config, run_config=run_config,
                   _restored_state=state)

    def experiment_dir(self) -> Optional[str]:
        if not self.run_config.storage_path:
            return None
        name = self.run_config.name or "tune_experiment"
        return os.path.join(self.run_config.storage_path, name)

    def _snapshot(self, trials: List[_Trial], num_created: int,
                  pending_configs: Optional[list] = None) -> None:
        exp_dir = self.experiment_dir()
        if not exp_dir:
            return
        os.makedirs(exp_dir, exist_ok=True)
        state = {
            "pending_configs": _jsonable(pending_configs or []),
            "metric": self.tune_config.metric,
            "mode": self.tune_config.mode,
            "num_samples": self.tune_config.num_samples,
            "name": self.run_config.name,
            "storage_path": self.run_config.storage_path,
            "num_created": num_created,
            "trials": [
                {
                    "trial_id": t.trial_id,
                    "config": _jsonable(t.config),
                    "done": t.done,
                    "error": repr(t.error) if t.error is not None else None,
                    "history": _jsonable(t.history),
                }
                for t in trials
            ],
        }
        tmp = os.path.join(exp_dir, _EXPERIMENT_STATE_FILE + ".tmp")
        with open(tmp, "w") as f:
            json.dump(state, f)
        os.replace(tmp, os.path.join(exp_dir, _EXPERIMENT_STATE_FILE))

    # -- the event loop ---------------------------------------------------

    def fit(self) -> ResultGrid:
        cfg = self.tune_config
        scheduler = cfg.scheduler or FIFOScheduler()
        if hasattr(scheduler, "set_metric") and cfg.metric:
            scheduler.set_metric(cfg.metric, cfg.mode)
        searcher = cfg.search_alg
        if searcher is not None:
            searcher.set_search_properties(cfg.metric, cfg.mode,
                                           self.param_space,
                                           num_samples=cfg.num_samples)
            target_trials = searcher.expected_trials(cfg.num_samples)
            variants = None
        else:
            variants = list(generate_variants(
                self.param_space, cfg.num_samples, cfg.seed))
            target_trials = len(variants)

        callbacks = list(getattr(self.run_config, "callbacks", None) or [])
        for cb in callbacks:
            cb.setup(experiment_dir=self.experiment_dir())

        trials: List[_Trial] = []
        num_created = 0

        # Restored experiments: replay finished trials, requeue the rest.
        restore_queue: List[_Trial] = []
        if self._restored_state is not None:
            for ts in self._restored_state["trials"]:
                trial = _Trial(trial_id=ts["trial_id"], config=ts["config"],
                               history=ts["history"], done=ts["done"])
                num_created += 1
                if ts["done"] and ts["error"] is None:
                    trials.append(trial)
                    if searcher is not None:
                        # Replay the recorded outcome so the searcher's
                        # model includes pre-crash observations.
                        searcher.register_completed(
                            trial.trial_id, trial.config,
                            trial.history[-1] if trial.history else None)
                else:
                    trial.done = False
                    trial.history = []
                    restore_queue.append(trial)
                    if searcher is not None:
                        # Register as pending so the searcher credits the
                        # rerun's completion to this config (it never
                        # suggest()-ed the trial in this process).
                        searcher.register_pending(trial.trial_id,
                                                  trial.config)
            for config in self._restored_state.get("pending_configs", []):
                trial = _Trial(
                    trial_id=(f"trial_{num_created:05d}_"
                              f"{uuid.uuid4().hex[:4]}"),
                    config=config)
                num_created += 1
                restore_queue.append(trial)
            if searcher is not None:
                # A restored searcher keeps producing its remaining samples.
                target_trials = max(num_created, target_trials)
            else:
                target_trials = num_created
            # The placeholder variants built from the (empty) restore
            # param_space must never leak into snapshots as pending work.
            variants = None

        max_concurrent = cfg.max_concurrent_trials or max(target_trials, 1)
        running: Dict[Any, _Trial] = {}  # outstanding result ref -> trial

        def next_trial() -> Optional[_Trial]:
            nonlocal num_created
            if restore_queue:
                return restore_queue.pop(0)
            if num_created >= target_trials:
                return None
            if searcher is not None:
                trial_id = f"trial_{num_created:05d}_{uuid.uuid4().hex[:4]}"
                config = searcher.suggest(trial_id)
                if config is None:
                    return None  # exhausted or concurrency-limited
                num_created += 1
                return _Trial(trial_id=trial_id, config=config)
            config = variants[num_created]
            trial = _Trial(
                trial_id=f"trial_{num_created:05d}_{uuid.uuid4().hex[:4]}",
                config=config)
            num_created += 1
            return trial

        def launch(trial: _Trial, checkpoint=None):
            actor_cls = TrainWorker.options(**self._trial_resources)
            trial.actor = actor_cls.remote(0, 1)
            # Don't block on creation: actor tasks are ordered, so the
            # result stream ref resolves once the trial actually starts —
            # trials queue naturally behind available resources.
            trial.actor.start_training.remote(
                self._trainable, trial.config,
                {"trial_id": trial.trial_id, "trial_name": trial.trial_id},
                checkpoint)
            ref = trial.actor.get_next_result.remote()
            running[ref] = trial
            if trial not in trials:
                trials.append(trial)
            if hasattr(scheduler, "on_trial_start"):
                scheduler.on_trial_start(trial.trial_id, trial.config)
            for cb in callbacks:
                cb.on_trial_start(trial.trial_id, trial.config)

        def fill_slots():
            while len(running) < max_concurrent:
                trial = next_trial()
                if trial is None:
                    break
                launch(trial)

        fill_slots()

        while running:
            ready, _ = ray_tpu.wait(list(running.keys()), num_returns=1,
                                    timeout=None)
            ref = ready[0]
            trial = running.pop(ref)
            payload = ray_tpu.get(ref)
            if payload.get("finished") or payload.get("timeout"):
                trial.done = True
                trial.error = payload.get("error")
                if payload.get("timeout"):
                    trial.error = TimeoutError("trial timed out")
                ray_tpu.kill(trial.actor)
                if searcher is not None:
                    searcher.on_trial_complete(
                        trial.trial_id,
                        trial.history[-1] if trial.history else None,
                        error=trial.error is not None)
                for cb in callbacks:
                    cb.on_trial_complete(trial.trial_id, trial.error)
                self._snapshot(trials, num_created,
                               variants[num_created:] if variants else [])
                fill_slots()
                continue
            metrics = dict(payload.get("metrics", {}))
            if payload.get("checkpoint") is not None:
                trial.checkpoint = payload["checkpoint"]
            trial.iteration += 1
            metrics.setdefault("training_iteration", trial.iteration)
            trial.history.append(metrics)
            for cb in callbacks:
                cb.on_trial_result(trial.trial_id, metrics)
            decision = scheduler.on_result(trial.trial_id, metrics)
            if decision == EXPLOIT:
                donor_id, new_config = scheduler.exploit_info(trial.trial_id)
                donor = next((t for t in trials
                              if t.trial_id == donor_id), None)
                donor_ckpt = donor.checkpoint if donor is not None else None
                if donor_ckpt is not None:
                    logger.info("PBT: %s exploits %s",
                                trial.trial_id, donor_id)
                    # Restart this trial from the donor's checkpoint with
                    # the mutated config (reference: pbt.py _exploit).
                    scheduler.commit_exploit(trial.trial_id)
                    ray_tpu.kill(trial.actor)
                    trial.config = new_config
                    launch(trial, checkpoint=donor_ckpt)
                    continue
                # Donor has no checkpoint yet: restarting would lose all
                # progress for nothing — keep the trial running
                # (reference pbt.py skips checkpointless exploits). The
                # scheduler must forget the tentative exploit too.
                scheduler.abort_exploit(trial.trial_id)
                decision = CONTINUE
            if decision == STOP or self._hit_stop_criteria(metrics):
                trial.stopped = True
                trial.actor.request_stop.remote()
            # Re-arm the result stream for this trial.
            ref = trial.actor.get_next_result.remote()
            running[ref] = trial

        results = [
            Result(metrics=t.history[-1] if t.history else {},
                   metrics_history=t.history, config=t.config,
                   error=t.error, trial_id=t.trial_id,
                   checkpoint=t.checkpoint)
            for t in trials
        ]
        self._snapshot(trials, num_created,
                       variants[num_created:] if variants else [])
        for cb in callbacks:
            cb.on_experiment_end(results)
        errs = [r for r in results if r.error is not None]
        if errs:
            logger.warning("%d/%d trials errored", len(errs), len(results))
        return ResultGrid(results, cfg.metric, cfg.mode)

    def _hit_stop_criteria(self, metrics: dict) -> bool:
        stop = self.run_config.stop if self.run_config else None
        if not stop:
            return False
        return any(metrics.get(k) is not None and metrics[k] >= v
                   for k, v in stop.items())


def _jsonable(obj):
    """Deep-copy obj keeping only JSON-serializable leaves (repr others)."""
    try:
        json.dumps(obj)
        return obj
    except (TypeError, ValueError):
        pass
    if isinstance(obj, dict):
        return {k: _jsonable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_jsonable(v) for v in obj]
    return repr(obj)
