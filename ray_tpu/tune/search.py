"""Search spaces + variant generation.

Analog of the reference's tune/search/ (sample.py Domains,
basic_variant.py BasicVariantGenerator): grid_search entries expand as a
cross-product, Domain objects sample per trial, num_samples multiplies the
grid — matching reference semantics.
"""

from __future__ import annotations

import random
from typing import Any, Dict, Iterator, List


class Domain:
    def sample(self, rng: random.Random) -> Any:
        raise NotImplementedError


class Uniform(Domain):
    def __init__(self, low, high):
        self.low, self.high = low, high

    def sample(self, rng):
        return rng.uniform(self.low, self.high)


class LogUniform(Domain):
    def __init__(self, low, high):
        import math
        self.log_low, self.log_high = math.log(low), math.log(high)

    def sample(self, rng):
        import math
        return math.exp(rng.uniform(self.log_low, self.log_high))


class Choice(Domain):
    def __init__(self, categories):
        self.categories = list(categories)

    def sample(self, rng):
        return rng.choice(self.categories)


class RandInt(Domain):
    def __init__(self, low, high):
        self.low, self.high = low, high

    def sample(self, rng):
        return rng.randrange(self.low, self.high)


class QUniform(Domain):
    def __init__(self, low, high, q):
        self.low, self.high, self.q = low, high, q

    def sample(self, rng):
        value = rng.uniform(self.low, self.high)
        return round(value / self.q) * self.q


def uniform(low, high) -> Uniform:
    return Uniform(low, high)


def loguniform(low, high) -> LogUniform:
    return LogUniform(low, high)


def choice(categories) -> Choice:
    return Choice(categories)


def randint(low, high) -> RandInt:
    return RandInt(low, high)


def quniform(low, high, q) -> QUniform:
    return QUniform(low, high, q)


def grid_search(values) -> Dict[str, list]:
    return {"grid_search": list(values)}


def _is_grid(value) -> bool:
    return isinstance(value, dict) and set(value.keys()) == {"grid_search"}


def _expand_grid(space: Dict[str, Any]) -> List[Dict[str, Any]]:
    """Cross-product over grid_search entries (non-recursive keys only at
    top level; nested dicts are recursed)."""
    variants: List[Dict[str, Any]] = [{}]
    for key, value in space.items():
        if _is_grid(value):
            variants = [dict(v, **{key: g}) for v in variants
                        for g in value["grid_search"]]
        elif isinstance(value, dict) and not _is_grid(value):
            subvariants = _expand_grid(value)
            variants = [dict(v, **{key: sub}) for v in variants
                        for sub in subvariants]
        else:
            variants = [dict(v, **{key: value}) for v in variants]
    return variants


def _sample_domains(config: Dict[str, Any], rng: random.Random
                    ) -> Dict[str, Any]:
    out = {}
    for key, value in config.items():
        if isinstance(value, Domain):
            out[key] = value.sample(rng)
        elif isinstance(value, dict):
            out[key] = _sample_domains(value, rng)
        elif callable(value) and getattr(value, "_tune_sample_fn", False):
            out[key] = value(None)
        else:
            out[key] = value
    return out


def sample_from(fn):
    """tune.sample_from equivalent."""
    fn._tune_sample_fn = True
    return fn


def generate_variants(param_space: Dict[str, Any], num_samples: int,
                      seed: int = 0) -> Iterator[Dict[str, Any]]:
    rng = random.Random(seed)
    grid = _expand_grid(param_space or {})
    for _ in range(num_samples):
        for variant in grid:
            yield _sample_domains(variant, rng)
