"""Search spaces + variant generation.

Analog of the reference's tune/search/ (sample.py Domains,
basic_variant.py BasicVariantGenerator): grid_search entries expand as a
cross-product, Domain objects sample per trial, num_samples multiplies the
grid — matching reference semantics.
"""

from __future__ import annotations

import random
from typing import Any, Dict, Iterator, List, Optional


class Domain:
    def sample(self, rng: random.Random) -> Any:
        raise NotImplementedError


class Uniform(Domain):
    def __init__(self, low, high):
        self.low, self.high = low, high

    def sample(self, rng):
        return rng.uniform(self.low, self.high)


class LogUniform(Domain):
    def __init__(self, low, high):
        import math
        self.log_low, self.log_high = math.log(low), math.log(high)

    def sample(self, rng):
        import math
        return math.exp(rng.uniform(self.log_low, self.log_high))


class Choice(Domain):
    def __init__(self, categories):
        self.categories = list(categories)

    def sample(self, rng):
        return rng.choice(self.categories)


class RandInt(Domain):
    def __init__(self, low, high):
        self.low, self.high = low, high

    def sample(self, rng):
        return rng.randrange(self.low, self.high)


class QUniform(Domain):
    def __init__(self, low, high, q):
        self.low, self.high, self.q = low, high, q

    def sample(self, rng):
        value = rng.uniform(self.low, self.high)
        return round(value / self.q) * self.q


def uniform(low, high) -> Uniform:
    return Uniform(low, high)


def loguniform(low, high) -> LogUniform:
    return LogUniform(low, high)


def choice(categories) -> Choice:
    return Choice(categories)


def randint(low, high) -> RandInt:
    return RandInt(low, high)


def quniform(low, high, q) -> QUniform:
    return QUniform(low, high, q)


def grid_search(values) -> Dict[str, list]:
    return {"grid_search": list(values)}


def _is_grid(value) -> bool:
    return isinstance(value, dict) and set(value.keys()) == {"grid_search"}


def _expand_grid(space: Dict[str, Any]) -> List[Dict[str, Any]]:
    """Cross-product over grid_search entries (non-recursive keys only at
    top level; nested dicts are recursed)."""
    variants: List[Dict[str, Any]] = [{}]
    for key, value in space.items():
        if _is_grid(value):
            variants = [dict(v, **{key: g}) for v in variants
                        for g in value["grid_search"]]
        elif isinstance(value, dict) and not _is_grid(value):
            subvariants = _expand_grid(value)
            variants = [dict(v, **{key: sub}) for v in variants
                        for sub in subvariants]
        else:
            variants = [dict(v, **{key: value}) for v in variants]
    return variants


def _sample_domains(config: Dict[str, Any], rng: random.Random
                    ) -> Dict[str, Any]:
    out = {}
    for key, value in config.items():
        if isinstance(value, Domain):
            out[key] = value.sample(rng)
        elif _is_grid(value):
            # Unexpanded grid marker (searcher path, where there is no
            # upfront cross-product): sample one of the grid values.
            out[key] = rng.choice(value["grid_search"])
        elif isinstance(value, dict):
            out[key] = _sample_domains(value, rng)
        elif callable(value) and getattr(value, "_tune_sample_fn", False):
            out[key] = value(None)
        else:
            out[key] = value
    return out


def sample_from(fn):
    """tune.sample_from equivalent."""
    fn._tune_sample_fn = True
    return fn


def generate_variants(param_space: Dict[str, Any], num_samples: int,
                      seed: int = 0) -> Iterator[Dict[str, Any]]:
    rng = random.Random(seed)
    grid = _expand_grid(param_space or {})
    for _ in range(num_samples):
        for variant in grid:
            yield _sample_domains(variant, rng)


# -- Searcher interface (reference: tune/search/searcher.py Searcher) -----

class Searcher:
    """Suggests configs and learns from completed trials.

    Analog of the reference's tune/search/searcher.py: ``suggest`` returns
    the next config (or None when exhausted), ``on_trial_complete`` feeds
    the final result back.
    """

    def set_search_properties(self, metric: str, mode: str,
                              param_space: Dict[str, Any],
                              num_samples: Optional[int] = None) -> None:
        self.metric = metric
        self.mode = mode
        self.param_space = param_space

    def expected_trials(self, num_samples: int) -> int:
        """Total trials this searcher intends to produce for the runner's
        ``num_samples`` setting (grid-expanding searchers return more)."""
        return num_samples

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        raise NotImplementedError

    def on_trial_complete(self, trial_id: str,
                          result: Optional[dict] = None,
                          error: bool = False) -> None:
        pass

    def register_completed(self, trial_id: str, config: Dict[str, Any],
                           result: Optional[dict],
                           error: bool = False) -> None:
        """Feed an externally-recorded completed trial (restore replay):
        like on_trial_complete but with the config supplied, since the
        searcher never suggested it in this process."""
        pass

    def register_pending(self, trial_id: str,
                         config: Dict[str, Any]) -> None:
        """Adopt an externally-created in-flight trial (restore requeue) so
        its eventual on_trial_complete is credited to this config."""
        pass


class BasicVariantGenerator(Searcher):
    """Grid cross-product x num_samples with Domain sampling — the default
    searcher (reference: tune/search/basic_variant.py)."""

    def __init__(self, num_samples: int = 1, seed: int = 0):
        self.num_samples = num_samples
        self.seed = seed
        self._it: Optional[Iterator[Dict[str, Any]]] = None

    def set_search_properties(self, metric, mode, param_space,
                              num_samples=None):
        super().set_search_properties(metric, mode, param_space)
        if num_samples is not None:
            self.num_samples = max(self.num_samples, num_samples)
        self._it = generate_variants(param_space, self.num_samples,
                                     self.seed)

    def expected_trials(self, num_samples: int) -> int:
        grid = len(_expand_grid(self.param_space or {}))
        return max(self.num_samples, num_samples) * max(grid, 1)

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        try:
            return next(self._it)
        except StopIteration:
            return None


class TPESearcher(Searcher):
    """Tree-structured-Parzen-style sequential searcher (the native analog
    of the reference's external-library searchers, tune/search/hyperopt/).

    After ``n_initial_points`` random configs, observations are split at the
    ``gamma`` quantile into good/bad sets; candidates are drawn from the
    good set's kernel density and scored by the good/bad density ratio.
    Numeric Domains only; non-numeric keys fall back to random sampling.
    """

    def __init__(self, n_initial_points: int = 8, gamma: float = 0.25,
                 n_candidates: int = 24, seed: int = 0):
        self.n_initial = n_initial_points
        self.gamma = gamma
        self.n_candidates = n_candidates
        self._rng = random.Random(seed)
        self._observations: List[tuple] = []  # (config, signed score)
        self._pending: Dict[str, Dict[str, Any]] = {}

    def _numeric_keys(self) -> List[str]:
        return [k for k, v in self.param_space.items()
                if isinstance(v, (Uniform, LogUniform, RandInt, QUniform))]

    def _random_config(self) -> Dict[str, Any]:
        return _sample_domains(self.param_space, self._rng)

    @staticmethod
    def _kde_logpdf(x: float, points: List[float], bandwidth: float
                    ) -> float:
        import math
        if not points:
            return 0.0
        total = 0.0
        for p in points:
            z = (x - p) / bandwidth
            total += math.exp(-0.5 * z * z)
        return math.log(total / (len(points) * bandwidth) + 1e-12)

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        keys = self._numeric_keys()
        if len(self._observations) < self.n_initial or not keys:
            config = self._random_config()
            self._pending[trial_id] = config
            return config
        ordered = sorted(self._observations, key=lambda o: -o[1])
        n_good = max(1, int(len(ordered) * self.gamma))
        good = [c for c, _ in ordered[:n_good]]
        bad = [c for c, _ in ordered[n_good:]] or good
        best, best_score = None, None
        for _ in range(self.n_candidates):
            candidate = self._random_config()
            # Mutate candidate toward the good set on numeric keys.
            donor = self._rng.choice(good)
            for key in keys:
                if self._rng.random() < 0.75:
                    candidate[key] = donor[key]
            score = 0.0
            for key in keys:
                values_g = [c[key] for c in good]
                values_b = [c[key] for c in bad]
                spread = (max(values_g + values_b) -
                          min(values_g + values_b)) or 1.0
                bw = max(spread / 4.0, 1e-9)
                score += (self._kde_logpdf(candidate[key], values_g, bw) -
                          self._kde_logpdf(candidate[key], values_b, bw))
            if best_score is None or score > best_score:
                best, best_score = candidate, score
        self._pending[trial_id] = best
        return best

    def on_trial_complete(self, trial_id, result=None, error=False):
        config = self._pending.pop(trial_id, None)
        self._observe(config, result, error)

    def register_completed(self, trial_id, config, result, error=False):
        self._observe(config, result, error)

    def register_pending(self, trial_id, config):
        self._pending[trial_id] = dict(config)

    def _observe(self, config, result, error):
        if config is None or error or not result:
            return
        value = result.get(self.metric)
        if value is None:
            return
        signed = value if self.mode == "max" else -value
        self._observations.append((config, signed))


class BOHBSearcher(TPESearcher):
    """Native BOHB (Falkner et al. 2018): TPE-style density-ratio
    suggestions whose model is built from observations at the LARGEST
    budget with enough samples — pair with HyperBandScheduler, whose
    rungs stop trials at different training_iteration budgets, exactly
    the reference's TuneBOHB + HB pairing (tune/search/bohb/ wraps the
    external hpbandster; this is the in-tree equivalent).

    Budgets are read from the completing trial's ``training_iteration``
    (the scheduler's rung = how long the trial was allowed to run);
    a model over high-budget observations transfers to suggestions for
    new (low-budget) trials, which is BOHB's core move."""

    def __init__(self, n_initial_points: int = 8, gamma: float = 0.25,
                 n_candidates: int = 24, min_points_per_budget: int = 6,
                 seed: int = 0):
        super().__init__(n_initial_points=n_initial_points, gamma=gamma,
                         n_candidates=n_candidates, seed=seed)
        self.min_points_per_budget = min_points_per_budget
        self._by_budget: Dict[int, List[tuple]] = {}

    def _observe(self, config, result, error):
        if config is None or error or not result:
            return
        value = result.get(self.metric)
        if value is None:
            return
        signed = value if self.mode == "max" else -value
        budget = int(result.get("training_iteration", 1) or 1)
        self._by_budget.setdefault(budget, []).append((config, signed))
        self._refresh_model()

    def _refresh_model(self) -> None:
        """Point _observations at the largest budget with enough
        samples (falling back to pooling everything below it)."""
        for budget in sorted(self._by_budget, reverse=True):
            rows = self._by_budget[budget]
            if len(rows) >= self.min_points_per_budget:
                self._observations = list(rows)
                return
        pooled: List[tuple] = []
        for rows in self._by_budget.values():
            pooled.extend(rows)
        self._observations = pooled

    def model_budget(self) -> Optional[int]:
        """The budget whose observations currently drive suggestions
        (None while pooling across budgets)."""
        for budget in sorted(self._by_budget, reverse=True):
            if len(self._by_budget[budget]) >=                     self.min_points_per_budget:
                return budget
        return None


class ConcurrencyLimiter(Searcher):
    """Caps in-flight suggestions (reference: tune/search/
    concurrency_limiter.py)."""

    def __init__(self, searcher: Searcher, max_concurrent: int):
        self.searcher = searcher
        self.max_concurrent = max_concurrent
        self._live: set = set()

    def set_search_properties(self, metric, mode, param_space,
                              num_samples=None):
        super().set_search_properties(metric, mode, param_space)
        self.searcher.set_search_properties(metric, mode, param_space,
                                            num_samples)

    def expected_trials(self, num_samples: int) -> int:
        return self.searcher.expected_trials(num_samples)

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        if len(self._live) >= self.max_concurrent:
            return None
        config = self.searcher.suggest(trial_id)
        if config is not None:
            self._live.add(trial_id)
        return config

    def on_trial_complete(self, trial_id, result=None, error=False):
        self._live.discard(trial_id)
        self.searcher.on_trial_complete(trial_id, result, error)

    def register_completed(self, trial_id, config, result, error=False):
        self.searcher.register_completed(trial_id, config, result, error)

    def register_pending(self, trial_id, config):
        # The requeued trial occupies a concurrency slot like any other
        # in-flight suggestion; it frees on completion.
        self._live.add(trial_id)
        self.searcher.register_pending(trial_id, config)


class BayesOptSearch(Searcher):
    """Gaussian-process Bayesian optimization (the native analog of the
    reference's tune/search/bayesopt/ wrapper around bayes_opt).

    Numeric domains are normalized to [0, 1] (log-scaled for LogUniform); a
    GP with an RBF kernel is fit on completed observations (numpy Cholesky)
    and the next config maximizes Expected Improvement over random
    candidates. Non-numeric keys fall back to random sampling.
    """

    def __init__(self, n_initial_points: int = 6, n_candidates: int = 256,
                 kernel_scale: float = 0.2, noise: float = 1e-6,
                 seed: int = 0):
        self.n_initial = n_initial_points
        self.n_candidates = n_candidates
        self.kernel_scale = kernel_scale
        self.noise = noise
        self._rng = random.Random(seed)
        self._observations: List[tuple] = []  # (config, signed score)
        self._pending: Dict[str, Dict[str, Any]] = {}

    def _numeric_keys(self) -> List[str]:
        return [k for k in sorted(self.param_space)
                if isinstance(self.param_space[k],
                              (Uniform, LogUniform, RandInt, QUniform))]

    def _to_unit(self, key: str, value: float) -> float:
        import math
        dom = self.param_space[key]
        if isinstance(dom, LogUniform):
            lo, hi = dom.log_low, dom.log_high
            return (math.log(value) - lo) / max(hi - lo, 1e-12)
        lo, hi = float(dom.low), float(dom.high)
        return (float(value) - lo) / max(hi - lo, 1e-12)

    def _features(self, config: Dict[str, Any]):
        import numpy as np
        return np.asarray([self._to_unit(k, config[k])
                           for k in self._numeric_keys()])

    def _gp_posterior(self, X, y, Xc):
        """GP posterior mean/std at candidates Xc (RBF kernel)."""
        import numpy as np

        def rbf(A, B):
            d2 = ((A[:, None, :] - B[None, :, :]) ** 2).sum(-1)
            return np.exp(-0.5 * d2 / self.kernel_scale ** 2)

        K = rbf(X, X) + self.noise * np.eye(len(X))
        L = np.linalg.cholesky(K)
        alpha = np.linalg.solve(L.T, np.linalg.solve(L, y))
        Ks = rbf(X, Xc)
        mu = Ks.T @ alpha
        v = np.linalg.solve(L, Ks)
        var = np.clip(1.0 - (v ** 2).sum(0), 1e-12, None)
        return mu, np.sqrt(var)

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        import numpy as np
        keys = self._numeric_keys()
        if len(self._observations) < self.n_initial or not keys:
            config = _sample_domains(self.param_space, self._rng)
            self._pending[trial_id] = config
            return config
        X = np.stack([self._features(c) for c, _ in self._observations])
        y = np.asarray([s for _, s in self._observations], dtype=float)
        y_mean, y_std = y.mean(), max(y.std(), 1e-9)
        yn = (y - y_mean) / y_std
        candidates = [_sample_domains(self.param_space, self._rng)
                      for _ in range(self.n_candidates)]
        Xc = np.stack([self._features(c) for c in candidates])
        mu, sigma = self._gp_posterior(X, yn, Xc)
        best = yn.max()
        # Expected Improvement.
        z = (mu - best) / sigma
        phi = np.exp(-0.5 * z ** 2) / np.sqrt(2 * np.pi)
        Phi = 0.5 * (1.0 + np.vectorize(math_erf)(z / np.sqrt(2)))
        ei = sigma * (z * Phi + phi)
        config = candidates[int(ei.argmax())]
        self._pending[trial_id] = config
        return config

    def on_trial_complete(self, trial_id, result=None, error=False):
        config = self._pending.pop(trial_id, None)
        self._observe(config, result, error)

    def register_completed(self, trial_id, config, result, error=False):
        self._observe(config, result, error)

    def register_pending(self, trial_id, config):
        self._pending[trial_id] = dict(config)

    def _observe(self, config, result, error):
        if config is None or error or not result:
            return
        value = result.get(self.metric)
        if value is None:
            return
        signed = value if self.mode == "max" else -value
        self._observations.append((config, signed))


def math_erf(x: float) -> float:
    import math
    return math.erf(x)


#: Reference-named alias (tune/search/bohb/ TuneBOHB): the budget-aware
#: searcher IS the BOHB sampling component; pair with HyperBandScheduler
#: for the reference's HB_BOHB.
TuneBOHB = BOHBSearcher
