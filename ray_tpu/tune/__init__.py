"""Tune: distributed hyperparameter search (reference: python/ray/tune)."""

from ray_tpu.air.session import get_checkpoint, get_trial_id, get_trial_name
from ray_tpu.air.session import report  # tune.report == session.report
from ray_tpu.tune.callbacks import (Callback, CSVLoggerCallback,
                                    JsonLoggerCallback,
                                    MLflowLoggerCallback, SyncerCallback,
                                    TBXLoggerCallback, WandbLoggerCallback)
from ray_tpu.tune.schedulers import (ASHAScheduler, FIFOScheduler,
                                     HyperBandScheduler, MedianStoppingRule,
                                     PopulationBasedTraining)
from ray_tpu.tune.search import (BasicVariantGenerator, BayesOptSearch,
                                 BOHBSearcher, ConcurrencyLimiter,
                                 Searcher, TPESearcher,
                                 TuneBOHB, choice, grid_search, loguniform,
                                 quniform, randint, sample_from, uniform)
from ray_tpu.tune.tuner import ResultGrid, TuneConfig, Tuner


def with_resources(trainable, resources: dict):
    """Attach per-trial resource requests (reference: tune.with_resources)."""
    mapped = {}
    for key, value in resources.items():
        if key in ("cpu", "CPU", "num_cpus"):
            mapped["num_cpus"] = value
        elif key in ("tpu", "TPU", "num_tpus", "gpu", "GPU", "num_gpus"):
            mapped["num_tpus"] = value
        else:
            mapped.setdefault("resources", {})[key] = value
    trainable._tune_resources = mapped
    return trainable


def with_parameters(trainable, **kwargs):
    """Bind large constant objects to the trainable
    (reference: tune.with_parameters)."""
    import functools

    @functools.wraps(trainable)
    def wrapped(config):
        return trainable(config, **kwargs)

    return wrapped


__all__ = [
    "ASHAScheduler",
    "BasicVariantGenerator",
    "BayesOptSearch",
    "Callback",
    "CSVLoggerCallback",
    "ConcurrencyLimiter",
    "FIFOScheduler",
    "HyperBandScheduler",
    "JsonLoggerCallback",
    "MLflowLoggerCallback",
    "MedianStoppingRule",
    "PopulationBasedTraining",
    "ResultGrid",
    "Searcher",
    "SyncerCallback",
    "TBXLoggerCallback",
    "TPESearcher",
    "BOHBSearcher",
    "TuneBOHB",
    "TuneConfig",
    "Tuner",
    "choice",
    "get_checkpoint",
    "get_trial_id",
    "get_trial_name",
    "grid_search",
    "loguniform",
    "quniform",
    "randint",
    "report",
    "sample_from",
    "uniform",
    "WandbLoggerCallback",
    "with_parameters",
    "with_resources",
]
