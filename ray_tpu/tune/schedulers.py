"""Trial schedulers: FIFO, ASHA, HyperBand, median stopping, and PBT.

Analogs of the reference's tune/schedulers/: async_hyperband.py
(ASHAScheduler), hyperband.py (HyperBandScheduler), median_stopping_rule.py
(MedianStoppingRule), and pbt.py (PopulationBasedTraining). Schedulers see
every trial report via ``on_result`` and return CONTINUE / STOP / EXPLOIT;
EXPLOIT (PBT only) tells the runner to restart the trial from a stronger
trial's checkpoint with a mutated config (``exploit_info``).
"""

from __future__ import annotations

import random
from typing import Any, Callable, Dict, List, Optional, Tuple

CONTINUE = "CONTINUE"
STOP = "STOP"
EXPLOIT = "EXPLOIT"


class FIFOScheduler:
    def on_result(self, trial_id: str, metrics: dict) -> str:
        return CONTINUE


class ASHAScheduler:
    def __init__(self, time_attr: str = "training_iteration",
                 metric: str = None, mode: str = "max",
                 max_t: int = 100, grace_period: int = 1,
                 reduction_factor: int = 4):
        self.time_attr = time_attr
        self.metric = metric
        self.mode = mode
        self.max_t = max_t
        self.grace_period = grace_period
        self.rf = reduction_factor
        # rung milestone -> {trial_id: signed metric at crossing}
        self._rungs: Dict[int, Dict[str, float]] = {}
        rung = grace_period
        while rung < max_t:
            self._rungs[rung] = {}
            rung *= reduction_factor

    def set_metric(self, metric: str, mode: str):
        if self.metric is None:
            self.metric = metric
            self.mode = mode

    def on_result(self, trial_id: str, metrics: dict) -> str:
        t = metrics.get(self.time_attr)
        value = metrics.get(self.metric)
        if t is None or value is None:
            return CONTINUE
        if t >= self.max_t:
            return STOP
        signed = value if self.mode == "max" else -value
        for rung in sorted(self._rungs, reverse=True):
            if t < rung:
                continue
            recorded = self._rungs[rung]
            # Record this trial's value at its first crossing of the rung.
            recorded.setdefault(trial_id, signed)
            # Decide on every report past the rung (not just at crossing):
            # a weak trial that crossed before enough peers had recorded is
            # still cut as soon as the quantile is established.
            if len(recorded) >= self.rf:
                ordered = sorted(recorded.values(), reverse=True)
                cutoff = ordered[max(0, len(ordered) // self.rf - 1)]
                if recorded[trial_id] < cutoff:
                    return STOP
            break
        return CONTINUE
