"""Trial schedulers: FIFO, ASHA, HyperBand, median stopping, and PBT.

Analogs of the reference's tune/schedulers/: async_hyperband.py
(ASHAScheduler), hyperband.py (HyperBandScheduler), median_stopping_rule.py
(MedianStoppingRule), and pbt.py (PopulationBasedTraining). Schedulers see
every trial report via ``on_result`` and return CONTINUE / STOP / EXPLOIT;
EXPLOIT (PBT only) tells the runner to restart the trial from a stronger
trial's checkpoint with a mutated config (fetched via ``exploit_info``).
"""

from __future__ import annotations

import math
import random
from typing import Any, Callable, Dict, List, Optional, Tuple

CONTINUE = "CONTINUE"
STOP = "STOP"
EXPLOIT = "EXPLOIT"


class FIFOScheduler:
    def on_result(self, trial_id: str, metrics: dict) -> str:
        return CONTINUE


class ASHAScheduler:
    def __init__(self, time_attr: str = "training_iteration",
                 metric: str = None, mode: str = "max",
                 max_t: int = 100, grace_period: int = 1,
                 reduction_factor: int = 4):
        self.time_attr = time_attr
        self.metric = metric
        self.mode = mode
        self.max_t = max_t
        self.grace_period = grace_period
        self.rf = reduction_factor
        # rung milestone -> {trial_id: signed metric at crossing}
        self._rungs: Dict[int, Dict[str, float]] = {}
        rung = grace_period
        while rung < max_t:
            self._rungs[rung] = {}
            rung *= reduction_factor

    def set_metric(self, metric: str, mode: str):
        if self.metric is None:
            self.metric = metric
            self.mode = mode

    def on_result(self, trial_id: str, metrics: dict) -> str:
        t = metrics.get(self.time_attr)
        value = metrics.get(self.metric)
        if t is None or value is None:
            return CONTINUE
        if t >= self.max_t:
            return STOP
        signed = value if self.mode == "max" else -value
        for rung in sorted(self._rungs, reverse=True):
            if t < rung:
                continue
            recorded = self._rungs[rung]
            # Record this trial's value at its first crossing of the rung.
            recorded.setdefault(trial_id, signed)
            # Decide on every report past the rung (not just at crossing):
            # a weak trial that crossed before enough peers had recorded is
            # still cut as soon as the quantile is established.
            if len(recorded) >= self.rf:
                ordered = sorted(recorded.values(), reverse=True)
                cutoff = ordered[max(0, len(ordered) // self.rf - 1)]
                if recorded[trial_id] < cutoff:
                    return STOP
            break
        return CONTINUE


class HyperBandScheduler:
    """Synchronous HyperBand (reference: tune/schedulers/hyperband.py).

    Trials are assigned round-robin to brackets; each bracket runs
    successive halving: at each milestone, the bottom ``1 - 1/eta`` of the
    bracket's live trials are stopped. Synchronous semantics are
    approximated per report: a trial reaching a milestone is held against
    the values recorded so far at that milestone and cut once enough peers
    have reported.
    """

    def __init__(self, time_attr: str = "training_iteration",
                 metric: str = None, mode: str = "max",
                 max_t: int = 81, reduction_factor: int = 3):
        self.time_attr = time_attr
        self.metric = metric
        self.mode = mode
        self.max_t = max_t
        self.eta = reduction_factor
        # s_max + 1 brackets per generation; bracket s holds up to eta^s
        # trials starting with budget r = max_t / eta^s.
        # round() before int(): log(1000)/log(10) = 2.999... must give 3.
        self.s_max = int(round(math.log(max_t) / math.log(self.eta), 10))
        # Flat list of live brackets: (milestones, capacity, count). A new
        # generation of brackets is appended when all existing ones fill,
        # as the reference creates fresh bracket cohorts on demand.
        self._brackets: List[list] = []
        self._trial_bracket: Dict[str, int] = {}
        self._new_generation()

    def _new_generation(self) -> None:
        # Most exploratory bracket (largest s, smallest initial budget)
        # fills first.
        for s in range(self.s_max, -1, -1):
            r = max(1, int(self.max_t / (self.eta ** s)))
            milestones: Dict[int, Dict[str, float]] = {}
            t = r
            while t < self.max_t:
                milestones[t] = {}
                t *= self.eta
            self._brackets.append([milestones, self.eta ** s, 0])

    def set_metric(self, metric: str, mode: str):
        if self.metric is None:
            self.metric = metric
            self.mode = mode

    def _bracket_for(self, trial_id: str) -> Dict[int, Dict[str, float]]:
        if trial_id not in self._trial_bracket:
            index = next((i for i, (_, cap, n) in enumerate(self._brackets)
                          if n < cap), None)
            if index is None:
                index = len(self._brackets)
                self._new_generation()
            self._brackets[index][2] += 1
            self._trial_bracket[trial_id] = index
        return self._brackets[self._trial_bracket[trial_id]][0]

    def on_result(self, trial_id: str, metrics: dict) -> str:
        t = metrics.get(self.time_attr)
        value = metrics.get(self.metric)
        if t is None or value is None:
            return CONTINUE
        if t >= self.max_t:
            return STOP
        signed = value if self.mode == "max" else -value
        milestones = self._bracket_for(trial_id)
        for milestone in sorted(milestones, reverse=True):
            if t < milestone:
                continue
            recorded = milestones[milestone]
            recorded.setdefault(trial_id, signed)
            if len(recorded) >= self.eta:
                ordered = sorted(recorded.values(), reverse=True)
                keep = max(1, len(ordered) // self.eta)
                cutoff = ordered[keep - 1]
                if recorded[trial_id] < cutoff:
                    return STOP
            break
        return CONTINUE


class MedianStoppingRule:
    """Stop a trial whose best result so far is worse than the median of
    the running averages of all completed-enough peers at the same time
    step (reference: tune/schedulers/median_stopping_rule.py).
    """

    def __init__(self, time_attr: str = "training_iteration",
                 metric: str = None, mode: str = "max",
                 grace_period: int = 1, min_samples_required: int = 3,
                 hard_stop: bool = True):
        self.time_attr = time_attr
        self.metric = metric
        self.mode = mode
        self.grace_period = grace_period
        self.min_samples = min_samples_required
        self.hard_stop = hard_stop
        # trial_id -> list of (t, signed value)
        self._history: Dict[str, List[Tuple[float, float]]] = {}

    def set_metric(self, metric: str, mode: str):
        if self.metric is None:
            self.metric = metric
            self.mode = mode

    def _running_avg(self, trial_id: str, up_to_t: float) -> Optional[float]:
        points = [v for (t, v) in self._history.get(trial_id, ())
                  if t <= up_to_t]
        return sum(points) / len(points) if points else None

    def on_result(self, trial_id: str, metrics: dict) -> str:
        t = metrics.get(self.time_attr)
        value = metrics.get(self.metric)
        if t is None or value is None:
            return CONTINUE
        signed = value if self.mode == "max" else -value
        self._history.setdefault(trial_id, []).append((t, signed))
        if t < self.grace_period:
            return CONTINUE
        peer_avgs = [
            avg for other, hist in self._history.items()
            if other != trial_id
            for avg in [self._running_avg(other, t)]
            if avg is not None
        ]
        if len(peer_avgs) < self.min_samples:
            return CONTINUE
        peer_avgs.sort()
        median = peer_avgs[len(peer_avgs) // 2]
        best = max(v for (_, v) in self._history[trial_id])
        if best < median:
            return STOP if self.hard_stop else CONTINUE
        return CONTINUE


class PopulationBasedTraining:
    """PBT (reference: tune/schedulers/pbt.py).

    Every ``perturbation_interval`` steps of ``time_attr``, a trial in the
    bottom ``quantile_fraction`` of the population exploits a trial from the
    top quantile: the runner restarts it from the donor's latest checkpoint
    with a mutated copy of the donor's config. ``on_result`` returns EXPLOIT
    for such trials; the runner then calls ``exploit_info(trial_id)``.
    """

    def __init__(self, time_attr: str = "training_iteration",
                 metric: str = None, mode: str = "max",
                 perturbation_interval: int = 4,
                 hyperparam_mutations: Optional[Dict[str, Any]] = None,
                 quantile_fraction: float = 0.25,
                 resample_probability: float = 0.25,
                 perturbation_factors: Tuple[float, float] = (0.8, 1.2),
                 custom_explore_fn: Optional[Callable[[dict], dict]] = None,
                 seed: int = 0):
        if not 0 < quantile_fraction <= 0.5:
            raise ValueError("quantile_fraction must be in (0, 0.5]")
        self.time_attr = time_attr
        self.metric = metric
        self.mode = mode
        self.interval = perturbation_interval
        self.mutations = hyperparam_mutations or {}
        self.quantile = quantile_fraction
        self.resample_prob = resample_probability
        self.factors = perturbation_factors
        self.custom_explore_fn = custom_explore_fn
        self._rng = random.Random(seed)
        self._last_perturb: Dict[str, float] = {}
        self._scores: Dict[str, float] = {}  # trial_id -> latest signed
        self._configs: Dict[str, dict] = {}  # trial_id -> current config
        self._exploit: Dict[str, Tuple[str, dict]] = {}
        self.num_perturbations = 0

    def set_metric(self, metric: str, mode: str):
        if self.metric is None:
            self.metric = metric
            self.mode = mode

    def on_trial_start(self, trial_id: str, config: dict) -> None:
        self._configs[trial_id] = dict(config)

    def _quantiles(self) -> Tuple[List[str], List[str]]:
        ordered = sorted(self._scores, key=self._scores.get)
        if len(ordered) <= 1:
            return [], []
        num = int(math.ceil(len(ordered) * self.quantile))
        num = min(num, len(ordered) // 2)
        if num < 1:
            return [], []
        return ordered[:num], ordered[-num:]

    def _explore(self, config: dict) -> dict:
        from ray_tpu.tune.search import Domain
        new = dict(config)
        for key, spec in self.mutations.items():
            if self._rng.random() < self.resample_prob or key not in new:
                if isinstance(spec, Domain):
                    new[key] = spec.sample(self._rng)
                elif isinstance(spec, (list, tuple)):
                    new[key] = self._rng.choice(list(spec))
                elif callable(spec):
                    new[key] = spec()
            elif isinstance(spec, (list, tuple)):
                # Discrete list spec: step to an adjacent allowed value —
                # never perturb off the list (reference pbt.py semantics).
                values = list(spec)
                if new.get(key) in values:
                    i = values.index(new[key])
                    i = max(0, min(len(values) - 1,
                                   i + self._rng.choice((-1, 1))))
                    new[key] = values[i]
                else:
                    new[key] = self._rng.choice(values)
            elif isinstance(new.get(key), (int, float)) and not isinstance(
                    new.get(key), bool):
                factor = self._rng.choice(self.factors)
                mutated = new[key] * factor
                new[key] = type(config[key])(mutated) \
                    if isinstance(config[key], int) else mutated
        if self.custom_explore_fn is not None:
            new = self.custom_explore_fn(new)
        return new

    def on_result(self, trial_id: str, metrics: dict) -> str:
        t = metrics.get(self.time_attr)
        value = metrics.get(self.metric)
        if t is None or value is None:
            return CONTINUE
        signed = value if self.mode == "max" else -value
        self._scores[trial_id] = signed
        last = self._last_perturb.get(trial_id, 0)
        if t - last < self.interval:
            return CONTINUE
        self._last_perturb[trial_id] = t
        lower, upper = self._quantiles()
        if trial_id not in lower or not upper:
            return CONTINUE
        donor = self._rng.choice(upper)
        donor_config = self._configs.get(donor, {})
        new_config = self._explore(donor_config)
        # Tentative until the runner confirms: if the donor has no
        # checkpoint yet the runner aborts, and this trial's recorded
        # config must stay what it is actually running.
        self._exploit[trial_id] = (donor, new_config)
        return EXPLOIT

    def exploit_info(self, trial_id: str) -> Tuple[str, dict]:
        """(donor_trial_id, mutated_config) for a trial told to EXPLOIT.
        Peek only — the runner then calls commit_exploit or abort_exploit."""
        return self._exploit[trial_id]

    def commit_exploit(self, trial_id: str) -> None:
        """The runner actually restarted the trial from the donor."""
        donor, new_config = self._exploit.pop(trial_id)
        self._configs[trial_id] = dict(new_config)
        self.num_perturbations += 1

    def abort_exploit(self, trial_id: str) -> None:
        """The exploit was skipped (e.g. donor had no checkpoint)."""
        self._exploit.pop(trial_id, None)
