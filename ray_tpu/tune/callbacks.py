"""Tune callbacks + loggers.

Analog of the reference's tune/callback.py (Callback hooks invoked by the
trial-runner event loop) and tune/logger/ (CSVLoggerCallback,
JsonLoggerCallback writing per-trial progress files under the experiment
directory).
"""

from __future__ import annotations

import csv
import json
import os
from typing import Any, Dict, List, Optional


class Callback:
    """Hooks the trial runner invokes (reference: tune/callback.py)."""

    def setup(self, **info) -> None:
        pass

    def on_trial_start(self, trial_id: str, config: dict) -> None:
        pass

    def on_trial_result(self, trial_id: str, result: dict) -> None:
        pass

    def on_trial_complete(self, trial_id: str,
                          error: Optional[BaseException] = None) -> None:
        pass

    def on_experiment_end(self, results: List[Any]) -> None:
        pass


class LoggerCallback(Callback):
    """Base for per-trial file loggers; resolves each trial's directory."""

    def __init__(self, experiment_dir: Optional[str] = None):
        self._experiment_dir = experiment_dir
        self._trial_dirs: Dict[str, str] = {}

    def setup(self, experiment_dir: Optional[str] = None, **info) -> None:
        if experiment_dir is not None:
            self._experiment_dir = experiment_dir

    def _trial_dir(self, trial_id: str) -> str:
        if trial_id not in self._trial_dirs:
            base = self._experiment_dir or os.path.join(
                os.path.expanduser("~"), "ray_tpu_results")
            path = os.path.join(base, trial_id)
            os.makedirs(path, exist_ok=True)
            self._trial_dirs[trial_id] = path
        return self._trial_dirs[trial_id]


def _flatten(d: dict, prefix: str = "") -> dict:
    out = {}
    for key, value in d.items():
        name = f"{prefix}{key}"
        if isinstance(value, dict):
            out.update(_flatten(value, name + "/"))
        else:
            out[name] = value
    return out


class CSVLoggerCallback(LoggerCallback):
    """progress.csv per trial (reference: tune/logger/csv.py)."""

    def __init__(self, experiment_dir: Optional[str] = None):
        super().__init__(experiment_dir)
        self._files: Dict[str, Any] = {}
        self._writers: Dict[str, csv.DictWriter] = {}

    def on_trial_result(self, trial_id: str, result: dict) -> None:
        flat = _flatten(result)
        if trial_id not in self._writers:
            path = os.path.join(self._trial_dir(trial_id), "progress.csv")
            f = open(path, "w", newline="")
            writer = csv.DictWriter(f, fieldnames=list(flat.keys()),
                                    extrasaction="ignore")
            writer.writeheader()
            self._files[trial_id] = f
            self._writers[trial_id] = writer
        self._writers[trial_id].writerow(flat)
        self._files[trial_id].flush()

    def on_trial_complete(self, trial_id, error=None) -> None:
        f = self._files.pop(trial_id, None)
        if f is not None:
            f.close()
        self._writers.pop(trial_id, None)

    def on_experiment_end(self, results) -> None:
        for f in self._files.values():
            f.close()
        self._files.clear()
        self._writers.clear()


class JsonLoggerCallback(LoggerCallback):
    """result.json (one JSON line per report) per trial
    (reference: tune/logger/json.py)."""

    def __init__(self, experiment_dir: Optional[str] = None):
        super().__init__(experiment_dir)
        self._seen: set = set()

    def setup(self, experiment_dir: Optional[str] = None, **info) -> None:
        super().setup(experiment_dir=experiment_dir, **info)
        # Scope the truncation guard to one fit(): a restore that reuses
        # this callback instance must truncate stale result.json again.
        self._seen.clear()

    def on_trial_start(self, trial_id: str, config: dict) -> None:
        path = os.path.join(self._trial_dir(trial_id), "params.json")
        with open(path, "w") as f:
            json.dump(config, f, default=repr)
        if trial_id not in self._seen:
            # First start in this process: truncate any stale result.json
            # left by a pre-restore run of the same trial (a PBT exploit
            # relaunch in the same process keeps appending).
            self._seen.add(trial_id)
            result_path = os.path.join(self._trial_dir(trial_id),
                                       "result.json")
            open(result_path, "w").close()

    def on_trial_result(self, trial_id: str, result: dict) -> None:
        path = os.path.join(self._trial_dir(trial_id), "result.json")
        with open(path, "a") as f:
            f.write(json.dumps(result, default=repr) + "\n")


def _tb_events_record(payload: bytes) -> bytes:
    """Frame one TFRecord: length, masked-crc(length), payload,
    masked-crc(payload) — the event-file format TensorBoard reads."""
    import struct

    def crc32c(data: bytes) -> int:
        # Pure-python CRC32C (Castagnoli), table-driven.
        table = _CRC32C_TABLE
        crc = 0xFFFFFFFF
        for b in data:
            crc = table[(crc ^ b) & 0xFF] ^ (crc >> 8)
        return crc ^ 0xFFFFFFFF

    def mask(crc: int) -> int:
        return ((crc >> 15 | crc << 17) + 0xA282EAD8) & 0xFFFFFFFF

    header = struct.pack("<Q", len(payload))
    return (header + struct.pack("<I", mask(crc32c(header)))
            + payload + struct.pack("<I", mask(crc32c(payload))))


def _make_crc32c_table():
    poly = 0x82F63B78
    table = []
    for i in range(256):
        crc = i
        for _ in range(8):
            crc = (crc >> 1) ^ poly if crc & 1 else crc >> 1
        table.append(crc)
    return table


_CRC32C_TABLE = _make_crc32c_table()


def _pb_varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        out.append(b | (0x80 if n else 0))
        if not n:
            return bytes(out)


def _pb_field(num: int, wire: int, payload: bytes) -> bytes:
    return _pb_varint((num << 3) | wire) + payload


def _tb_scalar_event(step: int, wall_time: float, tag: str,
                     value: float) -> bytes:
    """Hand-encoded tensorflow.Event proto holding one scalar Summary
    (Event{wall_time=1, step=2, summary=5{value=1{tag=1, simple_value=2}}})."""
    import struct
    sv = _pb_field(1, 2, _pb_varint(len(tag.encode()))
                   + tag.encode())  # Summary.Value.tag
    sv += _pb_field(2, 5, struct.pack("<f", float(value)))  # simple_value
    summary_value = _pb_field(1, 2, _pb_varint(len(sv)) + sv)
    event = _pb_field(1, 1, struct.pack("<d", wall_time))
    event += _pb_field(2, 0, _pb_varint(step))
    event += _pb_field(5, 2, _pb_varint(len(summary_value)) + summary_value)
    return event


class TBXLoggerCallback(LoggerCallback):
    """TensorBoard event files per trial, written natively (no tensorboard
    dependency) — the analog of the reference's tune/logger/tensorboardx.py.
    Numeric result fields become scalar summaries keyed ``ray/tune/<name>``.
    """

    def __init__(self, experiment_dir: Optional[str] = None):
        super().__init__(experiment_dir)
        self._files: Dict[str, Any] = {}
        self._steps: Dict[str, int] = {}

    def _file(self, trial_id: str):
        if trial_id not in self._files:
            import socket
            import time as _time
            fname = (f"events.out.tfevents.{int(_time.time())}."
                     f"{socket.gethostname()}")
            path = os.path.join(self._trial_dir(trial_id), fname)
            f = open(path, "ab")
            # File-version header event.
            import struct
            ver = b"brain.Event:2"
            event = (_pb_field(1, 1, struct.pack("<d", _time.time()))
                     + _pb_field(3, 2, _pb_varint(len(ver)) + ver))
            f.write(_tb_events_record(event))
            self._files[trial_id] = f
        return self._files[trial_id]

    def on_trial_result(self, trial_id: str, result: dict) -> None:
        import numbers
        import time as _time
        f = self._file(trial_id)
        step = self._steps.get(trial_id, 0) + 1
        self._steps[trial_id] = step
        step_val = result.get("training_iteration", step)
        for key, value in _flatten(result).items():
            if isinstance(value, numbers.Number) and not isinstance(
                    value, bool):
                f.write(_tb_events_record(_tb_scalar_event(
                    int(step_val), _time.time(), f"ray/tune/{key}",
                    float(value))))
        f.flush()

    def on_trial_complete(self, trial_id, error=None) -> None:
        f = self._files.pop(trial_id, None)
        if f is not None:
            f.close()

    def on_experiment_end(self, results) -> None:
        for f in self._files.values():
            f.close()
        self._files.clear()


class WandbLoggerCallback(Callback):
    """Weights & Biases logging (reference: air/callbacks/wandb.py). Gated:
    raises at setup if the wandb package is unavailable."""

    def __init__(self, project: str, group: Optional[str] = None,
                 **init_kwargs):
        self.project = project
        self.group = group
        self.init_kwargs = init_kwargs
        self._runs: Dict[str, Any] = {}

    def setup(self, **info) -> None:
        try:
            import wandb  # noqa: F401
        except ImportError as exc:
            raise ImportError(
                "WandbLoggerCallback requires the `wandb` package, which "
                "is not installed in this environment.") from exc

    def on_trial_start(self, trial_id: str, config: dict) -> None:
        import wandb
        self._runs[trial_id] = wandb.init(
            project=self.project, group=self.group, name=trial_id,
            config=config, reinit=True, **self.init_kwargs)

    def on_trial_result(self, trial_id: str, result: dict) -> None:
        run = self._runs.get(trial_id)
        if run is not None:
            run.log(_flatten(result))

    def on_trial_complete(self, trial_id, error=None) -> None:
        run = self._runs.pop(trial_id, None)
        if run is not None:
            run.finish()


class MLflowLoggerCallback(Callback):
    """MLflow tracking (reference: air/callbacks/mlflow.py). Gated: raises
    at setup if mlflow is unavailable."""

    def __init__(self, tracking_uri: Optional[str] = None,
                 experiment_name: str = "ray_tpu"):
        self.tracking_uri = tracking_uri
        self.experiment_name = experiment_name
        self._run_ids: Dict[str, str] = {}

    def setup(self, **info) -> None:
        try:
            import mlflow
            from mlflow.tracking import MlflowClient
        except ImportError as exc:
            raise ImportError(
                "MLflowLoggerCallback requires the `mlflow` package, which "
                "is not installed in this environment.") from exc
        if self.tracking_uri:
            mlflow.set_tracking_uri(self.tracking_uri)
        # Client API throughout: concurrent trials must not share mlflow's
        # fluent (thread-local stack) run state — ending one trial's run
        # must never terminate another's.
        self._client = MlflowClient()
        exp = self._client.get_experiment_by_name(self.experiment_name)
        self._experiment_id = (exp.experiment_id if exp is not None else
                               self._client.create_experiment(
                                   self.experiment_name))

    def on_trial_start(self, trial_id: str, config: dict) -> None:
        run = self._client.create_run(
            self._experiment_id, tags={"mlflow.runName": trial_id})
        self._run_ids[trial_id] = run.info.run_id
        for k, v in _flatten(config).items():
            self._client.log_param(run.info.run_id, k, v)

    def on_trial_result(self, trial_id: str, result: dict) -> None:
        import numbers
        run_id = self._run_ids.get(trial_id)
        if run_id:
            for k, v in _flatten(result).items():
                if isinstance(v, numbers.Number) and not isinstance(v, bool):
                    self._client.log_metric(run_id, k, float(v))

    def on_trial_complete(self, trial_id, error=None) -> None:
        run_id = self._run_ids.pop(trial_id, None)
        if run_id:
            self._client.set_terminated(
                run_id, status="FAILED" if error else "FINISHED")


class SyncerCallback(Callback):
    """Mirror trial/experiment output to a destination directory after
    every result (the local-FS analog of the reference's tune/syncer.py
    cloud upload; 'file://' and plain paths supported)."""

    def __init__(self, upload_dir: str, sync_period_s: float = 300.0):
        # Reference default: sync every 300s — a full-tree copy per result
        # would stall the (synchronous) callback loop.
        self.upload_dir = upload_dir[7:] if upload_dir.startswith(
            "file://") else upload_dir
        self.sync_period_s = sync_period_s
        self._last_sync: Optional[float] = None
        self._experiment_dir: Optional[str] = None

    def setup(self, experiment_dir: Optional[str] = None, **info) -> None:
        self._experiment_dir = experiment_dir

    def _sync(self, force: bool = False) -> None:
        import shutil
        import time as _time
        if not self._experiment_dir or not os.path.isdir(
                self._experiment_dir):
            return
        now = _time.monotonic()
        if (not force and self._last_sync is not None
                and now - self._last_sync < self.sync_period_s):
            return
        self._last_sync = now
        dest = os.path.join(self.upload_dir,
                            os.path.basename(self._experiment_dir))
        shutil.copytree(self._experiment_dir, dest, dirs_exist_ok=True)

    def on_trial_result(self, trial_id: str, result: dict) -> None:
        self._sync()

    def on_experiment_end(self, results) -> None:
        self._sync(force=True)
