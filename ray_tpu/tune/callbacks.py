"""Tune callbacks + loggers.

Analog of the reference's tune/callback.py (Callback hooks invoked by the
trial-runner event loop) and tune/logger/ (CSVLoggerCallback,
JsonLoggerCallback writing per-trial progress files under the experiment
directory).
"""

from __future__ import annotations

import csv
import json
import os
from typing import Any, Dict, List, Optional


class Callback:
    """Hooks the trial runner invokes (reference: tune/callback.py)."""

    def setup(self, **info) -> None:
        pass

    def on_trial_start(self, trial_id: str, config: dict) -> None:
        pass

    def on_trial_result(self, trial_id: str, result: dict) -> None:
        pass

    def on_trial_complete(self, trial_id: str,
                          error: Optional[BaseException] = None) -> None:
        pass

    def on_experiment_end(self, results: List[Any]) -> None:
        pass


class LoggerCallback(Callback):
    """Base for per-trial file loggers; resolves each trial's directory."""

    def __init__(self, experiment_dir: Optional[str] = None):
        self._experiment_dir = experiment_dir
        self._trial_dirs: Dict[str, str] = {}

    def setup(self, experiment_dir: Optional[str] = None, **info) -> None:
        if experiment_dir is not None:
            self._experiment_dir = experiment_dir

    def _trial_dir(self, trial_id: str) -> str:
        if trial_id not in self._trial_dirs:
            base = self._experiment_dir or os.path.join(
                os.path.expanduser("~"), "ray_tpu_results")
            path = os.path.join(base, trial_id)
            os.makedirs(path, exist_ok=True)
            self._trial_dirs[trial_id] = path
        return self._trial_dirs[trial_id]


def _flatten(d: dict, prefix: str = "") -> dict:
    out = {}
    for key, value in d.items():
        name = f"{prefix}{key}"
        if isinstance(value, dict):
            out.update(_flatten(value, name + "/"))
        else:
            out[name] = value
    return out


class CSVLoggerCallback(LoggerCallback):
    """progress.csv per trial (reference: tune/logger/csv.py)."""

    def __init__(self, experiment_dir: Optional[str] = None):
        super().__init__(experiment_dir)
        self._files: Dict[str, Any] = {}
        self._writers: Dict[str, csv.DictWriter] = {}

    def on_trial_result(self, trial_id: str, result: dict) -> None:
        flat = _flatten(result)
        if trial_id not in self._writers:
            path = os.path.join(self._trial_dir(trial_id), "progress.csv")
            f = open(path, "w", newline="")
            writer = csv.DictWriter(f, fieldnames=list(flat.keys()),
                                    extrasaction="ignore")
            writer.writeheader()
            self._files[trial_id] = f
            self._writers[trial_id] = writer
        self._writers[trial_id].writerow(flat)
        self._files[trial_id].flush()

    def on_trial_complete(self, trial_id, error=None) -> None:
        f = self._files.pop(trial_id, None)
        if f is not None:
            f.close()
        self._writers.pop(trial_id, None)

    def on_experiment_end(self, results) -> None:
        for f in self._files.values():
            f.close()
        self._files.clear()
        self._writers.clear()


class JsonLoggerCallback(LoggerCallback):
    """result.json (one JSON line per report) per trial
    (reference: tune/logger/json.py)."""

    def __init__(self, experiment_dir: Optional[str] = None):
        super().__init__(experiment_dir)
        self._seen: set = set()

    def setup(self, experiment_dir: Optional[str] = None, **info) -> None:
        super().setup(experiment_dir=experiment_dir, **info)
        # Scope the truncation guard to one fit(): a restore that reuses
        # this callback instance must truncate stale result.json again.
        self._seen.clear()

    def on_trial_start(self, trial_id: str, config: dict) -> None:
        path = os.path.join(self._trial_dir(trial_id), "params.json")
        with open(path, "w") as f:
            json.dump(config, f, default=repr)
        if trial_id not in self._seen:
            # First start in this process: truncate any stale result.json
            # left by a pre-restore run of the same trial (a PBT exploit
            # relaunch in the same process keeps appending).
            self._seen.add(trial_id)
            result_path = os.path.join(self._trial_dir(trial_id),
                                       "result.json")
            open(result_path, "w").close()

    def on_trial_result(self, trial_id: str, result: dict) -> None:
        path = os.path.join(self._trial_dir(trial_id), "result.json")
        with open(path, "a") as f:
            f.write(json.dumps(result, default=repr) + "\n")
