"""Cluster YAML validation (analog of autoscaler/ray-schema.json).

The reference validates `ray up` YAML against a JSON schema before
touching the cloud; a typo'd key silently ignored is a cluster that
never comes up. Same contract here, hand-rolled (no jsonschema dep):
required fields, per-field types, and unknown-key rejection with a
did-you-mean hint.
"""

from __future__ import annotations

import difflib
from typing import Any, Dict

#: field -> (type, required). Top-level cluster config.
TOP_LEVEL = {
    "cluster_name": (str, True),
    "provider": (dict, True),
    "min_workers": (int, False),
    "max_workers": (int, False),
    "head_node": (dict, False),
    "worker_nodes": (dict, False),
    "file_mounts": (dict, False),
    "initialization_commands": (list, False),
    "setup_commands": (list, False),
    "head_setup_commands": (list, False),
    "worker_setup_commands": (list, False),
    "head_start_ray_commands": (list, False),
    "worker_start_ray_commands": (list, False),
    "idle_timeout_minutes": ((int, float), False),
    "auth": (dict, False),
}

PROVIDER_FIELDS = {
    "type": (str, True),
    # provider-specific extras (project/zone/head_address/...) pass
    # through unvalidated — each provider owns its own knobs, like the
    # reference's per-provider schema sections.
}

AUTH_FIELDS = {
    "ssh_user": (str, False),
    "ssh_private_key": (str, False),
    "ssh_port": (int, False),
}


class ClusterConfigError(ValueError):
    """The YAML does not describe a launchable cluster."""


def _type_name(tp) -> str:
    if isinstance(tp, tuple):
        return " or ".join(t.__name__ for t in tp)
    return tp.__name__


def _check_fields(section: Dict[str, Any], spec: Dict[str, Any],
                  where: str, reject_unknown: bool) -> None:
    for field, (tp, required) in spec.items():
        if field not in section:
            if required:
                raise ClusterConfigError(
                    f"{where}: missing required field {field!r}")
            continue
        if not isinstance(section[field], tp) or \
                isinstance(section[field], bool):
            raise ClusterConfigError(
                f"{where}: {field!r} must be {_type_name(tp)}, got "
                f"{type(section[field]).__name__}")
    if reject_unknown:
        for key in section:
            if key not in spec:
                hint = difflib.get_close_matches(key, spec, n=1)
                suffix = f" (did you mean {hint[0]!r}?)" if hint else ""
                raise ClusterConfigError(
                    f"{where}: unknown field {key!r}{suffix}")


def validate_cluster_config(config: Dict[str, Any]) -> Dict[str, Any]:
    """Raise ClusterConfigError on the first problem; returns the
    config for chaining."""
    if not isinstance(config, dict):
        raise ClusterConfigError("cluster config must be a mapping")
    _check_fields(config, TOP_LEVEL, "cluster config",
                  reject_unknown=True)
    _check_fields(config["provider"], PROVIDER_FIELDS, "provider",
                  reject_unknown=False)
    if "auth" in config:
        _check_fields(config["auth"], AUTH_FIELDS, "auth",
                      reject_unknown=True)
    from ray_tpu.autoscaler import PROVIDER_TYPES
    ptype = config["provider"]["type"]
    if ptype not in PROVIDER_TYPES:
        raise ClusterConfigError(
            f"provider.type {ptype!r} is not one of "
            f"{sorted(PROVIDER_TYPES)}")
    lo = int(config.get("min_workers", 0))
    hi = config.get("max_workers")
    if lo < 0:
        raise ClusterConfigError("min_workers must be >= 0")
    if hi is not None and int(hi) < lo:
        raise ClusterConfigError(
            f"max_workers ({hi}) < min_workers ({lo})")
    for list_field in ("initialization_commands", "setup_commands",
                      "head_setup_commands", "worker_setup_commands",
                      "head_start_ray_commands",
                      "worker_start_ray_commands"):
        for item in config.get(list_field, ()):
            if not isinstance(item, str):
                raise ClusterConfigError(
                    f"{list_field} entries must be strings, got "
                    f"{type(item).__name__}")
    for target, source in (config.get("file_mounts") or {}).items():
        if not isinstance(target, str) or not isinstance(source, str):
            raise ClusterConfigError(
                "file_mounts must map remote path (str) -> local "
                "path (str)")
    return config
