"""Pluggable node providers (analog of python/ray/autoscaler/node_provider.py:13).

The reference's `NodeProvider` abstracts the cloud behind create/terminate/
list/tag operations; concrete providers exist for AWS/GCP/Azure/local/fake.
Here the same interface drives virtual nodes (FakeMultiNodeProvider — the
analog of autoscaler/_private/fake_multi_node/node_provider.py used by
test_autoscaler_fake_multinode.py) and TPU pod slices (TPUPodNodeProvider —
the TPU-native provider the reference never had: one "node" is one TPU host
of a pod slice, carrying its chips as schedulable resources).
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

TAG_RAY_NODE_KIND = "ray-node-kind"
TAG_RAY_NODE_STATUS = "ray-node-status"
TAG_RAY_USER_NODE_TYPE = "ray-user-node-type"
NODE_KIND_HEAD = "head"
NODE_KIND_WORKER = "worker"
STATUS_UP_TO_DATE = "up-to-date"


class NodeProvider:
    """Interface; mirrors the reference's abstract methods."""

    def __init__(self, provider_config: Dict[str, Any], cluster_name: str):
        self.provider_config = dict(provider_config or {})
        self.cluster_name = cluster_name

    def non_terminated_nodes(self, tag_filters: Dict[str, str]) -> List[str]:
        raise NotImplementedError

    def is_running(self, node_id: str) -> bool:
        raise NotImplementedError

    def is_terminated(self, node_id: str) -> bool:
        return not self.is_running(node_id)

    def node_tags(self, node_id: str) -> Dict[str, str]:
        raise NotImplementedError

    def set_node_tags(self, node_id: str, tags: Dict[str, str]) -> None:
        raise NotImplementedError

    def create_node(self, node_config: Dict[str, Any],
                    tags: Dict[str, str], count: int) -> None:
        raise NotImplementedError

    def terminate_node(self, node_id: str) -> None:
        raise NotImplementedError

    def internal_ip(self, node_id: str) -> str:
        return node_id

    def external_ip(self, node_id: str) -> str:
        return node_id

    def runtime_node_hex(self, node_id: str) -> Optional[str]:
        """Map a provider node id to the runtime's NodeID hex (providers
        whose ids already ARE runtime ids — the virtual providers —
        return it unchanged)."""
        return node_id


class _RecordNodeProvider(NodeProvider):
    """Shared bookkeeping for providers that track nodes as local records
    (lock + id→record dict + tag filtering); subclasses define what
    "alive" means and how nodes are created/terminated."""

    def __init__(self, provider_config: Optional[Dict[str, Any]] = None,
                 cluster_name: str = "local"):
        super().__init__(provider_config or {}, cluster_name)
        self._lock = threading.Lock()
        self._nodes: Dict[str, dict] = {}  # provider node id -> record

    def _is_alive(self, rec: dict) -> bool:
        raise NotImplementedError

    def non_terminated_nodes(self, tag_filters: Dict[str, str]) -> List[str]:
        with self._lock:
            out = []
            for node_id, rec in self._nodes.items():
                if not self._is_alive(rec):
                    continue
                if all(rec["tags"].get(k) == v
                       for k, v in (tag_filters or {}).items()):
                    out.append(node_id)
            return out

    def is_running(self, node_id: str) -> bool:
        with self._lock:
            rec = self._nodes.get(node_id)
            return rec is not None and self._is_alive(rec)

    def node_tags(self, node_id: str) -> Dict[str, str]:
        with self._lock:
            return dict(self._nodes[node_id]["tags"])

    def set_node_tags(self, node_id: str, tags: Dict[str, str]) -> None:
        with self._lock:
            self._nodes[node_id]["tags"].update(tags)


class FakeMultiNodeProvider(_RecordNodeProvider):
    """Launches virtual nodes into the live in-process cluster."""

    def __init__(self, provider_config: Optional[Dict[str, Any]] = None,
                 cluster_name: str = "fake"):
        super().__init__(provider_config, cluster_name)
        #: Bootstrap commands executed against this provider's nodes,
        #: recorded as (node_id, command) — the offline up/down test's
        #: observability into the updater lifecycle.
        self.command_log: list = []

    def get_command_runner(self, node_id: str, config: dict):
        from ray_tpu.autoscaler.command_runner import LocalCommandRunner
        return LocalCommandRunner(node_id, record=self.command_log)

    def _runtime(self):
        from ray_tpu._private.worker import global_worker
        return global_worker.runtime

    def _is_alive(self, rec: dict) -> bool:
        return not rec["terminated"]

    def create_node(self, node_config: Dict[str, Any],
                    tags: Dict[str, str], count: int) -> None:
        resources = dict(node_config.get("resources", {"CPU": 1}))
        resources.setdefault("memory", 1 << 30)
        runtime = self._runtime()
        for _ in range(count):
            vnode_id = runtime.add_node(resources)
            tags = dict(tags)
            tags.setdefault(TAG_RAY_NODE_STATUS, STATUS_UP_TO_DATE)
            with self._lock:
                self._nodes[vnode_id.hex()] = {
                    "tags": dict(tags),
                    "resources": resources,
                    "vnode_id": vnode_id,
                    "terminated": False,
                }

    def terminate_node(self, node_id: str) -> None:
        with self._lock:
            rec = self._nodes.get(node_id)
            if rec is None or rec["terminated"]:
                return
            rec["terminated"] = True
            vnode_id = rec["vnode_id"]
        self._runtime().remove_node(vnode_id)


# TPU pod slice topologies: accelerator type -> (hosts, chips per host).
# One autoscaler "node" = one host of the slice (4 chips on v4/v5p hosts,
# 8 on v5e/v6e single-host topologies vary; this table covers the common
# slices the JaxTrainer mesh config understands).
TPU_POD_TOPOLOGIES = {
    "v4-8": (1, 4),
    "v4-16": (2, 4),
    "v4-32": (4, 4),
    "v4-64": (8, 4),
    "v4-128": (16, 4),
    "v5p-8": (1, 4),
    "v5p-16": (2, 4),
    "v5litepod-8": (1, 8),
    "v5litepod-16": (2, 8),
    "v6e-8": (1, 8),
}


class TPUPodNodeProvider(FakeMultiNodeProvider):
    """Models TPU pod slices: `create_node` with an ``accelerator_type``
    node_config brings up every host of the slice at once (a slice is atomic
    — it fails and scales as a unit, unlike GPU nodes), each host carrying
    its chips plus an ``accelerator_type:TPU-<gen>`` constraint resource the
    way the reference auto-adds accelerator_type:<X> for GPUs
    (python/ray/_private/resource_spec.py:181-186)."""

    def create_node(self, node_config: Dict[str, Any],
                    tags: Dict[str, str], count: int) -> None:
        acc = node_config.get("accelerator_type", "v4-8")
        if acc not in TPU_POD_TOPOLOGIES:
            raise ValueError(
                f"Unknown TPU pod topology {acc!r}; known: "
                f"{sorted(TPU_POD_TOPOLOGIES)}")
        hosts, chips = TPU_POD_TOPOLOGIES[acc]
        gen = acc.split("-")[0].split("litepod")[0].upper()
        for _ in range(count):
            slice_tags = dict(tags)
            slice_tags["tpu-slice"] = acc
            cfg = {
                "resources": {
                    "CPU": float(node_config.get("cpus_per_host", 8)),
                    "TPU": float(chips),
                    f"accelerator_type:TPU-{gen}": 1.0,
                    f"TPU-{acc}-head": 1.0,  # rank-0 host marker
                },
            }
            super().create_node(cfg, slice_tags, 1)
            for _ in range(hosts - 1):
                host_cfg = {"resources": dict(cfg["resources"])}
                del host_cfg["resources"][f"TPU-{acc}-head"]
                super().create_node(host_cfg, slice_tags, 1)


class DaemonProcessNodeProvider(_RecordNodeProvider):
    """Launches REAL node-daemon processes against the live head server
    (the analog of a cloud provider booting worker VMs that `ray start
    --address=head` into the cluster): create_node spawns `python -m
    ray_tpu._private.multinode` subprocesses, terminate_node signals them
    (non-blocking; SIGKILL escalation on a later reconcile pass) — the
    head's connection-death handling then removes the node exactly like a
    cloud instance disappearing."""

    _KILL_GRACE_S = 5.0

    def __init__(self, provider_config: Optional[Dict[str, Any]] = None,
                 cluster_name: str = "daemons"):
        super().__init__(provider_config, cluster_name)
        self._counter = 0
        self._hex_cache: Dict[str, str] = {}
        self._alive_ids: set = set()
        self._alive_checked_at = 0.0
        address = self.provider_config.get("head_address")
        if not address:
            # Default: open (or reuse) this driver's head server.
            from ray_tpu._private.worker import (global_worker,
                                                 start_head_server)
            if not global_worker.connected:
                raise RuntimeError(
                    "DaemonProcessNodeProvider needs ray_tpu.init() first "
                    "(or an explicit provider_config['head_address'])")
            host, port = start_head_server(host="127.0.0.1")
            address = f"127.0.0.1:{port}"
        self.head_address = address

    def _is_alive(self, rec: dict) -> bool:
        import time
        proc = rec["proc"]
        if proc.poll() is not None:  # also reaps exited children
            return False
        # SIGTERM-ignoring daemon: escalate to SIGKILL after the grace.
        asked = rec.get("terminate_requested")
        if asked is not None and time.time() - asked > self._KILL_GRACE_S:
            proc.kill()
        # Reconcile with the head's view: a daemon the health checks
        # declared dead (hung process, socket still up) must not keep
        # counting against max_workers — kill the leftover process.
        alive_ids = self._runtime_alive_ids()
        if alive_ids is None:
            return True  # no runtime to consult — liveness unknown
        if not rec.get("joined"):
            if rec["id"] in alive_ids:
                rec["joined"] = True
            return True  # still connecting to the head
        if rec["id"] not in alive_ids:
            proc.kill()
            return False
        return True

    def _runtime_alive_ids(self):
        """Alive provider ids per the head's scheduler, memoized ~1s;
        None when there is no local runtime to consult (a disconnected
        driver must read as 'unknown', never as 'everything died')."""
        import time
        from ray_tpu._private.worker import global_worker
        if not global_worker.connected:
            self._alive_checked_at = 0.0
            return None
        now = time.monotonic()
        if now - self._alive_checked_at > 1.0:
            self._alive_ids = set()
            for node in global_worker.runtime.scheduler.nodes_snapshot():
                pid = node["Labels"].get("provider_node_id")
                if pid and node["Alive"]:
                    self._alive_ids.add(pid)
            self._alive_checked_at = now
        return self._alive_ids

    def create_node(self, node_config: Dict[str, Any],
                    tags: Dict[str, str], count: int) -> None:
        import json
        import subprocess
        import sys
        resources = dict(node_config.get("resources", {"CPU": 1}))
        num_cpus = float(resources.pop("CPU", 1))
        num_tpus = float(resources.pop("TPU", 0))
        memory = float(resources.pop("memory", 1 << 30))
        for _ in range(count):
            with self._lock:
                self._counter += 1
                provider_id = f"daemon-{self._counter}"
            cmd = [sys.executable, "-m", "ray_tpu._private.multinode",
                   "--address", self.head_address,
                   "--num-cpus", str(num_cpus),
                   "--num-tpus", str(num_tpus),
                   "--memory", str(memory),
                   # The daemon self-labels so the head-side runtime node
                   # can be matched back to this provider node.
                   "--labels", json.dumps({"provider_node_id":
                                           provider_id})]
            if resources:
                cmd += ["--resources", json.dumps(resources)]
            # Pre-registration daemon output goes to session launch
            # logs when a session exists (never DEVNULL — a daemon
            # that dies before joining must leave its words somewhere);
            # once registered it re-routes into per-proc raylet files.
            from ray_tpu._private import ray_logging
            out_f, err_f = ray_logging.open_launch_capture("autoscaler-daemon")
            kwargs = {}
            if out_f is not None:
                kwargs = {"stdout": out_f, "stderr": err_f}
            try:
                proc = subprocess.Popen(cmd, **kwargs)
            finally:
                for f in (out_f, err_f):
                    if f is not None:
                        f.close()  # the child holds its own copy
            node_tags = dict(tags)
            node_tags.setdefault(TAG_RAY_NODE_STATUS, STATUS_UP_TO_DATE)
            with self._lock:
                self._nodes[provider_id] = {
                    "id": provider_id,
                    "proc": proc, "tags": node_tags,
                    "resources": dict(node_config.get("resources", {})),
                }

    def terminate_node(self, node_id: str) -> None:
        import time
        with self._lock:
            rec = self._nodes.get(node_id)
            if rec is None:
                return
            rec.setdefault("terminate_requested", time.time())
            proc = rec["proc"]
        if proc.poll() is None:
            proc.terminate()  # non-blocking; _is_alive escalates later

    def internal_ip(self, node_id: str) -> str:
        return "127.0.0.1"

    external_ip = internal_ip

    def runtime_node_hex(self, node_id: str) -> Optional[str]:
        cached = self._hex_cache.get(node_id)
        if cached is not None:
            return cached
        from ray_tpu._private.worker import global_worker
        if not global_worker.connected:
            # A provider pointed at a REMOTE head has no local runtime to
            # consult — never auto-init a stray local cluster here.
            return None
        for node in global_worker.runtime.scheduler.nodes_snapshot():
            pid = node["Labels"].get("provider_node_id")
            if pid and node["Alive"]:
                self._hex_cache[pid] = node["NodeID"]
        return self._hex_cache.get(node_id)
