"""The autoscaler loop (analog of autoscaler/_private/autoscaler.py:168).

`StandardAutoscaler.update()` mirrors the reference's control loop: read
LoadMetrics (pending resource demand + per-node idleness from the cluster
scheduler, the analog of GCS resource reports), bin-pack unmet demand onto
configured node types (resource_demand_scheduler.py), launch via the
NodeProvider, and terminate nodes idle past the timeout. TPU specifics: a
node type whose config names an ``accelerator_type`` launches whole pod
slices atomically (TPUPodNodeProvider), because a slice is the unit of both
scheduling (an ICI mesh) and failure.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

from ray_tpu.autoscaler.node_provider import (NODE_KIND_WORKER,
                                              TAG_RAY_NODE_KIND,
                                              TAG_RAY_USER_NODE_TYPE,
                                              NodeProvider)


class LoadMetrics:
    """Cluster load snapshot (analog of autoscaler/_private/load_metrics.py;
    source = the in-process cluster scheduler instead of GCS reports)."""

    def __init__(self):
        self.pending_demand: List[Dict[str, float]] = []
        self.node_utilization: Dict[str, float] = {}
        self.node_idle_since: Dict[str, float] = {}
        self._last_update = 0.0

    def update(self) -> None:
        from ray_tpu._private.worker import global_worker
        runtime = global_worker.runtime
        self.pending_demand = runtime.pending_resource_demand()
        now = time.time()
        for state in runtime.scheduler.alive_nodes():
            node_id = state.node_id.hex()
            util = state.utilization()
            self.node_utilization[node_id] = util
            if util > 0:
                self.node_idle_since.pop(node_id, None)
            else:
                self.node_idle_since.setdefault(node_id, now)
        self._last_update = now


def _fits(capacity: Dict[str, float], demand: Dict[str, float]) -> bool:
    return all(capacity.get(k, 0.0) >= v for k, v in demand.items()
               if not k.startswith("node:"))


class StandardAutoscaler:
    """Config schema (subset of the reference's cluster YAML):

    .. code-block:: python

        {
          "max_workers": 8,
          "idle_timeout_minutes": 5,
          "available_node_types": {
            "cpu-worker": {"resources": {"CPU": 4},
                           "min_workers": 0, "max_workers": 4},
            "tpu-v4-8": {"node_config": {"accelerator_type": "v4-8"},
                         "resources": {"TPU": 4, "CPU": 8},
                         "min_workers": 0, "max_workers": 2},
          },
        }
    """

    def __init__(self, provider: NodeProvider, config: Dict[str, Any],
                 load_metrics: Optional[LoadMetrics] = None):
        self.provider = provider
        self.config = dict(config)
        self.load_metrics = load_metrics or LoadMetrics()
        self.node_types: Dict[str, dict] = dict(
            config.get("available_node_types", {}))
        self.max_workers = int(config.get("max_workers", 8))
        self.idle_timeout_s = float(
            config.get("idle_timeout_minutes", 5)) * 60.0
        self.num_launches = 0
        self.num_terminations = 0

    # -- views ------------------------------------------------------------

    def workers_of_type(self, type_name: str) -> List[str]:
        return self.provider.non_terminated_nodes(
            {TAG_RAY_USER_NODE_TYPE: type_name})

    def total_workers(self) -> List[str]:
        return self.provider.non_terminated_nodes(
            {TAG_RAY_NODE_KIND: NODE_KIND_WORKER})

    # -- the loop body ----------------------------------------------------

    def update(self) -> Dict[str, int]:
        """One reconcile pass. Returns {"launched": n, "terminated": m}."""
        self.load_metrics.update()
        launched = self._scale_up()
        terminated = self._scale_down()
        return {"launched": launched, "terminated": terminated}

    def _scale_up(self) -> int:
        # Enforce per-type min_workers first.
        launched = 0
        for type_name, spec in self.node_types.items():
            want = int(spec.get("min_workers", 0))
            have = len(self.workers_of_type(type_name))
            if have < want:
                n = want - have
                self._launch(type_name, n)
                launched += n
        # Bin-pack unmet demand: demands that no alive node can ever fit
        # need a new node of a type whose resources cover them.
        unmet = self._unmet_demand()
        for demand in unmet:
            if len(self.total_workers()) + launched >= self.max_workers:
                break
            type_name = self._pick_node_type(demand)
            if type_name is None:
                continue
            spec = self.node_types[type_name]
            if len(self.workers_of_type(type_name)) >= int(
                    spec.get("max_workers", self.max_workers)):
                continue
            self._launch(type_name, 1)
            launched += 1
        return launched

    def _unmet_demand(self) -> List[Dict[str, float]]:
        from ray_tpu._private.worker import global_worker
        runtime = global_worker.runtime
        caps = [dict(s.local.total)
                for s in runtime.scheduler.alive_nodes()]
        unmet = []
        for demand in self.load_metrics.pending_demand:
            if not any(_fits(cap, demand) for cap in caps):
                unmet.append(demand)
        return unmet

    def _pick_node_type(self, demand: Dict[str, float]) -> Optional[str]:
        best = None
        best_size = float("inf")
        for type_name, spec in self.node_types.items():
            resources = spec.get("resources", {})
            if _fits(resources, demand):
                size = sum(v for v in resources.values())
                if size < best_size:
                    best, best_size = type_name, size
        return best

    def _launch(self, type_name: str, count: int) -> None:
        spec = self.node_types[type_name]
        node_config = dict(spec.get("node_config", {}))
        if "resources" not in node_config and "resources" in spec:
            node_config["resources"] = dict(spec["resources"])
        self.provider.create_node(
            node_config,
            {TAG_RAY_NODE_KIND: NODE_KIND_WORKER,
             TAG_RAY_USER_NODE_TYPE: type_name},
            count)
        self.num_launches += count

    def _scale_down(self) -> int:
        now = time.time()
        terminated = 0
        for type_name, spec in self.node_types.items():
            keep = int(spec.get("min_workers", 0))
            workers = self.workers_of_type(type_name)
            for node_id in workers:
                if len(self.workers_of_type(type_name)) <= keep:
                    break
                hex_id = self.provider.runtime_node_hex(node_id) or node_id
                idle_since = self.load_metrics.node_idle_since.get(hex_id)
                if idle_since is not None and \
                        now - idle_since > self.idle_timeout_s:
                    self.provider.terminate_node(node_id)
                    self.num_terminations += 1
                    terminated += 1
        return terminated
