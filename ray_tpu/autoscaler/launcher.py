"""Cluster launcher: `ray-tpu up / down cluster.yaml`.

Analog of the reference's `ray up` / `ray down`
(scripts/scripts.py:1216,1292 over autoscaler/commands.py): a YAML
describes the provider and worker fleet; `up` creates the head-tagged
node plus ``min_workers`` workers through the provider registry
(`PROVIDER_TYPES`), `down` terminates every non-terminated node of the
cluster. The reference's SSH/docker setup phase collapses here — node
bootstrap is the provider's concern (GCloudTPUNodeProvider runs
`ray-tpu start` over `gcloud ssh`; the daemon provider spawns joined
processes directly).

YAML schema (the subset of autoscaler/ray-schema.json this runtime
uses)::

    cluster_name: my-cluster
    provider:
      type: gcp_tpu            # PROVIDER_TYPES key
      project: my-project
      zone: us-central2-b
      head_address: 10.0.0.2:6380
    min_workers: 2
    max_workers: 8             # recorded for the autoscaler
    worker_nodes:              # provider-specific node_config
      accelerator_type: v4-8
"""

from __future__ import annotations

import logging
from typing import Any, Dict, List

from ray_tpu.autoscaler.node_provider import (NODE_KIND_HEAD,
                                              NODE_KIND_WORKER,
                                              TAG_RAY_NODE_KIND,
                                              TAG_RAY_NODE_STATUS,
                                              TAG_RAY_USER_NODE_TYPE)

logger = logging.getLogger(__name__)


def load_cluster_config(path: str) -> Dict[str, Any]:
    import yaml

    from ray_tpu.autoscaler.schema import validate_cluster_config
    with open(path) as f:
        config = yaml.safe_load(f) or {}
    # Schema validation BEFORE touching the cloud (reference:
    # autoscaler/ray-schema.json via commands.py _bootstrap_config): a
    # typo'd key must fail here, not produce a cluster that never joins.
    return validate_cluster_config(config)


def _provider_for(config: Dict[str, Any]):
    from ray_tpu.autoscaler import get_node_provider
    return get_node_provider(config["provider"],
                             config["cluster_name"])


def _make_runner(provider, node_id: str, config: Dict[str, Any]):
    """Provider override first (fake/local providers run commands
    locally); otherwise plain ssh from the YAML's auth section
    (reference: NodeProvider.get_command_runner, node_provider.py)."""
    get = getattr(provider, "get_command_runner", None)
    if get is not None:
        return get(node_id, config)
    from ray_tpu.autoscaler.command_runner import SSHCommandRunner
    auth = config.get("auth", {})
    return SSHCommandRunner(
        provider.external_ip(node_id),
        ssh_user=auth.get("ssh_user", "ubuntu"),
        ssh_key=auth.get("ssh_private_key"),
        ssh_port=int(auth.get("ssh_port", 22)))


def _bootstrap_nodes(provider, config: Dict[str, Any],
                     node_ids: List[str], kind: str,
                     head_address: str) -> List[str]:
    """Run the updater lifecycle on freshly created nodes; returns ids
    that FAILED bootstrap (reference: commands.py get_or_create_head_node
    + NodeUpdaterThread per worker)."""
    setup = list(config.get("setup_commands", ())) + list(
        config.get(f"{kind}_setup_commands", ()))
    start = list(config.get(f"{kind}_start_ray_commands", ()))
    if not (setup or start or config.get("file_mounts")
            or config.get("initialization_commands")):
        return []  # provider self-joins its nodes (gcp_tpu does)
    if kind == "worker" and start and not head_address:
        # Exporting RAY_TPU_HEAD_ADDRESS='' would start workers that
        # silently never join. Fail the bootstrap loudly instead, and
        # tag update-failed so the next `up` retries these nodes once
        # a head exists (the retry filter keys off this tag).
        logger.error(
            "worker bootstrap skipped for %s: no head address (set "
            "provider.head_address or bring up a head first)", node_ids)
        from ray_tpu.autoscaler.updater import STATUS_UPDATE_FAILED
        for node_id in node_ids:
            provider.set_node_tags(
                node_id, {TAG_RAY_NODE_STATUS: STATUS_UPDATE_FAILED})
        return list(node_ids)
    from ray_tpu.autoscaler.updater import NodeUpdater, run_updaters
    updaters = [NodeUpdater(
        node_id=node_id, provider=provider,
        runner=_make_runner(provider, node_id, config),
        file_mounts=config.get("file_mounts"),
        initialization_commands=config.get("initialization_commands"),
        setup_commands=setup, start_commands=start,
        env={"RAY_TPU_HEAD_ADDRESS": head_address},
    ) for node_id in node_ids]
    return [u.node_id for u in run_updaters(updaters)]


def _head_address(provider, config: Dict[str, Any]) -> str:
    """The address workers join: explicit provider.head_address, else
    the (possibly just-created) head node's internal IP + head_port
    (reference: commands.py derives the head IP before worker updaters
    run — a fresh cluster has no address in the YAML)."""
    explicit = config["provider"].get("head_address", "")
    if explicit:
        return explicit
    heads = provider.non_terminated_nodes(
        {TAG_RAY_NODE_KIND: NODE_KIND_HEAD})
    if not heads:
        return ""
    port = int(config["provider"].get("head_port", 6380))
    return f"{provider.internal_ip(heads[0])}:{port}"


def up(config_path: str, *, no_head: bool = False) -> Dict[str, Any]:
    """Create the cluster: one head node (unless the provider config
    points at an existing head via ``head_address`` and ``no_head``)
    plus ``min_workers`` workers, then BOOTSTRAP each new node (file
    mounts, setup commands, start commands) so a fresh VM installs and
    joins without manual steps. Idempotent: existing nodes of each kind
    are counted, only the shortfall is created."""
    config = load_cluster_config(config_path)
    provider = _provider_for(config)
    created: Dict[str, int] = {"head": 0, "workers": 0}
    new_heads: List[str] = []
    if not no_head and not config["provider"].get("head_address"):
        heads = provider.non_terminated_nodes(
            {TAG_RAY_NODE_KIND: NODE_KIND_HEAD})
        if not heads:
            provider.create_node(
                dict(config.get("head_node", {})),
                {TAG_RAY_NODE_KIND: NODE_KIND_HEAD,
                 TAG_RAY_USER_NODE_TYPE: "head"}, 1)
            created["head"] = 1
            new_heads = [n for n in provider.non_terminated_nodes(
                {TAG_RAY_NODE_KIND: NODE_KIND_HEAD}) if n not in heads]
    want = int(config.get("min_workers", 0))
    before = provider.non_terminated_nodes(
        {TAG_RAY_NODE_KIND: NODE_KIND_WORKER})
    if want > len(before):
        provider.create_node(
            dict(config.get("worker_nodes", {})),
            {TAG_RAY_NODE_KIND: NODE_KIND_WORKER,
             TAG_RAY_USER_NODE_TYPE: "worker"}, want - len(before))
        created["workers"] = want - len(before)
    new_workers = [n for n in provider.non_terminated_nodes(
        {TAG_RAY_NODE_KIND: NODE_KIND_WORKER}) if n not in before]
    # Re-up RETRIES update-failed nodes of BOTH kinds (reference: the
    # updater re-runs on any non-up-to-date node): without this, a node
    # that failed its setup command counts toward the fleet forever and
    # the cluster sits permanently degraded. One tag-filtered list call
    # per kind — a per-node node_tags() scan would cost one provider
    # RPC per worker on every routine re-up.
    from ray_tpu.autoscaler.updater import STATUS_UPDATE_FAILED
    failed_filter = {TAG_RAY_NODE_STATUS: STATUS_UPDATE_FAILED}
    retry_heads = [n for n in provider.non_terminated_nodes(
        {TAG_RAY_NODE_KIND: NODE_KIND_HEAD, **failed_filter})
        if n not in new_heads]
    retry_workers = [n for n in provider.non_terminated_nodes(
        {TAG_RAY_NODE_KIND: NODE_KIND_WORKER, **failed_filter})
        if n not in new_workers]
    head_address = _head_address(provider, config)
    # Head bootstraps FIRST: workers' start commands join its address.
    failed = _bootstrap_nodes(provider, config, new_heads + retry_heads,
                              "head", head_address) + \
        _bootstrap_nodes(provider, config, new_workers + retry_workers,
                         "worker", head_address)
    nodes = provider.non_terminated_nodes({})
    return {"cluster_name": config["cluster_name"],
            "created": created, "nodes": nodes,
            "bootstrap_failed": failed}


def down(config_path: str) -> List[str]:
    """Terminate every non-terminated node of the cluster."""
    config = load_cluster_config(config_path)
    provider = _provider_for(config)
    nodes = provider.non_terminated_nodes({})
    for node_id in nodes:
        provider.terminate_node(node_id)
    return nodes
