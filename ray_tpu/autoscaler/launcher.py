"""Cluster launcher: `ray-tpu up / down cluster.yaml`.

Analog of the reference's `ray up` / `ray down`
(scripts/scripts.py:1216,1292 over autoscaler/commands.py): a YAML
describes the provider and worker fleet; `up` creates the head-tagged
node plus ``min_workers`` workers through the provider registry
(`PROVIDER_TYPES`), `down` terminates every non-terminated node of the
cluster. The reference's SSH/docker setup phase collapses here — node
bootstrap is the provider's concern (GCloudTPUNodeProvider runs
`ray-tpu start` over `gcloud ssh`; the daemon provider spawns joined
processes directly).

YAML schema (the subset of autoscaler/ray-schema.json this runtime
uses)::

    cluster_name: my-cluster
    provider:
      type: gcp_tpu            # PROVIDER_TYPES key
      project: my-project
      zone: us-central2-b
      head_address: 10.0.0.2:6380
    min_workers: 2
    max_workers: 8             # recorded for the autoscaler
    worker_nodes:              # provider-specific node_config
      accelerator_type: v4-8
"""

from __future__ import annotations

from typing import Any, Dict, List

from ray_tpu.autoscaler.node_provider import (NODE_KIND_HEAD,
                                              NODE_KIND_WORKER,
                                              TAG_RAY_NODE_KIND,
                                              TAG_RAY_USER_NODE_TYPE)


def load_cluster_config(path: str) -> Dict[str, Any]:
    import yaml
    with open(path) as f:
        config = yaml.safe_load(f) or {}
    for req in ("cluster_name", "provider"):
        if req not in config:
            raise ValueError(f"cluster config needs a {req!r} field")
    if "type" not in config["provider"]:
        raise ValueError("provider needs a 'type' "
                         "(one of the PROVIDER_TYPES keys)")
    return config


def _provider_for(config: Dict[str, Any]):
    from ray_tpu.autoscaler import get_node_provider
    return get_node_provider(config["provider"],
                             config["cluster_name"])


def up(config_path: str, *, no_head: bool = False) -> Dict[str, Any]:
    """Create the cluster: one head node (unless the provider config
    points at an existing head via ``head_address`` and ``no_head``)
    plus ``min_workers`` workers. Idempotent: existing nodes of each
    kind are counted, only the shortfall is created."""
    config = load_cluster_config(config_path)
    provider = _provider_for(config)
    created: Dict[str, int] = {"head": 0, "workers": 0}
    if not no_head and not config["provider"].get("head_address"):
        heads = provider.non_terminated_nodes(
            {TAG_RAY_NODE_KIND: NODE_KIND_HEAD})
        if not heads:
            provider.create_node(
                dict(config.get("head_node", {})),
                {TAG_RAY_NODE_KIND: NODE_KIND_HEAD,
                 TAG_RAY_USER_NODE_TYPE: "head"}, 1)
            created["head"] = 1
    want = int(config.get("min_workers", 0))
    have = len(provider.non_terminated_nodes(
        {TAG_RAY_NODE_KIND: NODE_KIND_WORKER}))
    if want > have:
        provider.create_node(
            dict(config.get("worker_nodes", {})),
            {TAG_RAY_NODE_KIND: NODE_KIND_WORKER,
             TAG_RAY_USER_NODE_TYPE: "worker"}, want - have)
        created["workers"] = want - have
    nodes = provider.non_terminated_nodes({})
    return {"cluster_name": config["cluster_name"],
            "created": created, "nodes": nodes}


def down(config_path: str) -> List[str]:
    """Terminate every non-terminated node of the cluster."""
    config = load_cluster_config(config_path)
    provider = _provider_for(config)
    nodes = provider.non_terminated_nodes({})
    for node_id in nodes:
        provider.terminate_node(node_id)
    return nodes
