from ray_tpu.autoscaler.autoscaler import LoadMetrics, StandardAutoscaler
from ray_tpu.autoscaler.gcp import GCloudTPUNodeProvider
from ray_tpu.autoscaler.node_provider import (DaemonProcessNodeProvider,
                                              FakeMultiNodeProvider,
                                              NodeProvider,
                                              TPUPodNodeProvider)

#: Provider registry (reference: autoscaler/_private/providers.py
#: _get_node_provider): cluster-config "provider.type" -> class.
PROVIDER_TYPES = {
    "fake_multinode": FakeMultiNodeProvider,
    "tpu_pod": TPUPodNodeProvider,
    "daemon_process": DaemonProcessNodeProvider,
    "gcp_tpu": GCloudTPUNodeProvider,
}


def get_node_provider(provider_config: dict,
                      cluster_name: str) -> NodeProvider:
    """Instantiate the provider named by provider_config['type']."""
    ptype = (provider_config or {}).get("type", "fake_multinode")
    try:
        cls = PROVIDER_TYPES[ptype]
    except KeyError:
        raise ValueError(
            f"Unknown provider type {ptype!r}; available: "
            f"{sorted(PROVIDER_TYPES)}") from None
    return cls(provider_config, cluster_name)


__all__ = [
    "StandardAutoscaler",
    "LoadMetrics",
    "NodeProvider",
    "DaemonProcessNodeProvider",
    "FakeMultiNodeProvider",
    "TPUPodNodeProvider",
    "GCloudTPUNodeProvider",
    "PROVIDER_TYPES",
    "get_node_provider",
]
