from ray_tpu.autoscaler.autoscaler import LoadMetrics, StandardAutoscaler
from ray_tpu.autoscaler.node_provider import (DaemonProcessNodeProvider,
                                              FakeMultiNodeProvider,
                                              NodeProvider,
                                              TPUPodNodeProvider)

__all__ = [
    "StandardAutoscaler",
    "LoadMetrics",
    "NodeProvider",
    "DaemonProcessNodeProvider",
    "FakeMultiNodeProvider",
    "TPUPodNodeProvider",
]
