"""Command runners: how the updater reaches a node to bootstrap it.

Analog of the reference's autoscaler/_private/command_runner.py:
SSHCommandRunner (ssh + ControlMaster + retries, rsync file mounts) and
DockerCommandRunner. TPU adaptation: `GcloudSSHCommandRunner` wraps
`gcloud compute tpus tpu-vm ssh` (TPU VMs are not directly
ssh-addressable without the gcloud IAP/hostkey plumbing), and
`LocalCommandRunner` executes on the current host (offline tests, and
single-host "clusters" of daemon processes).
"""

from __future__ import annotations

import logging
import os
import subprocess
import time
from typing import Any, Dict, List, Optional

logger = logging.getLogger(__name__)

#: ssh options mirroring the reference's (command_runner.py:130): fail
#: fast on dead hosts, no interactive prompts, multiplex connections.
SSH_OPTIONS = [
    "-o", "ConnectTimeout=10s",
    "-o", "StrictHostKeyChecking=no",
    "-o", "UserKnownHostsFile=/dev/null",
    "-o", "IdentitiesOnly=yes",
    "-o", "ExitOnForwardFailure=yes",
    "-o", "ServerAliveInterval=5",
    "-o", "ServerAliveCountMax=3",
]


class CommandRunnerError(RuntimeError):
    """A bootstrap command failed on the node (non-zero exit)."""

    def __init__(self, msg: str, exit_code: int, output: str = ""):
        super().__init__(msg)
        self.exit_code = exit_code
        self.output = output


class CommandRunnerInterface:
    """Run shell commands / sync files on one cluster node."""

    def run(self, cmd: str, *, timeout: float = 600.0,
            environment_variables: Optional[Dict[str, str]] = None) -> str:
        raise NotImplementedError

    def run_rsync_up(self, source: str, target: str) -> None:
        """Copy local ``source`` to node ``target``."""
        raise NotImplementedError

    def remote_shell_command_str(self) -> str:
        """The copy-pasteable shell line to reach this node."""
        raise NotImplementedError


def _env_prefix(env: Optional[Dict[str, str]]) -> str:
    if not env:
        return ""
    import shlex
    return "export " + " ".join(
        f"{k}={shlex.quote(str(v))}" for k, v in env.items()) + "; "


class SSHCommandRunner(CommandRunnerInterface):
    """Plain ssh/rsync runner (reference: command_runner.py:228
    SSHCommandRunner.run): used for any provider whose nodes expose an
    IP + key pair."""

    def __init__(self, node_ip: str, *, ssh_user: str = "ubuntu",
                 ssh_key: Optional[str] = None, ssh_port: int = 22):
        self.node_ip = node_ip
        self.ssh_user = ssh_user
        self.ssh_key = ssh_key
        self.ssh_port = ssh_port

    def _base_cmd(self) -> List[str]:
        cmd = ["ssh"] + SSH_OPTIONS + ["-p", str(self.ssh_port)]
        if self.ssh_key:
            cmd += ["-i", self.ssh_key]
        cmd.append(f"{self.ssh_user}@{self.node_ip}")
        return cmd

    def run(self, cmd: str, *, timeout: float = 600.0,
            environment_variables: Optional[Dict[str, str]] = None) -> str:
        full = self._base_cmd() + [
            "bash", "-lc",
            _quote(_env_prefix(environment_variables) + cmd)]
        return _checked_run(full, timeout, describe=f"ssh {self.node_ip}")

    def run_rsync_up(self, source: str, target: str) -> None:
        ssh_part = " ".join(
            ["ssh"] + SSH_OPTIONS + ["-p", str(self.ssh_port)] +
            (["-i", self.ssh_key] if self.ssh_key else []))
        cmd = ["rsync", "-az", "-e", ssh_part, source,
               f"{self.ssh_user}@{self.node_ip}:{target}"]
        _checked_run(cmd, 600.0, describe=f"rsync to {self.node_ip}")

    def remote_shell_command_str(self) -> str:
        return " ".join(self._base_cmd())


class GcloudSSHCommandRunner(CommandRunnerInterface):
    """`gcloud compute tpus tpu-vm ssh` runner: TPU VMs sit behind
    google's ssh wrapper (keys/IAP handled by gcloud), and pod slices
    need ``--worker`` targeting (reference has no TPU-pod runner; its
    GCP support predates TPU VMs)."""

    def __init__(self, node_id: str, *, project: str, zone: str,
                 worker="all"):
        self.node_id = node_id
        self.project = project
        self.zone = zone
        self.worker = worker

    def _base_cmd(self, remote: str) -> List[str]:
        return ["gcloud", "compute", "tpus", "tpu-vm", "ssh",
                self.node_id, "--project", self.project,
                "--zone", self.zone, "--worker", str(self.worker),
                "--command", remote, "--quiet"]

    def run(self, cmd: str, *, timeout: float = 600.0,
            environment_variables: Optional[Dict[str, str]] = None) -> str:
        remote = _env_prefix(environment_variables) + cmd
        return _checked_run(self._base_cmd(remote), timeout,
                            describe=f"gcloud ssh {self.node_id}")

    def run_rsync_up(self, source: str, target: str) -> None:
        cmd = ["gcloud", "compute", "tpus", "tpu-vm", "scp",
               "--recurse", source,
               f"{self.node_id}:{target}",
               "--project", self.project, "--zone", self.zone,
               "--worker", str(self.worker), "--quiet"]
        _checked_run(cmd, 600.0, describe=f"gcloud scp {self.node_id}")

    def remote_shell_command_str(self) -> str:
        return (f"gcloud compute tpus tpu-vm ssh {self.node_id} "
                f"--project {self.project} --zone {self.zone}")


class LocalCommandRunner(CommandRunnerInterface):
    """Execute on the current host (offline tests; local providers).
    Commands run through bash so the same YAML command strings work
    against every runner."""

    def __init__(self, node_id: str = "local", record: Optional[list] = None):
        self.node_id = node_id
        #: When a list is supplied, every run() appends (node_id, cmd) —
        #: tests assert bootstrap order without real processes.
        self.record = record

    def run(self, cmd: str, *, timeout: float = 600.0,
            environment_variables: Optional[Dict[str, str]] = None) -> str:
        if self.record is not None:
            self.record.append((self.node_id, cmd))
        env = dict(os.environ)
        env.update({k: str(v)
                    for k, v in (environment_variables or {}).items()})
        proc = subprocess.run(["bash", "-c", cmd], capture_output=True,
                              text=True, timeout=timeout, env=env)
        if proc.returncode != 0:
            raise CommandRunnerError(
                f"local command failed ({proc.returncode}): {cmd}",
                proc.returncode, proc.stderr[-2000:])
        return proc.stdout

    def run_rsync_up(self, source: str, target: str) -> None:
        if self.record is not None:
            self.record.append((self.node_id, f"rsync {source} {target}"))
        os.makedirs(os.path.dirname(target) or ".", exist_ok=True)
        subprocess.run(["cp", "-r", source, target], check=True)

    def remote_shell_command_str(self) -> str:
        return "bash"


def _quote(s: str) -> str:
    import shlex
    return shlex.quote(s)


def _checked_run(cmd: List[str], timeout: float, describe: str) -> str:
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=timeout)
    except subprocess.TimeoutExpired as exc:
        raise CommandRunnerError(
            f"{describe} timed out after {timeout}s", -1) from exc
    except FileNotFoundError as exc:
        raise CommandRunnerError(
            f"{describe}: {cmd[0]} not on PATH", -1) from exc
    if proc.returncode != 0:
        raise CommandRunnerError(
            f"{describe} failed ({proc.returncode}): "
            f"{proc.stderr[-2000:]}", proc.returncode,
            proc.stderr[-2000:])
    return proc.stdout


def wait_for_command_runner(runner: CommandRunnerInterface,
                            deadline_s: float = 300.0,
                            probe: str = "uptime") -> None:
    """Block until the node answers a trivial command (reference:
    updater.py wait_ready): fresh VMs take a while to accept ssh."""
    end = time.monotonic() + deadline_s
    delay = 2.0
    last: Optional[Exception] = None
    while time.monotonic() < end:
        try:
            runner.run(probe, timeout=30.0)
            return
        except CommandRunnerError as exc:
            last = exc
            time.sleep(delay)
            delay = min(delay * 1.5, 15.0)
    raise CommandRunnerError(
        f"node never became reachable within {deadline_s}s: {last}", -1)
