"""GCP TPU-VM node provider: real provisioning through gcloud.

Analog of the reference's cloud providers (`autoscaler/_private/gcp/
node_provider.py` behind the `NodeProvider` plug-in seam,
`autoscaler/node_provider.py:13`) specialized to TPU VMs: one provider
node is one `gcloud compute tpus tpu-vm` instance (a single-host slice,
or one host of a pod slice when ``accelerator_type`` names a multi-host
topology — gcloud addresses the whole slice as one resource, matching
the slice-is-atomic stance of TPUPodNodeProvider).

Everything goes through the ``gcloud`` CLI — no SDK dependency — via a
command-runner seam (``_gcloud``) the tests replace with a fake binary,
the same way autoscaler tests fake the cloud in the reference
(autoscaler/_private/fake_multi_node). gcloud itself is the source of
truth: ``non_terminated_nodes`` lists live instances by cluster label,
so externally-deleted TPUs disappear from the autoscaler's view without
local bookkeeping.

After creation, each TPU VM is bootstrapped into the cluster with
``gcloud compute tpus tpu-vm ssh --command "ray-tpu start --address
<head>"`` — the provisioning analog of the reference's
``command_runner.py`` + `updater.py` SSH bootstrap.
"""

from __future__ import annotations

import json
import logging
import shutil
import subprocess
import threading
from typing import Any, Dict, List, Optional

from ray_tpu.autoscaler.node_provider import (NodeProvider,
                                              STATUS_UP_TO_DATE,
                                              TAG_RAY_NODE_STATUS)

logger = logging.getLogger(__name__)

#: gcloud label keys (lowercase, [a-z0-9_-] only — GCP's constraint).
LABEL_CLUSTER = "ray-tpu-cluster"
LABEL_PREFIX = "ray-tpu-tag-"


def _to_label_key(tag: str) -> str:
    return LABEL_PREFIX + tag.lower().replace("_", "-")


def _from_label_key(key: str) -> Optional[str]:
    if not key.startswith(LABEL_PREFIX):
        return None
    return key[len(LABEL_PREFIX):]


class GCloudTPUNodeProvider(NodeProvider):
    """Provisions TPU VMs with gcloud. provider_config keys:

    * ``project`` / ``zone`` — required GCP location.
    * ``accelerator_type`` — e.g. ``v5litepod-8`` (default ``v4-8``).
    * ``runtime_version`` — TPU software version (default
      ``tpu-ubuntu2204-base``).
    * ``head_address`` — ``host:port`` the booted node's daemon joins;
      omit to skip the bootstrap ssh (e.g. when an init script in the
      image handles it).
    * ``gcloud_binary`` — override for tests (default: ``gcloud`` on
      PATH).
    * ``num_cpus`` / ``num_tpus`` — resources `ray-tpu start` advertises
      per host (defaults 8 CPUs; chips inferred from accelerator_type's
      trailing count).
    """

    def __init__(self, provider_config: Dict[str, Any],
                 cluster_name: str):
        super().__init__(provider_config, cluster_name)
        for req in ("project", "zone"):
            if not self.provider_config.get(req):
                raise ValueError(
                    f"GCloudTPUNodeProvider requires provider_config"
                    f"[{req!r}]")
        self._binary = self.provider_config.get("gcloud_binary") or \
            shutil.which("gcloud")
        if not self._binary:
            raise RuntimeError(
                "GCloudTPUNodeProvider requires the gcloud CLI on PATH "
                "(or provider_config['gcloud_binary']). Install the "
                "Google Cloud SDK on the head node.")
        self._lock = threading.Lock()
        self._counter = 0

    # -- command runner seam --------------------------------------------

    def _gcloud(self, *args: str, parse_json: bool = False,
                check: bool = True) -> Any:
        cmd = [self._binary, "compute", "tpus", "tpu-vm", *args,
               "--project", self.provider_config["project"],
               "--zone", self.provider_config["zone"]]
        if parse_json:
            cmd += ["--format", "json"]
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=self.provider_config.get(
                                  "gcloud_timeout_s", 600))
        if check and proc.returncode != 0:
            raise RuntimeError(
                f"gcloud {' '.join(args[:2])} failed "
                f"(exit {proc.returncode}): {proc.stderr[-1500:]}")
        if parse_json:
            return json.loads(proc.stdout or "null")
        return proc

    # -- provider interface ---------------------------------------------

    def _list(self) -> List[dict]:
        nodes = self._gcloud("list", parse_json=True) or []
        out = []
        for n in nodes:
            labels = n.get("labels") or {}
            if labels.get(LABEL_CLUSTER) == self.cluster_name:
                out.append(n)
        return out

    @staticmethod
    def _short_name(node: dict) -> str:
        # gcloud reports fully-qualified names
        # (projects/p/locations/z/nodes/NAME); the short name is the id
        # every other gcloud verb accepts.
        return node.get("name", "").rsplit("/", 1)[-1]

    def non_terminated_nodes(self, tag_filters: Dict[str, str]
                             ) -> List[str]:
        out = []
        for n in self._list():
            if n.get("state") in ("DELETING", "TERMINATED"):
                continue
            tags = self._tags_of(n)
            if all(tags.get(k) == v for k, v in tag_filters.items()):
                out.append(self._short_name(n))
        return out

    def _describe(self, node_id: str) -> Optional[dict]:
        proc = self._gcloud("describe", node_id, parse_json=True,
                            check=False)
        return proc if isinstance(proc, dict) else None

    def is_running(self, node_id: str) -> bool:
        node = self._describe(node_id)
        return bool(node) and node.get("state") == "READY"

    @staticmethod
    def _tags_of(node: dict) -> Dict[str, str]:
        tags = {}
        for k, v in (node.get("labels") or {}).items():
            tag = _from_label_key(k)
            if tag is not None:
                tags[tag] = v
        return tags

    def node_tags(self, node_id: str) -> Dict[str, str]:
        node = self._describe(node_id)
        return self._tags_of(node) if node else {}

    def set_node_tags(self, node_id: str, tags: Dict[str, str]) -> None:
        labels = ",".join(f"{_to_label_key(k)}={v}"
                          for k, v in tags.items())
        self._gcloud("update", node_id, "--update-labels", labels)

    def create_node(self, node_config: Dict[str, Any],
                    tags: Dict[str, str], count: int) -> None:
        cfg = self.provider_config
        acc = node_config.get("accelerator_type",
                              cfg.get("accelerator_type", "v4-8"))
        version = node_config.get("runtime_version",
                                  cfg.get("runtime_version",
                                          "tpu-ubuntu2204-base"))
        labels = {LABEL_CLUSTER: self.cluster_name}
        for k, v in dict(tags).items():
            labels[_to_label_key(k)] = v
        labels.setdefault(_to_label_key(TAG_RAY_NODE_STATUS),
                          STATUS_UP_TO_DATE)
        label_arg = ",".join(f"{k}={v}" for k, v in labels.items())
        for _ in range(count):
            import uuid
            # Unique across provider INSTANCES: a fresh launcher/
            # autoscaler process must never reuse a live node's name
            # (gcloud create would fail — or a fake overwrite it).
            name = f"{self.cluster_name}-tpu-{uuid.uuid4().hex[:8]}"
            self._gcloud("create", name,
                         "--accelerator-type", acc,
                         "--version", version,
                         "--labels", label_arg)
            self._bootstrap(name, acc)

    def _bootstrap(self, name: str, acc: str) -> None:
        """SSH the joined-cluster startup onto the fresh TPU VM (the
        reference's updater.py role). ``--worker=all`` covers every host
        of a multi-host slice."""
        head = self.provider_config.get("head_address")
        if not head:
            return
        chips = float(self.provider_config.get(
            "num_tpus", acc.rsplit("-", 1)[-1]))
        cpus = float(self.provider_config.get("num_cpus", 8))
        labels = json.dumps({"provider_node_id": name})
        start = (f"ray-tpu start --address {head} "
                 f"--num-cpus {cpus} --num-tpus {chips} "
                 f"--labels {labels!r}")
        self._gcloud("ssh", name, "--worker=all", "--command", start)

    def get_command_runner(self, node_id: str, config: dict):
        """Bootstrap commands reach TPU VMs through gcloud's ssh wrapper
        (keys/IAP handled by gcloud; plain ssh cannot reach them) —
        the launcher's updater path uses this for YAMLs that carry
        setup/start commands beyond the provider's own self-join."""
        from ray_tpu.autoscaler.command_runner import \
            GcloudSSHCommandRunner
        # worker="all": YAML setup/start commands must hit EVERY host of
        # a multi-host pod slice (the provider's own self-join path uses
        # --worker=all for the same reason).
        return GcloudSSHCommandRunner(
            node_id, project=self.provider_config["project"],
            zone=self.provider_config["zone"], worker="all")

    def terminate_node(self, node_id: str) -> None:
        self._gcloud("delete", node_id, "--quiet", check=False)

    def internal_ip(self, node_id: str) -> str:
        node = self._describe(node_id) or {}
        eps = node.get("networkEndpoints") or []
        return eps[0].get("ipAddress", "") if eps else ""

    def external_ip(self, node_id: str) -> str:
        node = self._describe(node_id) or {}
        eps = node.get("networkEndpoints") or []
        if eps:
            access = eps[0].get("accessConfig") or {}
            return access.get("externalIp", "") or \
                eps[0].get("ipAddress", "")
        return ""

    def runtime_node_hex(self, node_id: str) -> Optional[str]:
        """gcloud names are not runtime NodeIDs; the daemon self-labels
        with provider_node_id like DaemonProcessNodeProvider would — a
        disconnected driver reads as unknown."""
        from ray_tpu._private.worker import global_worker
        if not global_worker.connected:
            return None
        for node in global_worker.runtime.scheduler.nodes_snapshot():
            if node["Labels"].get("provider_node_id") == node_id:
                return node["NodeID"]
        return None
