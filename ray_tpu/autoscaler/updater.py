"""Node updater: bootstrap a freshly created node until it joins.

Analog of the reference's autoscaler/_private/updater.py (NodeUpdaterThread):
wait for the node to answer ssh, sync file mounts, run initialization +
setup commands, then the start command that launches the ray_tpu daemon
pointed at the head — tagging node status through the same lifecycle the
reference uses (waiting-for-ssh → syncing-files → setting-up-ray →
up-to-date | update-failed) so `ray-tpu status` and tests can observe
progress.
"""

from __future__ import annotations

import logging
import threading
from typing import Dict, List, Optional

from ray_tpu.autoscaler.command_runner import (CommandRunnerError,
                                               CommandRunnerInterface,
                                               wait_for_command_runner)
from ray_tpu.autoscaler.node_provider import (STATUS_UP_TO_DATE,
                                              TAG_RAY_NODE_STATUS)

logger = logging.getLogger(__name__)

STATUS_WAITING_FOR_SSH = "waiting-for-ssh"
STATUS_SYNCING_FILES = "syncing-files"
STATUS_SETTING_UP = "setting-up-ray"
STATUS_UPDATE_FAILED = "update-failed"


class NodeUpdater(threading.Thread):
    """Bootstraps ONE node; run many concurrently for a fleet
    (reference: updater.py:90 NodeUpdaterThread.run)."""

    def __init__(self, *, node_id: str, provider,
                 runner: CommandRunnerInterface,
                 file_mounts: Optional[Dict[str, str]] = None,
                 initialization_commands: Optional[List[str]] = None,
                 setup_commands: Optional[List[str]] = None,
                 start_commands: Optional[List[str]] = None,
                 env: Optional[Dict[str, str]] = None,
                 ssh_deadline_s: float = 300.0):
        super().__init__(name=f"ray_tpu-updater-{node_id}", daemon=True)
        self.node_id = node_id
        self.provider = provider
        self.runner = runner
        self.file_mounts = dict(file_mounts or {})
        self.initialization_commands = list(initialization_commands or ())
        self.setup_commands = list(setup_commands or ())
        self.start_commands = list(start_commands or ())
        self.env = dict(env or {})
        self.ssh_deadline_s = ssh_deadline_s
        self.error: Optional[Exception] = None
        self.abandoned = False  # overran run_updaters' shared deadline
        # Serializes the final tag against run_updaters' abandonment:
        # without it, a thread past the abandoned check could land
        # UP_TO_DATE after the deadline report said failed.
        self._final_lock = threading.Lock()

    def _tag(self, status: str) -> None:
        try:
            self.provider.set_node_tags(
                self.node_id, {TAG_RAY_NODE_STATUS: status})
        except Exception:  # noqa: BLE001 - tagging is observability only
            logger.exception("could not tag node %s", self.node_id)

    def run(self) -> None:
        try:
            self._tag(STATUS_WAITING_FOR_SSH)
            wait_for_command_runner(self.runner, self.ssh_deadline_s)
            if self.file_mounts:
                self._tag(STATUS_SYNCING_FILES)
                for target, source in self.file_mounts.items():
                    self.runner.run_rsync_up(source, target)
            self._tag(STATUS_SETTING_UP)
            # Initialization commands run on the RAW VM (docker/gcloud
            # config); setup commands prepare the runtime (pip install);
            # start commands launch the daemon (reference splits them
            # the same way, commands.py).
            for cmd in self.initialization_commands:
                self.runner.run(cmd, environment_variables=self.env)
            for cmd in self.setup_commands:
                self.runner.run(cmd, environment_variables=self.env)
            for cmd in self.start_commands:
                self.runner.run(cmd, environment_variables=self.env)
            with self._final_lock:
                if self.abandoned:
                    # run_updaters already reported this node failed (we
                    # overran its deadline): the tags must agree with
                    # that report, not flip to up-to-date afterwards.
                    self._tag(STATUS_UPDATE_FAILED)
                    return
                self._tag(STATUS_UP_TO_DATE)
        except Exception as exc:  # noqa: BLE001 - any failure tags the node
            self.error = exc
            self._tag(STATUS_UPDATE_FAILED)
            logger.error("bootstrap of node %s failed: %s",
                         self.node_id, exc)


class BootstrapTimeout(RuntimeError):
    """The node did not finish bootstrapping within the batch deadline."""


def run_updaters(updaters: List[NodeUpdater],
                 timeout_s: float = 1800.0) -> List[NodeUpdater]:
    """Start + join a batch under ONE shared deadline (N hung nodes cost
    timeout_s total, not N * timeout_s); returns the FAILED updaters
    (empty = all nodes bootstrapped). An overrunning updater is marked
    abandoned so its eventual completion cannot tag the node up-to-date
    in contradiction of this report."""
    import time
    for u in updaters:
        u.start()
    deadline = time.monotonic() + timeout_s
    for u in updaters:
        u.join(timeout=max(0.0, deadline - time.monotonic()))
        if u.is_alive():
            with u._final_lock:
                u.abandoned = True
                u.error = BootstrapTimeout(
                    f"node {u.node_id} still bootstrapping after "
                    f"{timeout_s}s")
                u._tag(STATUS_UPDATE_FAILED)
    return [u for u in updaters if u.error is not None]
