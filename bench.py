"""Headline benchmark: flagship GPT training throughput + MFU on one chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
vs_baseline is measured MFU / 0.40 (the north-star target from BASELINE.json:
GPT-J fine-tune at >=40% MFU; here measured on the single available chip with
the chip-sized preset).
"""

from __future__ import annotations

import json
import time


# Peak bf16 matmul FLOP/s per chip by platform.
PEAK_FLOPS = {
    "tpu v4": 275e12,
    "tpu v5 lite": 197e12,  # v5e
    "tpu v5": 459e12,  # v5p
    "tpu v5p": 459e12,
    "tpu v6 lite": 918e12,  # v6e/trillium
    "cpu": 1e11,  # nominal, for local smoke runs only
}


def _peak_flops(device) -> float:
    kind = getattr(device, "device_kind", "cpu").lower()
    for key, val in PEAK_FLOPS.items():
        if key in kind:
            return val
    return PEAK_FLOPS["cpu"]


def main():
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ray_tpu.models import gpt
    from ray_tpu.parallel import MeshConfig, ShardingRules, build_mesh
    from ray_tpu.parallel.train_step import (default_optimizer,
                                             init_train_state,
                                             make_train_step)

    device = jax.devices()[0]
    on_tpu = device.platform == "tpu"
    if on_tpu:
        preset, batch, seq, steps, warmup = "gpt-410m", 18, 1024, 10, 2
        # The tuned single-chip recipe: Pallas flash attention with 512x512
        # tiles (no S x S materialisation), selective rematerialisation
        # (save rotary q/k/v + attention output + pre-GELU FFN; recompute
        # only layernorms), chunked cross-entropy (the [tokens, vocab] fp32
        # logits never exist whole), batch 18 = the largest that compiles
        # on a 16G v5e. loss_chunk 6144 divides the 18x1024 token count
        # evenly (8192 would silently degrade to this anyway).
        # Measured v5e: ~0.50 MFU vs 0.35 full remat + dot.
        overrides = dict(attn_impl="flash", remat_policy="selective",
                         loss_chunk=6144)
    else:
        preset, batch, seq, steps, warmup = "gpt-tiny", 4, 128, 5, 1
        overrides = {}

    cfg = gpt.config(preset, max_seq_len=seq, **overrides)
    n_devices = 1
    mesh = build_mesh(
        MeshConfig(dp=1, fsdp=1, tp=1, sp=1, ep=1),
        devices=[device])
    rules = ShardingRules(batch=None, embed=None, heads=None, kv_heads=None,
                          mlp=None, vocab=None)
    optimizer = default_optimizer(learning_rate=1e-4)
    state = init_train_state(cfg, mesh, rules, optimizer, seed=0)
    step = make_train_step(cfg, mesh, rules, optimizer)

    rng = np.random.default_rng(0)

    def make_batch():
        toks = rng.integers(0, cfg.vocab_size, (batch, seq + 1))
        return {
            "tokens": jnp.asarray(toks[:, :-1], jnp.int32),
            "targets": jnp.asarray(toks[:, 1:], jnp.int32),
        }

    data = make_batch()
    for _ in range(warmup):
        state, metrics = step(state, data)
    float(metrics["loss"])  # full device sync (block_until_ready is not
    # sufficient on the remote-tunnel backend)

    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = step(state, data)
    float(metrics["loss"])
    dt = time.perf_counter() - t0

    tokens_per_step = batch * seq
    tokens_per_sec = tokens_per_step * steps / dt
    n_params = cfg.num_params()
    # Training FLOPs: 6N per token (fwd+bwd) + remat recompute is not counted
    # as useful FLOPs (standard MFU convention), + attention term.
    attn_flops = 12 * cfg.n_layers * cfg.d_model * seq
    flops_per_token = 6.0 * n_params + attn_flops
    mfu = tokens_per_sec * flops_per_token / (
        _peak_flops(device) * n_devices)

    result = {
        "metric": f"{preset}_train_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(mfu / 0.40, 4),
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
