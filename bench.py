"""Headline benchmark: flagship GPT training throughput + MFU on one chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "extra"}.
vs_baseline is measured MFU / 0.40 (the north-star target from BASELINE.json:
GPT-J fine-tune at >=40% MFU; here measured on the single available chip with
the chip-sized preset). "extra" carries the secondary metrics alongside the
headline (reference: release/microbenchmark run_microbenchmark.py):

* tasks_per_sec          — single-node trivial-task throughput (thread
                           backend, the in-driver hot path)
* remote_tasks_per_sec   — trivial tasks over real node-daemon processes
                           via the async head dispatch (thread-bounded)
* rllib_env_steps_per_sec — PPO rollout+train env-steps/s (added with the
                           Atari harness; see bench section below)
"""

from __future__ import annotations

import json
import os
import time


# Peak bf16 matmul FLOP/s per chip by platform.
PEAK_FLOPS = {
    "tpu v4": 275e12,
    "tpu v5 lite": 197e12,  # v5e
    "tpu v5": 459e12,  # v5p
    "tpu v5p": 459e12,
    "tpu v6 lite": 918e12,  # v6e/trillium
    "cpu": 1e11,  # nominal, for local smoke runs only
}


def _peak_flops(device) -> float:
    kind = getattr(device, "device_kind", "cpu").lower()
    for key, val in PEAK_FLOPS.items():
        if key in kind:
            return val
    return PEAK_FLOPS["cpu"]



def _stop_procs(procs) -> None:
    """SIGTERM first (daemons unlink their shm arenas on it), SIGKILL
    stragglers: a bare kill() leaks every daemon's arena into /dev/shm
    (measured 118GB after a day of bench/test churn)."""
    for p in procs:
        try:
            p.terminate()
        except Exception:  # noqa: BLE001
            pass
    import time as _t
    deadline = _t.monotonic() + 5
    for p in procs:
        try:
            p.wait(timeout=max(0.1, deadline - _t.monotonic()))
        except Exception:  # noqa: BLE001
            try:
                p.kill()
                p.wait(timeout=10)
            except Exception:  # noqa: BLE001
                pass


def bench_core_ops() -> dict:
    """Core task-throughput microbenchmarks (reference:
    _private/ray_perf.py + release/microbenchmark). Runs on CPU only —
    no TPU involvement — so it is cheap to run before the TPU bench."""
    import json as _json
    import subprocess
    import sys
    import time as _time

    import ray_tpu

    out = {}
    ray_tpu.init(num_cpus=8)

    @ray_tpu.remote
    def tiny(i):
        return i

    # warmup
    ray_tpu.get([tiny.remote(i) for i in range(100)])
    n = 3000
    best = 0.0
    for _ in range(3):  # best-of-3: throughput probes are noisy under
        t0 = _time.perf_counter()  # co-tenant CPU load
        ray_tpu.get([tiny.remote(i) for i in range(n)])
        best = max(best, n / (_time.perf_counter() - t0))
    out["tasks_per_sec"] = round(best, 1)

    # Remote daemons: async head dispatch over real OS processes. Every
    # wait is bounded — a failed daemon start must not hang the headline.
    procs = []
    try:
        host, port = ray_tpu.start_head_server(port=0, host="127.0.0.1")
        procs = [subprocess.Popen(
            [sys.executable, "-m", "ray_tpu._private.multinode",
             "--address", f"127.0.0.1:{port}", "--num-cpus", "4",
             "--resources", _json.dumps({"bench": 100})],
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
            for _ in range(2)]
        deadline = _time.monotonic() + 30
        while _time.monotonic() < deadline:
            if ray_tpu.cluster_resources().get("bench", 0) >= 200:
                break
            _time.sleep(0.1)
        else:
            raise TimeoutError("bench daemons never registered")

        @ray_tpu.remote(resources={"bench": 1},
                        runtime_env={"worker_process": False})
        def rtiny(i):
            return i

        ray_tpu.get([rtiny.remote(i) for i in range(50)],
                    timeout=60)  # warmup
        n = 2000
        best = 0.0
        for _ in range(3):
            t0 = _time.perf_counter()
            ray_tpu.get([rtiny.remote(i) for i in range(n)], timeout=120)
            best = max(best, n / (_time.perf_counter() - t0))
        out["remote_tasks_per_sec"] = round(best, 1)

        # The DEFAULT remote path: crash-isolated worker subprocesses,
        # pinned one-per-lease (reference: a granted lease IS a worker).
        @ray_tpu.remote(resources={"bench": 1})
        def rproc(i):
            return i

        ray_tpu.get([rproc.remote(i) for i in range(50)], timeout=60)
        best = 0.0
        for _ in range(3):
            t0 = _time.perf_counter()
            ray_tpu.get([rproc.remote(i) for i in range(1000)],
                        timeout=120)
            best = max(best, 1000 / (_time.perf_counter() - t0))
        out["remote_worker_tasks_per_sec"] = round(best, 1)
        from ray_tpu._private.worker import global_worker
        rt = getattr(global_worker, "_runtime", None)
        if rt is not None and hasattr(rt, "lease_stats"):
            out["lease_stats"] = dict(rt.lease_stats)
    except Exception as exc:  # noqa: BLE001 - must not sink the headline
        out.setdefault("remote_tasks_per_sec", None)
        out["remote_tasks_error"] = repr(exc)[:800]
    finally:
        _stop_procs(procs)
    ray_tpu.shutdown()
    return out


def bench_log_streaming() -> dict:
    """Driver-side log delivery rate: a subprocess worker emits 50k
    UNIQUE lines (unique defeats the storm guard — identical lines
    would collapse to two) and we count arrivals on the pubsub "logs"
    channel. log_to_driver=False keeps the 50k lines off this process's
    stdout (the bench emits one JSON line); the monitor publishes
    either way, so a direct subscriber sees the full stream. A
    companion task-throughput probe shows logging leaves the dispatch
    hot path within noise."""
    import time as _time

    import ray_tpu
    from ray_tpu._private.worker import global_worker

    out = {}
    n_lines = 50_000
    ray_tpu.init(num_cpus=8, log_to_driver=False)
    try:
        rt = global_worker._runtime
        sub_id = "bench-log-stream"
        rt.pubsub.subscribe(sub_id, "logs")

        @ray_tpu.remote(runtime_env={"worker_process": True})
        def chatter(n):
            import sys as _sys
            for i in range(n):
                _sys.stdout.write(f"bench-log-{i:06d}\n")
            _sys.stdout.flush()
            return n

        ref = chatter.remote(n_lines)
        got = 0
        t0 = _time.perf_counter()
        deadline = t0 + 120
        import json as _json
        while got < n_lines and _time.perf_counter() < deadline:
            item = rt.pubsub.poll(sub_id, timeout=1.0)
            if item is None:
                if got and ray_tpu.wait([ref], timeout=0)[0]:
                    break  # task done + stream quiet: drops are final
                continue
            batch = _json.loads(item[2])
            got += sum(1 for ln in batch.get("lines", ())
                       if ln.startswith("bench-log-"))
        dt = _time.perf_counter() - t0
        ray_tpu.get(ref, timeout=60)
        rt.pubsub.drop_subscriber(sub_id)
        out["log_lines_per_sec"] = round(got / dt, 1) if dt > 0 else None
        out["log_lines_delivered"] = got
        out["log_lines_emitted"] = n_lines

        # Throughput with the log subsystem live (compare tasks_per_sec
        # from bench_core_ops: must be within noise).
        @ray_tpu.remote
        def tiny(i):
            return i

        ray_tpu.get([tiny.remote(i) for i in range(100)])
        n = 3000
        best = 0.0
        for _ in range(3):
            t0 = _time.perf_counter()
            ray_tpu.get([tiny.remote(i) for i in range(n)])
            best = max(best, n / (_time.perf_counter() - t0))
        out["log_stream_tasks_per_sec"] = round(best, 1)
    finally:
        ray_tpu.shutdown()
    return out


def bench_metrics_overhead() -> dict:
    """Task throughput with metrics export ON (aggressive 0.5s tick so
    the agent actually works during the probe) vs OFF (interval 0): the
    core-runtime instrumentation + export pipeline must stay within
    noise of the uninstrumented path."""
    import os
    import time as _time

    import ray_tpu

    def _throughput() -> float:
        @ray_tpu.remote
        def tiny(i):
            return i

        ray_tpu.get([tiny.remote(i) for i in range(200)])  # warmup
        n = 2000
        best = 0.0
        for _ in range(3):
            t0 = _time.perf_counter()
            ray_tpu.get([tiny.remote(i) for i in range(n)])
            best = max(best, n / (_time.perf_counter() - t0))
        return best

    key = "RAY_TPU_METRICS_EXPORT_INTERVAL_S"
    prev = os.environ.get(key)
    try:
        os.environ[key] = "0.5"
        ray_tpu.init(num_cpus=8)
        on = _throughput()
        ray_tpu.shutdown()
        os.environ[key] = "0"
        ray_tpu.init(num_cpus=8)
        off = _throughput()
        ray_tpu.shutdown()
    finally:
        if prev is None:
            os.environ.pop(key, None)
        else:
            os.environ[key] = prev
    out = {"metrics_on_tasks_per_sec": round(on, 1),
           "metrics_off_tasks_per_sec": round(off, 1)}
    # Positive = export costs throughput; best-of-3 noise is a few %.
    out["metrics_overhead_pct"] = (
        round(100.0 * (off - on) / off, 2) if off else None)
    return out


def bench_tracing_overhead() -> dict:
    """Task throughput at three head-of-trace sampling rates: tracing
    fully off (the default-path hard gate — the unsampled hot path is
    one attribute read and must stay within noise of baseline), every
    trace sampled (rate 1.0, the worst case), and production-style 1%
    sampling. Mirrors bench_metrics_overhead."""
    import os
    import time as _time

    import ray_tpu
    from ray_tpu.util import tracing

    def _throughput() -> float:
        @ray_tpu.remote
        def tiny(i):
            return i

        ray_tpu.get([tiny.remote(i) for i in range(200)])  # warmup
        n = 2000
        best = 0.0
        for _ in range(3):
            t0 = _time.perf_counter()
            ray_tpu.get([tiny.remote(i) for i in range(n)])
            best = max(best, n / (_time.perf_counter() - t0))
        return best

    key = "RAY_TPU_TRACE_SAMPLE_RATE"
    prev = os.environ.get(key)

    def _run(rate) -> float:
        if rate is None:
            os.environ.pop(key, None)
        else:
            os.environ[key] = str(rate)
        tracing.set_sample_rate(None)  # drop the cached resolution
        ray_tpu.init(num_cpus=8)
        try:
            if rate is not None:
                tracing.enable_tracing()
            return _throughput()
        finally:
            ray_tpu.shutdown()
            tracing.disable_tracing()
            tracing.clear_spans()

    try:
        off = _run(None)          # tracing never enabled: the default path
        sampled = _run(1.0)       # every task traced end to end
        one_pct = _run(0.01)      # production-style head sampling
    finally:
        if prev is None:
            os.environ.pop(key, None)
        else:
            os.environ[key] = prev
        tracing.set_sample_rate(None)
    out = {
        # The throughput-key naming (`_per_sec`) opts this into the
        # regression auto-gate: a drop in the tracing-off number means
        # the disabled path grew a cost, which is the one hard no.
        "tracing_off_tasks_per_sec": round(off, 1),
        "tracing_sampled_tasks_per_sec": round(sampled, 1),
        "tracing_1pct_tasks_per_sec": round(one_pct, 1),
    }
    out["tracing_overhead_pct"] = (
        round(100.0 * (off - sampled) / off, 2) if off else None)
    out["tracing_1pct_overhead_pct"] = (
        round(100.0 * (off - one_pct) / off, 2) if off else None)
    return out


def bench_timeseries_overhead() -> dict:
    """Task throughput with the head time-series store ON (default
    window, aggressive 0.5s export tick so samples actually land in the
    rings) vs OFF (window 0 disables ingest entirely), plus the raw
    ingest cost of the store itself. The `_per_sec` keys opt into the
    regression auto-gate: the store must stay within noise of the
    disabled path."""
    import os
    import time as _time

    import ray_tpu

    def _throughput() -> float:
        @ray_tpu.remote
        def tiny(i):
            return i

        ray_tpu.get([tiny.remote(i) for i in range(200)])  # warmup
        n = 2000
        best = 0.0
        for _ in range(3):
            t0 = _time.perf_counter()
            ray_tpu.get([tiny.remote(i) for i in range(n)])
            best = max(best, n / (_time.perf_counter() - t0))
        return best

    export_key = "RAY_TPU_METRICS_EXPORT_INTERVAL_S"
    window_key = "RAY_TPU_TIMESERIES_WINDOW_S"
    prev = {k: os.environ.get(k) for k in (export_key, window_key)}
    def _arm(window: str) -> float:
        if window:
            os.environ[window_key] = window
        else:
            os.environ.pop(window_key, None)  # default: store on
        ray_tpu.init(num_cpus=8)
        try:
            return _throughput()
        finally:
            ray_tpu.shutdown()

    try:
        os.environ[export_key] = "0.5"
        # Throwaway pass: the FIRST init in a process pays one-time
        # costs (thread pools, lazy imports) that would otherwise be
        # billed entirely to whichever arm runs first. Then alternate
        # the arms so slow machine phases hit both equally.
        _arm("")
        on = off = 0.0
        for _ in range(2):
            on = max(on, _arm(""))
            off = max(off, _arm("0"))
    finally:
        for k, v in prev.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    out = {"timeseries_on_tasks_per_sec": round(on, 1),
           "timeseries_off_tasks_per_sec": round(off, 1)}
    out["timeseries_overhead_pct"] = (
        round(100.0 * (off - on) / off, 2) if off else None)

    # Ingest microbench: cumulative counter samples pushed straight into
    # a standalone store — the per-sample cost the metrics path pays.
    from ray_tpu._private.timeseries import TimeSeriesStore
    store = TimeSeriesStore(window_s=300, max_series=4096, staleness=600)
    n = 10_000
    entry = [{"name": "bench_ingest_total", "type": "counter", "desc": "",
              "tag_keys": ("k",), "series": {}}]
    t0 = _time.perf_counter()
    base = _time.monotonic()
    for i in range(n):
        entry[0]["series"] = {(str(i % 64),): float(i)}
        store.ingest_batch("bench", 1, "driver", entry,
                           now=base + i * 0.001)
    elapsed = _time.perf_counter() - t0
    out["timeseries_ingest_samples_per_sec"] = round(n / elapsed, 1)
    return out


def bench_alerting_overhead() -> dict:
    """Task throughput with the alert engine ON (aggressive 0.05s eval
    period + 0.5s export tick so evaluations actually happen under the
    workload) vs OFF (period 0 leaves the engine dormant), plus the raw
    rule-evaluation rate over a populated store. The `_per_sec` keys
    opt into the regression auto-gate: evaluating the built-in rule set
    every merge tick must stay within noise of the disabled path."""
    import os
    import time as _time

    import ray_tpu

    def _throughput() -> float:
        @ray_tpu.remote
        def tiny(i):
            return i

        ray_tpu.get([tiny.remote(i) for i in range(200)])  # warmup
        n = 2000
        best = 0.0
        for _ in range(3):
            t0 = _time.perf_counter()
            ray_tpu.get([tiny.remote(i) for i in range(n)])
            best = max(best, n / (_time.perf_counter() - t0))
        return best

    export_key = "RAY_TPU_METRICS_EXPORT_INTERVAL_S"
    period_key = "RAY_TPU_ALERT_EVAL_PERIOD_S"
    prev = {k: os.environ.get(k) for k in (export_key, period_key)}

    def _arm(period: str) -> float:
        os.environ[period_key] = period
        ray_tpu.init(num_cpus=8)
        try:
            return _throughput()
        finally:
            ray_tpu.shutdown()

    try:
        os.environ[export_key] = "0.5"
        # Throwaway pass (same reasoning as bench_timeseries_overhead):
        # first init pays one-time costs; then alternate the arms so
        # slow machine phases hit both equally.
        _arm("0.05")
        on = off = 0.0
        for _ in range(2):
            on = max(on, _arm("0.05"))
            off = max(off, _arm("0"))
    finally:
        for k, v in prev.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    out = {"alerting_on_tasks_per_sec": round(on, 1),
           "alerting_off_tasks_per_sec": round(off, 1)}
    out["alerting_overhead_pct"] = (
        round(100.0 * (off - on) / off, 2) if off else None)

    # Evaluation microbench: the built-in rule set stepped against a
    # standalone store holding live series — the per-tick cost the
    # ClusterMetrics.update path pays.
    from ray_tpu._private.alerting import AlertEngine
    from ray_tpu._private.timeseries import TimeSeriesStore
    store = TimeSeriesStore(window_s=300, max_series=4096, staleness=600)
    entry = [{"name": "ray_tpu_node_deaths_total", "type": "counter",
              "desc": "", "tag_keys": (), "series": {}}]
    base = _time.monotonic()
    for i in range(120):
        entry[0]["series"] = {(): float(i)}
        store.ingest_batch("bench", 1, "driver", entry,
                           now=base + i * 0.5)
    engine = AlertEngine(period_s=3600.0)
    n = 2000
    t0 = _time.perf_counter()
    for i in range(n):
        engine.evaluate(store, now=base + 60.0 + i * 0.001)
    elapsed = _time.perf_counter() - t0
    out["alerting_evals_per_sec"] = round(n / elapsed, 1)
    return out


def bench_profiling_overhead() -> dict:
    """Task throughput with the continuous profiler ON (default hz,
    aggressive 0.5s export tick so windows actually ship) vs OFF
    (RAY_TPU_PROFILE_HZ=0 leaves the whole plane dormant), plus the raw
    sampler walk rate. The `_per_sec` keys opt into the regression
    auto-gate; the acceptance bar is <= 2% cost at the default rate."""
    import os
    import statistics as _stats
    import time as _time

    import ray_tpu

    export_key = "RAY_TPU_METRICS_EXPORT_INTERVAL_S"
    hz_key = "RAY_TPU_PROFILE_HZ"
    prev = {k: os.environ.get(k) for k in (export_key, hz_key)}
    try:
        os.environ[export_key] = "0.5"
        os.environ.pop(hz_key, None)  # default: profiler on
        ray_tpu.init(num_cpus=8)
        try:
            from ray_tpu._private import profiling as _prof

            @ray_tpu.remote
            def tiny(i):
                return i

            def _tput_once(n: int = 400) -> float:
                t0 = _time.perf_counter()
                ray_tpu.get([tiny.remote(i) for i in range(n)])
                return n / (_time.perf_counter() - t0)

            for _ in range(5):
                _tput_once()  # warmup / one-time init costs
            # Shared-container throughput wanders far more between
            # seconds than the sampler costs, so arm-level maxima
            # measure machine phase, not profiling.  Instead: many
            # short back-to-back on/off pairs (order flipped each
            # round, profiler toggled inside the one live runtime)
            # and the median of the paired ratios.
            ratios = []
            off = 0.0
            for r in range(100):
                if r % 2 == 0:
                    _prof.ensure_profiler("driver")
                    on_t = _tput_once()
                    _prof.shutdown_profiler()
                    off_t = _tput_once()
                else:
                    off_t = _tput_once()
                    _prof.ensure_profiler("driver")
                    on_t = _tput_once()
                    _prof.shutdown_profiler()
                ratios.append(on_t / off_t)
                off = max(off, off_t)

            # Sampler microbench, inside the live runtime so the walk
            # covers a realistic thread population: raw walk rate of
            # sys._current_frames() — the per-tick cost every sampled
            # process pays, independent of transport.
            agent = _prof.ProfilerAgent("bench", hz=0, start=False)
            n = 2000
            t0 = _time.perf_counter()
            for _ in range(n):
                agent._sample_once(0)
            walks = n / (_time.perf_counter() - t0)
        finally:
            ray_tpu.shutdown()
    finally:
        for k, v in prev.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    ratio = _stats.median(ratios)
    # Report `on` at the best-phase baseline scaled by the paired
    # ratio so the two keys stay comparable across runs.
    out = {"profiling_on_tasks_per_sec": round(off * ratio, 1),
           "profiling_off_tasks_per_sec": round(off, 1)}
    out["profiling_overhead_pct"] = round(100.0 * (1.0 - ratio), 2)
    out["profiling_walks_per_sec"] = round(walks, 1)
    return out


def bench_flow_overhead() -> dict:
    """Task throughput with the dataplane flow recorder ON vs OFF
    (flow.set_enabled toggled inside one live runtime, same paired
    on/off methodology as the profiling bench), plus the raw
    record() rate — the per-transfer cost every pull/serve pays. The
    `_per_sec` keys opt into the regression auto-gate; the acceptance
    bar is <= 2% cost."""
    import os
    import statistics as _stats
    import time as _time

    import ray_tpu

    export_key = "RAY_TPU_METRICS_EXPORT_INTERVAL_S"
    prev = os.environ.get(export_key)
    try:
        os.environ[export_key] = "0.5"
        ray_tpu.init(num_cpus=8)
        try:
            from ray_tpu._private import flow as _flow

            @ray_tpu.remote
            def tiny(i):
                return i

            def _tput_once(n: int = 400) -> float:
                t0 = _time.perf_counter()
                ray_tpu.get([tiny.remote(i) for i in range(n)])
                return n / (_time.perf_counter() - t0)

            for _ in range(5):
                _tput_once()  # warmup / one-time init costs
            ratios = []
            off = 0.0
            for r in range(50):
                if r % 2 == 0:
                    _flow.set_enabled(True)
                    on_t = _tput_once()
                    _flow.set_enabled(False)
                    off_t = _tput_once()
                else:
                    _flow.set_enabled(False)
                    off_t = _tput_once()
                    _flow.set_enabled(True)
                    on_t = _tput_once()
                ratios.append(on_t / off_t)
                off = max(off, off_t)
            _flow.set_enabled(True)

            # Raw ledger microbench: record() calls/s straight into a
            # dedicated recorder (no transport) — the absolute cost a
            # pull path pays per completed transfer.
            rec = _flow.FlowRecorder(max_records=4096)
            n = 20000
            t0 = _time.perf_counter()
            for i in range(n):
                rec.record(key=f"k{i % 64}", nbytes=1 << 20,
                           duration_s=0.01, direction="in",
                           peer=("10.0.0.1", 9000), chunks=4,
                           parallelism=4)
            records = n / (_time.perf_counter() - t0)
        finally:
            ray_tpu.shutdown()
    finally:
        if prev is None:
            os.environ.pop(export_key, None)
        else:
            os.environ[export_key] = prev
    ratio = _stats.median(ratios)
    out = {"flow_on_tasks_per_sec": round(off * ratio, 1),
           "flow_off_tasks_per_sec": round(off, 1)}
    out["flow_overhead_pct"] = round(100.0 * (1.0 - ratio), 2)
    out["flow_records_per_sec"] = round(records, 1)
    return out


def bench_data_shuffle() -> dict:
    """Single-host shuffle throughput (reference:
    release_tests.yaml:3447 shuffle nightly — scaled to one host): a
    multi-GB random_shuffle through the streaming executor + object
    store, reported as MB/s."""
    import time as _time

    import numpy as np

    import ray_tpu
    from ray_tpu import data as rdata

    out = {}
    ray_tpu.init(num_cpus=8)
    try:
        n_blocks, rows_per_block, row_bytes = 32, 4096, 8 * 128
        total_mb = n_blocks * rows_per_block * row_bytes / 1e6  # ~134MB

        def gen(b):
            ids = np.asarray(b["id"], np.int64)
            return {"id": ids,
                    "payload": np.random.default_rng(int(ids[0])).random(
                        (len(ids), row_bytes // 8))}

        ds = rdata.range(n_blocks * rows_per_block,
                         parallelism=n_blocks).map_batches(gen)
        ds = ds.materialize()  # payload generation OUTSIDE the timer
        t0 = _time.perf_counter()
        shuffled = ds.random_shuffle(seed=0)
        count = shuffled.count()  # forces full execution
        dt = _time.perf_counter() - t0
        assert count == n_blocks * rows_per_block
        out["shuffle_mb_per_sec"] = round(total_mb / dt, 1)
        out["shuffle_data_mb"] = round(total_mb, 1)
    finally:
        ray_tpu.shutdown()
    return out


def bench_shuffle_multi_daemon() -> dict:
    """Multi-daemon shuffle at GB scale (reference:
    release_tests.yaml:3447 shuffle nightly): blocks are generated and
    kept DAEMON-resident (the head has 1 CPU, so map/partition/reduce
    tasks land on the two daemon processes), and the reduce stage's
    cross-node arguments ride the daemon-to-daemon data plane under pull
    admission control. Reports MB/s plus the bytes that actually moved
    node-to-node. Size via RAY_TPU_BENCH_SHUFFLE_GB (default 2)."""
    import json as _json
    import os as _os
    import subprocess
    import sys
    import time as _time

    import numpy as np

    import ray_tpu
    from ray_tpu import data as rdata

    out = {}
    total_gb = float(_os.environ.get("RAY_TPU_BENCH_SHUFFLE_GB", "2"))
    total_bytes = int(total_gb * (1 << 30))
    # Partition count sized so map-stage sub-blocks (total / n_blocks^2)
    # stay ABOVE remote_object_inline_limit_bytes: daemon-resident blocks
    # are the point — inline-sized ones would round-trip via the head.
    n_blocks = max(8, min(32, int((total_bytes / (2 << 20)) ** 0.5)))
    row_bytes = 1024
    rows = total_bytes // row_bytes
    # Fast export tick so the daemons' flow_batch frames (the per-link
    # matrix embedded below) land head-side within the wait loop.
    export_key = "RAY_TPU_METRICS_EXPORT_INTERVAL_S"
    prev_export = _os.environ.get(export_key)
    _os.environ[export_key] = "0.5"
    ray_tpu.init(num_cpus=1)  # head out of the compute: daemons do the work
    # Span recording feeds the per-stage time split below; the carried
    # trace context makes daemon-side spans ride metrics_batch frames
    # back to the head's assembler.
    from ray_tpu.util import tracing as _tracing
    _tracing.enable_tracing()
    procs = []
    try:
        host, port = ray_tpu.start_head_server(port=0, host="127.0.0.1")
        # Per-daemon arena sized for input + shuffled output resident at
        # once (profiling showed the 0.75x arena spent its active time
        # in _make_room/_spill_one disk churn, not moving bytes). Spill
        # still covers the overflow tail; it is no longer the main path.
        store = int(total_bytes * 1.25)
        procs = [subprocess.Popen(
            [sys.executable, "-m", "ray_tpu._private.multinode",
             "--address", f"127.0.0.1:{port}", "--num-cpus", "8",
             "--object-store-memory", str(store)],
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
            for _ in range(2)]
        deadline = _time.monotonic() + 30
        while _time.monotonic() < deadline:
            if ray_tpu.cluster_resources().get("CPU", 0) >= 17:
                break
            _time.sleep(0.1)
        else:
            raise TimeoutError("shuffle daemons never registered")

        def gen(b):
            ids = np.asarray(b["id"], np.int64)
            return {"id": ids,
                    "payload": np.random.default_rng(int(ids[0])).random(
                        (len(ids), row_bytes // 8))}

        ds = rdata.range(rows, parallelism=n_blocks).map_batches(gen)
        ds = ds.materialize()  # generation OUTSIDE the timer
        t0 = _time.perf_counter()
        count = ds.random_shuffle(seed=0).count()
        dt = _time.perf_counter() - t0
        assert count == rows, (count, rows)
        pulled = 0
        from ray_tpu._private.worker import global_worker
        rt = global_worker._runtime
        for conn in rt._remote_nodes.values():
            try:  # advisory: a daemon still draining spill I/O after a
                # big run may miss the stats deadline — never fail the
                # completed measurement over it
                stats = conn.get_stats(timeout=30)
                pulled += stats.get("transfer", {}).get("pulled_bytes", 0)
            except Exception:  # noqa: BLE001
                out["shuffle_multi_pulled_mb_partial"] = True
        out["shuffle_multi_mb_per_sec"] = round(total_bytes / 1e6 / dt, 1)
        out["shuffle_multi_data_mb"] = round(total_bytes / 1e6, 1)
        out["shuffle_multi_pulled_mb"] = round(pulled / 1e6, 1)
        out["shuffle_multi_daemons"] = 2
        # Embed the per-link flow matrix so the BENCH record answers
        # "where did those MB/s go" per node pair. Daemon flow batches
        # arrive on the export cadence; wait briefly for them.
        flows = {}
        flow_deadline = _time.monotonic() + 15
        while _time.monotonic() < flow_deadline:
            flows = rt.flows_snapshot()
            if any(lk.get("bytes_total", 0) > 0
                   for lk in flows.get("links", [])):
                break
            _time.sleep(0.5)
        out["shuffle_multi_link_matrix"] = [
            {"src": lk["src"][:12], "dst": lk["dst"][:12],
             "mbps": round(lk["mbps"], 2),
             "bytes_total": lk["bytes_total"],
             "failovers": lk["failovers"], "p95_s": round(lk["p95_s"], 4)}
            for lk in flows.get("links", [])[:8]]
        out["shuffle_multi_top_fanout"] = [
            {"key": o["key"][:24], "fanout": o["fanout"],
             "bytes_total": o["bytes_total"]}
            for o in flows.get("objects", [])[:5]]
        # Per-stage time split from the run's assembled traces: how the
        # shuffle's wall clock divided between queueing, argument pulls,
        # and map/reduce execute — the "where did the time go" answer
        # next to the raw MB/s.
        try:
            stages = rt.trace_summary().get("stages", {})
            out["shuffle_multi_stage_split"] = {
                stage: {"total_s": round(s["total_s"], 2),
                        "share": round(s["share"], 3)}
                for stage, s in sorted(
                    stages.items(),
                    key=lambda kv: -kv[1]["total_s"])[:8]}
        except Exception:  # noqa: BLE001 - advisory attribution only
            out["shuffle_multi_stage_split"] = None
    finally:
        _stop_procs(procs)
        ray_tpu.shutdown()
        _tracing.disable_tracing()
        _tracing.clear_spans()
        if prev_export is None:
            _os.environ.pop(export_key, None)
        else:
            _os.environ[export_key] = prev_export
    return out


def bench_broadcast() -> dict:
    """Spanning-tree broadcast: one head-resident blob replicated onto
    4 daemons through the collective dataplane (head seeds only its
    ``fanout`` direct children; deeper nodes cascade node-to-node).
    Reports aggregate replication MB/s, the tree depth, and the head's
    egress share. Size via RAY_TPU_BENCH_BROADCAST_MB (default 128)."""
    import os as _os
    import subprocess
    import sys
    import time as _time

    import numpy as np

    import ray_tpu

    out: dict = {}
    size = int(float(_os.environ.get(
        "RAY_TPU_BENCH_BROADCAST_MB", "128")) * 1e6)
    n_daemons = 4
    ray_tpu.init(num_cpus=1)
    procs = []
    try:
        host, port = ray_tpu.start_head_server(port=0, host="127.0.0.1")
        procs = [subprocess.Popen(
            [sys.executable, "-m", "ray_tpu._private.multinode",
             "--address", f"127.0.0.1:{port}", "--num-cpus", "2",
             "--object-store-memory", str(4 * size)],
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
            for _ in range(n_daemons)]
        deadline = _time.monotonic() + 30
        while _time.monotonic() < deadline:
            if ray_tpu.cluster_resources().get("CPU", 0) >= \
                    1 + 2 * n_daemons:
                break
            _time.sleep(0.1)
        else:
            raise TimeoutError("broadcast daemons never registered")
        blob = np.random.default_rng(0).random(size // 8)
        ref = ray_tpu.put(blob)
        t0 = _time.perf_counter()
        tree = ray_tpu.broadcast(ref)
        dt = _time.perf_counter() - t0
        assert tree["nodes"] == n_daemons, tree
        out["broadcast_mb_per_sec"] = round(
            tree["size"] * tree["nodes"] / 1e6 / dt, 1)
        out["broadcast_tree_depth"] = tree["depth"]
        out["broadcast_nodes"] = tree["nodes"]
        out["broadcast_data_mb"] = round(tree["size"] / 1e6, 1)
        # Head egress = fanout direct children x size; everything deeper
        # moved node-to-node.
        head_edges = sum(1 for e in tree["edges"]
                         if e["ok"] and e["src"] == "head")
        out["broadcast_head_egress_mb"] = round(
            head_edges * tree["size"] / 1e6, 1)
    finally:
        _stop_procs(procs)
        ray_tpu.shutdown()
    return out


def bench_pull_striped() -> dict:
    """Striped multi-source pull: one object resident on 4 in-process
    object servers, pulled with chunk stripes spread across all holders
    concurrently vs pinned to a single source. Loopback sockets, so the
    numbers measure the striping machinery, not a NIC. Size via
    RAY_TPU_BENCH_STRIPE_MB (default 256)."""
    import os as _os
    import time as _time

    from ray_tpu._private.dataplane import (NodeObjectTable, ObjectServer,
                                            pull_object)

    out: dict = {}
    size = int(float(_os.environ.get(
        "RAY_TPU_BENCH_STRIPE_MB", "256")) * 1e6)
    payload = bytes(bytearray(_os.urandom(1 << 20)) * (size >> 20))
    size = len(payload)
    src = NodeObjectTable()
    src.put("blob", payload)
    servers = [ObjectServer(src, host="127.0.0.1") for _ in range(4)]
    addrs = [("127.0.0.1", s.port) for s in servers]
    prev = {k: _os.environ.get(k) for k in
            ("RAY_TPU_PULL_CHUNK_BYTES", "RAY_TPU_PULL_PARALLELISM",
             "RAY_TPU_PULL_STRIPE_MAX_SOURCES")}
    _os.environ["RAY_TPU_PULL_CHUNK_BYTES"] = str(4 << 20)
    _os.environ["RAY_TPU_PULL_PARALLELISM"] = "8"
    try:
        for label, nsources in (("single", 1), ("striped", 4)):
            _os.environ["RAY_TPU_PULL_STRIPE_MAX_SOURCES"] = str(nsources)
            best = 0.0
            for _ in range(3):
                dst = NodeObjectTable()
                t0 = _time.perf_counter()
                pull_object(addrs[0], "blob", dst, size_hint=size,
                            fallback_addrs=addrs[1:])
                dt = _time.perf_counter() - t0
                with dst.pinned("blob") as got:
                    assert len(got) == size
                best = max(best, size / 1e6 / dt)
            out[f"pull_{label}_mb_per_sec"] = round(best, 1)
    finally:
        for s in servers:
            s.close()
        for k, v in prev.items():
            if v is None:
                _os.environ.pop(k, None)
            else:
                _os.environ[k] = v
    return out


def bench_envelope() -> dict:
    """Scalability envelope on one host (reference:
    release/benchmarks/README.md:5-12 — many_nodes / many_actors /
    many_pgs / many_tasks, scaled to the box): 25 virtual daemons join
    the head; then 100 placement groups schedule, 500 actors construct
    and answer a call each, and 50k trivial tasks run through the full
    wire path (lease streams, daemon-local dispatch, worker
    subprocesses bypassed for speed). Records creation/submit/dispatch
    rates and the head's RSS at peak — the quantitative probe of the
    head's remaining centralization. Knobs:
    RAY_TPU_BENCH_ENVELOPE_{DAEMONS,ACTORS,PGS,TASKS}."""
    import json as _json
    import os as _os
    import subprocess
    import sys
    import time as _time

    import ray_tpu

    n_daemons = int(_os.environ.get("RAY_TPU_BENCH_ENVELOPE_DAEMONS", 25))
    n_actors = int(_os.environ.get("RAY_TPU_BENCH_ENVELOPE_ACTORS", 500))
    n_pgs = int(_os.environ.get("RAY_TPU_BENCH_ENVELOPE_PGS", 100))
    n_tasks = int(_os.environ.get("RAY_TPU_BENCH_ENVELOPE_TASKS", 50000))
    out: dict = {"envelope_daemons": n_daemons}
    ray_tpu.init(num_cpus=1)
    procs = []
    try:
        host, port = ray_tpu.start_head_server(port=0, host="127.0.0.1")
        procs = [subprocess.Popen(
            [sys.executable, "-m", "ray_tpu._private.multinode",
             "--address", f"127.0.0.1:{port}", "--num-cpus", "2",
             "--resources", _json.dumps({"env": 1000}),
             "--object-store-memory", str(64 << 20)],
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
            for _ in range(n_daemons)]
        deadline = _time.monotonic() + 120
        t0 = _time.monotonic()
        while _time.monotonic() < deadline:
            if ray_tpu.cluster_resources().get("env", 0) >= \
                    n_daemons * 1000:
                break
            _time.sleep(0.2)
        else:
            raise TimeoutError("envelope daemons never all registered")
        out["envelope_join_s"] = round(_time.monotonic() - t0, 2)

        # -- placement groups (many_pgs) --------------------------------
        from ray_tpu.util import (placement_group,
                                  remove_placement_group)
        t0 = _time.perf_counter()
        pgs = [placement_group([{"env": 1}], strategy="PACK")
               for _ in range(n_pgs)]
        ray_tpu.get([pg.ready() for pg in pgs], timeout=120)
        out["envelope_pgs_per_sec"] = round(
            n_pgs / (_time.perf_counter() - t0), 1)

        # -- actors (many_actors) ---------------------------------------
        @ray_tpu.remote(resources={"env": 1}, num_cpus=0)
        class Ping:
            def ping(self):
                return 1

        t0 = _time.perf_counter()
        actors = [Ping.remote() for _ in range(n_actors)]
        ray_tpu.get([a.ping.remote() for a in actors], timeout=300)
        out["envelope_actors_per_sec"] = round(
            n_actors / (_time.perf_counter() - t0), 1)

        # -- tasks (many_tasks): full wire path, in-daemon execution ----
        @ray_tpu.remote(resources={"env": 0.01}, num_cpus=0.01,
                        runtime_env={"worker_process": False})
        def tiny(i):
            return i

        ray_tpu.get([tiny.remote(i) for i in range(200)], timeout=120)
        t0 = _time.perf_counter()
        refs = [tiny.remote(i) for i in range(n_tasks)]
        submit_dt = _time.perf_counter() - t0
        ray_tpu.get(refs, timeout=1200)
        total_dt = _time.perf_counter() - t0
        out["envelope_tasks"] = n_tasks
        out["envelope_submit_per_sec"] = round(n_tasks / submit_dt, 1)
        out["envelope_tasks_per_sec"] = round(n_tasks / total_dt, 1)

        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    out["envelope_head_rss_mb"] = round(
                        int(line.split()[1]) / 1024, 1)
                    break
        for a in actors:
            ray_tpu.kill(a)
        for pg in pgs:
            remove_placement_group(pg)
    finally:
        _stop_procs(procs)
        ray_tpu.shutdown()
    return out


def bench_detached_restart() -> dict:
    """Detached-actor failover latency: a GCS-owned detached actor lives
    on a daemon; the daemon is SIGKILLed and a replacement joins. The
    metric is kill -> first successful call on the restarted instance,
    i.e. the full death-detection + reschedule + re-init + reply path an
    operator sees when a node hosting a long-lived service dies."""
    import json as _json
    import subprocess
    import sys
    import time as _time

    import ray_tpu

    out = {}
    ray_tpu.init(num_cpus=1)
    procs = []

    def _spawn_daemon(port):
        return subprocess.Popen(
            [sys.executable, "-m", "ray_tpu._private.multinode",
             "--address", f"127.0.0.1:{port}", "--num-cpus", "2",
             "--resources", _json.dumps({"det": 1})],
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)

    try:
        host, port = ray_tpu.start_head_server(port=0, host="127.0.0.1")
        procs.append(_spawn_daemon(port))
        deadline = _time.monotonic() + 30
        while _time.monotonic() < deadline:
            if ray_tpu.cluster_resources().get("det", 0) >= 1:
                break
            _time.sleep(0.1)
        else:
            raise TimeoutError("daemon never registered")

        @ray_tpu.remote(resources={"det": 1}, max_restarts=1)
        class Svc:
            def ping(self):
                return "pong"

        svc = Svc.options(name="bench-det", lifetime="detached").remote()
        assert ray_tpu.get(svc.ping.remote(), timeout=60) == "pong"

        procs[0].kill()
        procs[0].wait(timeout=10)
        t0 = _time.perf_counter()
        procs.append(_spawn_daemon(port))
        deadline = _time.monotonic() + 120
        while _time.monotonic() < deadline:
            try:
                if ray_tpu.get(svc.ping.remote(), timeout=10) == "pong":
                    break
            except Exception:  # noqa: BLE001 - restart still in flight
                _time.sleep(0.05)
        else:
            raise TimeoutError("detached actor never restarted")
        out["detached_actor_restart_ms"] = round(
            (_time.perf_counter() - t0) * 1e3, 1)
        ray_tpu.kill(svc, no_restart=True)
    finally:
        _stop_procs(procs)
        ray_tpu.shutdown()
    return out


def bench_channel_reconnect() -> dict:
    """Session-channel self-healing latency: chaos closes the head->
    daemon socket mid-stream and the metric is faulted submit -> result
    of the same task, i.e. break detection + daemon re-dial + resume
    handshake + ring replay. Bounds the stall a transient network blip
    adds to in-flight work (vs. the node death + task retry it used to
    cost)."""
    import json as _json
    import subprocess
    import sys
    import time as _time

    import ray_tpu
    from ray_tpu._private import chaos

    out = {}
    ray_tpu.init(num_cpus=1)
    procs = []
    try:
        host, port = ray_tpu.start_head_server(port=0, host="127.0.0.1")
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "ray_tpu._private.multinode",
             "--address", f"127.0.0.1:{port}", "--num-cpus", "2",
             "--resources", _json.dumps({"chan": 1})],
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL))
        deadline = _time.monotonic() + 30
        while _time.monotonic() < deadline:
            if ray_tpu.cluster_resources().get("chan", 0) >= 1:
                break
            _time.sleep(0.1)
        else:
            raise TimeoutError("daemon never registered")

        @ray_tpu.remote(resources={"chan": 1})
        def ping(x):
            return x

        # Warm the lease/worker path so the faulted sample only measures
        # the channel recovery, not worker spawn.
        assert ray_tpu.get(ping.remote(0), timeout=60) == 0

        chaos.configure("sock_close:site=head.send:times=1")
        try:
            t0 = _time.perf_counter()
            assert ray_tpu.get(ping.remote(1), timeout=120) == 1
            out["channel_reconnect_ms"] = round(
                (_time.perf_counter() - t0) * 1e3, 1)
        finally:
            chaos.reset()
    finally:
        _stop_procs(procs)
        ray_tpu.shutdown()
    return out


def bench_object_recovery() -> dict:
    """Durable-spill recovery latency, split into its two components: a
    daemon spills its only copy of a large result through session://
    storage, then dies by SIGKILL. ``node_death_detect_ms`` is kill ->
    the membership table's death declaration (the fenced-membership
    detection path: channel break wakes the probe loop, hard probe
    failure declares); ``object_restore_ms`` is the subsequent ``get()``
    completion (node removal + tiered recovery via spill-URI restore,
    NOT producer re-execution). Both are latency-gated so a detection
    regression is visible on its own instead of hiding inside the
    restore time."""
    import json as _json
    import os as _os
    import signal as _signal
    import subprocess
    import sys
    import time as _time

    import numpy as _np

    import ray_tpu
    from ray_tpu._private.worker import global_worker

    out = {}
    ray_tpu.init(num_cpus=1)
    procs = []
    try:
        host, port = ray_tpu.start_head_server(port=0, host="127.0.0.1")
        env = dict(_os.environ)
        env["RAY_TPU_object_spill_uri"] = "session://"
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "ray_tpu._private.multinode",
             "--address", f"127.0.0.1:{port}", "--num-cpus", "2",
             "--resources", _json.dumps({"spillnode": 1}),
             "--object-store-memory", str(4 * 1024 * 1024)],
            env=env, stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL))
        deadline = _time.monotonic() + 30
        while _time.monotonic() < deadline:
            if ray_tpu.cluster_resources().get("spillnode", 0) >= 1:
                break
            _time.sleep(0.1)
        else:
            raise TimeoutError("daemon never registered")

        @ray_tpu.remote(resources={"spillnode": 1})
        def produce():
            return _np.arange(1024 * 1024, dtype=_np.int64)  # 8 MB

        ref = produce.remote()
        runtime = global_worker.runtime
        deadline = _time.monotonic() + 30
        while _time.monotonic() < deadline:
            if runtime._spill_uris_by_key:
                break
            _time.sleep(0.02)
        else:
            raise TimeoutError("spill URI never announced")
        import threading as _threading
        declared = _threading.Event()

        def _on_member_event(event):
            if event.get("event") == "dead":
                declared.set()

        runtime.membership.subscribe(_on_member_event)
        try:
            procs[0].send_signal(_signal.SIGKILL)
            t0 = _time.perf_counter()
            if not declared.wait(timeout=30):
                raise TimeoutError("node death never declared")
            out["node_death_detect_ms"] = round(
                (_time.perf_counter() - t0) * 1e3, 1)
            t1 = _time.perf_counter()
            value = ray_tpu.get(ref, timeout=120)
            out["object_restore_ms"] = round(
                (_time.perf_counter() - t1) * 1e3, 1)
        finally:
            runtime.membership.unsubscribe(_on_member_event)
        assert int(value[-1]) == 1024 * 1024 - 1
    finally:
        _stop_procs(procs)
        ray_tpu.shutdown()
    return out


def bench_head_failover() -> dict:
    """Head failover recovery latency: a subprocess driver owns the head
    (gcs_store-backed) with one daemon joined, then dies by SIGKILL.
    ``head_failover_recovery_ms`` is kill -> first task RESULT computed
    on the daemon under a NEW head on the same port + store — i.e. store
    replay, head rebirth, the daemon's jittered re-dial + re-register,
    and one scheduled round-trip. Latency-gated: this is the window a
    supervisor-restarted head adds to in-flight work."""
    import json as _json
    import os as _os
    import signal as _signal
    import socket as _socket
    import subprocess
    import sys
    import tempfile as _tempfile
    import time as _time

    import ray_tpu

    driver1 = """
import sys, time
import ray_tpu
path, port = sys.argv[1], int(sys.argv[2])
ray_tpu.init(num_cpus=1, _system_config={"gcs_store_path": path})
ray_tpu.start_head_server(port=port, host="127.0.0.1")
deadline = time.monotonic() + 30
while time.monotonic() < deadline:
    if ray_tpu.cluster_resources().get("fo", 0) >= 1:
        break
    time.sleep(0.1)
else:
    raise TimeoutError("daemon never joined")
print("READY", flush=True)
time.sleep(3600)
"""
    out = {}
    s = _socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    tmp = _tempfile.mkdtemp(prefix="ray_tpu_bench_failover_")
    store = _os.path.join(tmp, "gcs.bin")
    procs = []
    try:
        head1 = subprocess.Popen(
            [sys.executable, "-c", driver1, store, str(port)],
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True)
        procs.append(head1)
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "ray_tpu._private.multinode",
             "--address", f"127.0.0.1:{port}", "--num-cpus", "2",
             "--resources", _json.dumps({"fo": 1})],
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL))
        line = head1.stdout.readline()
        if "READY" not in line:
            raise RuntimeError(f"first head never came up: {line!r}")

        head1.send_signal(_signal.SIGKILL)
        head1.wait(timeout=10)
        t0 = _time.perf_counter()

        ray_tpu.init(num_cpus=1,
                     _system_config={"gcs_store_path": store})
        ray_tpu.start_head_server(port=port, host="127.0.0.1")
        deadline = _time.monotonic() + 120
        while _time.monotonic() < deadline:
            if ray_tpu.cluster_resources().get("fo", 0) >= 1:
                break
            _time.sleep(0.05)
        else:
            raise TimeoutError("daemon never re-registered")

        @ray_tpu.remote(resources={"fo": 1})
        def ping(x):
            return x

        assert ray_tpu.get(ping.remote(7), timeout=60) == 7
        out["head_failover_recovery_ms"] = round(
            (_time.perf_counter() - t0) * 1e3, 1)
    finally:
        _stop_procs(procs)
        ray_tpu.shutdown()
        import shutil as _shutil
        _shutil.rmtree(tmp, ignore_errors=True)
    return out


def bench_train_gang_restart() -> dict:
    """Train gang-restart latency: a chaos ``train.worker_kill`` takes a
    rank down mid-run and the metric is the longest gap between
    consecutive driver-side result rounds — i.e. death detection +
    gang shutdown + backoff + restart + resume from the durable
    checkpoint to the first post-restart report. Latency-gated (an
    INCREASE beyond threshold regresses; see compare_rounds)."""
    import shutil as _shutil
    import tempfile as _tempfile
    import time as _time

    import ray_tpu
    from ray_tpu._private import chaos
    from ray_tpu.air.checkpoint import Checkpoint
    from ray_tpu.air.config import FailureConfig, ScalingConfig
    from ray_tpu.train._internal.backend_executor import BackendExecutor
    from ray_tpu.train._internal.checkpoint_manager import \
        CheckpointManager
    from ray_tpu.train.backend import BackendConfig

    def loop(config):
        from ray_tpu.air import session
        ckpt = session.get_checkpoint()
        start = ckpt.to_dict()["step"] if ckpt else 0
        for step in range(start, 8):
            session.report(
                {"step": step},
                checkpoint=Checkpoint.from_dict({"step": step + 1}))

    out = {}
    ray_tpu.init(num_cpus=4)
    storage = _tempfile.mkdtemp(prefix="bench_train_gang_")
    try:
        manager = CheckpointManager(storage, "bench-gang")
        executor = BackendExecutor(
            BackendConfig(), ScalingConfig(num_workers=2),
            FailureConfig(max_failures=2), checkpoint_manager=manager)
        executor.start()
        round_times = []

        def on_result(metrics):
            round_times.append(_time.perf_counter())
            return True

        # 2 matching calls per start_training + 2 per result round: the
        # 7th lands in round 3's gather, after two durable checkpoints.
        chaos.configure("kill:site=train.worker_kill:after=6:times=1")
        try:
            result = executor.run(loop, {}, {"trial_id": "bench-gang"},
                                  result_callback=on_result)
        finally:
            chaos.reset()
            executor.shutdown()
        assert result.metrics["step"] == 7, result.metrics
        gaps = [b - a for a, b in zip(round_times, round_times[1:])]
        out["train_gang_restart_ms"] = round(max(gaps) * 1e3, 1)
    finally:
        ray_tpu.shutdown()
        _shutil.rmtree(storage, ignore_errors=True)
    return out


def bench_sharded_checkpoint() -> dict:
    """Sharded checkpoint save/restore at bench scale vs the monolithic
    path, plus elastic-shrink throughput retention. A ~48 MB synthetic
    param tree is saved (a) monolithically through
    ``CheckpointManager.register`` (one rank-0 writer for the full
    tree) and (b) as 4 per-rank shard files written by parallel threads
    with the manifest committed last; restore reassembles the full tree
    from the shards. ``train_ckpt_save_ms`` / ``train_ckpt_restore_ms``
    are latency-gated (an INCREASE beyond threshold regresses — see
    compare_rounds); the monolithic baseline rides along so the
    sharded-beats-monolithic acceptance is visible in every round. The
    retention extra shrinks an 8-rank sharded run to a 4-rank gang via
    reshard-on-restart and reports the per-rank step-rate kept."""
    import shutil as _shutil
    import tempfile as _tempfile
    import time as _time
    from concurrent.futures import ThreadPoolExecutor

    import numpy as np

    import ray_tpu
    from ray_tpu.air.checkpoint import Checkpoint
    from ray_tpu.train._internal import sharded_checkpoint as sc
    from ray_tpu.train._internal.checkpoint_manager import \
        CheckpointManager

    # One runtime for both halves — checkpoint-manager journal/metric
    # emission lazily boots a runtime, and a second init() would throw.
    ray_tpu.init(num_cpus=8)

    world = 4
    # 12 x (1024 x 1024) f32 layers = 48 MB, big enough that write
    # bandwidth (not fixed overhead) decides the comparison.
    state = {f"layer{i:02d}": {"w": np.random.default_rng(i)
             .standard_normal((1024, 1024)).astype(np.float32)}
             for i in range(12)}
    out = {}
    tmp = _tempfile.mkdtemp(prefix="bench_shard_ckpt_")
    try:
        mgr = CheckpointManager(tmp, "bench-shard")
        t0 = _time.perf_counter()
        mgr.register(Checkpoint.from_dict({"state": state}))
        out["train_ckpt_save_monolithic_ms"] = round(
            (_time.perf_counter() - t0) * 1e3, 1)

        # On a real gang each rank already holds only its shard, so
        # extraction is not part of the measured save path.
        flat, structure = sc.flatten_tree(state)
        specs = sc.default_specs(flat)
        axes = [("fsdp", world)]
        shards = [sc.extract_local_shard(flat, specs, axes, r)
                  for r in range(world)]
        seq = mgr.next_seq_base()
        t0 = _time.perf_counter()
        with ThreadPoolExecutor(max_workers=world) as pool:
            records = list(pool.map(
                lambda r: sc.write_shard(mgr._backend, "bench-shard",
                                         seq, r, shards[r]),
                range(world)))
        meta = sc.build_tree_meta(flat, structure, specs, axes,
                                  extra={"step": 1})
        handle = mgr.register_sharded(seq, meta, records)
        out["train_ckpt_save_ms"] = round(
            (_time.perf_counter() - t0) * 1e3, 1)
        assert handle is not None

        t0 = _time.perf_counter()
        restored = handle.load_full()
        out["train_ckpt_restore_ms"] = round(
            (_time.perf_counter() - t0) * 1e3, 1)
        rflat, _ = sc.flatten_tree(restored)
        assert all(np.array_equal(np.asarray(rflat[p]),
                                  np.asarray(flat[p])) for p in flat)
    finally:
        _shutil.rmtree(tmp, ignore_errors=True)

    # Elastic shrink retention: 8 ranks checkpoint sharded, the gang
    # loses placement down to 4, resumes via reshard and keeps going.
    from ray_tpu.air.config import FailureConfig, ScalingConfig
    from ray_tpu.train._internal.backend_executor import BackendExecutor
    from ray_tpu.train.backend import BackendConfig

    def loop(config):
        from ray_tpu.air import session
        ckpt = session.get_checkpoint()
        start = ckpt.to_dict()["step"] if ckpt else 0
        w = session.get_world_size()
        for i in range(start, 12):
            session.report_sharded(
                {"step": i, "world": w},
                {"w": np.full((256, 16), float(i), np.float32)},
                extra={"step": i + 1})
            if w == 8 and i + 1 >= 4:
                raise RuntimeError("slice lost")

    storage = _tempfile.mkdtemp(prefix="bench_shard_shrink_")
    orig_placeable = BackendExecutor._placeable_workers
    try:
        from ray_tpu._private.worker import global_worker
        global_worker._runtime.config.set("train_restart_wait_s", 0.1)
        # Only consulted on restart: the replacement gang caps at 4.
        BackendExecutor._placeable_workers = lambda self, desired: 4
        manager = CheckpointManager(storage, "bench-shrink")
        executor = BackendExecutor(
            BackendConfig(), ScalingConfig(num_workers=8, min_workers=4),
            FailureConfig(max_failures=1), checkpoint_manager=manager)
        executor.start()
        rounds = []

        def on_result(metrics):
            rounds.append((_time.perf_counter(), metrics.get("world")))
            return True

        result = executor.run(loop, {}, {"trial_id": "bench-shrink"},
                              result_callback=on_result)
        executor.shutdown()
        assert result.metrics["step"] == 11, result.metrics
        assert result.metrics["world"] == 4, result.metrics

        def _per_rank_rate(w):
            ts = [t for t, ww in rounds if ww == w]
            gaps = [b - a for a, b in zip(ts, ts[1:])]
            return (len(gaps) / sum(gaps) / w) if gaps else 0.0

        r8, r4 = _per_rank_rate(8), _per_rank_rate(4)
        if r8 > 0:
            out["train_shrink_mfu_retention_pct"] = round(
                100.0 * r4 / r8, 1)
    finally:
        BackendExecutor._placeable_workers = orig_placeable
        ray_tpu.shutdown()
        _shutil.rmtree(storage, ignore_errors=True)
    return out


def bench_serve() -> dict:
    """Serving-plane throughput/latency (reference: release/serve_tests
    autoscaling_single_deployment + single_deployment_1k_noop_replica):
    HTTP QPS + p50/p95 through proxy -> router -> replica with the
    controller OFF the request path, measured ACROSS a replica-count
    curve (1/2/4) — the scaling dimension release tests sweep. Replicas
    do 10ms of IO-shaped work under a per-replica concurrency cap so
    QPS is replica-bound (a GIL-holding busy loop or a pure noop would
    flatten the curve)."""
    import concurrent.futures
    import time as _time
    import urllib.request

    import ray_tpu
    from ray_tpu import serve

    out = {}
    ray_tpu.init(num_cpus=8)
    try:
        def one(url):
            t0 = _time.perf_counter()
            with urllib.request.urlopen(url, timeout=30) as resp:
                resp.read()
            return _time.perf_counter() - t0

        for replicas in (1, 2, 4):
            # 10ms IO-shaped work + concurrency cap 2: each replica
            # tops out at ~200 QPS, so QPS tracks the replica count —
            # the replica-bound regime the release test sweeps (a
            # GIL-holding busy loop would flatten the curve: replicas
            # of one deployment share a process).
            @serve.deployment(num_replicas=replicas,
                              max_concurrent_queries=2,
                              name=f"work{replicas}")
            class Work:
                def __call__(self, req):
                    _time.sleep(0.010)
                    return b"ok"

            serve.run(Work.bind(), route_prefix=f"/work{replicas}",
                      port=0)
            url = f"http://127.0.0.1:{serve.http_port()}/work{replicas}"
            for _ in range(20):  # warmup: routes + router membership
                one(url)
            n, workers = 400, 16
            lat = []
            t0 = _time.perf_counter()
            with concurrent.futures.ThreadPoolExecutor(workers) as pool:
                for dt in pool.map(lambda _: one(url), range(n)):
                    lat.append(dt)
            wall = _time.perf_counter() - t0
            lat.sort()
            out[f"serve_qps_r{replicas}"] = round(n / wall, 1)
            out[f"serve_p50_ms_r{replicas}"] = round(
                lat[n // 2] * 1000, 2)
            out[f"serve_p95_ms_r{replicas}"] = round(
                lat[int(n * 0.95)] * 1000, 2)
        out["serve_qps"] = out["serve_qps_r2"]  # continuity metric
        out["serve_p50_ms"] = out["serve_p50_ms_r2"]
        out["serve_p95_ms"] = out["serve_p95_ms_r2"]
        serve.shutdown()
    finally:
        ray_tpu.shutdown()
    return out


def bench_serve_chaos() -> dict:
    """Availability under replica churn (ISSUE 7 acceptance: serve stays
    up): hammer a 3-replica deployment from worker threads while a
    killer thread kills a RUNNING replica every second. Transparent
    router failover + controller replacement should hold the
    client-visible error rate at zero with bounded tail latency;
    serve_chaos_qps counts only SUCCESSFUL requests so a regression in
    either throughput or availability moves the gated metric."""
    import concurrent.futures
    import random as _random
    import threading
    import time as _time

    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.serve._private.controller import get_or_create_controller

    out = {}
    ray_tpu.init(num_cpus=8)
    try:
        @serve.deployment(num_replicas=3, max_concurrent_queries=4,
                          name="chaoswork")
        class Work:
            def __call__(self, x):
                _time.sleep(0.004)
                return x

        handle = serve.run(Work.bind())
        assert ray_tpu.get(handle.remote(0), timeout=60) == 0
        controller = get_or_create_controller()
        stop = threading.Event()
        kills = [0]

        def killer():
            while not stop.wait(1.0):
                try:
                    states = ray_tpu.get(
                        controller.replica_states.remote("chaoswork"),
                        timeout=10)
                    running = [s for s in states
                               if s["state"] == "RUNNING"]
                    if len(running) <= 1:
                        continue  # leave at least one replica serving
                    victim = _random.choice(running)
                    ray_tpu.kill(ray_tpu.get_actor(victim["name"]))
                    kills[0] += 1
                except Exception:  # noqa: BLE001 - victim already gone
                    pass

        kt = threading.Thread(target=killer, daemon=True)
        kt.start()
        lat, errors, submitted = [], [0], [0]

        def one(i):
            t0 = _time.perf_counter()
            try:
                if ray_tpu.get(handle.remote(i), timeout=30) != i:
                    raise AssertionError("wrong serve result")
                lat.append(_time.perf_counter() - t0)
            except Exception:  # noqa: BLE001 - client-visible failure
                errors[0] += 1

        duration = 6.0
        t0 = _time.perf_counter()
        with concurrent.futures.ThreadPoolExecutor(8) as pool:
            futs = []
            while _time.perf_counter() - t0 < duration:
                futs.append(pool.submit(one, submitted[0]))
                submitted[0] += 1
                _time.sleep(0.002)
            for f in futs:
                f.result()
        wall = _time.perf_counter() - t0
        stop.set()
        kt.join(timeout=5)
        lat.sort()
        out["serve_chaos_qps"] = round(len(lat) / wall, 1)
        out["serve_chaos_error_rate"] = round(
            errors[0] / max(1, submitted[0]), 4)
        out["serve_chaos_p95_ms"] = round(
            lat[int(len(lat) * 0.95)] * 1000, 2) if lat else None
        out["serve_chaos_kills"] = kills[0]
        serve.shutdown()
    finally:
        ray_tpu.shutdown()
    return out


def bench_serve_autoscale() -> dict:
    """Self-driving serve plane (ISSUE 16 acceptance): a closed-loop
    client ramp against an autoscaled deployment (1..8 replicas, sized
    purely by the controller's autoscale pass over windowed queue
    depth) — serve_autoscale_qps is the sustained successful-request
    rate once the plane has walked itself up, with the p95 and the
    replica count it reached recorded alongside; plus fixed-vs-adaptive
    micro-batching through the same latency budget (adaptive sheds the
    wait timeout under light load, so its p95 should sit well under the
    fixed queue's)."""
    import asyncio
    import concurrent.futures
    import os
    import time as _time

    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.serve._private.controller import get_or_create_controller

    out = {}
    knobs = {
        "RAY_TPU_serve_autoscale_interval_s": "0.25",
        "RAY_TPU_serve_autoscale_window_s": "2",
        "RAY_TPU_serve_autoscale_downscale_delay_s": "30",
        "RAY_TPU_metrics_report_interval_ms": "200",
    }
    saved = {k: os.environ.get(k) for k in knobs}
    os.environ.update(knobs)
    ray_tpu.init(num_cpus=8)
    try:
        # 10ms IO-shaped work, concurrency cap 2: one replica tops out
        # at ~200 QPS, so 16 closed-loop clients build real queue depth
        # and sustained QPS tracks the replica count the autoscaler
        # reaches (same replica-bound regime as bench_serve, but here
        # NOBODY sets num_replicas — the controller walks it up alone).
        @serve.deployment(max_concurrent_queries=2, autoscaling_config={
            "min_replicas": 1, "max_replicas": 8,
            "target_ongoing_requests": 2}, name="autowork")
        class Work:
            def __call__(self, x):
                _time.sleep(0.010)
                return x

        handle = serve.run(Work.bind())

        def one(i):
            t0 = _time.perf_counter()
            ray_tpu.get(handle.remote(i), timeout=30)
            return _time.perf_counter() - t0

        for i in range(10):
            one(i)
        # Baseline second at 1 replica, then the ramp: total n chosen so
        # the scaled-up steady state dominates the tail half.
        n, workers = 1600, 16
        lat = []
        t0 = _time.perf_counter()
        with concurrent.futures.ThreadPoolExecutor(workers) as pool:
            for dt in pool.map(one, range(n)):
                lat.append(dt)
        wall = _time.perf_counter() - t0
        tail = sorted(lat[n // 2:])  # steady state: post-ramp half
        out["serve_autoscale_qps"] = round(n / wall, 1)
        out["serve_autoscale_p95_ms"] = round(
            tail[int(len(tail) * 0.95)] * 1000, 2)
        status = ray_tpu.get(
            get_or_create_controller().autoscale_status.remote(),
            timeout=10)
        out["serve_autoscale_replicas_peak"] = \
            status["autowork"]["target"]
        serve.shutdown()

        # Fixed vs adaptive micro-batching, light sequential load: the
        # fixed queue eats its full 30ms wait per batch; the adaptive
        # one (10ms budget) halves the wait until p95 fits. p95 over
        # the LAST half so adaptation has converged.
        async def batch_p95(target_latency_s):
            from ray_tpu.serve.batching import _BatchQueue

            async def fn(items):
                await asyncio.sleep(0.002)
                return items

            q = _BatchQueue(fn, max_batch_size=16, timeout_s=0.03,
                            target_latency_s=target_latency_s,
                            name="bench")
            samples = []
            for i in range(60):
                t0 = _time.perf_counter()
                await q.submit(i)
                samples.append(_time.perf_counter() - t0)
            tail = sorted(samples[30:])
            return tail[int(len(tail) * 0.95)]

        fixed = asyncio.run(batch_p95(None))
        adaptive = asyncio.run(batch_p95(0.010))
        out["serve_batch_fixed_p95_ms"] = round(fixed * 1000, 2)
        out["serve_batch_adaptive_p95_ms"] = round(adaptive * 1000, 2)
        out["serve_batch_adaptive_speedup"] = round(
            fixed / max(adaptive, 1e-9), 2)
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        ray_tpu.shutdown()
    return out


RLLIB_BENCH_SCRIPT = """
import json, os, time
BATCH = 2048
os.environ.pop("XLA_FLAGS", None)
import jax
# Rollouts stay on CPU (batch-small inference over the remote-TPU
# tunnel is latency-bound; the reference samples on CPU workers too)
# while the fused PPO learner jits onto the chip when one is reachable
# — the reference's CPU-rollout/GPU-learner split as two jax backends.
learner_backend = None
try:
    jax.config.update("jax_platforms", "cpu,axon")
    jax.devices("axon")
    learner_backend = "axon"
except Exception:
    jax.config.update("jax_platforms", "cpu")
import ray_tpu
ray_tpu.init(num_cpus=8)
from ray_tpu.rllib import PPOConfig
from ray_tpu.rllib.env.atari import make_synthetic_atari
config = (PPOConfig()
          .environment(make_synthetic_atari, env_config={"drops": 8})
          .rollouts(num_rollout_workers=4, rollout_fragment_length=256,
                    # 2 envs/worker: batched inference AND full episodes
                    # inside each fragment (8 envs -> 64 steps/env never
                    # finishes an episode; reward_mean reads NaN).
                    num_envs_per_worker=2)
          .training(lr=3e-4, train_batch_size=BATCH, num_sgd_iter=4,
                    sgd_minibatch_size=256, learner_backend=learner_backend,
                    model={"conv_filters": [[16, 8, 4], [32, 4, 2],
                                            [64, 3, 2]],
                           "post_fcnet_dim": 256})
          .debugging(seed=0))
algo = config.build()
algo.train()  # warmup 1: policy fwd/bwd + learner program compiles
algo.train()  # warmup 2: any lazily-compiled tail (chip-learner path)
t0 = time.perf_counter()
iters = 3
for _ in range(iters):
    res = algo.train()
dt = time.perf_counter() - t0
print(json.dumps({
    "rllib_env_steps_per_sec": round(iters * BATCH / dt, 1),
    "rllib_reward_mean": round(
        float(res.get("episode_reward_mean", float("nan"))), 2),
    "rllib_learner_backend": learner_backend or "cpu",
}))
algo.stop()
ray_tpu.shutdown()
"""


RLLIB_GROUP_BENCH_SCRIPT = """
import json, os, time
BATCH = 2048
os.environ.pop("XLA_FLAGS", None)
import jax
jax.config.update("jax_platforms", "cpu")
import ray_tpu
ray_tpu.init(num_cpus=8)
from ray_tpu.rllib import PPOConfig
config = (PPOConfig()
          .environment("CartPole-v1")
          .rollouts(num_rollout_workers=2, rollout_fragment_length=256)
          .training(lr=3e-4, train_batch_size=BATCH, num_sgd_iter=4,
                    sgd_minibatch_size=512, num_learners=2)
          .debugging(seed=0))
algo = config.build()
algo.train()  # warmup: shard actors compile their grad/apply programs
t0 = time.perf_counter()
iters = 3
for _ in range(iters):
    res = algo.train()
dt = time.perf_counter() - t0
print(json.dumps({
    "rllib_group_env_steps_per_sec": round(iters * BATCH / dt, 1),
    "rllib_group_num_learners": 2,
}))
algo.stop()
ray_tpu.shutdown()
"""


def bench_rllib_learner_group() -> dict:
    """PPO through the learner GROUP (num_learners=2 gradient-shard
    actors; reference: trainer_runner.py): the synchronous-DP update
    path's end-to-end env-steps/s."""
    import json as _json
    import subprocess
    import sys

    proc = subprocess.run([sys.executable, "-c",
                           RLLIB_GROUP_BENCH_SCRIPT],
                          capture_output=True, text=True, timeout=1200)
    if proc.returncode != 0:
        raise RuntimeError(
            f"rllib group bench failed: {proc.stderr[-1500:]}")
    return _json.loads(proc.stdout.strip().splitlines()[-1])


RLLIB_DAEMON_BENCH_SCRIPT = """
import json, os, subprocess, sys, time
BATCH = 2048
os.environ.pop("XLA_FLAGS", None)
import jax
jax.config.update("jax_platforms", "cpu")
import ray_tpu
# Head keeps ONE cpu (the learner); rollout actors land on the daemons
# and their SampleBatches ship over the daemon->head channel — the
# actual scale-out configuration (BASELINE: env-steps/s on a pod).
ray_tpu.init(num_cpus=1)
host, port = ray_tpu.start_head_server(port=0, host="127.0.0.1")
procs = [subprocess.Popen(
    [sys.executable, "-m", "ray_tpu._private.multinode",
     "--address", f"127.0.0.1:{port}", "--num-cpus", "4"],
    stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    for _ in range(2)]
import atexit
def _atexit_stop():
    for p in procs:
        p.terminate()
    for p in procs:
        try:
            p.wait(timeout=5)
        except Exception:
            p.kill()
atexit.register(_atexit_stop)
deadline = time.monotonic() + 30
while time.monotonic() < deadline:
    if ray_tpu.cluster_resources().get("CPU", 0) >= 9:
        break
    time.sleep(0.1)
else:
    raise TimeoutError("rllib bench daemons never registered")
from ray_tpu.rllib import PPOConfig
from ray_tpu.rllib.env.atari import make_synthetic_atari
config = (PPOConfig()
          .environment(make_synthetic_atari, env_config={"drops": 8})
          .rollouts(num_rollout_workers=4, rollout_fragment_length=256,
                    num_envs_per_worker=2)
          .training(lr=3e-4, train_batch_size=BATCH, num_sgd_iter=2,
                    sgd_minibatch_size=256,
                    model={"conv_filters": [[16, 8, 4], [32, 4, 2],
                                            [64, 3, 2]],
                           "post_fcnet_dim": 256})
          .debugging(seed=0))
algo = config.build()
from ray_tpu._private.worker import global_worker
rt = global_worker._runtime
on_daemons = sum(
    1 for a in rt._actors.values()
    if getattr(a.creation_spec, "_node_id", None) in rt._remote_nodes)
algo.train()  # warmup: compiles + first weight sync
t0 = time.perf_counter()
iters = 2
for _ in range(iters):
    algo.train()
dt = time.perf_counter() - t0
print(json.dumps({
    "rllib_daemon_env_steps_per_sec": round(iters * BATCH / dt, 1),
    "rllib_rollout_actors_on_daemons": on_daemons,
}))
algo.stop()
for p in procs:
    p.terminate()  # SIGTERM: daemons unlink their shm arenas
for p in procs:
    try:
        p.wait(timeout=5)
    except Exception:
        p.kill()
ray_tpu.shutdown()
"""


def bench_rllib_daemons() -> dict:
    """Rollout scale-out: PPO env-steps/s with rollout actors placed on
    node-daemon processes, SampleBatches riding the object plane back to
    the head learner (the distributed-sampling configuration; the plain
    rllib bench measures the single-process path)."""
    import json as _json
    import subprocess
    import sys

    proc = subprocess.run([sys.executable, "-c",
                           RLLIB_DAEMON_BENCH_SCRIPT],
                          capture_output=True, text=True, timeout=1200)
    if proc.returncode != 0:
        raise RuntimeError(
            f"rllib daemon bench failed: {proc.stderr[-1500:]}")
    return _json.loads(proc.stdout.strip().splitlines()[-1])


def bench_rllib() -> dict:
    """The second north-star metric (BASELINE.json: "RLlib PPO Atari
    with JAX policy learner: env-steps/sec"): PPO with the CNN policy on
    the synthetic Atari-shaped env (84x84x4 uint8 after the deepmind
    wrapper stack; reference harness: tuned_examples/ppo/atari-ppo.yaml)
    — the full rollout(actors) + GAE + minibatch-SGD loop. Runs in a
    SUBPROCESS pinned to the CPU backend: this process holds the TPU,
    and per-step policy inference over the remote-chip tunnel would
    measure tunnel latency, not the framework."""
    import json as _json
    import subprocess
    import sys

    proc = subprocess.run([sys.executable, "-c", RLLIB_BENCH_SCRIPT],
                          capture_output=True, text=True, timeout=1200)
    if proc.returncode != 0:
        raise RuntimeError(f"rllib bench failed: {proc.stderr[-1500:]}")
    return _json.loads(proc.stdout.strip().splitlines()[-1])


def bench_diffusion() -> dict:
    """BASELINE.json config 5 ("Ray Serve Stable-Diffusion batch
    inference on TPU replicas"): DDIM sampling throughput of the
    diffusion UNet — the jitted program a Serve TPU replica runs per
    batched request (models/diffusion.py ddim_sample; Serve's batching
    layer adds microseconds against the 50-step UNet loop, so the
    replica's inner loop IS the number). The cifar-sized UNet keeps the
    one-off XLA compile inside the bench budget (~1.5 min; the SD-
    shaped sd-base preset compiles for 8+ minutes on this backend —
    examples/serve_diffusion.py serves it when you have the patience)."""
    import time as _time

    import jax

    from ray_tpu.models import diffusion

    device = jax.devices()[0]
    cfg = diffusion.config("ddpm-cifar")
    # Init on host then transfer once: the initializer is hundreds of
    # small RNG ops — op-by-op over the remote-chip tunnel costs
    # minutes; one device_put costs seconds.
    with jax.default_device(jax.devices("cpu")[0]):
        params = diffusion.init(cfg, jax.random.PRNGKey(0))
    params = jax.device_put(params, device)
    # Swept v5e: batch 8/107, 16/144, 32/190, 64/288, 128/306 imgs/s —
    # 64 is the knee and a realistic @serve.batch max_batch_size
    # (0.22s device time per batched request).
    batch, n_steps = 64, 50
    sample = jax.jit(lambda key: diffusion.ddim_sample(
        params, cfg, key, batch, n_steps=n_steps))
    out = sample(jax.random.PRNGKey(1))
    float(out.sum())  # sync (block_until_ready insufficient on tunnel)
    t0 = _time.perf_counter()
    iters = 3
    for i in range(iters):
        out = sample(jax.random.PRNGKey(2 + i))
    float(out.sum())
    dt = _time.perf_counter() - t0
    return {"diffusion_images_per_sec": round(iters * batch / dt, 2),
            "diffusion_batch": batch, "diffusion_ddim_steps": n_steps,
            "diffusion_preset": "ddpm-cifar"}


def _bench_gpt(preset: str, batch: int, seq: int, steps: int,
               warmup: int, overrides: dict, optimizer) -> dict:
    """One single-chip GPT training measurement -> tokens/s + MFU."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ray_tpu.models import gpt
    from ray_tpu.parallel import MeshConfig, ShardingRules, build_mesh
    from ray_tpu.parallel.train_step import (init_train_state,
                                             make_train_step)

    device = jax.devices()[0]
    cfg = gpt.config(preset, max_seq_len=seq, **overrides)
    mesh = build_mesh(MeshConfig(dp=1, fsdp=1, tp=1, sp=1, ep=1),
                      devices=[device])
    rules = ShardingRules(batch=None, embed=None, heads=None,
                          kv_heads=None, mlp=None, vocab=None)
    state = init_train_state(cfg, mesh, rules, optimizer, seed=0)
    step = make_train_step(cfg, mesh, rules, optimizer)
    rng = np.random.default_rng(0)
    toks = rng.integers(0, cfg.vocab_size, (batch, seq + 1))
    data = {"tokens": jnp.asarray(toks[:, :-1], jnp.int32),
            "targets": jnp.asarray(toks[:, 1:], jnp.int32)}
    for _ in range(max(warmup, 1)):  # >=1: the sync below needs metrics
        state, metrics = step(state, data)
    float(metrics["loss"])  # full device sync (block_until_ready is not
    # sufficient on the remote-tunnel backend)
    # SEGMENTED timing (r5): one continuous span produced a single dt
    # with zero distribution info — r03/r04 reported bit-identical
    # headlines and nothing could distinguish staleness from stability.
    # Three synced segments cost one extra pipeline drain each but give
    # a mean/std every run; the std is the tell (a reused/stale number
    # would repeat exactly, a live run varies at the ms level).
    n_segments = 3 if steps >= 3 else 1
    per = max(1, steps // n_segments)
    seg_times = []
    for s in range(n_segments):
        t0 = time.perf_counter()
        for _ in range(per):
            state, metrics = step(state, data)
        float(metrics["loss"])
        seg_times.append(time.perf_counter() - t0)
    total_steps = per * n_segments
    dt = sum(seg_times)
    tokens_per_sec = batch * seq * total_steps / dt
    per_step = [t / per for t in seg_times]
    step_mean = dt / total_steps
    step_std = (sum((t - step_mean) ** 2 for t in per_step)
                / len(per_step)) ** 0.5
    # Training FLOPs: 6N per token (fwd+bwd; remat recompute is not
    # counted as useful FLOPs — standard MFU convention) + attention.
    flops_per_token = 6.0 * cfg.num_params() + \
        12 * cfg.n_layers * cfg.d_model * seq
    mfu = tokens_per_sec * flops_per_token / _peak_flops(device)
    return {"tokens_per_sec": tokens_per_sec, "mfu": mfu,
            "step_time_mean_s": round(step_mean, 5),
            "step_time_std_s": round(step_std, 5),
            "segment_s": [round(t, 4) for t in seg_times]}


def bench_gptj6b(device) -> dict:
    """North-star reality check (BASELINE.json: GPT-J-6B fine-tune):
    train the ACTUAL 6b config single-chip when the chip's HBM can hold
    it, else measure the memory wall (exact byte math + the allocator's
    own error) and benchmark the largest trainable point (gpt-2.7b)
    instead. Either way BENCH carries a gptj6b_* entry."""
    import jax.numpy as jnp

    from ray_tpu.models import gpt
    from ray_tpu.parallel.train_step import memory_efficient_optimizer

    out: dict = {}
    cfg6 = gpt.config("gptj-6b", max_seq_len=1024)
    n_params = cfg6.num_params()
    # bf16 train footprint lower bound: params + grads (factored
    # adafactor moments add MBs, ignored). Measured on v5e: the 6b
    # program compiles to 28.57G vs 15.75G HBM.
    need = 2 * n_params * 2
    hbm_table = {"tpu v4": 32 << 30, "tpu v5 lite": 16 << 30,
                 "tpu v5p": 95 << 30, "tpu v6 lite": 32 << 30}
    kind = getattr(device, "device_kind", "").lower()
    hbm = next((v for k, v in hbm_table.items() if k in kind), 0)
    if not hbm:
        try:  # not in the table: believe the runtime
            hbm = (device.memory_stats() or {}).get(
                "bytes_limit", 16 << 30)
        except Exception:  # noqa: BLE001 - tunnel backends may not expose
            hbm = 16 << 30
    out["gptj6b_params"] = n_params
    out["gptj6b_train_bytes_min"] = need
    out["gptj6b_hbm_bytes"] = hbm
    note = (f"infeasible single-chip: bf16 params+grads = "
            f"{need / 1e9:.1f}GB > {hbm / 1e9:.1f}GB HBM")
    if need < hbm * 0.9:
        try:
            # Pure-bf16 train state (param_dtype default keeps fp32
            # masters — 48GB for 6b; adafactor needs no masters and the
            # bench is a throughput point, not a convergence run).
            m = _bench_gpt("gptj-6b", batch=1, seq=1024, steps=3,
                           warmup=1,
                           overrides=dict(attn_impl="flash",
                                          remat_policy="full",
                                          loss_chunk=4096,
                                          param_dtype=jnp.bfloat16),
                           optimizer=memory_efficient_optimizer(
                               learning_rate=1e-5))
            out["gptj6b_tokens_per_sec"] = round(m["tokens_per_sec"], 1)
            out["gptj6b_mfu"] = round(m["mfu"], 4)
            return out
        except Exception as exc:  # noqa: BLE001 - record the real wall
            note = f"6b attempt failed: {repr(exc)[:500]}"
    # Memory wall: document with the allocator's numbers, then ship the
    # largest trainable point. The 6b config itself trains with >=2
    # chips under fsdp (dryrun_multichip compiles that program).
    out["gptj6b_note"] = note
    try:
        # Mesh proof: lower the REAL 6b fsdp=8 program on the virtual
        # CPU mesh (own process: it pins jax_platforms=cpu) and record
        # XLA's per-device memory analysis — "fits with these bytes",
        # not just "compiles" (__graft_entry__.memory_proof_6b).
        import json as _json
        import subprocess
        import sys
        here = os.path.dirname(os.path.abspath(__file__))
        proc = subprocess.run(
            [sys.executable, "-c",
             "import sys, json; sys.path.insert(0, %r); "
             "import __graft_entry__ as g; "
             "print(json.dumps(g.memory_proof_6b(8)))" % here],
            capture_output=True, text=True, timeout=900)
        if proc.returncode == 0:
            proof = _json.loads(proc.stdout.strip().splitlines()[-1])
            out["gptj6b_fsdp8_need_bytes_per_device"] = \
                proof["per_device_need_bytes"]
            out["gptj6b_fsdp8_fits_v5e"] = proof["fits"]["v5e"]
        else:
            out["gptj6b_proof_error"] = proc.stderr[-500:]
    except Exception as exc:  # noqa: BLE001
        out["gptj6b_proof_error"] = repr(exc)[:500]
    # Swept v5e: batch 4/0.5566, 6/0.5685, 8/0.5701 MFU — 8 is the
    # largest that fits with full remat and the knee of the curve.
    m = _bench_gpt("gpt-2.7b", batch=8, seq=1024, steps=4, warmup=2,
                   overrides=dict(attn_impl="flash", remat_policy="full",
                                  loss_chunk=4096,
                                  param_dtype=jnp.bfloat16),
                   optimizer=memory_efficient_optimizer(
                       learning_rate=1e-5))
    out["gpt2_7b_tokens_per_sec"] = round(m["tokens_per_sec"], 1)
    out["gpt2_7b_mfu"] = round(m["mfu"], 4)
    return out


def bench_frame_path() -> dict:
    """Channel frame-path microbench over a socketpair — no cluster, so
    the v7 envelope + framing cost is visible in isolation.

    ``frame_send_mb_per_sec``: 8 MB payloads through
    ResilientChannel.send_parts (scatter-gather sendmsg, ring by
    reference — the zero-copy path the shuffle bench rides).
    ``frame_send_small_per_sec``: 128 B frames (joined sendall path —
    what tasks_per_sec rides)."""
    import socket as _socket
    import threading as _threading
    import time as _time

    from ray_tpu._private.channel import ResilientChannel

    out = {}
    a_sock, b_sock = _socket.socketpair()
    tx = ResilientChannel(a_sock, site="head", ring_bytes=1 << 30,
                          window_s=5.0)
    rx = ResilientChannel(b_sock, site="daemon", ring_bytes=1 << 30,
                          window_s=5.0)
    try:
        def _drain(n):
            for _ in range(n):
                rx.recv_frame()

        payload = memoryview(bytes(8 << 20))
        n_big = 24
        t = _threading.Thread(target=_drain, args=(n_big,), daemon=True)
        t.start()
        t0 = _time.perf_counter()
        for _ in range(n_big):
            tx.send_parts(payload)
        t.join()
        out["frame_send_mb_per_sec"] = round(
            n_big * 8 / (_time.perf_counter() - t0), 1)

        small = b"x" * 128
        n_small = 20000
        t = _threading.Thread(target=_drain, args=(n_small,), daemon=True)
        t.start()
        t0 = _time.perf_counter()
        for _ in range(n_small):
            tx.send_parts(small)
        t.join()
        out["frame_send_small_per_sec"] = round(
            n_small / (_time.perf_counter() - t0), 1)
    finally:
        tx.close()
        rx.close()
    return out


def _prior_round_bench():
    """Latest USABLE BENCH_r{N}.json next to this file (the driver
    records one per round); returns its parsed result dict or None.
    Rounds whose record carries no comparable numbers — parsed is null
    and the raw record has neither extras nor a headline value (e.g. a
    truncated capture) — are skipped, so the gate baselines against the
    newest round that can actually be compared."""
    import glob
    import re as _re
    here = os.path.dirname(os.path.abspath(__file__))
    rounds = []
    for path in glob.glob(os.path.join(here, "BENCH_r*.json")):
        m = _re.search(r"BENCH_r(\d+)\.json$", path)
        if m:
            rounds.append((int(m.group(1)), path))
    for _, path in sorted(rounds, reverse=True):
        try:
            with open(path) as f:
                rec = json.load(f)
        except (OSError, ValueError):
            continue
        parsed = rec.get("parsed") or rec
        if isinstance(parsed, dict) and (
                isinstance(parsed.get("extra"), dict)
                or isinstance(parsed.get("value"), (int, float))):
            return parsed, os.path.basename(path)
    return None, None


# Latency metrics gated by NAME, not suffix: `_ms` extras are mostly
# informational (detached_actor_restart_ms etc. must stay ungated — see
# test_only_throughput_suffixes_compared); these few regress when they
# INCREASE beyond the threshold.
_LATENCY_GATED = ("train_gang_restart_ms", "node_death_detect_ms",
                  "object_restore_ms", "head_failover_recovery_ms",
                  "train_ckpt_save_ms", "train_ckpt_restore_ms")


def compare_rounds(prev: dict, extra: dict, headline_value,
                   threshold: float = 0.10) -> list:
    """Pure comparator behind the regression gate: throughput metrics
    (``*per_sec``/``*_qps``/``*_mfu``/``*mb_per_sec`` keys of the prior
    round's extras, plus the headline value) that dropped by more than
    ``threshold`` (a fraction: 0.10 = 10%), plus the explicitly
    allowlisted ``_LATENCY_GATED`` metrics when they ROSE by more than
    ``threshold``. Improvements, non-numeric values, and metrics absent
    from either side are ignored. Returns
    [{metric, prev, now, drop_pct}, ...] (a latency rise is recorded as
    a negative drop_pct)."""
    import re as _re
    floor = 1.0 - threshold
    prev_extra = (prev or {}).get("extra") or {}
    regressions = []
    pattern = _re.compile(r"(per_sec|_qps|_mfu|mb_per_sec)$")
    for k, old in prev_extra.items():
        if not isinstance(old, (int, float)) or old <= 0:
            continue
        if not pattern.search(k):
            continue
        new = extra.get(k)
        if isinstance(new, (int, float)) and new < floor * old:
            drop = round(100 * (1 - new / old), 1)
            regressions.append({"metric": k, "prev": old, "now": new,
                                "drop_pct": drop})
    for k in _LATENCY_GATED:
        old = prev_extra.get(k)
        new = extra.get(k)
        if not isinstance(old, (int, float)) or old <= 0:
            continue
        if isinstance(new, (int, float)) and new > (1.0 + threshold) * old:
            drop = round(100 * (1 - new / old), 1)  # negative = rise
            regressions.append({"metric": k, "prev": old, "now": new,
                                "drop_pct": drop})
    prev_head = (prev or {}).get("value")
    if isinstance(prev_head, (int, float)) and prev_head > 0 and \
            isinstance(headline_value, (int, float)) and \
            headline_value < floor * prev_head:
        drop = round(100 * (1 - headline_value / prev_head), 1)
        regressions.append({"metric": "headline", "prev": prev_head,
                            "now": headline_value, "drop_pct": drop})
    return regressions


def _regression_gate(extra: dict, headline_value: float) -> None:
    """Compare throughput metrics against the prior round's recorded
    bench (reference: release microbenchmark trend tracking). A >=10%
    drop WARNS on stderr and is recorded in extra['regressions'] so it
    can never again go unnoticed for two rounds (tasks_per_sec fell
    10,349 -> 7,481 across r02-r04 silently)."""
    import sys as _sys
    prev, name = _prior_round_bench()
    if not prev:
        return
    extra["regression_baseline"] = name
    regressions = compare_rounds(prev, extra, headline_value,
                                 threshold=0.10)
    for r in regressions:
        print(f"REGRESSION WARNING: {r['metric']} {r['prev']} -> "
              f"{r['now']} (-{r['drop_pct']}%) vs {name}",
              file=_sys.stderr)
    if regressions:
        extra["regressions"] = regressions


def _recapture_microbench(extra: dict) -> None:
    """Refresh MICROBENCH.json every bench run (reference:
    release/microbenchmark runs nightly) so core-ops trends get a data
    point per round instead of a stale r2-era snapshot."""
    import datetime
    import platform

    from ray_tpu._private import ray_perf
    results = ray_perf.main(duration=1.0)
    here = os.path.dirname(os.path.abspath(__file__))
    doc = {
        "recorded": datetime.date.today().isoformat(),
        "host": {"machine": platform.machine(),
                 "cpus": os.cpu_count()},
        "note": ("Core ops/s microbenchmarks (reference: "
                 "_private/ray_perf.py:93 + release/microbenchmark). "
                 "Reproduce: `ray-tpu microbenchmark`. Re-captured by "
                 "every bench.py run."),
        "results": results,
    }
    with open(os.path.join(here, "MICROBENCH.json"), "w") as f:
        json.dump(doc, f, indent=1)
    extra["microbench"] = {r["name"]: round(r["ops_per_s"], 1)
                           for r in results}


def _parse_args(argv=None):
    import argparse
    ap = argparse.ArgumentParser(
        description="ray_tpu benchmark suite (one JSON result on stdout)")
    ap.add_argument(
        "--check-regressions", action="store_true",
        help="exit nonzero when any throughput metric dropped more than "
             "the regression threshold vs the prior round's BENCH file")
    ap.add_argument(
        "--regression-threshold", type=float, default=20.0,
        metavar="PCT",
        help="drop percentage that fails --check-regressions "
             "(default: 20)")
    return ap.parse_args(argv)


def _with_watchdog(fn, timeout_s=None):
    """Run one extras-suite bench under a SIGALRM watchdog.

    The multi-daemon benches can wedge (not fail) when a starved daemon
    is declared dead mid-shuffle and recovery livelocks — an exception
    guard alone never fires and the whole round hangs. The alarm raises
    TimeoutError in the main thread, which unwinds through the bench's
    own ``finally`` (daemon teardown, runtime shutdown) and is recorded
    as that extra's error like any other failure. The handler re-arms a
    short grace alarm so a teardown that also wedges cannot re-hang the
    round. Tune via RAY_TPU_BENCH_EXTRA_TIMEOUT_S (default 600; 0
    disables)."""
    import os as _os
    import signal as _signal

    if timeout_s is None:
        timeout_s = int(float(
            _os.environ.get("RAY_TPU_BENCH_EXTRA_TIMEOUT_S", "600")))
    if timeout_s <= 0 or not hasattr(_signal, "SIGALRM"):
        return fn()

    def _on_alarm(signum, frame):
        _signal.alarm(120)  # grace window for the bench's own cleanup
        raise TimeoutError(
            f"bench extra exceeded {timeout_s}s watchdog")

    old = _signal.signal(_signal.SIGALRM, _on_alarm)
    _signal.alarm(timeout_s)
    try:
        return fn()
    finally:
        _signal.alarm(0)
        _signal.signal(_signal.SIGALRM, old)


def main(argv=None):
    args = _parse_args(argv)
    import jax

    from ray_tpu.parallel.train_step import (default_optimizer,
                                             memory_efficient_optimizer)

    device = jax.devices()[0]
    on_tpu = device.platform == "tpu"
    extra = {}
    if on_tpu:
        # HEADLINE: gpt-1.3b — the HBM-pressure model, the closest
        # single-chip stand-in for the GPT-J-6B north star. Recipe:
        # adafactor (factored second moments; adam state alone would
        # blow the 16G chip), Pallas flash attention, FULL remat
        # (activation memory buys batch 12, which beats selective remat
        # at its smaller max batch), chunked CE. Measured v5e sweeps:
        # batch 2/0.42, 4/0.51, 8/0.59, 12/0.619, 13-16 regress;
        # loss_chunk 4096 > 2048 (0.6177) > 6144; 512x512 attn tiles
        # beat 1024-wide variants.
        head = _bench_gpt(
            "gpt-1.3b", batch=12, seq=1024, steps=6, warmup=2,
            overrides=dict(attn_impl="flash", remat_policy="full",
                           loss_chunk=4096),
            optimizer=memory_efficient_optimizer(learning_rate=1e-4))
        preset = "gpt-1.3b"
        # Continuity metric: the round-1 headline model and recipe.
        try:
            m410 = _bench_gpt(
                "gpt-410m", batch=18, seq=1024, steps=10, warmup=2,
                overrides=dict(attn_impl="flash",
                               remat_policy="selective",
                               loss_chunk=6144),
                optimizer=default_optimizer(learning_rate=1e-4))
            extra["gpt410m_tokens_per_sec"] = round(
                m410["tokens_per_sec"], 1)
            extra["gpt410m_mfu"] = round(m410["mfu"], 4)
        except Exception as exc:  # noqa: BLE001 - never sink the headline
            extra.setdefault("gpt410m_mfu", None)
            extra["gpt410m_error"] = repr(exc)[:800]
    else:
        head = _bench_gpt("gpt-tiny", batch=4, seq=128, steps=5,
                          warmup=1, overrides={},
                          optimizer=default_optimizer(learning_rate=1e-4))
        preset = "gpt-tiny"
    tokens_per_sec, mfu = head["tokens_per_sec"], head["mfu"]

    # Extras must not sink the headline, but a failure is RECORDED, never
    # silently nulled (reference: release/ray_release/result.py — release
    # test results always carry their failure cause).
    extras_suite = [
        ("core_ops", "tasks_per_sec", bench_core_ops),
        ("rllib", "rllib_env_steps_per_sec", bench_rllib),
        ("rllib_daemon", "rllib_daemon_env_steps_per_sec",
         bench_rllib_daemons),
        ("rllib_group", "rllib_group_env_steps_per_sec",
         bench_rllib_learner_group),
        ("shuffle", "shuffle_mb_per_sec", bench_data_shuffle),
        ("serve", "serve_qps", bench_serve),
        ("serve_availability_under_chaos", "serve_chaos_qps",
         bench_serve_chaos),
        ("serve_autoscale", "serve_autoscale_qps",
         bench_serve_autoscale),
        ("shuffle_multi", "shuffle_multi_mb_per_sec",
         bench_shuffle_multi_daemon),
        ("broadcast", "broadcast_mb_per_sec", bench_broadcast),
        ("pull_striped", "pull_striped_mb_per_sec", bench_pull_striped),
        ("envelope", "envelope_tasks_per_sec", bench_envelope),
        ("detached_restart", "detached_actor_restart_ms",
         bench_detached_restart),
        ("channel_reconnect", "channel_reconnect_ms",
         bench_channel_reconnect),
        ("object_recovery", "node_death_detect_ms", bench_object_recovery),
        ("head_failover", "head_failover_recovery_ms",
         bench_head_failover),
        ("train_gang_restart", "train_gang_restart_ms",
         bench_train_gang_restart),
        ("sharded_ckpt", "train_ckpt_save_ms", bench_sharded_checkpoint),
        ("log_stream", "log_lines_per_sec", bench_log_streaming),
        ("metrics_overhead", "metrics_overhead_pct",
         bench_metrics_overhead),
        ("tracing_overhead", "tracing_overhead_pct",
         bench_tracing_overhead),
        ("timeseries_overhead", "timeseries_overhead_pct",
         bench_timeseries_overhead),
        ("alerting_overhead", "alerting_overhead_pct",
         bench_alerting_overhead),
        ("profiling_overhead", "profiling_overhead_pct",
         bench_profiling_overhead),
        ("flow_overhead", "flow_records_per_sec", bench_flow_overhead),
        ("frame_path", "frame_send_mb_per_sec", bench_frame_path),
    ]
    if on_tpu:
        extras_suite.append(
            ("diffusion", "diffusion_images_per_sec", bench_diffusion))
        extras_suite.append(
            ("gptj6b", "gptj6b_params", lambda: bench_gptj6b(device)))
    for key, metric, fn in extras_suite:
        try:
            extra.update(_with_watchdog(fn))
        except Exception as exc:  # noqa: BLE001
            extra.setdefault(metric, None)
            extra[f"{key}_error"] = repr(exc)[:800]

    try:
        _recapture_microbench(extra)
    except Exception as exc:  # noqa: BLE001
        extra["microbench_error"] = repr(exc)[:800]

    # Run identity + distribution: a stale/reused result is now
    # distinguishable from a stable one (unique nonce, per-run stddev).
    import time as _time
    import uuid as _uuid
    extra["run_nonce"] = _uuid.uuid4().hex
    extra["run_unix_time"] = round(_time.time(), 1)
    for k in ("step_time_mean_s", "step_time_std_s", "segment_s"):
        if k in head:
            extra[f"headline_{k}"] = head[k]

    headline_value = round(tokens_per_sec, 1)
    try:
        _regression_gate(extra, headline_value)
    except Exception as exc:  # noqa: BLE001
        extra["regression_gate_error"] = repr(exc)[:800]

    result = {
        "metric": f"{preset}_train_tokens_per_sec_per_chip",
        "value": headline_value,
        "unit": "tokens/s/chip",
        "vs_baseline": round(mfu / 0.40, 4),
        "extra": extra,
    }
    print(json.dumps(result))

    if args.check_regressions:
        import sys as _sys
        prev, name = _prior_round_bench()
        gated = compare_rounds(prev, extra, headline_value,
                               threshold=args.regression_threshold / 100.0)
        if gated:
            print(f"FAIL: {len(gated)} metric(s) regressed more than "
                  f"{args.regression_threshold}% vs {name}: "
                  + ", ".join(r["metric"] for r in gated),
                  file=_sys.stderr)
            _sys.exit(1)


if __name__ == "__main__":
    main()
