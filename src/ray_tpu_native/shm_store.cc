// shm_store.cc — single-host shared-memory object store (C ABI).
//
// TPU-native equivalent of the reference's plasma store
// (src/ray/object_manager/plasma/store.h:55, plasma_allocator.cc,
// eviction_policy.cc): one POSIX shared-memory arena per node holding BOTH
// object payloads and ALL store metadata (entry table, free list, LRU
// chain, process-shared mutex), so any process on the host maps the same
// file and gets the same store — no broker process or socket protocol in
// the loop (plasma needs one because its metadata lives in the store
// server; putting metadata in the arena removes that hop).
//
// Layout:  [ Header | EntryTable | FreeBlockPool | data region ]
// - Entry table: open-addressing hash (linear probe, tombstones).
// - Allocator: first-fit over a shm-resident free-block list, coalescing.
// - Eviction: LRU over sealed refcount-0 entries, evicted under pressure.
// - Locking: one pthread process-shared robust mutex in the header.
//
// The Python binding (ray_tpu/_private/native_store.py) wraps payload
// offsets as zero-copy numpy views; jax.device_put on a view is the
// host->TPU DMA with no intermediate copy.
//
// Build: g++ -O2 -shared -fPIC -std=c++17 -o libshm_store.so shm_store.cc

#include <cerrno>
#include <cstdint>
#include <cstring>
#include <fcntl.h>
#include <pthread.h>
#include <string>
#include <sys/mman.h>
#include <unistd.h>

namespace {

constexpr uint64_t kMagic = 0x5261795450553032ULL;  // "RayTPU02"
constexpr uint32_t kMaxIdLen = 63;

enum EntryState : uint8_t {
  kEmpty = 0,
  kCreated = 1,
  kSealed = 2,
  kTombstone = 3,
};

struct Entry {
  char id[kMaxIdLen + 1];
  uint8_t state;
  uint8_t in_lru;
  int32_t refcount;
  uint64_t offset;
  uint64_t size;        // payload size
  uint64_t alloc_size;  // aligned allocation size
  int32_t lru_prev;     // entry index or -1
  int32_t lru_next;
};

struct FreeBlock {
  uint64_t offset;
  uint64_t size;
  int32_t next;  // pool index or -1
  uint8_t used;  // slot in use
};

struct Header {
  uint64_t magic;
  uint64_t capacity;
  uint64_t data_off;
  uint64_t data_size;
  uint64_t used;
  uint32_t max_objects;
  uint32_t num_objects;
  // When set, Create() never evicts to make room — it fails with -1 and
  // the owning daemon spills LRU victims to disk instead (reference:
  // raylet-orchestrated spill, src/ray/raylet/local_object_manager.h —
  // plasma itself only reports OutOfMemory; the policy lives above it).
  uint32_t evict_disabled;
  int32_t free_head;  // free-block list head (pool index)
  int32_t lru_head;   // least-recently-used entry index
  int32_t lru_tail;
  pthread_mutex_t mu;
};

class ShmStore {
 public:
  ShmStore(const char* name, uint64_t capacity, bool create)
      : name_(name) {
    int flags = create ? (O_RDWR | O_CREAT | O_EXCL) : O_RDWR;
    fd_ = shm_open(name, flags, 0600);
    bool we_created = fd_ >= 0 && create;
    if (fd_ < 0 && create) {  // exists: attach instead
      fd_ = shm_open(name, O_RDWR, 0600);
      we_created = false;
    }
    if (fd_ < 0) return;
    if (we_created && ftruncate(fd_, (off_t)capacity) != 0) {
      close(fd_);
      fd_ = -1;
      return;
    }
    if (!we_created) {
      // Attach: read capacity from the header (map a page first).
      void* probe = mmap(nullptr, sizeof(Header), PROT_READ, MAP_SHARED,
                         fd_, 0);
      if (probe == MAP_FAILED) {
        close(fd_);
        fd_ = -1;
        return;
      }
      capacity = static_cast<Header*>(probe)->capacity;
      munmap(probe, sizeof(Header));
    }
    capacity_ = capacity;
    base_ = static_cast<uint8_t*>(mmap(nullptr, capacity,
                                       PROT_READ | PROT_WRITE, MAP_SHARED,
                                       fd_, 0));
    if (base_ == MAP_FAILED) {
      base_ = nullptr;
      close(fd_);
      fd_ = -1;
      return;
    }
    hdr_ = reinterpret_cast<Header*>(base_);
    if (we_created) Init();
    entries_ = reinterpret_cast<Entry*>(base_ + sizeof(Header));
    pool_ = reinterpret_cast<FreeBlock*>(
        base_ + sizeof(Header) + sizeof(Entry) * hdr_->max_objects);
  }

  ~ShmStore() {
    if (base_) munmap(base_, capacity_);
    if (fd_ >= 0) close(fd_);
  }

  bool ok() const { return base_ != nullptr && hdr_->magic == kMagic; }
  uint8_t* base() const { return base_; }
  void unlink_shm() { shm_unlink(name_.c_str()); }

  int64_t Create(const char* id, uint64_t size) {
    size_t idlen = strnlen(id, kMaxIdLen + 1);
    if (idlen > kMaxIdLen) return -3;
    Lock l(hdr_);
    int32_t idx = FindLocked(id);
    if (idx >= 0) return -2;  // exists
    uint64_t alloc = (size ? size : 1);
    alloc = (alloc + 63) & ~uint64_t(63);
    int64_t off = AllocLocked(alloc);
    while (off < 0 && !hdr_->evict_disabled && EvictOneLocked())
      off = AllocLocked(alloc);
    if (off < 0) return -1;
    idx = InsertLocked(id);
    if (idx < 0) {
      FreeRegionLocked((uint64_t)off, alloc);
      return -4;  // table full
    }
    Entry& e = entries_[idx];
    e.state = kCreated;
    e.refcount = 1;  // creator ref until seal
    e.offset = (uint64_t)off;
    e.size = size;
    e.alloc_size = alloc;
    e.in_lru = 0;
    hdr_->used += alloc;
    hdr_->num_objects++;
    return off;
  }

  int Seal(const char* id) {
    Lock l(hdr_);
    int32_t idx = FindLocked(id);
    if (idx < 0) return -1;
    Entry& e = entries_[idx];
    e.state = kSealed;
    if (--e.refcount == 0) LruPushLocked(idx);
    return 0;
  }

  int64_t Get(const char* id, uint64_t* size) {
    Lock l(hdr_);
    int32_t idx = FindLocked(id);
    if (idx < 0) return -1;
    Entry& e = entries_[idx];
    if (e.state != kSealed) return -1;
    LruPopLocked(idx);
    e.refcount++;
    *size = e.size;
    return (int64_t)e.offset;
  }

  int Release(const char* id) {
    Lock l(hdr_);
    int32_t idx = FindLocked(id);
    if (idx < 0) return -1;
    Entry& e = entries_[idx];
    if (e.refcount <= 0) return -1;
    if (--e.refcount == 0 && e.state == kSealed) LruPushLocked(idx);
    return 0;
  }

  int Abort(const char* id) {
    // Discard a CREATED (never sealed) entry, e.g. a node-to-node pull
    // that died mid-transfer. Unlike Seal+Delete this never publishes
    // the partial payload: the entry goes straight from kCreated to
    // kTombstone under the lock, so no concurrent Get can pin it.
    Lock l(hdr_);
    int32_t idx = FindLocked(id);
    if (idx < 0) return -1;
    if (entries_[idx].state != kCreated) return -2;
    entries_[idx].refcount = 0;  // drop the creator ref
    RemoveLocked(idx);
    return 0;
  }

  int Delete(const char* id) {
    Lock l(hdr_);
    int32_t idx = FindLocked(id);
    if (idx < 0) return -1;
    if (entries_[idx].refcount > 0) return -2;
    RemoveLocked(idx);
    return 0;
  }

  int Contains(const char* id) {
    Lock l(hdr_);
    int32_t idx = FindLocked(id);
    return idx >= 0 && entries_[idx].state == kSealed;
  }

  uint64_t UsedBytes() {
    Lock l(hdr_);
    return hdr_->used;
  }

  uint64_t NumObjects() {
    Lock l(hdr_);
    return hdr_->num_objects;
  }

  void SetEvictDisabled(int v) {
    Lock l(hdr_);
    hdr_->evict_disabled = v ? 1 : 0;
  }

  // NUL-separated ids of evictable (sealed, refcount-0) entries in LRU
  // order, head first, until the buffer is full. Returns the count
  // written. The spilling daemon reads this to pick victims; each id is
  // re-checked at delete time, so a stale snapshot is harmless.
  uint64_t LruVictims(char* buf, uint64_t bufsize) {
    Lock l(hdr_);
    uint64_t count = 0, pos = 0;
    for (int32_t idx = hdr_->lru_head; idx >= 0;
         idx = entries_[idx].lru_next) {
      size_t len = strnlen(entries_[idx].id, kMaxIdLen) + 1;
      if (pos + len > bufsize) break;
      memcpy(buf + pos, entries_[idx].id, len);
      pos += len;
      count++;
    }
    return count;
  }

 private:
  struct Lock {
    explicit Lock(Header* h) : h_(h) {
      int rc = pthread_mutex_lock(&h->mu);
      if (rc == EOWNERDEAD) pthread_mutex_consistent(&h->mu);
    }
    ~Lock() { pthread_mutex_unlock(&h_->mu); }
    Header* h_;
  };

  void Init() {
    memset(base_, 0, sizeof(Header));
    hdr_->capacity = capacity_;
    // Size the entry table to ~capacity/64KB objects, clamped.
    uint32_t max_objects = (uint32_t)(capacity_ / 65536);
    if (max_objects < 1024) max_objects = 1024;
    if (max_objects > 1 << 20) max_objects = 1 << 20;
    hdr_->max_objects = max_objects;
    uint64_t meta = sizeof(Header) + sizeof(Entry) * (uint64_t)max_objects +
                    sizeof(FreeBlock) * (uint64_t)max_objects * 2;
    meta = (meta + 4095) & ~uint64_t(4095);
    hdr_->data_off = meta;
    hdr_->data_size = capacity_ - meta;
    hdr_->free_head = -1;
    hdr_->lru_head = hdr_->lru_tail = -1;
    memset(base_ + sizeof(Header), 0,
           sizeof(Entry) * (uint64_t)max_objects +
               sizeof(FreeBlock) * (uint64_t)max_objects * 2);
    // One initial free block spanning the data region.
    auto* pool = reinterpret_cast<FreeBlock*>(
        base_ + sizeof(Header) + sizeof(Entry) * max_objects);
    pool[0] = {hdr_->data_off, hdr_->data_size, -1, 1};
    hdr_->free_head = 0;
    pthread_mutexattr_t attr;
    pthread_mutexattr_init(&attr);
    pthread_mutexattr_setpshared(&attr, PTHREAD_PROCESS_SHARED);
    pthread_mutexattr_setrobust(&attr, PTHREAD_MUTEX_ROBUST);
    pthread_mutex_init(&hdr_->mu, &attr);
    pthread_mutexattr_destroy(&attr);
    __sync_synchronize();
    hdr_->magic = kMagic;
  }

  static uint64_t Hash(const char* id) {
    uint64_t h = 1469598103934665603ULL;  // FNV-1a
    for (const char* p = id; *p; ++p) {
      h ^= (uint8_t)*p;
      h *= 1099511628211ULL;
    }
    return h;
  }

  int32_t FindLocked(const char* id) {
    uint32_t n = hdr_->max_objects;
    uint32_t i = (uint32_t)(Hash(id) % n);
    for (uint32_t probes = 0; probes < n; ++probes, i = (i + 1) % n) {
      Entry& e = entries_[i];
      if (e.state == kEmpty) return -1;
      if (e.state != kTombstone && strcmp(e.id, id) == 0) return (int32_t)i;
    }
    return -1;
  }

  int32_t InsertLocked(const char* id) {
    uint32_t n = hdr_->max_objects;
    if (hdr_->num_objects >= n - 1) return -1;
    uint32_t i = (uint32_t)(Hash(id) % n);
    for (uint32_t probes = 0; probes < n; ++probes, i = (i + 1) % n) {
      Entry& e = entries_[i];
      if (e.state == kEmpty || e.state == kTombstone) {
        strncpy(e.id, id, kMaxIdLen);
        e.id[kMaxIdLen] = '\0';
        e.lru_prev = e.lru_next = -1;
        return (int32_t)i;
      }
    }
    return -1;
  }

  void RemoveLocked(int32_t idx) {
    Entry& e = entries_[idx];
    LruPopLocked(idx);
    FreeRegionLocked(e.offset, e.alloc_size);
    hdr_->used -= e.alloc_size;
    hdr_->num_objects--;
    e.state = kTombstone;
    e.refcount = 0;
  }

  // -- shm-resident first-fit allocator -------------------------------

  int32_t AllocPoolSlotLocked() {
    uint32_t slots = hdr_->max_objects * 2;
    for (uint32_t i = 0; i < slots; ++i) {
      if (!pool_[i].used) {
        pool_[i].used = 1;
        return (int32_t)i;
      }
    }
    return -1;
  }

  int64_t AllocLocked(uint64_t size) {
    int32_t prev = -1;
    for (int32_t cur = hdr_->free_head; cur >= 0;
         prev = cur, cur = pool_[cur].next) {
      FreeBlock& b = pool_[cur];
      if (b.size < size) continue;
      uint64_t off = b.offset;
      if (b.size == size) {
        if (prev < 0) {
          hdr_->free_head = b.next;
        } else {
          pool_[prev].next = b.next;
        }
        b.used = 0;
      } else {
        b.offset += size;
        b.size -= size;
      }
      return (int64_t)off;
    }
    return -1;
  }

  void FreeRegionLocked(uint64_t off, uint64_t size) {
    // Insert sorted by offset, coalescing neighbors.
    int32_t prev = -1, cur = hdr_->free_head;
    while (cur >= 0 && pool_[cur].offset < off) {
      prev = cur;
      cur = pool_[cur].next;
    }
    // Coalesce with prev.
    if (prev >= 0 && pool_[prev].offset + pool_[prev].size == off) {
      pool_[prev].size += size;
      // Then maybe with cur.
      if (cur >= 0 &&
          pool_[prev].offset + pool_[prev].size == pool_[cur].offset) {
        pool_[prev].size += pool_[cur].size;
        pool_[prev].next = pool_[cur].next;
        pool_[cur].used = 0;
      }
      return;
    }
    // Coalesce with cur.
    if (cur >= 0 && off + size == pool_[cur].offset) {
      pool_[cur].offset = off;
      pool_[cur].size += size;
      return;
    }
    int32_t slot = AllocPoolSlotLocked();
    if (slot < 0) return;  // leak the region rather than corrupt (rare)
    pool_[slot].offset = off;
    pool_[slot].size = size;
    pool_[slot].next = cur;
    if (prev < 0) {
      hdr_->free_head = slot;
    } else {
      pool_[prev].next = slot;
    }
  }

  // -- LRU of evictable entries ---------------------------------------

  void LruPushLocked(int32_t idx) {
    Entry& e = entries_[idx];
    if (e.in_lru) return;
    e.in_lru = 1;
    e.lru_prev = hdr_->lru_tail;
    e.lru_next = -1;
    if (hdr_->lru_tail >= 0) entries_[hdr_->lru_tail].lru_next = idx;
    hdr_->lru_tail = idx;
    if (hdr_->lru_head < 0) hdr_->lru_head = idx;
  }

  void LruPopLocked(int32_t idx) {
    Entry& e = entries_[idx];
    if (!e.in_lru) return;
    e.in_lru = 0;
    if (e.lru_prev >= 0) {
      entries_[e.lru_prev].lru_next = e.lru_next;
    } else {
      hdr_->lru_head = e.lru_next;
    }
    if (e.lru_next >= 0) {
      entries_[e.lru_next].lru_prev = e.lru_prev;
    } else {
      hdr_->lru_tail = e.lru_prev;
    }
    e.lru_prev = e.lru_next = -1;
  }

  bool EvictOneLocked() {
    int32_t idx = hdr_->lru_head;
    if (idx < 0) return false;
    RemoveLocked(idx);
    return true;
  }

  std::string name_;
  uint64_t capacity_ = 0;
  int fd_ = -1;
  uint8_t* base_ = nullptr;
  Header* hdr_ = nullptr;
  Entry* entries_ = nullptr;
  FreeBlock* pool_ = nullptr;
};

}  // namespace

extern "C" {

void* shm_store_open(const char* name, uint64_t capacity, int create) {
  auto* s = new ShmStore(name, capacity, create != 0);
  if (!s->ok()) {
    delete s;
    return nullptr;
  }
  return s;
}

void shm_store_close(void* store) { delete static_cast<ShmStore*>(store); }

void shm_store_unlink(void* store) {
  static_cast<ShmStore*>(store)->unlink_shm();
}

int64_t shm_store_create(void* store, const char* id, uint64_t size) {
  return static_cast<ShmStore*>(store)->Create(id, size);
}

int shm_store_seal(void* store, const char* id) {
  return static_cast<ShmStore*>(store)->Seal(id);
}

int64_t shm_store_get(void* store, const char* id, uint64_t* size) {
  return static_cast<ShmStore*>(store)->Get(id, size);
}

int shm_store_release(void* store, const char* id) {
  return static_cast<ShmStore*>(store)->Release(id);
}

int shm_store_delete(void* store, const char* id) {
  return static_cast<ShmStore*>(store)->Delete(id);
}

int shm_store_abort(void* store, const char* id) {
  return static_cast<ShmStore*>(store)->Abort(id);
}

int shm_store_contains(void* store, const char* id) {
  return static_cast<ShmStore*>(store)->Contains(id);
}

uint64_t shm_store_used_bytes(void* store) {
  return static_cast<ShmStore*>(store)->UsedBytes();
}

uint64_t shm_store_num_objects(void* store) {
  return static_cast<ShmStore*>(store)->NumObjects();
}

void shm_store_set_evict_disabled(void* store, int v) {
  static_cast<ShmStore*>(store)->SetEvictDisabled(v);
}

uint64_t shm_store_lru_victims(void* store, char* buf, uint64_t bufsize) {
  return static_cast<ShmStore*>(store)->LruVictims(buf, bufsize);
}

void shm_store_write(void* store, int64_t offset, const uint8_t* src,
                     uint64_t size) {
  memcpy(static_cast<ShmStore*>(store)->base() + offset, src, size);
}

const uint8_t* shm_store_pointer(void* store, int64_t offset) {
  return static_cast<ShmStore*>(store)->base() + offset;
}

}  // extern "C"
