// Sanitizer stress driver for the native runtime components.
//
// Analog of the reference's TSAN/ASAN CI configs (.bazelrc:92-116): every
// native library's C ABI is hammered from many threads at once while the
// binary runs under -fsanitize=thread or -fsanitize=address (see
// native_build.py build_stress_binary / tests/test_native_sanitize.py).
// The driver exits 0 on a clean run; a sanitizer report fails the run
// via halt_on_error/abort (asserted by the gated pytest).
//
// Intentionally cruel schedules: node churn during placement-group
// rescheduling, subscriber drops during long-polls, object delete racing
// reads, force-free racing borrower returns — the interleavings the
// single-process Python tests can't reliably produce.

#include <unistd.h>

#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

// C ABIs of the components under test (linked from their .cc files).
extern "C" {
// sched.cc
void* rsched_create();
void rsched_destroy(void*);
int64_t rsched_add_node(void*, const char*);
int rsched_remove_node(void*, int64_t);
int64_t rsched_pick_and_acquire(void*, const char*, int);
int rsched_try_acquire_on(void*, int64_t, const char*);
void rsched_release_on(void*, int64_t, const char*);
double rsched_utilization(void*);
int64_t rsched_pg_create(void*, const char*, int);
int rsched_pg_remove(void*, int64_t);
int64_t rsched_pg_reschedule_lost(void*, int64_t*, int64_t);
// refcount.cc
void* rrc_create();
void rrc_destroy(void*);
void rrc_add_owned(void*, const char*);
void rrc_add_local(void*, const char*);
int64_t rrc_remove_local(void*, const char*, char*, int64_t);
void rrc_add_borrower(void*, const char*, const char*);
int64_t rrc_remove_borrower(void*, const char*, const char*, char*,
                            int64_t);
void rrc_add_contained(void*, const char*, const char*);
int64_t rrc_force_free(void*, const char*, char*, int64_t);
int64_t rrc_last_freed(void*, char*, int64_t);
int rrc_has(void*, const char*);
int64_t rrc_num_tracked(void*);
// pubsub.cc
void* rpb_create();
void rpb_destroy(void*);
void rpb_subscribe(void*, const char*, const char*, const char*);
void rpb_unsubscribe(void*, const char*, const char*, const char*);
void rpb_drop_subscriber(void*, const char*);
int64_t rpb_publish(void*, const char*, const char*, const char*);
int64_t rpb_poll(void*, const char*, int64_t, char*, int64_t);
// shm_store.cc
void* shm_store_open(const char*, uint64_t, int);
void shm_store_close(void*);
void shm_store_unlink(void*);
int64_t shm_store_create(void*, const char*, uint64_t);
int shm_store_seal(void*, const char*);
int64_t shm_store_get(void*, const char*, uint64_t*);
int shm_store_release(void*, const char*);
int shm_store_delete(void*, const char*);
int shm_store_abort(void*, const char*);
uint64_t shm_store_used_bytes(void*);
uint64_t shm_store_num_objects(void*);
void shm_store_write(void*, int64_t, const uint8_t*, uint64_t);
// config.cc
void* rcfg_create(const char*);
void rcfg_destroy(void*);
int64_t rcfg_get_int(void*, const char*);
int rcfg_set(void*, const char*, const char*);
int64_t rcfg_dump(void*, char*, int64_t);
// memmon.cc
int64_t rmm_snapshot(char*, int64_t);
double rmm_usage_fraction();
}

namespace {

constexpr int kThreads = 4;
// Per-thread op counts: tuned so the full suite finishes in a few
// seconds natively (sanitizers run 5-15x slower; the gated test allows
// minutes). The sched loop intentionally LEAKS half its nodes to grow
// the scan set, so its cost is quadratic — keep its budget small.
constexpr int kIters = 2000;
constexpr int kSchedIters = 250;

void stress_sched() {
  void* s = rsched_create();
  std::vector<std::thread> ts;
  for (int t = 0; t < kThreads; ++t) {
    ts.emplace_back([s, t] {
      for (int i = 0; i < kSchedIters; ++i) {
        int64_t n = rsched_add_node(s, "CPU=4;memory=1000");
        int64_t picked = rsched_pick_and_acquire(s, "CPU=1", i % 3);
        if (picked >= 0) rsched_release_on(s, picked, "CPU=1");
        if (rsched_try_acquire_on(s, n, "CPU=2") == 1) {
          rsched_release_on(s, n, "CPU=2");
        }
        int64_t pg = rsched_pg_create(s, "CPU=1|CPU=1", t % 2);
        if (pg >= 0 && i % 4 == 0) {
          int64_t moved[8];
          rsched_pg_reschedule_lost(s, moved, 8);
        }
        if (pg >= 0) rsched_pg_remove(s, pg);
        rsched_utilization(s);
        if (i % 2 == 0) rsched_remove_node(s, n);
      }
    });
  }
  for (auto& th : ts) th.join();
  rsched_destroy(s);
  std::puts("sched ok");
}

void stress_refcount() {
  void* c = rrc_create();
  std::vector<std::thread> ts;
  for (int t = 0; t < kThreads; ++t) {
    ts.emplace_back([c, t] {
      char buf[4096];
      for (int i = 0; i < kIters; ++i) {
        std::string oid = "o" + std::to_string(t) + "_" +
                          std::to_string(i % 32);
        std::string shared = "shared" + std::to_string(i % 8);
        rrc_add_owned(c, oid.c_str());
        rrc_add_local(c, oid.c_str());
        rrc_add_borrower(c, shared.c_str(), "daemonA");
        rrc_add_contained(c, oid.c_str(), shared.c_str());
        rrc_remove_borrower(c, shared.c_str(), "daemonA", buf,
                            sizeof(buf));
        rrc_has(c, oid.c_str());
        rrc_remove_local(c, oid.c_str(), buf, sizeof(buf));
        if (i % 16 == 0) rrc_force_free(c, shared.c_str(), buf,
                                        sizeof(buf));
        rrc_last_freed(c, buf, sizeof(buf));
        rrc_num_tracked(c);
      }
    });
  }
  for (auto& th : ts) th.join();
  rrc_destroy(c);
  std::puts("refcount ok");
}

void stress_pubsub() {
  void* h = rpb_create();
  std::atomic<bool> stop{false};
  std::vector<std::thread> ts;
  for (int t = 0; t < 2; ++t) {  // publishers
    ts.emplace_back([h, &stop] {
      for (int i = 0; !stop.load() && i < kIters * 4; ++i) {
        std::string key = "k" + std::to_string(i % 16);
        rpb_publish(h, "obj_locations", key.c_str(), "payload");
      }
      stop.store(true);
    });
  }
  for (int t = 0; t < kThreads; ++t) {  // subscriber churn + pollers
    ts.emplace_back([h, t, &stop] {
      std::string sub = "sub" + std::to_string(t);
      char buf[1024];
      int rounds = 0;
      while (!stop.load() && rounds++ < kIters / 4) {
        rpb_subscribe(h, sub.c_str(), "obj_locations",
                      rounds % 2 ? "k1" : "");
        rpb_poll(h, sub.c_str(), 1, buf, sizeof(buf));
        if (rounds % 8 == 0) {
          rpb_drop_subscriber(h, sub.c_str());
        } else {
          rpb_unsubscribe(h, sub.c_str(), "obj_locations",
                          rounds % 2 ? "k1" : "");
        }
      }
      rpb_drop_subscriber(h, sub.c_str());
    });
  }
  for (auto& th : ts) th.join();
  rpb_destroy(h);
  std::puts("pubsub ok");
}

void stress_shm_store() {
  std::string name = "/rtpu_stress_" + std::to_string(getpid());
  void* s = shm_store_open(name.c_str(), 8 << 20, 1);
  if (s == nullptr) {  // environments without /dev/shm: skip, not fail
    std::puts("shm skipped");
    return;
  }
  std::vector<std::thread> ts;
  for (int t = 0; t < kThreads; ++t) {
    ts.emplace_back([s, t] {
      uint8_t payload[512];
      std::memset(payload, t, sizeof(payload));
      for (int i = 0; i < kIters; ++i) {
        // Keys deliberately COLLIDE across threads: create/seal/get/
        // delete race on the same entries.
        std::string id = "obj" + std::to_string(i % 16);
        int64_t off = shm_store_create(s, id.c_str(), sizeof(payload));
        if (off >= 0) {
          shm_store_write(s, off, payload, sizeof(payload));
          if (i % 32 == 0) {
            shm_store_abort(s, id.c_str());
          } else {
            shm_store_seal(s, id.c_str());
          }
        }
        uint64_t size = 0;
        if (shm_store_get(s, id.c_str(), &size) >= 0) {
          shm_store_release(s, id.c_str());
        }
        shm_store_used_bytes(s);
        shm_store_num_objects(s);
        if (i % 4 == 0) shm_store_delete(s, id.c_str());
      }
    });
  }
  for (auto& th : ts) th.join();
  shm_store_unlink(s);
  shm_store_close(s);
  std::puts("shm ok");
}

void stress_config() {
  void* c = rcfg_create("");
  std::vector<std::thread> ts;
  for (int t = 0; t < kThreads; ++t) {
    ts.emplace_back([c, t] {
      char buf[8192];
      for (int i = 0; i < kIters; ++i) {
        rcfg_set(c, "health_check_period_ms",
                 std::to_string(100 + i % 100).c_str());
        rcfg_get_int(c, "health_check_period_ms");
        if (i % 64 == 0) rcfg_dump(c, buf, sizeof(buf));
      }
    });
  }
  for (auto& th : ts) th.join();
  rcfg_destroy(c);
  std::puts("config ok");
}

void stress_memmon() {
  std::vector<std::thread> ts;
  for (int t = 0; t < kThreads; ++t) {
    ts.emplace_back([] {
      char snap[512];
      for (int i = 0; i < kIters / 4; ++i) {
        rmm_snapshot(snap, sizeof(snap));
        rmm_usage_fraction();
      }
    });
  }
  for (auto& th : ts) th.join();
  std::puts("memmon ok");
}

}  // namespace

int main() {
  stress_sched();
  stress_refcount();
  stress_pubsub();
  stress_shm_store();
  stress_config();
  stress_memmon();
  std::puts("ALL STRESS OK");
  return 0;
}
