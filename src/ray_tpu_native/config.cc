// Native runtime configuration flag table.
//
// C++ equivalent of the reference's RayConfig system
// (src/ray/common/ray_config_def.h: the RAY_CONFIG(type, name, default)
// macro table materialized as a singleton, overridable per-process via
// RAY_<name> environment variables or a _system_config blob handed to every
// process). Flags are typed (int64/double/bool/string); lookup is a hash
// map probe. The Python side holds one handle per runtime and reads flags
// through the flat C ABI (ray_tpu/_private/ray_config.py).

#include <cctype>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <mutex>
#include <string>
#include <unordered_map>

namespace {

enum class Type { kInt, kDouble, kBool, kStr };

struct Flag {
  Type type;
  int64_t i = 0;
  double d = 0.0;
  bool b = false;
  std::string s;
};

struct Config {
  std::mutex mu;
  std::unordered_map<std::string, Flag> flags;
};

void set_from_string(Flag* f, const std::string& val) {
  switch (f->type) {
    case Type::kInt:
      f->i = std::strtoll(val.c_str(), nullptr, 10);
      break;
    case Type::kDouble:
      f->d = std::strtod(val.c_str(), nullptr);
      break;
    case Type::kBool: {
      std::string low;
      for (char c : val) low += static_cast<char>(std::tolower(c));
      f->b = (low == "1" || low == "true" || low == "yes" || low == "on");
      break;
    }
    case Type::kStr:
      f->s = val;
      break;
  }
}

// The flag table. Mirrors the shape of ray_config_def.h: one row per flag
// with a typed default. TPU-specific additions at the bottom.
#define FLAG_INT(name, def) {#name, {Type::kInt, (def), 0.0, false, ""}}
#define FLAG_DBL(name, def) {#name, {Type::kDouble, 0, (def), false, ""}}
#define FLAG_BOOL(name, def) {#name, {Type::kBool, 0, 0.0, (def), ""}}
#define FLAG_STR(name, def) {#name, {Type::kStr, 0, 0.0, false, (def)}}

const std::unordered_map<std::string, Flag> kDefaults = {
    // -- scheduling (raylet/scheduling defaults) --
    FLAG_DBL(scheduler_spread_threshold, 0.5),
    FLAG_INT(max_pending_lease_requests_per_scheduling_category, 10),
    // Worker leasing (reference: direct_task_transport.cc OnWorkerIdle):
    // same-class tasks pipeline onto a leased daemon worker without
    // per-task scheduler involvement, up to this many in flight.
    FLAG_BOOL(worker_lease_enabled, true),
    FLAG_INT(max_tasks_in_flight_per_worker, 10),
    // Pull admission control (reference: pull_manager.h:52): bound on
    // bytes simultaneously in flight into one node's object table.
    FLAG_INT(pull_manager_max_inflight_bytes, 268435456),
    // Chunked parallel pulls: objects above pull_chunk_bytes are
    // fetched as concurrent ranged reads over up to pull_parallelism
    // pooled sockets per peer (0 chunk bytes disables chunking).
    FLAG_INT(pull_chunk_bytes, 4194304),
    FLAG_INT(pull_parallelism, 4),
    FLAG_INT(worker_prestart_count, 1),
    FLAG_INT(worker_cap_multiplier, 8),
    FLAG_INT(worker_cap_min, 64),
    // -- task/actor lifecycle --
    FLAG_INT(task_retry_delay_ms, 0),
    FLAG_INT(actor_restart_backoff_ms, 0),
    FLAG_INT(max_task_events, 100000),
    FLAG_INT(lineage_max_entries, 1000000),
    FLAG_INT(object_locations_max_entries, 1000000),
    // -- object store --
    FLAG_DBL(object_store_memory_fraction, 0.3),
    FLAG_INT(object_store_full_delay_ms, 100),
    FLAG_INT(object_spilling_threshold_bytes, 0),  // 0 = disabled
    FLAG_STR(object_spilling_directory, ""),
    // Spill-backend URI ("" = per-process file:// dir; "session://" =
    // host-shared session dir that survives daemon death; "mock-s3://b").
    FLAG_STR(object_spill_uri, ""),
    // Results bigger than this stay in the producing node daemon's store
    // and are fetched lazily (0 = always return inline).
    FLAG_INT(remote_object_inline_limit_bytes, 1048576),
    // -- GC / refcounting --
    FLAG_INT(gc_sweep_interval_ms, 500),
    // -- failure detection --
    // Reference tolerances (ray_config_def.h:739-745): a saturated host
    // can starve the daemon's pong thread for seconds (GIL + 1-CPU
    // boxes); 1s-period probing declared BUSY nodes dead mid-workload.
    FLAG_INT(health_check_period_ms, 3000),
    FLAG_INT(health_check_timeout_ms, 10000),
    FLAG_INT(health_check_failure_threshold, 5),
    FLAG_INT(node_death_grace_ms, 0),
    // Fenced membership (wire v9, _private/membership.py): per-period
    // health probes with a bounded timeout feed an accrual (phi)
    // suspicion score; death at the phi threshold, or unconditionally
    // at the hard lease.
    FLAG_DBL(health_probe_timeout_s, 1.0),
    FLAG_DBL(health_probe_period_s, 0.25),
    FLAG_DBL(node_lease_s, 10.0),
    FLAG_DBL(node_suspicion_threshold, 8.0),
    // Resilient session channels (wire v7): reconnect-and-resume
    // window before a broken channel escalates to node death, and the
    // byte budget of the unacked-frame resend ring.
    FLAG_DBL(channel_reconnect_window_s, 30.0),
    FLAG_INT(channel_resend_ring_bytes, 67108864),
    // Head failover: how long a daemon keeps re-dialing a dead head
    // (jittered backoff) before giving up -- the window a restarted
    // or standby head has to replay the gcs_store and accept
    // re-registrations.
    FLAG_DBL(head_failover_window_s, 120.0),
    // Deferred acks: pending after channel_ack_every unacked inbound
    // frames, flushed as a pure ack after channel_ack_flush_ms unless
    // an outbound frame piggybacked it first.
    FLAG_INT(channel_ack_every, 32),
    FLAG_INT(channel_ack_flush_ms, 20),
    // -- serve resilience --
    // Bounded replica startup (retried against the start budget),
    // graceful-drain window, parallel health-check cadence/threshold,
    // and the router's per-request failover retry budget.
    FLAG_DBL(serve_startup_timeout_s, 30.0),
    FLAG_INT(serve_start_budget, 3),
    FLAG_DBL(serve_drain_timeout_s, 30.0),
    FLAG_DBL(serve_health_check_period_s, 1.0),
    FLAG_DBL(serve_health_check_timeout_s, 5.0),
    FLAG_INT(serve_health_failure_threshold, 3),
    FLAG_INT(serve_failover_retries, 3),
    // -- serve autoscaling + batching --
    // Controller autoscale-pass cadence (<=0 disables) and the stats
    // window it sizes from; cluster-default up/down hysteresis delays;
    // scale-hint TTL (dead alert engine can't pin a hint); cluster
    // latency budget for adaptive batch queues (0 = fixed batching).
    FLAG_DBL(serve_autoscale_interval_s, 2.0),
    FLAG_DBL(serve_autoscale_window_s, 15.0),
    FLAG_DBL(serve_autoscale_upscale_delay_s, 0.0),
    FLAG_DBL(serve_autoscale_downscale_delay_s, 10.0),
    FLAG_DBL(serve_scale_hint_ttl_s, 120.0),
    FLAG_DBL(serve_batch_target_latency_ms, 0.0),
    // -- train fault tolerance --
    // Hang detector: a result round idle this long liveness-probes the
    // pending ranks (failed probe => system failure, gang restart);
    // restart waits this long for full resources before shrinking to
    // ScalingConfig.min_workers.
    FLAG_DBL(train_hang_timeout_s, 60.0),
    FLAG_DBL(train_restart_wait_s, 30.0),
    // Sharded checkpoints: per-parameter restore fan-out, crc32
    // verification on full-block reads/GC, and whether a resized gang
    // may resume by resharding (off = refuse).
    FLAG_INT(train_ckpt_shard_parallelism, 8),
    FLAG_BOOL(train_ckpt_verify_checksums, true),
    FLAG_BOOL(train_reshard_on_restart, true),
    // -- metrics / events --
    FLAG_INT(metrics_report_interval_ms, 10000),
    // Distributed tracing: head-of-trace sampling probability and the
    // number of assembled traces the head retains (oldest evicted).
    FLAG_DBL(trace_sample_rate, 1.0),
    FLAG_INT(trace_retention, 1000),
    // Head-side windowed time-series store: retention window seconds
    // (<= 0 disables) and the cap on distinct series held.
    FLAG_DBL(timeseries_window_s, 300.0),
    FLAG_INT(timeseries_max_series, 4096),
    // Continuous profiling: per-process sample hz (0 disables),
    // head-side retention window (<= 0 disables the store), origin /
    // per-bucket stack caps, the loop-lag flight-recorder threshold
    // (<= 0 disables) + incident-ring bound, and the on-demand burst
    // duration cap.
    FLAG_DBL(profile_hz, 10.0),
    FLAG_DBL(profile_window_s, 300.0),
    FLAG_INT(profile_max_series, 256),
    FLAG_INT(profile_max_stacks, 2000),
    FLAG_DBL(profile_flight_lag_s, 1.0),
    FLAG_INT(profile_max_incidents, 32),
    FLAG_DBL(profile_max_duration_s, 60.0),
    // Alerting plane + cluster event journal: evaluation cadence on the
    // head merge path (<= 0 disables), retained transition bound, the
    // journal ring size (<= 0 disables), and an optional spill-backend
    // URI for durable journal persistence.
    FLAG_DBL(alert_eval_period_s, 5.0),
    FLAG_INT(alert_max_firing_history, 256),
    FLAG_INT(events_max, 2048),
    FLAG_STR(events_spill_uri, ""),
    // Dataplane flow observability: per-process transfer ledger bound
    // (0 disables recording), head-side matrix window + cardinality
    // caps, slow_link / hot_object_fanout alert thresholds.
    FLAG_INT(flow_max_records, 4096),
    FLAG_DBL(flow_window_s, 60.0),
    FLAG_INT(flow_max_links, 512),
    FLAG_INT(flow_max_objects, 512),
    FLAG_DBL(flow_slow_link_mbps, 1.0),
    FLAG_INT(flow_fanout_nodes, 8),
    // Collective dataplane: broadcast tree fan-out, striped-pull source
    // cap, locality placement spillback utilization threshold.
    FLAG_INT(broadcast_fanout, 2),
    FLAG_INT(pull_stripe_max_sources, 4),
    FLAG_DBL(locality_spillback_threshold, 0.85),
    FLAG_BOOL(task_events_enabled, true),
    // -- memory monitor / OOM killing --
    FLAG_INT(memory_monitor_refresh_ms, 250),
    FLAG_DBL(memory_usage_threshold, 0.95),
    // -- chaos / fault injection (reference: asio_chaos.cc,
    //    RAY_testing_asio_delay_us) --
    FLAG_INT(testing_submit_delay_us, 0),
    FLAG_INT(testing_dispatch_delay_us, 0),
    FLAG_INT(testing_store_delay_us, 0),
    FLAG_INT(testing_rpc_failure_pct, 0),
    // -- TPU-native additions --
    FLAG_BOOL(tpu_autodetect, true),
    FLAG_INT(tpu_chips_per_host_default, 4),
    FLAG_STR(ici_topology, ""),
    FLAG_STR(gcs_store_path, ""),
    FLAG_BOOL(use_native_scheduler, true),
    FLAG_BOOL(use_native_object_store, true),
    FLAG_BOOL(use_native_refcount, true),
};

#undef FLAG_INT
#undef FLAG_DBL
#undef FLAG_BOOL
#undef FLAG_STR

}  // namespace

extern "C" {

// overrides: "name=value;name=value" (the _system_config analog). Env vars
// RAY_TPU_<name> take precedence over defaults, overrides over both.
void* rcfg_create(const char* overrides) {
  auto* c = new Config();
  c->flags = kDefaults;
  for (auto& kv : c->flags) {
    std::string env_name = "RAY_TPU_" + kv.first;
    const char* env = std::getenv(env_name.c_str());
    if (env != nullptr) set_from_string(&kv.second, env);
  }
  if (overrides != nullptr && *overrides) {
    const char* p = overrides;
    while (*p) {
      const char* eq = std::strchr(p, '=');
      if (eq == nullptr) break;
      const char* end = std::strchr(eq, ';');
      if (end == nullptr) end = eq + std::strlen(eq);
      std::string name(p, eq - p);
      std::string val(eq + 1, end - (eq + 1));
      auto it = c->flags.find(name);
      if (it != c->flags.end()) set_from_string(&it->second, val);
      p = (*end == ';') ? end + 1 : end;
    }
  }
  return c;
}

void rcfg_destroy(void* h) { delete static_cast<Config*>(h); }

// Returns 1 if the flag exists (writing its type into *type: 0 int, 1
// double, 2 bool, 3 str), 0 otherwise.
int rcfg_has(void* h, const char* name, int* type) {
  auto* c = static_cast<Config*>(h);
  std::lock_guard<std::mutex> g(c->mu);
  auto it = c->flags.find(name);
  if (it == c->flags.end()) return 0;
  if (type != nullptr) *type = static_cast<int>(it->second.type);
  return 1;
}

int64_t rcfg_get_int(void* h, const char* name, int64_t fallback) {
  auto* c = static_cast<Config*>(h);
  std::lock_guard<std::mutex> g(c->mu);
  auto it = c->flags.find(name);
  return (it != c->flags.end() && it->second.type == Type::kInt)
             ? it->second.i : fallback;
}

double rcfg_get_double(void* h, const char* name, double fallback) {
  auto* c = static_cast<Config*>(h);
  std::lock_guard<std::mutex> g(c->mu);
  auto it = c->flags.find(name);
  return (it != c->flags.end() && it->second.type == Type::kDouble)
             ? it->second.d : fallback;
}

int rcfg_get_bool(void* h, const char* name, int fallback) {
  auto* c = static_cast<Config*>(h);
  std::lock_guard<std::mutex> g(c->mu);
  auto it = c->flags.find(name);
  return (it != c->flags.end() && it->second.type == Type::kBool)
             ? (it->second.b ? 1 : 0) : fallback;
}

int64_t rcfg_get_str(void* h, const char* name, char* buf, int64_t cap) {
  auto* c = static_cast<Config*>(h);
  std::lock_guard<std::mutex> g(c->mu);
  auto it = c->flags.find(name);
  if (it == c->flags.end() || it->second.type != Type::kStr) return -1;
  int64_t needed = static_cast<int64_t>(it->second.s.size());
  if (buf != nullptr && needed < cap) {
    std::memcpy(buf, it->second.s.data(), it->second.s.size());
    buf[it->second.s.size()] = '\0';
  }
  return needed;
}

// Runtime mutation (tests / chaos toggles).
int rcfg_set(void* h, const char* name, const char* value) {
  auto* c = static_cast<Config*>(h);
  std::lock_guard<std::mutex> g(c->mu);
  auto it = c->flags.find(name);
  if (it == c->flags.end()) return 0;
  set_from_string(&it->second, value);
  return 1;
}

// Dump all flags as "name=value;..." for the state API / debugging.
int64_t rcfg_dump(void* h, char* buf, int64_t cap) {
  auto* c = static_cast<Config*>(h);
  std::lock_guard<std::mutex> g(c->mu);
  std::string out;
  for (const auto& kv : c->flags) {
    if (!out.empty()) out += ';';
    out += kv.first + "=";
    switch (kv.second.type) {
      case Type::kInt: out += std::to_string(kv.second.i); break;
      case Type::kDouble: out += std::to_string(kv.second.d); break;
      case Type::kBool: out += kv.second.b ? "true" : "false"; break;
      case Type::kStr: out += kv.second.s; break;
    }
  }
  int64_t needed = static_cast<int64_t>(out.size());
  if (buf != nullptr && needed < cap) {
    std::memcpy(buf, out.data(), out.size());
    buf[out.size()] = '\0';
  }
  return needed;
}

}  // extern "C"
