// Native memory monitor.
//
// C++ equivalent of the reference's MemoryMonitor
// (src/ray/common/memory_monitor.h:31 MemorySnapshot): reads system memory
// from /proc/meminfo and the process cgroup's limit/usage (v2 memory.max /
// memory.current, v1 fallback), reporting the tighter of the two as the
// effective bound — exactly the signal the raylet uses to drive its
// worker-killing policy.

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>

namespace {

// Parse "key:   12345 kB" style /proc/meminfo rows.
int64_t meminfo_kb(const char* key) {
  FILE* f = std::fopen("/proc/meminfo", "r");
  if (f == nullptr) return -1;
  char line[256];
  int64_t out = -1;
  size_t key_len = std::strlen(key);
  while (std::fgets(line, sizeof(line), f) != nullptr) {
    if (std::strncmp(line, key, key_len) == 0 && line[key_len] == ':') {
      out = std::strtoll(line + key_len + 1, nullptr, 10);
      break;
    }
  }
  std::fclose(f);
  return out;
}

int64_t read_int_file(const char* path) {
  FILE* f = std::fopen(path, "r");
  if (f == nullptr) return -1;
  char buf[64];
  int64_t out = -1;
  if (std::fgets(buf, sizeof(buf), f) != nullptr) {
    if (std::strncmp(buf, "max", 3) == 0) {
      out = -2;  // "no limit"
    } else {
      out = std::strtoll(buf, nullptr, 10);
    }
  }
  std::fclose(f);
  return out;
}

}  // namespace

extern "C" {

// Writes "system_total=B;system_available=B;cgroup_limit=B;cgroup_used=B"
// (bytes; -1 unknown, cgroup_limit -2 = unlimited). Returns needed length.
int64_t rmm_snapshot(char* buf, int64_t cap) {
  int64_t total_kb = meminfo_kb("MemTotal");
  int64_t avail_kb = meminfo_kb("MemAvailable");
  int64_t limit = read_int_file("/sys/fs/cgroup/memory.max");
  int64_t used = read_int_file("/sys/fs/cgroup/memory.current");
  if (limit == -1) {  // cgroup v1 fallback
    limit = read_int_file("/sys/fs/cgroup/memory/memory.limit_in_bytes");
    used = read_int_file("/sys/fs/cgroup/memory/memory.usage_in_bytes");
    // v1 reports "no limit" as a huge number (PAGE_COUNTER_MAX).
    if (limit > (int64_t{1} << 60)) limit = -2;
  }
  std::string out =
      "system_total=" +
      std::to_string(total_kb < 0 ? -1 : total_kb * 1024) +
      ";system_available=" +
      std::to_string(avail_kb < 0 ? -1 : avail_kb * 1024) +
      ";cgroup_limit=" + std::to_string(limit) +
      ";cgroup_used=" + std::to_string(used);
  int64_t needed = static_cast<int64_t>(out.size());
  if (buf != nullptr && needed < cap) {
    std::memcpy(buf, out.data(), out.size());
    buf[out.size()] = '\0';
  }
  return needed;
}

// Effective usage fraction in [0,1] (or -1 unknown): cgroup bound if
// limited, else system.
double rmm_usage_fraction() {
  char buf[256];
  rmm_snapshot(buf, sizeof(buf));
  int64_t total = -1, avail = -1, limit = -1, used = -1;
  std::sscanf(buf,
              "system_total=%ld;system_available=%ld;cgroup_limit=%ld;"
              "cgroup_used=%ld",
              &total, &avail, &limit, &used);
  if (limit > 0 && used >= 0) {
    return static_cast<double>(used) / static_cast<double>(limit);
  }
  if (total > 0 && avail >= 0) {
    return 1.0 - static_cast<double>(avail) / static_cast<double>(total);
  }
  return -1.0;
}

}  // extern "C"
