// Native publisher/subscriber with long-poll semantics.
//
// C++ equivalent of the reference's object/GCS pubsub
// (src/ray/pubsub/publisher.h:298, subscriber.h:329): channels keyed by
// (channel, key); subscribers register interest and long-poll — the poll
// blocks on a condition variable until a message lands or the timeout
// passes, exactly the PubsubLongPolling rpc shape (core_worker.proto:408)
// collapsed to an in-process API. Python callers poll from worker threads;
// ctypes releases the GIL around the blocking call.

#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <mutex>
#include <string>
#include <unordered_map>
#include <unordered_set>

namespace {

struct Subscriber {
  std::deque<std::string> inbox;  // "channel|key|payload"
  std::unordered_set<std::string> interests;  // "channel|key" ("" key = all)
  std::condition_variable cv;
  int pollers = 0;      // threads parked in rpb_poll
  bool dropped = false; // drop requested while pollers were parked
};

struct Hub {
  std::mutex mu;
  std::unordered_map<std::string, Subscriber> subs;
  int64_t max_inbox = 10000;
};

std::string topic(const char* channel, const char* key) {
  return std::string(channel) + "|" + key;
}

}  // namespace

extern "C" {

void* rpb_create() { return new Hub(); }
void rpb_destroy(void* h) { delete static_cast<Hub*>(h); }

// Register interest: key "" subscribes to every key on the channel.
void rpb_subscribe(void* h, const char* sub_id, const char* channel,
                   const char* key) {
  auto* hub = static_cast<Hub*>(h);
  std::lock_guard<std::mutex> g(hub->mu);
  hub->subs[sub_id].interests.insert(topic(channel, key));
}

void rpb_unsubscribe(void* h, const char* sub_id, const char* channel,
                     const char* key) {
  auto* hub = static_cast<Hub*>(h);
  std::lock_guard<std::mutex> g(hub->mu);
  auto it = hub->subs.find(sub_id);
  if (it != hub->subs.end()) it->second.interests.erase(topic(channel, key));
}

void rpb_drop_subscriber(void* h, const char* sub_id) {
  auto* hub = static_cast<Hub*>(h);
  std::lock_guard<std::mutex> g(hub->mu);
  auto it = hub->subs.find(sub_id);
  if (it == hub->subs.end()) return;
  if (it->second.pollers > 0) {
    // A poller is parked on this subscriber's condition variable:
    // destroying it now would be use-after-free. Mark dropped, wake the
    // pollers; the last one out erases the entry.
    it->second.dropped = true;
    it->second.cv.notify_all();
  } else {
    hub->subs.erase(it);
  }
}

// Fan a message out to every subscriber interested in (channel, key) or
// (channel, ""). Returns the number of deliveries.
int64_t rpb_publish(void* h, const char* channel, const char* key,
                    const char* payload) {
  auto* hub = static_cast<Hub*>(h);
  std::lock_guard<std::mutex> g(hub->mu);
  const std::string exact = topic(channel, key);
  const std::string wild = topic(channel, "");
  std::string msg = std::string(channel) + "|" + key + "|" + payload;
  int64_t delivered = 0;
  for (auto& kv : hub->subs) {
    Subscriber& sub = kv.second;
    if (sub.interests.count(exact) || sub.interests.count(wild)) {
      if (static_cast<int64_t>(sub.inbox.size()) >= hub->max_inbox) {
        sub.inbox.pop_front();  // drop oldest under backpressure
      }
      sub.inbox.push_back(msg);
      sub.cv.notify_all();
      delivered++;
    }
  }
  return delivered;
}

// Long-poll: block until a message is available or timeout_ms elapses.
// Writes "channel|key|payload"; returns needed length, 0 = timeout,
// -1 = unknown subscriber. A too-small buffer leaves the message queued
// (caller retries with a bigger buffer).
int64_t rpb_poll(void* h, const char* sub_id, int64_t timeout_ms,
                 char* buf, int64_t cap) {
  auto* hub = static_cast<Hub*>(h);
  std::unique_lock<std::mutex> lock(hub->mu);
  auto it = hub->subs.find(sub_id);
  if (it == hub->subs.end() || it->second.dropped) return -1;
  Subscriber& sub = it->second;
  sub.pollers++;
  if (sub.inbox.empty() && !sub.dropped) {
    sub.cv.wait_for(lock, std::chrono::milliseconds(timeout_ms),
                    [&] { return !sub.inbox.empty() || sub.dropped; });
  }
  sub.pollers--;
  if (sub.dropped) {
    if (sub.pollers == 0) hub->subs.erase(sub_id);
    return -1;
  }
  if (sub.inbox.empty()) return 0;
  const std::string& msg = sub.inbox.front();
  int64_t needed = static_cast<int64_t>(msg.size());
  if (buf != nullptr && needed < cap) {
    std::memcpy(buf, msg.data(), msg.size());
    buf[msg.size()] = '\0';
    sub.inbox.pop_front();
  }
  return needed;
}

int64_t rpb_inbox_size(void* h, const char* sub_id) {
  auto* hub = static_cast<Hub*>(h);
  std::lock_guard<std::mutex> g(hub->mu);
  auto it = hub->subs.find(sub_id);
  return (it == hub->subs.end() || it->second.dropped)
             ? -1
             : static_cast<int64_t>(it->second.inbox.size());
}

}  // extern "C"
