// Native reference counter: the ownership/borrowing distributed-GC core.
//
// C++ equivalent of the reference's ReferenceCounter
// (src/ray/core_worker/reference_count.h:61): per-object ownership with
// local references (language handles), submitted-task (dependency)
// references, borrowers, and contained-object pins, with cascade collection
// when a parent's value is released. The Python runtime calls in through a
// flat C ABI (ids as hex strings, lists ';'-joined); when an object's
// combined count reaches zero the removal call returns the freeable ids and
// the owner frees them from the store and prunes lineage.
//
// Single mutex: operations are O(refs touched); the hot path
// (add/remove_local) is a hash lookup + counter update.

#include <cstdint>
#include <cstring>
#include <mutex>
#include <string>
#include <unordered_map>
#include <unordered_set>
#include <vector>

namespace {

struct Ref {
  int64_t local = 0;        // language handles in this process
  int64_t task_deps = 0;    // pending submitted tasks depending on it
  int64_t contained_in = 0; // live parent values containing this object
  std::unordered_set<std::string> borrowers;
  std::vector<std::string> contained; // children pinned by our value
  bool owned = false;       // created via put/task-return in this process
  bool value_live = false;  // the store still holds the value

  bool freeable() const {
    return owned && value_live && local == 0 && task_deps == 0 &&
           contained_in == 0 && borrowers.empty();
  }
};

struct Counter {
  std::mutex mu;
  std::unordered_map<std::string, Ref> refs;
  // Result of the most recent freeing mutation. Mutating calls must commit
  // exactly once, so when the caller's buffer is too small it re-reads this
  // stash via rrc_last_freed instead of retrying the mutation.
  std::string last_freed;

  // Collect `oid` if freeable, cascading through contained children.
  void collect(const std::string& oid, std::vector<std::string>* out) {
    auto it = refs.find(oid);
    if (it == refs.end() || !it->second.freeable()) return;
    std::vector<std::string> children = std::move(it->second.contained);
    it->second.value_live = false;
    out->push_back(oid);
    // Entry stays (callers may still hold dangling handles and call
    // remove_local later); it is erased once fully unreferenced.
    maybe_erase(oid);
    for (const auto& child : children) {
      auto cit = refs.find(child);
      if (cit == refs.end()) continue;
      if (cit->second.contained_in > 0) cit->second.contained_in--;
      collect(child, out);
      maybe_erase(child);
    }
  }

  void maybe_erase(const std::string& oid) {
    auto it = refs.find(oid);
    if (it == refs.end()) return;
    const Ref& r = it->second;
    if (!r.value_live && r.local == 0 && r.task_deps == 0 &&
        r.contained_in == 0 && r.borrowers.empty()) {
      refs.erase(it);
    }
  }
};

std::vector<std::string> split(const char* s) {
  std::vector<std::string> out;
  if (s == nullptr || *s == '\0') return out;
  const char* start = s;
  for (const char* p = s;; ++p) {
    if (*p == ';' || *p == '\0') {
      if (p > start) out.emplace_back(start, p - start);
      if (*p == '\0') break;
      start = p + 1;
    }
  }
  return out;
}

int64_t write_str(const std::string& joined, char* buf, int64_t cap) {
  int64_t needed = static_cast<int64_t>(joined.size());
  if (buf != nullptr && needed < cap) {
    std::memcpy(buf, joined.data(), joined.size());
    buf[joined.size()] = '\0';
  }
  return needed;
}

int64_t write_list(const std::vector<std::string>& items, char* buf,
                   int64_t cap) {
  std::string joined;
  for (size_t i = 0; i < items.size(); ++i) {
    if (i) joined += ';';
    joined += items[i];
  }
  return write_str(joined, buf, cap);
}

// Stash + write the result of a freeing mutation.
int64_t commit_freed(Counter* c, const std::vector<std::string>& freed,
                     char* buf, int64_t cap) {
  std::string joined;
  for (size_t i = 0; i < freed.size(); ++i) {
    if (i) joined += ';';
    joined += freed[i];
  }
  c->last_freed = joined;
  return write_str(joined, buf, cap);
}

}  // namespace

extern "C" {

void* rrc_create() { return new Counter(); }
void rrc_destroy(void* h) { delete static_cast<Counter*>(h); }

// Object created in this process (put / task return); value is in the store.
void rrc_add_owned(void* h, const char* oid) {
  auto* c = static_cast<Counter*>(h);
  std::lock_guard<std::mutex> g(c->mu);
  Ref& r = c->refs[oid];
  r.owned = true;
  r.value_live = true;
}

void rrc_add_local(void* h, const char* oid) {
  auto* c = static_cast<Counter*>(h);
  std::lock_guard<std::mutex> g(c->mu);
  c->refs[oid].local++;
}

int64_t rrc_remove_local(void* h, const char* oid, char* buf, int64_t cap) {
  auto* c = static_cast<Counter*>(h);
  std::lock_guard<std::mutex> g(c->mu);
  std::vector<std::string> freed;
  auto it = c->refs.find(oid);
  if (it != c->refs.end()) {
    if (it->second.local > 0) it->second.local--;
    c->collect(oid, &freed);
    c->maybe_erase(oid);
  }
  return commit_freed(c, freed, buf, cap);
}

void rrc_add_task_deps(void* h, const char* oids) {
  auto* c = static_cast<Counter*>(h);
  std::lock_guard<std::mutex> g(c->mu);
  for (const auto& oid : split(oids)) c->refs[oid].task_deps++;
}

int64_t rrc_remove_task_deps(void* h, const char* oids, char* buf,
                             int64_t cap) {
  auto* c = static_cast<Counter*>(h);
  std::lock_guard<std::mutex> g(c->mu);
  std::vector<std::string> freed;
  for (const auto& oid : split(oids)) {
    auto it = c->refs.find(oid);
    if (it == c->refs.end()) continue;
    if (it->second.task_deps > 0) it->second.task_deps--;
    c->collect(oid, &freed);
    c->maybe_erase(oid);
  }
  return commit_freed(c, freed, buf, cap);
}

void rrc_add_borrower(void* h, const char* oid, const char* borrower) {
  auto* c = static_cast<Counter*>(h);
  std::lock_guard<std::mutex> g(c->mu);
  c->refs[oid].borrowers.insert(borrower);
}

int64_t rrc_remove_borrower(void* h, const char* oid, const char* borrower,
                            char* buf, int64_t cap) {
  auto* c = static_cast<Counter*>(h);
  std::lock_guard<std::mutex> g(c->mu);
  std::vector<std::string> freed;
  auto it = c->refs.find(oid);
  if (it != c->refs.end()) {
    it->second.borrowers.erase(borrower);
    c->collect(oid, &freed);
    c->maybe_erase(oid);
  }
  return commit_freed(c, freed, buf, cap);
}

// Parent's stored value contains `children`: pin them while parent's value
// lives. (Cross-process transfer analog of WrapObjectIds.)
void rrc_add_contained(void* h, const char* parent, const char* children) {
  auto* c = static_cast<Counter*>(h);
  std::lock_guard<std::mutex> g(c->mu);
  auto kids = split(children);
  Ref& p = c->refs[parent];
  for (const auto& kid : kids) {
    c->refs[kid].contained_in++;
    p.contained.push_back(kid);
  }
}

// Explicit free (ray.free analog): drop the value regardless of refcounts.
int64_t rrc_force_free(void* h, const char* oid, char* buf, int64_t cap) {
  auto* c = static_cast<Counter*>(h);
  std::lock_guard<std::mutex> g(c->mu);
  std::vector<std::string> freed;
  auto it = c->refs.find(oid);
  if (it != c->refs.end() && it->second.value_live) {
    std::vector<std::string> children = std::move(it->second.contained);
    it->second.value_live = false;
    freed.push_back(oid);
    c->maybe_erase(oid);
    for (const auto& child : children) {
      auto cit = c->refs.find(child);
      if (cit == c->refs.end()) continue;
      if (cit->second.contained_in > 0) cit->second.contained_in--;
      c->collect(child, &freed);
      c->maybe_erase(child);
    }
  }
  return commit_freed(c, freed, buf, cap);
}

// Read-only re-read of the last freeing mutation's result (for the
// grow-buffer retry: mutations must not run twice).
int64_t rrc_last_freed(void* h, char* buf, int64_t cap) {
  auto* c = static_cast<Counter*>(h);
  std::lock_guard<std::mutex> g(c->mu);
  return write_str(c->last_freed, buf, cap);
}

int rrc_has(void* h, const char* oid) {
  auto* c = static_cast<Counter*>(h);
  std::lock_guard<std::mutex> g(c->mu);
  auto it = c->refs.find(oid);
  return it != c->refs.end() && it->second.value_live ? 1 : 0;
}

int64_t rrc_local_count(void* h, const char* oid) {
  auto* c = static_cast<Counter*>(h);
  std::lock_guard<std::mutex> g(c->mu);
  auto it = c->refs.find(oid);
  return it == c->refs.end() ? 0 : it->second.local;
}

int64_t rrc_num_tracked(void* h) {
  auto* c = static_cast<Counter*>(h);
  std::lock_guard<std::mutex> g(c->mu);
  return static_cast<int64_t>(c->refs.size());
}

// Debug/state-API dump: "oid=local,task_deps,contained_in,borrowers;..."
int64_t rrc_dump(void* h, char* buf, int64_t cap) {
  auto* c = static_cast<Counter*>(h);
  std::lock_guard<std::mutex> g(c->mu);
  std::vector<std::string> rows;
  rows.reserve(c->refs.size());
  for (const auto& kv : c->refs) {
    rows.push_back(kv.first + "=" + std::to_string(kv.second.local) + "," +
                   std::to_string(kv.second.task_deps) + "," +
                   std::to_string(kv.second.contained_in) + "," +
                   std::to_string(kv.second.borrowers.size()));
  }
  return write_list(rows, buf, cap);
}

}  // extern "C"
