// sched.cc — native cluster resource scheduler (C ABI).
//
// TPU-native equivalent of the reference's C++ scheduling stack:
// fixed-point resource vectors (src/ray/raylet/scheduling/fixed_point.h),
// per-node accounting (scheduling/local_resource_manager.h:42), hybrid
// pack-then-spread node selection
// (scheduling/policy/hybrid_scheduling_policy.h:20-35), SPREAD with
// round-robin tie-break, and placement-group bundle placement with
// PACK / SPREAD / STRICT_PACK / STRICT_SPREAD strategies
// (scheduling/policy/bundle_scheduling_policy.h) plus lost-bundle
// rescheduling on node death.
//
// Resources cross the ABI as strings: "CPU=4;TPU=8;memory=1e9".
// Bundle lists use '|' between bundles: "CPU=1;TPU=2|CPU=2".
// Values are doubles, stored as int64 fixed-point in 1e-4 units (the
// reference's kResourceUnitScaling).
//
// Python binding: ray_tpu/_private/native_sched.py. Thread safety: one
// mutex per scheduler instance (all calls lock, like the reference's
// ClusterResourceScheduler usage under the raylet main loop).
//
// Build: g++ -O2 -shared -fPIC -std=c++17 -o libsched.so sched.cc

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <map>
#include <mutex>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

constexpr int64_t kScale = 10000;  // 1e-4 resource units
constexpr double kSpreadThreshold = 0.5;

using ResVec = std::map<int, int64_t>;  // interned name id -> fixed-point

struct Node {
  ResVec total;
  ResVec avail;
  bool alive = true;
};

struct Bundle {
  int64_t node = -1;
  ResVec reserved;
  ResVec avail;
};

struct PlacementGroup {
  int strategy = 0;
  std::vector<Bundle> bundles;
  bool alive = true;
};

struct Sched {
  std::mutex mu;
  std::vector<std::string> names;               // intern table
  std::unordered_map<std::string, int> ids;
  std::vector<Node> nodes;                      // handle = index
  std::vector<int64_t> order;                   // insertion order, alive only
  std::vector<PlacementGroup> pgs;              // handle = index
  uint64_t spread_rr = 0;

  int intern(const std::string& s) {
    auto it = ids.find(s);
    if (it != ids.end()) return it->second;
    int id = static_cast<int>(names.size());
    names.push_back(s);
    ids.emplace(s, id);
    return id;
  }
};

// "CPU=4;TPU=8" -> ResVec. Returns false on parse error.
bool ParseRes(Sched* s, const char* str, ResVec* out) {
  out->clear();
  if (str == nullptr) return true;
  const char* p = str;
  while (*p) {
    const char* eq = strchr(p, '=');
    if (eq == nullptr) return false;
    const char* end = strchr(eq + 1, ';');
    std::string name(p, eq - p);
    double v = atof(std::string(eq + 1, end ? end - eq - 1
                                            : strlen(eq + 1)).c_str());
    int64_t fixed = llround(v * kScale);
    if (fixed != 0 || v == 0.0) (*out)[s->intern(name)] = fixed;
    if (end == nullptr) break;
    p = end + 1;
  }
  return true;
}

int64_t FormatRes(const Sched* s, const ResVec& v, char* buf, int64_t cap) {
  int64_t off = 0;
  for (const auto& kv : v) {
    int n = snprintf(buf + off, cap > off ? cap - off : 0, "%s%s=%.10g",
                     off ? ";" : "", s->names[kv.first].c_str(),
                     static_cast<double>(kv.second) / kScale);
    if (n < 0) return -1;
    off += n;
  }
  if (off < cap) buf[off] = '\0';
  return off;  // required length (excl. NUL); caller re-calls if >= cap
}

bool Fits(const ResVec& avail, const ResVec& need) {
  for (const auto& kv : need) {
    auto it = avail.find(kv.first);
    int64_t have = it == avail.end() ? 0 : it->second;
    if (have < kv.second) return false;
  }
  return true;
}

void Sub(ResVec* avail, const ResVec& need) {
  for (const auto& kv : need) (*avail)[kv.first] -= kv.second;
}

void Add(ResVec* avail, const ResVec& need) {
  for (const auto& kv : need) (*avail)[kv.first] += kv.second;
}

// Max used-fraction over capacity resources, skipping node:* identity
// resources (the hybrid policy's "critical resource utilization").
double Utilization(const Sched* s, const Node& n) {
  double worst = 0.0;
  for (const auto& kv : n.total) {
    if (kv.second <= 0) continue;
    const std::string& name = s->names[kv.first];
    if (name.rfind("node:", 0) == 0) continue;
    auto it = n.avail.find(kv.first);
    int64_t avail = it == n.avail.end() ? 0 : it->second;
    double used = static_cast<double>(kv.second - avail) / kv.second;
    if (used > worst) worst = used;
  }
  return worst;
}

double RoundedUtil(const Sched* s, const Node& n) {
  return std::round(Utilization(s, n) * 1e6) / 1e6;
}

}  // namespace

extern "C" {

void* rsched_create() { return new Sched(); }

void rsched_destroy(void* h) { delete static_cast<Sched*>(h); }

int64_t rsched_add_node(void* h, const char* res) {
  Sched* s = static_cast<Sched*>(h);
  std::lock_guard<std::mutex> lock(s->mu);
  Node n;
  if (!ParseRes(s, res, &n.total)) return -1;
  n.avail = n.total;
  int64_t handle = static_cast<int64_t>(s->nodes.size());
  s->nodes.push_back(std::move(n));
  s->order.push_back(handle);
  return handle;
}

int rsched_remove_node(void* h, int64_t node) {
  Sched* s = static_cast<Sched*>(h);
  std::lock_guard<std::mutex> lock(s->mu);
  if (node < 0 || node >= static_cast<int64_t>(s->nodes.size()) ||
      !s->nodes[node].alive)
    return -1;
  s->nodes[node].alive = false;
  s->order.erase(std::find(s->order.begin(), s->order.end(), node));
  return 0;
}

int rsched_node_alive(void* h, int64_t node) {
  Sched* s = static_cast<Sched*>(h);
  std::lock_guard<std::mutex> lock(s->mu);
  return node >= 0 && node < static_cast<int64_t>(s->nodes.size()) &&
         s->nodes[node].alive;
}

int64_t rsched_num_nodes(void* h) {
  Sched* s = static_cast<Sched*>(h);
  std::lock_guard<std::mutex> lock(s->mu);
  return static_cast<int64_t>(s->order.size());
}

// which: 0 = total, 1 = available. Returns required length (excl. NUL),
// or -1 on bad node.
int64_t rsched_node_resources(void* h, int64_t node, int which, char* buf,
                              int64_t cap) {
  Sched* s = static_cast<Sched*>(h);
  std::lock_guard<std::mutex> lock(s->mu);
  if (node < 0 || node >= static_cast<int64_t>(s->nodes.size())) return -1;
  const Node& n = s->nodes[node];
  return FormatRes(s, which == 0 ? n.total : n.avail, buf, cap);
}

double rsched_utilization(void* h, int64_t node) {
  Sched* s = static_cast<Sched*>(h);
  std::lock_guard<std::mutex> lock(s->mu);
  if (node < 0 || node >= static_cast<int64_t>(s->nodes.size())) return 0.0;
  return Utilization(s, s->nodes[node]);
}

int rsched_fits(void* h, int64_t node, int which, const char* res) {
  Sched* s = static_cast<Sched*>(h);
  std::lock_guard<std::mutex> lock(s->mu);
  if (node < 0 || node >= static_cast<int64_t>(s->nodes.size())) return 0;
  ResVec need;
  if (!ParseRes(s, res, &need)) return 0;
  const Node& n = s->nodes[node];
  return Fits(which == 0 ? n.total : n.avail, need);
}

int rsched_try_acquire_on(void* h, int64_t node, const char* res) {
  Sched* s = static_cast<Sched*>(h);
  std::lock_guard<std::mutex> lock(s->mu);
  if (node < 0 || node >= static_cast<int64_t>(s->nodes.size()) ||
      !s->nodes[node].alive)
    return -1;
  ResVec need;
  if (!ParseRes(s, res, &need)) return -1;
  Node& n = s->nodes[node];
  if (!Fits(n.avail, need)) return -1;
  Sub(&n.avail, need);
  return 0;
}

void rsched_release_on(void* h, int64_t node, const char* res) {
  Sched* s = static_cast<Sched*>(h);
  std::lock_guard<std::mutex> lock(s->mu);
  if (node < 0 || node >= static_cast<int64_t>(s->nodes.size()) ||
      !s->nodes[node].alive)
    return;  // resources died with the node
  ResVec need;
  if (!ParseRes(s, res, &need)) return;
  Add(&s->nodes[node].avail, need);
}

void rsched_force_acquire_on(void* h, int64_t node, const char* res) {
  Sched* s = static_cast<Sched*>(h);
  std::lock_guard<std::mutex> lock(s->mu);
  if (node < 0 || node >= static_cast<int64_t>(s->nodes.size()) ||
      !s->nodes[node].alive)
    return;
  ResVec need;
  if (!ParseRes(s, res, &need)) return;
  Sub(&s->nodes[node].avail, need);  // may transiently overcommit
}

// strategy: 0 = DEFAULT/hybrid (pack in id order under the spread
// threshold, else least-utilized), 1 = SPREAD (least-utilized,
// round-robin tie-break). Returns the chosen node handle (resources
// acquired) or -1.
int64_t rsched_pick_and_acquire(void* h, const char* res, int strategy) {
  Sched* s = static_cast<Sched*>(h);
  std::lock_guard<std::mutex> lock(s->mu);
  ResVec need;
  if (!ParseRes(s, res, &need)) return -1;

  std::vector<int64_t> candidates;
  if (strategy == 1) {
    uint64_t rr = ++s->spread_rr;
    std::vector<int64_t> ranked(s->order);
    std::stable_sort(ranked.begin(), ranked.end(),
                     [&](int64_t a, int64_t b) {
                       return RoundedUtil(s, s->nodes[a]) <
                              RoundedUtil(s, s->nodes[b]);
                     });
    if (!ranked.empty()) {
      double lowest = RoundedUtil(s, s->nodes[ranked[0]]);
      size_t np = 0;
      while (np < ranked.size() &&
             RoundedUtil(s, s->nodes[ranked[np]]) == lowest)
        ++np;
      size_t k = rr % np;
      for (size_t i = 0; i < np; ++i)
        candidates.push_back(ranked[(k + i) % np]);
      for (size_t i = np; i < ranked.size(); ++i)
        candidates.push_back(ranked[i]);
    }
  } else {
    std::vector<int64_t> over;
    for (int64_t id : s->order) {
      if (Utilization(s, s->nodes[id]) < kSpreadThreshold)
        candidates.push_back(id);
      else
        over.push_back(id);
    }
    std::stable_sort(over.begin(), over.end(), [&](int64_t a, int64_t b) {
      return Utilization(s, s->nodes[a]) < Utilization(s, s->nodes[b]);
    });
    candidates.insert(candidates.end(), over.begin(), over.end());
  }

  for (int64_t id : candidates) {
    Node& n = s->nodes[id];
    if (!n.alive) continue;
    if (Fits(n.avail, need)) {
      Sub(&n.avail, need);
      return id;
    }
  }
  return -1;
}

// -- placement groups ---------------------------------------------------

// strategy: 0 PACK, 1 SPREAD, 2 STRICT_PACK, 3 STRICT_SPREAD.
// Returns pg handle or -1 if infeasible.
int64_t rsched_pg_create(void* h, const char* bundles_str, int strategy) {
  Sched* s = static_cast<Sched*>(h);
  std::lock_guard<std::mutex> lock(s->mu);

  std::vector<ResVec> bundles;
  {
    std::string all(bundles_str ? bundles_str : "");
    size_t pos = 0;
    while (pos <= all.size()) {
      size_t bar = all.find('|', pos);
      std::string one = all.substr(
          pos, bar == std::string::npos ? std::string::npos : bar - pos);
      ResVec v;
      if (!ParseRes(s, one.c_str(), &v)) return -1;
      bundles.push_back(std::move(v));
      if (bar == std::string::npos) break;
      pos = bar + 1;
    }
  }
  if (bundles.empty()) return -1;

  std::vector<int64_t> alive(s->order);
  if (alive.empty()) return -1;
  // Shadow availability for the dry run.
  std::unordered_map<int64_t, ResVec> shadow;
  for (int64_t id : alive) shadow[id] = s->nodes[id].avail;

  std::vector<std::pair<int64_t, const ResVec*>> placed;
  auto by_util = [&](std::vector<int64_t> ids) {
    std::stable_sort(ids.begin(), ids.end(), [&](int64_t a, int64_t b) {
      return Utilization(s, s->nodes[a]) < Utilization(s, s->nodes[b]);
    });
    return ids;
  };

  if (strategy == 2) {  // STRICT_PACK: all bundles on one node
    bool done = false;
    for (int64_t id : alive) {
      ResVec rem = shadow[id];
      bool ok = true;
      for (const auto& b : bundles) {
        if (!Fits(rem, b)) { ok = false; break; }
        Sub(&rem, b);
      }
      if (ok) {
        for (const auto& b : bundles) placed.emplace_back(id, &b);
        done = true;
        break;
      }
    }
    if (!done) return -1;
  } else if (strategy == 3) {  // STRICT_SPREAD: distinct node per bundle
    if (bundles.size() > alive.size()) return -1;
    std::vector<char> used(s->nodes.size(), 0);
    for (const auto& b : bundles) {
      int64_t chosen = -1;
      for (int64_t id : by_util(alive)) {
        if (used[id]) continue;
        if (Fits(shadow[id], b)) { chosen = id; break; }
      }
      if (chosen < 0) return -1;
      used[chosen] = 1;
      Sub(&shadow[chosen], b);
      placed.emplace_back(chosen, &b);
    }
  } else if (strategy == 1) {  // SPREAD: best-effort distinct, rotating
    for (size_t i = 0; i < bundles.size(); ++i) {
      std::vector<int64_t> ranked = by_util(alive);
      size_t k = i % ranked.size();
      std::rotate(ranked.begin(), ranked.begin() + k, ranked.end());
      int64_t chosen = -1;
      for (int64_t id : ranked)
        if (Fits(shadow[id], bundles[i])) { chosen = id; break; }
      if (chosen < 0) return -1;
      Sub(&shadow[chosen], bundles[i]);
      placed.emplace_back(chosen, &bundles[i]);
    }
  } else {  // PACK: first-fit in node order
    for (const auto& b : bundles) {
      int64_t chosen = -1;
      for (int64_t id : alive)
        if (Fits(shadow[id], b)) { chosen = id; break; }
      if (chosen < 0) return -1;
      Sub(&shadow[chosen], b);
      placed.emplace_back(chosen, &b);
    }
  }

  PlacementGroup pg;
  pg.strategy = strategy;
  for (auto& [node_id, bres] : placed) {
    Sub(&s->nodes[node_id].avail, *bres);  // commit
    Bundle b;
    b.node = node_id;
    b.reserved = *bres;
    b.avail = *bres;
    pg.bundles.push_back(std::move(b));
  }
  int64_t handle = static_cast<int64_t>(s->pgs.size());
  s->pgs.push_back(std::move(pg));
  return handle;
}

int rsched_pg_remove(void* h, int64_t pg) {
  Sched* s = static_cast<Sched*>(h);
  std::lock_guard<std::mutex> lock(s->mu);
  if (pg < 0 || pg >= static_cast<int64_t>(s->pgs.size()) ||
      !s->pgs[pg].alive)
    return -1;
  PlacementGroup& p = s->pgs[pg];
  p.alive = false;
  for (const Bundle& b : p.bundles)
    if (b.node >= 0 && s->nodes[b.node].alive)
      Add(&s->nodes[b.node].avail, b.reserved);
  return 0;
}

int rsched_pg_exists(void* h, int64_t pg) {
  Sched* s = static_cast<Sched*>(h);
  std::lock_guard<std::mutex> lock(s->mu);
  return pg >= 0 && pg < static_cast<int64_t>(s->pgs.size()) &&
         s->pgs[pg].alive;
}

int rsched_pg_num_bundles(void* h, int64_t pg) {
  Sched* s = static_cast<Sched*>(h);
  std::lock_guard<std::mutex> lock(s->mu);
  if (pg < 0 || pg >= static_cast<int64_t>(s->pgs.size())) return 0;
  return static_cast<int>(s->pgs[pg].bundles.size());
}

int64_t rsched_pg_bundle_node(void* h, int64_t pg, int bundle) {
  Sched* s = static_cast<Sched*>(h);
  std::lock_guard<std::mutex> lock(s->mu);
  if (pg < 0 || pg >= static_cast<int64_t>(s->pgs.size())) return -1;
  const PlacementGroup& p = s->pgs[pg];
  if (bundle < 0 || bundle >= static_cast<int>(p.bundles.size())) return -1;
  return p.bundles[bundle].node;
}

// which: 0 = reserved, 1 = available.
int64_t rsched_pg_bundle_resources(void* h, int64_t pg, int bundle,
                                   int which, char* buf, int64_t cap) {
  Sched* s = static_cast<Sched*>(h);
  std::lock_guard<std::mutex> lock(s->mu);
  if (pg < 0 || pg >= static_cast<int64_t>(s->pgs.size())) return -1;
  const PlacementGroup& p = s->pgs[pg];
  if (bundle < 0 || bundle >= static_cast<int>(p.bundles.size())) return -1;
  const Bundle& b = p.bundles[bundle];
  return FormatRes(s, which == 0 ? b.reserved : b.avail, buf, cap);
}

// Acquire inside a PG. bundle_index -1 = any bundle. Returns the bundle
// index used, or -1.
int rsched_pg_try_acquire(void* h, int64_t pg, int bundle_index,
                          const char* res) {
  Sched* s = static_cast<Sched*>(h);
  std::lock_guard<std::mutex> lock(s->mu);
  if (pg < 0 || pg >= static_cast<int64_t>(s->pgs.size()) ||
      !s->pgs[pg].alive)
    return -1;
  ResVec need;
  if (!ParseRes(s, res, &need)) return -1;
  PlacementGroup& p = s->pgs[pg];
  int lo = bundle_index >= 0 ? bundle_index : 0;
  int hi = bundle_index >= 0 ? bundle_index + 1
                             : static_cast<int>(p.bundles.size());
  for (int i = lo; i < hi && i < static_cast<int>(p.bundles.size()); ++i) {
    Bundle& b = p.bundles[i];
    if (b.node < 0 || !s->nodes[b.node].alive) continue;
    if (Fits(b.avail, need)) {
      Sub(&b.avail, need);
      return i;
    }
  }
  return -1;
}

void rsched_pg_release(void* h, int64_t pg, int bundle, const char* res) {
  Sched* s = static_cast<Sched*>(h);
  std::lock_guard<std::mutex> lock(s->mu);
  if (pg < 0 || pg >= static_cast<int64_t>(s->pgs.size())) return;
  PlacementGroup& p = s->pgs[pg];
  if (bundle < 0 || bundle >= static_cast<int>(p.bundles.size())) return;
  Bundle& b = p.bundles[bundle];
  if (b.node < 0 || !s->nodes[b.node].alive) return;
  ResVec need;
  if (!ParseRes(s, res, &need)) return;
  Add(&b.avail, need);
}

void rsched_pg_force_acquire(void* h, int64_t pg, int bundle,
                             const char* res) {
  Sched* s = static_cast<Sched*>(h);
  std::lock_guard<std::mutex> lock(s->mu);
  if (pg < 0 || pg >= static_cast<int64_t>(s->pgs.size())) return;
  PlacementGroup& p = s->pgs[pg];
  if (bundle < 0 || bundle >= static_cast<int>(p.bundles.size())) return;
  ResVec need;
  if (!ParseRes(s, res, &need)) return;
  Sub(&p.bundles[bundle].avail, need);
}

// Re-place bundles whose node died onto alive nodes (in insertion order).
// Writes touched pg handles into out (up to cap); returns the count.
int64_t rsched_pg_reschedule_lost(void* h, int64_t* out, int64_t cap) {
  Sched* s = static_cast<Sched*>(h);
  std::lock_guard<std::mutex> lock(s->mu);
  int64_t count = 0;
  for (int64_t pg_id = 0; pg_id < static_cast<int64_t>(s->pgs.size());
       ++pg_id) {
    PlacementGroup& p = s->pgs[pg_id];
    if (!p.alive) continue;
    bool touched = false;
    for (Bundle& b : p.bundles) {
      if (b.node >= 0 && s->nodes[b.node].alive) continue;
      touched = true;
      b.node = -1;
      for (int64_t id : s->order) {
        Node& n = s->nodes[id];
        if (Fits(n.avail, b.reserved)) {
          Sub(&n.avail, b.reserved);
          b.node = id;
          b.avail = b.reserved;
          break;
        }
      }
    }
    if (touched && count < cap) out[count] = pg_id;
    if (touched) ++count;
  }
  return count;
}

}  // extern "C"
