"""Multi-process cluster tests: real node-daemon subprocesses joining a
head over TCP (the analog of the reference's multi-raylet fixtures, but
with genuine OS processes — SURVEY.md §4's Cluster model upgraded from
virtual nodes to the wire protocol in _private/multinode.py)."""

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

import ray_tpu


def _spawn_daemon(port, *, num_cpus=4, resources=None):
    cmd = [sys.executable, "-m", "ray_tpu._private.multinode",
           "--address", f"127.0.0.1:{port}",
           "--num-cpus", str(num_cpus)]
    if resources:
        cmd += ["--resources", json.dumps(resources)]
    return subprocess.Popen(cmd, stdout=subprocess.DEVNULL,
                            stderr=subprocess.DEVNULL)


def _wait_for_resource(name, amount, timeout=20):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if ray_tpu.cluster_resources().get(name, 0) >= amount:
            return
        time.sleep(0.1)
    raise TimeoutError(
        f"resource {name}>={amount} never appeared: "
        f"{ray_tpu.cluster_resources()}")


@pytest.fixture
def head_with_daemons(ray_start_regular):
    """Head + 2 real daemon subprocesses, each with a 'remote' resource
    so tests can force placement off the head node."""
    host, port = ray_tpu.start_head_server(port=0, host="127.0.0.1")
    procs = [
        _spawn_daemon(port, num_cpus=4, resources={"remote": 2})
        for _ in range(2)]
    try:
        _wait_for_resource("remote", 4)
        yield port, procs
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
            p.wait(timeout=10)


def test_remote_node_task_execution(head_with_daemons):
    @ray_tpu.remote(resources={"remote": 1})
    def where(x):
        import os
        return os.getpid(), x * 2

    head_pid = os.getpid()
    results = ray_tpu.get([where.remote(i) for i in range(8)])
    pids = {pid for pid, _ in results}
    assert sorted(v for _, v in results) == [0, 2, 4, 6, 8, 10, 12, 14]
    assert head_pid not in pids, "tasks must run in the daemon processes"
    assert len(pids) >= 1

    # numpy payloads round-trip the wire
    @ray_tpu.remote(resources={"remote": 1})
    def matsum(a):
        return float(a.sum())

    arr = np.ones((256, 256), np.float32)
    assert ray_tpu.get(matsum.remote(arr)) == 256 * 256


def test_remote_node_error_propagation(head_with_daemons):
    from ray_tpu.exceptions import TaskError

    @ray_tpu.remote(max_retries=0, resources={"remote": 1})
    def boom():
        raise ValueError("remote kaboom")

    with pytest.raises(TaskError) as err:
        ray_tpu.get(boom.remote())
    assert isinstance(err.value.cause, ValueError)
    assert "remote kaboom" in str(err.value)


def test_remote_node_actor(head_with_daemons):
    @ray_tpu.remote(resources={"remote": 1})
    class Counter:
        def __init__(self, start):
            self.v = start

        def add(self, d):
            self.v += d
            return self.v

        def pid(self):
            import os
            return os.getpid()

    c = Counter.remote(100)
    assert ray_tpu.get([c.add.remote(1) for _ in range(5)]) == \
        [101, 102, 103, 104, 105]
    assert ray_tpu.get(c.pid.remote()) != os.getpid()
    ray_tpu.kill(c)


def test_remote_node_death_retries_elsewhere(head_with_daemons):
    port, procs = head_with_daemons

    @ray_tpu.remote(resources={"remote": 1}, max_retries=3)
    def slow(i):
        import os
        import time as t
        t.sleep(1.0)
        return os.getpid(), i

    refs = [slow.remote(i) for i in range(4)]
    time.sleep(0.4)  # let tasks land on both daemons
    procs[0].send_signal(signal.SIGKILL)
    procs[0].wait(timeout=10)
    results = ray_tpu.get(refs, timeout=60)
    assert sorted(i for _, i in results) == [0, 1, 2, 3]
    # the dead daemon's pid may appear for tasks that finished pre-kill,
    # but every task completed despite the node death
    assert ray_tpu.cluster_resources().get("remote", 0) == 2


def test_remote_actor_restarts_on_node_death(ray_start_regular):
    host, port = ray_tpu.start_head_server(port=0, host="127.0.0.1")
    p1 = _spawn_daemon(port, num_cpus=2, resources={"remote": 1})
    _wait_for_resource("remote", 1)

    @ray_tpu.remote(resources={"remote": 1}, max_restarts=2)
    class Stateful:
        def __init__(self):
            import os
            self.pid = os.getpid()
            self.n = 0

        def bump(self):
            self.n += 1
            return self.pid, self.n

    a = Stateful.remote()
    pid1, n = ray_tpu.get(a.bump.remote())
    assert n == 1
    p2 = _spawn_daemon(port, num_cpus=2, resources={"remote": 1})
    _wait_for_resource("remote", 2)
    try:
        p1.send_signal(signal.SIGKILL)
        p1.wait(timeout=10)
        # restart loses state (reference max_restarts semantics) and lands
        # on the surviving daemon
        deadline = time.monotonic() + 30
        while True:
            try:
                pid2, n2 = ray_tpu.get(a.bump.remote(), timeout=10)
                break
            except Exception:
                if time.monotonic() > deadline:
                    raise
                time.sleep(0.5)
        assert pid2 != pid1
        assert n2 == 1  # fresh state after restart
    finally:
        for p in (p1, p2):
            if p.poll() is None:
                p.kill()
            p.wait(timeout=10)


def test_object_ref_args_resolve_to_values(head_with_daemons):
    """ObjectRef args are resolved on the head and shipped by value."""
    @ray_tpu.remote
    def produce():
        return np.arange(1000)

    @ray_tpu.remote(resources={"remote": 1})
    def consume(arr):
        return int(arr.sum())

    ref = produce.remote()  # runs on the head (no 'remote' resource)
    assert ray_tpu.get(consume.remote(ref)) == 499500


def test_remote_tpu_ids_visible_in_daemon(ray_start_regular):
    host, port = ray_tpu.start_head_server(port=0, host="127.0.0.1")
    cmd = [sys.executable, "-m", "ray_tpu._private.multinode",
           "--address", f"127.0.0.1:{port}",
           "--num-cpus", "2", "--num-tpus", "2"]
    p = subprocess.Popen(cmd, stdout=subprocess.DEVNULL,
                         stderr=subprocess.DEVNULL)
    try:
        _wait_for_resource("TPU", 2)

        @ray_tpu.remote(num_tpus=1)
        def chips():
            return ray_tpu.get_tpu_ids()

        a, b = ray_tpu.get([chips.remote(), chips.remote()])
        assert len(a) == 1 and len(b) == 1
        assert set(a).isdisjoint(b), (a, b)  # disjoint chip assignment
    finally:
        if p.poll() is None:
            p.kill()
        p.wait(timeout=10)


@pytest.fixture
def head_small_inline_limit():
    """Cluster whose remote results above 1000 bytes stay daemon-resident
    (exercises the lazy-fetch data plane with small test payloads)."""
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=4, num_tpus=0, _memory=1e9,
                 _system_config={"remote_object_inline_limit_bytes": 1000})
    host, port = ray_tpu.start_head_server(port=0, host="127.0.0.1")
    p = _spawn_daemon(port, num_cpus=4, resources={"remote": 4})
    try:
        _wait_for_resource("remote", 4)
        yield port, p
    finally:
        if p.poll() is None:
            p.kill()
        p.wait(timeout=10)
        ray_tpu.shutdown()


def test_big_results_stay_daemon_resident(head_small_inline_limit):
    runtime = ray_tpu._private.worker.global_worker.runtime

    @ray_tpu.remote(resources={"remote": 1})
    def big():
        return np.arange(100_000)  # ~800KB >> 1000B limit

    ref = big.remote()
    # the store seals a lazy entry: ready for wait, value not yet local
    done, _ = ray_tpu.wait([ref], num_returns=1, timeout=30)
    assert done == [ref]
    oid = ref.object_id()
    assert runtime._remote_values.get(oid) is not None
    assert not runtime.store.is_materialized(oid)
    # first get pulls it over the wire and memoizes
    arr = ray_tpu.get(ref)
    assert int(arr.sum()) == 4999950000
    assert runtime.store.is_materialized(oid)


def test_remote_arg_locality_markers(head_small_inline_limit):
    """A daemon-resident value passed to a task on the same daemon is
    resolved locally there, not round-tripped through the head."""
    @ray_tpu.remote(resources={"remote": 1})
    def produce():
        return np.arange(50_000)

    @ray_tpu.remote(resources={"remote": 1})
    def consume(a):
        return int(a.sum())

    ref = produce.remote()
    ray_tpu.wait([ref], timeout=30)
    runtime = ray_tpu._private.worker.global_worker.runtime
    oid = ref.object_id()
    assert oid in runtime._remote_values  # still daemon-resident
    assert ray_tpu.get(consume.remote(ref)) == 1249975000
    # the head never materialized it: the arg traveled as a marker
    assert not runtime.store.is_materialized(oid)


def test_daemon_resident_value_reconstructed_on_death(
        head_small_inline_limit):
    port, p = head_small_inline_limit

    @ray_tpu.remote(resources={"remote": 1}, max_retries=2)
    def big(i):
        return np.full(30_000, i)

    ref = big.remote(7)
    ray_tpu.wait([ref], timeout=30)
    runtime = ray_tpu._private.worker.global_worker.runtime
    assert ref.object_id() in runtime._remote_values
    # second daemon joins, first dies before the value was fetched
    p2 = _spawn_daemon(port, num_cpus=4, resources={"remote": 4})
    try:
        _wait_for_resource("remote", 8)
        p.send_signal(signal.SIGKILL)
        p.wait(timeout=10)
        # lineage re-executes the task on the survivor
        arr = ray_tpu.get(ref, timeout=60)
        assert arr.shape == (30_000,) and int(arr[0]) == 7
    finally:
        if p2.poll() is None:
            p2.kill()
        p2.wait(timeout=10)


def test_hung_daemon_detected_by_health_checks(ray_start_regular):
    """A SIGSTOPped daemon keeps its socket open but stops replying; the
    head's membership loop (accrual suspicion + hard lease,
    gcs_health_check_manager analog) declares it dead and the node
    leaves the cluster."""
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=2, num_tpus=0,
                 _system_config={"health_probe_period_s": 0.05,
                                 "health_probe_timeout_s": 0.3,
                                 "node_lease_s": 3.0})
    host, port = ray_tpu.start_head_server(port=0, host="127.0.0.1")
    p = _spawn_daemon(port, num_cpus=2, resources={"remote": 2})
    try:
        _wait_for_resource("remote", 2)
        p.send_signal(signal.SIGSTOP)  # hung, not dead: TCP stays open
        deadline = time.monotonic() + 20
        while ray_tpu.cluster_resources().get("remote", 0) > 0:
            assert time.monotonic() < deadline, \
                "health checks never declared the hung daemon dead"
            time.sleep(0.2)
    finally:
        p.send_signal(signal.SIGCONT)
        p.kill()
        p.wait(timeout=10)
        ray_tpu.shutdown()


def test_autoscaler_launches_real_daemons(ray_start_regular):
    """End to end: infeasible demand -> autoscaler launches a REAL daemon
    process -> the task runs there; idle timeout terminates it."""
    from ray_tpu.autoscaler import (DaemonProcessNodeProvider,
                                    StandardAutoscaler)

    provider = DaemonProcessNodeProvider()
    autoscaler = StandardAutoscaler(provider, {
        "max_workers": 2,
        "idle_timeout_minutes": 0.0001,
        "available_node_types": {
            "burst-worker": {"resources": {"CPU": 2, "burst": 2},
                             "min_workers": 0, "max_workers": 2},
        },
    })

    @ray_tpu.remote(resources={"burst": 1})
    def job():
        import os
        return os.getpid()

    ref = job.remote()  # infeasible until the autoscaler acts
    result = autoscaler.update()
    assert result["launched"] == 1
    _wait_for_resource("burst", 2)
    pid = ray_tpu.get(ref, timeout=30)
    assert pid != os.getpid()
    # idle node is reaped once the timeout passes
    deadline = time.monotonic() + 30
    while autoscaler.num_terminations == 0:
        assert time.monotonic() < deadline
        time.sleep(0.3)
        autoscaler.update()
    deadline = time.monotonic() + 20
    while ray_tpu.cluster_resources().get("burst", 0) > 0:
        assert time.monotonic() < deadline
        time.sleep(0.2)


def test_rpc_chaos_injection_survived_by_retries(ray_start_regular):
    """testing_rpc_failure_pct makes control-plane requests randomly
    fail; task retries absorb it (reference: RAY_testing_* chaos flags
    exercised against a flaky RPC layer)."""
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=2, num_tpus=0,
                 _system_config={"testing_rpc_failure_pct": 20})
    host, port = ray_tpu.start_head_server(port=0, host="127.0.0.1")
    p = _spawn_daemon(port, num_cpus=4, resources={"remote": 4})
    try:
        _wait_for_resource("remote", 4)

        @ray_tpu.remote(resources={"remote": 1}, max_retries=10)
        def flaky_path(i):
            return i * 3

        out = ray_tpu.get([flaky_path.remote(i) for i in range(20)],
                          timeout=120)
        assert out == [i * 3 for i in range(20)]
    finally:
        if p.poll() is None:
            p.kill()
        p.wait(timeout=10)
        ray_tpu.shutdown()


def test_daemon_labels_reach_node_table(ray_start_regular):
    """`ray-tpu start --labels` (the cloud providers' provider_node_id
    self-tagging channel) lands in the head's node table."""
    host, port = ray_tpu.start_head_server(port=0, host="127.0.0.1")
    p = subprocess.Popen(
        [sys.executable, "-m", "ray_tpu._private.multinode",
         "--address", f"127.0.0.1:{port}", "--num-cpus", "1",
         "--resources", json.dumps({"lbl": 1}),
         "--labels", json.dumps({"provider_node_id": "node-42",
                                 "zone": "us-x1-a"})],
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    try:
        _wait_for_resource("lbl", 1)
        node = next(n for n in ray_tpu.nodes()
                    if n["Labels"].get("provider_node_id") == "node-42")
        assert node["Labels"]["zone"] == "us-x1-a"
        assert node["Alive"]
    finally:
        p.kill()
        p.wait(timeout=10)
