"""Worker-lease pipelining tests (reference: direct_task_transport.cc:174
OnWorkerIdle + lease_policy.cc): same-scheduling-class tasks stream onto a
single leased daemon worker without per-task scheduler involvement; leases
release on drain; pinned worker subprocesses are reused across a lease's
tasks; a lease under cross-class contention yields capacity."""

import json
import subprocess
import sys
import time

import pytest

import ray_tpu


def _spawn_daemon(port, *, num_cpus=4, resources=None):
    cmd = [sys.executable, "-m", "ray_tpu._private.multinode",
           "--address", f"127.0.0.1:{port}",
           "--num-cpus", str(num_cpus)]
    if resources:
        cmd += ["--resources", json.dumps(resources)]
    return subprocess.Popen(cmd, stdout=subprocess.DEVNULL,
                            stderr=subprocess.DEVNULL)


def _wait_for_resource(name, amount, timeout=20):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if ray_tpu.cluster_resources().get(name, 0) >= amount:
            return
        time.sleep(0.1)
    raise TimeoutError(
        f"resource {name}>={amount} never appeared: "
        f"{ray_tpu.cluster_resources()}")


def _runtime():
    from ray_tpu._private.worker import global_worker
    return global_worker._runtime


def _daemon_stats():
    rt = _runtime()
    return [conn.get_stats() for conn in rt._remote_nodes.values()]


@pytest.fixture
def lease_cluster(ray_start_regular):
    host, port = ray_tpu.start_head_server(port=0, host="127.0.0.1")
    procs = [_spawn_daemon(port, num_cpus=4, resources={"lease": 100})
             for _ in range(2)]
    try:
        _wait_for_resource("lease", 200)
        yield port, procs
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
            p.wait(timeout=10)


def test_many_tasks_ride_few_leases(lease_cluster):
    """N same-class tasks ride a handful of leases: lease creations are
    bounded by cluster CPU capacity, everything else pipelines
    (reference: OnWorkerIdle pushes queued tasks onto the granted
    lease)."""
    rt = _runtime()
    base = dict(rt.lease_stats)

    @ray_tpu.remote(resources={"lease": 1},
                    runtime_env={"worker_process": False})
    def tiny(i):
        return i * 2

    n = 200
    assert ray_tpu.get([tiny.remote(i) for i in range(n)],
                       timeout=60) == [i * 2 for i in range(n)]
    created = rt.lease_stats["created"] - base["created"]
    attached = rt.lease_stats["attached"] - base["attached"]
    # 8 cluster CPUs -> ~8 concurrent leases of this class (a few more
    # if the queue momentarily drains on a starved CI box); the vast
    # majority of the 200 tasks must have pipelined onto existing leases.
    assert 1 <= created <= 48, rt.lease_stats
    assert attached >= n - 48, rt.lease_stats
    # Daemon side agrees: tasks arrived tagged with lease ids.
    total = sum(s.get("lease_tasks_total", 0) for s in _daemon_stats())
    assert total >= n - 48


def test_lease_releases_on_drain(lease_cluster):
    """When the class queue drains, the lease gives its acquisition back:
    stats balance and the daemon retires its executors."""
    rt = _runtime()

    @ray_tpu.remote(resources={"lease": 1},
                    runtime_env={"worker_process": False})
    def tiny(i):
        return i

    ray_tpu.get([tiny.remote(i) for i in range(40)], timeout=60)
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        stats = rt.lease_stats
        if stats["released"] == stats["created"] and \
                all(s.get("leases", 0) == 0 for s in _daemon_stats()):
            break
        time.sleep(0.2)
    stats = rt.lease_stats
    assert stats["released"] == stats["created"], stats
    assert all(s.get("leases", 0) == 0 for s in _daemon_stats())
    # Full capacity is back.
    assert ray_tpu.available_resources().get("lease", 0) == 200


def test_pinned_worker_reused_across_lease(lease_cluster):
    """Worker-process tasks on one lease reuse ONE pinned subprocess:
    the daemon pool does not grow per task (reference: a granted lease
    IS a worker for its lifetime)."""
    @ray_tpu.remote(resources={"lease": 1})
    def wtask(i):
        import os
        return (i, os.getpid())

    n = 60
    out = ray_tpu.get([wtask.remote(i) for i in range(n)], timeout=120)
    assert [i for i, _ in out] == list(range(n))
    pids = {pid for _, pid in out}
    # 8 cluster CPUs + prestart: a handful of workers, never one-per-task.
    assert len(pids) <= 16, f"{len(pids)} distinct worker pids"
    for s in _daemon_stats():
        assert s.get("pool_workers", 0) <= 12, s


def test_cross_class_fairness_under_contention(lease_cluster):
    """A lease drains-and-releases when a DIFFERENT class is starved for
    capacity — a steady stream of class-A work must not starve class B
    forever (lease fairness)."""
    @ray_tpu.remote(resources={"lease": 100},
                    runtime_env={"worker_process": False})
    def big(i):
        import time as t
        t.sleep(0.05)
        return i

    # Saturate: each big() holds one daemon's full `lease` capacity, and
    # 30 queued tasks keep the leases fed — without the fairness release
    # they would never let go.
    a_refs = [big.remote(i) for i in range(30)]
    time.sleep(0.1)

    @ray_tpu.remote(resources={"lease": 100},
                    runtime_env={"worker_process": False})
    def other():
        return "ran"

    b_ref = other.remote()
    assert ray_tpu.get(b_ref, timeout=30) == "ran"
    assert ray_tpu.get(a_refs, timeout=60) == list(range(30))


def test_blocked_nested_get_lends_lease_capacity(lease_cluster):
    """A leased task that blocks on a nested get lends the lease's
    acquisition out so the nested work can run (composition under
    leasing; reference: NotifyDirectCallTaskBlocked)."""
    @ray_tpu.remote(resources={"lease": 100},
                    runtime_env={"worker_process": False})
    def inner():
        return 41

    @ray_tpu.remote(resources={"lease": 100},
                    runtime_env={"worker_process": False})
    def outer():
        import ray_tpu as rt
        return rt.get(inner.remote(), timeout=30) + 1

    # Two outers saturate BOTH daemons' lease capacity; their inners can
    # only run if the blocked outers lend their lease acquisitions back.
    assert ray_tpu.get([outer.remote(), outer.remote()],
                       timeout=60) == [42, 42]


def test_same_class_recursion_never_deadlocks(lease_cluster):
    """Review regression: a leased task spawning a SAME-class child and
    getting it, at full saturation. The child must never be stuck behind
    its blocked parent on the lease's serial executor (blocked leases
    spill their daemon-side queue and stop accepting attaches)."""
    @ray_tpu.remote(resources={"lease": 100},
                    runtime_env={"worker_process": False})
    def rec(n):
        if n <= 0:
            return 0
        import ray_tpu as rt
        return rt.get(rec.remote(n - 1), timeout=45) + 1

    # Both daemons saturated by the outermost calls; every nested level
    # must still make progress via lent capacity.
    assert ray_tpu.get([rec.remote(2), rec.remote(2)],
                       timeout=60) == [2, 2]


def test_burst_prefers_idle_capacity_over_pipelining(lease_cluster):
    """Review regression: a burst smaller than the pipeline window must
    still fan out across idle capacity — pipelining supplements lease
    requests, it never replaces them."""
    rt = _runtime()
    base = rt.lease_stats["created"]

    @ray_tpu.remote(resources={"lease": 1},
                    runtime_env={"worker_process": False})
    def slowish(i):
        import time as t
        t.sleep(0.3)
        return i

    # 8 cluster CPUs, 8 tasks, window 10: without acquire-first these
    # would serialize onto ONE lease (~2.4s); in parallel they take ~0.3s.
    t0 = time.monotonic()
    assert ray_tpu.get([slowish.remote(i) for i in range(8)],
                       timeout=30) == list(range(8))
    elapsed = time.monotonic() - t0
    created = rt.lease_stats["created"] - base
    assert created >= 4, f"only {created} leases for an 8-wide burst"
    assert elapsed < 2.0, f"8 parallel 0.3s tasks took {elapsed:.1f}s"


def test_lease_survives_node_death(lease_cluster):
    """Leased in-flight tasks on a dying node retry elsewhere; the dead
    node's leases are dropped without corrupting accounting."""
    port, procs = lease_cluster
    rt = _runtime()

    @ray_tpu.remote(resources={"lease": 1}, max_retries=2,
                    runtime_env={"worker_process": False})
    def slow(i):
        import time as t
        t.sleep(0.05)
        return i

    refs = [slow.remote(i) for i in range(60)]
    time.sleep(0.3)  # let leases spin up on both daemons
    procs[0].kill()
    assert ray_tpu.get(refs, timeout=90) == list(range(60))
    # Accounting settles: every surviving lease eventually releases.
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        if rt.lease_stats["released"] + 0 >= rt.lease_stats["created"] - 8:
            break
        time.sleep(0.2)
    with rt._lock:
        assert all(not lst or all(le.inflight >= 0 for le in lst)
                   for lst in rt._leases.values())


def test_daemon_actor_multi_return_big_results(lease_cluster):
    """Review regression: a daemon-resident actor method with
    num_returns>1 whose elements exceed the inline limit — each element
    must come back as its own daemon-resident object, not a single
    opaque stub."""
    import numpy as np

    @ray_tpu.remote(resources={"lease": 1})
    class Producer:
        def make(self):
            return np.full(1 << 19, 3, np.int64), np.full(8, 4, np.int64)

    actor = Producer.remote()
    big_ref, small_ref = actor.make.options(num_returns=2).remote()
    big = ray_tpu.get(big_ref, timeout=60)
    small = ray_tpu.get(small_ref, timeout=60)
    assert int(big[0]) == 3 and big.nbytes == (1 << 19) * 8
    assert list(small) == [4] * 8


def test_lease_resumes_serial_after_unspill(ray_start_regular):
    """A nested-get spill is WINDOWED, not sticky: once the blocked get
    returns, the head's unspill_lease frame restores serial execution —
    later same-class tasks must queue on the lease's serial executor
    again, not fan out onto threads against its ONE accounted
    acquisition (the over-subscription the sticky flag caused).
    Reference: leased worker = one task at a time,
    direct_task_transport.cc OnWorkerIdle."""
    host, port = ray_tpu.start_head_server(port=0, host="127.0.0.1")
    # ONE cpu on the daemon: exactly one lease can exist for the class.
    p = _spawn_daemon(port, num_cpus=1, resources={"solo": 1})
    try:
        _wait_for_resource("solo", 1)

        # One function = one scheduling class = one lease.
        @ray_tpu.remote(num_cpus=1, resources={"solo": 0.01},
                        runtime_env={"worker_process": False})
        def task(mode):
            import time as t

            import ray_tpu as rt
            if mode == "block":

                @rt.remote(num_cpus=0, resources={"solo": 0.01})
                def child():
                    t.sleep(1.0)
                    return "c"

                out = rt.get(child.remote(), timeout=30)  # spills lease
                t.sleep(1.0)  # unspilled now; keep the lease occupied
                return out
            t.sleep(0.3)
            return mode

        blocker = task.remote("block")
        time.sleep(1.5)  # blocker past its nested get, inside the sleep
        t0 = time.monotonic()
        naps = [task.remote(f"nap{i}") for i in range(4)]
        assert ray_tpu.get(naps, timeout=60) == [
            f"nap{i}" for i in range(4)]
        wall = time.monotonic() - t0
        assert ray_tpu.get(blocker, timeout=60) == "c"
        # Serial resumption: 4 x 0.3s naps queue BEHIND the blocker's
        # remaining sleep on the serial executor (>= 1.2s, measured
        # ~1.7s). The sticky-spill bug fanned them onto threads
        # concurrently with the blocker (~0.35s wall).
        assert wall >= 1.1, (
            f"4 same-class 0.3s tasks finished in {wall:.2f}s while the "
            "lease's task was still running - the lease is still "
            "spilled (concurrent execution on one acquisition)")
    finally:
        if p.poll() is None:
            p.kill()
        p.wait(timeout=10)


def test_daemon_num_returns_mismatch_reports_type_and_frees_stub(
        lease_cluster):
    """Advisor regression: a daemon task declaring num_returns=2 but
    returning one OVERSIZED value (daemon-resident stub) must (a) report
    the user's actual return type — not 'RemoteValueStub of length n/a'
    — and (b) free the stub from the daemon table instead of leaking it
    until session end."""
    import numpy as np

    def _daemon_object_count():
        return sum(s.get("table", {}).get("objects", 0)
                   for s in _daemon_stats())

    @ray_tpu.remote(resources={"lease": 1}, num_returns=2, max_retries=0,
                    runtime_env={"worker_process": False})
    def wrong_shape():
        return np.full(1 << 19, 7, np.int64)  # 4MB single value, not 2

    r1, _r2 = wrong_shape.remote()
    with pytest.raises(Exception) as exc_info:
        ray_tpu.get(r1, timeout=60)
    msg = str(exc_info.value)
    assert "num_returns=2" in msg
    assert "ndarray" in msg, f"real type hidden: {msg}"
    assert "RemoteValueStub" not in msg
    # The daemon-side payload is freed, not leaked: the table drains.
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        if _daemon_object_count() == 0:
            break
        time.sleep(0.2)
    assert _daemon_object_count() == 0, _daemon_stats()
