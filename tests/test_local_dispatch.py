"""Daemon-local dispatch authority tests (round 5): shared per-class
queues on the node daemon (reference: raylet local_task_manager.cc:101
owns per-class dispatch queues; the head only grants capacity),
blocked-capacity temp slots, head-triggered spillback reclaim, and
backlog reporting through the syncer."""

import json
import subprocess
import sys
import time

import pytest

import ray_tpu


def _spawn_daemon(port, *, num_cpus=4, resources=None):
    cmd = [sys.executable, "-m", "ray_tpu._private.multinode",
           "--address", f"127.0.0.1:{port}",
           "--num-cpus", str(num_cpus)]
    if resources:
        cmd += ["--resources", json.dumps(resources)]
    return subprocess.Popen(cmd, stdout=subprocess.DEVNULL,
                            stderr=subprocess.DEVNULL)


def _wait_for_resource(name, amount, timeout=20):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if ray_tpu.cluster_resources().get(name, 0) >= amount:
            return
        time.sleep(0.1)
    raise TimeoutError(
        f"resource {name}>={amount} never appeared: "
        f"{ray_tpu.cluster_resources()}")


@pytest.fixture
def one_daemon(ray_start_regular):
    host, port = ray_tpu.start_head_server(port=0, host="127.0.0.1")
    p = _spawn_daemon(port, num_cpus=4, resources={"nd": 100})
    try:
        _wait_for_resource("nd", 100)
        yield port, p
    finally:
        if p.poll() is None:
            p.kill()
        p.wait(timeout=10)


def test_shared_queue_no_head_of_line_blocking(one_daemon):
    """One slow task must not delay the short tasks the head pipelined
    behind it onto the same lease: the daemon's shared class queue lets
    any free slot take them (pre-r5, per-lease FIFO serialized them
    behind the sleeper)."""
    @ray_tpu.remote(resources={"nd": 1}, num_cpus=1)
    def slow():
        time.sleep(3.0)
        return "slow"

    @ray_tpu.remote(resources={"nd": 1}, num_cpus=1)
    def quick(i):
        return i

    slow_ref = slow.remote()
    time.sleep(0.3)  # let the slow task occupy one slot
    t0 = time.monotonic()
    out = ray_tpu.get([quick.remote(i) for i in range(40)], timeout=30)
    quick_dt = time.monotonic() - t0
    assert out == list(range(40))
    # 40 trivial tasks over the remaining 3 slots: far under the
    # sleeper's 3s. Serial-behind-the-sleeper would exceed it.
    assert quick_dt < 2.5, f"short tasks waited on the sleeper: {quick_dt}"
    assert ray_tpu.get(slow_ref, timeout=30) == "slow"


def test_nested_get_deadlock_free_on_shared_queue(one_daemon):
    """Parent blocks in a nested get on a child of the SAME class that
    was pipelined behind it: the spill → temp-slot lending must keep
    the child schedulable (classic composition deadlock)."""
    @ray_tpu.remote(resources={"nd": 1}, num_cpus=1)
    def child(x):
        return x + 1

    @ray_tpu.remote(resources={"nd": 1}, num_cpus=1)
    def parent(x):
        return ray_tpu.get(child.remote(x)) + 10

    # Saturate every slot with parents so children MUST ride lent
    # capacity (4 CPUs -> 4 slots, 8 parents).
    out = ray_tpu.get([parent.remote(i) for i in range(8)], timeout=60)
    assert out == [i + 11 for i in range(8)]


def test_backlog_reported_via_syncer(one_daemon):
    """The daemon's local queue depth reaches the head through the
    syncer (BACKLOG component) — proof the queue lives daemon-side and
    the head observes rather than owns it."""
    from ray_tpu._private import syncer as _sync
    from ray_tpu._private.worker import global_worker

    @ray_tpu.remote(resources={"nd": 1}, num_cpus=1)
    def sleeper(i):
        time.sleep(1.2)
        return i

    refs = [sleeper.remote(i) for i in range(40)]
    rt = global_worker._runtime
    server = rt._head_server
    seen = None
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline:
        view = server.syncer.view()
        for comps in view.values():
            b = comps.get(_sync.BACKLOG)
            if b and b.get("queued", 0) > 0:
                seen = b
                break
        if seen:
            break
        time.sleep(0.2)
    assert seen is not None, "backlog never reported through the syncer"
    assert seen["queued"] > 0
    ray_tpu.get(refs, timeout=90)


def _spillback_burst(res_name, *, n_tasks, task_sleep, join_after,
                     max_retries=0, value=lambda i: i, timeout=120):
    """Shared spillback harness: saturate one daemon's local queue,
    join a second mid-burst, return (results, lease_stats)."""
    host, port = ray_tpu.start_head_server(port=0, host="127.0.0.1")
    procs = [_spawn_daemon(port, num_cpus=2, resources={res_name: 100})]
    try:
        _wait_for_resource(res_name, 100)

        @ray_tpu.remote(resources={res_name: 1}, num_cpus=1,
                        max_retries=max_retries)
        def work(i, _sleep=task_sleep, _value=value):
            time.sleep(_sleep)
            return _value(i)

        refs = [work.remote(i) for i in range(n_tasks)]
        time.sleep(join_after)  # daemon 1's local queue is now deep
        procs.append(_spawn_daemon(port, num_cpus=2,
                                   resources={res_name: 100}))
        out = ray_tpu.get(refs, timeout=timeout)
        from ray_tpu._private.worker import global_worker
        return out, dict(global_worker._runtime.lease_stats)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
            p.wait(timeout=10)


def test_spillback_reclaims_misplaced_work(ray_start_regular):
    """Work pipelined onto a busy node's local queue is reclaimed when
    capacity appears elsewhere (reference: cluster_task_manager
    spillback). A second daemon joins mid-burst; the head pulls queued
    tasks back and re-dispatches them onto it."""
    out, stats = _spillback_burst("sb", n_tasks=30, task_sleep=0.4,
                                  join_after=1.0)
    assert out == list(range(30))
    assert stats.get("reclaimed", 0) > 0, (
        f"no spillback reclaim happened: {stats}")


def test_spillback_under_rpc_chaos(ray_start_regular):
    """Spillback reclaim racing chaos-injected RPC failures AND task
    completions: reclaimed replies, died completions, and retries all
    drive the same per-task continuation — every result must still be
    exactly-once correct (the reclaimed-vs-died race is the sharp edge
    of the r5 spillback protocol), and the reclaim path must actually
    have fired (a vacuous pass would not cover the race)."""
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=1,
                 _system_config={"testing_rpc_failure_pct": 10})
    try:
        out, stats = _spillback_burst("sbx", n_tasks=40, task_sleep=0.3,
                                      join_after=1.0, max_retries=20,
                                      value=lambda i: i * 7, timeout=180)
        assert out == [i * 7 for i in range(40)]
        assert stats.get("reclaimed", 0) > 0, (
            f"reclaim path never exercised under chaos: {stats}")
    finally:
        ray_tpu.shutdown()
