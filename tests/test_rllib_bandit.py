"""Contextual bandits: LinUCB/LinTS regret regression on a linear
environment where the optimal arm is context-dependent (reference:
rllib/algorithms/bandit tests with ParametricItemRecoEnv /
WheelBanditEnv)."""

import numpy as np
import pytest

import ray_tpu


class LinearBanditEnv:
    """K arms with hidden weight vectors; reward = theta_a . x + noise.
    One round per episode (bandit contract)."""

    def __init__(self, config=None):
        import gymnasium as gym
        config = config or {}
        self.dim = int(config.get("dim", 4))
        self.k = int(config.get("arms", 3))
        self.noise = float(config.get("noise", 0.05))
        rng = np.random.default_rng(int(config.get("seed", 0)))
        self.thetas = rng.normal(size=(self.k, self.dim))
        self._rng = rng
        self.observation_space = gym.spaces.Box(
            -1.0, 1.0, (self.dim,), np.float32)
        self.action_space = gym.spaces.Discrete(self.k)
        self._x = None

    def _ctx(self):
        x = self._rng.normal(size=(self.dim,))
        return (x / np.linalg.norm(x)).astype(np.float32)

    def reset(self, *, seed=None, options=None):
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._x = self._ctx()
        return self._x, {}

    def step(self, arm):
        x = self._x
        reward = float(self.thetas[int(arm)] @ x
                       + self.noise * self._rng.normal())
        self.best = float((self.thetas @ x).max())
        self._x = self._ctx()
        return self._x, reward, True, False, {}


@pytest.mark.parametrize("algo_name", ["BanditLinUCB", "BanditLinTS"])
def test_bandit_beats_uniform_and_approaches_optimal(ray_start_regular,
                                                     algo_name):
    from ray_tpu.rllib import BanditLinTSConfig, BanditLinUCBConfig
    cfg_cls = (BanditLinUCBConfig if algo_name == "BanditLinUCB"
               else BanditLinTSConfig)
    config = (cfg_cls()
              .environment(LinearBanditEnv,
                           env_config={"dim": 4, "arms": 3, "seed": 5})
              .training(rounds_per_iteration=200)
              .debugging(seed=11))
    algo = config.build()
    first = algo.train()["mean_reward_this_iter"]
    for _ in range(9):
        res = algo.train()
    last = res["mean_reward_this_iter"]

    # Uniform-random baseline on the same env/context stream.
    env = LinearBanditEnv({"dim": 4, "arms": 3, "seed": 5})
    env.reset(seed=123)
    rng = np.random.default_rng(0)
    uni, opt = [], []
    for _ in range(1000):
        _, r, *_ = env.step(rng.integers(3))
        uni.append(r)
        opt.append(env.best)
    uniform_mean, optimal_mean = np.mean(uni), np.mean(opt)

    assert last > uniform_mean + 0.5 * (optimal_mean - uniform_mean), (
        f"{algo_name}: last={last:.3f} uniform={uniform_mean:.3f} "
        f"optimal={optimal_mean:.3f}")
    # And the posterior sharpens over training.
    assert last >= first - 0.05
    # Greedy single-action API works.
    obs, _ = env.reset(seed=7)
    arm = algo.compute_single_action(obs)
    assert 0 <= arm < 3
    algo.stop()


def test_bandit_state_roundtrip(ray_start_regular):
    from ray_tpu.rllib import BanditLinUCBConfig
    config = (BanditLinUCBConfig()
              .environment(LinearBanditEnv, env_config={"seed": 2})
              .training(rounds_per_iteration=50)
              .debugging(seed=3))
    algo = config.build()
    algo.train()
    state = algo.get_state()
    algo2 = config.build()
    algo2.set_state(state)
    x = np.ones(4, np.float32) / 2.0
    assert algo.compute_single_action(x) == algo2.compute_single_action(x)
    algo.stop()
    algo2.stop()


def test_bandit_algorithm_save_restore(ray_start_regular, tmp_path):
    """Algorithm.save/restore persists the arm posteriors (the bandit's
    real 'weights')."""
    from ray_tpu.rllib import BanditLinUCBConfig
    cfg = (BanditLinUCBConfig()
           .environment(LinearBanditEnv, env_config={"seed": 9})
           .training(rounds_per_iteration=100)
           .debugging(seed=6))
    algo = cfg.build()
    algo.train()
    path = algo.save(str(tmp_path))
    algo2 = cfg.build()
    algo2.restore(path)
    x = np.ones(4, np.float32) / 2.0
    assert algo.compute_single_action(x) == algo2.compute_single_action(x)
    np.testing.assert_allclose(algo._arms[0].A_inv, algo2._arms[0].A_inv)
    algo.stop(); algo2.stop()
