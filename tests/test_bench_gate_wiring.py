"""The regression gate as a HARD gate (tier-1 enforced).

Two layers:

* Wiring — the next bench round (BENCH_r06+) will actually be produced
  with ``regression_baseline`` set against a USABLE prior round:
  ``_prior_round_bench`` must skip records that carry no comparable
  numbers (BENCH_r05's ``parsed`` is null — its values survive only in
  a truncated log tail), and ``_regression_gate`` must stamp the
  baseline name into the extras it is given.

* Enforcement — the latest recorded ``BENCH_r*.json`` may not carry a
  non-empty ``regressions`` list unless every regressed metric is
  waived: either by a ``regressions_waived`` note inside the bench
  record itself or by a matching entry in the repo-level
  ``BENCH_WAIVERS.json``. An unwaived regression fails tier-1 here, so
  a hot-path slowdown can never ride along silently again.
"""

import glob
import importlib.util
import json
import os
import re
import sys

import pytest

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_BENCH_PATH = os.path.join(_ROOT, "bench.py")


@pytest.fixture(scope="module")
def bench():
    spec = importlib.util.spec_from_file_location(
        "_bench_gate_wiring", _BENCH_PATH)
    mod = importlib.util.module_from_spec(spec)
    sys.modules["_bench_gate_wiring"] = mod
    try:
        spec.loader.exec_module(mod)
        yield mod
    finally:
        sys.modules.pop("_bench_gate_wiring", None)


def _bench_rounds():
    rounds = []
    for path in glob.glob(os.path.join(_ROOT, "BENCH_r*.json")):
        m = re.search(r"BENCH_r(\d+)\.json$", path)
        if m:
            rounds.append((int(m.group(1)), path))
    return sorted(rounds)


def _round_regressions(path):
    """Regressed metric names recorded in one bench round — from the
    parsed extras when usable, else recovered from the raw record text
    (r05's parsed payload is null; its regressions list survives only
    inside the truncated ``tail`` string)."""
    with open(path) as f:
        raw = f.read()
    try:
        rec = json.loads(raw)
    except ValueError:
        rec = {}
    parsed = rec.get("parsed") if isinstance(rec, dict) else None
    if isinstance(parsed, dict):
        regs = (parsed.get("extra") or {}).get("regressions")
        if isinstance(regs, list):
            return {r.get("metric") for r in regs if isinstance(r, dict)}
    # Quotes may be escaped (the list often survives only inside the
    # record's quoted ``tail`` string).
    if not re.search(r'\\?"regressions\\?"\s*:', raw):
        return set()
    return set(re.findall(r'\\?"metric\\?"\s*:\s*\\?"([^"\\]+)', raw))


def _waived_metrics(path, rec_round):
    """Union of waivers covering ``rec_round``: the record's own
    ``regressions_waived`` note plus repo-level BENCH_WAIVERS.json."""
    waived = set()
    with open(path) as f:
        raw = f.read()
    try:
        rec = json.loads(raw)
    except ValueError:
        rec = {}
    parsed = rec.get("parsed") if isinstance(rec, dict) else None
    if isinstance(parsed, dict):
        note = (parsed.get("extra") or {}).get("regressions_waived")
        if isinstance(note, (list, tuple)):
            waived.update(note)
    wpath = os.path.join(_ROOT, "BENCH_WAIVERS.json")
    if os.path.exists(wpath):
        with open(wpath) as f:
            doc = json.load(f)
        for w in doc.get("waivers", []):
            if w.get("round") == rec_round:
                waived.update(w.get("metrics", []))
    return waived


def test_prior_round_baseline_is_usable(bench):
    """The next round's gate has a real baseline: extras with numbers
    to compare, not a truncated record."""
    prev, name = bench._prior_round_bench()
    if prev is None:
        pytest.skip("no BENCH_r*.json recorded yet")
    assert isinstance(name, str) and name.startswith("BENCH_r")
    assert isinstance(prev.get("extra"), dict) or \
        isinstance(prev.get("value"), (int, float))


def test_unusable_rounds_are_skipped_as_baseline(bench):
    """A round whose parsed payload is null (driver stored only the
    truncated tail) must not become the comparison baseline."""
    rounds = _bench_rounds()
    if not rounds:
        pytest.skip("no BENCH_r*.json recorded yet")
    _, name = bench._prior_round_bench()
    for _, path in rounds:
        with open(path) as f:
            rec = json.load(f)
        parsed = rec.get("parsed") or rec
        usable = isinstance(parsed, dict) and (
            isinstance(parsed.get("extra"), dict)
            or isinstance(parsed.get("value"), (int, float)))
        if os.path.basename(path) == name:
            assert usable, f"gate selected unusable baseline {name}"
        elif not usable:
            assert name != os.path.basename(path)


def test_regression_gate_stamps_baseline(bench):
    """bench.py main() calls _regression_gate(extra, headline): the
    produced record must carry regression_baseline whenever any prior
    usable round exists — BENCH_r06 will be comparable by construction."""
    prev, name = bench._prior_round_bench()
    if prev is None:
        pytest.skip("no BENCH_r*.json recorded yet")
    extra = {}
    bench._regression_gate(extra, headline_value=None)
    assert extra.get("regression_baseline") == name


def test_check_regressions_flag_wired(bench):
    args = bench._parse_args(["--check-regressions",
                              "--regression-threshold", "15"])
    assert args.check_regressions is True
    assert args.regression_threshold == 15.0


def test_latest_round_regressions_are_waived():
    """HARD GATE: the newest BENCH_r*.json may not record regressions
    that nobody waived. Fix the hot path or add a reasoned waiver."""
    rounds = _bench_rounds()
    if not rounds:
        pytest.skip("no BENCH_r*.json recorded yet")
    _, path = rounds[-1]
    rec_round = os.path.basename(path)
    regressed = _round_regressions(path)
    if not regressed:
        return
    unwaived = regressed - _waived_metrics(path, rec_round)
    assert not unwaived, (
        f"{rec_round} records unwaived regressions {sorted(unwaived)}: "
        "claw the metric back or add a reasoned waiver to "
        "BENCH_WAIVERS.json (round + metrics + reason)")
