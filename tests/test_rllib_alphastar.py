"""AlphaStar league training (reference: rllib/algorithms/alpha_star/
alpha_star.py + league_builder.py): main/exploiter learners, PFSP
matchmaking, snapshot-on-win-rate league growth, and an exploitable
two-player zero-sum env."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.rllib import AlphaStar, AlphaStarConfig
from ray_tpu.rllib.env.examples import TwoPlayerRepeatedRPS


@pytest.fixture
def ray_session():
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=4, num_tpus=0)
    yield
    ray_tpu.shutdown()


def _build(**league_kw):
    config = (AlphaStarConfig()
              .environment(TwoPlayerRepeatedRPS,
                           env_config={"rounds": 8})
              .training(train_batch_size=256, num_sgd_iter=4,
                        sgd_minibatch_size=64, lr=5e-3,
                        model={"fcnet_hiddens": [32, 32]})
              .league(matches_per_iteration=12, **league_kw)
              .debugging(seed=0))
    return config.build()


def test_league_structure_and_matchmaking(ray_session):
    algo: AlphaStar = _build()
    assert set(algo.learning) == {"main", "main_exploiter_0",
                                  "league_exploiter_0"}
    assert list(algo.league) == ["main_v0"]
    result = algo.train()
    assert result["league_size"] >= 1
    assert "main/mean_score" in result
    assert "main_exploiter_0/mean_score" in result
    # PFSP prefers opponents the learner LOSES to.
    algo.win_rates[("main", "main_v0")] = 0.9   # easy
    algo.win_rates[("main", "main_v1")] = 0.1   # hard
    algo.league["main_v1"] = algo.league["main_v0"]
    picks = [algo._pfsp_pick("main", ["main_v0", "main_v1"])
             for _ in range(200)]
    assert picks.count("main_v1") > picks.count("main_v0") * 3
    algo.stop()


def test_main_learns_to_exploit_frozen_snapshot(ray_session):
    """The learning main must reliably beat the frozen initial snapshot
    after a few league iterations (counter-play against a fixed,
    biased opponent is learnable in repeated RPS)."""
    algo: AlphaStar = _build(
        win_rate_threshold_for_new_snapshot=0.65)
    before = algo.win_rate_vs("main_v0", episodes=30)
    best = 0.0
    for _ in range(12):
        algo.train()
        rate = algo.win_rate_vs("main_v0", episodes=30)
        best = max(best, rate)
        if best >= 0.6:
            break
    assert best >= 0.6, (before, best)
    algo.stop()


def test_snapshots_join_league_and_exploiters_reset(ray_session):
    algo: AlphaStar = _build(
        win_rate_threshold_for_new_snapshot=0.55)
    grew = False
    for _ in range(15):
        result = algo.train()
        if result["league_size"] > 1:
            grew = True
            break
    assert grew, "league never grew beyond the initial snapshot"
    names = set(algo.league)
    assert "main_v0" in names and len(names) >= 2
    algo.stop()


def test_league_state_checkpoint_roundtrip(ray_session, tmp_path):
    algo: AlphaStar = _build()
    algo.train()
    algo.win_rates[("main", "main_v0")] = 0.77
    path = algo.save(str(tmp_path))
    algo2: AlphaStar = _build()
    algo2.restore(path)
    assert algo2.win_rates[("main", "main_v0")] == 0.77
    assert set(algo2.league) == set(algo.league)
    w1 = algo.learning["main"].get_weights()
    w2 = algo2.learning["main"].get_weights()
    import jax
    for a, b in zip(jax.tree.leaves(w1), jax.tree.leaves(w2)):
        np.testing.assert_allclose(a, b)
    algo.stop()
    algo2.stop()
