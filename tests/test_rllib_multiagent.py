"""Multi-agent RLlib tests (model: reference rllib/tests/
test_multi_agent_env.py): MultiAgentEnv contract, joint sampling into
per-policy batches, and multi-policy PPO training."""

import gymnasium as gym
import numpy as np
import pytest

from ray_tpu.rllib import MultiAgentBatch, MultiAgentEnv, PPOConfig, \
    SampleBatch


class OpposingBandits(MultiAgentEnv):
    """Two agents, opposite optima: a0 is rewarded for action 1, a1 for
    action 0 — separate policies MUST diverge to solve it (a shared
    policy cannot make both happy), which makes learning attributable."""

    agent_ids = {"a0", "a1"}
    observation_space = gym.spaces.Box(-1, 1, (2,), np.float32)
    action_space = gym.spaces.Discrete(2)

    def __init__(self, episode_len=10):
        self.episode_len = episode_len
        self._t = 0

    def _obs(self):
        return {a: np.zeros(2, np.float32) for a in ("a0", "a1")}

    def reset(self, *, seed=None, options=None):
        self._t = 0
        return self._obs(), {}

    def step(self, action_dict):
        self._t += 1
        rewards = {"a0": float(action_dict["a0"] == 1),
                   "a1": float(action_dict["a1"] == 0)}
        done = self._t >= self.episode_len
        terminateds = {"a0": done, "a1": done, "__all__": done}
        truncateds = {"a0": False, "a1": False, "__all__": False}
        return self._obs(), rewards, terminateds, truncateds, {}


def test_multi_agent_batch_container():
    b = MultiAgentBatch(
        {"p0": SampleBatch({"obs": np.zeros((4, 2))}),
         "p1": SampleBatch({"obs": np.zeros((6, 2))})}, env_steps=5)
    assert b.env_steps() == 5
    assert b.agent_steps() == 10
    cat = MultiAgentBatch.concat_samples([b, b])
    assert cat.env_steps() == 10
    assert len(cat.policy_batches["p1"]) == 12


def test_multi_agent_rollout_worker_batches(ray_start_regular):
    from ray_tpu.rllib.evaluation.multi_agent_worker import (
        MultiAgentRolloutWorker)
    config = (PPOConfig()
              .environment(lambda cfg: OpposingBandits())
              .multi_agent(policies={"p0": None, "p1": None},
                           policy_mapping_fn=lambda aid: "p" + aid[1]))
    worker = MultiAgentRolloutWorker(config.env_creator(),
                                     config.policy_config(), seed=1)
    batch = worker.sample(25)
    assert isinstance(batch, MultiAgentBatch)
    assert batch.env_steps() == 25
    # both agents act every joint step
    assert len(batch.policy_batches["p0"]) == 25
    assert len(batch.policy_batches["p1"]) == 25
    for sb in batch.policy_batches.values():
        # GAE postprocessing completed for every fragment
        assert SampleBatch.ADVANTAGES in sb
        assert SampleBatch.VALUE_TARGETS in sb
    stats = worker.episode_stats()
    assert stats["episodes"] == 2  # 25 steps / 10-step episodes
    assert np.isfinite(stats["episode_reward_mean"])


def test_multi_agent_ppo_learns_opposing_policies(ray_start_regular):
    config = (PPOConfig()
              .environment(lambda cfg: OpposingBandits())
              .rollouts(num_rollout_workers=2)
              .multi_agent(policies={"p0": None, "p1": None},
                           policy_mapping_fn=lambda aid: "p" + aid[1])
              .training(lr=5e-3, train_batch_size=400,
                        num_sgd_iter=6, sgd_minibatch_size=100)
              .debugging(seed=3))
    algo = config.build()
    for _ in range(10):
        res = algo.train()
    assert np.isfinite(res["p0/total_loss"])
    assert np.isfinite(res["p1/total_loss"])
    assert res["agent_steps_this_iter"] == 2 * res["timesteps_total"] / \
        res["training_iteration"]
    # the per-step joint reward approaches 2.0 (both agents optimal)
    assert res["episode_reward_mean"] > 16, res["episode_reward_mean"]
    # the policies DIVERGED: p0 greedy-picks 1, p1 greedy-picks 0
    obs = np.zeros(2, np.float32)
    assert algo.compute_single_action(obs, policy_id="p0") == 1
    assert algo.compute_single_action(obs, policy_id="p1") == 0
    # checkpoint round-trips the whole policy map
    path = algo.save()
    algo2 = (PPOConfig()
             .environment(lambda cfg: OpposingBandits())
             .rollouts(num_rollout_workers=1)
             .multi_agent(policies={"p0": None, "p1": None},
                          policy_mapping_fn=lambda aid: "p" + aid[1])
             ).build()
    algo2.restore(path)
    assert algo2.compute_single_action(obs, policy_id="p0") == 1
    assert algo2.compute_single_action(obs, policy_id="p1") == 0
    algo.stop()
    algo2.stop()


def test_multi_agent_shared_policy(ray_start_regular):
    """Both agents mapped onto ONE policy: its batch sees rows from both
    (parameter sharing, the most common multi-agent configuration)."""
    config = (PPOConfig()
              .environment(lambda cfg: OpposingBandits())
              .rollouts(num_rollout_workers=1)
              .multi_agent(policies={"shared": None},
                           policy_mapping_fn=lambda aid: "shared")
              .training(train_batch_size=100)
              .debugging(seed=5))
    algo = config.build()
    res = algo.train()
    assert "shared/total_loss" in res
    assert res["agent_steps_this_iter"] == 200  # 2 agents x 100 steps
    algo.stop()


def test_multi_agent_config_validation():
    with pytest.raises(ValueError, match="policy_mapping_fn"):
        (PPOConfig()
         .environment(lambda cfg: OpposingBandits())
         .multi_agent(policies={"p0": None})).policy_config()
    # mapping to an unknown policy fails loudly at worker construction
    from ray_tpu.rllib.evaluation.multi_agent_worker import (
        resolve_policy_specs)
    env = OpposingBandits()
    with pytest.raises(ValueError, match="not in config.policies"):
        resolve_policy_specs({"p0": None}, lambda aid: "nope", env)
    with pytest.raises(ValueError, match="not reachable"):
        resolve_policy_specs({"p0": None, "unused": None},
                             lambda aid: "p0", env)
