"""bench.py regression comparator: pure-function unit tests (the gate
behind ``bench.py --check-regressions``)."""

import importlib.util
import os
import sys

import pytest

_BENCH_PATH = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "bench.py")


@pytest.fixture(scope="module")
def bench():
    spec = importlib.util.spec_from_file_location("_bench_gate", _BENCH_PATH)
    mod = importlib.util.module_from_spec(spec)
    sys.modules["_bench_gate"] = mod
    try:
        spec.loader.exec_module(mod)
        yield mod
    finally:
        sys.modules.pop("_bench_gate", None)


def _prev(extra=None, value=None):
    return {"value": value, "extra": extra or {}}


def test_drop_beyond_threshold_flagged(bench):
    prev = _prev({"tasks_per_sec": 1000.0})
    got = bench.compare_rounds(prev, {"tasks_per_sec": 700.0}, None,
                               threshold=0.20)
    assert len(got) == 1
    assert got[0]["metric"] == "tasks_per_sec"
    assert got[0]["prev"] == 1000.0
    assert got[0]["now"] == 700.0
    assert got[0]["drop_pct"] == 30.0


def test_drop_within_threshold_passes(bench):
    prev = _prev({"tasks_per_sec": 1000.0})
    assert bench.compare_rounds(prev, {"tasks_per_sec": 850.0}, None,
                                threshold=0.20) == []
    # ... but the same drop fails a tighter gate.
    assert len(bench.compare_rounds(prev, {"tasks_per_sec": 850.0}, None,
                                    threshold=0.10)) == 1


def test_improvement_ignored(bench):
    prev = _prev({"shuffle_mb_per_sec": 100.0}, value=50.0)
    got = bench.compare_rounds(prev, {"shuffle_mb_per_sec": 400.0}, 60.0,
                               threshold=0.10)
    assert got == []


def test_only_throughput_suffixes_compared(bench):
    prev = _prev({
        "detached_actor_restart_ms": 10.0,   # latency: lower is better
        "run_unix_time": 1e9,
        "gpt410m_mfu": 0.5,
    })
    extra = {"detached_actor_restart_ms": 500.0, "run_unix_time": 1.0,
             "gpt410m_mfu": 0.1}
    got = bench.compare_rounds(prev, extra, None, threshold=0.10)
    assert [r["metric"] for r in got] == ["gpt410m_mfu"]


def test_headline_compared(bench):
    prev = _prev({}, value=100.0)
    got = bench.compare_rounds(prev, {}, 70.0, threshold=0.20)
    assert [r["metric"] for r in got] == ["headline"]
    assert bench.compare_rounds(prev, {}, 85.0, threshold=0.20) == []


def test_missing_prev_or_values_ignored(bench):
    assert bench.compare_rounds(None, {"tasks_per_sec": 1.0}, 1.0) == []
    assert bench.compare_rounds({}, {"tasks_per_sec": 1.0}, 1.0) == []
    # prev metric absent from the current run: not a regression.
    prev = _prev({"tasks_per_sec": 1000.0, "serve_qps": None}, value=None)
    assert bench.compare_rounds(prev, {}, None, threshold=0.10) == []
    # non-numeric current value (a recorded failure) is skipped too.
    assert bench.compare_rounds(prev, {"tasks_per_sec": None}, None) == []
