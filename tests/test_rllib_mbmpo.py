"""MBMPO: ensemble dynamics models as meta-learning tasks
(reference: rllib/algorithms/mbmpo)."""

import numpy as np
import pytest

import ray_tpu  # noqa: F401


def _cpu_jax():
    import jax
    jax.config.update("jax_platforms", "cpu")


def _build(seed=0, **training):
    from ray_tpu.rllib import MBMPOConfig
    from ray_tpu.rllib.env.examples import PointGoalEnv
    cfg = MBMPOConfig().environment(PointGoalEnv).debugging(seed=seed)
    if training:
        cfg = cfg.training(**training)
    return cfg.build()


def test_requires_reward_fn_and_box(ray_start_regular):
    _cpu_jax()
    from ray_tpu.rllib import MBMPOConfig
    with pytest.raises(ValueError, match="reward_fn"):
        (MBMPOConfig().environment("Pendulum-v1")
         .debugging(seed=0)).build()


def test_dynamics_ensemble_fits_and_disagrees(ray_start_regular):
    """Member losses fall as the ensemble trains; bootstrap resamples
    keep members distinct (nonzero prediction disagreement)."""
    _cpu_jax()
    algo = _build(dynamics_epochs=10)
    first = algo.train()["dynamics_loss"]
    for _ in range(3):
        last = algo.train()["dynamics_loss"]
    assert last < first, (first, last)
    s = np.zeros((4, 1), np.float32)
    a = np.full((4, 1), 0.5, np.float32)
    d = algo.dynamics_disagreement(s, a)
    assert d > 0.0
    algo.stop()


def test_imagination_uses_models_not_env(ray_start_regular):
    """Imagined rollouts must not advance the real env."""
    _cpu_jax()
    algo = _build(dynamics_epochs=5)
    algo.train()  # fills buffer + fits models
    env_pos = algo._env.pos
    env_t = algo._env._t
    obs, act, adv, ret = algo._imagine_batch(
        algo.local_policy.params, 0)
    assert obs.shape[0] == (algo.config.imagined_episodes *
                            algo.config.imagined_horizon)
    assert algo._env.pos == env_pos and algo._env._t == env_t
    assert np.isfinite(ret)
    algo.stop()


@pytest.mark.slow
def test_mbmpo_learns_from_imagination(ray_start_regular):
    """The model-based gate: nearly all gradient steps come from
    imagined rollouts, yet REAL env return climbs from random (~-60)
    past -25 within 15 iterations (observed ~-15)."""
    _cpu_jax()
    algo = _build()
    best = -1e9
    for _ in range(15):
        res = algo.train()
        r = res["episode_reward_mean"]
        if r == r:
            best = max(best, r)
    assert best > -25.0, best
    algo.stop()
