"""Tune tests (modeled on the reference's tune/tests coverage)."""

import pytest

import ray_tpu as ray
from ray_tpu import tune
from ray_tpu.air import RunConfig, ScalingConfig
from ray_tpu.tune import ASHAScheduler, TuneConfig, Tuner


def test_grid_search(ray_start_regular):
    def trainable(config):
        tune.report({"score": config["a"] * 10 + config["b"]})

    tuner = Tuner(
        trainable,
        param_space={"a": tune.grid_search([1, 2, 3]),
                     "b": tune.grid_search([0, 1])},
        tune_config=TuneConfig(metric="score", mode="max"),
    )
    results = tuner.fit()
    assert len(results) == 6
    best = results.get_best_result()
    assert best.metrics["score"] == 31
    assert best.config == {"a": 3, "b": 1}


def test_random_sampling(ray_start_regular):
    def trainable(config):
        tune.report({"value": config["lr"]})

    tuner = Tuner(
        trainable,
        param_space={"lr": tune.loguniform(1e-5, 1e-1)},
        tune_config=TuneConfig(metric="value", mode="min", num_samples=8),
    )
    results = tuner.fit()
    assert len(results) == 8
    values = [r.metrics["value"] for r in results]
    assert all(1e-5 <= v <= 1e-1 for v in values)
    assert len(set(values)) > 1  # actually sampled


def test_num_samples_multiplies_grid(ray_start_regular):
    def trainable(config):
        tune.report({"x": config["g"]})

    tuner = Tuner(
        trainable,
        param_space={"g": tune.grid_search([1, 2])},
        tune_config=TuneConfig(num_samples=3, metric="x", mode="max"),
    )
    assert len(tuner.fit()) == 6


def test_trial_errors_recorded(ray_start_regular):
    def trainable(config):
        if config["i"] == 1:
            raise ValueError("bad trial")
        tune.report({"ok": 1})

    tuner = Tuner(
        trainable,
        param_space={"i": tune.grid_search([0, 1, 2])},
        tune_config=TuneConfig(metric="ok", mode="max"),
    )
    results = tuner.fit()
    assert len(results.errors) == 1
    assert results.get_best_result().metrics["ok"] == 1


def test_asha_stops_bad_trials(ray_start_regular):
    def trainable(config):
        for i in range(20):
            tune.report({"score": config["quality"] * (i + 1)})

    tuner = Tuner(
        trainable,
        param_space={"quality": tune.grid_search([1, 2, 3, 4, 5, 6, 7, 8])},
        tune_config=TuneConfig(
            metric="score", mode="max",
            scheduler=ASHAScheduler(max_t=20, grace_period=2,
                                    reduction_factor=4)),
    )
    results = tuner.fit()
    best = results.get_best_result()
    assert best.config["quality"] == 8
    # Early stopping must have cut at least one weak trial short.
    lengths = [len(r.metrics_history) for r in results]
    assert min(lengths) < 20


def test_stop_criteria(ray_start_regular):
    def trainable(config):
        for i in range(100):
            tune.report({"iters": i})

    tuner = Tuner(
        trainable,
        tune_config=TuneConfig(metric="iters", mode="max"),
        run_config=RunConfig(stop={"iters": 5}),
    )
    results = tuner.fit()
    assert len(results[0].metrics_history) <= 8


def test_tuner_over_trainer(ray_start_regular):
    from ray_tpu.air import session
    from ray_tpu.train import DataParallelTrainer

    def loop(config):
        session.report({"final": config["x"] * 2})

    trainer = DataParallelTrainer(
        loop, scaling_config=ScalingConfig(num_workers=1))
    tuner = Tuner(
        trainer,
        param_space={"x": tune.grid_search([1, 5])},
        tune_config=TuneConfig(metric="final", mode="max"),
    )
    results = tuner.fit()
    assert len(results) == 2
    assert results.get_best_result().metrics["final"] == 10


def test_tuner_over_jax_trainer(ray_start_regular):
    """Regression: the trainer-clone path must work for JaxTrainer
    (its __init__ signature differs from DataParallelTrainer's)."""
    from ray_tpu.air import session
    from ray_tpu.train import JaxTrainer

    def loop(config):
        session.report({"final": config["x"] * 3})

    trainer = JaxTrainer(loop, scaling_config=ScalingConfig(num_workers=1))
    results = Tuner(
        trainer,
        param_space={"x": tune.grid_search([1, 2])},
        tune_config=TuneConfig(metric="final", mode="max"),
    ).fit()
    assert not results.errors
    assert results.get_best_result().metrics["final"] == 6


def test_with_parameters_and_resources(ray_start_regular):
    big_object = list(range(1000))

    def trainable(config, data=None):
        tune.report({"n": len(data) + config["k"]})

    wrapped = tune.with_parameters(trainable, data=big_object)
    wrapped = tune.with_resources(wrapped, {"cpu": 2})
    tuner = Tuner(wrapped, param_space={"k": tune.grid_search([0, 1])},
                  tune_config=TuneConfig(metric="n", mode="max"))
    results = tuner.fit()
    assert results.get_best_result().metrics["n"] == 1001


def test_hyperband_stops_bad_trials(ray_start_regular):
    from ray_tpu.tune import HyperBandScheduler

    def trainable(config):
        for i in range(30):
            tune.report({"score": config["q"] * (i + 1)})

    results = Tuner(
        trainable,
        param_space={"q": tune.grid_search(list(range(1, 10)))},
        tune_config=TuneConfig(
            metric="score", mode="max",
            scheduler=HyperBandScheduler(max_t=27, reduction_factor=3)),
    ).fit()
    assert results.get_best_result().config["q"] == 9
    lengths = [len(r.metrics_history) for r in results]
    assert min(lengths) < 27


def test_median_stopping_rule(ray_start_regular):
    from ray_tpu.tune import MedianStoppingRule

    def trainable(config):
        for i in range(15):
            tune.report({"score": config["q"] * (i + 1)})

    results = Tuner(
        trainable,
        param_space={"q": tune.grid_search([1, 2, 5, 6, 7, 8])},
        tune_config=TuneConfig(
            metric="score", mode="max",
            scheduler=MedianStoppingRule(grace_period=3,
                                         min_samples_required=3)),
    ).fit()
    assert results.get_best_result().config["q"] == 8
    lengths = [len(r.metrics_history) for r in results]
    assert min(lengths) < 15  # below-median trials were cut


def test_pbt_exploits_checkpoints(ray_start_regular):
    """Weak PBT trials must restart from a stronger trial's checkpoint with
    a mutated config (the EXPLOIT protocol)."""
    from ray_tpu.air.checkpoint import Checkpoint
    from ray_tpu.tune import PopulationBasedTraining

    def trainable(config):
        ckpt = tune.get_checkpoint()
        level = ckpt.to_dict()["level"] if ckpt is not None else 0
        for i in range(12):
            level += config["rate"]
            tune.report({"level": level},
                        checkpoint=Checkpoint.from_dict({"level": level}))

    pbt = PopulationBasedTraining(
        perturbation_interval=3,
        hyperparam_mutations={"rate": tune.uniform(0.1, 10.0)},
        quantile_fraction=0.5, seed=3)
    results = Tuner(
        trainable,
        param_space={"rate": tune.grid_search([0.1, 0.2, 8.0, 9.0])},
        tune_config=TuneConfig(metric="level", mode="max", scheduler=pbt),
    ).fit()
    assert not results.errors
    assert pbt.num_perturbations > 0
    # An exploited weak trial inherits a strong trial's level: every trial's
    # final level should be far above what the weak configs alone reach
    # (0.2-rate trial alone caps at 12*0.2 = 2.4 without exploiting).
    final_levels = sorted(
        max(h["level"] for h in r.metrics_history) for r in results)
    assert final_levels[0] > 2.4


def test_tpe_searcher_converges(ray_start_regular):
    from ray_tpu.tune import TPESearcher

    def trainable(config):
        x = config["x"]
        tune.report({"loss": (x - 3.0) ** 2})

    results = Tuner(
        trainable,
        param_space={"x": tune.uniform(-10.0, 10.0)},
        tune_config=TuneConfig(metric="loss", mode="min", num_samples=30,
                               search_alg=TPESearcher(n_initial_points=8,
                                                      seed=0),
                               max_concurrent_trials=4),
    ).fit()
    assert len(results) == 30
    best = results.get_best_result()
    # TPE should get meaningfully closer to x=3 than random's expected best.
    assert abs(best.config["x"] - 3.0) < 1.5


def test_concurrency_limiter(ray_start_regular):
    from ray_tpu.tune import BasicVariantGenerator, ConcurrencyLimiter

    def trainable(config):
        tune.report({"v": config["x"]})

    searcher = ConcurrencyLimiter(BasicVariantGenerator(num_samples=6),
                                  max_concurrent=2)
    results = Tuner(
        trainable,
        param_space={"x": tune.uniform(0, 1)},
        tune_config=TuneConfig(metric="v", mode="max", num_samples=6,
                               search_alg=searcher),
    ).fit()
    assert len(results) == 6
    assert not results.errors


def test_logger_callbacks(ray_start_regular, tmp_path):
    from ray_tpu.tune import CSVLoggerCallback, JsonLoggerCallback

    def trainable(config):
        for i in range(3):
            tune.report({"step": i, "x": config["x"]})

    results = Tuner(
        trainable,
        param_space={"x": tune.grid_search([1, 2])},
        tune_config=TuneConfig(metric="x", mode="max"),
        run_config=RunConfig(
            name="exp", storage_path=str(tmp_path),
            callbacks=[CSVLoggerCallback(), JsonLoggerCallback()]),
    ).fit()
    assert not results.errors
    exp_dir = tmp_path / "exp"
    trial_dirs = [d for d in exp_dir.iterdir() if d.is_dir()]
    assert len(trial_dirs) == 2
    for d in trial_dirs:
        csv_lines = (d / "progress.csv").read_text().strip().splitlines()
        assert len(csv_lines) == 4  # header + 3 reports
        json_lines = (d / "result.json").read_text().strip().splitlines()
        assert len(json_lines) == 3
        import json as _json
        assert _json.loads((d / "params.json").read_text())["x"] in (1, 2)


def test_experiment_snapshot_and_restore(ray_start_regular, tmp_path):
    def trainable(config):
        tune.report({"v": config["x"] * 10})

    run_config = RunConfig(name="resume_exp", storage_path=str(tmp_path))
    results = Tuner(
        trainable,
        param_space={"x": tune.grid_search([1, 2, 3])},
        tune_config=TuneConfig(metric="v", mode="max"),
        run_config=run_config,
    ).fit()
    assert len(results) == 3
    state_file = tmp_path / "resume_exp" / "experiment_state.json"
    assert state_file.exists()

    # Restore: all trials finished, so results come back without rerunning.
    restored = Tuner.restore(str(tmp_path / "resume_exp"), trainable)
    results2 = restored.fit()
    assert len(results2) == 3
    assert results2.get_best_result().metrics["v"] == 30


def test_restore_requeues_pending_variants(ray_start_regular, tmp_path):
    """A crash before all variants launch must not lose the unlaunched ones:
    the snapshot stores pending configs and restore requeues them."""
    import json as _json

    # Simulate a crashed run: 1 of 4 grid points finished, 3 still pending.
    exp_dir = tmp_path / "crashed"
    exp_dir.mkdir()
    state = {
        "metric": "v", "mode": "max", "num_samples": 1,
        "name": "crashed", "storage_path": str(tmp_path),
        "num_created": 1,
        "pending_configs": [{"x": 2}, {"x": 3}, {"x": 4}],
        "trials": [{"trial_id": "trial_00000_dead", "config": {"x": 1},
                    "done": True, "error": None,
                    "history": [{"v": 10, "training_iteration": 1}]}],
    }
    (exp_dir / "experiment_state.json").write_text(_json.dumps(state))

    def trainable(config):
        tune.report({"v": config["x"] * 10})

    results = Tuner.restore(str(exp_dir), trainable).fit()
    assert len(results) == 4
    assert results.get_best_result().metrics["v"] == 40


def test_bayesopt_search_beats_random_on_quadratic(ray_start_regular):
    """BayesOpt should concentrate samples near the optimum of a smooth
    1-D objective."""
    from ray_tpu import tune
    from ray_tpu.tune.search import BayesOptSearch

    def objective(config):
        tune.report({"score": -(config["x"] - 0.7) ** 2})

    tuner = tune.Tuner(
        objective,
        param_space={"x": tune.uniform(0.0, 1.0)},
        tune_config=tune.TuneConfig(
            metric="score", mode="max", num_samples=20,
            search_alg=BayesOptSearch(n_initial_points=5, seed=1)),
    )
    results = tuner.fit()
    best = results.get_best_result()
    assert abs(best.config["x"] - 0.7) < 0.15, best.config


def test_tbx_logger_writes_valid_event_file(tmp_path, ray_start_regular):
    """The hand-encoded TFRecord framing must round-trip: length-prefixed
    records with valid masked CRC32C."""
    import struct
    from ray_tpu.tune.callbacks import (TBXLoggerCallback, _CRC32C_TABLE,
                                        _tb_events_record)

    cb = TBXLoggerCallback(str(tmp_path))
    cb.on_trial_result("trial1", {"loss": 0.5, "training_iteration": 1})
    cb.on_trial_result("trial1", {"loss": 0.25, "training_iteration": 2})
    cb.on_trial_complete("trial1")
    files = list((tmp_path / "trial1").glob("events.out.tfevents.*"))
    assert len(files) == 1
    raw = files[0].read_bytes()

    def crc32c(data):
        crc = 0xFFFFFFFF
        for b in data:
            crc = _CRC32C_TABLE[(crc ^ b) & 0xFF] ^ (crc >> 8)
        return crc ^ 0xFFFFFFFF

    def unmask(m):
        rot = (m - 0xA282EAD8) & 0xFFFFFFFF
        return ((rot << 15) | (rot >> 17)) & 0xFFFFFFFF

    records = []
    off = 0
    while off < len(raw):
        (length,) = struct.unpack_from("<Q", raw, off)
        (len_crc,) = struct.unpack_from("<I", raw, off + 8)
        assert unmask(len_crc) == crc32c(raw[off:off + 8])
        payload = raw[off + 12:off + 12 + length]
        (pay_crc,) = struct.unpack_from("<I", raw, off + 12 + length)
        assert unmask(pay_crc) == crc32c(payload)
        records.append(payload)
        off += 12 + length + 4
    # header + 2 result events (each carrying >= 1 scalar)
    assert len(records) >= 3
    assert b"brain.Event:2" in records[0]
    assert b"ray/tune/loss" in b"".join(records[1:])


def test_syncer_callback_mirrors_experiment_dir(tmp_path, ray_start_regular):
    from ray_tpu.tune.callbacks import SyncerCallback

    exp = tmp_path / "exp"
    exp.mkdir()
    (exp / "result.json").write_text("{}")
    dest = tmp_path / "bucket"
    cb = SyncerCallback(f"file://{dest}")
    cb.setup(experiment_dir=str(exp))
    cb.on_trial_result("t1", {"a": 1})
    assert (dest / "exp" / "result.json").exists()


def test_wandb_mlflow_gated():
    from ray_tpu.tune.callbacks import (MLflowLoggerCallback,
                                        WandbLoggerCallback)
    with pytest.raises(ImportError):
        WandbLoggerCallback(project="p").setup()
    with pytest.raises(ImportError):
        MLflowLoggerCallback().setup()


def test_bayesopt_loguniform_domain(ray_start_regular):
    """LogUniform params must survive the GP phase (log_low/log_high)."""
    from ray_tpu import tune
    from ray_tpu.tune.search import BayesOptSearch

    def objective(config):
        import math
        tune.report({"score": -abs(math.log10(config["lr"]) + 2.0)})

    tuner = tune.Tuner(
        objective,
        param_space={"lr": tune.loguniform(1e-4, 1e-1)},
        tune_config=tune.TuneConfig(
            metric="score", mode="max", num_samples=12,
            search_alg=BayesOptSearch(n_initial_points=4, seed=2)),
    )
    best = tuner.fit().get_best_result()
    assert 1e-4 <= best.config["lr"] <= 1e-1


def test_bohb_budget_model_selection():
    """BOHB models the largest budget with enough samples; below the
    threshold it pools across budgets (reference: tune/search/bohb
    pairing with HyperBand rungs)."""
    from ray_tpu.tune import BOHBSearcher
    s = BOHBSearcher(min_points_per_budget=3, seed=0)
    s.set_search_properties("loss", "min", {"x": tune.uniform(0, 1)})
    for i in range(2):
        s._observe({"x": 0.1 * i}, {"loss": 1.0,
                                    "training_iteration": 9}, False)
    for i in range(4):
        s._observe({"x": 0.2 * i}, {"loss": 2.0,
                                    "training_iteration": 3}, False)
    # Budget 9 has only 2 points -> model falls to budget 3 (4 points).
    assert s.model_budget() == 3
    assert len(s._observations) == 4
    s._observe({"x": 0.9}, {"loss": 0.5, "training_iteration": 9}, False)
    assert s.model_budget() == 9
    assert len(s._observations) == 3


def test_bohb_with_hyperband_converges(ray_start_regular):
    """The BOHB pairing end to end: HyperBand rungs produce mixed-budget
    completions; the searcher still homes in on the optimum."""
    from ray_tpu.tune import BOHBSearcher, HyperBandScheduler

    def trainable(config):
        x = config["x"]
        for i in range(8):
            tune.report({"loss": (x - 3.0) ** 2 + 0.1 / (i + 1)})

    results = Tuner(
        trainable,
        param_space={"x": tune.uniform(-10.0, 10.0)},
        tune_config=TuneConfig(
            metric="loss", mode="min", num_samples=30,
            search_alg=BOHBSearcher(n_initial_points=8, seed=0),
            scheduler=HyperBandScheduler(max_t=8, metric="loss",
                                         mode="min"),
            max_concurrent_trials=4),
    ).fit()
    assert len(results) == 30
    best = results.get_best_result()
    assert abs(best.config["x"] - 3.0) < 1.5
