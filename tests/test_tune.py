"""Tune tests (modeled on the reference's tune/tests coverage)."""

import pytest

import ray_tpu as ray
from ray_tpu import tune
from ray_tpu.air import RunConfig, ScalingConfig
from ray_tpu.tune import ASHAScheduler, TuneConfig, Tuner


def test_grid_search(ray_start_regular):
    def trainable(config):
        tune.report({"score": config["a"] * 10 + config["b"]})

    tuner = Tuner(
        trainable,
        param_space={"a": tune.grid_search([1, 2, 3]),
                     "b": tune.grid_search([0, 1])},
        tune_config=TuneConfig(metric="score", mode="max"),
    )
    results = tuner.fit()
    assert len(results) == 6
    best = results.get_best_result()
    assert best.metrics["score"] == 31
    assert best.config == {"a": 3, "b": 1}


def test_random_sampling(ray_start_regular):
    def trainable(config):
        tune.report({"value": config["lr"]})

    tuner = Tuner(
        trainable,
        param_space={"lr": tune.loguniform(1e-5, 1e-1)},
        tune_config=TuneConfig(metric="value", mode="min", num_samples=8),
    )
    results = tuner.fit()
    assert len(results) == 8
    values = [r.metrics["value"] for r in results]
    assert all(1e-5 <= v <= 1e-1 for v in values)
    assert len(set(values)) > 1  # actually sampled


def test_num_samples_multiplies_grid(ray_start_regular):
    def trainable(config):
        tune.report({"x": config["g"]})

    tuner = Tuner(
        trainable,
        param_space={"g": tune.grid_search([1, 2])},
        tune_config=TuneConfig(num_samples=3, metric="x", mode="max"),
    )
    assert len(tuner.fit()) == 6


def test_trial_errors_recorded(ray_start_regular):
    def trainable(config):
        if config["i"] == 1:
            raise ValueError("bad trial")
        tune.report({"ok": 1})

    tuner = Tuner(
        trainable,
        param_space={"i": tune.grid_search([0, 1, 2])},
        tune_config=TuneConfig(metric="ok", mode="max"),
    )
    results = tuner.fit()
    assert len(results.errors) == 1
    assert results.get_best_result().metrics["ok"] == 1


def test_asha_stops_bad_trials(ray_start_regular):
    def trainable(config):
        for i in range(20):
            tune.report({"score": config["quality"] * (i + 1)})

    tuner = Tuner(
        trainable,
        param_space={"quality": tune.grid_search([1, 2, 3, 4, 5, 6, 7, 8])},
        tune_config=TuneConfig(
            metric="score", mode="max",
            scheduler=ASHAScheduler(max_t=20, grace_period=2,
                                    reduction_factor=4)),
    )
    results = tuner.fit()
    best = results.get_best_result()
    assert best.config["quality"] == 8
    # Early stopping must have cut at least one weak trial short.
    lengths = [len(r.metrics_history) for r in results]
    assert min(lengths) < 20


def test_stop_criteria(ray_start_regular):
    def trainable(config):
        for i in range(100):
            tune.report({"iters": i})

    tuner = Tuner(
        trainable,
        tune_config=TuneConfig(metric="iters", mode="max"),
        run_config=RunConfig(stop={"iters": 5}),
    )
    results = tuner.fit()
    assert len(results[0].metrics_history) <= 8


def test_tuner_over_trainer(ray_start_regular):
    from ray_tpu.air import session
    from ray_tpu.train import DataParallelTrainer

    def loop(config):
        session.report({"final": config["x"] * 2})

    trainer = DataParallelTrainer(
        loop, scaling_config=ScalingConfig(num_workers=1))
    tuner = Tuner(
        trainer,
        param_space={"x": tune.grid_search([1, 5])},
        tune_config=TuneConfig(metric="final", mode="max"),
    )
    results = tuner.fit()
    assert len(results) == 2
    assert results.get_best_result().metrics["final"] == 10


def test_tuner_over_jax_trainer(ray_start_regular):
    """Regression: the trainer-clone path must work for JaxTrainer
    (its __init__ signature differs from DataParallelTrainer's)."""
    from ray_tpu.air import session
    from ray_tpu.train import JaxTrainer

    def loop(config):
        session.report({"final": config["x"] * 3})

    trainer = JaxTrainer(loop, scaling_config=ScalingConfig(num_workers=1))
    results = Tuner(
        trainer,
        param_space={"x": tune.grid_search([1, 2])},
        tune_config=TuneConfig(metric="final", mode="max"),
    ).fit()
    assert not results.errors
    assert results.get_best_result().metrics["final"] == 6


def test_with_parameters_and_resources(ray_start_regular):
    big_object = list(range(1000))

    def trainable(config, data=None):
        tune.report({"n": len(data) + config["k"]})

    wrapped = tune.with_parameters(trainable, data=big_object)
    wrapped = tune.with_resources(wrapped, {"cpu": 2})
    tuner = Tuner(wrapped, param_space={"k": tune.grid_search([0, 1])},
                  tune_config=TuneConfig(metric="n", mode="max"))
    results = tuner.fit()
    assert results.get_best_result().metrics["n"] == 1001
