"""Sharded, crash-safe, reshardable train checkpoints (ISSUE 20).

Covers the whole two-phase-commit contract: per-rank shard writes
through the spill backends with the rank-0 manifest written last as the
commit record, uncommitted shard sets invisible to ``latest()`` and
garbage-collected on the next index load, checksum rejection of corrupt
shards, chaos ``io_oserror`` on a shard write failing that save attempt
cleanly, a SIGKILLed-rank-mid-save gang restart that resumes the last
committed checkpoint, elastic shrink (8 -> 4) resuming via reshard with
numerically identical parameters, ``num_to_keep`` pruning that removes
manifest + all shards, the mock-s3 backend, and the new config knobs.
"""

import os
import sys

import cloudpickle
import numpy as np
import pytest

import ray_tpu

# Actor threads may unpickle these train loops outside the tests/
# package — ship this module by value (same idiom as the other train
# suites).
cloudpickle.register_pickle_by_value(sys.modules[__name__])

from ray_tpu._private import builtin_metrics, chaos, events, spill  # noqa: E402
from ray_tpu.air import (CheckpointConfig, FailureConfig, RunConfig,  # noqa: E402
                         ScalingConfig, session)
from ray_tpu.train import DataParallelTrainer, ShardedCheckpoint  # noqa: E402
from ray_tpu.train._internal import sharded_checkpoint as sc  # noqa: E402
from ray_tpu.train._internal.backend_executor import (  # noqa: E402
    BackendExecutor, TrainingFailedError)
from ray_tpu.train._internal.checkpoint_manager import (  # noqa: E402
    CheckpointManager)
from ray_tpu.train.backend import BackendConfig  # noqa: E402


def _counter_total(counter, tag_substr=None):
    if tag_substr is None:
        return sum(counter.series().values())
    return sum(v for k, v in counter.series().items()
               if any(tag_substr in str(part) for part in k))


def _set_flag(name, value):
    from ray_tpu._private.worker import global_worker
    global_worker._runtime.config.set(name, value)


def _state_at(step):
    """Deterministic full training state as a function of the step —
    every rank can recompute it, so restores are checkable exactly."""
    base = np.arange(13 * 4, dtype=np.float32).reshape(13, 4)
    return {"w": base * float(step + 1),
            "b": np.full((7,), float(step), np.float32),
            "opt": [np.ascontiguousarray(base.T) / float(step + 1),
                    np.float32(step)]}


def _trees_equal(a, b):
    fa, _ = sc.flatten_tree(a)
    fb, _ = sc.flatten_tree(b)
    if set(fa) != set(fb):
        return False
    return all(np.array_equal(np.asarray(fa[p]), np.asarray(fb[p]))
               for p in fa)


def _save_sharded(backend, run, seq, state, world, extra=None):
    """Write all shards + commit a manifest directly (no gang)."""
    flat, structure = sc.flatten_tree(state)
    axes = [("fsdp", world)]
    specs = sc.default_specs(flat)
    records = [
        sc.write_shard(backend, run, seq, rank,
                       sc.extract_local_shard(flat, specs, axes, rank))
        for rank in range(world)
    ]
    meta = sc.build_tree_meta(flat, structure, specs, axes, extra=extra)
    manifest = sc.build_manifest(run, seq, meta, records)
    uri = sc.write_manifest(backend, run, seq, manifest)
    return manifest, uri, records


# ---------------------------------------------------------------------------
# Shard math
# ---------------------------------------------------------------------------


def test_axis_split_bounds_balanced():
    assert sc.axis_split_bounds(8, 4) == [(0, 2), (2, 4), (4, 6), (6, 8)]
    # Non-divisible: the first S % N shards carry one extra row and the
    # bounds tile the dimension exactly — the property resharding needs.
    bounds = sc.axis_split_bounds(13, 6)
    assert bounds[0] == (0, 3)
    assert bounds[-1] == (11, 13)
    assert [b - a for a, b in bounds] == [3, 2, 2, 2, 2, 2]
    # More shards than rows: trailing shards own empty ranges.
    assert sc.axis_split_bounds(2, 4)[-1] == (2, 2)
    with pytest.raises(ValueError):
        sc.axis_split_bounds(4, 0)


def test_shard_slices_and_overlap():
    axes = {"dp": 2, "fsdp": 2}
    # Dim 0 sharded over a tuple of axes composes row-major.
    spec = [["dp", "fsdp"], []]
    blocks = [sc.shard_slices((8, 3), spec, axes,
                              {"dp": d, "fsdp": f})
              for d in range(2) for f in range(2)]
    assert [b[0] for b in blocks] == [
        slice(0, 2), slice(2, 4), slice(4, 6), slice(6, 8)]
    assert all(b[1] == slice(0, 3) for b in blocks)
    assert sc.slices_overlap((slice(0, 4),), (slice(2, 6),)) == \
        (slice(2, 4),)
    assert sc.slices_overlap((slice(0, 2),), (slice(2, 6),)) is None
    # 0-d leaves: empty slice tuples overlap as () — NOT None.
    assert sc.slices_overlap((), ()) == ()


def test_normalize_spec_accepts_partition_spec():
    from jax.sharding import PartitionSpec
    assert sc.normalize_spec(PartitionSpec("fsdp", None), 2) == \
        [["fsdp"], []]
    assert sc.normalize_spec(PartitionSpec(("dp", "fsdp")), 2) == \
        [["dp", "fsdp"], []]
    assert sc.normalize_spec(None, 2) == [[], []]


# ---------------------------------------------------------------------------
# Manifest round-trip + restore/reshard (no cluster needed)
# ---------------------------------------------------------------------------


def test_manifest_roundtrip_and_full_restore(tmp_path):
    backend = spill.FileSpillBackend(str(tmp_path))
    state = _state_at(5)
    manifest, uri, records = _save_sharded(backend, "rt", 3, state, 8,
                                           extra={"step": 5})
    assert len(records) == 8
    ck = ShardedCheckpoint.from_manifest_uri(uri)
    assert ck.seq == 3 and ck.world_size == 8
    assert ck.extra == {"step": 5}
    assert ck.to_dict() == {"step": 5}
    restored = ck.load_full()
    assert _trees_equal(restored, state)
    # Container types survive the structure skeleton.
    assert isinstance(restored, dict) and isinstance(restored["opt"], list)
    # Monolithic payload APIs are refused, loudly.
    with pytest.raises(ValueError, match="load_for_rank"):
        ck.to_directory()


@pytest.mark.parametrize("new_world", [6, 4])
def test_reshard_numerical_identity(tmp_path, new_world):
    """A checkpoint saved on 8 ranks reassembles bit-identically on 6
    or 4 — per-rank blocks pulled as byte ranges from the old shards."""
    backend = spill.FileSpillBackend(str(tmp_path))
    state = _state_at(2)
    manifest, uri, _ = _save_sharded(backend, "rs", 1, state, 8)
    ck = ShardedCheckpoint.from_manifest_uri(uri)
    new_axes = [("fsdp", new_world)]
    reassembled = {p: np.empty(tuple(m["shape"]), np.dtype(m["dtype"]))
                   for p, m in manifest["params"].items()}
    for rank in range(new_world):
        local, _ = sc.flatten_tree(ck.load_for_rank(rank, new_world))
        coords = sc.rank_coords(rank, new_axes)
        for p, arr in local.items():
            slc = sc.shard_slices(tuple(manifest["params"][p]["shape"]),
                                  manifest["specs"][p], dict(new_axes),
                                  coords)
            reassembled[p][slc] = arr
    flat, structure = sc.flatten_tree(state)
    for p in flat:
        assert np.array_equal(np.asarray(flat[p]), reassembled[p]), p


def test_checksum_rejection(tmp_path):
    backend = spill.FileSpillBackend(str(tmp_path))
    manifest, uri, records = _save_sharded(backend, "crc", 1,
                                           _state_at(0), 2)
    # Corrupt one shard in place (same size, so only the crc catches it).
    victim = backend.path_for(backend.uri_for(records[1]["file"]))
    size = os.path.getsize(victim)
    with open(victim, "r+b") as f:
        f.seek(size // 2)
        f.write(b"\xff\xff\xff\xff")
    ck = ShardedCheckpoint.from_manifest_uri(uri)
    with pytest.raises(ValueError, match="checksum"):
        ck.load_full(verify=True)


# ---------------------------------------------------------------------------
# Two-phase commit: visibility, orphan GC, adoption, pruning
# ---------------------------------------------------------------------------


def test_uncommitted_shards_invisible_and_gcd(tmp_path):
    """Shard files without a manifest (rank died before the commit) are
    invisible to latest() and swept by the next index load."""
    mgr = CheckpointManager(str(tmp_path), "torn")
    backend = mgr._backend
    flat, structure = sc.flatten_tree(_state_at(1))
    specs = sc.default_specs(flat)
    for rank in range(2):  # both shards land, the manifest never does
        sc.write_shard(backend, "torn", 1, rank,
                       sc.extract_local_shard(flat, specs,
                                              [("fsdp", 2)], rank))
    assert mgr.latest() is None
    assert len(backend.list_files("train-torn-ckpt-")) == 2
    orphans_before = _counter_total(builtin_metrics.train_ckpt_orphans_gc())
    events.drain_pending()
    mgr2 = CheckpointManager(str(tmp_path), "torn")
    assert mgr2.latest() is None
    assert backend.list_files("train-torn-ckpt-") == []
    assert _counter_total(builtin_metrics.train_ckpt_orphans_gc()) >= \
        orphans_before + 2
    assert any("orphan" in e["message"] for e in events.drain_pending())


def test_corrupt_shard_uncommits_manifest_on_gc(tmp_path):
    """A committed manifest whose shard fails its checksum is
    uncommitted by GC: manifest + shards removed, latest() falls back."""
    mgr = CheckpointManager(str(tmp_path), "bitrot")
    backend = mgr._backend
    _save_sharded(backend, "bitrot", 1, _state_at(0), 2)  # good, older
    _, _, records = _save_sharded(backend, "bitrot", 2, _state_at(1), 2)
    victim = backend.path_for(backend.uri_for(records[0]["file"]))
    size = os.path.getsize(victim)
    with open(victim, "r+b") as f:
        f.seek(size // 2)
        f.write(b"\x00\x00\x00\x00")
    mgr2 = CheckpointManager(str(tmp_path), "bitrot")
    latest = mgr2.latest()
    assert isinstance(latest, ShardedCheckpoint)
    assert latest.seq == 1  # seq 2 was uncommitted by GC
    names = backend.list_files("train-bitrot-ckpt-")
    assert not any("000002" in n for n in names), names


def test_committed_manifest_adopted_into_index(tmp_path):
    """Crash AFTER the manifest write but BEFORE the index write: the
    checkpoint IS committed (manifest = commit record); the next index
    load adopts it."""
    mgr = CheckpointManager(str(tmp_path), "adopt")
    _save_sharded(mgr._backend, "adopt", 4, _state_at(3), 2,
                  extra={"step": 3})
    # mgr's in-memory index never saw it; a fresh load reconciles.
    mgr2 = CheckpointManager(str(tmp_path), "adopt")
    latest = mgr2.latest()
    assert isinstance(latest, ShardedCheckpoint)
    assert latest.seq == 4 and latest.extra == {"step": 3}
    assert mgr2.next_seq_base() == 5
    assert _trees_equal(latest.load_full(), _state_at(3))


def test_register_sharded_commits_and_prunes_all_files(tmp_path):
    """register_sharded writes the manifest last and num_to_keep
    pruning deletes manifest + every shard of evicted checkpoints —
    never the newest committed one."""
    mgr = CheckpointManager(str(tmp_path), "prune",
                            CheckpointConfig(num_to_keep=1))
    backend = mgr._backend
    for seq in (1, 2):
        state = _state_at(seq)
        flat, structure = sc.flatten_tree(state)
        specs = sc.default_specs(flat)
        records = [
            sc.write_shard(backend, "prune", seq, rank,
                           sc.extract_local_shard(flat, specs,
                                                  [("fsdp", 2)], rank))
            for rank in range(2)
        ]
        meta = sc.build_tree_meta(flat, structure, specs,
                                  [("fsdp", 2)], extra={"step": seq})
        handle = mgr.register_sharded(seq, meta, records)
        assert isinstance(handle, ShardedCheckpoint)
    names = backend.list_files("train-prune-ckpt-")
    # Only seq 2 survives: 1 manifest + 2 shards.
    assert all("000002" in n for n in names), names
    assert len(names) == 3, names
    latest = mgr.latest()
    assert latest.seq == 2
    assert _trees_equal(latest.load_full(), _state_at(2))


def test_register_sharded_refuses_partial_gang(tmp_path):
    mgr = CheckpointManager(str(tmp_path), "partial")
    flat, structure = sc.flatten_tree(_state_at(0))
    specs = sc.default_specs(flat)
    rec = sc.write_shard(mgr._backend, "partial", 1, 1,
                         sc.extract_local_shard(flat, specs,
                                                [("fsdp", 2)], 1))
    meta = sc.build_tree_meta(flat, structure, specs, [("fsdp", 2)])
    with pytest.raises(ValueError, match="contiguous"):
        mgr.register_sharded(1, meta, [rec])  # rank 0 missing


def test_chaos_io_oserror_fails_write_keeps_prior(tmp_path):
    """An injected IO error on a shard write surfaces as SpillFailure
    (the save attempt fails cleanly); the previously committed
    checkpoint is untouched and restorable."""
    backend = spill.FileSpillBackend(str(tmp_path))
    manifest, uri, _ = _save_sharded(backend, "io", 1, _state_at(7), 2,
                                     extra={"step": 7})
    flat, _ = sc.flatten_tree(_state_at(8))
    specs = sc.default_specs(flat)
    chaos.configure(
        "io_oserror:site=train.ckpt_shard_write_error:times=1")
    try:
        with pytest.raises(spill.SpillFailure):
            sc.write_shard(backend, "io", 2, 0,
                           sc.extract_local_shard(flat, specs,
                                                  [("fsdp", 2)], 0))
    finally:
        chaos.reset()
    prior = ShardedCheckpoint.from_manifest_uri(uri)
    assert _trees_equal(prior.load_full(), _state_at(7))


def test_mock_s3_backend_roundtrip(tmp_path, monkeypatch):
    monkeypatch.setenv("RAY_TPU_MOCK_S3_DIR", str(tmp_path / "s3"))
    mgr = CheckpointManager("mock-s3://ckpt-bucket", "cloudy")
    flat, structure = sc.flatten_tree(_state_at(1))
    specs = sc.default_specs(flat)
    records = [
        sc.write_shard(mgr._backend, "cloudy", 1, rank,
                       sc.extract_local_shard(flat, specs,
                                              [("fsdp", 2)], rank))
        for rank in range(2)
    ]
    meta = sc.build_tree_meta(flat, structure, specs, [("fsdp", 2)],
                              extra={"step": 1})
    handle = mgr.register_sharded(1, meta, records)
    assert handle.uri.startswith("mock-s3://ckpt-bucket/")
    # A brand-new manager (fresh process analog) restores through the
    # same bucket URI.
    latest = CheckpointManager("mock-s3://ckpt-bucket", "cloudy").latest()
    assert isinstance(latest, ShardedCheckpoint)
    assert _trees_equal(latest.load_full(), _state_at(1))


def test_config_knobs_present():
    from ray_tpu._private.ray_config import _PY_DEFAULTS
    assert _PY_DEFAULTS["train_ckpt_shard_parallelism"] == 8
    assert _PY_DEFAULTS["train_ckpt_verify_checksums"] is True
    assert _PY_DEFAULTS["train_reshard_on_restart"] is True


def test_shard_parallelism_one_still_loads(tmp_path, monkeypatch):
    monkeypatch.setenv("RAY_TPU_train_ckpt_shard_parallelism", "1")
    backend = spill.FileSpillBackend(str(tmp_path))
    _, uri, _ = _save_sharded(backend, "serial", 1, _state_at(4), 4)
    ck = ShardedCheckpoint.from_manifest_uri(uri)
    assert _trees_equal(ck.load_full(), _state_at(4))


# ---------------------------------------------------------------------------
# End-to-end through the gang (report_sharded -> two-phase commit)
# ---------------------------------------------------------------------------


def _sharded_loop(total):
    def loop():
        rank = session.get_world_rank()
        world = session.get_world_size()
        ckpt = session.get_checkpoint()
        start = 0
        resume_ok = 1.0
        if ckpt is not None:
            start = ckpt.to_dict()["step"]
            # The restore path every rank takes on (re)start: my block
            # under the CURRENT mesh, resharded from the saved one.
            local, _ = sc.flatten_tree(ckpt.load_for_rank(rank, world))
            flat, _ = sc.flatten_tree(_state_at(start))
            specs = sc.default_specs(flat)
            expected = sc.extract_local_shard(flat, specs,
                                              [("fsdp", world)], rank)
            for p, arr in expected.items():
                if not np.array_equal(arr, np.asarray(local[p])):
                    resume_ok = 0.0
        for i in range(start, total):
            session.report_sharded(
                {"step": i, "world": world, "resume_ok": resume_ok},
                _state_at(i + 1), extra={"step": i + 1})
    return loop


def test_sharded_train_end_to_end(ray_start_regular, tmp_path):
    """4 ranks each write their own shard file every save; the driver
    commits the manifest after all acks; metrics/journal record it."""
    persisted_before = _counter_total(
        builtin_metrics.train_checkpoints_persisted())
    saves_hist = builtin_metrics.train_ckpt_save_seconds()
    saves_before = sum(saves_hist._counts.values())
    events.drain_pending()

    trainer = DataParallelTrainer(
        _sharded_loop(3),
        scaling_config=ScalingConfig(num_workers=4),
        run_config=RunConfig(name="shard-e2e",
                             storage_path=str(tmp_path)))
    result = trainer.fit()
    assert result.metrics["step"] == 2
    ck = result.checkpoint
    assert isinstance(ck, ShardedCheckpoint)
    assert ck.world_size == 4 and ck.extra == {"step": 3}
    assert _trees_equal(ck.load_full(), _state_at(3))

    # N parallel per-rank shard files on storage, per-rank byte counters.
    names = [n for n in os.listdir(tmp_path) if ".shard-" in n]
    assert {n.rsplit("-", 1)[1] for n in names} >= \
        {"0000", "0001", "0002", "0003"}
    shard_bytes = builtin_metrics.train_ckpt_shard_bytes().series()
    ranks_seen = {part for key in shard_bytes for part in key}
    assert {"0", "1", "2", "3"} <= ranks_seen
    assert _counter_total(
        builtin_metrics.train_checkpoints_persisted()) >= \
        persisted_before + 3
    assert sum(saves_hist._counts.values()) >= saves_before + 3
    msgs = [e["message"] for e in events.drain_pending()]
    assert any("sharded checkpoint" in m and "committed" in m
               for m in msgs), msgs

    # A fresh run under the same name auto-resumes from the commit.
    second = DataParallelTrainer(
        _sharded_loop(5),
        scaling_config=ScalingConfig(num_workers=4),
        run_config=RunConfig(name="shard-e2e",
                             storage_path=str(tmp_path)))
    r2 = second.fit()
    assert r2.metrics["step"] == 4
    assert r2.metrics["resume_ok"] == 1.0
    assert len(r2.metrics_history) == 2  # started at step 3
    assert r2.checkpoint.extra == {"step": 5}


def test_chaos_shard_write_error_save_aborts_cleanly(ray_start_regular,
                                                    tmp_path):
    """One rank's shard write raises: that save attempt aborts without
    a manifest, training continues, later saves commit normally."""
    failures_before = _counter_total(
        builtin_metrics.train_checkpoint_persist_failures())
    trainer = DataParallelTrainer(
        _sharded_loop(3),
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(name="shard-io", storage_path=str(tmp_path)))
    chaos.configure(
        "io_oserror:site=train.ckpt_shard_write_error:times=1")
    try:
        result = trainer.fit()
        fired = any(op["fired"] for op in chaos.stats())
    finally:
        chaos.reset()
    assert fired, "chaos io error never fired"
    assert result.metrics["step"] == 2
    # The first save (step 1) aborted; the run's last save committed.
    assert result.checkpoint.extra == {"step": 3}
    assert _trees_equal(result.checkpoint.load_full(), _state_at(3))
    assert _counter_total(
        builtin_metrics.train_checkpoint_persist_failures()) >= \
        failures_before + 1
    # No torn seq-1 manifest on storage.
    manifests = [n for n in os.listdir(tmp_path) if n.endswith(".manifest")]
    assert not any("000001" in n for n in manifests), manifests


def test_chaos_sigkill_rank_mid_save_acceptance(ray_start_regular,
                                                tmp_path):
    """ISSUE 20 chaos acceptance: SIGKILL one rank mid-save -> the
    partial save never commits, the gang restarts, resume loads the
    last COMMITTED checkpoint, and the next index load GCs the torn
    shard set."""
    restarts_before = _counter_total(
        builtin_metrics.train_gang_restarts(), "system")
    trainer = DataParallelTrainer(
        _sharded_loop(4),
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(
            name="shard-kill", storage_path=str(tmp_path),
            failure_config=FailureConfig(max_failures=1)))
    # after=2: save 1's two shard writes pass, then the first rank to
    # reach save 2's kill gate dies with its shard unwritten — the
    # other rank's seq-2 shard becomes commit-less debris.
    chaos.configure("kill:site=train.ckpt_shard_kill:after=2:times=1")
    try:
        result = trainer.fit()
        fired = any(op["fired"] for op in chaos.stats())
    finally:
        chaos.reset()
    assert fired, "chaos kill never fired"
    # The run finished its full target on the restarted gang.
    assert result.metrics["step"] == 3
    assert result.metrics["resume_ok"] == 1.0
    assert result.checkpoint.extra == {"step": 4}
    assert _trees_equal(result.checkpoint.load_full(), _state_at(4))
    assert _counter_total(builtin_metrics.train_gang_restarts(),
                          "system") >= restarts_before + 1
    events.drain_pending()
    # The torn shard set is debris until the next index load sweeps it.
    mgr = CheckpointManager(str(tmp_path), "shard-kill")
    latest = mgr.latest()
    assert isinstance(latest, ShardedCheckpoint)
    assert latest.extra == {"step": 4}
    committed = {f for e in mgr._tracked for f in e.get("files", [])} | \
        {os.path.basename(e["uri"].split("://", 1)[1])
         for e in mgr._tracked}
    leftover = [n for n in mgr._backend.list_files("train-shard-kill-ckpt-")
                if ".shard-" in n or n.endswith(".manifest")]
    assert all(n in committed for n in leftover), (leftover, committed)


def test_elastic_shrink_reshard_acceptance(ray_start_regular, monkeypatch,
                                           tmp_path):
    """ISSUE 20 elastic acceptance: mid-run shrink 8 -> min_workers 4
    resumes via reshard with numerically identical params and finishes
    the full target step count; reshards_total{shrink} increments."""
    shrink_before = _counter_total(builtin_metrics.train_reshards(),
                                   "shrink")
    _set_flag("train_restart_wait_s", 0.1)
    monkeypatch.setattr(BackendExecutor, "_placeable_workers",
                        lambda self, desired: 4)

    def loop():
        rank = session.get_world_rank()
        world = session.get_world_size()
        ckpt = session.get_checkpoint()
        start = 0
        resume_ok = 1.0
        if ckpt is not None:
            start = ckpt.to_dict()["step"]
            local, _ = sc.flatten_tree(ckpt.load_for_rank(rank, world))
            flat, _ = sc.flatten_tree(_state_at(start))
            specs = sc.default_specs(flat)
            expected = sc.extract_local_shard(flat, specs,
                                              [("fsdp", world)], rank)
            for p, arr in expected.items():
                if not np.array_equal(arr, np.asarray(local[p])):
                    resume_ok = 0.0
        for i in range(start, 4):
            session.report_sharded(
                {"step": i, "world": world, "resume_ok": resume_ok},
                _state_at(i + 1), extra={"step": i + 1})
            if world == 8 and i + 1 >= 2:
                raise RuntimeError("slice lost")

    mgr = CheckpointManager(str(tmp_path), "elastic-shrink")
    executor = BackendExecutor(
        BackendConfig(),
        ScalingConfig(num_workers=8, min_workers=4),
        FailureConfig(max_failures=1),
        checkpoint_manager=mgr)
    executor.start()
    try:
        result = executor.run(loop, {}, {"trial_id": "shrink"})
    finally:
        executor.shutdown()
    # Finished the FULL target on the 4-rank gang.
    assert result.metrics["step"] == 3
    assert result.metrics["world"] == 4
    assert result.metrics["resume_ok"] == 1.0
    ck = result.checkpoint
    assert isinstance(ck, ShardedCheckpoint)
    assert ck.world_size == 4 and ck.extra == {"step": 4}
    assert _trees_equal(ck.load_full(), _state_at(4))
    assert _counter_total(builtin_metrics.train_reshards(), "shrink") >= \
        shrink_before + 1


def test_reshard_on_restart_disabled_refuses(ray_start_regular, tmp_path):
    """With train_reshard_on_restart off, a gang sized differently from
    the saved mesh refuses to resume (a config veto, not a retryable
    TrainingFailedError)."""
    backend = spill.FileSpillBackend(str(tmp_path))
    _, uri, _ = _save_sharded(backend, "frozen", 1, _state_at(1), 2,
                              extra={"step": 1})
    ck = ShardedCheckpoint.from_manifest_uri(uri)
    executor = BackendExecutor(BackendConfig(),
                               ScalingConfig(num_workers=1))
    _set_flag("train_reshard_on_restart", False)
    try:
        with pytest.raises(RuntimeError,
                           match="train_reshard_on_restart"):
            executor._reshard_accounting(ck, new_world=1)
        # Same-size resume is always allowed.
        executor._reshard_accounting(ck, new_world=2)
    finally:
        _set_flag("train_reshard_on_restart", True)


if __name__ == "__main__":
    sys.exit(pytest.main(["-v", "-x", __file__]))
