"""Head-dispatch scalability: many concurrent remote tasks complete with
a BOUNDED head thread count (the thread-per-call fix — reference:
direct_task_transport's callback-driven client, release/benchmarks
'10k+ simultaneously running tasks')."""

import json
import subprocess
import sys
import threading
import time

import pytest

import ray_tpu


def _spawn_daemon(port, num_cpus):
    return subprocess.Popen(
        [sys.executable, "-m", "ray_tpu._private.multinode",
         "--address", f"127.0.0.1:{port}",
         "--num-cpus", str(num_cpus),
         "--resources", json.dumps({"remote": 100})],
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)


@pytest.fixture
def four_daemons(ray_start_regular):
    host, port = ray_tpu.start_head_server(port=0, host="127.0.0.1")
    procs = [_spawn_daemon(port, 8) for _ in range(4)]
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        if ray_tpu.cluster_resources().get("remote", 0) >= 400:
            break
        time.sleep(0.1)
    else:
        raise TimeoutError("daemons never joined")
    try:
        yield
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
            p.wait(timeout=10)


def test_10k_tasks_bounded_head_threads(four_daemons):
    """10,000 concurrent trivial tasks over 4 daemons: all complete, and
    the head never grows a thread per in-flight call."""

    # worker_process False: this test measures HEAD dispatch scalability
    # (thread boundedness + throughput), and the single-CPU CI box can't
    # also afford a worker-subprocess hop per task.
    @ray_tpu.remote(resources={"remote": 1}, num_cpus=1,
                    runtime_env={"worker_process": False})
    def tiny(i):
        return i

    base_threads = threading.active_count()
    t0 = time.monotonic()
    refs = [tiny.remote(i) for i in range(10_000)]
    # Peak thread check mid-flight.
    peak = 0
    done = []

    def probe():
        while not done:
            nonlocal_peak[0] = max(nonlocal_peak[0],
                                   threading.active_count())
            time.sleep(0.05)

    nonlocal_peak = [0]
    t = threading.Thread(target=probe, daemon=True)
    t.start()
    results = ray_tpu.get(refs, timeout=300)
    elapsed = time.monotonic() - t0
    done.append(True)
    t.join(timeout=2)

    assert results == list(range(10_000))
    rate = 10_000 / elapsed
    # Bounded: recv loops (4) + health (1) + completion pool (8) + a few
    # dep waiters — nowhere near one-thread-per-task. Generous cap to
    # stay robust on slow CI.
    assert nonlocal_peak[0] - base_threads < 64, \
        f"head grew {nonlocal_peak[0] - base_threads} threads"
    print(f"\n10k remote tasks: {rate:.0f} tasks/s, "
          f"peak extra threads {nonlocal_peak[0] - base_threads}")
    assert rate > 200, f"remote task throughput too low: {rate:.0f}/s"
