"""Fenced membership and fast failure detection (wire v9).

Covers the three legs of the membership subsystem:

* **Epoch fencing** — every registration mints a monotonically
  increasing ``node_epoch`` (persisted through the gcs_store, so a
  restarted head keeps minting above its previous life); a declared
  death fences the epoch, and stale-incarnation frames / resume
  attempts are dropped+counted (``ray_tpu_frames_fenced_total``), never
  applied.
* **Accrual suspicion + lease** — per-node phi-accrual suspicion fed by
  channel liveness, adapting to each node's cadence (slow-cadence nodes
  are not falsely declared); ``RAY_TPU_node_lease_s`` bounds detection
  unconditionally; hard socket failures (SIGKILL) declare sub-second.
* **Partition chaos** — the ``partition`` chaos kind blackholes
  transport sites deterministically (p/seed/after/times grammar, ``ms``
  heal window), proving partition -> suspicion -> declaration ->
  fenced resume -> clean re-register with exactly-once detached-actor
  side effects.
"""

import json
import os
import signal
import socket
import struct
import subprocess
import sys
import threading
import time

import pytest

import ray_tpu
from ray_tpu._private import builtin_metrics, chaos, membership
from ray_tpu._private import wire as _wire


def _fenced_total() -> float:
    return sum(builtin_metrics.frames_fenced().series().values())


# -- unit: accrual detector ----------------------------------------------

def test_phi_adapts_to_node_cadence():
    """The same silence is damning for a chatty node and unremarkable
    for one that routinely goes quiet (an XLA compile must not read as
    death)."""
    base = time.monotonic()
    fast = membership.AccrualDetector(floor_s=0.05)
    slow = membership.AccrualDetector(floor_s=0.05)
    fast.last_arrival = slow.last_arrival = base
    for i in range(1, 21):
        fast.record(base + 0.05 * i)          # frame every 50ms
    for i in range(1, 5):
        slow.record(base + 5.0 * i)           # quiet 5s between reports
    t_fast = fast.last_arrival + 2.0
    t_slow = slow.last_arrival + 2.0
    assert fast.phi(t_fast) > 8.0             # 2s silent: way off-cadence
    assert slow.phi(t_slow) < 1.0             # 2s silent: routine
    # Silence shorter than the cadence is never suspicious.
    assert fast.phi(fast.last_arrival + 0.01) < 0.1


def test_phi_floor_defeats_burst_skew():
    """A burst of sub-millisecond arrivals must not shrink the mean so
    far that a routine pause looks fatal — the floor clamps it."""
    base = time.monotonic()
    det = membership.AccrualDetector(floor_s=0.25)
    det.last_arrival = base
    for i in range(1, 50):
        det.record(base + 0.001 * i)          # 1ms burst
    assert det.mean_interval() == pytest.approx(0.25)
    assert det.phi(det.last_arrival + 0.3) < 1.0


# -- unit: membership table ----------------------------------------------

def test_declare_dead_exactly_once_and_fences_epoch():
    table = membership.MembershipTable()
    e1 = table.mint_epoch("node-a")
    assert table.current_epoch("node-a") == e1
    assert not table.is_fenced(e1)
    assert not table.is_fenced(0)             # 0 = epoch unknown
    assert table.declare_dead("node-a", "test") is True
    assert table.declare_dead("node-a", "test") is False  # exactly once
    assert table.is_fenced(e1)
    # An epoch this head never minted (daemon re-registering across a
    # head restart) is NOT fenced — the rebind path depends on that.
    assert not table.is_fenced(e1 + 1000)


def test_second_incarnation_gets_fresh_liveness_budget():
    """A re-registered daemon is a new incarnation: new (higher) epoch,
    zeroed suspicion state — and the old epoch stays fenced while the
    new one is clean."""
    table = membership.MembershipTable()
    e1 = table.mint_epoch("node-a")
    live1 = table.liveness("node-a")
    live1.soft_failures = 7                   # partition evidence piled up
    table.declare_dead("node-a", "partition")
    e2 = table.mint_epoch("node-a")
    assert e2 > e1
    live2 = table.liveness("node-a")
    assert live2 is not live1
    assert live2.soft_failures == 0
    assert live2.epoch == e2
    assert table.is_fenced(e1) and not table.is_fenced(e2)


def test_epoch_counter_survives_head_restart(tmp_path):
    """Epochs persist through the gcs_store: a restarted head mints
    strictly above everything its previous life handed out, so the old
    life's fenced epochs can never be re-issued."""
    from ray_tpu._private.gcs_store import GcsStore
    path = str(tmp_path / "gcs.pkl")
    store = GcsStore(path)
    t1 = membership.MembershipTable(store)
    epochs = [t1.mint_epoch("node-a"), t1.mint_epoch("node-b"),
              t1.mint_epoch("node-a")]
    t2 = membership.MembershipTable(GcsStore(path))  # "restarted head"
    assert t2.mint_epoch("node-c") > max(epochs)


def test_membership_events_fan_out():
    table = membership.MembershipTable()
    events = []

    def bad(_event):
        raise RuntimeError("one bad subscriber must not break the rest")

    table.subscribe(bad)
    table.subscribe(events.append)
    epoch = table.mint_epoch("node-a")
    table.declare_dead("node-a", "why not")
    assert [e["event"] for e in events] == ["joined", "dead"]
    assert events[0]["epoch"] == events[1]["epoch"] == epoch
    assert events[1]["reason"] == "why not"
    table.unsubscribe(events.append)
    table.mint_epoch("node-b")
    assert len(events) == 2


# -- unit: partition chaos grammar ---------------------------------------

@pytest.fixture
def chaos_reset():
    yield
    chaos.reset()


def _drop_pattern(spec, site, n):
    chaos.configure(spec)
    pattern = []
    for _ in range(n):
        try:
            chaos.maybe_inject(site)
            pattern.append(False)
        except chaos.ChaosPartition:
            pattern.append(True)
    chaos.reset()
    return pattern


def test_partition_same_seed_same_drops(chaos_reset):
    spec = "partition:p=0.4:seed=7:site=head"
    p1 = _drop_pattern(spec, "head.send", 200)
    p2 = _drop_pattern(spec, "head.send", 200)
    assert p1 == p2
    assert any(p1) and not all(p1)            # p<1: some pass, some drop
    assert _drop_pattern("partition:p=0.4:seed=8:site=head",
                         "head.send", 200) != p1


def test_partition_after_times_grammar(chaos_reset):
    pattern = _drop_pattern("partition:site=head:after=3:times=2",
                            "head.recv", 8)
    assert pattern == [False, False, False, True, True,
                       False, False, False]


def test_partition_only_fires_at_transport_sites(chaos_reset):
    chaos.configure("partition:site=head")
    chaos.maybe_inject("head.dispatch")       # not .send/.recv: no-op
    with pytest.raises(chaos.ChaosPartition):
        chaos.maybe_inject("head.health.send")
    stats = chaos.stats()
    assert stats[0]["fired"] == 1


def test_partition_heal_window_is_permanent(chaos_reset):
    """``ms`` arms on the FIRST fire: inside the window every matching
    call is blackholed (p/times notwithstanding); after it elapses the
    partition is healed forever."""
    chaos.configure("partition:site=head:ms=120")
    with pytest.raises(chaos.ChaosPartition):
        chaos.maybe_inject("head.send")       # arms the window
    with pytest.raises(chaos.ChaosPartition):
        chaos.maybe_inject("head.health.recv")
    time.sleep(0.15)
    for _ in range(20):                       # healed: never fires again
        chaos.maybe_inject("head.send")
    assert chaos.stats()[0]["fired"] == 2


def test_partition_is_soft_evidence_classification():
    """ChaosPartition must look like an unreachable peer (transient
    OSError for the channel layer) but be distinguishable from a hard
    reset so membership can classify it as soft evidence."""
    from ray_tpu._private.channel import is_transient
    exc = chaos.ChaosPartition("blackholed")
    assert isinstance(exc, OSError)
    assert is_transient(exc)
    assert not isinstance(exc, ConnectionError)


# -- unit: stale-epoch frames at the channel layer -----------------------

def _send_enveloped(sock, seq, ack, epoch, payload):
    frame = _wire.wrap_seq(seq, ack, payload, epoch)
    sock.sendall(struct.pack(">Q", len(frame)) + frame)


def test_stale_epoch_frame_dropped_and_counted():
    """A frame stamped with another incarnation's epoch is dropped and
    counted, never returned; epoch-0 (pre-registration) frames pass."""
    from ray_tpu._private.channel import ResilientChannel
    left, right = socket.socketpair()
    chan = ResilientChannel(right, site="test", ring_bytes=1 << 16,
                            window_s=0.5)
    chan.epoch = 7
    before = _fenced_total()
    try:
        _send_enveloped(left, 1, 0, 99, b"stale-incarnation")
        _send_enveloped(left, 1, 0, 7, b"current")
        assert chan.recv_frame() == b"current"
        assert _fenced_total() == before + 1
        _send_enveloped(left, 2, 0, 0, b"epoch-unknown")
        assert chan.recv_frame() == b"epoch-unknown"
        assert _fenced_total() == before + 1
    finally:
        chan.close()
        left.close()


def test_wire_v9_envelope_roundtrip():
    env = _wire.wrap_seq(5, 3, b"payload", epoch=42)
    assert _wire.unwrap_seq(env) == (5, 3, 42, b"payload")
    # Additive: epoch defaults to 0 for writers that don't know it yet.
    assert _wire.unwrap_seq(_wire.wrap_seq(1, 0, b"x"))[2] == 0


# -- integration helpers -------------------------------------------------

def _spawn_daemon(port, *, num_cpus=2, resources=None, env=None):
    cmd = [sys.executable, "-m", "ray_tpu._private.multinode",
           "--address", f"127.0.0.1:{port}",
           "--num-cpus", str(num_cpus)]
    if resources:
        cmd += ["--resources", json.dumps(resources)]
    return subprocess.Popen(cmd, env=env, stdout=subprocess.DEVNULL,
                            stderr=subprocess.DEVNULL)


def _wait_for_resource(name, amount, timeout=25):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if ray_tpu.cluster_resources().get(name, 0) >= amount:
            return
        time.sleep(0.1)
    raise TimeoutError(
        f"resource {name}>={amount} never appeared: "
        f"{ray_tpu.cluster_resources()}")


# -- integration: resume fencing at the protocol level -------------------

def test_resume_from_fenced_epoch_refused():
    """A resume handshake carrying a fenced epoch gets a ``fenced``
    reply (and bumps ``ray_tpu_frames_fenced_total``); an unknown
    session with a bogus token gets ``resume_rejected`` — the daemon's
    cue to re-register."""
    from ray_tpu._private.multinode import (_dumps, _loads, _recv_frame,
                                            _send_frame)
    from ray_tpu._private.worker import global_worker
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=1, num_tpus=0)
    try:
        host, port = ray_tpu.start_head_server(port=0, host="127.0.0.1")
        runtime = global_worker.runtime
        epoch = runtime.membership.mint_epoch("feedfacecafe")
        runtime.membership.declare_dead("feedfacecafe", "partitioned away")
        before = _fenced_total()

        with socket.create_connection((host, port), timeout=5) as sock:
            _send_frame(sock, _dumps({
                "type": "resume", "protocol": _wire.PROTOCOL_VERSION,
                "node_id": "feedfacecafe", "token": "whatever",
                "epoch": epoch, "last_seq": 0}))
            reply = _loads(_recv_frame(sock))
        assert reply["type"] == "fenced"
        assert reply["epoch"] == epoch
        assert _fenced_total() > before

        # Old/unknown token (no fenced epoch): plain rejection.
        with socket.create_connection((host, port), timeout=5) as sock:
            _send_frame(sock, _dumps({
                "type": "resume", "protocol": _wire.PROTOCOL_VERSION,
                "node_id": "feedfacecafe", "token": "stale-token",
                "epoch": 0, "last_seq": 0}))
            reply = _loads(_recv_frame(sock))
        assert reply["type"] == "resume_rejected"
    finally:
        ray_tpu.shutdown()


# -- integration: hard-failure detection speed ---------------------------

def test_sigkill_daemon_declared_dead_fast():
    """At DEFAULT settings a SIGKILLed daemon is declared dead in well
    under the lease: the broken channel wakes the membership loop, the
    health probe hits a reset socket, and the hard path declares
    immediately."""
    from ray_tpu._private.worker import global_worker
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=1, num_tpus=0)
    p = None
    try:
        host, port = ray_tpu.start_head_server(port=0, host="127.0.0.1")
        p = _spawn_daemon(port, resources={"mortal": 1})
        _wait_for_resource("mortal", 1)
        dead = threading.Event()

        def on_event(event):
            if event.get("event") == "dead":
                dead.set()

        runtime = global_worker.runtime
        runtime.membership.subscribe(on_event)
        try:
            p.send_signal(signal.SIGKILL)
            t0 = time.perf_counter()
            assert dead.wait(timeout=10), "death never declared"
            detect_s = time.perf_counter() - t0
        finally:
            runtime.membership.unsubscribe(on_event)
        # Sub-second by design; 2s leaves margin for a loaded CI box.
        assert detect_s < 2.0, f"detection took {detect_s:.2f}s"
    finally:
        if p is not None and p.poll() is None:
            p.kill()
        if p is not None:
            p.wait(timeout=10)
        ray_tpu.shutdown()


# -- integration: partition -> suspicion -> fence -> re-register ---------

def test_partition_fences_old_incarnation_exactly_once(tmp_path):
    """The acceptance scenario: a daemon hosting a detached actor is
    partitioned (head-side bidirectional blackhole) past the lease, the
    head declares it dead and fences the epoch; the daemon's resume is
    refused with ``fenced``; after the partition heals it re-registers
    as a NEW incarnation, the detached actor is rebound exactly once,
    and every invocation executed exactly once (no duplicate side
    effects from the stale instance)."""
    from ray_tpu._private.worker import global_worker
    marker = str(tmp_path / "ticks.txt")
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=2, num_tpus=0, _system_config={
        "health_probe_period_s": 0.1,
        "health_probe_timeout_s": 0.4,
        "node_lease_s": 1.5,
    })
    p = None
    events = []
    try:
        host, port = ray_tpu.start_head_server(port=0, host="127.0.0.1")
        runtime = global_worker.runtime
        runtime.membership.subscribe(events.append)
        p = _spawn_daemon(port, resources={"part": 1})
        _wait_for_resource("part", 1)

        @ray_tpu.remote(resources={"part": 1}, max_restarts=-1)
        class Effector:
            """Appends one line per invocation: a duplicated side
            effect (stale instance still applying frames) shows up as a
            repeated tag."""

            def __init__(self, path):
                import uuid
                self.path = path
                self.uid = uuid.uuid4().hex[:8]

            def tick(self, tag):
                with open(self.path, "a") as f:
                    f.write(f"{tag} {self.uid}\n")
                return self.uid

        actor = Effector.options(
            name="fenced-effector", lifetime="detached").remote(marker)
        uid_before = ray_tpu.get(actor.tick.remote("pre"), timeout=30)

        # Bidirectional blackhole of every head<->daemon transport site
        # (session send/recv + health probes) for 3.5s — long past the
        # 1.5s lease.
        t_partition = time.monotonic()
        chaos.configure("partition:site=head:ms=3500")
        deadline = time.monotonic() + 30
        while not any(e["event"] == "dead" for e in events):
            assert time.monotonic() < deadline, \
                f"partitioned node never declared dead: {events}"
            time.sleep(0.1)
        first_epoch = next(e["epoch"] for e in events
                           if e["event"] == "joined")
        assert runtime.membership.is_fenced(first_epoch)

        # Call into the (dead, restart-pending) actor mid-partition.
        # Actor invocations are at-most-once: the call either executes
        # exactly once on a NEW incarnation or fails with
        # ActorDiedError — it must never run twice (stale instance +
        # restarted one).
        mid_ref = actor.tick.remote("mid")

        # Heal: short-lived incarnations minted inside the blackhole may
        # each die at their lease; once the window has elapsed the
        # daemon's next registration sticks and the detached actor comes
        # back. Probe with uniquely-tagged ticks (at-most-once: a failed
        # probe is a dropped call, never a duplicated one).
        time.sleep(max(0.0, t_partition + 4.0 - time.monotonic()))
        from ray_tpu.exceptions import ActorDiedError
        try:
            mid_uid = ray_tpu.get(mid_ref, timeout=60)
        except ActorDiedError:
            mid_uid = None  # dropped with a dead incarnation, not dup'd
        if mid_uid is not None:
            assert mid_uid != uid_before
        uid_after = None
        post_tags = []
        deadline = time.monotonic() + 40
        while uid_after is None:
            tag = f"post{len(post_tags)}"
            post_tags.append(tag)
            try:
                handle = ray_tpu.get_actor("fenced-effector")
                uid_after = ray_tpu.get(handle.tick.remote(tag),
                                        timeout=10)
            except Exception:  # noqa: BLE001 - incarnation still settling
                assert time.monotonic() < deadline, \
                    "actor never came back after the partition healed"
                time.sleep(0.3)
        assert uid_after != uid_before

        joined = [e["epoch"] for e in events if e["event"] == "joined"]
        assert joined[-1] > first_epoch
        assert runtime.membership.is_fenced(first_epoch)
        assert not runtime.membership.is_fenced(joined[-1])

        # Exactly-once side effects: no tag ever appears twice, and
        # every post-fence execution came from a NEW incarnation (the
        # stale instance applied nothing after its epoch was fenced).
        with open(marker) as f:
            lines = [ln.split() for ln in f.read().splitlines()]
        tags = [tag for tag, _uid in lines]
        assert tags.count("pre") == 1
        assert tags.count("mid") == (1 if mid_uid is not None else 0)
        for tag in post_tags:
            assert tags.count(tag) <= 1       # dropped or ran ONCE
        assert tags.count(post_tags[-1]) == 1
        for tag, uid in lines:
            if tag != "pre":
                assert uid != uid_before
        assert {uid for tag, uid in lines if tag == post_tags[-1]} \
            == {uid_after}
    finally:
        chaos.reset()
        if p is not None and p.poll() is None:
            p.kill()
        if p is not None:
            p.wait(timeout=10)
        ray_tpu.shutdown()
